"""Round-21 serve-plane tests: shared-prefix KV reuse, chunked prefill
admission, per-slot sampling (serve/prefix_cache.py + engine.py,
DESIGN.md §26).

Three invariants anchor everything here:

1. PARITY — with the prefix cache ON, every greedy request's tokens are
   token-identical to (a) the same engine with the cache OFF and (b)
   batch-at-a-time generate() with a contiguous cache, across admission
   paths (classic / chunked / partial-hit / full-hit-COW), both model
   families (incl. gemma sliding-window layers), base and adapter rows.
2. COMPILE STABILITY — after every bucket width and the COW re-feed
   program have traced once, hits / misses / COW / multi-chunk walks /
   cancels add ZERO executables (trace_counts-pinned).
3. ACCOUNTING — shared pages are refcounted while shared, parked (not
   leaked, not double-freed) on last release; terminal states leave
   refcounts == {} and in_use == 0.
"""

import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gemma3
from mobilefinetuner_tpu.models import gemma3, gpt2
from mobilefinetuner_tpu.models.generate import (SampleConfig,
                                                 gemma3_generate,
                                                 gpt2_generate)
from mobilefinetuner_tpu.serve import (AdapterBank, ServeConfig,
                                       ServeEngine, chain_keys)

# n_positions=96 (vs test_serve.py's 64) so chunked prompts up to 48
# tokens + generation fit — the multi-chunk walk needs room
GPT2_CFG = dataclasses.replace(
    GPT2Config.tiny(vocab_size=211), n_embd=64, n_head=4, n_positions=96,
    n_layer=3, embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0)
GEMMA_CFG = dataclasses.replace(
    Gemma3TextConfig.tiny(vocab_size=199), hidden_size=48, head_dim=12,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    num_hidden_layers=4, sliding_window=6, sliding_window_pattern=3)


@pytest.fixture(scope="module")
def gpt2_params():
    return gpt2.init_params(GPT2_CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def gemma_params():
    return gemma3.init_params(GEMMA_CFG, jax.random.PRNGKey(1))


def oracle(family, params, req, lora=None):
    """Batch-at-a-time generate() with a CONTIGUOUS cache — the greedy
    ground truth every admission path must reproduce bit-exactly."""
    gen = gpt2_generate if family == "gpt2" else gemma3_generate
    config = GPT2_CFG if family == "gpt2" else GEMMA_CFG
    ids = jnp.asarray([req.prompt], jnp.int32)
    cfg = SampleConfig(max_new_tokens=req.max_new_tokens, greedy=True,
                       eos_id=None, pad_id=0)
    return np.asarray(gen(config, params, ids, jnp.ones_like(ids), cfg,
                          lora=lora))[0].tolist()


def rand_lora(seed, scale=0.05):
    lora = init_lora_gemma3(GEMMA_CFG, LoRASpec(rank=3, alpha=6.0),
                            jax.random.PRNGKey(seed))
    leaves, td = jax.tree.flatten(lora)
    keys = jax.random.split(jax.random.PRNGKey(seed + 50), len(leaves))
    return jax.tree.unflatten(td, [
        l if l.ndim == 0 else scale * jax.random.normal(k, l.shape)
        for l, k in zip(leaves, keys)])


# ------------------------------ key hashing ----------------------------------

def test_chain_keys_full_blocks_chained_and_identity_seeded():
    p = list(range(100, 120))                    # 20 tokens, block_T 8
    ks = chain_keys(p, 8, "base")
    assert len(ks) == 2                          # partial tail never keyed
    # position-chained: a shorter prompt's chain is a prefix of the
    # longer one's, and a one-token change in block 0 reroots BOTH keys
    assert chain_keys(p[:16], 8, "base") == ks
    assert chain_keys(p[:8], 8, "base") == ks[:1]
    mut = [p[0] + 1] + p[1:]
    assert chain_keys(mut, 8, "base")[0] != ks[0]
    assert chain_keys(mut, 8, "base")[1] != ks[1]
    # same tokens under a different KV identity (another adapter /
    # another hot-swap generation) must never collide
    assert chain_keys(p, 8, "t1:0") != ks
    assert chain_keys(p, 8, "t1:1") != chain_keys(p, 8, "t1:0")
    assert chain_keys(p[:7], 8, "base") == []


# ------------------ cache-on engine: parity + stability ----------------------

@pytest.fixture(scope="module")
def cache_engine(gpt2_params):
    eng = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=3, block_T=8, num_blocks=64, max_prompt=16,
                    max_new_tokens=12, prefix_cache=True,
                    max_prompt_chunked=48))
    yield eng
    eng.close()


def _mix_prompts(rng, common):
    """Every round-21 admission path in one request set: classic miss,
    partial hit, full hit (COW re-feed), chunked long prompt, chunked
    with a partial hit shortening the suffix."""
    return [common + list(rng.integers(1, 200, 5)),
            common + list(rng.integers(1, 200, 3)),
            list(common),                              # full hit -> COW
            list(rng.integers(1, 200, 40)),            # chunked
            common[:8] + list(rng.integers(1, 200, 30))]


def test_prefix_reuse_parity_then_zero_retrace(cache_engine, gpt2_params):
    """Three waves of the full admission matrix. Wave 1 traces and is
    oracle-equal; wave 2 (repeat prompts -> full hits + COW) is
    oracle-equal and may still trace lazily-compiled programs (COW,
    newly-reachable small buckets); wave 3 must add ZERO executables."""
    eng = cache_engine
    rng = np.random.default_rng(0)
    common = list(rng.integers(1, 200, 16))    # two full blocks
    prompts = _mix_prompts(rng, common)

    for wave in range(3):
        if wave == 2:
            warm = eng.total_traces()
        reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        done = eng.drain()
        assert len(done) == len(reqs)
        for r in done:
            assert r.tokens == oracle("gpt2", gpt2_params, r), \
                f"wave {wave} req {r.id}"
        assert eng.alloc.in_use == 0 and eng.alloc.refcounts == {}
        eng.prefix.check_consistent()

    assert eng.total_traces() == warm, \
        (eng.total_traces(), warm, dict(eng.trace_counts))
    assert eng.cow_copies >= 1          # wave 2+ full hits re-fed via COW
    assert eng.prefix.hit_rate > 0.3    # repeats dominate the lookups
    h = eng.health()
    assert h["prefix_hit_rate"] == eng.prefix.hit_rate
    assert h["cow_copies"] == eng.cow_copies


def test_cache_off_engine_matches_cache_on_tokens(gpt2_params):
    """The cache is invisible in outputs: same prompts through a
    cache-OFF engine produce the same greedy tokens."""
    eng = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=3, block_T=8, num_blocks=64, max_prompt=16,
                    max_new_tokens=12, max_prompt_chunked=48))
    assert eng.prefix is None
    rng = np.random.default_rng(0)     # same stream as the cache-on test
    prompts = _mix_prompts(rng, list(rng.integers(1, 200, 16)))
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.drain()
    for r in reqs:
        assert r.tokens == oracle("gpt2", gpt2_params, r)
    eng.close()


def test_shared_pages_refcounted_while_live(cache_engine):
    """Two concurrent requests over the same registered prefix hold the
    SAME physical pages at refcount 2; draining parks them (ref 0,
    contents retained) rather than freeing or leaking."""
    eng = cache_engine
    rng = np.random.default_rng(7)
    common = list(rng.integers(1, 200, 16))
    seed = eng.submit(common, max_new_tokens=2)   # registers the blocks
    eng.drain()
    ra = eng.submit(common + list(rng.integers(1, 200, 4)),
                    max_new_tokens=4)
    rb = eng.submit(common + list(rng.integers(1, 200, 6)),
                    max_new_tokens=4)
    eng.step()                                    # both admitted
    assert ra.blocks[:2] == rb.blocks[:2] != seed.blocks
    for b in ra.blocks[:2]:
        assert eng.alloc.refcounts[b] == 2
    eng.drain()
    assert eng.alloc.refcounts == {} and eng.alloc.in_use == 0
    assert eng.alloc.parked_blocks > 0
    eng.prefix.check_consistent()


def test_chunk_buckets_capped_at_max_prompt_and_cancel_mid_walk(
        cache_engine, gpt2_params):
    """Auto-derived chunk widths cap at block-rounded max_prompt — NOT
    the chunked true cap — so a long prompt walks MULTIPLE chunks
    (bounding per-step prefill work) instead of one wide dispatch. A
    cancel mid-walk releases everything and leaves zero new traces."""
    eng = cache_engine
    assert eng.chunk_buckets == (8, 16)           # not (8, 16, 32, 48)
    warm = eng.total_traces()
    rng = np.random.default_rng(11)
    victim = eng.submit(list(rng.integers(1, 200, 40)), max_new_tokens=4)
    eng.step()                                    # first chunk only
    assert victim.state == "active" and victim.prefilling
    assert 0 < victim.prefill_pos < len(victim.prompt)
    eng.cancel(victim)
    assert victim.state == "cancelled" and not victim.blocks
    assert eng.alloc.in_use == 0 and eng.alloc.refcounts == {}
    survivor = eng.submit(list(rng.integers(1, 200, 40)), max_new_tokens=4)
    eng.drain()
    assert survivor.tokens == oracle("gpt2", gpt2_params, survivor)
    assert eng.total_traces() == warm


# ------------------------------ sampling -------------------------------------

def test_sampling_deterministic_and_temp0_is_greedy(gpt2_params):
    eng = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=64, max_prompt=16,
                    max_new_tokens=12, sampling=True))
    rng = np.random.default_rng(3)
    common = list(rng.integers(1, 200, 16))
    greedy = eng.submit(common, max_new_tokens=8)     # temperature 0
    s1 = eng.submit(common, max_new_tokens=8, temperature=0.9,
                    top_k=40, top_p=0.95, seed=1234)
    eng.drain()
    # sampling lanes compiled in, temperature 0: STILL the exact oracle
    assert greedy.tokens == oracle("gpt2", gpt2_params, greedy)
    s2 = eng.submit(common, max_new_tokens=8, temperature=0.9,
                    top_k=40, top_p=0.95, seed=1234)
    s3 = eng.submit(common, max_new_tokens=8, temperature=0.9,
                    top_k=40, top_p=0.95, seed=99)
    eng.drain()
    assert s1.tokens == s2.tokens                 # same seed, same slotting
    assert s2.tokens != s3.tokens or s2.tokens != greedy.tokens
    eng.close()


def test_sampling_seed_survives_admission_path_change(gpt2_params):
    """The per-request PRNG is keyed on (seed, position) — NOT on how
    the prompt entered the pool — so a fresh chunked admission and a
    later prefix-hit admission of the same request sample identically."""
    eng = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=64, max_prompt=16,
                    max_new_tokens=12, sampling=True, prefix_cache=True,
                    max_prompt_chunked=48))
    rng = np.random.default_rng(5)
    long_p = list(rng.integers(1, 200, 36))
    a = eng.submit(long_p, max_new_tokens=8, temperature=0.8, seed=7)
    eng.drain()                                   # chunked, cold cache
    b = eng.submit(long_p, max_new_tokens=8, temperature=0.8, seed=7)
    eng.drain()                                   # prefix hit
    assert eng.prefix.hit_rate > 0
    assert a.tokens == b.tokens
    eng.close()


# ------------------- gemma: sliding window + adapters ------------------------

def test_gemma_adapters_share_prefix_without_cross_tenant_reuse(
        gemma_params):
    """Sliding-window family, cache + chunking + adapter bank: the same
    token prefix under base vs. adapter routes gets DISTINCT cached
    pages (KV identity includes adapter generation), and every request
    matches its own adapter's contiguous-generate oracle."""
    a1 = rand_lora(5)
    bank = AdapterBank(rand_lora(5), capacity=2)
    eng = ServeEngine(
        "gemma", GEMMA_CFG, gemma_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=64, max_prompt=16,
                    max_new_tokens=10, prefix_cache=True,
                    max_prompt_chunked=40),
        bank=bank)
    eng.load_adapter("t1", a1)
    rng = np.random.default_rng(9)
    common = list(rng.integers(3, 190, 16))
    prompts = [common + list(rng.integers(3, 190, 5)),   # base, miss
               list(common),                             # base, full hit
               common + list(rng.integers(3, 190, 3)),   # t1: same tokens,
               list(rng.integers(3, 190, 33))]           # other identity
    route = [None, None, "t1", None]
    trees = {None: None, "t1": a1}
    reqs = [eng.submit(p, max_new_tokens=8, adapter=a)
            for p, a in zip(prompts, route)]
    eng.drain()
    for r, a in zip(reqs, route):
        assert r.tokens == oracle("gemma", gemma_params, r,
                                  lora=trees[a]), f"req {r.id} ({a})"
    # the adapter row shares TOKENS with the base rows but must not have
    # hit their pages: its chain keys live under a different identity
    assert chain_keys(common, 8, "base") != chain_keys(common, 8, "t1:0")
    assert eng.alloc.refcounts == {} and eng.alloc.in_use == 0
    eng.prefix.check_consistent()
    eng.close()
