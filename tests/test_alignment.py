"""End-to-end alignment harness test: --align_dump_dir on the train CLIs
produces npy dumps that the torch/PEFT mirror (tools/align_torch_mirror.py)
reproduces within tolerance — activations per layer, logits, loss, adapter
grads, post-AdamW-step adapter, and the N-step loss curve.

This is the rebuild of the reference's whole alignment culture in CI form
(reference: train_lora_gemma.cpp:620-920 align mode + pytorch_alignment/
mirror scripts + scripts/Finetune/run_*_alignment.sh, SURVEY.md §4.2):
where the reference dumps npy and leaves the comparison to a human-run
shell script, the mirror here runs in-process against real HF
transformers + PEFT and asserts the errors.
"""

import json
import os
import sys

import pytest

from tests.fixtures import (write_tiny_gemma3_dir, write_tiny_gpt2_dir,
                            write_wikitext_dir)

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
sys.path.insert(0, os.path.abspath(TOOLS))


@pytest.fixture(scope="module")
def dirs(tmp_path_factory):
    root = tmp_path_factory.mktemp("align")
    data = write_wikitext_dir(str(root / "wt2"))
    gpt2 = str(root / "gpt2")
    write_tiny_gpt2_dir(gpt2)
    gemma = str(root / "gemma")
    write_tiny_gemma3_dir(gemma)
    return {"root": root, "data": data, "gpt2": gpt2, "gemma": gemma}


def run_mirror(dump_dir, tol=2e-3):
    import align_torch_mirror
    rc = align_torch_mirror.main(["--dump_dir", dump_dir,
                                  "--tol", str(tol)])
    return rc


def test_gpt2_align_dump_matches_torch_mirror(dirs, capsys):
    from mobilefinetuner_tpu.cli import gpt2_lora_finetune
    dump = str(dirs["root"] / "dump_gpt2")
    rc = gpt2_lora_finetune.main([
        "--pretrained_dir", dirs["gpt2"], "--data_dir", dirs["data"],
        "--align_dump_dir", dump, "--align_steps", "3",
        "--seq_len", "32", "--batch_size", "2", "--lr", "1e-3",
        "--lora_targets", "attn_qkv,attn_proj,mlp_fc_in,mlp_fc_out"])
    assert rc == 0
    for f in ("act_embed.npy", "act_layer_00.npy", "logits.npy",
              "loss.npy", "losses.npy", "meta.json"):
        assert os.path.exists(os.path.join(dump, f)), f
    assert run_mirror(dump) == 0, capsys.readouterr().out
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["pass"], report


def test_gemma_align_dump_matches_torch_mirror(dirs, capsys):
    from mobilefinetuner_tpu.cli import train_lora_gemma
    dump = str(dirs["root"] / "dump_gemma")
    rc = train_lora_gemma.main([
        "--model_dir", dirs["gemma"], "--data_dir", dirs["data"],
        "--align_dump_dir", dump, "--align_steps", "3",
        "--seq_len", "32", "--batch_size", "2", "--lr", "1e-3",
        "--targets", "full"])
    assert rc == 0
    assert run_mirror(dump) == 0, capsys.readouterr().out
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["pass"], report
