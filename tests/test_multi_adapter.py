"""Multi-adapter batched serving (lora.stack_adapters + assign_adapters,
models/lora_apply.py "ids" routing): each batch row must produce EXACTLY
the output it would get from a single-adapter run with its own adapter —
greedy generation is row-independent, so the oracle is row-wise equality."""

import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.lora.lora import (LoRASpec, assign_adapters,
                                           init_lora_gemma3, init_lora_gpt2,
                                           stack_adapters)
from mobilefinetuner_tpu.models import gemma3, gpt2


def randomize(lora, seed):
    """B leaves init to zero (delta == 0 would make the test vacuous)."""
    leaves, treedef = jax.tree.flatten(lora)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(leaves))
    return jax.tree.unflatten(treedef, [
        l if l.ndim == 0 else 0.05 * jax.random.normal(k, l.shape)
        for l, k in zip(leaves, keys)])


GPT2_CFG = GPT2Config.tiny(vocab_size=211)
GEMMA_CFG = Gemma3TextConfig.tiny(vocab_size=199)


def make_adapters(init_fn, config, n=3, targets=None):
    spec = LoRASpec(rank=4, alpha=8.0, targets=targets)
    return [randomize(init_fn(config, spec, jax.random.PRNGKey(i)), 100 + i)
            for i in range(n)]


def test_assign_adapters_rejects_out_of_range_ids():
    """A jnp gather CLAMPS out-of-range indices, so before this check an
    id typo silently served the LAST adapter's weights to the
    overflowing rows — assign_adapters must instead raise a named
    ValueError for concrete ids outside the stacked bank."""
    stacked = stack_adapters(make_adapters(init_lora_gpt2, GPT2_CFG, n=2))
    with pytest.raises(ValueError, match=r"out of range.*2 adapter"):
        assign_adapters(stacked, [0, 2, 1])
    with pytest.raises(ValueError, match="out of range"):
        assign_adapters(stacked, [-1, 0])
    # in-range ids (incl. numpy arrays) pass through untouched
    out = assign_adapters(stacked, np.asarray([1, 0]))
    assert out["blocks"]["attn_qkv"]["ids"].tolist() == [1, 0]
    # traced ids (the serve engine routes inside jit) skip the check
    import jax as jax_mod

    @jax_mod.jit
    def route(ids):
        return assign_adapters(stacked, ids)["blocks"]["attn_qkv"]["ids"]

    assert route(jnp.asarray([0, 1])).tolist() == [0, 1]


def test_stack_adapters_validates_structure():
    a = make_adapters(init_lora_gpt2, GPT2_CFG, n=2)
    stacked = stack_adapters(a)
    entry = stacked["blocks"]["attn_qkv"]
    assert entry["A"].shape[0] == 2 and entry["scale"].shape == (2,)
    with pytest.raises(ValueError):
        stack_adapters([])
    other = init_lora_gpt2(GPT2_CFG, LoRASpec(rank=4, alpha=8.0,
                                              targets=["attn_proj"]),
                           jax.random.PRNGKey(9))
    with pytest.raises(ValueError):
        stack_adapters([a[0], other])


@pytest.mark.parametrize("family", ["gpt2", "gemma"])
def test_multi_adapter_forward_matches_per_row(family):
    if family == "gpt2":
        config, init_fn, model = GPT2_CFG, init_lora_gpt2, gpt2
    else:
        config, init_fn, model = GEMMA_CFG, init_lora_gemma3, gemma3
    vocab = config.vocab_size if family == "gpt2" else config.vocab_size
    params = model.init_params(config, jax.random.PRNGKey(0))
    adapters = make_adapters(init_fn, config, n=3)
    rng = np.random.default_rng(0)
    B, S = 5, 16
    ids_tok = jnp.asarray(rng.integers(0, vocab, (B, S)), jnp.int32)
    amask = jnp.ones_like(ids_tok)
    route = [0, 2, 1, 2, 0]
    multi = assign_adapters(stack_adapters(adapters), route)
    out_multi = model.forward(config, params, ids_tok,
                              attention_mask=amask, lora=multi)
    for b, a_idx in enumerate(route):
        out_single = model.forward(config, params, ids_tok[b:b + 1],
                                   attention_mask=amask[b:b + 1],
                                   lora=adapters[a_idx])
        np.testing.assert_allclose(np.asarray(out_multi[b]),
                                   np.asarray(out_single[0]),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("family", ["gpt2", "gemma"])
def test_multi_adapter_generate_matches_per_row(family):
    from mobilefinetuner_tpu.models.generate import (SampleConfig,
                                                     gemma3_generate,
                                                     gpt2_generate)
    if family == "gpt2":
        config, init_fn, gen = GPT2_CFG, init_lora_gpt2, gpt2_generate
        params = gpt2.init_params(config, jax.random.PRNGKey(0))
    else:
        config, init_fn, gen = GEMMA_CFG, init_lora_gemma3, gemma3_generate
        params = gemma3.init_params(config, jax.random.PRNGKey(0))
    adapters = make_adapters(init_fn, config, n=2)
    rng = np.random.default_rng(1)
    B, P, N = 4, 8, 6
    prompts = jnp.asarray(rng.integers(1, config.vocab_size, (B, P)),
                          jnp.int32)
    amask = jnp.ones_like(prompts)
    cfg = SampleConfig(max_new_tokens=N, greedy=True, eos_id=None)
    route = [1, 0, 0, 1]
    multi = assign_adapters(stack_adapters(adapters), route)
    out_multi = np.asarray(gen(config, params, prompts, amask, cfg,
                               lora=multi))
    for b, a_idx in enumerate(route):
        out_single = np.asarray(gen(config, params, prompts[b:b + 1],
                                    amask[b:b + 1], cfg,
                                    lora=adapters[a_idx]))
        np.testing.assert_array_equal(out_multi[b], out_single[0],
                                      err_msg=f"row {b} adapter {a_idx}")


def test_multi_adapter_cli(tmp_path):
    """generate CLI end-to-end: two adapters served in one batch; routed
    rows must equal the single-adapter runs."""
    import json
    from fixtures import write_tiny_gpt2_dir, write_wikitext_dir
    from mobilefinetuner_tpu.cli.generate import main as gen_main
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main as train
    import contextlib, io
    gpt2_dir = str(tmp_path / "gpt2")
    write_tiny_gpt2_dir(gpt2_dir)
    wiki = write_wikitext_dir(str(tmp_path / "wiki"))
    paths = []
    for seed in (1, 2):
        out = str(tmp_path / f"a{seed}.safetensors")
        rc = train(["--pretrained_dir", gpt2_dir, "--data_dir", wiki,
                    "--steps", "2", "--batch_size", "2", "--seq_len",
                    "32", "--seed", str(seed), "--lora_out", out])
        assert rc == 0
        paths.append(out)

    def run(argv):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert gen_main(argv) == 0
        return [json.loads(ln) for ln in buf.getvalue().splitlines()
                if ln.startswith("{")]

    base = ["--pretrained_dir", gpt2_dir, "--greedy", "--no_eos_stop",
            "--max_new_tokens", "6", "--json",
            "--prompt", "hello there", "--prompt", "general kenobi"]
    multi = run(base + ["--lora_path", ",".join(paths),
                        "--adapter_ids", "1,0"])
    single1 = run(base[:-2] + ["--lora_path", paths[1], "--lora_dynamic"])
    single0 = run(["--pretrained_dir", gpt2_dir, "--greedy",
                   "--no_eos_stop", "--max_new_tokens", "6", "--json",
                   "--prompt", "general kenobi",
                   "--lora_path", paths[0], "--lora_dynamic"])
    assert multi[0]["ids"] == single1[0]["ids"]
    assert multi[1]["ids"] == single0[0]["ids"]