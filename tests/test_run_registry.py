"""Run-registry tests (core/run_registry.py, DESIGN.md §28): two-phase
self-contained records through the Telemetry flush path, append-only
interrupted-repair for SIGKILLed runs (the r15 kill-safe contract at
registry granularity), resolution by run id / prefix / git rev, and the
context-manager exit-name convention."""

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mobilefinetuner_tpu.core.run_registry import (RunRegistry,
                                                   config_fingerprint,
                                                   git_rev, registry_from)
from mobilefinetuner_tpu.core.telemetry import Telemetry, validate_event

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def read_lines(path):
    with open(path) as f:
        return [json.loads(l) for l in f.read().splitlines() if l.strip()]


# --------------------------- record lifecycle -------------------------------

def test_begin_and_finalize_write_two_validating_records(tmp_path):
    path = str(tmp_path / "runs.jsonl")
    reg = RunRegistry(path)
    h = reg.begin("eval", "eval_ppl", config={"split": "valid", "b": 2},
                  platform="cpu", artifacts=["/tmp/out.json"])
    h.finalize("ok")
    recs = read_lines(path)
    assert [r["phase"] for r in recs] == ["start", "end"]
    for r in recs:
        assert r["event"] == "run"
        assert validate_event(r) is None, validate_event(r)
    start, end = recs
    # two-phase records are SELF-CONTAINED: the end record re-carries
    # the full identity block, no join needed to interpret it
    assert end["run_id"] == start["run_id"]
    assert end["kind"] == "eval" and end["tool"] == "eval_ppl"
    assert start["status"] == "running" and end["status"] == "ok"
    assert start["wall_s"] is None and end["wall_s"] >= 0
    assert start["pid"] == os.getpid()
    assert end["config_fingerprint"] == config_fingerprint(
        {"split": "valid", "b": 2})


def test_records_fold_to_one_finalized_record_per_run(tmp_path):
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    h1 = reg.begin("bench", "bench", platform="cpu")
    h1.finalize("ok", artifacts=["BENCH_SUITE.json"])
    h2 = reg.begin("serve", "serve_bench", platform="cpu")
    h2.finalize("preempted")
    recs = reg.records()
    assert len(recs) == 2
    by_id = {r["run_id"]: r for r in recs}
    assert by_id[h1.run_id]["status"] == "ok"
    assert by_id[h1.run_id]["artifacts"] == ["BENCH_SUITE.json"]
    assert by_id[h2.run_id]["status"] == "preempted"


def test_finalize_is_idempotent(tmp_path):
    path = str(tmp_path / "runs.jsonl")
    reg = RunRegistry(path)
    h = reg.begin("train", "train_lora", platform="cpu")
    h.finalize("ok")
    h.finalize("interrupted")  # nested crash handler racing end_run
    recs = [r for r in read_lines(path) if r["phase"] == "end"]
    assert len(recs) == 1 and recs[0]["status"] == "ok"


def test_context_manager_uses_exception_name_as_status(tmp_path):
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    try:
        with reg.begin("train", "t", platform="cpu"):
            raise MemoryError("boom")
    except MemoryError:
        pass
    (rec,) = reg.records()
    assert rec["status"] == "MemoryError"
    with reg.begin("train", "t2", platform="cpu"):
        pass
    by_tool = {r["tool"]: r for r in reg.records()}
    assert by_tool["t2"]["status"] == "ok"


def test_registered_run_mirrors_into_own_telemetry_stream(tmp_path):
    """The `run` event rides the run's own --telemetry_out stream too —
    the observatory's join key between stream and registry."""
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    stream = str(tmp_path / "run.jsonl")
    with Telemetry(stream) as tel:
        h = reg.begin("eval", "eval_mmlu", platform="cpu", telemetry=tel)
        h.finalize("ok")
    evs = [r for r in read_lines(stream) if r["event"] == "run"]
    assert [r["phase"] for r in evs] == ["start", "end"]
    assert evs[0]["run_id"] == h.run_id
    for r in evs:
        assert validate_event(r) is None


# --------------------------- crash repair -----------------------------------

_KILL_CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
from mobilefinetuner_tpu.core.run_registry import RunRegistry
reg = RunRegistry(sys.argv[1])
h = reg.begin("train", "killed_tool", platform="cpu")
print("REGISTERED", flush=True)
time.sleep(60)  # SIGKILLed before finalize
"""


def test_sigkill_between_start_and_finalize_settles_interrupted(tmp_path):
    """The r15 kill-safe contract at registry granularity: a run
    SIGKILLed mid-flight leaves a durable start record (per-event
    flush), and the NEXT registry open appends an `interrupted` end
    record — append-only repair, nothing rewritten, no zombie
    "running" rows."""
    path = str(tmp_path / "runs.jsonl")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    child = subprocess.Popen(
        [sys.executable, "-c", _KILL_CHILD.format(repo=REPO), path],
        stdout=subprocess.PIPE, env=env, text=True)
    try:
        assert child.stdout.readline().strip() == "REGISTERED"
        os.kill(child.pid, signal.SIGKILL)
    finally:
        child.wait(timeout=30)

    raw = read_lines(path)
    assert [r["phase"] for r in raw] == ["start"]  # durable, unfinalized
    reg = RunRegistry(path)
    (rec,) = reg.records()  # records() settles by default
    assert rec["status"] == "interrupted"
    # the repair is APPEND-ONLY: start line untouched, end line added
    raw = read_lines(path)
    assert [r["phase"] for r in raw] == ["start", "end"]
    assert raw[0] == [r for r in raw if r["phase"] == "start"][0]
    # settle is idempotent — a second open appends nothing
    assert reg.settle() == 0
    assert len(read_lines(path)) == 2


def test_settle_leaves_live_runs_alone(tmp_path):
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    reg.begin("train", "live_tool", platform="cpu")  # this pid: alive
    assert reg.settle() == 0
    (rec,) = reg.records()
    assert rec["status"] == "running"


# --------------------------- resolution -------------------------------------

def test_resolve_by_id_prefix_and_git_rev(tmp_path):
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    h = reg.begin("bench", "bench", platform="cpu", root=REPO)
    h.finalize("ok")
    rec = reg.resolve(h.run_id)
    assert rec and rec["tool"] == "bench"
    # unique prefix resolves too (operator-friendly short ids)
    assert reg.resolve(h.run_id[:-2])["run_id"] == h.run_id
    rev = git_rev(REPO)
    assert rev and len(rev) == 12
    assert reg.resolve(rev)["run_id"] == h.run_id
    assert reg.resolve(rev[:7])["run_id"] == h.run_id
    assert reg.resolve("nonexistent") is None


def test_artifact_for_returns_first_existing_artifact(tmp_path):
    art = tmp_path / "BENCH_X.json"
    art.write_text("{}")
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    h = reg.begin("bench", "bench", platform="cpu",
                  artifacts=["/nope/gone.json", str(art)])
    h.finalize("ok")
    assert reg.artifact_for(h.run_id) == str(art)
    assert reg.artifact_for(h.run_id, suffix=".jsonl") is None


def test_fingerprint_ignores_unserializable_and_ordering():
    a = config_fingerprint({"b": 1, "a": "x", "fn": object()})
    b = config_fingerprint({"a": "x", "b": 1})
    assert a == b and len(a) == 12
    assert config_fingerprint({"a": "y", "b": 1}) != a
    assert config_fingerprint(None) is None


def test_registry_from_env_and_flag(tmp_path, monkeypatch):
    monkeypatch.delenv("MFT_RUN_REGISTRY", raising=False)
    assert registry_from("") is None

    class Args:
        run_registry = ""
    assert RunRegistry.from_args(Args()) is None
    monkeypatch.setenv("MFT_RUN_REGISTRY", str(tmp_path / "env.jsonl"))
    assert registry_from("").path.endswith("env.jsonl")
    Args.run_registry = str(tmp_path / "flag.jsonl")
    assert RunRegistry.from_args(Args()).path.endswith("flag.jsonl")


def test_concurrent_writers_are_keyed_by_run_id_not_seq(tmp_path):
    """Two handles appending through short-lived Telemetry opens may
    interleave; readers key on run_id so both runs resolve."""
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    h1 = reg.begin("eval", "a", platform="cpu")
    h2 = reg.begin("eval", "b", platform="cpu")
    h2.finalize("ok")
    h1.finalize("ok")
    recs = reg.records()
    assert {r["tool"] for r in recs} == {"a", "b"}
    assert all(r["status"] == "ok" for r in recs)
