"""MMLU runner unit tests (reference: mmlu/mmlu_runner.{h,cpp} behavior)."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import write_tiny_mmlu_dir

from mobilefinetuner_tpu.eval.mmlu import (MCQItem, build_prompt, evaluate,
                                           letter_token_ids, load_split,
                                           parse_csv_line, read_mmlu_csv)

ITEM = MCQItem("toy", "What is 2 + 2 ?", "3", "4", "5", "6", "B")

_PREP_COUNTER = [0]


def _load_prep():
    """Import tools/mmlu_prep.py under a fresh module name per call (the
    tool mutates no global state, but tests must not share one import)."""
    import importlib.util
    _PREP_COUNTER[0] += 1
    spec = importlib.util.spec_from_file_location(
        f"mmlu_prep{_PREP_COUNTER[0]}",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "mmlu_prep.py"))
    prep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(prep)
    return prep


def test_parse_csv_line_quotes():
    assert parse_csv_line('a,"b, c",d') == ["a", "b, c", "d"]
    assert parse_csv_line('"say ""hi""",x') == ['say "hi"', "x"]
    assert parse_csv_line("plain,row") == ["plain", "row"]


def test_build_prompt_zero_shot():
    p = build_prompt(ITEM)
    assert p == ("Question: What is 2 + 2 ?\n"
                 "A. 3\nB. 4\nC. 5\nD. 6\nAnswer: ")


def test_build_prompt_few_shot_separators():
    shot = MCQItem("toy", "Which animal barks ?", "dog", "cat", "fish",
                   "bird", "A")
    p = build_prompt(ITEM, [shot])
    # shot answered + blank-line separator, then the query with trailing
    # space (mmlu_runner.cpp build_prompt)
    assert p.startswith("Question: Which animal barks ?\n")
    assert "Answer: A\n\nQuestion: What is 2 + 2 ?" in p
    assert p.endswith("Answer: ")


def test_headerless_csv_subject_from_filename(tmp_path):
    root = write_tiny_mmlu_dir(str(tmp_path))
    by_subject = load_split(root, "test")
    assert set(by_subject) == {"toy_math", "toy_facts"}
    assert all(len(v) == 4 for v in by_subject.values())
    assert by_subject["toy_math"][0].answer == "B"


def test_headered_csv(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text("subject,question,A,B,C,D,answer\n"
                 "astro,Is space big?,yes,no,maybe,unknown,A\n")
    items = read_mmlu_csv(str(p))
    assert items[0].subject == "astro" and items[0].answer == "A"


def test_evaluate_with_oracle_logits(tmp_path):
    """A logits_fn that always prefers the correct letter's token id gives
    accuracy 1.0; one preferring a wrong letter gives 0."""
    root = write_tiny_mmlu_dir(str(tmp_path))
    by_subject = load_split(root, "test")
    encode = lambda s: [ord(c) for c in s[-200:]]
    lids = letter_token_ids(encode)
    answers = {build_prompt(i, None): i.answer
               for items in by_subject.values() for i in items}

    def oracle(prompt_suffix_ids):
        # recover which item this is by matching the prompt tail
        text = "".join(chr(c) for c in prompt_suffix_ids[0])
        logits = np.zeros(300, np.float32)
        for p, ans in answers.items():
            if text.endswith(p[-min(len(p), 200):]):
                logits[lids["ABCD".index(ans)]] = 10.0
                return logits
        return logits

    res = evaluate(by_subject, oracle, encode, fewshot_k=0)
    assert res.macro == 1.0 and res.micro == 1.0 and res.total == 8

    def always_wrong(ids):
        logits = np.zeros(300, np.float32)
        text = "".join(chr(c) for c in ids[0])
        for p, ans in answers.items():
            if text.endswith(p[-min(len(p), 200):]):
                wrong = next(l for l in "ABCD" if l != ans)
                logits[lids["ABCD".index(wrong)]] = 10.0
        return logits

    res2 = evaluate(by_subject, always_wrong, encode, fewshot_k=0)
    assert res2.micro == 0.0


def test_fewshot_excludes_current_item(tmp_path):
    """Few-shot context must not contain the query itself (no-leak rule,
    mmlu_runner.cpp evaluate)."""
    root = write_tiny_mmlu_dir(str(tmp_path))
    by_subject = load_split(root, "test")
    seen_prompts = []
    encode = lambda s: [ord(c) for c in s]

    def spy(ids):
        seen_prompts.append("".join(chr(c) for c in ids[0]))
        return np.zeros(300, np.float32)

    evaluate({"toy_math": by_subject["toy_math"]}, spy, encode, fewshot_k=2)
    for prompt in seen_prompts:
        q = prompt.rsplit("Question: ", 1)[1]
        shots_part = prompt[: len(prompt) - len("Question: " + q)]
        assert q.split("\n")[0] not in shots_part


def test_batched_matches_itemwise(tmp_path):
    """evaluate_batched must produce identical predictions/reports to
    evaluate() for any logits function — here a deterministic hash of the
    prompt ids, so every item has a well-defined 'model opinion' and the
    two runners must agree item for item (incl. fewshot exclusion and the
    padded partial final batch)."""
    from mobilefinetuner_tpu.eval.mmlu import evaluate_batched
    root = write_tiny_mmlu_dir(str(tmp_path))
    by_subject = load_split(root, "test")
    encode = lambda s: [ord(c) for c in s]

    def fake_logits_row(ids_row):
        h = (np.int64(7) * np.sum(ids_row, dtype=np.int64)) % 997
        v = np.zeros(300, np.float32)
        v[h % 300] = 5.0
        v[(h * 3) % 300] = 4.0
        return v

    def itemwise(ids):  # [1, S] (no padding in the itemwise runner)
        return fake_logits_row(ids[0])

    def batched(ids, last):  # [B, S] right-padded; sum ignores pad zeros
        return np.stack([fake_logits_row(ids[r, :last[r] + 1])
                         for r in range(ids.shape[0])])

    for k in (0, 1):
        a = evaluate(by_subject, itemwise, encode, fewshot_k=k)
        b = evaluate_batched(by_subject, batched, encode, fewshot_k=k,
                             batch_size=3, max_len=512)
        assert a.total == b.total
        assert a.micro == b.micro and a.macro == b.macro
        assert [(r.subject, r.correct, r.total) for r in a.per_subject] \
            == [(r.subject, r.correct, r.total) for r in b.per_subject]


def test_category_rollup_math():
    """4-macro-category rollup (reference: hendrycks_test/categories.py):
    macro = mean of member subjects' accuracies, micro = pooled items;
    non-official subjects land in 'uncategorized'."""
    from mobilefinetuner_tpu.eval.mmlu import MMLUResult, SubjectReport
    from mobilefinetuner_tpu.eval.mmlu_categories import (
        category_rollup, subject_macro_category)
    assert subject_macro_category("college_physics") == "STEM"
    assert subject_macro_category("jurisprudence") == "humanities"
    assert subject_macro_category("sociology") == "social sciences"
    assert subject_macro_category("marketing") == \
        "other (business, health, misc.)"
    assert subject_macro_category("klingon_opera") == "uncategorized"

    rs = [SubjectReport("college_physics", 3, 4),   # 0.75 STEM
          SubjectReport("abstract_algebra", 1, 4),  # 0.25 STEM
          SubjectReport("sociology", 2, 2),         # 1.00 social sciences
          SubjectReport("klingon_opera", 0, 2)]     # uncategorized
    result = MMLUResult(rs, 0.0, 0.0, 12)
    cats = category_rollup(result)
    assert cats["STEM"] == {"macro_accuracy": 0.5,
                            "micro_accuracy": 0.5,
                            "subjects": 2, "correct": 4, "total": 8}
    assert cats["social sciences"]["macro_accuracy"] == 1.0
    assert cats["uncategorized"]["total"] == 2
    assert "humanities" not in cats  # no evaluated subjects -> omitted


def test_mmlu_prep_synthetic_and_zip_roundtrip(tmp_path):
    """tools/mmlu_prep.py: synthetic mode covers the full 57-subject
    taxonomy in Hendrycks layout; zip normalization re-emits the same
    items (quoted fields survive)."""
    import contextlib
    import io
    import json as json_mod
    import zipfile
    prep = _load_prep()

    out1 = str(tmp_path / "synth")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert prep.main(["--synthetic", "4", "--out", out1]) == 0
    rep = json_mod.loads(buf.getvalue())
    assert rep["splits"]["test"] == {"subjects": 57, "items": 57 * 4}
    assert rep["official_subjects_missing"] == []

    by_subject = load_split(out1, "test")
    assert len(by_subject) == 57
    item = by_subject["abstract_algebra"][0]
    assert item.answer in "ABCD"
    assert '"' in item.question  # quoted key survived the CSV round trip

    # zip -> normalized dir round trip preserves items
    zpath = str(tmp_path / "src.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        with open(os.path.join(out1, "test",
                               "abstract_algebra_test.csv")) as f:
            z.writestr("data/test/abstract_algebra_test.csv", f.read())
    out2 = str(tmp_path / "fromzip")
    with contextlib.redirect_stdout(io.StringIO()):
        assert prep.main(["--source", zpath, "--out", out2]) == 0
    again = load_split(out2, "test")["abstract_algebra"]
    orig = by_subject["abstract_algebra"]
    assert [(i.question, i.A, i.B, i.C, i.D, i.answer) for i in again] == \
        [(i.question, i.A, i.B, i.C, i.D, i.answer) for i in orig]


def test_mmlu_prep_zip_headered_csv_no_junk_row(tmp_path):
    """Headered CSVs inside a zip go through the runner's own header
    detection — the header row must NOT become a dataset item (regression:
    the zip branch used to parse rows blindly)."""
    import contextlib
    import io
    import zipfile
    prep = _load_prep()
    zpath = str(tmp_path / "h.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("data/test/astronomy_test.csv",
                   "question,a,b,c,d,answer\nWhat is 2+2?,1,2,3,4,D\n")
    out = str(tmp_path / "out")
    with contextlib.redirect_stdout(io.StringIO()):
        assert prep.main(["--source", zpath, "--out", out]) == 0
    items = load_split(out, "test")["astronomy"]
    assert len(items) == 1
    assert items[0].question == "What is 2+2?"
    assert items[0].answer == "D"


def test_mmlu_prep_headered_subject_column_survives(tmp_path):
    """A headered CSV carrying its OWN subject column must keep those
    labels through normalization (regression: collect_source used to
    refile every row under the filename-derived subject). An EMPTY
    subject cell falls back to the filename subject, and a subject cell
    that is not a safe filename component (path separators, '..') must
    not become a path — it is refiled under the filename subject too."""
    import contextlib
    import io
    import zipfile
    prep = _load_prep()
    zpath = str(tmp_path / "s.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr(
            "data/test/mixed_bag_test.csv",
            "subject,question,a,b,c,d,answer\n"
            "astronomy,What orbits Earth?,Moon,Sun,Mars,Venus,A\n"
            "virology,What is a virion?,particle,cell,organ,spore,A\n"
            ",Empty subject cell?,w,x,y,z,A\n"
            "../escape,Traversal subject?,w,x,y,z,A\n"
            "bad/slash,Separator subject?,w,x,y,z,A\n")
    out = str(tmp_path / "out")
    with contextlib.redirect_stdout(io.StringIO()):
        assert prep.main(["--source", zpath, "--out", out]) == 0
    split = load_split(out, "test")
    assert sorted(split) == ["astronomy", "mixed_bag", "virology"]
    assert split["astronomy"][0].question == "What orbits Earth?"
    assert split["virology"][0].question == "What is a virion?"
    # empty + unsafe subjects all landed under the filename subject
    assert sorted(i.question for i in split["mixed_bag"]) == [
        "Empty subject cell?", "Separator subject?", "Traversal subject?"]
    # and nothing escaped <out>/test/ ('../escape' would have written
    # <out>/escape_test.csv)
    assert not os.path.exists(os.path.join(out, "escape_test.csv"))
