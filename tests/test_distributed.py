"""Multi-host distributed backend tests (parallel/distributed.py).

A real multi-process run needs N hosts; what CAN be validated here (the
reference's mocked-telemetry testing culture, SURVEY.md §4.6, applied to
the distributed runtime) is everything except the socket layer:
single-process no-op semantics, the DCN-aware mesh layout rule, and the
global-array feeding path (make_array_from_callback produces bit-identical
placement to device_put when every shard is addressable).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mobilefinetuner_tpu.parallel import distributed as dist
from mobilefinetuner_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                               shard_batch, shard_params)


def test_initialize_noop_single_process(monkeypatch):
    """No coordinator, no env, no pod -> initialize must not start the
    distributed service (it would hang waiting for peers)."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert dist.initialize() is False
    assert jax.process_count() == 1


def test_is_coordinator_single_process():
    assert dist.is_coordinator() is True


def test_hybrid_mesh_single_process_matches_make_mesh():
    m = dist.make_hybrid_mesh(data=2, fsdp=4)
    assert m.axis_names == ("data", "fsdp")
    assert m.shape["data"] == 2 and m.shape["fsdp"] == 4
    assert set(np.asarray(m.devices).ravel()) == set(jax.devices())


def test_hybrid_mesh_infers_fsdp():
    m = dist.make_hybrid_mesh(data=2, fsdp=None)
    assert m.shape["fsdp"] == len(jax.devices()) // 2


def test_hybrid_mesh_rejects_bad_shape():
    with pytest.raises(ValueError):
        dist.make_hybrid_mesh(data=3, fsdp=3)


def test_device_put_global_matches_device_put():
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    sh = NamedSharding(mesh, P(None, "fsdp"))
    x = np.arange(32 * 8, dtype=np.float32).reshape(32, 8)
    a = dist.device_put_global(x, sh)
    b = jax.device_put(x, sh)
    assert a.sharding == b.sharding
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_device_put_global_batch_sharding():
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    sh = batch_sharding(mesh)
    x = np.arange(16 * 4, dtype=np.int32).reshape(16, 4)
    arr = dist.device_put_global(x, sh)
    assert arr.sharding == sh
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_gather_to_host_single_process_identity():
    t = {"a": jax.numpy.ones((4, 4)), "b": 3}
    out = dist.gather_to_host(t)
    assert out["a"] is t["a"] and out["b"] == 3


def test_make_array_from_callback_path_equivalence():
    """The multi-process feeding path (exercised explicitly, since
    process_count()==1 would route around it): callback-built global
    arrays must equal the device_put result shard for shard."""
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    sh = batch_sharding(mesh)
    x = np.random.default_rng(0).normal(size=(16, 8)).astype(np.float32)
    via_cb = jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])
    via_dp = jax.device_put(x, sh)
    np.testing.assert_array_equal(np.asarray(via_cb), np.asarray(via_dp))
    for s_cb, s_dp in zip(via_cb.addressable_shards,
                          via_dp.addressable_shards):
        assert s_cb.device == s_dp.device
        np.testing.assert_array_equal(np.asarray(s_cb.data),
                                      np.asarray(s_dp.data))


def test_shard_batch_routes_through_global_path():
    """shard_batch output must be usable as a jit input over the mesh and
    carry the expected batch sharding."""
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    batch = {"input_ids": np.ones((8, 16), np.int32),
             "labels": np.full((8, 16), -100, np.int32)}
    placed = shard_batch(batch, mesh)
    assert placed["input_ids"].sharding.spec == P(("data", "fsdp"))

    @jax.jit
    def f(b):
        return jnp.sum(b["input_ids"])

    assert int(f(placed)) == 8 * 16


def _launch_smoke(nprocs: int, ndev: int, timeout: int = 420):
    """Launch tools/multihost_smoke.py as nprocs coordinated processes
    (jax.distributed over CPU, ndev virtual devices each) and assert every
    process converges to the SAME loss — which requires the cross-process
    collectives (param all-gathers, grad reductions) to have actually
    run."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}  # workers set their own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(repo, "tools", "multihost_smoke.py"),
         coord, str(nprocs), str(i), str(ndev)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(nprocs)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert "MULTIHOST_OK" in out, out
    ok_lines = [ln for out in outs for ln in out.splitlines()
                if "MULTIHOST_OK" in ln]
    losses = {ln.split(" loss=")[1].split()[0] for ln in ok_lines}
    assert len(losses) == 1, f"processes disagree: {losses}"
    # the Gemma phase (V-sharded embed + vocab-parallel CE over DCN) must
    # also agree across processes
    glosses = {ln.split("gemma_loss=")[1].split()[0] for ln in ok_lines}
    assert len(glosses) == 1, f"Gemma losses disagree: {glosses}"


# this jaxlib's CPU client refuses cross-process computations outright
# (XlaRuntimeError: "Multiprocess computations aren't implemented on the
# CPU backend"), so the coordinated-process smokes below cannot pass
# under JAX_PLATFORMS=cpu — they'd burn ~30 s of tier-1 budget spawning
# and compiling before hitting that wall. Skip them on CPU; they run on
# any real backend (and as the pod-dryrun artifact).
_CPU_NO_MULTIPROCESS = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="cross-process computations unimplemented on the CPU backend")


@_CPU_NO_MULTIPROCESS
def test_two_process_training_step_agrees():
    """REAL multi-process validation at (2 procs × 4 dev)."""
    _launch_smoke(nprocs=2, ndev=4)


@_CPU_NO_MULTIPROCESS
def test_four_process_hybrid_mesh_agrees():
    """Four coordinated processes × 2 devices: the DCN-aware hybrid mesh
    packs fsdp inside each process's slice and the data axis crosses all
    four processes (the pod topology at CI scale; the 8-proc × 8-dev
    v5e-64 shape runs as an artifact via tools/multihost_smoke.py and
    the driver's dryrun_multichip(64))."""
    _launch_smoke(nprocs=4, ndev=2)


def test_shard_params_global_path():
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    params = {"w": np.random.default_rng(1).normal(
        size=(256, 512)).astype(np.float32)}
    placed = shard_params(params, mesh, min_size=1024)
    spec = placed["w"].sharding.spec
    assert "fsdp" in tuple(spec)
    np.testing.assert_allclose(np.asarray(placed["w"]), params["w"])
