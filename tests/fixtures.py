"""Tiny on-disk HF-format checkpoint + data fixtures for CLI tests.

Builds what the CLIs expect to find in a real model dir: config.json,
model.safetensors with HF key schemes, tokenizer files — all tiny enough
for CPU test runs (the analog of the reference's committed small fixtures,
SURVEY.md §4.2)."""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.io.checkpoints import gpt2_params_to_hf
from mobilefinetuner_tpu.io.safetensors_io import save_safetensors
from mobilefinetuner_tpu.models import gemma3, gpt2

WIKI_LINES = [
    " = Heading = ",
    " The quick brown fox jumps over the lazy dog . ",
    " In 1984 , George Orwell wrote about surveillance states . ",
    " Prices rose 3.5 % to $ 1,234.56 yesterday . ",
    " Tokenization matters for language models . ",
    " A small corpus still produces many chunks when repeated . ",
] * 30


def write_wikitext_dir(d: str) -> str:
    os.makedirs(d, exist_ok=True)
    for split, frac in (("train", 1.0), ("valid", 0.3), ("test", 0.3)):
        n = int(len(WIKI_LINES) * frac)
        with open(os.path.join(d, f"wiki.{split}.tokens"), "w") as f:
            f.write("\n".join(WIKI_LINES[:n]) + "\n")
    return d


def train_tiny_gpt2_tokenizer(d: str):
    """Train a tiny byte-level BPE with the HF tokenizers lib and save
    vocab.json/merges.txt (the files GPT2BPETokenizer.from_pretrained
    reads)."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    trainer = trainers.BpeTrainer(
        vocab_size=600, special_tokens=["<|endoftext|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False)
    tok.train_from_iterator(WIKI_LINES, trainer)
    tok.model.save(d)
    with open(os.path.join(d, "vocab.json")) as f:
        return len(json.load(f))


def write_tiny_gpt2_dir(d: str, seed: int = 0,
                        **config_overrides) -> GPT2Config:
    """HF-format GPT-2 checkpoint dir: config.json + model.safetensors
    (HF GPT2LMHeadModel keys, Conv1D [in, out] layout) + tokenizer files.
    config_overrides replace GPT2Config.tiny fields — the elastic-resume
    mesh tests use n_embd=128 so the stacked per-layer leaves exceed the
    FSDP min_size and actually re-shard across mesh shapes."""
    import dataclasses
    os.makedirs(d, exist_ok=True)
    vocab_size = train_tiny_gpt2_tokenizer(d)
    config = GPT2Config.tiny(vocab_size=vocab_size)
    if config_overrides:
        config = dataclasses.replace(config, **config_overrides)
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"model_type": "gpt2", "vocab_size": config.vocab_size,
                   "n_positions": config.n_positions,
                   "n_embd": config.n_embd, "n_layer": config.n_layer,
                   "n_head": config.n_head,
                   "layer_norm_epsilon": config.layer_norm_epsilon,
                   "activation_function": "gelu_new"}, f)
    params = gpt2.init_params(config, jax.random.PRNGKey(seed))
    tensors = gpt2_params_to_hf(jax.tree.map(np.asarray, params))
    save_safetensors(os.path.join(d, "model.safetensors"), tensors,
                     metadata={"format": "pt"})
    return config


from mobilefinetuner_tpu.io.checkpoints import \
    gemma3_params_to_hf  # production inverse mapper (io/checkpoints.py)


def train_tiny_gemma_tokenizer(path: str):
    from tokenizers import Tokenizer, models, normalizers, trainers
    byte_tokens = [f"<0x{b:02X}>" for b in range(256)]
    tok = Tokenizer(models.BPE(unk_token="<unk>", byte_fallback=True))
    tok.normalizer = normalizers.Replace(" ", "▁")
    trainer = trainers.BpeTrainer(
        vocab_size=700,
        special_tokens=["<pad>", "<eos>", "<bos>", "<unk>"] + byte_tokens,
        show_progress=False)
    tok.train_from_iterator(WIKI_LINES, trainer)
    tok.save(path)
    return tok.get_vocab_size()


def write_tiny_gemma3_dir(d: str, seed: int = 0) -> Gemma3TextConfig:
    os.makedirs(d, exist_ok=True)
    vocab_size = train_tiny_gemma_tokenizer(os.path.join(d,
                                                         "tokenizer.json"))
    config = Gemma3TextConfig.tiny(vocab_size=vocab_size)
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"model_type": "gemma3_text",
                   "vocab_size": config.vocab_size,
                   "hidden_size": config.hidden_size,
                   "intermediate_size": config.intermediate_size,
                   "num_hidden_layers": config.num_hidden_layers,
                   "num_attention_heads": config.num_attention_heads,
                   "num_key_value_heads": config.num_key_value_heads,
                   "head_dim": config.head_dim,
                   "max_position_embeddings":
                       config.max_position_embeddings,
                   "rms_norm_eps": config.rms_norm_eps,
                   "rope_theta": config.rope_theta,
                   "rope_local_base_freq": config.rope_local_base_freq,
                   "sliding_window": config.sliding_window,
                   "query_pre_attn_scalar": config.query_pre_attn_scalar,
                   "sliding_window_pattern":
                       config.sliding_window_pattern}, f)
    params = gemma3.init_params(config, jax.random.PRNGKey(seed))
    tensors = gemma3_params_to_hf(jax.tree.map(np.asarray, params))
    save_safetensors(os.path.join(d, "model.safetensors"), tensors,
                     metadata={"format": "pt"})
    return config


MMLU_ROWS = [
    ("What is 2 + 2 ?", "3", "4", "5", "6", "B"),
    ("The sky is usually what color ?", "green", "red", "blue", "yellow",
     "C"),
    ("Which animal barks ?", "dog", "cat", "fish", "bird", "A"),
    ("How many days in a week ?", "five", "six", "eight", "seven", "D"),
]


def write_tiny_mmlu_dir(d: str, split: str = "test") -> str:
    sd = os.path.join(d, split)
    os.makedirs(sd, exist_ok=True)
    for subject in ("toy_math", "toy_facts"):
        with open(os.path.join(sd, f"{subject}_{split}.csv"), "w") as f:
            for q, a, b, c, dd, ans in MMLU_ROWS:
                f.write(f'"{q}",{a},{b},{c},{dd},{ans}\n')
    return d
