"""Split-QKV LoRA (column-range adapters on the fused c_attn) and
model-level dropout (embd/resid/attn pdrop) — VERDICT r1 #9.

Reference anchors: lora_injector.h:169-191 (Hook col_offset/col_size
split-QKV injection), core/ops.cpp:2670 (dropout op), HF GPT-2 train-mode
dropout placement (embeddings, residual branches, attention probs).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                           merge_gpt2)
from mobilefinetuner_tpu.models import gpt2

CFG = GPT2Config.tiny()
E = CFG.n_embd


@pytest.fixture(scope="module")
def base():
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                             CFG.vocab_size)
    return params, ids


def randomized(lora, seed=7):
    leaves, treedef = jax.tree.flatten(lora)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(leaves))
    return jax.tree.unflatten(treedef, [
        l if l.ndim == 0 else 0.05 * jax.random.normal(k, l.shape)
        for l, k in zip(leaves, keys)])


def test_split_qkv_equals_fused_with_masked_columns(base):
    """An attn_q adapter == a fused attn_qkv adapter whose B is zero
    outside the q columns (the defining property of the column slice)."""
    params, ids = base
    spec_f = LoRASpec(rank=4, alpha=8.0, targets=["attn_qkv"])
    fused = randomized(init_lora_gpt2(CFG, spec_f, jax.random.PRNGKey(2)))
    Bf = fused["blocks"]["attn_qkv"]["B"]
    fused["blocks"]["attn_qkv"]["B"] = \
        Bf.at[:, :, E:].set(0.0)  # only q columns active

    split = {"blocks": {"attn_q": {
        "A": fused["blocks"]["attn_qkv"]["A"],
        "B": Bf[:, :, :E],
        "scale": fused["blocks"]["attn_qkv"]["scale"]}}}

    out_f = gpt2.forward(CFG, params, ids, lora=fused)
    out_s = gpt2.forward(CFG, params, ids, lora=split)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_f),
                               rtol=1e-5, atol=1e-5)


def test_split_qkv_merge_equals_dynamic(base):
    """merge_gpt2 folds split-target ΔW into the right column range."""
    params, ids = base
    spec = LoRASpec(rank=4, alpha=8.0,
                    targets=["attn_q", "attn_k", "attn_v"])
    lora = randomized(init_lora_gpt2(CFG, spec, jax.random.PRNGKey(3)))
    dyn = gpt2.forward(CFG, params, ids, lora=lora)
    merged = gpt2.forward(CFG, merge_gpt2(params, lora), ids)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(dyn),
                               rtol=1e-5, atol=1e-5)


def test_split_qkv_gradients_flow(base):
    params, ids = base
    spec = LoRASpec(rank=4, alpha=8.0, targets=["attn_k", "attn_v"])
    # randomize: with the zero B init, A gradients are exactly zero by
    # the chain rule (dL/dA goes through B) — not what's under test
    lora = randomized(init_lora_gpt2(CFG, spec, jax.random.PRNGKey(4)))

    def loss(l):
        out = gpt2.forward(CFG, params, ids, lora=l)
        return (out.astype(jnp.float32) ** 2).mean()

    g = jax.grad(loss)(lora)
    for t in ("attn_k", "attn_v"):
        assert float(jnp.abs(g["blocks"][t]["A"]).max()) > 0, t
        assert float(jnp.abs(g["blocks"][t]["B"]).max()) > 0, t


def test_split_qkv_peft_export_rejected():
    from mobilefinetuner_tpu.lora.peft_io import export_peft
    spec = LoRASpec(rank=4, alpha=8.0, targets=["attn_q"])
    lora = init_lora_gpt2(CFG, spec, jax.random.PRNGKey(5))
    with pytest.raises(ValueError, match="PEFT"):
        export_peft("/tmp/never_written_peft", lora, spec, "gpt2")


def test_split_qkv_native_adapter_roundtrip(tmp_path, base):
    from mobilefinetuner_tpu.lora.peft_io import load_adapter, save_adapter
    params, ids = base
    spec = LoRASpec(rank=4, alpha=8.0,
                    targets=["attn_q", "attn_v", "attn_proj"])
    lora = randomized(init_lora_gpt2(CFG, spec, jax.random.PRNGKey(6)))
    path = str(tmp_path / "split.safetensors")
    save_adapter(path, lora, spec)
    lora2, spec2 = load_adapter(path)
    assert spec2.targets == sorted(spec.targets)
    out1 = gpt2.forward(CFG, params, ids, lora=lora)
    out2 = gpt2.forward(CFG, params, ids, lora=lora2)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out1),
                               rtol=1e-6, atol=1e-6)


# ------------------------------- dropout ------------------------------------


def test_model_dropout_active_in_train_mode(base):
    params, ids = base
    cfg = dataclasses.replace(CFG, embd_pdrop=0.1, resid_pdrop=0.1,
                              attn_pdrop=0.1)
    rng = jax.random.PRNGKey(9)
    out_train = gpt2.forward(cfg, params, ids, dropout_rng=rng)
    out_eval = gpt2.forward(cfg, params, ids)  # no rng = eval mode
    assert not np.allclose(np.asarray(out_train), np.asarray(out_eval))
    # different rng -> different masks
    out_train2 = gpt2.forward(cfg, params, ids,
                              dropout_rng=jax.random.PRNGKey(10))
    assert not np.allclose(np.asarray(out_train), np.asarray(out_train2))
    # same rng -> deterministic
    out_again = gpt2.forward(cfg, params, ids, dropout_rng=rng)
    np.testing.assert_array_equal(np.asarray(out_train),
                                  np.asarray(out_again))


def test_zero_pdrop_ignores_rng(base):
    """rates of 0 (the default) make the rng inert — eval == train."""
    params, ids = base
    out_rng = gpt2.forward(CFG, params, ids,
                           dropout_rng=jax.random.PRNGKey(3))
    out = gpt2.forward(CFG, params, ids)
    np.testing.assert_array_equal(np.asarray(out_rng), np.asarray(out))


def test_pdrop_parsed_from_config_json(tmp_path):
    import json
    import os
    d = str(tmp_path)
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"model_type": "gpt2", "n_embd": 32, "n_layer": 2,
                   "n_head": 2, "vocab_size": 97,
                   "embd_pdrop": 0.1, "resid_pdrop": 0.2,
                   "attn_pdrop": 0.3}, f)
    cfg = GPT2Config.from_pretrained(d)
    assert (cfg.embd_pdrop, cfg.resid_pdrop, cfg.attn_pdrop) == \
        (0.1, 0.2, 0.3)


def test_dropout_preserves_expectation(base):
    """Inverted dropout: E[out] ~= input (sanity on the 1/keep scaling)."""
    from mobilefinetuner_tpu.models.gpt2 import _dropout
    x = jnp.ones((256, 256))
    y = _dropout(x, 0.3, jax.random.PRNGKey(0))
    assert float(y.mean()) == pytest.approx(1.0, abs=0.02)
    vals = np.unique(np.asarray(y))
    assert np.all(np.isclose(vals, 0.0) | np.isclose(vals, 1 / 0.7))