"""Run-telemetry contract tests (core/telemetry.py, DESIGN.md §13):
every event type validates against the shared field spec, sequence
numbers stay monotonic across a simulated crash/resume append, a tiny
CPU e2e train produces run_start..run_end with the health fields, the
compiled HLO of BOTH model families carries the named phase scopes, and
the satellites (spike detector, max-across-devices HBM gauge, CSV
schema, report tool) hold their contracts."""

import csv
import json
import os
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.core.telemetry import (EVENT_SCHEMA, SpikeConfig,
                                                SpikeDetector, Telemetry,
                                                device_peak_flops, mfu_from,
                                                run_manifest,
                                                transformer_flops,
                                                validate_event)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import (write_tiny_gemma3_dir, write_tiny_gpt2_dir,
                      write_wikitext_dir)


def read_events(path):
    with open(path) as f:
        return [json.loads(l) for l in f.read().splitlines() if l.strip()]


# --------------------------- schema contract --------------------------------

REPRESENTATIVE = {
    "run_start": dict(jax_version="0.0", mesh_shape={"data": 1},
                      process_count=1, process_index=0, device_kind="cpu",
                      device_count=8, config={"steps": 3}),
    "compile": dict(step=0, wall_s=1.5, flops=1e9, peak_hbm_mb=123.0),
    "step_stats": dict(step=1, loss=3.2, ema=3.3, lr=1e-4, grad_norm=0.5,
                       step_time_ms=10.0, host_wait_ms=0.1, slept_ms=0.0,
                       tok_s=1000.0, mfu=None, param_norm=12.0,
                       update_ratio=1e-3, nonfinite_count=0, skipped=0,
                       hbm_mb=100.0, queue_depth=2,
                       host_step_ms={"0": 10.0, "1": 31.0},
                       # round-18 multi-tenant engine: per-tenant
                       # sections (optional on read — solo streams omit)
                       tenants={"alice": {"slot": 0, "step": 12,
                                          "loss": 3.1, "tokens": 4096,
                                          "wait_ms": 0.2}}),
    "throttle": dict(step=5, sleep_ms=100.0, battery=80.0, temp=30.0,
                     source="telemetry"),
    "anomaly": dict(step=7, kind="loss_spike", loss=9.9, ema=3.0,
                    zscore=8.4),
    "straggler": dict(step=50, slow_host=1, host_ms=31.0, fleet_ms=10.0,
                      ratio=3.1),
    "hang": dict(step=51, stall_s=120.5, deadline_s=60.0,
                 stacks_file="/tmp/run.jsonl.stacks",
                 device_probe="timeout", action="continue"),
    "eval": dict(step=10, loss=3.1, ppl=22.2, tokens=4096),
    # round-10 snapshot/write split (io/async_ckpt.py): wall_s is the
    # BLOCKING cost charged to the loop, the write fields the background
    # cost; the split fields are optional on read (pre-async streams)
    "checkpoint": dict(step=10, final=False, wall_s=0.2,
                       snapshot_ms=1.3, write_ms=198.7, bytes=1 << 20,
                       mb_s=5.03, **{"async": True}),
    "ckpt_dropped": dict(step=10, superseded_by=12),
    "request": dict(id=3, phase="finish", prompt_tokens=17, adapter=1,
                    queue_ms=4.2, new_tokens=32, ttft_ms=81.0,
                    tpot_ms=9.5, reason=None, rid=41),
    # round-14 serve robustness (DESIGN.md §19): cadenced health
    # snapshot from ServeEngine.health() — queue/occupancy/page
    # headroom/p95 step latency + cumulative terminal-state counters
    "serve_stats": dict(step=50, queue_depth=3, active=8, occupancy=1.0,
                        free_blocks=120, p95_step_ms=12.5, finished=40,
                        cancelled=1, rejected=2, timeout=1, error=0,
                        hbm_mb=512.0, pool_mb=64.0, mesh=[1, 1],
                        prefix_hit_rate=0.61, cow_copies=4,
                        blocks_in_use=40),
    # round-16 memory admission (DESIGN.md §21): one verdict per
    # preflight/dispatch/serve-build check, one event per degradation-
    # ladder rung walked
    "mem_check": dict(est_mb=8.5, cap_mb=3.0, verdict="over",
                      phase="preflight"),
    "degrade": {"step": None, "rung": "accum_x2", "from": "accum=1",
                "to": "accum=2", "est_mb": 3.7},
    # round-15 numerical-fault recovery (DESIGN.md §20): checkpoint-
    # integrity verdicts on every load path and the in-process
    # divergence→rollback decisions
    "ckpt_verify": dict(path="/tmp/a_step6.safetensors", ok=False,
                        reason="checksum_mismatch:blocks.attn_qkv.A",
                        step=6, action="reject"),
    "rollback": dict(step=8, reason="skip_streak", ok=True, to_step=6,
                     steps_lost=2, ckpt="/tmp/a_step6.safetensors",
                     data_offset=1, budget_left=1),
    # round-17 live observability (DESIGN.md §22): one completed host
    # span (monotonic t0 + duration on a named track; trace_export
    # renders them) and one anomaly-triggered profiler capture
    "span": dict(name="step", track="phase", t0=1234.567891,
                 dur_ms=10.5),
    "profile_capture": dict(step=12, trigger="slow_step",
                            path="/tmp/run.jsonl.profiles/cap0",
                            steps=2, budget_left=1),
    # round-22 serve-fleet router (DESIGN.md §27): one placement
    # decision from the cadenced replica scrape — rid is the same id
    # the chosen replica's request events carry
    "route": dict(rid=41, replica=1, policy="affinity", adapter="a",
                  queue_depth=2, occupancy=0.75, scrape_age_ms=38.5,
                  candidates=2),
    # round-18 multi-tenant training engine (DESIGN.md §23): one job
    # lifecycle transition; the `tenant` payload field doubles as the
    # cross-event attribution key the validator type-checks anywhere
    "tenant": dict(name="alice", slot=0, phase="finish", step=200,
                   job_steps=200, tokens=819200, loss=2.87,
                   path="/tmp/out/alice.safetensors", tenant="alice"),
    # round-13 elastic fleet (DESIGN.md §18): the drain marker and the
    # fleet controller's decision timeline
    "preempt": dict(step=7, signal="SIGTERM"),
    "controller": dict(action="restart", worker=1, reason="exit:113",
                       attempt=1, backoff_s=0.5, step=5,
                       recovery_s=0.82),
    "run_end": dict(steps=10, wall_s=60.0, exit="ok",
                    goodput={"total_s": 60.0, "step_s": 50.0,
                             "productive_frac": 0.83},
                    reason=None),
    # round-23 run registry (DESIGN.md §28): one self-contained
    # lifecycle record per registered run (start mirrors into the run's
    # own stream as the observatory's join key; end carries the
    # terminal status), and one sentinel verdict per trended series
    "run": dict(run_id="20260807T120000-1234-abc123", phase="start",
                kind="train", tool="train_lora_gemma", status="running",
                git_rev="abcdef123456", config_fingerprint="0123456789ab",
                platform="cpu", mesh={"data": 1}, pid=1234,
                artifacts=["/tmp/run.jsonl"], wall_s=None),
    "trend": dict(metric="tokens_per_sec_per_chip", config="gpt2s_lora",
                  platform="tpu", value=100.0, median=110.0, mad=2.0,
                  z=3.4, direction="higher", regressed=False,
                  run="r23", n=12),
}


def test_every_event_type_has_a_representative_and_validates(tmp_path):
    """One emit per event type in the taxonomy; each line read back from
    disk passes the shared validator, seq is 0..n-1 in order."""
    assert set(REPRESENTATIVE) == set(EVENT_SCHEMA)
    path = str(tmp_path / "t.jsonl")
    with Telemetry(path) as tel:
        for ev, fields in REPRESENTATIVE.items():
            assert tel.emit(ev, **fields) is not None
    recs = read_events(path)
    assert [r["event"] for r in recs] == list(REPRESENTATIVE)
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    assert [r["seq"] for r in recs] == list(range(len(REPRESENTATIVE)))


def test_validator_rejects_bad_events():
    ok = dict(event="eval", seq=0, t=1.0, step=1, loss=1.0, ppl=2.0,
              tokens=3)
    assert validate_event(ok) is None
    assert validate_event({**ok, "event": "nope"}) is not None
    assert validate_event({k: v for k, v in ok.items()
                           if k != "ppl"}) is not None
    assert validate_event({**ok, "tokens": "many"}) is not None
    assert validate_event({**ok, "seq": -1}) is not None
    # bool must not satisfy a numeric field
    assert validate_event({**ok, "loss": True}) is not None
    # extra fields are allowed (schema is a floor)
    assert validate_event({**ok, "extra": {"x": 1}}) is None
    # the round-18 tenant attribution field: any event may carry it,
    # but when present it must be a tenant name string (or null)
    assert validate_event({**ok, "tenant": "alice"}) is None
    assert validate_event({**ok, "tenant": None}) is None
    assert validate_event({**ok, "tenant": 7}) is not None
    # the request phase set is CLOSED (round 14): an unknown phase is a
    # schema violation, not an extra-field allowance
    req = dict(event="request", seq=0, t=1.0, **REPRESENTATIVE["request"])
    assert validate_event(req) is None
    assert validate_event({**req, "phase": "exploded"}) is not None
    # `reason` is optional on read (r11 streams predate it)
    assert validate_event({k: v for k, v in req.items()
                           if k != "reason"}) is None


def test_nonfinite_floats_serialize_as_strict_json(tmp_path):
    """A diverged run's NaN loss must not produce RFC-8259-invalid
    `NaN` literals — non-finite floats land as null, and the anomaly
    kind carries the information."""
    path = str(tmp_path / "t.jsonl")
    with Telemetry(path) as tel:
        tel.emit("anomaly", step=1, kind="nonfinite_loss",
                 loss=float("nan"), ema=float("inf"), zscore=None)
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    rec = json.loads(raw)  # strict parse succeeds
    assert rec["loss"] is None and rec["ema"] is None
    assert validate_event(rec) is None


def test_disabled_telemetry_is_noop(tmp_path):
    tel = Telemetry("")
    assert tel.emit("run_end", steps=0, wall_s=0.0, exit="ok") is None
    tel.close()
    tel = Telemetry(str(tmp_path / "x.jsonl"), enabled=False)
    assert tel.emit("run_end", steps=0, wall_s=0.0, exit="ok") is None
    assert not os.path.exists(tmp_path / "x.jsonl")


def test_seq_monotonic_across_crash_resume(tmp_path):
    """Appending to an existing stream (resumed run) continues the seq
    numbering — even past a truncated tail line from a killed writer."""
    path = str(tmp_path / "t.jsonl")
    with Telemetry(path) as tel:
        for i in range(3):
            tel.emit("eval", step=i, loss=1.0, ppl=2.0, tokens=1)
    # simulate a crash mid-write: a partial JSON line at the tail
    with open(path, "a") as f:
        f.write('{"event": "step_stats", "seq": 99, "t"')
    with Telemetry(path) as tel:
        tel.emit("eval", step=3, loss=1.0, ppl=2.0, tokens=1)
        tel.emit("run_end", steps=4, wall_s=1.0, exit="ok")
    recs = []
    for line in open(path).read().splitlines():
        try:
            recs.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    seqs = [r["seq"] for r in recs]
    assert seqs == [0, 1, 2, 3, 4]  # resumed past the corrupt line
    assert all(a < b for a, b in zip(seqs, seqs[1:]))


# --------------------------- spike detector ---------------------------------

def test_spike_detector_fires_on_spike_not_noise():
    det = SpikeDetector(SpikeConfig(zscore=6.0, beta=0.9, warmup=10))
    rng = np.random.default_rng(0)
    for i in range(50):
        assert det.update(3.0 + 0.01 * float(rng.normal())) is None
    anom = det.update(9.0)  # a real divergence step
    assert anom is not None and anom["kind"] == "loss_spike"
    assert anom["zscore"] > 6.0
    # the spike is winsorized into the EMA: an immediately following
    # normal loss is NOT anomalous
    assert det.update(3.0) is None


def test_spike_detector_readapts_to_level_shift():
    """A persistent loss plateau shift fires during the transition but
    must NOT fire forever — the winsorized EMA walks to the new level."""
    det = SpikeDetector(SpikeConfig(zscore=4.0, beta=0.9, warmup=5))
    rng = np.random.default_rng(1)
    for _ in range(40):
        det.update(2.0 + 0.01 * float(rng.normal()))
    fired = [det.update(4.0 + 0.01 * float(rng.normal())) is not None
             for _ in range(300)]
    assert any(fired[:50])        # the shift was detected...
    assert not any(fired[-50:])   # ...and the detector re-armed


def test_spike_detector_warmup_and_nonfinite():
    det = SpikeDetector(SpikeConfig(zscore=6.0, warmup=20))
    assert det.update(5.0) is None
    assert det.update(500.0) is None  # wild early loss: still warming up
    nf = det.update(float("nan"))
    assert nf is not None and nf["kind"] == "nonfinite_loss"
    # NaN is absorbing: consecutive non-finite losses fire ONCE (the
    # transition), not once per step
    assert det.update(float("inf")) is None
    assert det.update(float("nan")) is None
    # a recovery followed by a new divergence fires again
    assert det.update(5.0) is None
    assert det.update(float("nan"))["kind"] == "nonfinite_loss"
    # disabled detector never fires
    off = SpikeDetector(SpikeConfig(zscore=0.0))
    assert off.update(float("nan")) is None


# --------------------------- MFU accounting ---------------------------------

def test_mfu_helpers():
    assert device_peak_flops("TPU v5 lite") == 197e12
    assert device_peak_flops("TPU v5p chip") == 459e12
    assert device_peak_flops("cpu") == 0.0
    assert mfu_from(197e12 * 0.5, 1.0, 197e12) == pytest.approx(0.5)
    assert mfu_from(None, 1.0, 197e12) is None
    assert mfu_from(1e12, 1.0, 0.0) is None  # unknown peak -> no MFU


def test_transformer_flops_scales_linearly_in_tokens():
    f1 = transformer_flops(1e6, 1e8, 4, 128, 12, 12, 64, full_ft=False)
    f2 = transformer_flops(1e6, 1e8, 8, 128, 12, 12, 64, full_ft=False)
    assert f2 > f1 * 1.99  # attention grows superlinearly in S, not B


# --------------------------- HBM gauge satellite ----------------------------

class _FakeDev:
    def __init__(self, bytes_in_use, broken=False):
        self._b = bytes_in_use
        self._broken = broken

    def memory_stats(self):
        if self._broken:
            raise RuntimeError("no stats on this platform")
        return {"bytes_in_use": self._b}


def test_live_hbm_mb_reports_max_across_devices():
    """An imbalanced shard (e.g. vocab-parallel embed remainder on one
    chip) must not be under-reported by reading only device 0."""
    from mobilefinetuner_tpu.core.xla_stats import live_hbm_mb
    devs = [_FakeDev(100 * 2 ** 20), _FakeDev(900 * 2 ** 20),
            _FakeDev(50 * 2 ** 20)]
    assert live_hbm_mb(devices=devs) == pytest.approx(900.0)
    # one broken device must not zero the others
    devs = [_FakeDev(0, broken=True), _FakeDev(300 * 2 ** 20)]
    assert live_hbm_mb(devices=devs) == pytest.approx(300.0)


class _NoStatsDev:
    platform = "faketpu"

    def memory_stats(self):
        return {}  # this jax's CPU backend shape: stats exist, empty


def test_live_hbm_mb_is_none_when_no_device_reports():
    """Round-16 satellite: a backend without bytes_in_use must report
    None — not a silent 0.0 that masquerades as 'nothing allocated' in
    the telemetry hbm_mb field — and record the backend for its
    one-time log (the `_no_stats_logged` latch is the observable; the
    project logger does not propagate to caplog)."""
    from mobilefinetuner_tpu.core import xla_stats
    from mobilefinetuner_tpu.core.xla_stats import live_hbm_mb
    xla_stats._no_stats_logged.discard("faketpu")
    assert live_hbm_mb(devices=[]) is None
    assert "faketpu" not in xla_stats._no_stats_logged
    assert live_hbm_mb(devices=[_NoStatsDev()]) is None
    assert "faketpu" in xla_stats._no_stats_logged  # logged, latched
    assert live_hbm_mb(devices=[_NoStatsDev()]) is None  # 2nd: quiet
    # a broken device alongside a reporting one still yields the max
    assert live_hbm_mb(
        devices=[_FakeDev(0, broken=True),
                 _FakeDev(64 * 2 ** 20)]) == pytest.approx(64.0)


# --------------------------- named-scope tracing ----------------------------

def _assert_scopes(txt, scopes):
    """Each named scope must appear as a path component of some HLO
    op_name (autodiff wraps scopes in jvp(...)/transpose(...) markers).
    Migrated r19: the matcher is core/static_checks.assert_hlo_scopes —
    the same helper tools/check_compiled_contracts.py pins the compiled
    train/decode/multitenant programs with."""
    from mobilefinetuner_tpu.core.static_checks import assert_hlo_scopes
    assert_hlo_scopes(txt, scopes)


def test_gpt2_train_step_hlo_scopes_and_health_metrics():
    """One compiled GPT-2 train step pins BOTH contracts: (a) the
    embed/attention/mlp/loss/optimizer named scopes survive into the
    compiled HLO metadata (the semantic trace annotation), and (b) the
    on-device health metrics come back as DEVICE scalars in the metrics
    dict (they ride the buffered fetch) with sane values on a healthy
    step."""
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                               trainable_mask)
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
    from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                                   init_optimizer,
                                                   make_train_step)
    cfg = GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    lora = init_lora_gpt2(cfg, LoRASpec(rank=2, alpha=4.0),
                          jax.random.PRNGKey(1))
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=4, lr=1e-3, warmup_ratio=0.0,
                     schedule="constant")

    def loss_fn(lo, p, mb):
        logits = gpt2.forward(cfg, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"], lora=lo)
        return lm_cross_entropy_sum(logits, mb["labels"])

    rng = np.random.default_rng(0)
    ids = jnp.array(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    batch = {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
             "labels": ids}
    step = make_train_step(loss_fn, tc, mask=mask, donate=False)
    opt = init_optimizer(lora, tc, mask)
    compiled = step.lower(lora, params, opt, batch, jnp.int32(0)).compile()
    _assert_scopes(compiled.as_text(),
                   ["embed", "attention", "mlp", "loss", "optimizer"])
    _, _, m = compiled(lora, params, opt, batch, jnp.int32(0))
    for k in ("param_norm", "update_ratio", "nonfinite_count"):
        assert isinstance(m[k], jax.Array), k  # device-resident
    assert float(m["param_norm"]) > 0
    assert 0 < float(m["update_ratio"]) < 1.0
    assert int(m["nonfinite_count"]) == 0


def test_gemma_train_step_hlo_carries_named_scopes():
    """Same contract for the Gemma family (chunked-CE loss path)."""
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                               trainable_mask)
    from mobilefinetuner_tpu.models import gemma3
    from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
    from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                                   init_optimizer,
                                                   make_train_step)
    cfg = Gemma3TextConfig.tiny()
    params = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    lora = init_lora_gemma3(cfg, LoRASpec(rank=2, alpha=4.0),
                            jax.random.PRNGKey(1))
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=4, lr=1e-3, warmup_ratio=0.0,
                     schedule="constant")

    def loss_fn(lo, p, mb):
        hidden = gemma3.hidden_states(
            cfg, p, mb["input_ids"],
            attention_mask=mb["attention_mask"], lora=lo)
        return chunked_lm_cross_entropy_sum(hidden, p["embed"],
                                            mb["labels"], num_chunks=2)

    ids = jnp.zeros((2, 16), jnp.int32)
    batch = {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
             "labels": ids}
    step = make_train_step(loss_fn, tc, mask=mask, donate=False)
    opt = init_optimizer(lora, tc, mask)
    txt = step.lower(lora, params, opt, batch,
                     jnp.int32(0)).compile().as_text()
    _assert_scopes(txt, ["embed", "attention", "mlp", "loss", "optimizer"])


# --------------------------- CPU e2e acceptance -----------------------------

@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gpt2tel")
    write_tiny_gpt2_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def wiki_dir(tmp_path_factory):
    return write_wikitext_dir(str(tmp_path_factory.mktemp("wt2tel")))


def test_cpu_e2e_stream_and_report(gpt2_dir, wiki_dir, tmp_path):
    """The acceptance run: a tiny CPU train with --telemetry_out yields
    run_start, >=1 compile, >=1 step_stats carrying mfu/tok_s/
    param_norm/update_ratio/nonfinite_count, an eval, checkpoint events,
    and run_end — all passing the schema contract, seq strictly
    monotonic — and both new sinks (CSV schema, report tool) read it."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    stream = str(tmp_path / "run.jsonl")
    csv_path = str(tmp_path / "m.csv")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "4", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--telemetry_out", stream, "--metrics_csv", csv_path,
               "--eval_interval", "4", "--eval_batches", "2",
               "--pm_schedule", "0-0:1", "--log_interval", "2"])
    assert rc == 0
    recs = read_events(stream)
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    seqs = [r["seq"] for r in recs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    assert kinds.count("compile") >= 1
    assert kinds.count("step_stats") >= 1
    assert "throttle" in kinds  # pm_schedule slept on step 0
    assert "eval" in kinds and "checkpoint" in kinds
    run_start = recs[0]
    assert run_start["config"]["steps"] == 4
    assert run_start["process_count"] == 1
    ss = [r for r in recs if r["event"] == "step_stats"]
    for field in ("mfu", "tok_s", "param_norm", "update_ratio",
                  "nonfinite_count"):
        assert field in ss[-1]
    assert ss[-1]["param_norm"] > 0
    assert ss[-1]["nonfinite_count"] == 0
    assert ss[-1]["tok_s"] > 0
    end = recs[-1]
    assert end["exit"] == "ok" and end["steps"] == 4

    # resume appends to the SAME stream with continued seq
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "5", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--resume_from", str(tmp_path / "a.safetensors"),
               "--telemetry_out", stream])
    assert rc == 0
    recs2 = read_events(stream)
    seqs2 = [r["seq"] for r in recs2]
    assert seqs2 == sorted(seqs2) and len(set(seqs2)) == len(seqs2)
    assert [r["event"] for r in recs2].count("run_start") == 2

    # CSV satellite: grad_norm/tok_s/mfu columns landed
    with open(csv_path) as f:
        rows = list(csv.DictReader(f))
    assert {"grad_norm", "tok_s", "mfu"} <= set(rows[0])
    assert float(rows[0]["grad_norm"]) > 0
    assert float(rows[0]["tok_s"]) > 0

    # report tool renders the stream (both modes)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import telemetry_report
    assert telemetry_report.main([stream]) == 0
    events, bad = telemetry_report.load_events(stream)
    s = telemetry_report.summarize(events, bad)
    assert s["runs"] == 2 and s["seq_monotonic"]
    assert s["run_end"]["exit"] == "ok"
    assert s["step_stats"]["flushes"] >= 1
    assert s["throttle"]["decisions"] >= 1
    assert s["throttle"]["total_sleep_ms"] > 0  # from step_stats.slept_ms


def test_setup_crash_still_emits_run_end(gpt2_dir, wiki_dir, tmp_path,
                                         monkeypatch):
    """A failure BETWEEN run_start and the step loop (step build, device
    placement) must still terminate the stream with run_end{exit:<type>}
    — a stream ending at run_start is indistinguishable from a SIGKILL."""
    from mobilefinetuner_tpu.cli import common
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main

    def boom(*a, **k):
        raise RuntimeError("simulated setup OOM")

    monkeypatch.setattr(common, "make_train_step", boom)
    stream = str(tmp_path / "crash.jsonl")
    with pytest.raises(RuntimeError):
        main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
              "--steps", "2", "--batch_size", "2", "--seq_len", "32",
              "--lora_out", str(tmp_path / "a.safetensors"),
              "--telemetry_out", stream])
    recs = read_events(stream)
    assert [r["event"] for r in recs] == ["run_start", "run_end"]
    assert recs[-1]["exit"] == "RuntimeError"
    assert recs[-1]["steps"] == 0
    for r in recs:
        assert validate_event(r) is None


def test_eval_ppl_telemetry_stream(gpt2_dir, wiki_dir, tmp_path, capsys):
    from mobilefinetuner_tpu.cli.eval_ppl import main
    stream = str(tmp_path / "eval.jsonl")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_root", wiki_dir,
               "--split", "valid", "--seq_len", "32", "--batch_size", "2",
               "--max_batches", "2", "--telemetry_out", stream])
    assert rc == 0
    capsys.readouterr()
    recs = read_events(stream)
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    assert "eval" in kinds


# --------------------------- plot_loss both schemas -------------------------

def test_plot_loss_reads_both_csv_schemas(tmp_path):
    old = tmp_path / "old.csv"
    old.write_text(
        "timestamp,epoch,step,loss,avg_loss,lr,step_time_ms,hbm_mb\n"
        "1.0,0,1,3.5,3.5,0.0001,10.0,100\n"
        "2.0,0,2,3.4,3.45,0.0001,10.0,100\n")
    new = tmp_path / "new.csv"
    new.write_text(
        "timestamp,epoch,step,loss,avg_loss,lr,grad_norm,step_time_ms,"
        "host_wait_ms,tok_s,mfu,hbm_mb\n"
        "1.0,0,1,3.5,3.5,0.0001,0.8,10.0,0.1,6400.0,,100\n")
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import plot_loss
    for p in (old, new):
        steps, loss, avg, lr = plot_loss.read_metrics(str(p))
        assert steps and len(steps) == len(loss) == len(avg) == len(lr)
