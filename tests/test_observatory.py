"""Observatory tests (tools/observatory.py, DESIGN.md §28): the
committed-artifact backfill must ingest cleanly and span the repo's
history, the noise-aware sentinel must gate an injected regression
(exit 2, naming run+metric) while the clean corpus stays 0, the trend
events must validate against EVENT_SCHEMA (tier-1 selfcheck), and the
bench_compare satellites — exit 3 on dropped direction-aware metrics,
--run registry resolution byte-identical to a path invocation — must
hold."""

import contextlib
import io
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import pytest

import bench_compare
import observatory
from mobilefinetuner_tpu.core.run_registry import RunRegistry
from report_sections import sparkline, trend_lines

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_main(mod, argv):
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = mod.main(argv)
    return rc, out.getvalue()


# --------------------------- backfill ---------------------------------------

def test_backfill_ingests_committed_history_clean(tmp_path):
    report = str(tmp_path / "TREND.md")
    rc, out = run_main(observatory,
                       ["--backfill", "--root", REPO, "--report", report,
                        "--json"])
    assert rc == 0, out
    verdict = json.loads(out)
    assert verdict["regressions"] == []
    assert verdict["points"] > 500 and verdict["series"] > 100
    md = open(report).read()
    # history starts at the earliest committed round and the table is
    # the shared sparkline renderer
    assert "rounds r01->" in md
    assert "| trend |" in md or "trend" in md.splitlines()[6]


def test_selfcheck_passes_on_committed_corpus(capsys):
    assert observatory.selfcheck(REPO) == 0
    assert "selfcheck ok" in capsys.readouterr().out


def test_injected_regression_exits_2_and_names_run_and_metric(tmp_path):
    # continue a real committed series with a collapsed-throughput
    # candidate: half the tokens/sec of history must fire the gate.
    # The config must have >= min_n PRIOR committed points, so pick the
    # deepest throughput series in the backfill rather than hardcoding
    # one artifact's first row.
    store = []
    for pat in observatory.BACKFILL_GLOBS:
        import glob
        for p in sorted(glob.glob(os.path.join(REPO, pat))):
            store.extend(observatory.ingest_file(p))
    deep = max((s for s in observatory.build_series(store)
                if s["metric"] == "tokens_per_sec_per_chip"),
               key=lambda s: len(s["values"]))
    assert len(deep["values"]) >= 5, "throughput history too shallow"
    cfg = deep["config"]
    tok = [{"value": deep["values"][-1]}]
    bad = str(tmp_path / "BENCH_r99.json")
    with open(bad, "w") as f:
        json.dump({"rows": [{"config": cfg,
                             "tokens_per_sec_per_chip":
                                 tok[0]["value"] / 2.0}]}, f)
    rc, out = run_main(observatory,
                       ["--backfill", "--root", REPO, bad, "--json"])
    assert rc == 2
    regs = json.loads(out)["regressions"]
    assert any(r["run"] == "r99" and
               r["metric"] == "tokens_per_sec_per_chip" and
               r["config"] == cfg for r in regs), regs


def test_candidate_order_places_positional_paths_last(tmp_path):
    p = str(tmp_path / "BENCH_r02.json")
    with open(p, "w") as f:
        json.dump({"rows": [{"config": "c", "tok_s": 5.0}]}, f)
    # despite the r02 name, a positional path is the candidate — judged
    # as the LATEST point, after all committed history
    rows = observatory.ingest_file(p, order=observatory.CANDIDATE_ORDER)
    assert rows[0]["order"] == observatory.CANDIDATE_ORDER
    assert rows[0]["order"] > observatory.HEAD_ORDER


# --------------------------- sentinel ---------------------------------------

def _series(values, metric="tok_s", platform="tpu", config="c"):
    return [{"platform": platform, "config": config, "metric": metric,
             "runs": [f"r{i:02d}" for i in range(len(values))],
             "values": values}]


def test_sentinel_gates_only_with_all_three_conditions():
    # stable history, collapsed latest: fires
    v = observatory.sentinel(_series([100, 101, 99, 100, 100, 50]))[0]
    assert v["regressed"] and v["z"] > 4
    # same collapse but under min_n prior points: cannot gate
    v = observatory.sentinel(_series([100, 100, 50]))[0]
    assert not v["regressed"]
    # big z but under pct_floor: cannot gate
    v = observatory.sentinel(
        _series([100.0, 100.0, 100.0, 100.0, 100.0, 99.0]),
        rel_floor=0.0001)[0]
    assert v["z"] > 4 and not v["regressed"]
    # informational metric (no direction): trended, never gated
    v = observatory.sentinel(
        _series([100, 100, 100, 100, 100, 50], metric="loss_final"))[0]
    assert v["direction"] is None and not v["regressed"]


def test_sentinel_lower_better_direction():
    v = observatory.sentinel(
        _series([10, 10, 11, 10, 10, 25], metric="step_time_ms"))[0]
    assert v["direction"] == "lower" and v["regressed"]
    # improvement in a lower-better metric never fires
    v = observatory.sentinel(
        _series([10, 10, 11, 10, 10, 2], metric="step_time_ms"))[0]
    assert not v["regressed"]


def test_sentinel_rel_floor_keeps_flat_history_from_infinite_sigma():
    # MAD = 0; without the relative floor any nonzero delta would be
    # infinite sigmas — the 5% floor keeps a 1% wiggle at z ~ 0.2
    v = observatory.sentinel(_series([100.0] * 6 + [99.0]))[0]
    assert v["z"] < 1 and not v["regressed"]


def test_platform_split_isolates_cpu_from_tpu():
    tpu = {"device": "TPU v4", "rows": [{"config": "c", "tok_s": 100}]}
    cpu = {"synthetic": True, "rows": [{"config": "c", "tok_s": 1}]}
    store = []
    for name, data in (("BENCH_A_r01.json", tpu), ("BENCH_B_r02.json", cpu)):
        import tempfile
        d = tempfile.mkdtemp()
        p = os.path.join(d, name)
        with open(p, "w") as f:
            json.dump(data, f)
        store.extend(observatory.ingest_file(p))
    series = observatory.build_series(store)
    assert {s["platform"] for s in series} == {"tpu", "cpu"}
    assert all(len(s["values"]) == 1 for s in series)


def test_platform_of_variants():
    assert observatory.platform_of({"device": "TPU v5e"}) == "tpu"
    assert observatory.platform_of({"device_kind": "v4"}) == "tpu"
    assert observatory.platform_of({"platform": "cpu"}) == "cpu"
    assert observatory.platform_of({"synthetic": True}) == "cpu"
    assert observatory.platform_of({}) == "unknown"


def test_registry_runs_are_the_candidate(tmp_path, monkeypatch):
    monkeypatch.delenv("MFT_RUN_REGISTRY", raising=False)
    art = tmp_path / "BENCH_REG.json"
    art.write_text(json.dumps(
        {"rows": [{"config": "c", "tok_s": 42.0}]}))
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    h = reg.begin("bench", "bench", platform="cpu",
                  artifacts=[str(art)])
    h.finalize("ok")
    rows = observatory.ingest_registry(reg)
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["wall_s"]["config"].startswith("bench_bench")
    assert by_metric["tok_s"]["run"] == h.run_id
    assert all(r["order"] == observatory.CANDIDATE_ORDER for r in rows)


# --------------------------- rendering --------------------------------------

def test_sparkline_and_trend_lines():
    assert sparkline([0, 1]) == "▁█"
    assert len(sparkline([1, 2, 3, None, 5])) == 5
    assert sparkline([5.0, 5.0, 5.0]) == "▁▁▁"  # flat: all-min, no crash
    verdicts = observatory.sentinel(_series([100, 101, 99, 100, 100, 50]))
    lines = trend_lines(verdicts)
    joined = "\n".join(lines)
    assert "**REGRESSED**" in joined and "tok_s" in joined


def test_report_sections_back_compat_reexport():
    # serve_bench/fleet_report historically import section builders from
    # telemetry_report; the r23 extraction must keep that path alive
    from telemetry_report import emit_output, percentile  # noqa: F401
    import report_sections
    assert percentile is report_sections.percentile


# --------------------------- bench_compare satellites ------------------------

def _write(path, rows):
    with open(path, "w") as f:
        json.dump({"rows": rows}, f)
    return str(path)


def test_bench_compare_exit_3_on_dropped_metric(tmp_path):
    old = _write(tmp_path / "old.json",
                 [{"config": "c", "tok_s": 100.0, "step_time_ms": 10.0}])
    new = _write(tmp_path / "new.json", [{"config": "c", "tok_s": 100.0}])
    rc, out = run_main(bench_compare, [old, new, "--threshold", "5"])
    assert rc == 3
    assert "missing from NEW" in out and "step_time_ms" in out
    # without a threshold the drop is reported but never gates
    rc, _out = run_main(bench_compare, [old, new])
    assert rc == 0
    # a regression outranks the drop: exit 2 wins
    new2 = _write(tmp_path / "new2.json",
                  [{"config": "c", "tok_s": 50.0}])
    rc, _out = run_main(bench_compare, [old, new2, "--threshold", "5"])
    assert rc == 2


def test_bench_compare_json_verdict_lists_dropped(tmp_path):
    old = _write(tmp_path / "old.json",
                 [{"config": "c", "tok_s": 100.0, "notes_count": 3.0}])
    new = _write(tmp_path / "new.json", [{"config": "c", "tok_s": 100.0}])
    rc, out = run_main(bench_compare, [old, new, "--json",
                                       "--threshold", "5"])
    c = json.loads(out)
    # notes_count has no direction: reported as dropped, never gated
    assert c["dropped"] == [{"config": "c", "metric": "notes_count",
                             "direction": None}]
    assert c["gated_drops"] == [] and rc == 0


def test_bench_compare_run_resolution_byte_identical(tmp_path,
                                                     monkeypatch):
    monkeypatch.delenv("MFT_RUN_REGISTRY", raising=False)
    old = _write(tmp_path / "BENCH_OLD.json",
                 [{"config": "c", "tok_s": 100.0}])
    new = _write(tmp_path / "BENCH_NEW.json",
                 [{"config": "c", "tok_s": 90.0}])
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    h1 = reg.begin("bench", "bench", platform="cpu", artifacts=[old])
    h1.finalize("ok")
    h2 = reg.begin("bench", "bench", platform="cpu", artifacts=[new])
    h2.finalize("ok")
    rc_path, out_path = run_main(bench_compare, [old, new])
    rc_run, out_run = run_main(
        bench_compare, ["--registry", str(tmp_path / "runs.jsonl"),
                        "--run", h1.run_id, h2.run_id])
    assert rc_run == rc_path
    assert out_run == out_path  # byte-identical: resolution IS a path


def test_bench_compare_run_without_registry_errors(capsys):
    rc = bench_compare.main(["--run", "a", "b"])
    assert rc == 1
    assert "registry" in capsys.readouterr().err


def test_bench_compare_run_unresolvable_token(tmp_path, capsys):
    reg = RunRegistry(str(tmp_path / "runs.jsonl"))
    h = reg.begin("bench", "bench", platform="cpu")
    h.finalize("ok")
    rc = bench_compare.main(["--registry", str(tmp_path / "runs.jsonl"),
                             "--run", "nope", h.run_id])
    assert rc == 1
    assert "no .json artifact" in capsys.readouterr().err


# --------------------------- observatory CLI surface -------------------------

def test_observatory_nothing_ingested_is_an_error(capsys):
    with pytest.MonkeyPatch.context() as mp:
        mp.delenv("MFT_RUN_REGISTRY", raising=False)
        rc = observatory.main([])
    assert rc == 1
    assert "nothing ingested" in capsys.readouterr().err


def test_observatory_store_and_telemetry_out(tmp_path):
    store = str(tmp_path / "store.jsonl")
    stream = str(tmp_path / "trend.jsonl")
    rc, _out = run_main(observatory,
                        ["--backfill", "--root", REPO, "--store", store,
                         "--telemetry_out", stream, "--json"])
    assert rc == 0
    rows = [json.loads(l) for l in open(store)]
    assert all({"platform", "config", "metric", "value", "order"}
               <= set(r) for r in rows)
    evs = [json.loads(l) for l in open(stream)]
    trends = [e for e in evs if e.get("event") == "trend"]
    assert trends and all("regressed" in e for e in trends)
    from mobilefinetuner_tpu.core.telemetry import validate_event
    for e in trends:
        assert validate_event(e) is None, validate_event(e)
