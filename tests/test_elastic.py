"""Elastic-fleet robustness tests (DESIGN.md §18): preemption drain
(SIGTERM -> one step + one drain -> resumable exit), mesh-shape-agnostic
resume (save at mesh (1,4), resume at (1,2)/(1,1) with the loss
trajectory matching the uninterrupted run and the Adam sidecar
byte-equal through the re-shard round trip), the streaming-data bounded
retry, the coordinator-connect retry, and the watchdog's flush-before-
abort stream hygiene."""

import csv
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import jax
import numpy as np
import pytest

from mobilefinetuner_tpu.core.preempt import EXIT_PREEMPTED, PreemptionGuard
from mobilefinetuner_tpu.core.telemetry import validate_event

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import write_tiny_gpt2_dir, write_wikitext_dir

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def read_events(path):
    out = []
    with open(path) as f:
        for line in f.read().splitlines():
            if line.strip():
                out.append(json.loads(line))
    return out


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


# --------------------------- preemption guard (unit) ------------------------

def test_preemption_guard_sets_flag_then_escalates():
    prev_term = signal.getsignal(signal.SIGTERM)
    guard = PreemptionGuard().install()
    assert guard.installed
    assert signal.getsignal(signal.SIGTERM) == guard._handler
    try:
        assert not guard.triggered
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)  # delivery is at the next bytecode boundary
        assert guard.triggered and guard.signal_name == "SIGTERM"
        # a SECOND signal aborts the drain (the operator outranks a
        # wedged final save)
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.2)
    finally:
        guard.uninstall()
    # handlers restored: SIGTERM is back to whatever it was before
    assert signal.getsignal(signal.SIGTERM) == prev_term


# --------------------------- fixtures ---------------------------------------

@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gpt2elastic")
    write_tiny_gpt2_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def gpt2_big_dir(tmp_path_factory):
    """n_embd=128 so stacked per-layer leaves exceed FSDP min_size —
    the (1,4)->(1,2) resume genuinely re-shards, not just re-replicates."""
    d = tmp_path_factory.mktemp("gpt2elastic_big")
    write_tiny_gpt2_dir(str(d), n_embd=128)
    return str(d)


@pytest.fixture(scope="module")
def wiki_dir(tmp_path_factory):
    return write_wikitext_dir(str(tmp_path_factory.mktemp("wt2elastic")))


# --------------------------- SIGTERM drain e2e ------------------------------

def test_cli_sigterm_drain_e2e(gpt2_dir, wiki_dir, tmp_path):
    """The acceptance criterion: a subprocess training run receiving
    SIGTERM mid-run exits with the RESUMABLE code, leaves a loadable
    atomic checkpoint at the drain step, and its stream ends with a
    schema-valid run_end{reason=preempted} — then an actual resume
    continues from that step."""
    stream = str(tmp_path / "run.jsonl")
    adapter = str(tmp_path / "a.safetensors")
    p = subprocess.Popen(
        [sys.executable, "-m",
         "mobilefinetuner_tpu.cli.gpt2_lora_finetune",
         "--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
         "--steps", "500", "--batch_size", "2", "--seq_len", "32",
         "--lora_out", adapter, "--telemetry_out", stream,
         "--log_interval", "1", "--pm_schedule", "0-:15"],
        cwd=REPO, env=_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        # wait until the run is PAST compile and mid-training (a
        # step_stats flush proves a completed step)
        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.exists(stream) \
                    and "step_stats" in open(stream).read():
                break
            if p.poll() is not None:
                pytest.fail(f"run died early:\n{p.communicate()[0]}")
            time.sleep(0.1)
        else:
            pytest.fail("run never reached a training step")
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == EXIT_PREEMPTED, out

    recs = read_events(stream)
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    kinds = [r["event"] for r in recs]
    assert "preempt" in kinds
    pre = next(r for r in recs if r["event"] == "preempt")
    assert pre["signal"] == "SIGTERM"
    end = recs[-1]
    assert end["event"] == "run_end"
    assert end["exit"] == "preempted" and end["reason"] == "preempted"
    # the drain took a FINAL checkpoint and it landed before run_end
    cks = [r for r in recs if r["event"] == "checkpoint"]
    assert cks and cks[-1]["final"] is True
    # the checkpoint is loadable and carries the drain step
    assert os.path.exists(adapter) and os.path.exists(adapter + ".opt")
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    saved_step = int(np.asarray(
        SafeTensorsReader(adapter + ".opt").load_all()["step"]))
    assert saved_step == pre["step"]

    # resume: the step counter survives and the run completes
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", str(saved_step + 2), "--batch_size", "2",
               "--seq_len", "32", "--lora_out", adapter,
               "--resume_from", adapter, "--telemetry_out", stream])
    assert rc == 0
    end2 = read_events(stream)[-1]
    assert end2["event"] == "run_end" and end2["exit"] == "ok"
    assert end2["steps"] == 2  # exactly the un-run remainder


# --------------------------- mesh-shrink resume parity ----------------------

def _losses(csv_path):
    with open(csv_path) as f:
        return {int(r["step"]): float(r["loss"])
                for r in csv.DictReader(f)}


def test_mesh_shrink_resume_parity_full_ft(gpt2_big_dir, wiki_dir,
                                           tmp_path):
    """The acceptance criterion: a full-FT checkpoint saved at mesh
    (1,4) resumes at (1,2) and (1,1) — step counter, FSDP'd Adam
    sidecar, and skip_steps data fast-forward all survive the reshape —
    and the post-resume loss trajectory matches the uninterrupted
    (1,4) baseline (tolerance covers cross-mesh reduction-order float
    drift; the data order is bit-identical by construction)."""
    from mobilefinetuner_tpu.cli.gpt2_full_finetune import main
    base = ["--pretrained_dir", gpt2_big_dir, "--data_dir", wiki_dir,
            "--batch_size", "4", "--seq_len", "32", "--log_interval", "1"]
    ck = str(tmp_path / "full.safetensors")

    # ONE uninterrupted (1,4) run is both the baseline trajectory AND
    # (via --save_every 3) the interruption point: the periodic step-3
    # checkpoint is exactly what a preempted run would resume from —
    # same total_steps, so the LR schedule matches by construction.
    csv_a = str(tmp_path / "a.csv")
    assert main(base + ["--steps", "6", "--mesh_fsdp", "4",
                        "--save_every", "3", "--metrics_csv", csv_a,
                        "--output_path", ck]) == 0
    baseline = _losses(csv_a)
    assert set(baseline) == {1, 2, 3, 4, 5, 6}
    ck3 = str(tmp_path / "full_step3.safetensors")
    assert os.path.exists(ck3) and os.path.exists(ck3 + ".opt")

    for fsdp in ("2", "1"):
        csv_r = str(tmp_path / f"r{fsdp}.csv")
        assert main(base + ["--steps", "6", "--mesh_fsdp", fsdp,
                            "--resume_from", ck3, "--metrics_csv", csv_r,
                            "--output_path",
                            str(tmp_path / f"y{fsdp}.safetensors")]) == 0
        resumed = _losses(csv_r)
        # step counter + skip_steps survived: exactly steps 4..6 ran
        assert set(resumed) == {4, 5, 6}, resumed
        for s in (4, 5, 6):
            assert resumed[s] == pytest.approx(baseline[s], rel=1e-5), \
                (fsdp, s, resumed[s], baseline[s])


def test_opt_sidecar_reshard_byte_roundtrip(tmp_path):
    """Adam sidecar values are BYTE-equal after the save -> load ->
    place-at-a-different-mesh -> gather round trip, and the big leaves
    actually land FSDP-sharded at the new mesh (placement is data
    movement, never arithmetic)."""
    from mobilefinetuner_tpu.cli import common
    from mobilefinetuner_tpu.optim import adam as adam_mod
    from mobilefinetuner_tpu.parallel.mesh import make_mesh
    from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                                   init_optimizer)
    rng = np.random.default_rng(0)
    params = {"big": rng.standard_normal((64, 2048)).astype(np.float32),
              "small": rng.standard_normal((7,)).astype(np.float32)}
    state = {"step": np.asarray(17, np.int32),
             "m": {k: rng.standard_normal(v.shape).astype(np.float32)
                   for k, v in params.items()},
             "v": {k: np.abs(rng.standard_normal(v.shape)
                             ).astype(np.float32)
                   for k, v in params.items()}}
    tc = TrainConfig(total_steps=10)
    path = str(tmp_path / "s.opt")
    adam_mod.save_state(path, state, tc.adam())

    template = jax.eval_shape(lambda t: init_optimizer(t, tc, None),
                              params)
    loaded, _ = adam_mod.load_state(path, template, to_host=True)
    # host-side load: nothing committed to a device yet
    assert all(isinstance(x, np.ndarray) for x in jax.tree.leaves(loaded))
    assert int(loaded["step"]) == 17

    mesh2 = make_mesh(data=1, fsdp=2, devices=jax.devices()[:2])
    placed = common.place_opt_state(loaded, mesh2)
    # the big leaves re-sharded at the NEW mesh shape
    assert "fsdp" in str(placed["m"]["big"].sharding.spec)
    assert "fsdp" in str(placed["v"]["big"].sharding.spec)
    # byte equality through the round trip
    for key in ("m", "v"):
        for leaf in ("big", "small"):
            np.testing.assert_array_equal(
                np.asarray(placed[key][leaf]), state[key][leaf])
    assert int(placed["step"]) == 17


# --------------------------- streaming-data retry ---------------------------

EOS = 999


def _encode(line: str):
    return [abs(hash(w)) % 900 for w in line.split()]


@pytest.fixture()
def corpus_file(tmp_path):
    path = str(tmp_path / "wiki.train.tokens")
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(120):
            n = int(rng.integers(3, 20))
            f.write(" ".join(f"w{rng.integers(0, 300)}"
                             for _ in range(n)) + "\n")
    return path


def _make_flaky(path, retries, backoff=0.001):
    from mobilefinetuner_tpu.data.wikitext2 import (WT2Config,
                                                    WikiText2Dataset)

    class Flaky(WikiText2Dataset):
        fail_next = 0

        def _open_text(self, p):
            if self.fail_next > 0:
                self.fail_next -= 1
                raise OSError(f"transient I/O ({self.fail_next} left)")
            return super()._open_text(p)

    cfg = WT2Config(seq_len=16, batch_size=2, shuffle=False,
                    streaming=True, window_tokens=48, retries=retries,
                    retry_backoff_s=backoff)
    return Flaky(path, "train", cfg, _encode, eos_id=EOS)


def test_streaming_refetch_retries_then_succeeds(corpus_file):
    """Satellite: two injected failures then success — data identical
    to the clean read, one anomaly-shaped event per retry, run alive."""
    from mobilefinetuner_tpu.data.wikitext2 import (WT2Config,
                                                    WikiText2Dataset)
    clean = WikiText2Dataset(
        corpus_file, "train",
        WT2Config(seq_len=16, batch_size=2, shuffle=False,
                  streaming=True, window_tokens=48),
        _encode, eos_id=EOS)
    ds = _make_flaky(corpus_file, retries=3)
    events = []
    ds.event_sink = lambda **f: events.append(f)
    far = ds.num_chunks - 1  # outside the resident window: forces I/O
    ds.fail_next = 2
    got = ds._chunk_tokens(far)
    np.testing.assert_array_equal(got, clean._chunk_tokens(far))
    assert len(events) == 2
    for i, e in enumerate(events):
        assert e["kind"] == "data_retry"
        assert e["attempt"] == i + 1
        assert "transient I/O" in e["error"]
        assert e["backoff_s"] > 0
    # the next (clean) fetch emits nothing
    ds.fail_next = 0
    ds._chunk_tokens(0)
    assert len(events) == 2


def test_production_retry_sink_emits_valid_anomaly(corpus_file,
                                                   tmp_path):
    """The PRODUCTION sink (common.make_data_retry_sink — what
    run_training actually wires) against the real _io_retry payload:
    the dataset swallows sink exceptions by design, so an argument
    mismatch here would silently eat the telemetry forever (it did,
    once: kind was passed twice). The event must land in a real stream
    and pass the schema validator."""
    from mobilefinetuner_tpu.cli.common import make_data_retry_sink
    from mobilefinetuner_tpu.core.telemetry import Telemetry
    ds = _make_flaky(corpus_file, retries=3)
    stream = str(tmp_path / "retry.jsonl")
    tel = Telemetry(stream)
    ds.event_sink = make_data_retry_sink(tel, {"step": 7})
    ds.fail_next = 2
    ds._chunk_tokens(ds.num_chunks - 1)  # survives via two retries
    tel.close()
    recs = read_events(stream)
    assert len(recs) == 2, recs  # one anomaly PER retry, none eaten
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
        assert r["event"] == "anomaly" and r["kind"] == "data_retry"
        assert r["step"] == 8  # cur_step + 1
        assert "transient I/O" in r["error"] and r["backoff_s"] > 0


def test_streaming_refetch_budget_exhausted_raises(corpus_file):
    ds = _make_flaky(corpus_file, retries=1)
    ds.fail_next = 5
    with pytest.raises(OSError, match="transient"):
        ds._chunk_tokens(ds.num_chunks - 1)


def test_retries_off_fails_fast(corpus_file):
    ds = _make_flaky(corpus_file, retries=0)
    events = []
    ds.event_sink = lambda **f: events.append(f)
    ds.fail_next = 1
    with pytest.raises(OSError):
        ds._chunk_tokens(ds.num_chunks - 1)
    assert events == []  # fail-fast: no retry happened


# --------------------------- coordinator-connect retry ----------------------

def test_initialize_retries_coordinator_then_succeeds(monkeypatch):
    from mobilefinetuner_tpu.parallel import distributed as dist
    monkeypatch.setattr(dist, "_INITIALIZED", False)
    calls = []

    def flaky(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError(f"connection refused #{len(calls)}")

    monkeypatch.setattr(dist.jax.distributed, "initialize", flaky)
    assert dist.initialize(coordinator="127.0.0.1:1", num_processes=1,
                           process_id=0, connect_retries=4,
                           connect_backoff_s=0.001) is True
    assert len(calls) == 3  # two failures absorbed by the backoff


def test_initialize_raises_original_error_after_budget(monkeypatch):
    from mobilefinetuner_tpu.parallel import distributed as dist
    monkeypatch.setattr(dist, "_INITIALIZED", False)
    errs = []

    def always_fail(**kw):
        errs.append(RuntimeError(f"refused #{len(errs) + 1}"))
        raise errs[-1]

    monkeypatch.setattr(dist.jax.distributed, "initialize", always_fail)
    with pytest.raises(RuntimeError) as ei:
        dist.initialize(coordinator="127.0.0.1:1", num_processes=1,
                        process_id=0, connect_retries=2,
                        connect_backoff_s=0.001)
    assert len(errs) == 3          # budget: 1 try + 2 retries
    assert ei.value is errs[0]     # the ORIGINAL error, not the last


def test_initialize_autodetect_failure_never_retries(monkeypatch):
    """--multihost with nothing to address keeps the degrade-to-single-
    process behavior — exactly one attempt."""
    from mobilefinetuner_tpu.parallel import distributed as dist
    monkeypatch.setattr(dist, "_INITIALIZED", False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    calls = []

    def fail(**kw):
        calls.append(kw)
        raise RuntimeError("no pod metadata")

    monkeypatch.setattr(dist.jax.distributed, "initialize", fail)
    assert dist.initialize(force=True, connect_retries=5,
                           connect_backoff_s=0.001) is False
    assert len(calls) == 1


# --------------------------- watchdog abort flush ---------------------------

def test_watchdog_abort_flushes_and_terminates_stream(tmp_path):
    """Satellite regression: after a forced exit-113 abort, the shard
    read back is clean — every line complete (the hang record included),
    the file newline-terminated — because the abort path runs the
    telemetry flush barrier before os._exit."""
    stream = str(tmp_path / "wd.jsonl")
    script = textwrap.dedent(f"""
        import sys, time
        sys.path.insert(0, {REPO!r})
        from mobilefinetuner_tpu.core.telemetry import (Telemetry,
                                                        HangWatchdog)
        tel = Telemetry({stream!r})
        tel.emit("eval", step=1, loss=1.0, ppl=2.0, tokens=3)
        wd = HangWatchdog(mult=2.0, min_deadline_s=0.15, grace_s=0.15,
                          abort=True,
                          stacks_file={stream!r} + ".stacks",
                          on_hang=lambda p: tel.emit(
                              "hang", last_seq=tel.last_seq, **p),
                          flush_fn=tel.flush_tail)
        wd.start()
        time.sleep(30)   # the watchdog aborts us at ~0.15 s
    """)
    r = subprocess.run([sys.executable, "-c", script], env=_env(),
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 113, (r.returncode, r.stderr)
    raw = open(stream, "rb").read()
    assert raw.endswith(b"\n")  # no truncated tail line
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from telemetry_report import load_events
    events, bad = load_events(stream)
    assert bad == 0
    kinds = [e["event"] for e in events]
    assert kinds == ["eval", "hang"]
    assert events[-1]["action"] == "abort"
