"""Host-offload placement tests — analog of the reference sharder tests
(reference: opt_ops/sharding/test_parameter_sharder.cpp
register->offload->reload->verify round trip; test_sharder_strict.cpp strict
budget adherence), on the TPU memory hierarchy (HBM vs pinned host RAM)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.parallel.mesh import (make_mesh, params_shardings,
                                               replicated_sharding)
from mobilefinetuner_tpu.parallel.offload import (HOST, OffloadConfig,
                                                  host_kind,
                                                  apply_placement, fetch,
                                                  placement_stats,
                                                  plan_placement)


def tree(sizes):
    return {f"p{i}": jnp.arange(n, dtype=jnp.float32)
            for i, n in enumerate(sizes)}


def test_disabled_plan_keeps_everything_resident():
    t = tree([100, 200])
    plan = plan_placement(t, OffloadConfig(enable=False))
    assert not any(jax.tree.leaves(plan))


def test_budget_spills_largest_first():
    # 4 params of 4KiB/8KiB/16KiB/32KiB floats; budget 24KiB ->
    # offload the 32KiB then the 16KiB leaf.
    t = tree([1024, 2048, 4096, 8192])
    cfg = OffloadConfig(enable=True, max_resident_bytes=24 * 1024,
                        min_offload_size=1024)
    plan = plan_placement(t, cfg)
    assert plan == {"p0": False, "p1": False, "p2": True, "p3": True}
    stats = placement_stats(t, plan, cfg)
    assert stats["resident_bytes"] == (1024 + 2048) * 4
    assert stats["n_offloaded"] == 2


def test_strict_budget_zero_streams_everything():
    """Strict budget adherence (test_sharder_strict.cpp analog): budget 0
    offloads every leaf above min_offload_size."""
    t = tree([1024, 8192])
    cfg = OffloadConfig(enable=True, max_resident_bytes=0,
                        min_offload_size=256)
    plan = plan_placement(t, cfg)
    assert plan == {"p0": True, "p1": True}


def test_tiny_params_never_offloaded():
    t = tree([8, 16, 8192])
    cfg = OffloadConfig(enable=True, max_resident_bytes=0,
                        min_offload_size=1024)
    plan = plan_placement(t, cfg)
    assert plan["p0"] is False and plan["p1"] is False


def test_round_trip_values_preserved_f32():
    t = tree([4096, 512])
    cfg = OffloadConfig(enable=True, max_resident_bytes=1024,
                        offload_dtype="float32", min_offload_size=256)
    plan = plan_placement(t, cfg)
    sh = replicated_sharding(make_mesh(1, 1, devices=jax.devices()[:1]))
    placed = apply_placement(t, plan, sh, cfg)
    # offloaded leaves actually live in host memory
    for x, off in zip(jax.tree.leaves(placed), jax.tree.leaves(plan)):
        if off:
            assert x.sharding.memory_kind == host_kind()
    back = fetch(placed, plan, sh)
    for k in t:
        np.testing.assert_array_equal(np.asarray(back[k]), np.asarray(t[k]))
        assert back[k].sharding.memory_kind != HOST


def test_bf16_offload_quantizes():
    """offload_dtype=bfloat16 is the analog of the reference's
    quantize_fp16_on_disk (parameter_sharder.cpp:215-232): storage is
    16-bit, values round to bf16 precision."""
    x = jnp.asarray([1.0, 1e-3, 12345.678], jnp.float32)
    t = {"w": jnp.tile(x, 2048)}
    cfg = OffloadConfig(enable=True, max_resident_bytes=0,
                        offload_dtype="bfloat16", min_offload_size=16)
    plan = plan_placement(t, cfg)
    assert plan["w"]
    sh = replicated_sharding(make_mesh(1, 1, devices=jax.devices()[:1]))
    placed = apply_placement(t, plan, sh, cfg)
    assert placed["w"].dtype == jnp.bfloat16
    back = fetch(placed, plan, sh, compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(back["w"]), np.asarray(t["w"]),
                               rtol=1e-2)


def test_fetch_inside_jit_computes():
    """The require()-analog works under jit: a host-resident param feeds a
    compiled matmul (the H2D move is part of the XLA program)."""
    t = {"w": jnp.ones((64, 64), jnp.float32)}
    cfg = OffloadConfig(enable=True, max_resident_bytes=0,
                        offload_dtype="float32", min_offload_size=16)
    plan = plan_placement(t, cfg)
    sh = replicated_sharding(make_mesh(1, 1, devices=jax.devices()[:1]))
    placed = apply_placement(t, plan, sh, cfg)

    @jax.jit
    def f(p, x):
        p = fetch(p, plan, sh)
        return x @ p["w"]

    out = f(placed, jnp.ones((2, 64)))
    np.testing.assert_allclose(np.asarray(out), 64.0)


def _gpt2_offload_setup(config, budget_bytes, offload_dtype="float32",
                        stream=True, seed=0):
    """Init a GPT-2 tree, place it under `budget_bytes`, return
    (placed_params, offload_arg) the model forward accepts."""
    from mobilefinetuner_tpu.models import gpt2
    params = gpt2.init_params(config, jax.random.PRNGKey(seed))
    cfg = OffloadConfig(enable=True, max_resident_bytes=budget_bytes,
                        offload_dtype=offload_dtype, min_offload_size=1024)
    plan = plan_placement(params, cfg)
    sh = replicated_sharding(make_mesh(1, 1, devices=jax.devices()[:1]))
    shardings = jax.tree.map(lambda _: sh, params)
    placed = apply_placement(params, plan, shardings, cfg)
    return params, placed, ((plan, shardings) if stream else None)


def test_streamed_forward_matches_resident():
    """Per-layer streaming is numerically invisible: budget-0 streamed
    logits == fully-resident logits."""
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.models import gpt2
    config = GPT2Config.tiny()
    raw, placed, offload = _gpt2_offload_setup(config, 0)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                             config.vocab_size)
    ref = jax.jit(lambda p, i: gpt2.forward(config, p, i))(raw, ids)
    out = jax.jit(lambda p, i: gpt2.forward(config, p, i,
                                            offload=offload))(placed, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_streamed_lora_grads_match_resident():
    """The backward under streaming (remat re-fetches each layer from host)
    produces the same LoRA gradients as the fully-resident path."""
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gpt2
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum

    config = GPT2Config.tiny()
    spec = LoRASpec(rank=4, alpha=8.0,
                    targets=["attn_qkv", "attn_proj"], init="gpt2")
    lora = init_lora_gpt2(config, spec, jax.random.PRNGKey(7))
    raw, placed, offload = _gpt2_offload_setup(config, 0)
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                             config.vocab_size)
    labels = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                                config.vocab_size)

    def loss(lora_t, p, off):
        logits = gpt2.forward(config, p, ids, lora=lora_t, offload=off)
        s, w = lm_cross_entropy_sum(logits, labels)
        return s / w

    g_ref = jax.jit(jax.grad(lambda l: loss(l, raw, None)))(lora)
    g_str = jax.jit(jax.grad(lambda l: loss(l, placed, offload)))(lora)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_str)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_streaming_bounds_compiled_peak_memory():
    """THE budget guarantee (VERDICT r1 #1): with streaming, the compiled
    train-loss program's device footprint excludes the offloaded stacks —
    they are counted as HOST arguments and only ~one layer at a time ever
    occupies device memory (XLA compiled memory analysis).

    Host/device memory-space accounting only exists on real accelerator
    backends (the CPU backend bills pinned_host as device memory —
    parallel/host_devices.py), so this delegates to a subprocess on the
    machine's default platform and skips when that platform is cpu. The
    same check is runnable standalone: python tools/check_stream_memory.py
    """
    import json
    import os
    import re
    import subprocess
    import sys
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # the harness's virtual 8-CPU-device forcing (conftest) must not
    # leak into the child: it is probing the machine's REAL default
    # platform, and an 8-virtual-device CPU mesh makes the child's
    # compile crawl for minutes before it reaches the cpu-skip path
    if "XLA_FLAGS" in env:
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            env["XLA_FLAGS"]).strip()
    script = os.path.join(os.path.dirname(__file__), "..", "tools",
                          "check_stream_memory.py")
    assert os.path.exists(script), script
    try:
        proc = subprocess.run(
            [sys.executable, script], env=env, capture_output=True,
            text=True, timeout=240,
            cwd=os.path.join(os.path.dirname(__file__), ".."))
    except subprocess.TimeoutExpired:
        # a container with the TPU toolchain baked in but no TPU
        # attached BLOCKS in backend probing (libtpu waits, ~0 CPU) —
        # indistinguishable from "accelerator unavailable", and exactly
        # the case the stderr sniff below skips. Bound it: burning the
        # whole tier-1 budget on a dead probe proves nothing.
        pytest.skip("default-platform subprocess did not finish in "
                    "240s (backend probe blocked — no usable "
                    "accelerator for the memory-space check)")
    if not proc.stdout.strip():
        # crashed before printing JSON: a locked/unavailable accelerator
        # (e.g. the parent pytest process holds the TPU) is a skip; any
        # other crash is a real failure
        err = proc.stderr.lower()
        if any(s in err for s in ("already in use",
                                  "unable to initialize backend",
                                  "failed to initialize",
                                  "device or resource busy")):
            pytest.skip(f"accelerator unavailable in subprocess: "
                        f"{proc.stderr.strip().splitlines()[-1][:200]}")
        raise AssertionError((proc.returncode, proc.stderr[-2000:]))
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    if report.get("reason", "").startswith("cpu backend"):
        pytest.skip(f"no accelerator backend: {report['reason']}")
    assert proc.returncode == 0 and report.get("ok"), (report, proc.stderr)


def test_fetch_layer_drops_leading_axis_of_fsdp_spec():
    """fetch_layer on an FSDP-sharded stack: the per-layer slice keeps the
    non-layer partition axes and lands in device memory."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mobilefinetuner_tpu.parallel.offload import fetch_layer
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    stack = jnp.arange(6 * 256 * 8, dtype=jnp.float32).reshape(6, 256, 8)
    sh = NamedSharding(mesh, P(None, "fsdp", None),
                       memory_kind=host_kind())
    t = {"w": jax.device_put(stack, sh)}
    plan = {"w": True}
    shardings = {"w": sh}

    @jax.jit
    def pick(p, i):
        return fetch_layer(p, plan, i, shardings)

    out = pick(t, jnp.int32(3))
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(stack[3]))
    assert out["w"].sharding.memory_kind != HOST


def test_offload_composes_with_fsdp_mesh():
    """A param can be FSDP-sharded across chips AND host-offloaded: the
    partition spec survives with_memory_kind."""
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    t = {"w": jnp.ones((256, 64), jnp.float32),
         "b": jnp.ones((64,), jnp.float32)}
    shardings = params_shardings(t, mesh, min_size=1024)
    cfg = OffloadConfig(enable=True, max_resident_bytes=0,
                        offload_dtype="float32", min_offload_size=1024)
    plan = plan_placement(t, cfg)
    placed = apply_placement(t, plan, shardings, cfg)
    assert placed["w"].sharding.memory_kind == host_kind()
    assert not placed["w"].sharding.is_fully_replicated  # still FSDP-sharded
    back = fetch(placed, plan, shardings)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.ones((256, 64)))


def test_gemma_streamed_lora_grads_match_resident():
    """Gemma-3 per-layer streaming (budget 0): forward and LoRA grads match
    the fully-resident path (gpt2 analog above; this covers the gemma block
    wiring through layer_slicer/fetch_layer)."""
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gemma3
    from mobilefinetuner_tpu.models import gemma3
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum

    config = Gemma3TextConfig.tiny()
    params = gemma3.init_params(config, jax.random.PRNGKey(0))
    cfg = OffloadConfig(enable=True, max_resident_bytes=0,
                        offload_dtype="float32", min_offload_size=1024)
    plan = plan_placement(params, cfg)
    sh = replicated_sharding(make_mesh(1, 1, devices=jax.devices()[:1]))
    shardings = jax.tree.map(lambda _: sh, params)
    placed = apply_placement(params, plan, shardings, cfg)
    offload = (plan, shardings)
    spec = LoRASpec(rank=4, alpha=8.0, targets="attn")
    lora = init_lora_gemma3(config, spec, jax.random.PRNGKey(7))
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                             config.vocab_size)
    labels = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                                config.vocab_size)

    def loss(lora_t, p, off):
        logits = gemma3.forward(config, p, ids, lora=lora_t, offload=off)
        s, w = lm_cross_entropy_sum(logits, labels)
        return s / w

    f_ref = jax.jit(lambda l: loss(l, params, None))
    f_str = jax.jit(lambda l: loss(l, placed, offload))
    np.testing.assert_allclose(np.asarray(f_str(lora)),
                               np.asarray(f_ref(lora)), rtol=1e-5)
    g_ref = jax.jit(jax.grad(lambda l: loss(l, params, None)))(lora)
    g_str = jax.jit(jax.grad(lambda l: loss(l, placed, offload)))(lora)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_str)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_plan_spills_streamable_stacks_before_whole_fetch_leaves():
    """Placement prefers >=3-D layer stacks (streamed per layer, DMA
    overlapped by XLA's while-loop double buffering) over 2-D whole-fetch
    leaves like the embedding table (a serial transfer on the step's
    critical path): at an intermediate budget, the big 2-D leaf stays
    resident even though it is the largest."""
    t = {"embed": jnp.ones((1024, 64), jnp.float32),        # 256 KiB, 2-D
         "blocks": {
             "stack": jnp.ones((4, 64, 128), jnp.float32),  # 128 KiB, 3-D
             "stack2": jnp.ones((4, 32, 64), jnp.float32),  # 32 KiB, 3-D
         },
         # a >=3-D leaf OUTSIDE blocks is whole-fetched by resolve_offload,
         # so the planner must NOT prefer it over keeping embed resident
         "loose3d": jnp.ones((4, 16, 32), jnp.float32)}     # 8 KiB, 3-D
    cfg = OffloadConfig(enable=True, max_resident_bytes=288 * 1024,
                        min_offload_size=1024)
    plan = plan_placement(t, cfg)
    # spilling both stacks (160 KiB; 424 - 160 = 264 KiB resident) meets
    # the 288 KiB budget without touching embed or the loose 3-D leaf,
    # even though embed is the largest leaf
    assert plan == {"embed": False, "loose3d": False,
                    "blocks": {"stack": True, "stack2": True}}
    # but when the budget cannot be met by streamable stacks alone, the
    # whole-fetch leaves spill too (largest first)
    cfg2 = OffloadConfig(enable=True, max_resident_bytes=100 * 1024,
                         min_offload_size=1024)
    plan2 = plan_placement(t, cfg2)
    assert plan2["embed"] is True

    from mobilefinetuner_tpu.parallel.offload import streams_only_budget
    b = streams_only_budget(t, min_offload_size=1024)
    assert b == (256 + 8) * 1024  # embed + loose3d stay resident
    plan3 = plan_placement(t, OffloadConfig(enable=True,
                                            max_resident_bytes=b,
                                            min_offload_size=1024))
    assert plan3 == {"embed": False, "loose3d": False,
                     "blocks": {"stack": True, "stack2": True}}
