"""End-to-end CLI tests on tiny fixtures — the analog of the reference's
training smoke tests (test_10step_train.cpp, test_10step_convergence.cpp)
plus checkpoint-resume coverage the reference lacks (SURVEY.md §5)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import (write_tiny_gemma3_dir, write_tiny_gpt2_dir,
                      write_tiny_mmlu_dir, write_wikitext_dir)


@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gpt2ckpt")
    write_tiny_gpt2_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def gemma_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gemmackpt")
    write_tiny_gemma3_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def wiki_dir(tmp_path_factory):
    return write_wikitext_dir(str(tmp_path_factory.mktemp("wt2")))


def test_gpt2_lora_finetune_smoke(gpt2_dir, wiki_dir, tmp_path):
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    out = str(tmp_path / "adapter.safetensors")
    registry = str(tmp_path / "runs.jsonl")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "3", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", out, "--eval_interval", "3",
               "--eval_batches", "2",
               "--eval_out", str(tmp_path / "eval.jsonl"),
               "--run_registry", registry])
    assert rc == 0
    assert os.path.exists(out)
    assert os.path.exists(out + ".opt")
    # exactly one FINALIZED registry record per CLI run (DESIGN.md §28)
    from mobilefinetuner_tpu.core.run_registry import RunRegistry
    (rec,) = RunRegistry(registry).records()
    assert rec["status"] == "ok" and rec["kind"] == "train"
    assert rec["wall_s"] > 0 and rec["platform"]
    records = [json.loads(l) for l in
               open(tmp_path / "eval.jsonl").read().splitlines()]
    assert any(r["type"] == "final_eval" for r in records)
    assert all(np.isfinite(r["loss"]) for r in records)


def test_gpt2_lora_resume_restores_step(gpt2_dir, wiki_dir, tmp_path):
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    out = str(tmp_path / "adapter.safetensors")
    main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
          "--steps", "2", "--batch_size", "2", "--seq_len", "32",
          "--lora_out", out])
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "4", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", out, "--resume_from", out])
    assert rc == 0
    # optimizer sidecar after the resumed run must be at step 4
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    step = SafeTensorsReader(out + ".opt").load_all()["step"]
    assert int(step) == 4


def test_micro_batches_resume_continues_data_order(wiki_dir):
    """A resumed stream must continue where the interrupted one stopped,
    not replay epoch 0 (data-replay regression)."""
    from mobilefinetuner_tpu.cli.common import micro_batches
    from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset
    enc = lambda s: [ord(c) % 97 for c in s][:20]
    cfg = WT2Config(seq_len=16, batch_size=2, seed=7)
    mk = lambda: WikiText2Dataset(wiki_dir, "train", cfg, enc, 96)
    full = [b for _, (_, b) in zip(range(8), micro_batches(mk(), 2))]
    resumed = [b for _, (_, b) in zip(range(3), micro_batches(mk(), 2,
                                                              skip_steps=5))]
    for a, b in zip(full[5:], resumed):
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])


def test_gpt2_lora_checkpoint_suffix(gpt2_dir, wiki_dir, tmp_path):
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    out = str(tmp_path / "a.safetensors")
    main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
          "--steps", "4", "--batch_size", "2", "--seq_len", "32",
          "--lora_out", out, "--save_every", "2"])
    assert os.path.exists(str(tmp_path / "a_step2.safetensors"))
    assert os.path.exists(out)


def test_gpt2_lora_training_reduces_loss(gpt2_dir, wiki_dir, tmp_path):
    """10-step loss decrease (test_10step_convergence.cpp analog)."""
    from mobilefinetuner_tpu.cli import common
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    csv_path = str(tmp_path / "m.csv")
    main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
          "--steps", "10", "--batch_size", "4", "--seq_len", "32",
          "--lr", "5e-3", "--lora_targets",
          "attn_qkv,attn_proj,mlp_fc_in,mlp_fc_out",
          "--lora_out", str(tmp_path / "a.safetensors"),
          "--metrics_csv", csv_path])
    import csv as csv_mod
    with open(csv_path) as f:
        rows = list(csv_mod.DictReader(f))
    first, last = float(rows[0]["loss"]), float(rows[-1]["loss"])
    assert last < first, (first, last)


def test_profiler_trace_and_hbm_column(gpt2_dir, wiki_dir, tmp_path):
    """--profile_dir emits a jax.profiler trace and the metrics CSV carries
    the hbm_mb observability column (performance_monitor.h:44-57 analog)."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    prof = str(tmp_path / "prof")
    csv_path = str(tmp_path / "m.csv")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "6", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--profile_dir", prof, "--profile_start", "2",
               "--profile_steps", "2", "--metrics_csv", csv_path])
    assert rc == 0
    trace_files = [os.path.join(r, f) for r, _, fs in os.walk(prof)
                   for f in fs]
    assert trace_files, "no profiler trace emitted"
    import csv as csv_mod
    with open(csv_path) as f:
        rows = list(csv_mod.DictReader(f))
    assert "hbm_mb" in rows[0]
    assert float(rows[0]["hbm_mb"]) > 0


def test_profiler_window_past_total_steps_still_stops_trace(
        gpt2_dir, wiki_dir, tmp_path):
    """Leak regression: a 2-step run whose profile window
    (profile_start + profile_steps) extends past total_steps must STILL
    stop the trace — the stop now lives in the loop's finally block, so
    every exit path closes it. Symptoms of the leak: no trace files
    flushed, and the process-global profiler left active (a later
    start_trace would raise)."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    import jax as _jax
    prof = str(tmp_path / "prof")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "2", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--profile_dir", prof, "--profile_start", "1",
               "--profile_steps", "50"])
    assert rc == 0
    trace_files = [os.path.join(r, f) for r, _, fs in os.walk(prof)
                   for f in fs]
    assert trace_files, "trace leaked: stop_trace never ran"
    # the global profiler state is clean: a fresh trace can start
    prof2 = str(tmp_path / "prof2")
    _jax.profiler.start_trace(prof2)
    _jax.profiler.stop_trace()


def test_gpt2_lora_with_offload_and_governor(gpt2_dir, wiki_dir, tmp_path):
    """shard_* + pm_* flags wired end-to-end (sharded-training smoke,
    scripts/benchmark/test_all_models_sharding.sh analog)."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "2", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--shard_enable", "--shard_budget_mb", "0",
               "--pm_schedule", "0-:1"])
    assert rc == 0


def test_gpt2_lora_multichip_fsdp(gpt2_dir, wiki_dir, tmp_path):
    """--mesh_data/--mesh_fsdp engage the ("data","fsdp") mesh: frozen base
    FSDP-sharded, batch data-parallel over all 8 virtual devices."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "2", "--batch_size", "8", "--seq_len", "32",
               "--mesh_data", "2", "--mesh_fsdp", "4",
               "--lora_out", str(tmp_path / "a.safetensors")])
    assert rc == 0


def test_gpt2_lora_mesh_divisibility_guard(gpt2_dir, wiki_dir, tmp_path):
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    import pytest as _pytest
    with _pytest.raises(SystemExit):
        main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
              "--steps", "1", "--batch_size", "2", "--seq_len", "32",
              "--mesh_fsdp", "8",
              "--lora_out", str(tmp_path / "a.safetensors")])


def test_gpt2_lora_dropout_smoke(gpt2_dir, wiki_dir, tmp_path):
    """--lora_dropout runs and trains; the per-(step, micro-batch) keys ride
    in batch['dropout_rng'] (fixed-key mask-reuse regression)."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "2", "--batch_size", "2", "--seq_len", "32",
               "--grad_accum_steps", "2", "--lora_dropout", "0.2",
               "--lora_out", str(tmp_path / "a.safetensors")])
    assert rc == 0


def test_gpt2_full_finetune_smoke(gpt2_dir, wiki_dir, tmp_path):
    from mobilefinetuner_tpu.cli.gpt2_full_finetune import main
    out = str(tmp_path / "full.safetensors")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "2", "--batch_size", "2", "--seq_len", "32",
               "--output_path", out])
    assert rc == 0
    # saved full model must load back as an HF-keyed checkpoint
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    keys = set(SafeTensorsReader(out).keys())
    assert "wte.weight" in keys and "h.0.attn.c_attn.weight" in keys


def test_gemma_full_finetune_smoke(gemma_dir, wiki_dir, tmp_path):
    """Gemma full FT (beyond-reference: the reference's full-FT binary is
    GPT-2-only): trains, saves an HF-keyed full model, and the saved file
    round-trips through the from_hf mapper (transpose inverse) AND the
    CLI's --resume_from path."""
    import numpy as np
    from mobilefinetuner_tpu.cli.gemma_full_finetune import main
    out = str(tmp_path / "gfull.safetensors")
    rc = main(["--model_dir", gemma_dir, "--data_dir", wiki_dir,
               "--steps", "2", "--batch_size", "2", "--seq_len", "32",
               "--loss_chunks", "2", "--output_path", out])
    assert rc == 0
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.io.checkpoints import gemma3_params_from_hf
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    tensors = SafeTensorsReader(out).load_all(promote_to_f32=True)
    assert "model.embed_tokens.weight" in tensors
    cfg = Gemma3TextConfig.from_pretrained(gemma_dir)
    params = gemma3_params_from_hf(tensors, cfg)
    # transpose round trip: the HF [out, in] q_proj equals our stacked
    # [L, in, out] leaf transposed back
    np.testing.assert_array_equal(
        tensors["model.layers.0.self_attn.q_proj.weight"],
        np.asarray(params["blocks"]["attn"]["q_w"][0]).T)
    assert os.path.exists(out + ".opt")  # Adam state sidecar
    # resume path: retrain 1 step FROM the saved file
    out2 = str(tmp_path / "gfull2.safetensors")
    rc = main(["--model_dir", gemma_dir, "--data_dir", wiki_dir,
               "--steps", "1", "--batch_size", "2", "--seq_len", "32",
               "--loss_chunks", "2", "--resume_from", out,
               "--output_path", out2])
    assert rc == 0 and os.path.exists(out2)


def test_gemma_full_finetune_opt_offload(gemma_dir, wiki_dir, tmp_path):
    """--opt_offload: master weights + Adam m/v live in the host tier and
    stream through the scanned update (optim/opt_offload.py). The saved
    file must be the f32 MASTER (HF-keyed), and resume must restore the
    sidecar step counter."""
    import numpy as np
    from mobilefinetuner_tpu.cli.gemma_full_finetune import main
    out = str(tmp_path / "goff.safetensors")
    rc = main(["--model_dir", gemma_dir, "--data_dir", wiki_dir,
               "--steps", "2", "--batch_size", "2", "--seq_len", "32",
               "--loss_chunks", "2", "--opt_offload",
               "--output_path", out])
    assert rc == 0
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    r = SafeTensorsReader(out)
    assert "model.embed_tokens.weight" in r.keys()
    # master is saved f32 (not the bf16 compute copy)
    assert r.shape_dtype("model.embed_tokens.weight")[1] == "F32"
    assert os.path.exists(out + ".opt")
    # resume: 1 more step from the saved master + sidecar
    out2 = str(tmp_path / "goff2.safetensors")
    rc = main(["--model_dir", gemma_dir, "--data_dir", wiki_dir,
               "--steps", "3", "--batch_size", "2", "--seq_len", "32",
               "--loss_chunks", "2", "--opt_offload",
               "--resume_from", out, "--output_path", out2])
    assert rc == 0 and os.path.exists(out2)
    # the resumed run continued (saved weights differ from the resume src)
    a = SafeTensorsReader(out).load_all()["model.embed_tokens.weight"]
    b = SafeTensorsReader(out2).load_all()["model.embed_tokens.weight"]
    assert not np.allclose(a, b)
    # mesh guard
    import pytest as _pytest
    with _pytest.raises(SystemExit):
        main(["--model_dir", gemma_dir, "--data_dir", wiki_dir,
              "--steps", "1", "--batch_size", "8", "--seq_len", "32",
              "--opt_offload", "--mesh_fsdp", "4",
              "--output_path", str(tmp_path / "x.safetensors")])


def test_gemma_full_finetune_opt_offload_16bit(gemma_dir, wiki_dir,
                                               tmp_path):
    """The 16-bit host tier through the CLI: bf16 master (stochastic-
    rounded) + bf16 m / sqrt-v stream, f32 master still saved, sidecar
    resume with the SAME dtype flags works."""
    import numpy as np
    from mobilefinetuner_tpu.cli.gemma_full_finetune import main
    out = str(tmp_path / "g16.safetensors")
    flags = ["--model_dir", gemma_dir, "--data_dir", wiki_dir,
             "--batch_size", "2", "--seq_len", "32", "--loss_chunks", "2",
             "--opt_offload", "--opt_offload_state_dtype", "bfloat16",
             "--opt_offload_master_dtype", "bfloat16"]
    rc = main(flags + ["--steps", "2", "--output_path", out])
    assert rc == 0
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    r = SafeTensorsReader(out)
    # the checkpoint contract is unchanged: master saved as F32 (the
    # stored bf16 master upcasts losslessly)
    assert r.shape_dtype("model.embed_tokens.weight")[1] == "F32"
    assert os.path.exists(out + ".opt")
    out2 = str(tmp_path / "g16b.safetensors")
    rc = main(flags + ["--steps", "3", "--resume_from", out,
                       "--output_path", out2])
    assert rc == 0
    a = SafeTensorsReader(out).load_all()["model.embed_tokens.weight"]
    b = SafeTensorsReader(out2).load_all()["model.embed_tokens.weight"]
    assert not np.allclose(a, b)


def test_train_lora_gemma_smoke(gemma_dir, wiki_dir, tmp_path):
    from mobilefinetuner_tpu.cli.train_lora_gemma import main
    out_dir = str(tmp_path / "gl")
    rc = main(["--model_dir", gemma_dir, "--data_dir", wiki_dir,
               "--max_steps", "3", "--batch", "2", "--seq_len", "32",
               "--targets", "light", "--output_dir", out_dir])
    assert rc == 0
    assert os.path.exists(os.path.join(out_dir, "gemma_lora.safetensors"))


def test_train_lora_gemma_pretokenized(gemma_dir, wiki_dir, tmp_path):
    """Pretokenized .bin mode (wikitext2_dataset.h:92-111 analog)."""
    from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
    from mobilefinetuner_tpu.data.wikitext2 import pretokenize
    from mobilefinetuner_tpu.cli.train_lora_gemma import main
    tok = GemmaTokenizer.from_pretrained(gemma_dir)
    bin_path = str(tmp_path / "wt2.bin")
    pretokenize(os.path.join(wiki_dir, "wiki.train.tokens"),
                lambda s: tok.encode(s, add_bos=False), tok.eos_id, bin_path)
    rc = main(["--model_dir", gemma_dir, "--max_steps", "2", "--batch", "2",
               "--seq_len", "32", "--targets", "light",
               "--pretokenized_path", bin_path,
               "--output_dir", str(tmp_path / "out")])
    assert rc == 0


def test_eval_ppl_smoke(gpt2_dir, wiki_dir, tmp_path, capsys):
    from mobilefinetuner_tpu.cli.eval_ppl import main
    rc = main(["--pretrained_dir", gpt2_dir, "--data_root", wiki_dir,
               "--split", "valid", "--seq_len", "32", "--batch_size", "2",
               "--max_batches", "3",
               "--out", str(tmp_path / "ppl.jsonl")])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["type"] == "final" and np.isfinite(rec["ppl"])
    final = [json.loads(l) for l in
             open(tmp_path / "ppl.jsonl").read().splitlines()
             if json.loads(l)["type"] == "final"]
    assert final and final[0]["ppl"] == rec["ppl"]


def test_eval_ppl_adapter_merge_matches_dynamic(gpt2_dir, wiki_dir,
                                                tmp_path, capsys):
    """merged and dynamic adapter application give the same PPL
    (merge/unmerge correctness, test_lora_correctness.cpp analog)."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main as train
    from mobilefinetuner_tpu.cli.eval_ppl import main as eval_ppl
    adapter = str(tmp_path / "a.safetensors")
    train(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
           "--steps", "3", "--batch_size", "2", "--seq_len", "32",
           "--lr", "5e-3", "--lora_out", adapter])
    outs = []
    for extra in (["--lora_merge"], []):
        eval_ppl(["--pretrained_dir", gpt2_dir, "--data_root", wiki_dir,
                  "--split", "valid", "--seq_len", "32",
                  "--batch_size", "2", "--max_batches", "2",
                  "--lora_path", adapter] + extra)
        outs.append(json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]))
    assert outs[0]["ppl"] == pytest.approx(outs[1]["ppl"], rel=1e-4)


def test_eval_ppl_gemma_adapter_merge_matches_dynamic(gemma_dir, wiki_dir,
                                                      tmp_path, capsys):
    """Gemma eval parity (the reference has NO Gemma eval binary): family
    auto-detect, chunked-CE eval, and merge == dynamic via merge_gemma3."""
    from mobilefinetuner_tpu.cli.eval_ppl import main as eval_ppl
    from mobilefinetuner_tpu.cli.train_lora_gemma import main as train
    out_dir = str(tmp_path / "g")
    train(["--model_dir", gemma_dir, "--data_dir", wiki_dir,
           "--steps", "3", "--batch_size", "2", "--seq_len", "32",
           "--lr", "5e-3", "--output_dir", out_dir])
    adapter = os.path.join(out_dir, "gemma_lora.safetensors")
    outs = []
    for extra in (["--lora_merge"], []):
        eval_ppl(["--pretrained_dir", gemma_dir, "--data_root", wiki_dir,
                  "--split", "valid", "--seq_len", "32",
                  "--batch_size", "2", "--max_batches", "2",
                  "--lora_path", adapter] + extra)
        outs.append(json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]))
    assert outs[0]["family"] == "gemma"
    assert np.isfinite(outs[0]["ppl"])
    assert outs[0]["ppl"] == pytest.approx(outs[1]["ppl"], rel=1e-4)


def test_eval_mmlu_smoke(gpt2_dir, tmp_path, capsys):
    from mobilefinetuner_tpu.cli.eval_mmlu import main
    mmlu_root = write_tiny_mmlu_dir(str(tmp_path / "mmlu"))
    rc = main(["--pretrained_dir", gpt2_dir, "--mmlu_root", mmlu_root,
               "--split", "test", "--fewshot", "1"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["total_items"] == 8
    assert 0.0 <= rec["macro_accuracy"] <= 1.0


def test_eval_mmlu_gemma_smoke(gemma_dir, tmp_path, capsys):
    """Gemma family auto-detected; letter-id lookup must not collapse to
    the auto-BOS token (eval/mmlu.py letter_encode_fn)."""
    from mobilefinetuner_tpu.cli.eval_mmlu import main
    from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
    from mobilefinetuner_tpu.eval.mmlu import LETTERS, letter_token_ids
    tok = GemmaTokenizer.from_pretrained(gemma_dir)
    ids = letter_token_ids(lambda s: tok.encode(s, add_bos=False))
    assert len(set(ids)) > 1, "letter ids collapsed (BOS leak?)"
    mmlu_root = write_tiny_mmlu_dir(str(tmp_path / "mmlu"))
    rc = main(["--pretrained_dir", gemma_dir, "--mmlu_root", mmlu_root,
               "--split", "test"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["total_items"] == 8
    assert 0.0 <= rec["macro_accuracy"] <= 1.0
