"""WikiText-2 pipeline: chunking/padding/label semantics, per-epoch seeded
shuffle, streaming == in-RAM equivalence, pretokenized mode, data_fraction,
stride overlap masking. (Reference analog: data/test_wikitext2_dataset.cpp.)"""

import numpy as np
import pytest

from mobilefinetuner_tpu.data.wikitext2 import (IGNORE_INDEX, WT2Config,
                                                WikiText2Dataset,
                                                pretokenize)

EOS = 999


def _encode(line: str):
    # toy whitespace "tokenizer": word -> stable small int
    return [abs(hash(w)) % 900 for w in line.split()]


@pytest.fixture(scope="module")
def corpus_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("wt2")
    path = str(d / "wiki.train.tokens")
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for i in range(200):
            n = int(rng.integers(3, 30))
            f.write(" ".join(f"w{rng.integers(0, 500)}"
                             for _ in range(n)) + "\n")
            if i % 17 == 0:
                f.write("\n")  # blank lines are skipped
    return path


def _mk(path, **kw):
    cfg = WT2Config(seq_len=32, batch_size=4, seed=7, **kw)
    return WikiText2Dataset(path, "train", cfg, _encode, eos_id=EOS)


def test_batch_shapes_and_labels(corpus_file):
    ds = _mk(corpus_file)
    batch = next(ds.epoch(0))
    assert batch["input_ids"].shape == (4, 32)
    assert batch["input_ids"].dtype == np.int32
    assert batch["attention_mask"].dtype == np.float32
    assert batch["labels"].dtype == np.int32
    # full chunks: labels == input_ids, mask all ones
    assert (batch["attention_mask"] == 1.0).all()
    np.testing.assert_array_equal(batch["input_ids"], batch["labels"])


def test_eos_inserted_between_lines(corpus_file):
    ds = _mk(corpus_file, shuffle=False)
    flat = np.concatenate([ds._chunk_tokens(i)
                           for i in range(ds.num_chunks)])
    assert (flat == EOS).sum() >= 150  # one EOS per nonempty line


def test_shuffle_is_seeded_and_per_epoch(corpus_file):
    ds = _mk(corpus_file)
    b0a = next(ds.epoch(0))["input_ids"]
    b0b = next(ds.epoch(0))["input_ids"]
    b1 = next(ds.epoch(1))["input_ids"]
    np.testing.assert_array_equal(b0a, b0b)  # same epoch -> same order
    assert not np.array_equal(b0a, b1)  # different epoch -> reshuffled


def test_streaming_equals_in_ram(corpus_file):
    ram = _mk(corpus_file, shuffle=False)
    stream = _mk(corpus_file, shuffle=False, streaming=True,
                 window_tokens=64)
    assert ram.num_chunks == stream.num_chunks
    assert ram.total_valid_tokens() == stream.total_valid_tokens()
    for i in range(ram.num_chunks):
        np.testing.assert_array_equal(ram._chunk_tokens(i),
                                      stream._chunk_tokens(i))
    # random access out of window order
    for i in (ram.num_chunks - 1, 0, ram.num_chunks // 2, 1):
        np.testing.assert_array_equal(ram._chunk_tokens(i),
                                      stream._chunk_tokens(i))


def test_pretokenized_mode(tmp_path, corpus_file):
    out_bin = str(tmp_path / "toks.bin")
    n = pretokenize(corpus_file, _encode, EOS, out_bin)
    ram = _mk(corpus_file, shuffle=False)
    cfg = WT2Config(seq_len=32, batch_size=4, seed=7, shuffle=False)
    pre = WikiText2Dataset("", "train", cfg, _encode, eos_id=EOS,
                           pretokenized_bin=out_bin)
    assert n == ram.total_valid_tokens()
    assert pre.num_chunks == ram.num_chunks
    for i in range(ram.num_chunks):
        np.testing.assert_array_equal(ram._chunk_tokens(i),
                                      pre._chunk_tokens(i))


def test_data_fraction(corpus_file):
    full = _mk(corpus_file)
    half = _mk(corpus_file, data_fraction=0.5)
    assert half.num_chunks <= full.num_chunks // 2 + 1


def test_stride_overlap_label_masking(corpus_file):
    ds = _mk(corpus_file, stride=16, shuffle=False)
    ids1, mask1, lab1 = ds.chunk(1)
    # overlapping prefix (seq_len - stride = 16 tokens) is label-masked
    assert (lab1[:16] == IGNORE_INDEX).all()
    assert (lab1[16:] != IGNORE_INDEX).any()
    ids0, _, lab0 = ds.chunk(0)
    np.testing.assert_array_equal(lab0, ids0)  # first chunk unmasked
    # chunk 1 starts stride tokens in
    np.testing.assert_array_equal(ids1[:16], ids0[16:])


def test_drop_last_and_padding(tmp_path):
    path = str(tmp_path / "small.txt")
    with open(path, "w") as f:
        f.write("a b c d e\n" * 7)
    cfg = WT2Config(seq_len=32, batch_size=2, drop_last=False,
                    shuffle=False)
    ds = WikiText2Dataset(path, "train", cfg, _encode, eos_id=EOS)
    chunks = [ds.chunk(i) for i in range(ds.num_chunks)]
    ids, mask, lab = chunks[-1]
    n_valid = int(mask.sum())
    assert n_valid < 32
    assert (lab[n_valid:] == IGNORE_INDEX).all()
    assert (ids[n_valid:] == EOS).all()  # pad with pad_id(=eos)
