#!/usr/bin/env bash
# GPT-2-small full fine-tune: every parameter trained, Adam state
# FSDP-sharded when a mesh is given.
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GPT2_DIR:?set GPT2_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.gpt2_full_finetune \
    --pretrained_dir "$GPT2_DIR" --data_dir "$WT2_DIR" \
    --epochs 1 --batch_size 32 --seq_len 128 --dtype bfloat16 \
    --lr 2e-5 --warmup_ratio 0.03 \
    --metrics_csv "$OUT/gpt2s_full_metrics.csv" \
    --output_path "$OUT/gpt2s_full_ft.safetensors" "$@"
