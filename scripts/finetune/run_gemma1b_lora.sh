#!/usr/bin/env bash
# Gemma-3-1B LoRA, the fastest single-chip config: --remat lifts the
# activation-memory batch cap (B=24 runs 12% faster than no-remat B=8 at
# half the peak HBM — the recompute costs less than the small batch did;
# BENCH_SUITE gemma1b_lora_bf16_remat_B24).
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GEMMA1B_DIR:?set GEMMA1B_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.train_lora_gemma \
    --model_dir "$GEMMA1B_DIR" --data_dir "$WT2_DIR" \
    --epochs 1 --batch_size 24 --seq_len 256 --dtype bfloat16 \
    --rank 8 --alpha 32 --targets full --lr 1e-4 --remat \
    --loss_chunks 12 \
    --metrics_csv "$OUT/gemma1b_metrics.csv" \
    --output_dir "$OUT/gemma1b" "$@"
