#!/usr/bin/env bash
# Alignment run: dump one batch's activations/grads/post-step adapter as
# npy, then compare against a real transformers+PEFT mirror
# (reference: train_lora_gemma.cpp --align_dump_dir + pytorch_alignment/).
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GPT2_DIR:?set GPT2_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.gpt2_lora_finetune \
    --pretrained_dir "$GPT2_DIR" --data_dir "$WT2_DIR" \
    --batch_size 2 --seq_len 64 --align_dump_dir "$OUT/align_gpt2" "$@"
python tools/align_torch_mirror.py --dump_dir "$OUT/align_gpt2"
