#!/usr/bin/env bash
# Gemma-3-270M LoRA, the BASELINE driver config (r=8 alpha=32, S=256,
# full targets, chunked 262k-vocab CE) then eval_ppl merged.
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GEMMA_DIR:?set GEMMA_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.train_lora_gemma \
    --model_dir "$GEMMA_DIR" --data_dir "$WT2_DIR" \
    --epochs 1 --batch_size 16 --seq_len 256 --dtype bfloat16 \
    --rank 8 --alpha 32 --targets full --lr 1e-4 --warmup_ratio 0.03 \
    --metrics_csv "$OUT/gemma270m_metrics.csv" \
    --output_dir "$OUT/gemma270m" "$@"
python -m mobilefinetuner_tpu.cli.eval_ppl \
    --pretrained_dir "$GEMMA_DIR" --data_root "$WT2_DIR" --split test \
    --seq_len 1024 --lora_path "$OUT/gemma270m/gemma_lora.safetensors" \
    --lora_merge
