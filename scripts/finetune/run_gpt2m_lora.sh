#!/usr/bin/env bash
# GPT-2-medium (355M) LoRA — same recipe as small at B=32.
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GPT2M_DIR:?set GPT2M_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.gpt2_lora_finetune \
    --pretrained_dir "$GPT2M_DIR" --data_dir "$WT2_DIR" \
    --epochs 1 --batch_size 32 --seq_len 128 --dtype bfloat16 \
    --lr 2e-4 --warmup_ratio 0.03 \
    --metrics_csv "$OUT/gpt2m_lora_metrics.csv" \
    --lora_out "$OUT/gpt2m_adapter.safetensors" "$@"
