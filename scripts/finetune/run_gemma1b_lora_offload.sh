#!/usr/bin/env bash
# Gemma-3-1B LoRA with host-offload streaming: frozen weights live in
# host RAM and stream into HBM one layer at a time (the reference's
# ParameterSharder analog; ~1.5 GB peak HBM instead of ~14 GB).
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GEMMA1B_DIR:?set GEMMA1B_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.train_lora_gemma \
    --model_dir "$GEMMA1B_DIR" --data_dir "$WT2_DIR" \
    --epochs 1 --batch_size 8 --seq_len 256 --dtype bfloat16 \
    --rank 8 --alpha 32 --targets full --lr 1e-4 \
    --shard_enable --shard_budget_mb 2048 --shard_stream 1 \
    --metrics_csv "$OUT/gemma1b_metrics.csv" \
    --output_dir "$OUT/gemma1b" "$@"
