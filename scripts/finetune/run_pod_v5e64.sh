#!/usr/bin/env bash
# v5e-64 pod recipe: GPT-2-small full fine-tune, FSDP over the pod
# (BASELINE driver config "v5e-64 FSDP").
#
# A v5e-64 slice is 16 hosts x 4 chips. This script is what EACH host
# runs; launch it on every worker at once, e.g.:
#
#   gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" \
#       --worker=all --command "GPT2_DIR=... WT2_DIR=... \
#           bash repo/scripts/finetune/run_pod_v5e64.sh"
#
# --multihost brings up jax.distributed with TPU-pod auto-detection (no
# coordinator flags needed on a pod; off-pod, set JAX_COORDINATOR_ADDRESS/
# JAX_NUM_PROCESSES/JAX_PROCESS_ID or --dist_coordinator per process —
# tools/multihost_smoke.py demonstrates the explicit form at 8 procs x 8
# devices on CPU). The DCN-aware hybrid mesh packs the fsdp axis inside
# each host's ICI domain and lets the data axis cross hosts
# (parallel/distributed.py make_hybrid_mesh); --mesh_fsdp 4 keeps param
# all-gathers / grad reduce-scatters on ICI, and the data axis absorbs
# the remaining 16x host dimension automatically (build_mesh resolves
# data = devices/fsdp when --mesh_data is left at its default). Batch
# below is GLOBAL (64 per chip x 64 chips would be 4096; 1024 keeps
# S=128 steps short) and must divide data x fsdp. Every process reads
# the same data dir; the input pipeline feeds each host only its
# addressable shards.
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GPT2_DIR:?set GPT2_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.gpt2_full_finetune \
    --pretrained_dir "$GPT2_DIR" --data_dir "$WT2_DIR" \
    --epochs 1 --batch_size 1024 --seq_len 128 --dtype bfloat16 \
    --lr 2e-5 --warmup_ratio 0.03 \
    --multihost --mesh_fsdp 4 \
    --metrics_csv "$OUT/pod_v5e64_metrics.csv" \
    --output_path "$OUT/pod_v5e64_full_ft.safetensors" "$@"
