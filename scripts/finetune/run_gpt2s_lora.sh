#!/usr/bin/env bash
# GPT-2-small LoRA, the BASELINE driver config (r=8 alpha=16, S=128) —
# 1 epoch of WikiText-2 then eval_ppl with the adapter merged.
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GPT2_DIR:?set GPT2_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.gpt2_lora_finetune \
    --pretrained_dir "$GPT2_DIR" --data_dir "$WT2_DIR" \
    --epochs 1 --batch_size 64 --seq_len 128 --dtype bfloat16 \
    --lr 2e-4 --warmup_ratio 0.03 --eval_interval 200 \
    --metrics_csv "$OUT/gpt2s_lora_metrics.csv" \
    --lora_out "$OUT/gpt2s_adapter.safetensors" \
    --peft_export_dir "$OUT/gpt2s_peft" "$@"
python -m mobilefinetuner_tpu.cli.eval_ppl \
    --pretrained_dir "$GPT2_DIR" --data_root "$WT2_DIR" --split test \
    --seq_len 1024 --lora_path "$OUT/gpt2s_adapter.safetensors" --lora_merge
