#!/usr/bin/env bash
# Gemma-3-270M FULL fine-tune (all 268M params; beyond-reference — the
# reference's full-FT binary is GPT-2-only) — 1 epoch, bf16, chunked
# 262k-vocab CE. The saved full model reloads via --resume_from (or copy
# it over model.safetensors in a checkpoint dir to run eval_ppl on it).
set -euo pipefail
cd "$(dirname "$0")/../.."
: "${GEMMA_DIR:?set GEMMA_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
python -m mobilefinetuner_tpu.cli.gemma_full_finetune \
    --model_dir "$GEMMA_DIR" --data_dir "$WT2_DIR" \
    --epochs 1 --batch_size 8 --seq_len 256 --dtype bfloat16 \
    --lr 2e-5 --warmup_ratio 0.03 \
    --metrics_csv "$OUT/gemma270m_full_metrics.csv" \
    --output_path "$OUT/gemma270m_full_ft.safetensors" "$@"
