#!/usr/bin/env bash
# Governor on/off wall-time comparison (the reference's
# energy_benchmark.sh analog): same short training run, once at full
# speed and once throttled by the deterministic schedule + mocked
# telemetry. The throttled run should take ~1.5-2x longer (the
# reference's published throttling cost, README.md:427-431).
set -euo pipefail
: "${GPT2_DIR:?set GPT2_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
STEPS=${STEPS:-50}
common=(--pretrained_dir "$GPT2_DIR" --data_dir "$WT2_DIR"
        --steps "$STEPS" --batch_size 8 --seq_len 128 --dtype bfloat16
        --log_interval 0)
echo "== full speed =="
time python -m mobilefinetuner_tpu.cli.gpt2_lora_finetune \
    "${common[@]}" --lora_out "$OUT/e_base.safetensors"
echo "== throttled (schedule 0-:40ms + low-battery telemetry) =="
time python -m mobilefinetuner_tpu.cli.gpt2_lora_finetune \
    "${common[@]}" --lora_out "$OUT/e_thr.safetensors" \
    --pm_interval 10 --pm_schedule "0-:40" \
    --pm_manual_batt 10 --pm_manual_temp 45
