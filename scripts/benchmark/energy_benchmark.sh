#!/usr/bin/env bash
# Governor on/off wall-time comparison (the reference's
# energy_benchmark.sh analog): same short training run, once at full
# speed and once throttled by the deterministic schedule + mocked
# telemetry. The throttled run should take ~1.5-2x longer (the
# reference's published throttling cost, README.md:427-431 —
# BASELINE.md's energy row). Writes the measured pair + ratio to
# $JSON_OUT (default $OUT/energy.json) so the claim is pinned by an
# artifact (ENERGY_r06.json at the repo root), not just terminal output.
set -euo pipefail
: "${GPT2_DIR:?set GPT2_DIR}" "${WT2_DIR:?set WT2_DIR}"
OUT=${OUT:-out}; mkdir -p "$OUT"
STEPS=${STEPS:-50}
JSON_OUT=${JSON_OUT:-$OUT/energy.json}
# Throttle sleep per step (ms). The reference's 1.5-2x cost comes from a
# throttle comparable to its step time (~50% duty cycle); pick
# THROTTLE_MS accordingly for the hardware under test (e.g. ~40 for a
# v5e train step, ~750 for the tiny-model CPU fixture run).
THROTTLE_MS=${THROTTLE_MS:-40}
common=(--pretrained_dir "$GPT2_DIR" --data_dir "$WT2_DIR"
        --steps "$STEPS" --batch_size 8 --seq_len 128 --dtype bfloat16
        --log_interval 0)

run_timed() {  # echoes wall seconds; training output goes to stderr
  local t0 t1
  t0=$(date +%s.%N)
  "$@" >&2
  t1=$(date +%s.%N)
  awk -v a="$t0" -v b="$t1" 'BEGIN{printf "%.2f", b - a}'
}

echo "== full speed =="
BASE_S=$(run_timed python -m mobilefinetuner_tpu.cli.gpt2_lora_finetune \
    "${common[@]}" --lora_out "$OUT/e_base.safetensors")
echo "base: ${BASE_S}s"
echo "== throttled (schedule 0-:${THROTTLE_MS}ms + low-battery telemetry) =="
THR_S=$(run_timed python -m mobilefinetuner_tpu.cli.gpt2_lora_finetune \
    "${common[@]}" --lora_out "$OUT/e_thr.safetensors" \
    --pm_interval 10 --pm_schedule "0-:${THROTTLE_MS}" \
    --pm_manual_batt 10 --pm_manual_temp 45)
echo "throttled: ${THR_S}s"

python - "$JSON_OUT" "$BASE_S" "$THR_S" "$STEPS" "$THROTTLE_MS" <<'PY'
import json, platform, sys
out, base, thr, steps, ms = (sys.argv[1], float(sys.argv[2]),
                             float(sys.argv[3]), int(sys.argv[4]),
                             int(sys.argv[5]))
json.dump({
    "steps": steps,
    "base_wall_s": base,
    "base_ms_per_step": round(base / steps * 1000, 1),
    "throttled_wall_s": thr,
    "throttle_ratio": round(thr / base, 3),
    "schedule": f"0-:{ms}ms, pm_interval=10, batt=10%, temp=45C",
    "reference_claim": "1.5-2x training-time cost (BASELINE.md energy row)",
    "platform": platform.machine(),
}, open(out, "w"), indent=1)
print(f"wrote {out}")
PY
