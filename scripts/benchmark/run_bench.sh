#!/usr/bin/env bash
# Full benchmark suite on the local accelerator -> BENCH_SUITE.json
# (tokens/sec/chip, MFU, compiled peak HBM per config).
set -euo pipefail
cd "$(dirname "$0")/../.."
python bench.py
