"""Headline benchmark: GPT-2-small LoRA training throughput (tokens/sec/chip).

Config mirrors the driver's primary config (BASELINE.json): GPT-2-small
124M, LoRA r=8 alpha=16, seq_len=128, WikiText-2-shaped batches. Baseline is
the reference's published epoch time — 4-6 h/epoch at batch=4, S=128 on a
mobile SoC (reference README.md:419), i.e. ~2.39M-token WikiText-2 train
split / 18000 s midpoint ≈ 133 tokens/sec.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                           trainable_mask)
from mobilefinetuner_tpu.models import gpt2
from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
from mobilefinetuner_tpu.train.trainer import (TrainConfig, init_optimizer,
                                               make_train_step)

BASELINE_TOKENS_PER_SEC = 2_391_884 / 18_000.0  # ≈ 132.9 (reference CPU)


def main():
    config = GPT2Config.gpt2_small()
    on_tpu = jax.devices()[0].platform == "tpu"
    batch, seq = (32, 128) if on_tpu else (4, 64)
    compute_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    steps = 50 if on_tpu else 3

    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    spec = LoRASpec(rank=8, alpha=16.0)
    lora = init_lora_gpt2(config, spec, jax.random.PRNGKey(1))
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=1000, lr=2e-4, schedule="constant",
                     warmup_ratio=0.0, grad_accum_steps=1)

    def loss_fn(lora, params, mb):
        logits = gpt2.forward(config, params, mb["input_ids"],
                              attention_mask=mb["attention_mask"], lora=lora,
                              compute_dtype=compute_dtype)
        return lm_cross_entropy_sum(logits, mb["labels"])

    step_fn = make_train_step(loss_fn, tc, mask=mask, donate=True)
    opt = init_optimizer(lora, tc, mask)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, config.vocab_size, (batch, seq)),
                      jnp.int32)
    b = {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
         "labels": ids}

    # Warmup: compile + 2 steady-state steps. NOTE: sync via host readback
    # of a scalar, not block_until_ready — the latter does not actually
    # wait for completion on the tunneled TPU platform.
    for s in range(3):
        lora, opt, m = step_fn(lora, params, opt, b, jnp.int32(s))
    float(m["loss"])

    t0 = time.perf_counter()
    for s in range(steps):
        lora, opt, m = step_fn(lora, params, opt, b, jnp.int32(s + 3))
    float(m["loss"])
    dt = time.perf_counter() - t0

    toks_per_sec = batch * seq * steps / dt
    print(json.dumps({
        "metric": "gpt2s_lora_train_tokens_per_sec_per_chip",
        "value": round(toks_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(toks_per_sec / BASELINE_TOKENS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
