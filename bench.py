"""Benchmark suite: the driver's BASELINE configs on one chip.

Covers the three driver configs (BASELINE.md): GPT-2-small LoRA (r=8 α=16
S=128), GPT-2-small full fine-tune, and Gemma-3-270M LoRA (r=8 α=32 S=256,
full targets, chunked 262k-vocab CE) — each with bf16/f32, grad-accum, and
host-offload-streaming variants, plus a long-context config where the
Pallas flash kernel (auto-dispatched) is measured against the forced XLA
path. Per config: tokens/sec/chip, an MFU estimate, and the compiled peak
device memory (XLA memory analysis: device args + temps + outputs − donated
aliases; runtime memory_stats is not exposed on the tunneled platform).

The reference's analog is scripts/benchmark/ (wall-time + peak RSS over
baseline-vs-sharded configs, measure_rss.sh:22-42) — peak compiled HBM is
the TPU-native RSS, and the offload variants are the sharded runs.

stdout: ONE JSON line (the headline GPT-2s LoRA config; driver contract).
The full suite is written to BENCH_SUITE.json and summarized on stderr.
Baseline: the reference's 4-6 h/epoch (batch=4, S=128, mobile SoC,
README.md:419) ≈ 2.39M-token epoch / 18000 s ≈ 133 tokens/sec.

Sync note: timings read a scalar back to host; block_until_ready does not
wait on the tunneled TPU platform.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                           init_lora_gpt2, trainable_mask)
from mobilefinetuner_tpu.models import gemma3, gpt2
from mobilefinetuner_tpu.ops.loss import (chunked_lm_cross_entropy_sum,
                                          lm_cross_entropy_sum)
from mobilefinetuner_tpu.parallel.mesh import (make_mesh,
                                               replicated_sharding)
from mobilefinetuner_tpu.parallel.offload import (OffloadConfig,
                                                  apply_placement,
                                                  plan_placement,
                                                  resolve_offload)
from mobilefinetuner_tpu.train.trainer import (TrainConfig, init_optimizer,
                                               make_train_step)

BASELINE_TOKENS_PER_SEC = 2_391_884 / 18_000.0  # ≈ 132.9 (reference CPU)
# Per-chip bf16 peak resolved from the device kind via the SAME table the
# training loop's in-loop MFU uses (core/telemetry.device_peak_flops), so
# the two MFU columns share numerator AND denominator; 197e12 (v5e) is
# the fallback for unknown kinds (CPU smoke runs — their MFU is not
# meaningful anyway). The same number applies to "float32" configs:
# XLA's default matmul precision on TPU runs f32 matmuls as bf16 passes
# on the MXU, so the available peak is the bf16 one (measured f32 MFU vs
# a hypothetical smaller f32 peak came out >1, confirming the
# default-precision lowering). Resolved LAZILY: device_peak_flops()
# touches jax.devices(), and importing bench must not initialize the
# backend as a side effect (it would pin a single-process backend under
# an importer that calls jax.distributed.initialize afterwards).
from mobilefinetuner_tpu.core.telemetry import device_peak_flops

_PEAK_CACHE = {}


def peak_flops(dtype: str) -> float:
    if "chip" not in _PEAK_CACHE:
        _PEAK_CACHE["chip"] = device_peak_flops() or 197e12
    return _PEAK_CACHE["chip"]


# The analytic per-step FLOP estimator lives in core/telemetry.py so the
# in-loop step_stats.mfu and this suite's MFU column agree by
# construction (tests/test_bench_contract.py pins the identity).
from mobilefinetuner_tpu.core.telemetry import transformer_flops  # noqa: E402


def executed_flops(n_block_mm, n_head_mm, n_active, B, S, n_layer, n_head,
                   head_dim, full_ft, remat_blocks, remat_head,
                   attn_factor=1.0):
    """FLOPs the compiled step actually EXECUTES — the MFU denominator the
    6ND-style formula above gets wrong in two ways (DESIGN.md §5): it
    counts neither the rematerialization recompute (the checkpointed
    chunked-CE head and, with --remat, the whole block stack run forward
    a second time in the backward) nor the fact that the embedding table
    GATHERS rather than multiplies (only matmul parameters do FLOPs).
    n_block_mm: matmul params in the layer stack (ndim>=3 leaves);
    n_head_mm: lm-head matmul params (V*H for the tied-embed head);
    n_active: EXTRA trainable matmul params beyond the base stacks (the
    LoRA A/B factors; pass 0 for full FT — the full_ft branch already
    counts dW over n_block_mm + n_head_mm). attn_factor: fraction of the dense S^2
    attention actually executed (_attn_factor; 1.0 for the XLA path)."""
    T = B * S
    attn = int(4 * B * n_layer * n_head * S * S * head_dim * attn_factor)
    mm = n_block_mm + n_head_mm + n_active
    fwd = 2 * T * mm + attn
    recompute = ((2 * T * (n_block_mm + n_active) + attn)
                 if remat_blocks else 0) \
        + (2 * T * n_head_mm if remat_head else 0)
    bwd_dx = 2 * T * mm + 2 * attn
    bwd_dw = 2 * T * (n_active if not full_ft
                      else n_block_mm + n_head_mm + n_active)
    return fwd + recompute + bwd_dx + bwd_dw


def _attn_factor(S, head_dim, impl="auto"):
    """Fraction of the dense S^2 attention the step actually executes.
    The flash kernel visits only causally-reachable 512-row blocks: with
    nb = S/512 blocks it runs (nb+1)/(2*nb) of the dense work (1.0 at
    S=512 — a single block skips nothing; 0.75 at S=1024; -> 0.5 as nb
    grows). XLA's masked dense attention always executes everything."""
    from mobilefinetuner_tpu.ops.attention import resolve_impl
    use_flash = impl == "flash" or (impl == "auto"
                                    and resolve_impl(S, head_dim)
                                    == "flash")
    if not use_flash:
        return 1.0
    nb = max(S // 512, 1)
    return (nb + 1) / (2 * nb)


def matmul_param_counts(params, head_key):
    """(block matmul params, head matmul params): ndim>=3 leaves under
    "blocks" are the [L, in, out] weight stacks; the tied head is the
    [V, H] table, a real matmul in the logits projection."""
    n_block = sum(x.size for x in jax.tree.leaves(params["blocks"])
                  if x.ndim >= 3)
    n_head = params[head_key].size
    return n_block, n_head


# Loss columns are comparable ACROSS rows of the same model: every row
# trains on the SAME seeded token stream (prefix-stable across batch
# shapes) for the same number of TOKENS (not steps), then the loss is
# probed on a shared held-out eval stream. Rows that differ only in
# batching/offload/remat land within optimizer-dynamics noise of each
# other, so the column is a training-quality regression signal (round-3
# verdict: per-row step counts made losses pure config skew).
# 24576 = lcm of every row's tokens/step (1024..24576, all powers of two
# times 1 or 3), so the mark is EXACT for every current row; a future
# non-dividing shape rounds up and reports its actual loss_tokens_seen.
LOSS_MARK_TOKENS = 24_576
WARMUP_STEPS = 3


def _loss_mark(tokens_per_step: int) -> int:
    """Steps to reach the loss mark (shared by measure/row_batches so the
    stream length and the training schedule cannot drift apart)."""
    return -(-LOSS_MARK_TOKENS // tokens_per_step)


def measure(step_fn, trainable, frozen, opt, batches, eval_batch,
            steps) -> dict:
    from mobilefinetuner_tpu.core.xla_stats import compiled_peak_bytes
    # AOT-compile once and call the executable directly (jit dispatch
    # would recompile: AOT results don't populate the jit cache), reusing
    # the same compiled object for the memory analysis.
    compiled = step_fn.lower(trainable, frozen, opt, batches[0],
                             jnp.int32(0)).compile()
    peak = compiled_peak_bytes(compiled)
    tokens_per_step = int(batches[0]["input_ids"].size)
    mark = _loss_mark(tokens_per_step)
    tr, op = trainable, opt
    for s in range(mark):
        tr, op, m = compiled(tr, frozen, op, batches[s], jnp.int32(s))
    # comparable-loss probe: the step's loss metric is evaluated at the
    # CURRENT weights before its update, so feeding the shared eval batch
    # reads held-out loss after exactly `mark * tokens_per_step`
    # (== LOSS_MARK_TOKENS for every current row) training tokens. The
    # probe's outputs MUST become the live state (tr/op are donated, so
    # the inputs are dead after the call); that lands one eval-batch
    # update in the weights used for the timed window — accepted: the
    # loss column is read pre-update and throughput is schedule-identical.
    tr, op, m = compiled(tr, frozen, op, eval_batch, jnp.int32(mark))
    loss = float(m["loss"])
    # rows whose mark is short still get WARMUP_STEPS executions before
    # the timed window opens
    warm = max(0, WARMUP_STEPS - mark)
    for s in range(warm):
        tr, op, m = compiled(tr, frozen, op, batches[mark + s],
                             jnp.int32(mark + 1 + s))
    if warm:
        float(m["loss"])
    t0 = time.perf_counter()
    base = mark + warm
    for s in range(steps):
        tr, op, m = compiled(tr, frozen, op, batches[base + s],
                             jnp.int32(base + 1 + s))
    float(m["loss"])  # host sync closes the timed window
    dt = time.perf_counter() - t0
    return {"dt": dt, "loss": loss, "peak_bytes": peak,
            "loss_tokens_seen": mark * tokens_per_step}


EVAL_SEED = 12_345


def synth_batch(vocab, B, S, seed=0):
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, vocab, (B, S)), jnp.int32)
    return {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
            "labels": ids}


def synth_stream(vocab, B, S, n_batches, seed=0):
    """n_batches distinct step batches sliced from ONE seeded token
    stream. numpy's per-element generation makes the stream prefix-stable
    across total sizes, so every row of a model trains on the same
    underlying tokens regardless of its batch shape — only the
    partitioning differs (as it would across real-data configs)."""
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, (n_batches, B, S))
    out = []
    for t in toks:
        ids = jnp.asarray(t, jnp.int32)
        out.append({"input_ids": ids, "attention_mask": jnp.ones_like(ids),
                    "labels": ids})
    return out


def row_batches(vocab, step_b, S, steps):
    """(train stream, eval batch) for one bench row: enough distinct
    batches to cover the loss mark + warmup + the timed window, plus the
    shared held-out eval batch (EVAL_SEED streams are prefix-stable too,
    so different-B rows eval on nested token sets)."""
    mark = _loss_mark(step_b * S)
    n = mark + max(0, WARMUP_STEPS - mark) + steps
    return (synth_stream(vocab, step_b, S, n),
            synth_batch(vocab, step_b, S, seed=EVAL_SEED))


def offload_setup(params, budget_bytes=0):
    """budget_bytes: int, or "streams_only" — the intermediate-budget point
    that spills exactly the streamable layer stacks (whose per-layer
    streaming overlaps compute) and keeps whole-fetch leaves (embedding
    table, norms, biases) HBM-resident, avoiding the serial embed transfer
    on the step's critical path (offload.streams_only_budget)."""
    if budget_bytes == "streams_only":
        from mobilefinetuner_tpu.parallel.offload import streams_only_budget
        budget_bytes = streams_only_budget(params)
    ocfg = OffloadConfig(enable=True, max_resident_bytes=budget_bytes,
                         offload_dtype="bfloat16")
    plan = plan_placement(params, ocfg)
    sh = replicated_sharding(make_mesh(1, 1, devices=jax.devices()[:1]))
    shardings = jax.tree.map(lambda _: sh, params)
    placed = apply_placement(params, plan, shardings, ocfg)
    return placed, (plan, shardings)


def bench_gpt2_lora(B, S, dtype, accum=1, offload=False, impl="auto",
                    steps=40, size="small", remat=False,
                    lora_impl="auto"):
    base = {"small": GPT2Config.gpt2_small, "medium": GPT2Config.gpt2_medium,
            "large": GPT2Config.gpt2_large, "xl": GPT2Config.gpt2_xl,
            "tiny": GPT2Config.tiny}[size]()
    # long-context rows past GPT-2's native 1024 positions: the bench
    # trains randomly-initialized weights, so extending the learned
    # position table is shape plumbing, not a semantics change
    if S > base.n_positions:
        base = dataclasses.replace(base, n_positions=S)
    config = dataclasses.replace(base, attention_impl=impl)
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    spec = LoRASpec(rank=8, alpha=16.0)
    lora = init_lora_gpt2(config, spec, jax.random.PRNGKey(1))
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=1000, lr=2e-4, schedule="constant",
                     warmup_ratio=0.0, grad_accum_steps=accum)
    off = None
    if offload:
        params, off = offload_setup(params)

    def loss_fn(lora_t, p, mb):
        logits = gpt2.forward(config, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              lora=lora_t, compute_dtype=dtype,
                              offload=off, remat=remat,
                              lora_impl=lora_impl)
        return lm_cross_entropy_sum(logits, mb["labels"])

    step_fn = make_train_step(loss_fn, tc, mask=mask, donate=True)
    opt = init_optimizer(lora, tc, mask)
    batches, eval_batch = row_batches(config.vocab_size, B * accum, S,
                                      steps)
    r = measure(step_fn, lora, params, opt, batches, eval_batch, steps)
    r["lora_impl"] = lora_impl
    n_frozen = gpt2.param_count(params)
    n_active = sum(x.size for x in jax.tree.leaves(lora))
    r["flops"] = transformer_flops(n_active, n_frozen, B * accum, S,
                                   config.n_layer, config.n_head,
                                   config.head_dim, full_ft=False)
    n_block, n_head = matmul_param_counts(params, "wte")
    r["flops_exec"] = executed_flops(
        n_block, n_head, n_active, B * accum, S, config.n_layer,
        config.n_head, config.head_dim, full_ft=False,
        remat_blocks=remat or offload, remat_head=False,
        attn_factor=_attn_factor(S, config.head_dim, impl))
    r["tokens"] = B * accum * S
    return r


def bench_gpt2_full(B, S, dtype, steps=40):
    config = GPT2Config.gpt2_small()
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    tc = TrainConfig(total_steps=1000, lr=2e-5, schedule="constant",
                     warmup_ratio=0.0, grad_accum_steps=1)

    def loss_fn(p, _unused, mb):
        logits = gpt2.forward(config, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              compute_dtype=dtype)
        return lm_cross_entropy_sum(logits, mb["labels"])

    step_fn = make_train_step(loss_fn, tc, mask=None, donate=True)
    opt = init_optimizer(params, tc, None)
    batches, eval_batch = row_batches(config.vocab_size, B, S, steps)
    r = measure(step_fn, params, {}, opt, batches, eval_batch, steps)
    n = gpt2.param_count(params)
    r["flops"] = transformer_flops(n, 0, B, S, config.n_layer,
                                   config.n_head, config.head_dim,
                                   full_ft=True)
    n_block, n_head = matmul_param_counts(params, "wte")
    r["flops_exec"] = executed_flops(
        n_block, n_head, 0, B, S, config.n_layer, config.n_head,
        config.head_dim, full_ft=True, remat_blocks=False,
        remat_head=False)
    r["tokens"] = B * S
    return r


def bench_gemma_lora(B, S, dtype, accum=1, offload=False, steps=20,
                     loss_chunks=4, size="270m", offload_budget=0,
                     remat=False, impl="auto", lora_impl="auto"):
    config = (Gemma3TextConfig.gemma3_1b() if size == "1b"
              else Gemma3TextConfig.gemma3_270m())
    config = dataclasses.replace(config, attention_impl=impl)
    params = gemma3.init_params(config, jax.random.PRNGKey(0))
    spec = LoRASpec(rank=8, alpha=32.0, targets="full")
    lora = init_lora_gemma3(config, spec, jax.random.PRNGKey(1))
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=1000, lr=2e-4, schedule="constant",
                     warmup_ratio=0.0, grad_accum_steps=accum)
    off = None
    if offload:
        params, off = offload_setup(params, offload_budget)

    def loss_fn(lora_t, p, mb):
        p2, stream = resolve_offload(p, off)
        hidden = gemma3.hidden_states(
            config, p2, mb["input_ids"],
            attention_mask=mb["attention_mask"], lora=lora_t,
            compute_dtype=dtype, block_stream=stream, remat=remat,
            lora_impl=lora_impl)
        return chunked_lm_cross_entropy_sum(hidden, p2["embed"],
                                            mb["labels"],
                                            num_chunks=loss_chunks,
                                            lora_impl=lora_impl)

    step_fn = make_train_step(loss_fn, tc, mask=mask, donate=True)
    opt = init_optimizer(lora, tc, mask)
    batches, eval_batch = row_batches(config.vocab_size, B * accum, S,
                                      steps)
    r = measure(step_fn, lora, params, opt, batches, eval_batch, steps)
    r["lora_impl"] = lora_impl
    n_frozen = sum(x.size for x in jax.tree.leaves(params))
    n_active = sum(x.size for x in jax.tree.leaves(lora))
    r["flops"] = transformer_flops(
        n_active, n_frozen, B * accum, S, config.num_hidden_layers,
        config.num_attention_heads, config.head_dim, full_ft=False)
    n_block, n_head = matmul_param_counts(params, "embed")
    r["flops_exec"] = executed_flops(
        n_block, n_head, n_active, B * accum, S,
        config.num_hidden_layers, config.num_attention_heads,
        config.head_dim, full_ft=False,
        remat_blocks=remat or offload,   # streaming forces body remat
        remat_head=True,                 # chunked CE is checkpointed
        attn_factor=_attn_factor(S, config.head_dim, impl))
    r["tokens"] = B * accum * S
    return r


def bench_multitenant(dtype, steps, k=8, model="gpt2", B_per=2, S=128,
                      size="small", ref_step_ms=None):
    """Multi-tenant LoRA rows (round 18, DESIGN.md §23): k independent
    adapter jobs through ONE fused train step — stacked [k, r, d] bank,
    ids-routed `_multi_lora` forward, per-slot Adam/LR/clip
    (train/trainer.make_multi_train_step). Each tenant contributes B_per
    rows per step, so the k sweep holds PER-TENANT work constant and
    step_time-vs-k is the fusion claim (LoRAFusion: the memory-bound
    LoRA step has compute headroom for k jobs — near-flat step time).
    Aggregate tokens/s/chip counts every tenant's rows. ref_step_ms:
    the family's k=1 step time, for the step_time_vs_k1 column."""
    from mobilefinetuner_tpu.lora.lora import stack_adapters
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_rows
    from mobilefinetuner_tpu.optim.adam import init_multi_state
    from mobilefinetuner_tpu.train.trainer import make_multi_train_step
    if model == "gemma":
        config = Gemma3TextConfig.gemma3_270m() if size != "tiny" else \
            Gemma3TextConfig.tiny(vocab_size=211)
        params = gemma3.init_params(config, jax.random.PRNGKey(0))
        fwd = gemma3.forward
        spec = LoRASpec(rank=8, alpha=32.0, targets="full", init="peft")
        init_fn, n_layer, n_head, head_dim = (
            init_lora_gemma3, config.num_hidden_layers,
            config.num_attention_heads, config.head_dim)
    else:
        base = {"small": GPT2Config.gpt2_small,
                "tiny": GPT2Config.tiny}[size]()
        config = base
        params = gpt2.init_params(config, jax.random.PRNGKey(0))
        fwd = gpt2.forward
        spec = LoRASpec(rank=8, alpha=16.0)
        init_fn, n_layer, n_head, head_dim = (
            init_lora_gpt2, config.n_layer, config.n_head,
            config.head_dim)
    bank = stack_adapters([init_fn(config, spec, jax.random.PRNGKey(i))
                           for i in range(k)])
    mask = trainable_mask(bank)
    tc = TrainConfig(total_steps=1000, lr=2e-4, schedule="constant",
                     warmup_ratio=0.0)

    def loss_rows(tr, p, mb):
        from mobilefinetuner_tpu.lora.lora import assign_adapters
        routed = assign_adapters(tr, mb["adapter_ids"])
        logits = fwd(config, p, mb["input_ids"],
                     attention_mask=mb["attention_mask"], lora=routed,
                     compute_dtype=dtype)
        return lm_cross_entropy_rows(logits, mb["labels"])

    step_fn = make_multi_train_step(loss_rows, tc, k, mask=mask)
    opt = init_multi_state(bank, tc.adam(), k, mask)
    sched = {"step": jnp.zeros(k, jnp.int32),
             "total": jnp.full(k, 1000.0, jnp.float32),
             "lr": jnp.full(k, 2e-4, jnp.float32),
             "warmup_ratio": jnp.zeros(k, jnp.float32),
             "active": jnp.ones(k, bool)}
    ids = jnp.asarray(np.repeat(np.arange(k, dtype=np.int32), B_per))
    # the shared loss-mark/eval-probe protocol (measure()): train to
    # LOSS_MARK_TOKENS on the seeded stream, read held-out loss on the
    # shared EVAL_SEED batch — the loss column stays comparable across
    # rows (and across `steps` settings), like every other row
    tokens_per_step = k * B_per * S
    mark = _loss_mark(tokens_per_step)
    warm = max(0, WARMUP_STEPS - mark)
    batches = synth_stream(config.vocab_size, k * B_per, S,
                           mark + warm + steps)
    eval_batch = synth_batch(config.vocab_size, k * B_per, S,
                             seed=EVAL_SEED)
    for b in batches + [eval_batch]:
        b["adapter_ids"] = ids
    from mobilefinetuner_tpu.core.xla_stats import compiled_peak_bytes
    compiled = step_fn.lower(bank, params, opt, batches[0],
                             sched).compile()
    peak = compiled_peak_bytes(compiled)
    tr, op = bank, opt

    def advance(tr, op, batch, sched):
        tr, op, m = compiled(tr, params, op, batch, sched)
        return tr, op, m, dict(sched, step=sched["step"] + 1)

    for s in range(mark):
        tr, op, m, sched = advance(tr, op, batches[s], sched)
    # held-out probe: the step's loss metric reads the CURRENT weights
    # pre-update (its outputs must become the live state — donation);
    # aggregate = token-weighted mean over the k slots
    tr, op, m, sched = advance(tr, op, eval_batch, sched)
    l_k = np.asarray(m["loss"], np.float64)
    w_k = np.asarray(m["tokens"], np.float64)
    loss = float((l_k * w_k).sum() / max(w_k.sum(), 1.0))
    for s in range(warm):
        tr, op, m, sched = advance(tr, op, batches[mark + s], sched)
    if warm:
        float(np.asarray(m["loss"])[0])
    t0 = time.perf_counter()
    for s in range(steps):
        tr, op, m, sched = advance(tr, op, batches[mark + warm + s],
                                   sched)
    np.asarray(m["loss"])  # host sync closes the timed window
    dt = time.perf_counter() - t0
    n_frozen = sum(x.size for x in jax.tree.leaves(params))
    # MFU numerator: each token routes through exactly ONE adapter, so
    # the active-param term is one adapter's factors, not the k-slot
    # bank (charging the whole bank would inflate MFU with k)
    n_active = sum(int(x.size) for x in jax.tree.leaves(bank)) // k
    return {"dt": dt, "loss": loss, "peak_bytes": peak,
            "k": k, "tokens": tokens_per_step,
            "flops": transformer_flops(n_active, n_frozen, k * B_per, S,
                                       n_layer, n_head, head_dim,
                                       full_ft=False),
            "ref_step_ms": ref_step_ms,
            "loss_tokens_seen": mark * tokens_per_step}


def mt_finish(name, r, dtype, steps) -> dict:
    """Row schema for the multitenant sweep: the base finish() columns
    plus k, step_time_ms, and step_time_vs_k1 — the LoRAFusion target
    is step_time_vs_k1 staying near 1.0 as k grows (near-flat step time
    while aggregate tokens/s scales with k)."""
    row = finish(name, r, dtype, steps)
    step_ms = r["dt"] / steps * 1000.0
    row["k"] = r["k"]
    row["step_time_ms"] = round(step_ms, 2)
    row["step_time_vs_k1"] = (round(step_ms / r["ref_step_ms"], 3)
                              if r.get("ref_step_ms")
                              else (1.0 if r["k"] == 1 else None))
    return row


def _pipeline_corpus(path: str, n_lines: int = 8000, seed: int = 0):
    """Synthetic WikiText-shaped corpus for the input-pipeline rows."""
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_lines):
            n = int(rng.integers(8, 40))
            f.write(" ".join(f"w{rng.integers(0, 5000)}"
                             for _ in range(n)) + "\n")


def bench_input_pipeline(dtype, steps, model="gpt2", prefetch=2, B=8,
                         S=128, accum=2, size=None, warmup=2,
                         window_tokens=20_000):
    """Input-pipeline rows: the REAL host data path — streaming-mode
    WikiText2Dataset (bounded window, per-epoch shuffle, on-demand
    re-tokenization), grad-accum step-batch assembly, and device
    placement — feeding the standard LoRA train step, with the async
    prefetcher on (depth `prefetch`, lookahead 1) or off (prefetch=0,
    the synchronous reference path). Reports tokens/s plus the step
    loop's measured host-wait, so the BENCH artifact carries the
    host/device breakdown the overlap claims rest on. The other rows
    feed pre-built device arrays and so never see host cost; these two
    columns are where input-pipeline regressions become visible."""
    import itertools
    import tempfile
    import zlib

    from mobilefinetuner_tpu.cli.common import micro_batches
    from mobilefinetuner_tpu.core.xla_stats import compiled_peak_bytes
    from mobilefinetuner_tpu.data.prefetch import Prefetcher
    from mobilefinetuner_tpu.data.wikitext2 import (WT2Config,
                                                    WikiText2Dataset)
    from mobilefinetuner_tpu.parallel.mesh import make_batch_placer

    if model == "gemma":
        config = (Gemma3TextConfig.tiny() if size == "tiny"
                  else Gemma3TextConfig.gemma3_270m())
        params = gemma3.init_params(config, jax.random.PRNGKey(0))
        spec = LoRASpec(rank=8, alpha=32.0, targets="full")
        lora = init_lora_gemma3(config, spec, jax.random.PRNGKey(1))

        def loss_fn(lora_t, p, mb):
            hidden = gemma3.hidden_states(
                config, p, mb["input_ids"],
                attention_mask=mb["attention_mask"], lora=lora_t,
                compute_dtype=dtype)
            return chunked_lm_cross_entropy_sum(hidden, p["embed"],
                                                mb["labels"], num_chunks=4)
    else:
        config = (GPT2Config.tiny() if size == "tiny"
                  else GPT2Config.gpt2_small())
        params = gpt2.init_params(config, jax.random.PRNGKey(0))
        spec = LoRASpec(rank=8, alpha=16.0)
        lora = init_lora_gpt2(config, spec, jax.random.PRNGKey(1))

        def loss_fn(lora_t, p, mb):
            logits = gpt2.forward(config, p, mb["input_ids"],
                                  attention_mask=mb["attention_mask"],
                                  lora=lora_t, compute_dtype=dtype)
            return lm_cross_entropy_sum(logits, mb["labels"])

    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=1000, lr=2e-4, schedule="constant",
                     warmup_ratio=0.0, grad_accum_steps=accum)
    step_fn = make_train_step(loss_fn, tc, mask=mask, donate=True)
    opt = init_optimizer(lora, tc, mask)

    # deterministic word->id hash (crc32, NOT python hash(): unsalted, so
    # prefetch-on and prefetch-off rows train on the identical stream and
    # their loss columns stay comparable across runs too)
    V = config.vocab_size
    encode = lambda s: [zlib.crc32(w.encode()) % (V - 1)
                        for w in s.split()]
    with tempfile.TemporaryDirectory() as d:
        corpus = f"{d}/wiki.train.tokens"
        _pipeline_corpus(corpus)
        cfg = WT2Config(seq_len=S, batch_size=B, seed=0, streaming=True,
                        window_tokens=window_tokens)
        ds = WikiText2Dataset(corpus, "train", cfg, encode, eos_id=V - 1)
        place = make_batch_placer(
            make_mesh(1, 1, devices=jax.devices()[:1]))
        gen = (b for _, b in micro_batches(ds, accum))
        # budget: the compile batch + max(warmup-1, 0) + the timed steps
        stream = Prefetcher(
            itertools.islice(gen, max(warmup, 1) + steps),
            depth=prefetch, place_fn=place, lookahead=1)
        try:
            first = next(stream)
            compiled = step_fn.lower(lora, params, opt, first,
                                     jnp.int32(0)).compile()
            peak = compiled_peak_bytes(compiled)
            tr, op, m = compiled(lora, params, opt, first, jnp.int32(0))
            for s in range(1, warmup):
                tr, op, m = compiled(tr, params, op, next(stream),
                                     jnp.int32(s))
            float(m["loss"])  # drain: the timed window starts clean
            wait_ms = 0.0
            t0 = time.perf_counter()
            for s in range(steps):
                tw = time.perf_counter()
                batch = next(stream)
                wait_ms += (time.perf_counter() - tw) * 1000
                tr, op, m = compiled(tr, params, op, batch,
                                     jnp.int32(warmup + s))
            loss = float(m["loss"])  # host sync closes the window
            dt = time.perf_counter() - t0
        finally:
            stream.close()
    return {"dt": dt, "loss": loss, "peak_bytes": peak,
            "tokens": B * accum * S, "host_wait_ms": wait_ms,
            "flops": 0}


def cap_frac_of(peak_mb):
    """peak_hbm_mb / per-device HBM capacity — how close to the ceiling
    this row runs, the number the round-16 admission layer budgets
    against (core/memory_guard.device_capacity_mb: memory_stats
    bytes_limit, else the device-kind table). None when either side is
    unknown (e.g. CPU smoke runs)."""
    from mobilefinetuner_tpu.core.memory_guard import device_capacity_mb
    cap, _ = device_capacity_mb()
    if not cap or not peak_mb:
        return None
    return round(peak_mb / cap, 4)


def pipe_finish(name, r, dtype, steps) -> dict:
    """Input-pipeline row shape: throughput + host/device breakdown."""
    toks_per_sec = r["tokens"] * steps / r["dt"]
    peak_mb = round(r["peak_bytes"] / 2 ** 20, 1)
    return {
        "config": name,
        "tokens_per_sec_per_chip": round(toks_per_sec, 1),
        "vs_baseline": round(toks_per_sec / BASELINE_TOKENS_PER_SEC, 2),
        # fraction of the timed window the step loop spent blocked on the
        # input pipeline (queue wait + lookahead placement); the sync-vs-
        # prefetch row pair is the overlap measurement
        "host_wait_frac": round(r["host_wait_ms"] / (r["dt"] * 1000), 4),
        "host_wait_ms_per_step": round(r["host_wait_ms"] / steps, 2),
        "mfu": None,
        "peak_hbm_mb": peak_mb,
        "cap_frac": cap_frac_of(peak_mb),
        "loss": round(r["loss"], 4),
    }


_GEMMA1B_NP = None


def bench_gemma_full_offload(B, S, dtype, steps=10, loss_chunks=8,
                             tier16: bool = False):
    """Gemma-1B FULL fine-tune on one chip: f32 master weights + Adam m/v
    live in pinned host RAM and stream through the scanned update
    (optim/opt_offload.py); the device holds only the bf16 compute copy.
    Resident full FT would need ~16 GB of optimizer state alone — the
    reference cannot do this at any scale.

    tier16 stores the streamed master (stochastic-rounded) and m/v
    (sqrt-encoded v) in bf16 on the host — 12 GB/step of DMA instead of
    24 (OptOffloadSpec; the analog of the reference's fp16 slow tier,
    parameter_sharder.cpp:215-232)."""
    from mobilefinetuner_tpu.optim.opt_offload import (
        OptOffloadSpec, init_opt_offload, make_offload_train_step,
        plan_opt_offload)
    spec = OptOffloadSpec(state_dtype="bfloat16", master_dtype="bfloat16") \
        if tier16 else OptOffloadSpec()
    config = Gemma3TextConfig.gemma3_1b()
    # host-numpy param cache shared by the f32 and tier16 rows: the 1B
    # init + device->host staging costs minutes on this platform and is
    # identical for both specs (init_opt_offload stages from host numpy
    # either way)
    global _GEMMA1B_NP
    if _GEMMA1B_NP is None:
        _GEMMA1B_NP = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)),
            gemma3.init_params(config, jax.random.PRNGKey(0)))
    params = _GEMMA1B_NP
    n = sum(x.size for x in jax.tree.leaves(params))
    plan = plan_opt_offload(params, spec)
    compute, opt = init_opt_offload(params, plan, compute_dtype=dtype,
                                    spec=spec)
    del params  # the module-level np cache keeps the host copy
    tc = TrainConfig(total_steps=1000, lr=2e-5, schedule="constant",
                     warmup_ratio=0.0)

    def loss_fn(p, _unused, mb):
        hidden = gemma3.hidden_states(
            config, p, mb["input_ids"],
            attention_mask=mb["attention_mask"], compute_dtype=dtype,
            remat=True)
        return chunked_lm_cross_entropy_sum(hidden, p["embed"],
                                            mb["labels"],
                                            num_chunks=loss_chunks)

    step_fn = make_offload_train_step(loss_fn, tc, plan,
                                      compute_dtype=dtype, donate=True,
                                      spec=spec)
    batches, eval_batch = row_batches(config.vocab_size, B, S, steps)
    r = measure(step_fn, compute, None, opt, batches, eval_batch, steps)
    r["flops"] = transformer_flops(
        n, 0, B, S, config.num_hidden_layers,
        config.num_attention_heads, config.head_dim, full_ft=True)
    n_block, n_head = matmul_param_counts(compute, "embed")
    r["flops_exec"] = executed_flops(
        n_block, n_head, 0, B, S, config.num_hidden_layers,
        config.num_attention_heads, config.head_dim, full_ft=True,
        remat_blocks=True, remat_head=True,
        attn_factor=_attn_factor(S, config.head_dim))
    r["tokens"] = B * S
    return r


def bench_generate(B=8, P=128, N=64, dtype=jnp.bfloat16, pipeline=8,
                   model="gpt2", adapters=0):
    """Generate throughput (models/generate.py): B prompts of length P, N
    greedy tokens each; tokens/sec counts only the B*N GENERATED tokens.

    Two numbers: `latency_ms` is one synchronous call (prefill + N decode
    steps + the host round trip — what an interactive user sees; on the
    tunneled platform this includes ~105 ms of fixed dispatch RTT that a
    directly-attached chip would not pay), and the primary tokens/sec is
    SUSTAINED serving throughput: `pipeline` calls dispatched
    back-to-back with one sync at the end, so the dispatch latency
    overlaps device work the way a serving loop overlaps requests.

    The B=8 marginal decode cost is byte-floor-bound (weights+cache reads
    per token-step, DESIGN.md §10a), so batch is the serving-throughput
    lever — hence the B=32 rows alongside the historical B=8 row.

    adapters=k serves k distinct stacked LoRA adapters routed round-robin
    over the batch rows through the dynamic per-layer LoRA path
    (lora.stack_adapters + assign_adapters; correctness oracle:
    tests/test_multi_adapter.py row-exact equality)."""
    from mobilefinetuner_tpu.models.generate import (SampleConfig,
                                                     gemma3_generate,
                                                     gpt2_generate)
    if model == "gemma":
        config = Gemma3TextConfig.gemma3_270m()
        params = gemma3.init_params(config, jax.random.PRNGKey(0))
        gen = gemma3_generate
    else:
        config = GPT2Config.gpt2_small()
        params = gpt2.init_params(config, jax.random.PRNGKey(0))
        gen = gpt2_generate
    lora = None
    if adapters:
        from mobilefinetuner_tpu.lora.lora import (LoRASpec,
                                                   assign_adapters,
                                                   init_lora_gemma3,
                                                   init_lora_gpt2,
                                                   stack_adapters)
        init_fn = init_lora_gemma3 if model == "gemma" else init_lora_gpt2
        spec = LoRASpec(rank=8, alpha=16.0)
        adv = [init_fn(config, spec, jax.random.PRNGKey(i))
               for i in range(adapters)]
        # randomize B so the adapter deltas are real work, not zeros
        adv = [jax.tree.map(
            lambda l, k=i: l if l.ndim == 0 else
            0.02 * jax.random.normal(jax.random.PRNGKey(k + 77), l.shape),
            a) for i, a in enumerate(adv)]
        lora = assign_adapters(stack_adapters(adv),
                               [b % adapters for b in range(B)])
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, config.vocab_size, (B, P)), jnp.int32)
    mask = jnp.ones_like(ids)
    cfg = SampleConfig(max_new_tokens=N, greedy=True, eos_id=None)
    # params AND lora as jit ARGUMENTS (a closure would bake the weights
    # and adapter stacks into the HLO as constants — oversized programs
    # for the compile service, and a serving loop swaps adapters without
    # recompiling)
    fn = jax.jit(lambda p, lo, i, m: gen(config, p, i, m, cfg,
                                         compute_dtype=dtype, lora=lo))
    out = fn(params, lora, ids, mask)
    np.asarray(out)  # compile + run
    t0 = time.perf_counter()
    out = fn(params, lora, ids, mask)
    np.asarray(out)  # host sync
    latency = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [fn(params, lora, ids, mask) for _ in range(pipeline)]
    np.asarray(outs[-1])
    dt = time.perf_counter() - t0
    return {"dt": dt, "tokens": pipeline * B * N, "loss": 0.0,
            "peak_bytes": 0, "flops": 0,
            "latency_ms": round(latency * 1000, 1)}


def finish(name, r, dtype, steps) -> dict:
    toks_per_sec = r["tokens"] * steps / r["dt"]
    peak_mb = round(r["peak_bytes"] / 2 ** 20, 1)
    return {
        "config": name,
        "tokens_per_sec_per_chip": round(toks_per_sec, 1),
        "vs_baseline": round(toks_per_sec / BASELINE_TOKENS_PER_SEC, 2),
        "mfu": round(r["flops"] * steps / r["dt"] / peak_flops(dtype), 4),
        # mfu from XLA's executed-FLOP count (remat recompute included,
        # embedding gathers excluded); mfu above is the standard 6ND-style
        # formula — both published so neither misleads alone
        "mfu_executed": (round(r["flops_exec"] * steps / r["dt"]
                               / peak_flops(dtype), 4)
                         if r.get("flops_exec") else None),
        "peak_hbm_mb": peak_mb,
        # how close to the per-device HBM ceiling the row ran (the
        # round-16 admission layer's cap source); None off-accelerator
        "cap_frac": cap_frac_of(peak_mb),
        # held-out loss after >= LOSS_MARK_TOKENS training tokens on the
        # shared stream — comparable across rows of the same model
        "loss": round(r["loss"], 4),
        "loss_tokens_seen": r.get("loss_tokens_seen"),
        # present on the LoRA rows: which models/lora_apply.py path the
        # row ran (the lorafused-vs-loranaive pairs are the r12 delta)
        **({"lora_impl": r["lora_impl"]} if "lora_impl" in r else {}),
    }


def main():
    on_tpu = jax.devices()[0].platform != "cpu"
    # run registry (core/run_registry.py, DESIGN.md §28): bench.py takes
    # no flags, so registration rides $MFT_RUN_REGISTRY alone. A kill
    # mid-suite leaves the start record; the next registry open settles
    # it to "interrupted" (completed rows survive via the per-row flush).
    from mobilefinetuner_tpu.core.run_registry import registry_from
    _reg = registry_from("")
    run_rec = _reg.begin(
        "bench", "bench", config={"on_tpu": on_tpu},
        platform=jax.devices()[0].platform,
        artifacts=["BENCH_SUITE.json"]) if _reg else None
    steps = 40 if on_tpu else 2
    gsteps = 20 if on_tpu else 2
    bf16, f32 = "bfloat16", "float32"
    # batch sizes from the v5e sweep (B=64 beats 32 by 12% for GPT-2s
    # LoRA at 10.9 GB peak; B=128 OOMs on the [B,S,V] CE temps; Gemma
    # B=16/chunks=4 beats 8/8 by 30% at 8.4 GB)
    B = 64 if on_tpu else 2
    FB = 32 if on_tpu else 2  # full-FT: Adam m/v + grads double the tree
    S = 128 if on_tpu else 64
    GB, GS = (16, 256) if on_tpu else (2, 64)

    suite = []

    def flush_suite():
        # incremental: a killed/timed-out run still leaves every
        # completed row on disk (the 1B full-offload rows alone take
        # ~30 min; losing 19 finished rows to a timeout is worse than
        # a partial artifact). temp+rename so a kill MID-flush can't
        # leave truncated JSON.
        import os
        with open("BENCH_SUITE.json.tmp", "w") as f:
            json.dump({"suite": suite,
                       "peak_flops_assumed": {"bfloat16": peak_flops("bfloat16"),
                                              "float32": peak_flops("float32")},
                       "baseline_tokens_per_sec": BASELINE_TOKENS_PER_SEC},
                      f, indent=1)
        os.replace("BENCH_SUITE.json.tmp", "BENCH_SUITE.json")

    def run(name, fn, dtype, n, finisher=finish, **kw):
        try:
            r = fn(dtype=jnp.bfloat16 if dtype == bf16 else jnp.float32,
                   steps=n, **kw)
            row = finisher(name, r, dtype, n)
        except Exception as e:  # record, don't kill the suite
            row = {"config": name, "error": f"{type(e).__name__}: {e}"}
        suite.append(row)
        print(json.dumps(row), file=sys.stderr)
        flush_suite()
        return row

    headline = run(f"gpt2s_lora_bf16_B{B}_S128", bench_gpt2_lora, bf16,
                   steps, B=B, S=S)
    # driver contract: exactly one JSON line on stdout (headline config).
    # Printed IMMEDIATELY after the headline row — the full suite now
    # runs >1 h on the chip (two 1B full-FT offload configs alone cost
    # ~30 min of init+compile), and a driver-side timeout killing the
    # tail must not lose the headline metric (completed rows survive in
    # BENCH_SUITE.json via the per-row flush either way). A failed
    # headline reports value 0 and exits 1 at the END — the remaining
    # rows still run and land in the artifact.
    if "error" in headline:
        print(json.dumps({
            "metric": "gpt2s_lora_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec/chip", "vs_baseline": 0.0,
            "error": headline["error"]}), flush=True)
    else:
        print(json.dumps({
            "metric": "gpt2s_lora_train_tokens_per_sec_per_chip",
            "value": headline["tokens_per_sec_per_chip"],
            "unit": "tokens/sec/chip",
            "vs_baseline": headline["vs_baseline"],
            "mfu": headline["mfu"],
            "peak_hbm_mb": headline["peak_hbm_mb"],
            "cap_frac": headline.get("cap_frac"),
        }), flush=True)
    if on_tpu:  # the full suite is a TPU artifact; off-TPU is a smoke
        run(f"gpt2s_lora_f32_B{B}_S128", bench_gpt2_lora, f32, steps,
            B=B, S=S)
        run("gpt2s_lora_bf16_accum4", bench_gpt2_lora, bf16, steps,
            B=max(B // 4, 1), S=S, accum=4)
        run("gpt2s_lora_bf16_offload_stream", bench_gpt2_lora, bf16,
            steps, B=B, S=S, offload=True)
        run(f"gpt2s_full_bf16_B{FB}_S128", bench_gpt2_full, bf16, steps,
            B=FB, S=S)
        run(f"gpt2s_full_f32_B{FB}_S128", bench_gpt2_full, f32, steps,
            B=FB, S=S)
        run(f"gemma270m_lora_bf16_B{GB}_S256", bench_gemma_lora, bf16,
            gsteps, B=GB, S=GS)
        run("gemma270m_lora_bf16_offload_stream", bench_gemma_lora, bf16,
            gsteps, B=GB, S=GS, offload=True)
        # intermediate-budget point on the overhead/residency curve: spill
        # only the streamable layer stacks, keep the 262k-vocab embedding
        # HBM-resident (its whole-tensor fetch is a serial transfer on the
        # critical path; the per-layer streams overlap compute). B=32 so
        # each fetched byte feeds 2x the tokens — with the B=32 resident
        # row next to it as the apples-to-apples comparison.
        run("gemma270m_lora_bf16_offload_embed_resident_B32",
            bench_gemma_lora, bf16, gsteps, B=32, S=GS, offload=True,
            offload_budget="streams_only")
        run("gemma270m_lora_bf16_resident_B32", bench_gemma_lora, bf16,
            gsteps, B=32, S=GS)
        # the reference's benchmark table spans GPT-2 S/M and Gemma
        # 270M/1B (README.md:406-411); cover the larger two as well
        run("gpt2m_lora_bf16_B32_S128", bench_gpt2_lora, bf16, steps,
            B=32, S=S, size="medium")
        # the README claims GPT-2 small/medium/large/xl: measure all four
        run("gpt2l_lora_bf16_B16_S128", bench_gpt2_lora, bf16,
            max(steps // 2, 2), B=16, S=S, size="large")
        run("gpt2xl_lora_bf16_B8_S128", bench_gpt2_lora, bf16,
            max(steps // 4, 2), B=8, S=S, size="xl", remat=True)
        run("gemma1b_lora_bf16_B8_S256", bench_gemma_lora, bf16,
            max(gsteps // 2, 2), B=8, S=GS, loss_chunks=8, size="1b")
        run("gemma1b_lora_bf16_offload_stream", bench_gemma_lora, bf16,
            max(gsteps // 2, 2), B=8, S=GS, offload=True, loss_chunks=8,
            size="1b")  # same B as the resident row: comparable
        # what the freed HBM is FOR: the resident model caps out at B=8
        # (14.5 GB peak); streaming the blocks frees enough HBM for B=32,
        # amortizing the (DMA-bound) layer fetches over 4x the tokens
        run("gemma1b_lora_bf16_offload_B32", bench_gemma_lora, bf16,
            max(gsteps // 2, 2), B=32, S=GS, offload=True, loss_chunks=8,
            size="1b", offload_budget="streams_only")
        # the offload FRONTIER between the 1.2 GB floor and the 3.9 GB
        # streams-only point (r3 verdict #5): at minimum memory the step
        # is bound by the serial 604 MB embed fetch (~270 ms at the
        # ~2 GiB/s single-stream host link), so batch is the lever —
        # B=16 at budget 0 clears 10k tok/s in 1.7 GB
        run("gemma1b_lora_bf16_offload_B16", bench_gemma_lora, bf16,
            max(gsteps // 2, 2), B=16, S=GS, offload=True, loss_chunks=8,
            size="1b")
        run("gemma1b_lora_bf16_offload_embed_resident_B16",
            bench_gemma_lora, bf16, max(gsteps // 2, 2), B=16, S=GS,
            offload=True, loss_chunks=8, size="1b",
            offload_budget="streams_only")
        # rematerialization as a THROUGHPUT lever at the 1B scale: the
        # recompute costs less than the batch-size constraint it lifts
        # (B=8 no-remat is activation-bound at 14.5 GB; remat B=24 runs
        # 12% faster at half the memory — v5e sweep: B=16 17.2k,
        # B=24 18.0k, B=32 18.0k, so 24 is the knee)
        run("gemma1b_lora_bf16_remat_B24", bench_gemma_lora, bf16,
            max(gsteps // 2, 2), B=24, S=GS, loss_chunks=12, size="1b",
            remat=True)
        # FULL fine-tuning of the 1B model on one 16 GB chip: master +
        # Adam state stream from pinned host (~24 GB/step of DMA that XLA
        # overlaps with compute — measured B sweep: 8->1.1k, 24->2.8k,
        # 48->4.7k, 96->6.8k, 128->7.5k tok/s at 13.4 GB peak; the
        # optimizer stream is a fixed cost, so batch amortizes it)
        run("gemma1b_full_bf16_opt_offload_B96", bench_gemma_full_offload,
            bf16, max(gsteps // 2, 2), B=96, S=GS)
        # the 16-bit host tier halves the dominant optimizer DMA
        # (24 -> 12 GB/step): bf16 master (stochastic-rounded write-back)
        # + bf16 m + sqrt-encoded bf16 v, dequantized on-chip
        run("gemma1b_full_bf16_opt_offload16_B96",
            bench_gemma_full_offload, bf16, max(gsteps // 2, 2), B=96,
            S=GS, tier16=True)
        # the 1B host-numpy cache (~4 GB) has no further consumers —
        # release it before the flash/generate rows
        global _GEMMA1B_NP
        _GEMMA1B_NP = None
        # flash vs xla at the long-context shape ('auto' resolves flash)
        run("gpt2s_lora_bf16_S1024_flash", bench_gpt2_lora, bf16, steps,
            B=4, S=1024, impl="flash")
        run("gpt2s_lora_bf16_S1024_xla", bench_gpt2_lora, bf16, steps,
            B=4, S=1024, impl="xla")
        # the r4 crossover retune: flash wins from S=512 at D=64 (e2e
        # +20%; the dispatch-floor-limited microbench said parity —
        # resolve_impl docstring has the measurement story)
        run("gpt2s_lora_bf16_S512_flash", bench_gpt2_lora, bf16, steps,
            B=16, S=512, impl="flash")
        run("gpt2s_lora_bf16_S512_xla", bench_gpt2_lora, bf16, steps,
            B=16, S=512, impl="xla")
        # S=2048 long-context e2e (r6): the regime the memory-efficient
        # attention exists for. Pins DESIGN §6a's 2.7-2.8x claim (which
        # only had a microbench artifact behind it) with driver-captured
        # e2e rows, and exercises the merged one-pass backward kernel at
        # depth 4 k-blocks per row block. GPT-2s runs with the position
        # table extended to 2048 (randomly-init weights — shape plumbing
        # only); the Gemma pair is the FIRST e2e measurement of the
        # D=256 S>=2048 crossover resolve_impl asserts.
        run("gpt2s_lora_bf16_S2048_flash", bench_gpt2_lora, bf16, steps,
            B=2, S=2048, impl="flash")
        run("gpt2s_lora_bf16_S2048_xla", bench_gpt2_lora, bf16, steps,
            B=2, S=2048, impl="xla")
        run("gemma270m_lora_bf16_S2048_flash", bench_gemma_lora, bf16,
            gsteps, B=2, S=2048, impl="flash")
        run("gemma270m_lora_bf16_S2048_xla", bench_gemma_lora, bf16,
            gsteps, B=2, S=2048, impl="xla")
        # LoRA hot-path rows (r12, DESIGN.md §17): fused (shape-aware
        # contraction order + Pallas epilogue at eligible sites) vs the
        # naive oracle, both families, S=512/1024/2048 — the tokens/s
        # delta of never round-tripping the [N, d_out] adapter delta
        # through HBM. Parity is pinned by tests/test_lora.py; these
        # rows price it.
        for s_len, b_sz in ((512, 16), (1024, 4), (2048, 2)):
            for li in ("naive", "fused"):
                run(f"gpt2s_lora_bf16_S{s_len}_lora{li}",
                    bench_gpt2_lora, bf16, steps, B=b_sz, S=s_len,
                    lora_impl=li)
                run(f"gemma270m_lora_bf16_S{s_len}_lora{li}",
                    bench_gemma_lora, bf16, gsteps,
                    B=max(b_sz // 2, 2), S=s_len, lora_impl=li)
        # multi-tenant LoRA rows (r18, DESIGN.md §23): k adapter jobs
        # through ONE fused train step, per-tenant work held constant —
        # step_time_vs_k1 near 1.0 while aggregate tokens/s scales with
        # k is the LoRAFusion claim (the memory-bound LoRA step has
        # compute headroom for k jobs). k=32 GPT-2s at B_per=2 keeps
        # the peak under the fused-CE temps ceiling.
        mt_ref = {}
        for fam, mt_kw in (("gpt2s", dict(model="gpt2", B_per=2, S=S)),
                           ("gemma270m", dict(model="gemma", B_per=2,
                                              S=GS))):
            for kk in (1, 8, 32):
                row = run(f"{fam}_multitenant_k{kk}_bf16",
                          bench_multitenant, bf16, gsteps, k=kk,
                          finisher=mt_finish,
                          ref_step_ms=mt_ref.get(fam), **mt_kw)
                if kk == 1 and "step_time_ms" in row:
                    mt_ref[fam] = row["step_time_ms"]
        # input-pipeline rows (r7): every other row feeds pre-built
        # device arrays, so host-side batch production (streaming-window
        # tokenization + accum assembly + placement) never shows up in
        # them. These four run the REAL data path and measure the step
        # loop's host-wait with the async prefetcher off vs on — the
        # sync/prefetch pair per model is the overlap measurement, and
        # host_wait_frac is the bubble the prefetcher exists to close.
        run(f"gpt2s_input_pipeline_sync_B{B}_S128", bench_input_pipeline,
            bf16, steps, B=B, S=S, prefetch=0, finisher=pipe_finish)
        run(f"gpt2s_input_pipeline_prefetch2_B{B}_S128",
            bench_input_pipeline, bf16, steps, B=B, S=S, prefetch=2,
            finisher=pipe_finish)
        run(f"gemma270m_input_pipeline_sync_B{GB}_S256",
            bench_input_pipeline, bf16, gsteps, model="gemma", B=GB,
            S=GS, prefetch=0, finisher=pipe_finish)
        run(f"gemma270m_input_pipeline_prefetch2_B{GB}_S256",
            bench_input_pipeline, bf16, gsteps, model="gemma", B=GB,
            S=GS, prefetch=2, finisher=pipe_finish)
        # end-to-end generate throughput (prefill + sequential decode;
        # tokens/sec counts generated tokens only).
        # finish() is training-shaped, so pass run() a custom finisher.
        gen_finish = lambda name, r, dtype, n: {
            "config": name,
            "tokens_per_sec_per_chip": round(r["tokens"] / r["dt"], 1),
            "single_call_latency_ms": r["latency_ms"],
            "vs_baseline": None, "mfu": None, "peak_hbm_mb": None,
            "cap_frac": None, "loss": None}
        run("gpt2s_generate_e2e_B8_P128_N64",
            lambda dtype, steps: bench_generate(dtype=dtype), bf16, 1,
            finisher=gen_finish)
        # serving regime: the B=8 marginal decode cost is pinned at the
        # weights+cache byte floor (DESIGN.md §10a), so batch is the
        # throughput lever — B=32 amortizes the dominant weight stream
        # over 4x the rows
        run("gpt2s_generate_e2e_B32_P128_N64",
            lambda dtype, steps: bench_generate(B=32, dtype=dtype), bf16,
            1, finisher=gen_finish)
        run("gemma270m_generate_e2e_B8_P128_N64",
            lambda dtype, steps: bench_generate(model="gemma",
                                                dtype=dtype), bf16, 1,
            finisher=gen_finish)
        run("gemma270m_generate_e2e_B32_P128_N64",
            lambda dtype, steps: bench_generate(B=32, model="gemma",
                                                dtype=dtype), bf16, 1,
            finisher=gen_finish)
        # multi-adapter batched serving (4 adapters round-robin over the
        # rows, dynamic per-layer LoRA path): priced against the B=32
        # merged-weights row above (r4 verdict #6)
        run("gpt2s_generate_multi_adapter4_B32_P128_N64",
            lambda dtype, steps: bench_generate(B=32, adapters=4,
                                                dtype=dtype), bf16, 1,
            finisher=gen_finish)

    # (run() flushed after every row; the headline stdout line was
    # printed right after the headline row above)
    if run_rec is not None:
        # per-row errors are recorded IN the artifact; the suite itself
        # completed, so the registry record is "ok" either way
        run_rec.finalize("ok")
    return 1 if "error" in headline else 0


if __name__ == "__main__":
    sys.exit(main())
