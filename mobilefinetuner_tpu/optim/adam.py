"""Adam / AdamW over parameter pytrees, with state (de)serialization.

TPU-native re-design of the reference optimizer
(reference: optim/adam.h:23-105, adam.cpp:25-91 — scalar-loop Adam with bias
correction, optional AMSGrad, per-param state): here the update is a pure
pytree transform that XLA fuses into a handful of elementwise kernels, and
state lives as pytrees shardable with the same FSDP specs as the params
(ZeRO optimizer-state partitioning for free).

Weight-decay semantics: the reference applies L2-INTO-GRADIENT decay
(adam.cpp:65-67), not decoupled AdamW, despite its config comment
(SURVEY.md §2.12.2). We default to proper decoupled AdamW and keep
`coupled_weight_decay=True` as a reference-parity mode.

State save/load mirrors Adam::save/load (adam.cpp:103+) but uses a
safetensors blob instead of a bespoke binary format.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class AdamConfig:
    lr: float = 1e-4
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    # True = reference-parity L2-into-gradient decay (adam.cpp:65-67);
    # False = decoupled AdamW.
    coupled_weight_decay: bool = False
    amsgrad: bool = False


def init_state(params, config: AdamConfig,
               mask: Optional[Any] = None) -> dict:
    """Adam state pytree. `mask` (pytree of bools) marks trainable leaves;
    non-trainable leaves get zero-size placeholders (no HBM for frozen
    params — the state-partitioning dimension of ZeRO, SURVEY.md §2.11)."""
    if mask is None:
        zeros = jax.tree.map(jnp.zeros_like, params)
        mk = lambda: jax.tree.map(jnp.zeros_like, params)
    else:
        def z(p, m):
            return jnp.zeros_like(p) if m else jnp.zeros((0,), p.dtype)
        mk = lambda: jax.tree.map(z, params, mask)
        zeros = mk()
    state = {"step": jnp.zeros((), jnp.int32), "m": zeros, "v": mk()}
    if config.amsgrad:
        state["v_hat"] = mk()
    return state


def adam_update(grads, state: dict, params, config: AdamConfig,
                lr: jnp.ndarray, mask: Optional[Any] = None,
                with_norms: bool = False):
    """One Adam step: returns (new_params, new_state), or with
    with_norms=True (new_params, new_state, (update_norm, param_norm)).

    lr is a traced scalar so LR schedules don't retrigger compilation.
    mask: pytree of bools — False leaves pass through unchanged (used to
    freeze LoRA "scale" leaves and any non-trainable params).
    with_norms: also return the global L2 norm of the applied update
    Δw = -lr·(m̂/(√v̂+ε) [+ wd·w]) and of the PRE-update trainable
    params, both accumulated INSIDE the per-leaf update where the delta
    already exists — a post-hoc `new_params - params` would keep the
    donated pre-update tree alive past the in-place update and cost a
    params-sized peak-HBM bump on full fine-tunes. Only masked-True
    (trainable) leaves contribute.
    """
    step = state["step"] + 1
    b1, b2 = config.beta1, config.beta2
    bc1 = 1.0 - b1 ** step.astype(jnp.float32)
    bc2 = 1.0 - b2 ** step.astype(jnp.float32)

    def leaf_update(p, g, m, v, vh, do):
        if not do:
            return p, m, v, vh, None, None
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        if config.coupled_weight_decay and config.weight_decay:
            g = g + config.weight_decay * pf
        m2 = b1 * m + (1 - b1) * g
        v2 = b2 * v + (1 - b2) * g * g
        m_hat = m2 / bc1
        if config.amsgrad:
            vh2 = jnp.maximum(vh, v2)
            denom = jnp.sqrt(vh2 / bc2) + config.eps
        else:
            vh2 = vh
            denom = jnp.sqrt(v2 / bc2) + config.eps
        upd = m_hat / denom
        if not config.coupled_weight_decay and config.weight_decay:
            upd = upd + config.weight_decay * pf
        delta = lr * upd
        usq = jnp.sum(delta * delta) if with_norms else None
        psq = jnp.sum(pf * pf) if with_norms else None
        return (pf - delta).astype(p.dtype), m2, v2, vh2, usq, psq

    leaves_p, treedef = jax.tree.flatten(params)
    leaves_g = treedef.flatten_up_to(grads)
    leaves_m = treedef.flatten_up_to(state["m"])
    leaves_v = treedef.flatten_up_to(state["v"])
    leaves_vh = (treedef.flatten_up_to(state["v_hat"])
                 if config.amsgrad else [None] * len(leaves_p))
    leaves_do = (treedef.flatten_up_to(mask) if mask is not None
                 else [True] * len(leaves_p))

    out = [leaf_update(p, g, m, v, vh if vh is not None else 0.0, do)
           for p, g, m, v, vh, do in zip(leaves_p, leaves_g, leaves_m,
                                         leaves_v, leaves_vh, leaves_do)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_state = {"step": step,
                 "m": treedef.unflatten([o[1] for o in out]),
                 "v": treedef.unflatten([o[2] for o in out])}
    if config.amsgrad:
        new_state["v_hat"] = treedef.unflatten([o[3] for o in out])
    if with_norms:
        usq = [o[4] for o in out if o[4] is not None]
        psq = [o[5] for o in out if o[5] is not None]
        upd_norm = jnp.sqrt(jnp.sum(jnp.stack(usq))) if usq \
            else jnp.float32(0.0)
        w_norm = jnp.sqrt(jnp.sum(jnp.stack(psq))) if psq \
            else jnp.float32(0.0)
        return new_p, new_state, (upd_norm, w_norm)
    return new_p, new_state


# ------------------------- multi-adapter (stacked) Adam ---------------------
# The multi-tenant train engine (mobilefinetuner_tpu/multitenant/) stacks k
# independent LoRA jobs' trainables along a leading adapter axis
# (lora.stack_adapters layout). Optimizer state stacks the same way —
# m/v [k, ...] with a PER-SLOT step counter [k] — so k jobs' Adam updates
# run as one fused elementwise pass, and per-slot bias correction / LR /
# apply-masking are all DATA (tenant join/leave never retraces). Every
# per-slot computation below is the scalar adam_update formula broadcast
# over the leading axis; the k-adapter-vs-solo parity oracle
# (tests/test_multitenant.py) pins the identity to <= 1e-5.


def init_multi_state(stacked_params, config: AdamConfig, k: int,
                     mask: Optional[Any] = None) -> dict:
    """Adam state for a stacked [k, ...] trainable bank: m/v mirror the
    stacked leaves (zero-size placeholders on masked leaves, like
    init_state) and `step` is a PER-SLOT [k] int32 counter — a freshly
    admitted job starts its bias correction at 0 regardless of how long
    its slot's neighbors have been training."""
    base = init_state(stacked_params, config, mask)
    base["step"] = jnp.zeros((k,), jnp.int32)
    return base


def _bsel(v, x):
    """Broadcast a per-slot [k] vector over a stacked [k, ...] leaf."""
    return v.reshape(v.shape[:1] + (1,) * (x.ndim - 1))


def multi_adam_update(grads, state: dict, params, config: AdamConfig,
                      lr_k: jnp.ndarray, apply_k: jnp.ndarray,
                      mask: Optional[Any] = None,
                      with_norms: bool = False):
    """One stacked Adam step over a [k, ...] adapter bank.

    lr_k: per-slot learning rates [k] (traced — per-tenant schedules are
    data). apply_k: per-slot bool [k]; False slots pass params AND state
    through untouched (inactive slots between jobs, and skipped slots
    under the non-finite guard — a masked slot's m must not decay and
    its step counter must not advance, or a refilled slot would inherit
    a corrupted bias correction). Bias correction uses each slot's OWN
    step counter. Returns (new_params, new_state) or, with
    with_norms=True, (..., (update_norm [k], param_norm [k])) — per-slot
    norms of the WOULD-BE update (reported even for masked slots, like
    the solo path reports the skipped update's ratio).
    """
    app = jnp.asarray(apply_k).astype(bool)
    step2 = state["step"] + 1
    b1, b2 = config.beta1, config.beta2
    bc1 = 1.0 - b1 ** step2.astype(jnp.float32)   # [k]
    bc2 = 1.0 - b2 ** step2.astype(jnp.float32)

    def leaf_update(p, g, m, v, vh, do):
        if not do:
            return p, m, v, vh, None, None
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        if config.coupled_weight_decay and config.weight_decay:
            g = g + config.weight_decay * pf
        m2 = b1 * m + (1 - b1) * g
        v2 = b2 * v + (1 - b2) * g * g
        m_hat = m2 / _bsel(bc1, m2)
        if config.amsgrad:
            vh2 = jnp.maximum(vh, v2)
            denom = jnp.sqrt(vh2 / _bsel(bc2, vh2)) + config.eps
        else:
            vh2 = vh
            denom = jnp.sqrt(v2 / _bsel(bc2, v2)) + config.eps
        upd = m_hat / denom
        if not config.coupled_weight_decay and config.weight_decay:
            upd = upd + config.weight_decay * pf
        delta = _bsel(lr_k, upd) * upd
        axes = tuple(range(1, delta.ndim))
        usq = jnp.sum(delta * delta, axis=axes) if with_norms else None
        psq = jnp.sum(pf * pf, axis=axes) if with_norms else None
        sel = _bsel(app, p)
        newp = jnp.where(sel, (pf - delta).astype(p.dtype), p)
        m2 = jnp.where(sel, m2, m)
        v2 = jnp.where(sel, v2, v)
        if config.amsgrad:
            vh2 = jnp.where(sel, vh2, vh)
        return newp, m2, v2, vh2, usq, psq

    leaves_p, treedef = jax.tree.flatten(params)
    leaves_g = treedef.flatten_up_to(grads)
    leaves_m = treedef.flatten_up_to(state["m"])
    leaves_v = treedef.flatten_up_to(state["v"])
    leaves_vh = (treedef.flatten_up_to(state["v_hat"])
                 if config.amsgrad else [None] * len(leaves_p))
    leaves_do = (treedef.flatten_up_to(mask) if mask is not None
                 else [True] * len(leaves_p))
    out = [leaf_update(p, g, m, v, vh if vh is not None else 0.0, do)
           for p, g, m, v, vh, do in zip(leaves_p, leaves_g, leaves_m,
                                         leaves_v, leaves_vh, leaves_do)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_state = {"step": jnp.where(app, step2, state["step"]),
                 "m": treedef.unflatten([o[1] for o in out]),
                 "v": treedef.unflatten([o[2] for o in out])}
    if config.amsgrad:
        new_state["v_hat"] = treedef.unflatten([o[3] for o in out])
    if with_norms:
        usq = [o[4] for o in out if o[4] is not None]
        psq = [o[5] for o in out if o[5] is not None]
        k = int(state["step"].shape[0])
        upd_norm = (jnp.sqrt(sum(usq)) if usq
                    else jnp.zeros((k,), jnp.float32))
        w_norm = (jnp.sqrt(sum(psq)) if psq
                  else jnp.zeros((k,), jnp.float32))
        return new_p, new_state, (upd_norm, w_norm)
    return new_p, new_state


def slot_norms(grads) -> jnp.ndarray:
    """Per-slot L2 norms [k] over a stacked [k, ...] grad tree — each
    slot's norm over ITS OWN adapter only, matching global_norm over the
    corresponding solo tree (the per-tenant clip must see exactly the
    norm the solo run would)."""
    sq = None
    for g in jax.tree.leaves(grads):
        g = g.astype(jnp.float32)
        s = jnp.sum(jnp.square(g), axis=tuple(range(1, g.ndim)))
        sq = s if sq is None else sq + s
    return jnp.sqrt(sq)


def clip_by_slot_norm(grads, max_norm: float):
    """Per-slot clip-by-global-norm over a stacked [k, ...] grad tree:
    returns (clipped_grads, pre_clip_norms [k]). Slot j's scale factor
    is exactly clip_by_global_norm's for its solo tree."""
    norms = slot_norms(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norms, 1e-12))
    return jax.tree.map(
        lambda g: (g * _bsel(scale, g)).astype(g.dtype), grads), norms


def global_norm(grads) -> jnp.ndarray:
    """Global L2 norm over a grad pytree (clip_and_get_grad_norm analog,
    gpt2_lora_finetune/main.cpp:490-516)."""
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in jax.tree.leaves(grads)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def clip_by_global_norm(grads, max_norm: float):
    """Returns (clipped_grads, pre_clip_norm)."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads), norm


# ----------------------------- state I/O ------------------------------------

def save_state(path: str, state: dict, config: AdamConfig,
               extra_metadata: Optional[Dict[str, str]] = None):
    """Serialize optimizer state + config to a safetensors blob
    (Adam::save analog, adam.cpp:103+). Device leaves come to host via
    one batched issue-then-wait (io/async_ckpt.snapshot) instead of a
    serialized per-leaf pull; the write itself is atomically published
    by save_safetensors (which also publishes the integrity manifest
    the verify-on-load paths check). `extra_metadata` rides in the
    safetensors header — the train CLIs stamp `loop_step` there: under
    `--skip_nonfinite` the Adam step counter lags the loop step by the
    skipped updates, so the sidecar's `step` tensor alone is the wrong
    resume point (cli/common.maybe_resume_opt_state prefers the
    metadata)."""
    from mobilefinetuner_tpu.io.async_ckpt import snapshot
    from mobilefinetuner_tpu.io.safetensors_io import save_safetensors
    state = snapshot(state)  # no-op on trees already on host
    flat = {}
    leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    for path_keys, leaf in leaves:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                       for k in path_keys)
        flat[key] = np.asarray(leaf)
    md = {f"adam_{f.name}": str(getattr(config, f.name))
          for f in dataclasses.fields(config)}
    if extra_metadata:
        md.update({str(k): str(v) for k, v in extra_metadata.items()})
    save_safetensors(path, flat, metadata=md)


def load_state(path: str, state_template: dict,
               to_host: bool = False,
               verify: bool = False) -> Tuple[dict, AdamConfig]:
    """Restore optimizer state into the template's structure. The
    template only contributes tree structure + leaf shape/dtype, so
    `jax.eval_shape` ShapeDtypeStructs work — no device allocation
    needed to describe the target. to_host=True keeps the restored
    leaves as HOST numpy (the elastic-resume path: the caller places
    them onto THIS run's mesh afterwards — `cli/common.place_opt_state`
    — so a sidecar saved at mesh (1,N) re-shards at any (1,M) instead
    of landing committed to the default device). verify=True checks the
    integrity manifest first (CheckpointIntegrityError on mismatch)."""
    from mobilefinetuner_tpu.io.safetensors_io import (SafeTensorsReader,
                                                       verify_file)
    if verify:
        verify_file(path)
    reader = SafeTensorsReader(path)
    raw = reader.load_all()
    leaves, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    out = []
    for path_keys, leaf in leaves:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                       for k in path_keys)
        if to_host:
            arr = np.asarray(raw[key]).astype(leaf.dtype).reshape(leaf.shape)
        else:
            arr = jnp.asarray(raw[key]).astype(leaf.dtype).reshape(leaf.shape)
        out.append(arr)
    md = reader.metadata
    cfg = AdamConfig(
        lr=float(md["adam_lr"]), beta1=float(md["adam_beta1"]),
        beta2=float(md["adam_beta2"]), eps=float(md["adam_eps"]),
        weight_decay=float(md["adam_weight_decay"]),
        coupled_weight_decay=md["adam_coupled_weight_decay"] == "True",
        amsgrad=md["adam_amsgrad"] == "True")
    return jax.tree.unflatten(jax.tree.structure(state_template), out), cfg
