"""LR schedules: warmup + cosine-to-floor or linear decay.

Reference semantics: gpt2_lora_finetune/main.cpp:469-488 (linear warmup over
warmup_ratio of total steps, then cosine decay to 10% of peak) and
gemma_trainer.cpp:64-85 (warmup + linear or cosine). Pure functions of the
step index so they trace into the jitted train step without recompilation.
"""

from __future__ import annotations

import jax.numpy as jnp


def lr_schedule(step, total_steps: int, base_lr: float,
                warmup_ratio: float = 0.03, kind: str = "cosine",
                min_lr_ratio: float = 0.1):
    """LR at `step` (0-based, traced or static).

    kind: "cosine" (decay to min_lr_ratio*base_lr, main.cpp:469-488),
    "linear" (decay to min_lr_ratio*base_lr), "constant".
    """
    step = jnp.asarray(step, jnp.float32)
    total = jnp.asarray(max(total_steps, 1), jnp.float32)
    return _schedule_value(step, total, base_lr, warmup_ratio, kind,
                           min_lr_ratio)


def multi_lr_schedule(step_k, total_k, base_lr_k,
                      warmup_ratio_k, kind: str = "cosine",
                      min_lr_ratio: float = 0.1):
    """Vectorized schedule for the multi-tenant engine: per-slot [k]
    arrays of (tenant-local step, step budget, peak LR, warmup ratio)
    — all TRACED data, so tenants with different budgets/LRs share one
    compiled step — through the SAME formula as lr_schedule (the
    k-adapter-vs-solo parity oracle depends on the identity). `kind`
    and `min_lr_ratio` stay static/engine-wide: a per-slot schedule
    SHAPE would be a traced branch, which is exactly what the
    zero-retrace contract forbids."""
    step = jnp.asarray(step_k, jnp.float32)
    total = jnp.maximum(jnp.asarray(total_k, jnp.float32), 1.0)
    base = jnp.asarray(base_lr_k, jnp.float32)
    wr = jnp.asarray(warmup_ratio_k, jnp.float32)
    return _schedule_value(step, total, base, wr, kind, min_lr_ratio)


def _schedule_value(step, total, base_lr, warmup_ratio, kind,
                    min_lr_ratio):
    warmup = jnp.maximum(jnp.floor(total * warmup_ratio), 0.0)
    warm_lr = base_lr * (step + 1.0) / jnp.maximum(warmup, 1.0)
    progress = jnp.clip((step - warmup) / jnp.maximum(total - warmup, 1.0),
                        0.0, 1.0)
    floor = base_lr * min_lr_ratio
    if kind == "cosine":
        decayed = floor + (base_lr - floor) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * progress))
    elif kind == "linear":
        decayed = base_lr + (floor - base_lr) * progress
    elif kind == "constant":
        decayed = jnp.asarray(base_lr, jnp.float32)
    else:
        raise ValueError(f"unknown schedule kind {kind!r}")
    return jnp.where(step < warmup, warm_lr, decayed)
