"""Optimizer-state + master-weight host offload: full fine-tuning of models
whose f32 master weights + Adam state exceed HBM (Gemma-1B on one 16 GB
v5e chip: 1.0B params -> 12 GB of master+m+v alone, before grads and
activations).

This extends the budget philosophy of the frozen-parameter offloader
(parallel/offload.py; reference: opt_ops/sharding/parameter_sharder.h:37-41)
to the one tree the reference never sharded: its Adam state always stays
RAM-resident (adam.cpp per-param state), because the reference never
trains models whose optimizer state outgrows memory. Full-FT trainable
set per gpt2_full_finetune/main.cpp:318-322.

Design (single chip):
  - The DEVICE holds only the compute-dtype (bf16) copy of the weights.
  - Master f32 weights and Adam m/v live in PINNED HOST RAM in "streamed
    layout": each offloaded leaf reshaped to [C, ...] so chunk c is a
    contiguous leading-axis slice ([L, ...] block stacks keep C = L; big
    2-D tables like the 262k embedding are row-chunked).
  - The train step stays ONE XLA program: scan-accumulated grads ->
    global-norm clip -> LR schedule -> per-leaf scanned Adam update whose
    carry IS the host-resident state. Each iteration dynamic-slices
    master/m/v chunk c host->HBM, runs the elementwise Adam math on chip,
    dynamic-update-slices the new f32 state back into the host carry, and
    emits the refreshed bf16 compute chunk as a scan output. XLA pipelines
    the per-iteration DMAs (measured ~6.9 GiB/s effective on v5e for the
    6x round trip; a 1B-param model moves 24 GB/step -> the optimizer
    scan, not the matmuls, bounds step time — that is the price of full
    FT in 16 GB).
  - Small leaves (norms) keep resident f32 master + m/v on device and go
    through the plain adam_update path.

Numerics vs the resident trainer (train/trainer.py): per-micro-batch
gradients are taken w.r.t. the bf16 compute copy (bf16 grads, f32
accumulation across micro-batches); master math, moments, and bias
correction are f32 on chip, matching adam.py's leaf_update (amsgrad is
not supported — make_offload_train_step rejects it). This matches
standard bf16 mixed-precision training; the resident path differentiates
w.r.t. f32 leaves instead.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import SingleDeviceSharding

from mobilefinetuner_tpu.optim.adam import (AdamConfig, clip_by_global_norm,
                                            global_norm)
from mobilefinetuner_tpu.optim.schedule import lr_schedule


@dataclasses.dataclass(frozen=True)
class OptOffloadSpec:
    """What streams: leaves >= min_stream_bytes with a chunkable leading
    structure. chunk_bytes targets the per-iteration slice size for
    row-chunked 2-D leaves (bigger slices amortize DMA latency; the host
    link is latency-bound ~2 GiB/s single-stream).

    The 16-BIT HOST TIER (round-5 verdict item 3; the analog of the
    reference's fp16 slow-tier quantization, parameter_sharder.cpp:215-232,
    applied to the tree the reference never sharded):
      state_dtype: storage dtype for streamed Adam m AND v ("float32"
        default, "bfloat16"/"float16" halve their stream). 16-bit v is
        stored as sqrt(v): the square root halves the exponent range
        (f16-safe down to grad ~2e-4 instead of underflowing at grad^2)
        and puts the 16-bit relative error directly on the sqrt(v)
        denominator the update actually uses.
      master_dtype: storage dtype for streamed f32 master weights
        ("float32" default; "bfloat16" halves the master stream and
        quantizes the update write-back with STOCHASTIC ROUNDING so the
        tiny lr*update increments survive in expectation instead of
        vanishing below the bf16 ulp).
    Resident (small) leaves always stay f32. Both knobs change stored
    bits, so a sidecar written with one spec must be resumed with the
    same spec (resume_opt_sidecar checks stored-vs-template dtypes and
    fails loudly on mismatch)."""
    min_stream_bytes: int = 1 << 22          # 4 MB
    chunk_bytes: int = 96 << 20              # ~96 MB target slice
    state_dtype: str = "float32"
    master_dtype: str = "float32"


def plan_opt_offload(params, spec: OptOffloadSpec = OptOffloadSpec()):
    """Pytree of int matching `params`: 0 = resident, C > 0 = stream in C
    leading-axis chunks. >=3-D leaves ([L, ...] stacks) use C = L; 2-D
    leaves row-chunk to ~chunk_bytes with C dividing the row count."""
    def leaf_plan(x):
        nbytes = int(np.prod(np.shape(x))) * 4  # f32 master/m/v
        if nbytes < spec.min_stream_bytes or np.ndim(x) < 2:
            return 0
        if np.ndim(x) >= 3:
            return int(np.shape(x)[0])
        rows = int(np.shape(x)[0])
        row_bytes = nbytes // rows
        target_rows = max(1, spec.chunk_bytes // max(row_bytes, 1))
        # smallest chunk count >= the ideal that divides the row count
        # (chunks must tile evenly for the [C, rows/C, ...] view). The
        # search is BOUNDED: an awkward row count (e.g. prime) must not
        # explode into a per-row scan of kilobyte DMAs on the
        # latency-bound host link — past 4x the ideal, fall back to the
        # largest divisor UNDER the ideal (possibly 1 = one whole-leaf
        # chunk, a transient-HBM cost instead of a pathological loop).
        ideal = max(1, -(-rows // target_rows))
        for c in range(ideal, min(4 * ideal, rows) + 1):
            if rows % c == 0:
                return c
        return max(c for c in range(1, ideal + 1) if rows % c == 0)
    return jax.tree.map(leaf_plan, params)


def _streamed_shape(x, c: int):
    s = np.shape(x)
    if np.ndim(x) >= 3:
        return s  # [L, ...] stacks already have the chunk axis
    return (c, s[0] // c) + tuple(s[1:])


def _shardings(device=None):
    """(device_sharding, host_sharding). On the CPU backend the "host"
    tier is device memory too: CPU jit drops host memory kinds on
    outputs, which breaks AOT re-calls (compiled-for-host inputs vs
    device-kind state coming back) — and host==device there anyway, so
    the fallback changes placement, not semantics. The memory-kind NAMES
    come from parallel/offload.host_kind/device_kind (the one copy of
    the jax kind-name skew). Tests exercise the full numerics on CPU;
    the actual pinned-host tier runs on TPU."""
    from mobilefinetuner_tpu.parallel.offload import device_kind, host_kind
    device = device or jax.devices()[0]
    return (SingleDeviceSharding(device, memory_kind=device_kind()),
            SingleDeviceSharding(device, memory_kind=host_kind()))


def init_opt_offload(params, plan, compute_dtype=jnp.bfloat16, device=None,
                     spec: OptOffloadSpec = OptOffloadSpec()):
    """Place a full-FT problem: returns (compute_params, opt_state).

    compute_params: compute-dtype copy on device, ORIGINAL shapes — this
    is the tree the loss differentiates. opt_state: {"step", "master",
    "m", "v"} with streamed leaves as [C, ...] pinned-host arrays in the
    spec's storage dtypes (v sqrt-encoded when 16-bit — see
    OptOffloadSpec) and resident leaves as device f32."""
    dev_sh, host_sh = _shardings(device)
    m_dt = jnp.dtype(spec.master_dtype)
    s_dt = jnp.dtype(spec.state_dtype)

    def place_master(x, c):
        # host-numpy staging: jnp.asarray would allocate on DEVICE first
        # and round-trip device->host — a transient HBM spike the size of
        # the leaf (1.2 GB for the 262k embed), on top of the still-live
        # source params, defeating the offload
        x = np.asarray(x, np.float32)
        if c == 0:
            return jax.device_put(jnp.asarray(x), dev_sh)
        arr = x.reshape(_streamed_shape(x, c))
        if m_dt != jnp.float32:
            # plain round-to-nearest at INIT (the checkpoint's own
            # precision); stochastic rounding guards the per-step
            # update write-back, not the initial cast
            arr = arr.astype(m_dt)
        return jax.device_put(arr, host_sh)

    def place_zeros(x, c):
        z = np.zeros(_streamed_shape(x, c) if c else np.shape(x),
                     np.float32 if not c else s_dt)
        return jax.device_put(z, host_sh if c else dev_sh)

    compute = jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x).astype(compute_dtype),
                                 dev_sh), params)
    opt_state = {
        "step": jnp.zeros((), jnp.int32),
        "master": jax.tree.map(place_master, params, plan),
        "m": jax.tree.map(place_zeros, params, plan),
        "v": jax.tree.map(place_zeros, params, plan),
    }
    return compute, opt_state


def master_to_params(opt_state, plan, shape_tree):
    """Gather the f32 master back to host numpy in ORIGINAL shapes (for
    save_gemma3 / checkpoint writers). One batched issue-then-wait pull
    of the whole master tree (io/async_ckpt.snapshot) — the previous
    per-leaf device_get serialized a blocking transfer per tensor."""
    from mobilefinetuner_tpu.io.async_ckpt import snapshot
    master = snapshot(opt_state["master"])

    def back(x, c, ref):
        return np.asarray(x, np.float32).reshape(np.shape(ref))
    return jax.tree.map(back, master, plan, shape_tree)


def save_opt_sidecar(path: str, opt_state, adam_cfg):
    """Persist {step, m, v} next to the saved master model (the master IS
    the model file — master_to_params + the family's checkpoint writer)."""
    from mobilefinetuner_tpu.optim.adam import save_state
    sub = {"step": opt_state["step"], "m": opt_state["m"],
           "v": opt_state["v"]}
    save_state(path, jax.device_get(sub), adam_cfg)


def resume_opt_sidecar(path: str, opt_state):
    """Load a sidecar written by save_opt_sidecar into a freshly
    init_opt_offload'ed state (master comes from the resumed model file),
    re-placing every leaf onto its template sharding (host tiers).

    The STORED dtypes must match the template's: the streamed shapes are
    spec-independent and load_state casts silently, so without this check
    a sidecar written under one OptOffloadSpec and resumed under another
    would reinterpret raw-f32 v as sqrt-encoded bf16 (or vice versa) and
    silently corrupt every Adam denominator. Resume with the same
    --opt_offload_{state,master}_dtype flags the run was saved with."""
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    from mobilefinetuner_tpu.optim.adam import load_state
    sub = {"step": opt_state["step"], "m": opt_state["m"],
           "v": opt_state["v"]}
    reader = SafeTensorsReader(path)
    st_dtypes = {"F32": jnp.float32, "BF16": jnp.bfloat16,
                 "F16": jnp.float16, "I32": jnp.int32}
    for path_keys, leaf in jax.tree_util.tree_flatten_with_path(sub)[0]:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                       for k in path_keys)
        try:
            stored = reader.shape_dtype(key)[1]
        except KeyError:
            raise ValueError(
                f"opt sidecar {path} is missing tensor {key!r}: the "
                f"sidecar was written under a different offload "
                f"layout/plan than this run's template (it holds "
                f"{len(reader.keys())} tensors) — resume with the "
                f"flags/model the sidecar was saved with, or start "
                f"fresh optimizer state") from None
        if st_dtypes.get(stored, None) != leaf.dtype:
            raise ValueError(
                f"opt sidecar dtype mismatch at {key}: stored {stored}, "
                f"expected {leaf.dtype} — resume with the same "
                f"--opt_offload_state_dtype/--opt_offload_master_dtype "
                f"the sidecar was saved with")
    loaded, _ = load_state(path, sub)
    placed = jax.tree.map(lambda x, t: jax.device_put(x, t.sharding),
                          loaded, sub)
    return dict(opt_state, **placed)


def _lowbias32(x):
    """lowbias32 uint32 mix (the same constants as _sr_bfloat16's
    per-element scramble and the flash kernel's dropout hash)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _sr_salt(step_no, leaf_idx: int):
    """uint32 SR salt base for (step, leaf); stream_leaf adds the chunk
    index. The step counter is MIXED through lowbias32 rather than
    multiplied by 2**20: the old int32 product wrapped with period 4096
    steps (2**32 / 2**20), silently repeating every element's rounding
    draw from step s at step s + 4096. Hashing decorrelates all 32 bits
    of the step, so no two steps in an int32 counter's range share a
    salt; 1009 (prime) keeps the per-leaf offsets disjoint from the
    chunk increments for any realistic chunk count."""
    return _lowbias32(step_no.astype(jnp.uint32)) \
        + jnp.uint32(leaf_idx * 1009)


def _sr_bfloat16(x, salt):
    """Stochastic-rounding f32 -> bf16: add a counter-based uniform u16
    to the low mantissa bits, then truncate. Each element's random draw
    is a pure function of (its index, salt) — salt folds in (step, leaf,
    chunk), so the quantization is deterministic given the step counter
    and interrupted == uninterrupted training stays bit-for-bit (the
    resume contract, tests/test_opt_offload.py). Same lowbias32-style
    integer mix as the flash kernel's dropout (ops/flash_attention.py
    _keep_mask), for the same reason: no [shape]-sized key tensors, and
    hardware/interpret agree exactly."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    idx = jnp.arange(x.size, dtype=jnp.uint32).reshape(x.shape)
    z = _lowbias32(idx * jnp.uint32(0x9E3779B9) ^ salt.astype(jnp.uint32))
    q = bits + (z & jnp.uint32(0xFFFF))
    out = jax.lax.bitcast_convert_type(
        (q >> 16).astype(jnp.uint16), jnp.bfloat16)
    # non-finite inputs would carry into the exponent; master weights are
    # finite, but keep the guard exact rather than assumed
    return jnp.where(jnp.isfinite(x), out, x.astype(jnp.bfloat16))


def make_offload_train_step(loss_fn, train_cfg, plan,
                            compute_dtype=jnp.bfloat16, device=None,
                            donate: bool = True, mask=None,
                            spec: OptOffloadSpec = OptOffloadSpec()):
    """Offloaded analog of trainer.make_train_step — same contract:
    step_fn(compute_params, frozen, opt_state, batch, step) ->
    (compute_params, opt_state, metrics). loss_fn(compute_params, frozen,
    micro_batch) -> (sum_loss, weight). Full-FT only: a trainable-leaf
    mask is rejected loudly (the streamed update has no frozen-leaf
    branch — silently updating masked leaves would diverge from the
    resident trainer). spec's state_dtype/master_dtype select the 16-bit
    host tier (OptOffloadSpec) and must match init_opt_offload's."""
    from mobilefinetuner_tpu.train.trainer import reshape_for_accum
    if mask is not None:
        raise NotImplementedError(
            "make_offload_train_step supports full fine-tuning only "
            "(mask=None); masked/frozen leaves are not streamed")
    accum = train_cfg.grad_accum_steps
    cfg: AdamConfig = train_cfg.adam()
    if cfg.amsgrad:
        # adam_math below has no v_hat stream; silently running plain
        # Adam would diverge from the resident trainer's algorithm
        raise NotImplementedError(
            "amsgrad is not supported with optimizer-state offload")
    dev_sh, host_sh = _shardings(device)
    b1, b2 = cfg.beta1, cfg.beta2
    m_dt = jnp.dtype(spec.master_dtype)
    s_dt = jnp.dtype(spec.state_dtype)
    if m_dt not in (jnp.float32, jnp.bfloat16):
        raise ValueError(
            f"master_dtype must be float32 or bfloat16 (stochastic "
            f"rounding targets bf16), got {spec.master_dtype}")

    def adam_math(w, g, m, v, lr, bc1, bc2):
        g = g.astype(jnp.float32)
        if cfg.coupled_weight_decay and cfg.weight_decay:
            g = g + cfg.weight_decay * w
        m2 = b1 * m + (1 - b1) * g
        v2 = b2 * v + (1 - b2) * g * g
        upd = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + cfg.eps)
        if not cfg.coupled_weight_decay and cfg.weight_decay:
            upd = upd + cfg.weight_decay * w
        return w - lr * upd, m2, v2

    def stream_leaf(g, w_h, m_h, v_h, lr, bc1, bc2, salt0):
        """Per-leaf scanned update with the host state as the carry.
        Chunks move host->HBM in their STORAGE dtypes (the whole point of
        the 16-bit tier: fewer bytes on the latency-bound host link) and
        dequantize on-chip; the refreshed state quantizes on-chip before
        the write-back."""
        C = w_h.shape[0]
        g_st = g.reshape(w_h.shape)
        sqrt_v = s_dt != jnp.float32      # v stored as sqrt(v) in 16-bit

        def body(carry, i):
            w_c, m_c, v_c = carry
            sl = lambda t: jax.lax.dynamic_index_in_dim(t, i, 0,
                                                        keepdims=False)
            w = jax.device_put(sl(w_c), dev_sh).astype(jnp.float32)
            m = jax.device_put(sl(m_c), dev_sh).astype(jnp.float32)
            v = jax.device_put(sl(v_c), dev_sh).astype(jnp.float32)
            if sqrt_v:
                v = v * v
            w2, m2, v2 = adam_math(w, sl(g_st), m, v, lr, bc1, bc2)
            if m_dt == jnp.bfloat16:
                w2 = _sr_bfloat16(w2, salt0 + i.astype(jnp.uint32))
            v_store = jnp.sqrt(v2) if sqrt_v else v2
            up = lambda t, x: jax.lax.dynamic_update_index_in_dim(
                t, jax.device_put(x.astype(t.dtype), host_sh), i, 0)
            # the emitted compute copy derives from the QUANTIZED master
            # (w2 above is already bf16 when master_dtype is), so a
            # resumed run — whose compute copy is re-derived from the
            # stored master — is bit-identical to the uninterrupted one
            return ((up(w_c, w2), up(m_c, m2), up(v_c, v_store)),
                    w2.astype(compute_dtype))

        (w_h, m_h, v_h), bf = jax.lax.scan(body, (w_h, m_h, v_h),
                                           jnp.arange(C))
        return w_h, m_h, v_h, bf.reshape(g.shape)

    def step_fn(compute, frozen, opt_state, batch, step):
        micro = reshape_for_accum(batch, accum)
        vg = jax.value_and_grad(
            lambda tr, mb: loss_fn(tr, frozen, mb), has_aux=True)

        def body(carry, mb):
            g_acc, loss_acc, w_acc = carry
            (s, w), g = vg(compute, mb)
            g_acc = jax.tree.map(
                lambda a, gi: a + gi.astype(jnp.float32), g_acc, g)
            return (g_acc, loss_acc + s, w_acc + w.astype(jnp.float32)), \
                None

        g0 = jax.tree.map(lambda t: jnp.zeros(t.shape, jnp.float32),
                          compute)
        (g_sum, loss_sum, w_sum), _ = jax.lax.scan(
            body, (g0, jnp.float32(0.0), jnp.float32(0.0)), micro)
        inv = 1.0 / jnp.maximum(w_sum, 1.0)
        grads = jax.tree.map(lambda g: g * inv, g_sum)
        loss = loss_sum * inv
        if train_cfg.clip_grad_norm and train_cfg.clip_grad_norm > 0:
            grads, norm = clip_by_global_norm(grads,
                                              train_cfg.clip_grad_norm)
        else:
            norm = global_norm(grads)
        lr = lr_schedule(step, train_cfg.total_steps, train_cfg.lr,
                         train_cfg.warmup_ratio, train_cfg.schedule,
                         train_cfg.min_lr_ratio)
        step_no = opt_state["step"] + 1
        bc1 = 1.0 - b1 ** step_no.astype(jnp.float32)
        bc2 = 1.0 - b2 ** step_no.astype(jnp.float32)

        leaves_g, treedef = jax.tree.flatten(grads)
        leaves_w = treedef.flatten_up_to(opt_state["master"])
        leaves_m = treedef.flatten_up_to(opt_state["m"])
        leaves_v = treedef.flatten_up_to(opt_state["v"])
        leaves_c = treedef.flatten_up_to(plan)
        out_w, out_m, out_v, out_bf = [], [], [], []
        for li, (g, w, m, v, c) in enumerate(zip(leaves_g, leaves_w,
                                                 leaves_m, leaves_v,
                                                 leaves_c)):
            if c:
                # SR salt: unique per (step, leaf, chunk) — chunk is
                # added inside stream_leaf; uint32 throughout, step mixed
                # via lowbias32 (_sr_salt has the period-4096 story)
                salt0 = _sr_salt(step_no, li)
                w2, m2, v2, bf = stream_leaf(g, w, m, v, lr, bc1, bc2,
                                             salt0)
            else:
                w2, m2, v2 = adam_math(w, g, m, v, lr, bc1, bc2)
                bf = w2.astype(compute_dtype)
            out_w.append(w2)
            out_m.append(m2)
            out_v.append(v2)
            out_bf.append(bf)
        new_state = {"step": step_no,
                     "master": treedef.unflatten(out_w),
                     "m": treedef.unflatten(out_m),
                     "v": treedef.unflatten(out_v)}
        metrics = {"loss": loss, "grad_norm": norm, "lr": lr}
        return treedef.unflatten(out_bf), new_state, metrics

    # donating pinned-host buffers is TPU-only (the CPU PJRT backend
    # aborts on donated host-kind args — tests run with donate off)
    donate_argnums = (0, 2) if donate and jax.default_backend() != "cpu" \
        else ()
    return jax.jit(step_fn, donate_argnums=donate_argnums)
