"""Shared model-family dispatch for the eval/generate CLIs.

One place that knows how to go from --pretrained_dir to (config, params,
tokenizer, merge fn, model module) for both families — eval_ppl, eval_mmlu,
and generate all consume this instead of keeping drifting copies of the
same load/sniff/merge block. The reference has no analog (each of its
binaries is single-family by construction); family auto-detection reads
the HF config.json (model_type / nested text_config).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Optional, Tuple

import jax

from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.lora import peft_io

log = get_logger()


def detect_family(model_dir: str) -> str:
    """gpt2 vs gemma from config.json (model_type or nested text_config)."""
    with open(os.path.join(model_dir, "config.json")) as f:
        raw = json.load(f)
    mt = str(raw.get("model_type", "")).lower()
    if "gemma" in mt or "text_config" in raw:
        return "gemma"
    return "gpt2"


@dataclasses.dataclass
class FamilyBundle:
    family: str              # "gpt2" | "gemma"
    config: Any
    params: Any              # host numpy tree (device_put is the caller's
                             # decision — see eval_ppl's commit-once note)
    tok: Any
    model: Any               # models.gpt2 or models.gemma3 module
    merge_fn: Callable       # merge_gpt2 / merge_gemma3
    head_key: str            # tied lm_head weight key: "wte" / "embed"
    max_len: int             # n_positions / max_position_embeddings


def load_family(pretrained_dir: str, family: str = "auto") -> FamilyBundle:
    if family == "auto":
        try:
            family = detect_family(pretrained_dir)
        except OSError:
            raise SystemExit(
                f"no readable config.json under {pretrained_dir}")
    log.info(f"model family: {family}")
    if family == "gemma":
        from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
        from mobilefinetuner_tpu.io.checkpoints import load_gemma3
        from mobilefinetuner_tpu.lora.lora import merge_gemma3
        from mobilefinetuner_tpu.models import gemma3
        config, params = load_gemma3(pretrained_dir)
        return FamilyBundle(
            family, config, params,
            GemmaTokenizer.from_pretrained(pretrained_dir),
            gemma3, merge_gemma3, "embed",
            config.max_position_embeddings)
    from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
    from mobilefinetuner_tpu.io.checkpoints import load_gpt2
    from mobilefinetuner_tpu.lora.lora import merge_gpt2
    from mobilefinetuner_tpu.models import gpt2
    config, params = load_gpt2(pretrained_dir)
    return FamilyBundle(
        family, config, params,
        GPT2BPETokenizer.from_pretrained(pretrained_dir),
        gpt2, merge_gpt2, "wte", config.n_positions)


def apply_adapter(bundle: FamilyBundle, lora_path: str,
                  lora_merge: bool) -> Optional[Any]:
    """Load an adapter; merged -> fold into bundle.params and return None,
    dynamic -> return the LoRA tree for the model's lora= argument."""
    if not lora_path:
        return None
    lora, spec = peft_io.load_adapter(lora_path)
    log.info(f"adapter: r={spec.rank} alpha={spec.alpha} "
             f"targets={spec.targets} "
             f"({'merged' if lora_merge else 'dynamic'})")
    if lora_merge:
        bundle.params = bundle.merge_fn(bundle.params, lora)
        return None
    return lora
