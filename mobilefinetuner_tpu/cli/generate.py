"""Text-generation CLI: KV-cached autoregressive sampling for GPT-2 and
Gemma-3, with optional LoRA adapters (merged or dynamic).

A capability the reference framework ships only as excluded legacy code
(reference: legacy/transformer/kv_cache.cpp + autoregressive_ops,
SURVEY.md §2.10 — "the active framework is training/eval only, no sampling
loop"). Here it is a first-class surface over models/generate.py: one
compiled program per (batch, prompt-length-bucket, max_new_tokens).

Usage:
  python -m mobilefinetuner_tpu.cli.generate \
      --pretrained_dir /path/gpt2 --prompt "The meaning of life is" \
      [--prompt ...] [--lora_path adapter.safetensors] \
      [--max_new_tokens 64] [--greedy | --temperature 0.8 --top_k 50 \
       --top_p 0.95] [--seed 0] [--dtype bfloat16] [--json]

Model family is auto-detected from config.json (model_type / presence of
Gemma fields); --model forces it.
"""

from __future__ import annotations

import argparse
import json as json_mod
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.models.generate import (SampleConfig, gemma3_generate,
                                                 gpt2_generate, left_pad)

log = get_logger()


from mobilefinetuner_tpu.cli.family import apply_adapter, load_family


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "generate", description="KV-cached sampling (GPT-2 / Gemma-3)")
    p.add_argument("--pretrained_dir", required=True)
    p.add_argument("--model", choices=["auto", "gpt2", "gemma"],
                   default="auto")
    p.add_argument("--prompt", action="append", default=[],
                   help="repeatable; one generation per prompt")
    p.add_argument("--prompt_file", default="",
                   help="one prompt per line (adds to --prompt)")
    p.add_argument("--lora_path", default="",
                   help="adapter safetensors; merged into the base weights "
                        "by default. Repeat-free multi-adapter form: a "
                        "comma list serves SEVERAL adapters in one batch "
                        "(implies --lora_dynamic; route prompts with "
                        "--adapter_ids)")
    p.add_argument("--adapter_ids", default="",
                   help="comma list, one 0-based adapter index per "
                        "prompt (default: prompt i -> adapter "
                        "i %% n_adapters)")
    p.add_argument("--lora_dynamic", action="store_true",
                   help="apply the adapter dynamically at every site "
                        "instead of merging — no merged weight copy, so "
                        "many adapters can be served off one base")
    p.add_argument("--lora_impl", choices=["auto", "naive", "fused"],
                   default="auto",
                   help="dynamic-LoRA hot-path implementation "
                        "(models/lora_apply.py; parity-pinned — 'naive' "
                        "is the oracle, 'fused' the shape-aware + "
                        "Pallas-epilogue path, 'auto' resolves per "
                        "call site)")
    p.add_argument("--max_new_tokens", type=int, default=64)
    p.add_argument("--prefill_chunk", type=int, default=0,
                   help="Gemma long-prompt mode: prefill in W-token "
                        "windows against the growing KV cache (prefill "
                        "score memory O(W*P) instead of O(P^2) blocks); "
                        "0 = whole-prompt forward. GPT-2's 1024 learned "
                        "positions cap prompts before memory does, so "
                        "the flag is Gemma-only")
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=1.0)
    p.add_argument("--greedy", action="store_true")
    p.add_argument("--no_eos_stop", action="store_true",
                   help="keep sampling past the eos token")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32")
    p.add_argument("--json", action="store_true", dest="json_out",
                   help="emit one JSON object per prompt on stdout")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    prompts = list(args.prompt)
    if args.prompt_file:
        with open(args.prompt_file, encoding="utf-8") as f:
            prompts += [ln.rstrip("\n") for ln in f if ln.strip()]
    if not prompts:
        raise SystemExit("no prompts (--prompt / --prompt_file)")
    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" \
        else jnp.float32
    b = load_family(args.pretrained_dir, args.model)
    if args.prefill_chunk and b.family == "gpt2":
        raise SystemExit("--prefill_chunk is Gemma-only (GPT-2's learned "
                         "positions cap prompts at n_positions)")
    if b.family == "gpt2":
        gen = gpt2_generate
    else:
        import functools
        gen = functools.partial(
            gemma3_generate,
            prefill_chunk=args.prefill_chunk or None)
    tok, encode = b.tok, b.tok.encode  # Gemma: add_bos default (HF parity)
    lora_paths = [p for p in args.lora_path.split(",") if p]
    if len(lora_paths) > 1:
        # multi-adapter batch serving: stack the adapters and route each
        # prompt to its adapter (lora/lora.py stack_adapters semantics)
        from mobilefinetuner_tpu.lora import peft_io
        from mobilefinetuner_tpu.lora.lora import (assign_adapters,
                                                   stack_adapters)
        adapters = [peft_io.load_adapter(p)[0] for p in lora_paths]
        if args.adapter_ids:
            try:
                ids = [int(x) for x in args.adapter_ids.split(",") if x]
            except ValueError:
                raise SystemExit(
                    f"--adapter_ids must be a comma list of integers, "
                    f"got {args.adapter_ids!r}")
            if len(ids) != len(prompts):
                raise SystemExit(
                    f"--adapter_ids has {len(ids)} entries for "
                    f"{len(prompts)} prompts")
            bad = [i for i in ids if not 0 <= i < len(adapters)]
            if bad:
                raise SystemExit(f"adapter ids out of range: {bad}")
        else:
            ids = [i % len(adapters) for i in range(len(prompts))]
        lora = assign_adapters(stack_adapters(adapters), ids)
        log.info(f"multi-adapter serving: {len(adapters)} adapters, "
                 f"prompt routing {ids}")
    else:
        if args.adapter_ids:
            raise SystemExit(
                "--adapter_ids requires at least two --lora_path entries "
                "(comma list) to route between")
        lora = apply_adapter(b, args.lora_path,
                             lora_merge=not args.lora_dynamic)
    config, params = b.config, b.params

    encoded = [encode(p) for p in prompts]
    empty = [p for p, e in zip(prompts, encoded) if not e]
    if empty:
        raise SystemExit(f"prompt(s) encode to zero tokens: {empty!r}")
    ids, mask = left_pad(encoded, tok.pad_id)
    cfg = SampleConfig(
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        greedy=args.greedy,
        eos_id=None if args.no_eos_stop else tok.eos_id,
        pad_id=tok.pad_id)
    rng = jax.random.PRNGKey(args.seed)

    t0 = time.time()
    # jit with params/rng as ARGUMENTS: closing over full-size weights
    # would embed them in the HLO as constants (oversized programs)
    gen_jit = jax.jit(lambda p, l, i, m, r: gen(
        config, p, i, m, cfg, r, compute_dtype=compute_dtype, lora=l,
        lora_impl=args.lora_impl))
    out = np.asarray(gen_jit(params, lora, jnp.asarray(ids),
                             jnp.asarray(mask), rng))
    dt = time.time() - t0
    n_tok = int(out.size)
    log.info(f"{n_tok} tokens in {dt:.2f}s "
             f"({n_tok / max(dt, 1e-9):.1f} tok/s incl. compile)")

    for i, prompt in enumerate(prompts):
        row = out[i].tolist()
        if cfg.eos_id is not None and cfg.eos_id in row:
            row = row[:row.index(cfg.eos_id) + 1]
        text = tok.decode(row)
        if args.json_out:
            print(json_mod.dumps({"prompt": prompt, "ids": row,
                                  "text": text}))
        else:
            print(f"=== {prompt!r}\n{text}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
