"""MMLU evaluation CLI.

TPU-native rebuild of the reference `eval_mmlu` binary
(reference: gpt2_lora_finetune/eval_mmlu.cpp + mmlu/mmlu_runner.{h,cpp}):
load GPT-2 (+ optional merged adapter), evaluate 4-choice accuracy with
k-shot prompts, report per-subject + macro/micro.

Variable-length prompts vs XLA's static shapes: prompts are right-padded to
power-of-two length buckets, so the whole eval compiles a handful of
programs instead of one per length. The last REAL token's logits are
selected by index (padding never shifts the prediction).

Usage:
  python -m mobilefinetuner_tpu.cli.eval_mmlu \
      --pretrained_dir /path/gpt2 --mmlu_root /path/mmlu --split test \
      [--fewshot 5] [--lora_path adapter.safetensors --lora_merge]
"""

from __future__ import annotations

import argparse
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.core.logging import JSONLWriter, get_logger
from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
from mobilefinetuner_tpu.eval import mmlu
from mobilefinetuner_tpu.io.checkpoints import load_gpt2
from mobilefinetuner_tpu.lora import peft_io
from mobilefinetuner_tpu.lora.lora import merge_gpt2
from mobilefinetuner_tpu.models import gpt2

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="eval_mmlu", description="MMLU 4-choice accuracy (TPU)")
    p.add_argument("--pretrained_dir", required=True)
    p.add_argument("--mmlu_root", required=True,
                   help="dir containing <split>/ with per-subject CSVs")
    p.add_argument("--split", default="test")
    p.add_argument("--fewshot", type=int, default=0)
    p.add_argument("--lora_path", default="")
    p.add_argument("--lora_merge", action="store_true")
    p.add_argument("--max_items", type=int, default=0,
                   help="cap items per subject (debug)")
    p.add_argument("--out", default="", help="JSON report path")
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32")
    return p


def make_logits_fn(config, params, lora, compute_dtype):
    """Bucketed-length last-token logits: np [1,S] -> np [V]."""

    @jax.jit
    def fwd(params, lora, ids, last_idx):
        logits = gpt2.forward(config, params, ids, lora=lora,
                              compute_dtype=compute_dtype)
        return logits[0, last_idx, :]

    def logits_fn(ids: np.ndarray) -> np.ndarray:
        S = ids.shape[1]
        if S > config.n_positions:  # keep the prompt tail
            ids = ids[:, -config.n_positions:]
            S = ids.shape[1]
        bucket = 1 << (S - 1).bit_length()
        bucket = min(max(bucket, 32), config.n_positions)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :S] = ids[0]
        return np.asarray(fwd(params, lora, padded, jnp.int32(S - 1)))

    return logits_fn


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    config, params = load_gpt2(args.pretrained_dir)

    lora = None
    if args.lora_path:
        lora, spec = peft_io.load_adapter(args.lora_path)
        log.info(f"adapter: r={spec.rank} "
                 f"({'merged' if args.lora_merge else 'dynamic'})")
        if args.lora_merge:
            params = merge_gpt2(params, lora)
            lora = None

    # Commit weights to device once; numpy-backed jit args would be
    # re-transferred per item (see eval_ppl.py).
    params = jax.device_put(params)
    if lora is not None:
        lora = jax.device_put(lora)

    tok = GPT2BPETokenizer.from_pretrained(args.pretrained_dir)
    by_subject = mmlu.load_split(args.mmlu_root, args.split)
    n_items = sum(len(v) for v in by_subject.values())
    log.info(f"MMLU {args.split}: {len(by_subject)} subjects, "
             f"{n_items} items, fewshot={args.fewshot}")

    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    logits_fn = make_logits_fn(config, params, lora, compute_dtype)

    done = [0]

    def progress(subject, i, n):
        done[0] += 1
        if done[0] % 50 == 0:
            log.info(f"{done[0]} items... ({subject} {i}/{n})")

    result = mmlu.evaluate(by_subject, logits_fn, tok.encode,
                           fewshot_k=args.fewshot, progress_fn=progress,
                           max_items_per_subject=args.max_items)

    report = {
        "split": args.split, "fewshot": args.fewshot,
        "macro_accuracy": round(result.macro, 4),
        "micro_accuracy": round(result.micro, 4),
        "total_items": result.total,
        "per_subject": {r.subject: {"accuracy": round(r.accuracy, 4),
                                    "correct": r.correct, "total": r.total}
                        for r in result.per_subject},
    }
    for r in result.per_subject:
        log.info(f"  {r.subject}: {r.accuracy:.3f} "
                 f"({r.correct}/{r.total})")
    log.info(f"macro={result.macro:.4f} micro={result.micro:.4f}")
    if args.out:
        JSONLWriter(args.out).write(report)
    print(json.dumps({"macro_accuracy": report["macro_accuracy"],
                      "micro_accuracy": report["micro_accuracy"],
                      "total_items": result.total}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
