"""MMLU evaluation CLI, GPT-2 and Gemma-3.

TPU-native rebuild of the reference `eval_mmlu` binary
(reference: gpt2_lora_finetune/eval_mmlu.cpp + mmlu/mmlu_runner.{h,cpp}):
load a model (+ optional adapter, merged or dynamic), evaluate 4-choice
accuracy with k-shot prompts, report per-subject + macro/micro. The
reference binary is GPT-2-only; like eval_ppl, this CLI auto-detects the
family from config.json so the Gemma track has the same eval story.

Variable-length prompts vs XLA's static shapes: prompts are right-padded to
power-of-two length buckets, so the whole eval compiles a handful of
programs instead of one per length. The last REAL token's logits are
selected by index (padding never shifts the prediction), and only that one
position is projected through the lm_head — materializing [1, S, V] logits
would cost ~1 MB/token on Gemma's 262k vocab for values that are discarded.

Usage:
  python -m mobilefinetuner_tpu.cli.eval_mmlu \
      --pretrained_dir /path/gpt2-or-gemma --mmlu_root /path/mmlu \
      --split test [--fewshot 5] [--lora_path adapter.safetensors \
      --lora_merge]
"""

from __future__ import annotations

import argparse
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.cli.family import apply_adapter, load_family
from mobilefinetuner_tpu.core.logging import JSONLWriter, get_logger
from mobilefinetuner_tpu.eval import mmlu

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="eval_mmlu", description="MMLU 4-choice accuracy (TPU)")
    p.add_argument("--pretrained_dir", required=True)
    p.add_argument("--family", choices=["auto", "gpt2", "gemma"],
                   default="auto")
    p.add_argument("--mmlu_root", required=True,
                   help="dir containing <split>/ with per-subject CSVs")
    p.add_argument("--split", default="test")
    p.add_argument("--fewshot", type=int, default=0)
    p.add_argument("--lora_path", default="")
    p.add_argument("--lora_merge", action="store_true")
    p.add_argument("--max_items", type=int, default=0,
                   help="cap items per subject (debug)")
    p.add_argument("--out", default="", help="JSON report path")
    p.add_argument("--synthetic", action="store_true",
                   help="stamp the report synthetic=true: the model "
                        "weights and/or MMLU data are synthetic (harness "
                        "proof, not a real evaluation) — keeps artifacts "
                        "self-describing")
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32")
    p.add_argument("--telemetry_out", default="",
                   help="JSONL run-telemetry stream (core/telemetry.py)")
    p.add_argument("--run_registry", default="",
                   help="append-only run registry stream (core/"
                        "run_registry.py): one crash-safe record per "
                        "eval run; default $MFT_RUN_REGISTRY, empty = "
                        "off")
    p.add_argument("--eval_batch", type=int, default=16,
                   help="items per forward (bucketed batching; the "
                        "reference runs per-item — on the MXU that "
                        "leaves the batch dimension idle)")
    from mobilefinetuner_tpu.cli.common import add_mem_flags
    add_mem_flags(p)
    return p


def setup_family(args):
    """(hidden_fn, head_key, compute_dtype, tok, letter_encode, max_len,
    params, lora): family dispatch via cli/family.py. hidden_fn(params,
    lora, ids) -> [1, S, E] final-norm hidden states; params[head_key] is
    the (tied) lm_head weight [V, E]; letter_encode is the BOS-free
    encoder for the A-D letter-id lookup (None = use tok.encode as-is)."""
    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" \
        else jnp.float32
    b = load_family(args.pretrained_dir, args.family)
    lora = apply_adapter(b, args.lora_path, args.lora_merge)
    config, model = b.config, b.model

    def hidden_fn(params, lora, ids):
        return model.hidden_states(config, params, ids, lora=lora,
                                   compute_dtype=compute_dtype)

    if b.family == "gemma":
        # letter-id lookup must not see the auto-BOS (eval/mmlu.py)
        tok = b.tok
        letter_encode = lambda s: tok.encode(s, add_bos=False)
        # prompts are bucketed; cap at 4096 (far above MMLU prompt sizes,
        # far below the 32k max — a 32k zero-pad bucket would be waste)
        max_len = min(b.max_len, 4096)
    else:
        letter_encode = None  # GPT-2 encode adds no sequence-start token
        max_len = b.max_len

    # Commit weights to device once; numpy-backed jit args would be
    # re-transferred per item (see eval_ppl.py).
    params = jax.device_put(b.params)
    lora = jax.device_put(lora) if lora is not None else None
    return (hidden_fn, b.head_key, compute_dtype, b.tok, letter_encode,
            max_len, params, lora)


def make_batched_logits_fn(hidden_fn, head_key, compute_dtype, params,
                           lora, worst_shape=None):
    """Batched bucketed last-REAL-token logits: (ids [B,S], last [B]) ->
    [B, V]. Only the selected positions go through the lm_head (a full
    [B, S, V] would cost ~1 MB/token on Gemma's 262k vocab).

    `worst_shape` (B, S): additionally AOT-compile that bucket and
    return the compiled executable (the round-16 admission preflight's
    subject). Calls matching it dispatch through the SAME executable —
    an AOT compile does not seed the jit cache, and without the routing
    the eval's most expensive bucket would compile twice."""

    @jax.jit
    def fwd(params, lora, ids, last_idx):
        h = hidden_fn(params, lora, ids)            # [B, S, E]
        head = params[head_key].astype(compute_dtype)
        rows = h[jnp.arange(h.shape[0]), last_idx]  # [B, E]
        logits = rows @ head.T                      # [B, V]
        # hidden_fn applies only the per-layer sites; an lm_head
        # adapter entry must land at this head projection too, or the
        # scored model differs from the trained one (DESIGN.md §17)
        if lora is not None and "lm_head" in lora.get("blocks", {}):
            from mobilefinetuner_tpu.models.lora_apply import maybe_lora
            logits = maybe_lora(logits, rows, lora["blocks"]["lm_head"])
        return logits

    compiled_worst = None
    if worst_shape is not None:
        compiled_worst = fwd.lower(
            params, lora,
            jax.ShapeDtypeStruct(worst_shape, jnp.int32),
            jax.ShapeDtypeStruct((worst_shape[0],), jnp.int32)).compile()

    def logits_fn(ids: np.ndarray, last: np.ndarray) -> np.ndarray:
        if compiled_worst is not None and ids.shape == worst_shape:
            return np.asarray(compiled_worst(
                params, lora, jnp.asarray(ids), jnp.asarray(last)))
        return np.asarray(fwd(params, lora, jnp.asarray(ids),
                              jnp.asarray(last)))

    return logits_fn, compiled_worst


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import time as _time
    from mobilefinetuner_tpu.core.telemetry import Telemetry, run_manifest
    # fleet-aware: each process writes its own host-stamped shard
    # (coordinator at the given path; merge with tools/fleet_report.py)
    tel = Telemetry.for_process(getattr(args, "telemetry_out", ""))
    tel.emit("run_start", **run_manifest(vars(args)))
    # run registry (core/run_registry.py): a crash between here and
    # finalize settles to "interrupted" on the next registry open
    from mobilefinetuner_tpu.core.run_registry import RunRegistry
    _reg = RunRegistry.from_args(args)
    run_rec = _reg.begin(
        "eval", "eval_mmlu", config=vars(args),
        platform=jax.devices()[0].platform,
        artifacts=[p for p in (tel.path, args.out) if p],
        telemetry=tel) if _reg else None
    t0 = _time.time()
    (hidden_fn, head_key, compute_dtype, tok, letter_encode, max_len,
     params, lora) = setup_family(args)

    by_subject = mmlu.load_split(args.mmlu_root, args.split)
    n_items = sum(len(v) for v in by_subject.values())
    log.info(f"MMLU {args.split}: {len(by_subject)} subjects, "
             f"{n_items} items, fewshot={args.fewshot}")

    # memory-admission preflight (DESIGN.md §21) on the REAL worst-case
    # bucket: the work list is materialized (prompts encoded once, the
    # same list the runner consumes) and the largest bucket it actually
    # lands in — not the theoretical max_len cap — is what gets
    # compiled and checked. The runner always pads batches to
    # eval_batch rows, so the preflight's compiled executable SERVES
    # every batch of that bucket (logits_fn routes matching shapes
    # through it): the check costs no extra compile. Same flags and
    # mem_check event as the train path; no ladder (eval has no
    # levers), so --on_oom_risk fail raises before any item is scored
    # and degrade/warn proceed with a warning.
    from mobilefinetuner_tpu.cli.common import preflight_eval_compile
    work, _totals = mmlu.materialize_work(
        by_subject, tok.encode, fewshot_k=args.fewshot,
        max_items_per_subject=args.max_items, max_len=max_len)
    worst_S = max((mmlu.bucket_for(len(w[4]), max_len=max_len)
                   for w in work), default=max_len)
    B = max(args.eval_batch, 1)
    logits_fn, compiled_worst = preflight_eval_compile(
        lambda: make_batched_logits_fn(
            hidden_fn, head_key, compute_dtype, params, lora,
            worst_shape=(B, worst_S)),
        args, tel, what=f"eval_mmlu worst-case bucket [{B}, {worst_S}]",
        compiled_of=lambda out: out[1])
    done = [0]

    def progress(subject, i, n):
        done[0] += 1
        if done[0] % 50 == 0:
            log.info(f"{done[0]} items... ({subject} {i}/{n})")

    result = mmlu.evaluate_batched(
        by_subject, logits_fn, tok.encode, fewshot_k=args.fewshot,
        progress_fn=progress, max_items_per_subject=args.max_items,
        letter_encode_fn=letter_encode,
        batch_size=B, max_len=max_len, work=work)

    from mobilefinetuner_tpu.eval.mmlu_categories import category_rollup
    categories = category_rollup(result)
    report = {
        "split": args.split, "fewshot": args.fewshot,
        # provenance: a reader must be able to tell a harness proof on
        # synthetic weights/data from a real evaluation (round-3 verdict:
        # the r03 report lacked this and could be mistaken for real)
        "synthetic": bool(args.synthetic),
        "model_dir": args.pretrained_dir,
        "mmlu_root": args.mmlu_root,
        "macro_accuracy": round(result.macro, 4),
        "micro_accuracy": round(result.micro, 4),
        "total_items": result.total,
        "categories": categories,
        "per_subject": {r.subject: {"accuracy": round(r.accuracy, 4),
                                    "correct": r.correct, "total": r.total}
                        for r in result.per_subject},
    }
    for r in result.per_subject:
        log.info(f"  {r.subject}: {r.accuracy:.3f} "
                 f"({r.correct}/{r.total})")
    for cat, c in categories.items():
        log.info(f"  [{cat}] macro={c['macro_accuracy']:.3f} "
                 f"micro={c['micro_accuracy']:.3f} "
                 f"({c['correct']}/{c['total']}, {c['subjects']} subjects)")
    log.info(f"macro={result.macro:.4f} micro={result.micro:.4f}")
    if args.out:
        JSONLWriter(args.out).write(report)
    # an accuracy eval is not NLL-shaped: loss/ppl are null (the schema
    # allows it) and the real result rides as accuracy fields, which
    # telemetry_report renders
    tel.emit("eval", step=result.total, loss=None, ppl=None,
             tokens=result.total, macro_accuracy=report["macro_accuracy"],
             micro_accuracy=report["micro_accuracy"])
    # finalize before run_end so the mirrored `run` end event lands in
    # the stream while run_end stays the stream's LAST event
    if run_rec is not None:
        run_rec.finalize("ok")
    tel.emit("run_end", steps=result.total,
             wall_s=round(_time.time() - t0, 3), exit="ok", goodput=None)
    tel.close()
    print(json.dumps({"macro_accuracy": report["macro_accuracy"],
                      "micro_accuracy": report["micro_accuracy"],
                      "total_items": result.total,
                      "categories": {c: v["macro_accuracy"]
                                     for c, v in categories.items()}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
