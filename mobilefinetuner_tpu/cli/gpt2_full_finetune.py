"""GPT-2 full fine-tuning CLI (all 124M+ params trainable).

TPU-native rebuild of the reference `gpt2_full_finetune` binary
(reference: gpt2_full_finetune/main.cpp — same skeleton as the LoRA CLI but
every parameter is trainable :318-322, the full model is saved as
safetensors :156-237, and resume reloads that file). Adam state is
FSDP-sharded with the params (the m/v trees inherit the param shardings),
which is exactly ZeRO's optimizer-state partitioning — the reference's
single-device sharder has no analog for this.

Usage (tiny smoke):
  python -m mobilefinetuner_tpu.cli.gpt2_full_finetune \
      --pretrained_dir /path/gpt2 --data_dir /path/wikitext-2 \
      --steps 10 --output_path out/model.safetensors
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

import jax

from mobilefinetuner_tpu.cli import common
from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset
from mobilefinetuner_tpu.io import async_ckpt
from mobilefinetuner_tpu.io.checkpoints import (gpt2_params_from_hf,
                                                load_gpt2, save_gpt2)
from mobilefinetuner_tpu.models import gpt2
from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
from mobilefinetuner_tpu.optim import adam as adam_mod
from mobilefinetuner_tpu.parallel.mesh import shard_params

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gpt2_full_finetune",
        description="GPT-2 full fine-tuning on WikiText-2 (TPU)")
    p.add_argument("--data_dir", required=True)
    p.add_argument("--pretrained_dir", required=True)
    p.add_argument("--output_path", default="gpt2_full_ft.safetensors")
    p.add_argument("--resume_from", default="",
                   help="full-model safetensors to resume from")
    p.add_argument("--eval_out", default="")
    common.add_train_flags(p, lr=5e-5, seq_len=128, batch_size=1)
    common.add_pm_flags(p)
    common.add_mesh_flags(p)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    config, params = load_gpt2(args.pretrained_dir)
    config = dataclasses.replace(
        config, attention_impl=args.attention_impl)
    if args.no_model_dropout:
        config = dataclasses.replace(config, embd_pdrop=0.0,
                                     resid_pdrop=0.0, attn_pdrop=0.0)
    if args.resume_from:
        # verify-on-load with lineage fallback (DESIGN.md §20)
        common.resolve_resume_from(args)
        params = gpt2_params_from_hf(
            common.load_full_resume(args.resume_from), config)
        log.info(f"resumed full model from {args.resume_from}")
    if args.seq_len > config.n_positions:
        args.seq_len = config.n_positions

    tok = GPT2BPETokenizer.from_pretrained(args.pretrained_dir)
    wt2 = WT2Config(seq_len=args.seq_len, batch_size=args.batch_size,
                    data_fraction=args.data_fraction, seed=args.seed,
                    **common.data_retry_kwargs(args))
    train_ds = WikiText2Dataset(args.data_dir, "train", wt2, tok.encode,
                                tok.eos_id)
    valid_ds = None
    if args.eval_interval:
        wt2_eval = WT2Config(seq_len=args.seq_len,
                             batch_size=args.eval_batch_size, shuffle=False,
                             **common.data_retry_kwargs(args))
        valid_ds = WikiText2Dataset(args.data_dir, "valid", wt2_eval,
                                    tok.encode, tok.eos_id)

    steps_per_epoch = max(train_ds.num_batches() // args.grad_accum_steps, 1)
    total_steps = common.resolve_total_steps(args, steps_per_epoch)
    tc = common.train_config_from_args(args, total_steps)
    log.info(f"full FT: {gpt2.param_count(params):,} trainable params, "
             f"{total_steps} steps")

    opt_state, start_step = common.maybe_resume_opt_state(
        args, params, tc, None)

    # Full FT: params themselves are the trainable tree — FSDP-shard them
    # (and thus Adam m/v) over the mesh; no host offload of trainables.
    mesh, cp_mesh = common.build_mesh(args)
    if cp_mesh is not None and config.attn_pdrop > 0:
        log.warning(f"attn_pdrop={config.attn_pdrop} is unsupported by "
                    f"ring attention; attention-probs dropout is OFF in "
                    f"sequence-parallel mode (--no_model_dropout "
                    f"silences this)")
    # mesh-shape-agnostic placement (elastic resume, DESIGN.md §18): the
    # checkpoint + sidecar hold FULL host tensors, so whatever mesh THIS
    # run built re-shards them here — a save at (1,N) resumes at (1,M)
    # with the Adam m/v landing on the same FSDP specs as the params
    # (shard_params is multi-host safe, unlike a raw device_put).
    params = shard_params(params, mesh)
    if opt_state is not None:
        opt_state = common.place_opt_state(opt_state, mesh)
    compute_dtype = common.compute_dtype_from_args(args)
    model_pdrop = max(config.embd_pdrop, config.resid_pdrop,
                      config.attn_pdrop)
    base_rng = (jax.random.PRNGKey(args.seed + 1)
                if model_pdrop > 0 else None)

    def loss_fn(params_t, _unused, mb):
        rng = mb["dropout_rng"][0] if "dropout_rng" in mb else None
        logits = gpt2.forward(config, params_t, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              compute_dtype=compute_dtype, remat=args.remat,
                              dropout_rng=rng, cp_mesh=cp_mesh)
        return lm_cross_entropy_sum(logits, mb["labels"])

    def nll_fn(params_t, _unused, mb):
        logits = gpt2.forward(config, params_t, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              compute_dtype=compute_dtype,
                              cp_mesh=cp_mesh)
        return lm_cross_entropy_sum(logits, mb["labels"])

    def save_hook(step, params_t, opt_st, final, ckpt=None):
        path = args.output_path
        if not final:
            root, ext = os.path.splitext(path)
            path = f"{root}_step{step}{ext}"
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        # full-FT trees are the expensive case: the batched snapshot is
        # the loop's only stall; the HF key-mapping + write of params
        # AND the 2x-params .opt sidecar happen off-loop
        (params_h, opt_h), snap_ms = async_ckpt.timed_snapshot(
            (params_t, opt_st))

        def write():
            save_gpt2(path, params_h)
            adam_mod.save_state(path + ".opt", opt_h, tc.adam(),
                                extra_metadata={"loop_step": str(step)})
            common.record_ckpt_files(args, args.output_path, step,
                                     [path, path + ".opt"])
            log.info(f"saved full model -> {path}")
            return [path, path + ".opt"]

        async_ckpt.submit(ckpt, step, write, final=final,
                          snapshot_ms=snap_ms)

    def load_trainable(path):
        """Rollback inverse of save_hook: HF-keyed full model file ->
        the stacked host param tree (mesh placement happens in
        run_training's rollback, reusing the elastic-resume rule)."""
        from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
        return gpt2_params_from_hf(
            SafeTensorsReader(path).load_all(promote_to_f32=True), config)

    # in-loop MFU from the shared estimator (core/telemetry.py)
    from mobilefinetuner_tpu.core.telemetry import transformer_flops
    flops = transformer_flops(
        gpt2.param_count(params), 0,
        args.batch_size * tc.grad_accum_steps, args.seq_len,
        config.n_layer, config.n_head, config.head_dim, full_ft=True)

    common.run_training(
        args, trainable=params, frozen=None, loss_fn=loss_fn,
        nll_fn=nll_fn, train_ds=train_ds, valid_ds=valid_ds,
        total_steps=total_steps, tc=tc, mask=None, start_step=start_step,
        opt_state=opt_state, save_hook=save_hook, mesh=mesh,
        replicate_trainable=False, dropout_rng=base_rng,
        flops_per_step=flops,
        load_hook=common.make_rollback_loader(tc, None, load_trainable),
        ckpt_path=args.output_path,
        # memory-admission ladder (DESIGN.md §21): full FT gets the
        # remat and accum_x2 rungs (loss_fn reads args.remat at trace
        # time; accum doubles inside run_training at constant global
        # batch). No offload rung — the TRAINABLE tree is the HBM cost
        # here and offload targets frozen bases only.
        degrade_builders=None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
