"""Shared CLI machinery: flag groups mirroring the reference binaries'
surfaces, governor/offload/mesh wiring, and the generic training loop driver.

Reference flag surfaces: gpt2_lora_finetune/main.cpp:80-171 (CmdArgs
defaults), train_lora_gemma.cpp parse block, eval_ppl.cpp, eval_mmlu.cpp.
TPU-native additions beyond the reference: --dtype (bf16 compute), --remat
(gradient checkpointing), --mesh_data/--mesh_fsdp (multi-chip mesh), and
optimizer-state save/resume (the reference leaves Adam state unwired,
SURVEY.md §5 Checkpoint/resume).
"""

from __future__ import annotations

import argparse
import contextlib
import itertools
import math
import os
import statistics
import time
from typing import Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.core.logging import (JSONLWriter, MetricsLogger,
                                              get_logger)
from mobilefinetuner_tpu.core.preempt import EXIT_PREEMPTED, PreemptionGuard
from mobilefinetuner_tpu.core.telemetry import (GoodputMeter, HangWatchdog,
                                                SpikeConfig, SpikeDetector,
                                                Telemetry, device_peak_flops,
                                                mfu_from, run_manifest)
from mobilefinetuner_tpu.core.xla_stats import (compiled_flops,
                                                compiled_peak_mb,
                                                live_hbm_mb)
from mobilefinetuner_tpu.data.prefetch import Prefetcher
from mobilefinetuner_tpu.data.wikitext2 import WikiText2Dataset
from mobilefinetuner_tpu.ops.loss import perplexity_from_loss
from mobilefinetuner_tpu.parallel.mesh import (make_batch_placer, make_mesh,
                                               params_shardings,
                                               replicated_sharding)
from mobilefinetuner_tpu.parallel.offload import (OffloadConfig,
                                                  apply_placement, fetch,
                                                  placement_stats,
                                                  plan_placement)
from mobilefinetuner_tpu.system.governor import GovernorConfig, StepGovernor
from mobilefinetuner_tpu.train.trainer import (StepClock, TrainConfig,
                                               init_optimizer,
                                               make_eval_step,
                                               make_train_step)

log = get_logger()


# --------------------------- flag groups ------------------------------------

def add_train_flags(p: argparse.ArgumentParser, lr: float = 1e-4,
                    seq_len: int = 128, batch_size: int = 1):
    """Training hparams (gpt2_lora_finetune/main.cpp CmdArgs defaults)."""
    g = p.add_argument_group("training")
    g.add_argument("--epochs", type=int, default=0,
                   help="epochs (overrides steps when > 0)")
    g.add_argument("--steps", type=int, default=0, help="training steps")
    g.add_argument("--batch_size", type=int, default=batch_size,
                   help="micro-batch size per accumulation step")
    g.add_argument("--grad_accum_steps", "--grad_accum", type=int, default=1)
    g.add_argument("--seq_len", type=int, default=seq_len)
    g.add_argument("--lr", type=float, default=lr)
    g.add_argument("--weight_decay", type=float, default=0.0)
    g.add_argument("--warmup_steps", type=int, default=0)
    g.add_argument("--warmup_ratio", type=float, default=None,
                   help="overrides warmup_steps when set")
    g.add_argument("--clip_grad_norm", "--max_grad_norm", type=float,
                   default=1.0)
    g.add_argument("--lr_schedule", choices=["cosine", "linear", "constant"],
                   default="cosine")
    g.add_argument("--data_fraction", type=float, default=1.0)
    g.add_argument("--log_interval", type=int, default=1)
    g.add_argument("--eval_interval", type=int, default=0)
    g.add_argument("--eval_batches", type=int, default=50)
    g.add_argument("--eval_batch_size", type=int, default=2)
    g.add_argument("--save_every", type=int, default=0)
    g.add_argument("--async_save", type=int, default=1,
                   help="1 = snapshot-then-write checkpointing "
                        "(io/async_ckpt.py): at a save step the loop "
                        "blocks only for a batched device->host "
                        "snapshot; key-mapping, bf16 encode, and the "
                        "safetensors write run on a background thread "
                        "(depth-1 queue — a save landing while one is "
                        "in flight coalesces to the newest snapshot "
                        "with a ckpt_dropped telemetry event; final "
                        "saves drain). 0 = fully synchronous oracle. "
                        "Files are byte-identical either way and every "
                        "writer publishes atomically (tmp+fsync+rename "
                        "— a kill mid-write cannot corrupt the "
                        "checkpoint --resume_from loads); telemetry's "
                        "checkpoint event splits snapshot_ms (blocking) "
                        "from write_ms/bytes/mb_s (background)")
    g.add_argument("--ema_beta", type=float, default=0.9)
    g.add_argument("--seed", type=int, default=42)
    g.add_argument("--coupled_weight_decay", action="store_true",
                   help="L2-into-gradient decay for reference parity "
                        "(adam.cpp:65-67); default is decoupled AdamW")
    g.add_argument("--metrics_csv", default="",
                   help="CSV metrics sink (logger.h:131-190 analog)")
    # TPU-native knobs
    g.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32", help="compute dtype")
    g.add_argument("--remat", action="store_true",
                   help="gradient checkpointing over the layer scan")
    g.add_argument("--attention_impl", choices=["auto", "xla", "flash"],
                   default="auto",
                   help="'auto' picks per shape (flash from S >= 512 at "
                        "D <= 128, S >= 2048 at D = 256; measured e2e on "
                        "v5e, ops/attention.resolve_impl); 'flash' = "
                        "Pallas block-sparse kernel; 'xla' = plain fused "
                        "attention")
    g.add_argument("--lora_impl", choices=["auto", "naive", "fused"],
                   default="auto",
                   help="LoRA hot-path implementation "
                        "(models/lora_apply.py, DESIGN.md §17): 'naive' "
                        "= the parity oracle, fixed (x@A)@B order; "
                        "'fused' = shape-aware contraction order + the "
                        "Pallas epilogue kernels at eligible sites (the "
                        "[N, d_out] adapter delta never round-trips "
                        "HBM); 'auto' resolves per call site — fused "
                        "where the kernel is eligible and the delta is "
                        "memory-bound, else naive. All impls accumulate "
                        "the rank-r bottleneck in f32; value+grad "
                        "parity is pinned by tests/test_lora.py. The "
                        "per-target resolution is logged in the "
                        "telemetry run_start manifest")
    g.add_argument("--no_model_dropout", action="store_true",
                   help="zero the checkpoint's embd/resid/attn pdrop "
                        "(HF GPT-2 configs carry 0.1; dropout changes "
                        "loss curves — both attention impls support "
                        "train-mode attn dropout, the flash kernel via "
                        "its in-kernel hash mask)")
    g.add_argument("--profile_dir", default="",
                   help="emit a jax.profiler trace of a few steady-state "
                        "steps to this directory (the reference's "
                        "performance_monitor.h analog; view with "
                        "tensorboard/xprof)")
    g.add_argument("--profile_start", type=int, default=10,
                   help="first profiled step (past compile+warmup)")
    g.add_argument("--profile_steps", type=int, default=5)
    g.add_argument("--prefetch", type=int, default=2,
                   help="async input pipeline (data/prefetch.py): a "
                        "background thread produces host batches into a "
                        "bounded queue of this depth, and batch k+1's "
                        "device placement is issued while step k "
                        "computes. 0 = fully synchronous kill-switch. "
                        "The batch sequence is byte-identical either "
                        "way (incl. resume and multi-host sharding); "
                        "the metrics' host_wait_ms column shows what "
                        "the overlap buys")
    g.add_argument("--telemetry_out", default="",
                   help="append-only JSONL run-telemetry stream "
                        "(core/telemetry.py): run_start manifest, "
                        "compile, step_stats (loss/mfu/tok_s/health), "
                        "throttle/eval/checkpoint/anomaly, run_end. "
                        "Under multi-host every process writes: the "
                        "coordinator to this path, host k to "
                        "PATH.host<k> (merge with tools/"
                        "fleet_report.py); appending to an existing "
                        "file continues its sequence numbers "
                        "(crash/resume). Render with "
                        "tools/telemetry_report.py")
    g.add_argument("--run_registry", default="",
                   help="append-only run registry stream "
                        "(core/run_registry.py, DESIGN.md §28): one "
                        "crash-safe `run` record per invocation — id, "
                        "git rev, config fingerprint, mesh, platform, "
                        "artifacts, terminal status — finalized on any "
                        "exit path; a SIGKILLed run is settled to "
                        "'interrupted' on the next registry open. "
                        "Default: $MFT_RUN_REGISTRY; empty = off. "
                        "Query with tools/observatory.py; resolve runs "
                        "by id/rev in bench_compare/telemetry_report/"
                        "fleet_report via --run")
    g.add_argument("--spike_z", type=float, default=8.0,
                   help="loss-spike detector: emit an `anomaly` "
                        "telemetry event when a step's loss exceeds "
                        "this many EMA standard deviations (host-side, "
                        "on the flushed metrics; <= 0 disables)")
    g.add_argument("--spike_beta", type=float, default=0.98,
                   help="EMA decay of the spike detector's running "
                        "mean/variance")
    g.add_argument("--spike_warmup", type=int, default=20,
                   help="steps observed before the spike detector arms "
                        "(early-training loss is legitimately wild)")
    # fleet observability (DESIGN.md §14)
    g.add_argument("--watchdog", type=int, default=1,
                   choices=[0, 1, 2],
                   help="hang watchdog: a daemon thread dumps every "
                        "Python thread's stack (faulthandler) and emits "
                        "a `hang` telemetry event when no step completes "
                        "within watchdog_mult x the rolling-median step "
                        "time. 0 = off (kill-switch), 1 = report and "
                        "keep waiting (deadline backs off 2x), 2 = "
                        "report then abort the process (exit 113 — for "
                        "pods where a wedged collective should fail "
                        "fast instead of burning the reservation)")
    g.add_argument("--watchdog_mult", type=float, default=10.0,
                   help="hang deadline = this many rolling-median step "
                        "times (floored at --watchdog_min_s)")
    g.add_argument("--watchdog_min_s", type=float, default=60.0,
                   help="hang deadline floor in seconds; also the "
                        "pre-first-step grace (compile/eval/checkpoint "
                        "pauses suspend the clock, so they need no "
                        "extra padding)")
    g.add_argument("--straggler_cadence", type=int, default=0,
                   help="every K steps gather each host's median step "
                        "time across the fleet (collective; "
                        "deterministic cadence), stamp the per-host "
                        "map into step_stats.host_step_ms, and emit a "
                        "`straggler` event for any host slower than "
                        "straggler_mult x the fleet median. 0 = off "
                        "(default: single-host runs have nothing to "
                        "compare)")
    g.add_argument("--straggler_mult", type=float, default=1.5,
                   help="straggler threshold: host median step time vs "
                        "fleet median")
    # live observability plane (DESIGN.md §22)
    g.add_argument("--trace_spans", type=int, default=0, choices=[0, 1],
                   help="1 = emit `span` events (core/trace.py) into "
                        "the telemetry stream: the goodput phases "
                        "(init/compile/step/input_wait/eval/checkpoint/"
                        "...) on a 'phase' track, each async checkpoint "
                        "write on 'ckpt', each prefetch-producer batch "
                        "on 'prefetch' — one tools/trace_export.py run "
                        "turns the stream into a Perfetto-loadable "
                        "timeline whose per-phase span sums reconcile "
                        "with run_end's goodput buckets. Opt-in: a "
                        "traced loop emits a handful of events per "
                        "step. Requires --telemetry_out")
    g.add_argument("--auto_profile", type=int, default=0, choices=[0, 1],
                   help="1 = flight recorder: arm a ONE-SHOT "
                        "jax.profiler capture when a sensor fires — a "
                        "flush interval slower than "
                        "auto_profile_slow_mult x the rolling median, "
                        "a loss_spike/divergence anomaly, a straggler "
                        "attribution, or the hang watchdog pre-exit — "
                        "saving the device trace of the BAD step next "
                        "to the stack dumps (a pre-scheduled "
                        "--profile_dir window cannot catch these). "
                        "Each capture emits a `profile_capture` event; "
                        "cooldown + budget bound the disk cost")
    g.add_argument("--auto_profile_dir", default="",
                   help="capture root (default: <telemetry_out>"
                        ".profiles); each capture lands in its own "
                        "cap<k>_<trigger>_step<n> subdirectory")
    g.add_argument("--auto_profile_steps", type=int, default=2,
                   help="steps per triggered capture")
    g.add_argument("--auto_profile_cooldown", type=float, default=300.0,
                   help="seconds between captures (a persistently sick "
                        "run produces a few traces, not a disk full)")
    g.add_argument("--auto_profile_budget", type=int, default=2,
                   help="max captures per run")
    g.add_argument("--auto_profile_slow_mult", type=float, default=3.0,
                   help="slow-step trigger: capture when a flush "
                        "interval's per-step time exceeds this multiple "
                        "of the rolling median (<= 0 disables the "
                        "slow-step sensor; anomaly/straggler/hang "
                        "triggers stay armed)")
    g.add_argument("--metrics_port", type=int, default=0,
                   help="serve a live OpenMetrics /metrics endpoint + "
                        "/healthz on this port (core/metrics_http.py): "
                        "step-time/TTFT histograms, tok/s, MFU, live "
                        "HBM, queue depth, goodput fractions, skip/"
                        "rollback/degrade counters — fed from the same "
                        "emit path the telemetry sink uses (no second "
                        "instrumentation layer, zero added device "
                        "syncs). Coordinator-only under multi-host. "
                        "0 = off")
    g.add_argument("--metrics_addr", default="127.0.0.1",
                   help="bind address for --metrics_port (default "
                        "loopback: the endpoint exposes operational "
                        "detail; exporting it beyond the host is an "
                        "explicit decision)")
    # elastic fleet (DESIGN.md §18)
    g.add_argument("--on_preempt", choices=["drain", "off"],
                   default="drain",
                   help="SIGTERM/SIGINT handling (core/preempt.py): "
                        "'drain' (default) finishes the step in flight, "
                        "takes ONE final atomic checkpoint through the "
                        "async checkpointer, ends the telemetry stream "
                        "with run_end{reason=preempted}, and exits with "
                        f"the resumable code {EXIT_PREEMPTED} — a "
                        "preemption notice costs one step plus one "
                        "drain instead of the steps since the last "
                        "periodic save (a second signal aborts the "
                        "drain). 'off' keeps default signal behavior")
    g.add_argument("--data_retries", type=int, default=3,
                   help="bounded retry budget for transient I/O errors "
                        "on the streaming data refetch path (shared-"
                        "filesystem hiccups under a fleet): each retry "
                        "backs off exponentially with jitter and emits "
                        "an anomaly{kind=data_retry} telemetry event; "
                        "after the budget the original error raises. "
                        "0 = fail fast")
    g.add_argument("--data_backoff_s", type=float, default=0.5,
                   help="base backoff for --data_retries (doubles per "
                        "attempt, +25%% jitter to desynchronize a fleet "
                        "retrying the same filesystem)")
    # numerical-fault recovery (DESIGN.md §20)
    g.add_argument("--skip_nonfinite", type=int, default=0,
                   help="1 = guarded update: when a step's gradients "
                        "carry any non-finite element (or the global "
                        "grad norm is non-finite) the Adam update "
                        "degenerates to identity INSIDE the compiled "
                        "step (params/opt state pass through a "
                        "jnp.where tree-select; donation, shardings, "
                        "and the LR schedule untouched) and a "
                        "`skipped` count rides step_stats with zero "
                        "added syncs. A clean run is byte-identical "
                        "with the guard on or off. 0 = off (a NaN "
                        "grad poisons the params, as before)")
    g.add_argument("--rollback_budget", type=int, default=0,
                   help="> 0 arms in-process rollback: on sustained "
                        "divergence (anomaly{kind=divergence}), a "
                        "streak of --rollback_skip_streak skipped/"
                        "nonfinite steps, or a nonfinite loss with the "
                        "skip guard off, the loop reloads the newest "
                        "VERIFIED lineage checkpoint + .opt sidecar "
                        "without restarting the process or recompiling "
                        "the step, fast-forwards the data stream, and "
                        "keeps training — at most this many times per "
                        "run (each decision emits a `rollback` event). "
                        "Requires --save_every checkpoints. 0 = off")
    g.add_argument("--rollback_skip_streak", type=int, default=3,
                   help="consecutive skipped-update/nonfinite-loss "
                        "steps that trigger a rollback (a single "
                        "skipped step is the guard doing its job, not "
                        "a reason to lose progress)")
    g.add_argument("--rollback_data_offset", type=int, default=1,
                   help="extra data-stream steps skipped per rollback "
                        "so the replayed window sees a DIVERGED batch "
                        "sequence (a deterministically poisonous batch "
                        "must not be replayed verbatim); 0 replays the "
                        "byte-pinned original sequence")
    g.add_argument("--keep_ckpts", type=int, default=0,
                   help="retain only the K newest step-tagged "
                        "checkpoints in the lineage (<final>.lineage."
                        "json), GC'ing older files AFTER the pruned "
                        "lineage publishes atomically (a kill mid-GC "
                        "leaves orphans, never a lineage naming "
                        "deleted files); the final artifact is never "
                        "pruned. 0 = keep all")
    g.add_argument("--verify_ckpt", type=int, default=1,
                   help="1 = verify the per-tensor checksum manifest "
                        "on every checkpoint load (--resume_from and "
                        "rollback): a corrupt/truncated/stale file is "
                        "rejected with a ckpt_verify{ok=false} event "
                        "and the load falls back down the lineage "
                        "chain instead of crashing or silently "
                        "loading garbage. 0 = trust the newest file")
    g.add_argument("--inject", default="",
                   help="fault-injection harness (the multihost_smoke/"
                        "serve_bench --inject pattern, CPU-testable): "
                        "grad_nan:<step>[:<n>] poisons n (default 1) "
                        "consecutive step batches with NaN so the "
                        "gradients go non-finite; loss_spike:<step>"
                        "[:<n>] scrambles n batches' labels (loss "
                        "level-shift); ckpt_corrupt flips a byte in "
                        "the newest lineage checkpoint after its "
                        "first periodic save; hbm_pressure:<mb> "
                        "allocates <mb> MB of device ballast before "
                        "compile (on TPU a real RESOURCE_EXHAUSTED "
                        "follows; backends that cannot genuinely OOM "
                        "raise a simulated one at the first dispatch) "
                        "— drives the --on_oom_risk degradation "
                        "ladder end to end. Each fires ONCE per "
                        "process (latched), so a post-rollback replay "
                        "of the same steps runs clean")
    add_mem_flags(p)


def add_mem_flags(p: argparse.ArgumentParser):
    """Memory-admission knobs (core/memory_guard.py, DESIGN.md §21) —
    shared by the train CLIs (full preflight + degradation ladder) and
    the eval CLIs (preflight only: eval has no ladder, so 'degrade'
    behaves like 'warn' there)."""
    g = p.add_argument_group("memory admission (DESIGN.md §21)")
    g.add_argument("--hbm_cap_mb", type=int, default=0,
                   help="per-device memory capacity override in MB for "
                        "the admission preflight; 0 = auto (the "
                        "backend's memory_stats bytes_limit, else a "
                        "device-kind table of public HBM sizes). The "
                        "override is what lets CPU tests drive the "
                        "verdict deterministically")
    g.add_argument("--hbm_headroom", type=float, default=0.1,
                   help="admission margin: a config is OVER when its "
                        "estimate exceeds capacity x (1 - headroom) — "
                        "runtime allocations the compile-time analysis "
                        "cannot see (collectives scratch, fragmentation) "
                        "need somewhere to live")
    g.add_argument("--on_oom_risk", choices=["fail", "degrade", "warn"],
                   default="degrade",
                   help="what a failed admission does: 'fail' raises a "
                        "named MemoryAdmissionError immediately after "
                        "compile — before data loading, not 40 steps in "
                        "(the r13 controller reads it as an inadmissible "
                        "CONFIG, not a restartable crash); 'degrade' "
                        "(default) walks the bounded ladder — enable "
                        "--remat, double grad-accum at constant global "
                        "batch, enable weight offload/streaming — "
                        "recompiling and re-preflighting at each rung "
                        "(each decision is a `degrade` telemetry event; "
                        "loss trajectory stays parity-pinned <=1e-5), "
                        "raising the named error with the attempted "
                        "ladder when the last rung still does not fit; "
                        "'warn' logs and proceeds (the pre-round-16 "
                        "behavior). A RESOURCE_EXHAUSTED caught at "
                        "compile or first dispatch takes the same "
                        "ladder. Verdict 'unknown' (no capacity source) "
                        "always proceeds — admission never refuses on a "
                        "guess")
    g.add_argument("--prefetch_rss_mb", type=int, default=0,
                   help="host-RSS shed guard for the async input "
                        "pipeline: while the process RSS exceeds this "
                        "many MB the producer stops assembling "
                        "lookahead batches until the queue drains "
                        "(degrade toward depth-1 instead of the OS "
                        "OOM-killer picking a victim). 0 = off")


def add_align_flags(p: argparse.ArgumentParser):
    """Alignment-harness flags (train_lora_gemma.cpp:620-920 analog)."""
    g = p.add_argument_group("alignment harness")
    g.add_argument("--align_dump_dir", default="",
                   help="align mode: dump one batch's activations/grads/"
                        "post-step adapter as npy and exit; compare with "
                        "tools/align_torch_mirror.py")
    g.add_argument("--align_steps", type=int, default=5,
                   help="steps of the align-mode loss curve")


def add_pm_flags(p: argparse.ArgumentParser):
    """Energy-governor flags (CmdArgs pm_* block; pm_interval=0 disables)."""
    g = p.add_argument_group("step governor (pm_*)")
    g.add_argument("--pm_interval", type=int, default=0,
                   help="telemetry check every K steps; 0 disables")
    g.add_argument("--pm_batt_thresh", type=float, default=20.0)
    g.add_argument("--pm_temp_thresh", type=float, default=42.0)
    g.add_argument("--pm_fb_high", type=float, default=2.0)
    g.add_argument("--pm_fb_low", type=float, default=0.5)
    g.add_argument("--pm_ft_high", type=float, default=2.0)
    g.add_argument("--pm_ft_low", type=float, default=0.5)
    g.add_argument("--pm_manual_batt", type=float, default=100.0)
    g.add_argument("--pm_manual_temp", type=float, default=30.0)
    g.add_argument("--pm_disable_batt", action="store_true")
    g.add_argument("--pm_disable_temp", action="store_true")
    g.add_argument("--pm_schedule", default="",
                   help='deterministic override, e.g. "0-99:300,100-:50"')


def add_shard_flags(p: argparse.ArgumentParser):
    """Offload flags (CmdArgs shard_* block). --shard_dir is accepted for
    reference-CLI compatibility but unused: the offload tier is pinned host
    RAM, not disk (parallel/offload.py)."""
    g = p.add_argument_group("parameter offload (shard_*)")
    g.add_argument("--shard_enable", action="store_true")
    g.add_argument("--shard_dir", default="",
                   help="ignored (offload targets host RAM, not disk)")
    g.add_argument("--shard_budget_mb", type=int, default=512,
                   help="HBM budget for resident frozen params")
    g.add_argument("--shard_fp16_disk", type=int, default=1,
                   help="1 = store offloaded params as bf16 (TPU-idiomatic "
                        "16-bit; analog of fp16-on-disk quantization)")
    g.add_argument("--shard_stream", type=int, default=1,
                   help="1 = stream offloaded block weights host->HBM one "
                        "layer at a time inside the layer scan (bounds peak "
                        "HBM like the reference's per-layer require(), "
                        "parameter_sharder.cpp:242-271); 0 = whole-tree "
                        "fetch per step (budget governs idle placement "
                        "only)")


def add_mesh_flags(p: argparse.ArgumentParser):
    g = p.add_argument_group("device mesh")
    g.add_argument("--mesh_data", type=int, default=1,
                   help="data-parallel mesh axis size")
    g.add_argument("--mesh_fsdp", type=int, default=1,
                   help="fsdp mesh axis size; 0 = all remaining devices "
                        "(default 1 = single chip, like the reference; "
                        "multi-chip is opt-in)")
    g.add_argument("--sequence_parallel", action="store_true",
                   help="long-context mode: shard the SEQUENCE axis over "
                        "the fsdp mesh axis and run ring attention "
                        "(parallel/ring_attention.py); seq_len must "
                        "divide by mesh_fsdp")
    g.add_argument("--multihost", action="store_true",
                   help="multi-process run: bring up jax.distributed "
                        "(auto-detected on TPU pods) and lay the mesh out "
                        "DCN-aware (fsdp on ICI within a host, data "
                        "across hosts; parallel/distributed.py)")
    g.add_argument("--dist_coordinator", default="",
                   help="coordinator host:port (or JAX_COORDINATOR_ADDRESS; "
                        "omit on TPU pods — auto-detected)")
    g.add_argument("--dist_num_processes", type=int, default=0,
                   help="process count (or JAX_NUM_PROCESSES; 0 = auto)")
    g.add_argument("--dist_process_id", type=int, default=-1,
                   help="this process's id (or JAX_PROCESS_ID; -1 = auto)")


def governor_from_args(args, event_sink=None) -> StepGovernor:
    cfg = GovernorConfig(
        enable=args.pm_interval > 0 or bool(args.pm_schedule),
        # 0 = telemetry disabled: a schedule-only run stays full speed on
        # uncovered steps; pm_interval > 0 makes uncovered steps fall
        # through to the telemetry policy (reference PowerMonitor).
        check_interval_steps=args.pm_interval,
        battery_threshold=args.pm_batt_thresh,
        temp_threshold=args.pm_temp_thresh,
        freq_batt_high=args.pm_fb_high,
        freq_batt_low=args.pm_fb_low,
        freq_temp_high=args.pm_ft_high,
        freq_temp_low=args.pm_ft_low,
        schedule=args.pm_schedule,
        manual_battery=None if args.pm_disable_batt else args.pm_manual_batt,
        manual_temp=None if args.pm_disable_temp else args.pm_manual_temp,
    )
    return StepGovernor(cfg, event_sink=event_sink)


def offload_config_from_args(args) -> OffloadConfig:
    return OffloadConfig(
        enable=bool(args.shard_enable),
        max_resident_bytes=args.shard_budget_mb * 1024 * 1024,
        offload_dtype="bfloat16" if args.shard_fp16_disk else "float32")


def build_mesh(args):
    """Returns (mesh, cp_mesh): cp_mesh is the mesh again when
    --sequence_parallel is set (pass it to the model forwards so ring
    attention engages), else None — deriving it HERE keeps every CLI's
    wiring consistent. --multihost (or JAX_* env) first brings up the
    distributed runtime and switches to the DCN-aware hybrid layout."""
    from mobilefinetuner_tpu.parallel.distributed import (initialize,
                                                          make_hybrid_mesh)
    # initialize() no-ops without --multihost / --dist_coordinator /
    # JAX_COORDINATOR_ADDRESS-style env, so the env-var-only launch mode
    # works without any flag
    multi = initialize(
        coordinator=getattr(args, "dist_coordinator", ""),
        num_processes=getattr(args, "dist_num_processes", 0) or None,
        process_id=(getattr(args, "dist_process_id", -1)
                    if getattr(args, "dist_process_id", -1) >= 0 else None),
        force=getattr(args, "multihost", False))
    n = len(jax.devices())
    multi = multi or jax.process_count() > 1
    if multi:
        # the mesh must span every process's devices, so (data, fsdp) is
        # interpreted globally. mesh_fsdp=0 keeps its "all remaining"
        # meaning, resolved hierarchy-aware: fsdp = one host's ICI domain
        # (or the global remainder when an explicit data size is given).
        fsdp = args.mesh_fsdp
        if fsdp == 0:
            fsdp = (n // args.mesh_data if args.mesh_data > 1
                    else len(jax.local_devices()))
        data = args.mesh_data if args.mesh_data > 1 else n // fsdp
        mesh = make_hybrid_mesh(data=data, fsdp=fsdp)
    else:
        data = args.mesh_data
        fsdp = args.mesh_fsdp or (n // max(data, 1))
        mesh = make_mesh(data=data, fsdp=fsdp,
                         devices=jax.devices()[:data * fsdp])
    size = data * fsdp
    sp = getattr(args, "sequence_parallel", False)
    if size > 1:
        log.info(f"mesh: data={data} fsdp={fsdp}"
                 + (f" over {jax.process_count()} processes" if multi
                    else "")
                 + (" (sequence-parallel)" if sp else ""))
        # one validation block for both layouts: batch shards over the
        # whole mesh (or just "data" under sequence parallelism, where
        # "fsdp" carries the sequence axis instead)
        b_div = max(data, 1) if sp else size
        b_axis = f"mesh_data={data}" if sp else f"the mesh size {size}"
        if args.batch_size % b_div != 0:
            raise SystemExit(
                f"batch_size={args.batch_size} (the "
                f"{'GLOBAL ' if multi else ''}micro-batch) must be "
                f"divisible by {b_axis}")
        if sp and args.seq_len % fsdp != 0:
            raise SystemExit(
                f"seq_len={args.seq_len} must divide by mesh_fsdp={fsdp} "
                f"in sequence-parallel mode")
        if (multi and getattr(args, "eval_interval", 0)
                and getattr(args, "eval_batch_size", 1) % b_div != 0):
            raise SystemExit(
                f"eval_batch_size={args.eval_batch_size} must be "
                f"divisible by {b_axis} under multi-host (eval batches "
                f"shard like train batches)")
    return mesh, (mesh if sp else None)


# --------------------------- loop helpers -----------------------------------

def load_full_resume(path: str):
    """Raw HF-keyed tensor dict from a full-model resume source: an HF
    checkpoint dir (single-file or sharded) or a single safetensors file.
    Shared by the full-FT CLIs (gpt2_full_finetune, gemma_full_finetune)
    so the load idiom cannot drift between them."""
    from mobilefinetuner_tpu.io.checkpoints import load_hf_state_dict
    if os.path.isdir(path):
        return load_hf_state_dict(path)
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    return SafeTensorsReader(path).load_all(promote_to_f32=True)


def resolve_total_steps(args, steps_per_epoch: int) -> int:
    """epochs overrides steps (reference CmdArgs semantics)."""
    if args.epochs > 0:
        return max(args.epochs * steps_per_epoch, 1)
    if args.steps > 0:
        return args.steps
    return max(steps_per_epoch, 1)  # default: one epoch


def train_config_from_args(args, total_steps: int) -> TrainConfig:
    if args.warmup_ratio is not None:
        warmup_ratio = args.warmup_ratio
    else:
        warmup_ratio = args.warmup_steps / max(total_steps, 1)
    return TrainConfig(
        total_steps=total_steps, lr=args.lr, warmup_ratio=warmup_ratio,
        schedule=args.lr_schedule, clip_grad_norm=args.clip_grad_norm,
        grad_accum_steps=args.grad_accum_steps,
        weight_decay=args.weight_decay,
        coupled_weight_decay=args.coupled_weight_decay,
        skip_nonfinite=bool(getattr(args, "skip_nonfinite", 0)))


def micro_batches(dataset: WikiText2Dataset, accum: int,
                  skip_steps: int = 0) -> Iterator[tuple]:
    """Yield (epoch, [accum*micro_b, ...] step batch) forever, cycling
    epochs (the reference's per-step micro-batch pulls, main.cpp:569-583).

    The step batch is assembled ONCE: chunk rows are written straight
    into preallocated [accum*b, S] arrays (dataset.fill_rows) instead of
    per-micro-batch np.stack followed by an np.concatenate over the
    accumulation — that was two full copies of every step batch on the
    host critical path. The arrays are freshly allocated per step, never
    reused: the async prefetch queue (data/prefetch.py) may hold several
    step batches at once, and a recycled buffer would corrupt them.

    skip_steps fast-forwards the stream past batches an interrupted run
    already consumed, WITHOUT building them — a resumed run continues the
    exact data order of an uninterrupted one (same seed => same per-epoch
    shuffles) instead of replaying epoch 0 from the top."""
    nb = dataset.num_batches()
    if nb == 0:
        raise ValueError(
            "dataset yields zero batches (num_chunks < batch_size with "
            "drop_last=True — seq_len/batch_size too large or "
            "--data_fraction too small for this split)")
    b = dataset.config.batch_size
    S = dataset.config.seq_len
    # the stream is continuous across epochs (a partial accumulation at an
    # epoch boundary carries into the next epoch), so step s consumes
    # micro-batches [s*accum, (s+1)*accum) of the concatenated stream
    epoch, bi = divmod(skip_steps * accum, nb)
    order = dataset.chunk_order(epoch)
    while True:
        # collect the step's chunk-index slices first — they can cross an
        # epoch boundary (reshuffling the order) and, without drop_last,
        # the final slice of an epoch may be short — then fill one buffer
        slices = []
        for _ in range(accum):
            if bi >= nb:
                bi = 0
                epoch += 1
                order = dataset.chunk_order(epoch)
            slices.append(order[bi * b:(bi + 1) * b])
            bi += 1
        rows = sum(len(s) for s in slices)
        ids = np.empty((rows, S), np.int32)
        mask = np.empty((rows, S), np.float32)
        labels = np.empty((rows, S), np.int32)
        r0 = 0
        for sl in slices:
            dataset.fill_rows(sl, ids, mask, labels, row0=r0)
            r0 += len(sl)
        yield epoch, {"input_ids": ids, "attention_mask": mask,
                      "labels": labels}


def evaluate(eval_step, trainable, frozen, dataset: WikiText2Dataset,
             max_batches: int, mesh=None,
             sequence_parallel: bool = False, prefetch: int = 2) -> dict:
    """Token-weighted mean NLL over the split -> {loss, ppl, tokens}
    (eval_ppl.cpp:157-200 semantics), under the no-grad eval step.
    `mesh`: place eval batches like train batches (required under
    multi-host, where raw host numpy cannot feed a global-mesh jit).

    The sum-NLL/token-count accumulators stay ON DEVICE (one tiny add per
    batch rides the async dispatch queue) and transfer once after the
    loop — per-batch float()/int() forced a full device sync per eval
    step. Batches come through the same background producer + placement
    lookahead as training (prefetch=0: synchronous)."""
    place = make_batch_placer(mesh, sequence_parallel)
    source = dataset.epoch(0)
    if max_batches:
        source = itertools.islice(source, max_batches)
    total, count, n = None, None, 0
    with Prefetcher(source, depth=prefetch, place_fn=place) as batches:
        for b in batches:
            s, c = eval_step(trainable, frozen, b)
            total = s if total is None else total + s
            count = c if count is None else count + c
            n += 1
    if n == 0:
        tokens, mean = 0, 0.0
    else:
        # graftlint: disable=sync-hazard(one transfer after the eval loop, the r07 on-device accumulation contract)
        total, count = jax.device_get((total, count))
        tokens = int(count)
        mean = float(total) / max(tokens, 1)
    return {"loss": mean, "ppl": perplexity_from_loss(mean),
            "tokens": tokens, "batches": n}


def compute_dtype_from_args(args):
    return jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32


def log_lora_impl_resolution(args, target_dims, rank: int,
                             compute_dtype) -> None:
    """Resolve `--lora_impl auto` per target for the run's dominant
    shapes (models/lora_apply.impl_summary) and stamp the result into
    args so the telemetry run_start manifest records which path served
    the run. Shared by the LoRA CLIs — the convention must not drift
    between them."""
    from mobilefinetuner_tpu.models.lora_apply import impl_summary
    args.lora_impl_resolved = impl_summary(
        target_dims, args.batch_size * args.seq_len, rank,
        args.lora_impl, jnp.dtype(compute_dtype).itemsize)
    log.info(f"lora_impl={args.lora_impl} -> {args.lora_impl_resolved}")


def maybe_resume_opt_state(args, trainable, tc: TrainConfig, mask=None):
    """(opt_state, start_step) from the .opt sidecar next to
    --resume_from, or (None, 0). The sidecar carries Adam m/v AND the step
    counter — restoring both is an improvement over the reference, which
    never wires Adam::save/load into any CLI (SURVEY.md §5).

    The restored tree is HOST numpy and the template is abstract
    (jax.eval_shape — shapes/dtypes only, no device zeros allocated just
    to be overwritten): nothing here commits the state to any device, so
    the SAME sidecar loads at any mesh shape — the caller places it (the
    full-FT CLIs via `place_opt_state` at their mesh, the LoRA path via
    run_training's replication), which is what makes `--resume_from`
    mesh-shape-agnostic (elastic resume, DESIGN.md §18)."""
    from mobilefinetuner_tpu.optim import adam as adam_mod
    from mobilefinetuner_tpu.train.trainer import init_optimizer
    path = getattr(args, "resume_from", "")
    if not path or not os.path.exists(path + ".opt"):
        return None, 0
    # trainable rides as the abstracted ARGUMENT (not a closure constant:
    # eval_shape only abstracts arguments — a closed-over concrete tree
    # would make zeros_like allocate real device zeros during tracing)
    template = jax.eval_shape(lambda t: init_optimizer(t, tc, mask),
                              trainable)
    opt_state, _ = adam_mod.load_state(path + ".opt", template,
                                       to_host=True)
    # the LOOP step, not Adam's: under --skip_nonfinite the Adam step
    # counter lags the loop step by the skipped updates, so resuming at
    # opt_state["step"] would replay already-consumed batches. The
    # sidecar's loop_step metadata (round 15) is authoritative; the
    # lineage json is the fallback for sidecars that predate it.
    from mobilefinetuner_tpu.io.checkpoints import lineage_step_for
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    md = SafeTensorsReader(path + ".opt").metadata
    if "loop_step" in md:
        start_step = int(md["loop_step"])
    else:
        start_step = lineage_step_for(path)
        if start_step is None:
            start_step = int(opt_state["step"])
    log.info(f"restored optimizer state @ step {start_step} "
             f"(adam step {int(opt_state['step'])})")
    return opt_state, start_step


def place_opt_state(opt_state, mesh):
    """Place a host-side resumed Adam tree onto THIS run's mesh with the
    same FSDP rule as the params (`mesh.shard_params`): m/v leaves share
    the param shapes, so they land on the param specs by construction —
    ZeRO's optimizer-state partitioning survives a mesh reshape — while
    the step scalar and masked zero-size placeholders replicate. With
    the sidecar holding full tensors (writers gather before saving),
    this is the whole elastic-resume placement story: save at mesh
    (1,N), load + re-shard at (1,M), byte-identical values
    (tests/test_elastic.py pins the round trip)."""
    from mobilefinetuner_tpu.parallel.mesh import shard_params
    return shard_params(opt_state, mesh)


def data_retry_kwargs(args) -> dict:
    """WT2Config kwargs for the bounded-retry streaming refetch
    (--data_retries/--data_backoff_s) — one place, so the four train
    CLIs cannot drift. Applied to the TRAIN and EVAL datasets alike (a
    mid-run eval refetch over the same flaky filesystem deserves the
    same budget)."""
    return {"retries": max(getattr(args, "data_retries", 0), 0),
            "retry_backoff_s": getattr(args, "data_backoff_s", 0.5)}


def make_data_retry_sink(tel, cur_step: dict):
    """The WikiText2Dataset.event_sink adapter: render a survived-retry
    report (`_io_retry`'s kind/attempt/error/what/backoff_s kwargs) as
    an `anomaly`{kind=data_retry} telemetry event plus a log line.
    Module-level (not an inline closure) so the wiring is unit-testable
    against the real payload shape — the dataset swallows sink
    exceptions by design, which would otherwise hide an argument
    mismatch here forever. `cur_step` is the loop's mutable
    latest-step cell; the stamp is approximate by design (the retry
    happens BETWEEN steps on the producer thread)."""
    def sink(**fields):
        kind = fields.pop("kind", "data_retry")
        tel.emit("anomaly", step=cur_step["step"] + 1, kind=kind,
                 loss=None, ema=None, zscore=None, **fields)
        log.warning(
            f"data retry #{fields.get('attempt')}: "
            f"{fields.get('error')} (backing off "
            f"{fields.get('backoff_s')}s)")
    return sink


def resolve_resume_from(args) -> None:
    """Verify `--resume_from` against its integrity lineage BEFORE any
    load touches it (DESIGN.md §20 verify-on-load contract): the
    checksum manifest of the named checkpoint (+ .opt sidecar) is
    recomputed; a corrupt/truncated/stale file makes the resolution
    FALL BACK down `<path>.lineage.json` to the newest verified entry
    instead of crashing — or worse, silently loading garbage into a
    run. args.resume_from is REWRITTEN to the resolved path (all
    downstream loads — adapter/model file and the opt sidecar — then
    agree on the same artifact), and the per-candidate ckpt_verify
    verdicts are stashed on args for run_training to emit right after
    run_start (the stream's first event must stay run_start). Shared
    by all four train CLIs so the fallback rule cannot drift."""
    path = getattr(args, "resume_from", "")
    if not path:
        return
    if os.path.isdir(path):
        # an HF checkpoint DIRECTORY (full-FT resume source): external
        # HF artifacts carry no per-file manifests and there is no
        # lineage to fall back down — load as before
        return
    from mobilefinetuner_tpu.io.checkpoints import resolve_checkpoint
    resolved, _step, events = resolve_checkpoint(
        path, verify=bool(getattr(args, "verify_ckpt", 1)))
    args._ckpt_verify_events = events
    if resolved != path:
        log.warning(f"--resume_from {path} failed integrity "
                    f"verification; falling back down the lineage to "
                    f"{resolved}")
        args.resume_from = resolved
    elif events and not events[0]["ok"]:
        log.warning(f"--resume_from {path}: {events[-1]['reason']} "
                    f"(loading unverified — no verified lineage "
                    f"alternative)")


def offload_rung_state(args, params, mesh):
    """The degradation ladder's offload-rung POLICY, shared by the two
    LoRA CLIs so it cannot fork: force host offload at the streams-only
    budget (whole-fetch leaves stay resident, [L,...] stacks stream per
    layer) plus remat (streaming requires a remat'd scan body), then
    re-place the frozen base through the CLI's own setup path. Returns
    the new (params, fetch_fn, offload_arg) — or None when offload is
    already on (nothing left to give back). The caller rebinds its
    closure cells and hands (new_params, loss_fn) to run_training."""
    if args.shard_enable:
        return None
    from mobilefinetuner_tpu.parallel.offload import streams_only_budget
    args.shard_enable = True
    args.remat = True
    args.shard_budget_mb = max(
        int(streams_only_budget(params)) // 2 ** 20, 1)
    return setup_frozen_params(args, params, mesh)


def preflight_eval_compile(make_compiled, args, tel, what="eval step",
                           compiled_of=lambda out: out):
    """Run an eval CLI's AOT compile UNDER the admission contract
    (DESIGN.md §21): a RESOURCE_EXHAUSTED from the compiler itself is
    an admission verdict, not an unnamed crash — it lands as
    mem_check{verdict=over, phase=compile} plus a schema-valid run_end
    before the named MemoryAdmissionError raises (fleet tooling must
    read an inadmissible eval config, not a crashed host). On success
    the result is preflighted as usual. `make_compiled` is the compile
    thunk; `compiled_of` extracts the compiled executable from its
    return value (identity by default — eval_mmlu's factory returns a
    (logits_fn, compiled) pair)."""
    from mobilefinetuner_tpu.core import memory_guard as mg
    try:
        out = make_compiled()
    except Exception as e:
        if not mg.is_resource_exhausted(e):
            raise
        cap, src = mg.device_capacity_mb(getattr(args, "hbm_cap_mb", 0))
        check = mg.MemCheck(
            est_mb=None, cap_mb=cap, verdict="over", phase="compile",
            headroom=getattr(args, "hbm_headroom", 0.1), cap_source=src)
        tel.emit("mem_check", **check.event())
        tel.emit("run_end", steps=0, wall_s=0.0,
                 exit="MemoryAdmissionError", goodput=None)
        tel.close()
        raise mg.MemoryAdmissionError(
            f"{what} failed memory admission at compile: {e}",
            check=check) from e
    preflight_compiled_eval(compiled_of(out), args, tel, what=what)
    return out


def preflight_compiled_eval(compiled, args, tel, what="eval step"):
    """Admission preflight for an eval CLI's compiled forward
    (DESIGN.md §21): the same mem_check the train path emits, minus
    the degradation ladder (eval has no remat/accum/offload levers, so
    --on_oom_risk degrade behaves like warn here). Under 'fail' an
    over verdict terminates the stream with a schema-valid run_end and
    raises the named MemoryAdmissionError — before the eval data loop
    starts."""
    from mobilefinetuner_tpu.core import memory_guard as mg
    check = mg.preflight(compiled, cap_mb=getattr(args, "hbm_cap_mb", 0),
                         headroom=getattr(args, "hbm_headroom", 0.1))
    tel.emit("mem_check", **check.event())
    if check.verdict != "over":
        return check
    if getattr(args, "on_oom_risk", "warn") == "fail":
        tel.emit("run_end", steps=0, wall_s=0.0,
                 exit="MemoryAdmissionError", goodput=None)
        tel.close()
        raise mg.MemoryAdmissionError(
            f"{what} failed memory admission ({check.describe()})",
            check=check)
    log.warning(f"memory admission ({what}): {check.describe()} "
                f"(proceeding)")
    return check


def record_ckpt_files(args, final_path: str, step: int, files) -> None:
    """Write-hook tail shared by the train CLIs: record a completed
    save into `<final_path>.lineage.json` and GC past --keep_ckpts
    (io/checkpoints.record_checkpoint — lineage publishes atomically
    BEFORE any unlink, so a kill mid-GC never strands the retained
    set). Runs on the async writer thread; failures are logged, not
    raised (a lineage bookkeeping error must not fail the save whose
    files are already durable)."""
    try:
        from mobilefinetuner_tpu.io.checkpoints import record_checkpoint
        record_checkpoint(final_path, step, list(files),
                          keep=max(getattr(args, "keep_ckpts", 0), 0))
    except Exception as e:
        log.warning(f"checkpoint lineage update failed: {e}")


def make_rollback_loader(tc: TrainConfig, mask, load_trainable):
    """Build run_training's `load_hook(path) -> (trainable_host,
    opt_state_host)` from a CLI's trainable loader. `load_trainable`
    maps a checkpoint path to the host trainable tree (the adapter for
    the LoRA CLIs, the full param tree for full FT); the Adam sidecar
    at `<path>.opt` is restored to HOST numpy against an abstract
    template (no device allocation — the caller places both trees at
    THIS run's mesh, reusing the elastic-resume machinery)."""
    from mobilefinetuner_tpu.optim import adam as adam_mod

    def load_hook(path):
        tr_h = load_trainable(path)
        template = jax.eval_shape(
            lambda t: init_optimizer(t, tc, mask), tr_h)
        opt_h, _ = adam_mod.load_state(path + ".opt", template,
                                       to_host=True)
        return tr_h, opt_h
    return load_hook


def parse_train_inject(spec: str):
    """--inject grammar -> (kind, step, n) | ('ckpt_corrupt', None, 1)
    | ('hbm_pressure', None, <mb>) | None. Shared validation so a typo
    dies at startup, not at the injection step. slow_step's third slot
    is the sleep in ms (the FaultInjector re-reads it); its optional
    FOURTH slot is the repeat count."""
    if not spec:
        return None
    parts = spec.split(":")
    kind = parts[0]
    if kind == "ckpt_corrupt":
        return ("ckpt_corrupt", None, 1)
    if kind == "hbm_pressure":
        if len(parts) < 2:
            raise SystemExit(f"--inject hbm_pressure needs a ballast "
                             f"size in MB: {spec!r}")
        return ("hbm_pressure", None, max(int(parts[1]), 1))
    if kind == "slow_step":
        # host-side straggler step(s): sleep <ms> before dispatching
        # step(s) >= <step> — the sensor food for --auto_profile's
        # slow-step trigger and the straggler/latency-tail harness
        # (the serve-side twin is serve_bench --inject slow_step)
        if len(parts) < 3:
            raise SystemExit(f"--inject slow_step needs a step and ms: "
                             f"slow_step:<step>:<ms>[:<n>], got {spec!r}")
        ms = float(parts[2])  # validated here, stored by the injector
        if not (ms >= 0) or math.isinf(ms):  # `not >=` catches NaN too
            raise SystemExit(f"--inject slow_step ms must be a finite "
                             f"non-negative number, got {parts[2]!r}")
        n = int(parts[3]) if len(parts) > 3 else 1
        return ("slow_step", int(parts[1]), max(n, 1))
    if kind not in ("grad_nan", "loss_spike"):
        raise SystemExit(
            f"--inject must be grad_nan:<step>[:<n>] | "
            f"loss_spike:<step>[:<n>] | slow_step:<step>:<ms>[:<n>] | "
            f"ckpt_corrupt | hbm_pressure:<mb>, got {spec!r}")
    if len(parts) < 2:
        raise SystemExit(f"--inject {kind} needs a step: {spec!r}")
    step = int(parts[1])
    n = int(parts[2]) if len(parts) > 2 else 1
    return (kind, step, max(n, 1))


class FaultInjector:
    """Host-side numerical-fault injection for the train path (the
    r13/r14 --inject pattern): poisons step BATCHES on the input side —
    a NaN `grad_scale` row multiplies the accumulated gradients INSIDE
    the compiled step (genuinely non-finite grads through the real
    backward), scrambled labels drive a real loss level-shift — so the
    skip/rollback machinery is exercised end to end, not simulated.
    Each fault fires ONCE per process (latched by a fired counter):
    after a rollback replays the poisoned window, the same steps run
    clean — the recovery, not the fault, repeats."""

    def __init__(self, spec: str):
        parsed = parse_train_inject(spec)
        self.kind, self.at, self.n = parsed if parsed else (None, None, 0)
        self.fired = 0
        self.ballast = None  # hbm_pressure: the held device allocation
        self.slow_ms = (float(spec.split(":")[2])
                        if self.kind == "slow_step" else 0.0)

    def maybe_slow(self, step: int) -> None:
        """slow_step:<step>:<ms>[:<n>]: a host-side sleep before the
        dispatch of n consecutive steps from <step> — a real straggler
        step the flush-interval timing (and therefore the slow-step
        sensor, the straggler window, and the watchdog median) sees,
        without doctoring any metric."""
        if self.kind != "slow_step" or self.fired >= self.n \
                or step < self.at:
            return
        self.fired += 1
        log.warning(f"--inject slow_step: sleeping {self.slow_ms:.0f} ms "
                    f"before step {step} ({self.fired}/{self.n})")
        time.sleep(self.slow_ms / 1000.0)

    @property
    def active(self) -> bool:
        return self.kind is not None

    def arm_ballast(self) -> None:
        """hbm_pressure:<mb>: allocate and HOLD <mb> MB on the default
        device BEFORE the step compiles. On TPU that shrinks real free
        HBM, so compile/first-dispatch hits a genuine
        RESOURCE_EXHAUSTED when the config was near the ceiling; the
        allocation also lands in memory_stats bytes_in_use, so the
        preflight's live-bytes term sees it on any backend that
        reports stats."""
        if self.kind != "hbm_pressure" or self.ballast is not None:
            return
        mb = self.n
        self.ballast = jax.device_put(
            np.zeros(mb * 2 ** 20 // 4, np.float32))
        # graftlint: disable=sync-hazard(fault injection: the ballast must be resident before the next compile)
        self.ballast.block_until_ready()
        log.warning(f"--inject hbm_pressure: holding {mb} MB of device "
                    f"ballast")

    def maybe_oom_dispatch(self, step: int) -> None:
        """The dispatch half of hbm_pressure on backends that cannot
        genuinely exhaust device memory (CPU grows the host heap
        instead): raise ONE simulated RESOURCE_EXHAUSTED at the first
        dispatch, so the ladder's caught-at-dispatch recovery path is
        exercised end to end. On real accelerators (TPU/GPU) this is a
        no-op — the held ballast produces the real thing."""
        if self.kind != "hbm_pressure" or self.fired \
                or jax.default_backend() in ("tpu", "gpu", "cuda",
                                             "rocm"):
            return  # real accelerators: the held ballast OOMs for real
        self.fired = 1
        raise RuntimeError(
            f"RESOURCE_EXHAUSTED: simulated OOM at dispatch of step "
            f"{step} (--inject hbm_pressure:{self.n} on a backend "
            f"that cannot genuinely exhaust device memory)")

    def maybe_poison(self, step: int, batch: dict) -> dict:
        if self.kind == "grad_nan":
            # EVERY batch carries the [B] grad_scale row while armed
            # (batch structure must be constant for the AOT-compiled
            # step); only the poison window carries NaN
            batch = dict(batch)
            poison = self.fired < self.n and step >= self.at
            if poison:
                self.fired += 1
            batch["grad_scale"] = np.full(
                batch["input_ids"].shape[0],
                np.nan if poison else 1.0, np.float32)
            if poison:
                log.warning(f"--inject grad_nan: NaN grads for step "
                            f"{step} ({self.fired}/{self.n})")
            return batch
        if self.kind == "loss_spike" and self.fired < self.n \
                and step >= self.at:
            self.fired += 1
            # misaligned labels = a REAL loss level-shift through the
            # actual forward, not a doctored metric
            batch = dict(batch)
            batch["labels"] = np.roll(batch["labels"], 7, axis=-1)
            log.warning(f"--inject loss_spike: scrambled labels for "
                        f"step {step} ({self.fired}/{self.n})")
        return batch

    def maybe_corrupt_ckpt(self, ckpt_path: str) -> bool:
        """ckpt_corrupt: flip one payload byte in the newest lineage
        checkpoint (once). Returns True when it fired."""
        if self.kind != "ckpt_corrupt" or self.fired:
            return False
        from mobilefinetuner_tpu.io.checkpoints import lineage_entries
        entries = lineage_entries(ckpt_path)
        if not entries:
            return False
        victim = entries[0]["files"][0]
        try:
            with open(victim, "r+b") as f:
                f.seek(-1, os.SEEK_END)
                b = f.read(1)
                f.seek(-1, os.SEEK_END)
                f.write(bytes([b[0] ^ 0xFF]))
        except OSError as e:
            log.warning(f"--inject ckpt_corrupt failed: {e}")
            return False
        self.fired = 1
        log.warning(f"--inject ckpt_corrupt: flipped a byte in {victim}")
        return True


class EMA:
    """EMA-smoothed loss (CmdArgs ema_beta, default 0.9)."""

    def __init__(self, beta: float):
        self.beta = beta
        self.value: Optional[float] = None

    def update(self, x: float) -> float:
        self.value = x if self.value is None else \
            self.beta * self.value + (1 - self.beta) * x
        return self.value


def run_training(args, *, trainable, frozen, loss_fn, nll_fn,
                 train_ds: WikiText2Dataset,
                 valid_ds: Optional[WikiText2Dataset],
                 total_steps: int, tc: TrainConfig,
                 mask=None, start_step: int = 0, opt_state=None,
                 save_hook: Optional[Callable] = None,
                 mesh=None, replicate_trainable: bool = True,
                 dropout_rng=None, step_builder=None,
                 flops_per_step: Optional[float] = None,
                 load_hook: Optional[Callable] = None,
                 ckpt_path: str = "",
                 degrade_builders: Optional[dict] = None):
    """The shared optimizer-step loop: compiled step + eval cadence + EMA +
    metrics CSV + JSONL eval records + governor throttle + periodic saves
    + the run-telemetry event stream (--telemetry_out, core/telemetry.py).

    save_hook(step, trainable, opt_state, final, ckpt=None) persists
    checkpoints: the hook snapshots its trees to host (blocking, batched
    — io/async_ckpt.timed_snapshot) and routes the disk write through
    `ckpt` (async_ckpt.submit), which under --async_save runs it on a
    background thread so the step loop resumes after the snapshot.
    dropout_rng: base PRNG key; when set, a fresh per-sample key array
    folded with the step index rides in batch["dropout_rng"], so dropout
    masks differ across steps AND micro-batches (a fixed closure key would
    silently reuse one mask for the whole run).
    flops_per_step: the CLI's analytic transformer_flops estimate for ONE
    optimizer step — drives the in-loop MFU in the log line, the CSV, and
    step_stats (None: MFU omitted).
    load_hook(path) -> (trainable_host, opt_state_host) is the INVERSE
    of save_hook (make_rollback_loader builds it): with it, `ckpt_path`
    (the run's final artifact, whose .lineage.json tracks the
    step-tagged last-known-good set) and --rollback_budget > 0, the
    loop closes the SpikeDetector loop in-process — on sustained
    divergence / a skipped-step streak / nonfinite loss it reloads the
    newest VERIFIED lineage checkpoint at this run's mesh, rebuilds the
    data stream (byte-pinned skip_steps + --rollback_data_offset), and
    keeps training with the SAME compiled step (DESIGN.md §20).
    degrade_builders: the CLI's hooks for the memory-admission
    degradation ladder (DESIGN.md §21). The step is AOT-compiled BEFORE
    the data stream exists (a zero probe batch with the stream's exact
    shapes/placement) and preflighted against device capacity
    (core/memory_guard.py); under --on_oom_risk=degrade a failed
    admission — or a RESOURCE_EXHAUSTED caught at compile/first
    dispatch — walks remat -> accum_x2 -> offload, recompiling and
    re-preflighting at each rung. The remat rung flips args.remat
    (every CLI's loss closure reads it at trace time); accum_x2 doubles
    tc.grad_accum_steps for the STEP only (the stream keeps assembling
    the original global batch, so batch shapes/shardings never change);
    the "offload" key of degrade_builders, when provided, is
    `() -> (new_frozen, loss_fn) | None` — it re-places the frozen base
    with host offload enabled (None: not applicable / already on).
    Returns (trainable, opt_state, last_metrics).
    """
    from mobilefinetuner_tpu.parallel.distributed import (allgather_scalars,
                                                          device_put_global,
                                                          gather_to_host,
                                                          is_coordinator)
    # multi-host: every process runs the identical compiled step over global
    # arrays; the CSV/JSONL/checkpoint sinks write once, on process 0.
    # TELEMETRY writes on every process — the coordinator to the given
    # path, host k to PATH.host<k>, each record host-stamped — so a
    # stalled worker leaves evidence instead of silently dropping events
    # (merge with tools/fleet_report.py). Saving first gathers
    # cross-process-sharded trees to host on EVERY process
    # (gather_to_host is collective), then only process 0 writes.
    coord = is_coordinator()
    multiproc = jax.process_count() > 1
    tel = Telemetry.for_process(getattr(args, "telemetry_out", ""))
    tel.emit("run_start", **run_manifest(vars(args), mesh))
    # run registry (core/run_registry.py, DESIGN.md §28): one durable
    # record per run, coordinator-only (one run, one record — the
    # per-host shards already carry the host story). The start record
    # flushes immediately, so a SIGKILL mid-run is settled to
    # "interrupted" on the next registry open; finalize rides end_run,
    # the same single-exit path run_end uses.
    import sys as _sys
    from mobilefinetuner_tpu.core.run_registry import RunRegistry
    _registry = RunRegistry.from_args(args) if coord else None
    run_rec = _registry.begin(
        "train", os.path.basename(_sys.argv[0] or "train").replace(
            ".py", ""),
        config=vars(args), mesh=dict(mesh.shape) if mesh is not None
        else None,
        platform=jax.devices()[0].platform,
        artifacts=[p for p in (tel.path,
                               getattr(args, "out", "")) if p],
        telemetry=tel) if _registry else None
    # --resume_from integrity verdicts (resolve_resume_from ran in the
    # CLI, BEFORE this stream existed): emitted here so the acceptance
    # contract — a corrupted newest checkpoint resolves down the
    # lineage WITH ckpt_verify evidence in the run's own stream — holds
    # while run_start stays the stream's first event of the run.
    for _ev in getattr(args, "_ckpt_verify_events", None) or []:
        tel.emit("ckpt_verify", **_ev)
    t_start = time.time()
    # span tracing (--trace_spans, core/trace.py): ONE tracer threaded
    # to every producer — the goodput meter's phase track, the async
    # checkpoint writer, the prefetch producer — all emitting `span`
    # events into the same stream tools/trace_export.py converts
    from mobilefinetuner_tpu.core.trace import AutoProfiler, Tracer
    tracer = (Tracer(tel.emit)
              if getattr(args, "trace_spans", 0) and tel.enabled
              else None)
    # wall-clock bucket accounting over run_training's whole span; the
    # buckets sum to run_end.wall_s by construction (DESIGN.md §14);
    # under --trace_spans every phase segment also lands as a span, so
    # the exported timeline reconciles with the buckets structurally
    meter = GoodputMeter(tracer=tracer)
    done_steps = 0
    governor = None  # assigned in setup; end_run late-binds the local
    wd = None        # assigned in setup; the outer finally stops it
    ckpt = None      # async checkpointer; end_run drains it
    guard = None     # preemption guard; the outer finally uninstalls it
    metrics_srv = None  # live /metrics endpoint; outer finally closes it
    auto_prof = None    # anomaly-triggered profiler; ditto

    def end_run(exit_name: str, steps: int, **extra_fields):
        """Terminate the stream exactly once on any exit path: run_end
        carries the goodput buckets (plus the governor's own run-total
        sleep counter — an independently-clocked cross-check of the
        meter's governor_sleep bucket); emit/close no-op on a closed
        stream, so nested handlers compose without double emission.
        The async checkpoint writer is drained FIRST: a snapshot already
        taken is a recovery point worth finishing even when the loop
        died, and its checkpoint event must land before run_end closes
        the stream (write errors are swallowed here — they must not
        mask the exception that brought us down)."""
        if ckpt is not None:
            ckpt.close(raise_errors=False)
        # a capture still open at exit must land its profile_capture
        # event BEFORE run_end closes the stream (emit on a closed
        # stream is a hard no-op — the on-disk trace would lose its
        # pointer); the outer finally's close() is then idempotent
        if auto_prof is not None:
            auto_prof.close()
        extra = dict(extra_fields)
        if governor is not None:
            extra["governor_slept_ms"] = round(governor.total_slept_ms, 1)
        # finalize the registry record BEFORE run_end: the mirrored
        # `run` end event must land inside the run's own stream, and
        # run_end must stay the stream's LAST event (the r13 controller
        # keys restart decisions off it); finalize is idempotent, so
        # nested handlers compose exactly like emit/close do
        if run_rec is not None:
            run_rec.finalize(exit_name)
        tel.emit("run_end", steps=steps,
                 wall_s=round(time.time() - t_start, 3),
                 exit=exit_name, goodput=meter.summary(), **extra)
        tel.close()
    # EVERYTHING after run_start runs under one handler: a setup
    # failure (device placement OOM, stream construction) must still
    # terminate the stream with run_end{exit: <type>} — emit/close
    # are no-ops once the stream is closed, so the inner handlers
    # (loop, post-loop tail) and this outer one compose without
    # double emission.
    try:
        governor = governor_from_args(
            args, event_sink=lambda p: tel.emit("throttle", **p))
        # live OpenMetrics endpoint (--metrics_port, DESIGN.md §22):
        # the registry attaches as a telemetry OBSERVER — every number
        # a scraper reads came through the same emit call the JSONL
        # sink wrote, and the registry never touches a device (it has
        # no jax import to touch one with). Coordinator-only: one
        # endpoint per run, like the CSV/JSONL sinks. A bind failure
        # raises HERE, before data loading, under the run_end contract.
        if getattr(args, "metrics_port", 0) > 0 and coord:
            from mobilefinetuner_tpu.core.metrics_http import \
                start_metrics
            metrics_srv = start_metrics(
                tel, args.metrics_port,
                addr=getattr(args, "metrics_addr", "127.0.0.1"))
            log.info(f"metrics endpoint: http://{metrics_srv.addr}:"
                     f"{metrics_srv.port}/metrics (+ /healthz)")
        # anomaly-triggered profiler capture (--auto_profile, DESIGN.md
        # §22): a one-shot jax.profiler capture armed by the sensors —
        # slow step, loss spike/divergence, straggler, hang pre-exit —
        # under a budget and cooldown; each capture is a
        # `profile_capture` event pointing at the trace on disk
        if getattr(args, "auto_profile", 0):
            prof_root = getattr(args, "auto_profile_dir", "") or \
                ((tel.path + ".profiles") if tel.path
                 else "auto_profile_traces")
            auto_prof = AutoProfiler(
                prof_root, sink=tel.emit,
                steps=getattr(args, "auto_profile_steps", 2),
                cooldown_s=getattr(args, "auto_profile_cooldown", 300.0),
                budget=getattr(args, "auto_profile_budget", 2))
        slow_mult = getattr(args, "auto_profile_slow_mult", 3.0)
        # preemption drain (core/preempt.py, DESIGN.md §18): SIGTERM/
        # SIGINT flips a flag the loop checks at every step boundary —
        # finish the step, one final atomic save, run_end{reason=
        # preempted}, exit EXIT_PREEMPTED. Main-thread only (signal
        # semantics); embedded runs degrade to default behavior.
        if getattr(args, "on_preempt", "drain") == "drain":
            guard = PreemptionGuard().install()
            if not guard.installed:
                guard = None
        # streaming-data retry telemetry: the datasets' bounded-retry
        # refetch (data/wikitext2.py _io_retry) reports each survived
        # I/O error as an anomaly{kind=data_retry} through this sink.
        # cur_step is the loop's latest dispatched step — the producer
        # thread runs a batch or two ahead, so the stamp is approximate
        # by design (the retry has no exact step; it happens BETWEEN
        # steps on the producer side).
        cur_step = {"step": start_step}
        _data_retry_sink = make_data_retry_sink(tel, cur_step)
        for _ds in (train_ds, valid_ds):
            if _ds is not None and getattr(_ds, "event_sink", None) is None:
                _ds.event_sink = _data_retry_sink
        # snapshot-then-write checkpointing (io/async_ckpt.py): the save
        # hooks snapshot on the loop thread (blocking, batched D2H) and
        # hand the disk write to this checkpointer's background thread;
        # --async_save 0 is the synchronous oracle (same writer, inline).
        # The checkpointer emits the `checkpoint`/`ckpt_dropped` events
        # itself — including from its writer thread; Telemetry.emit is
        # lock-serialized — so the blocking/background split is recorded
        # where it is measured.
        from mobilefinetuner_tpu.io.async_ckpt import AsyncCheckpointer
        ckpt = AsyncCheckpointer(
            enabled=bool(getattr(args, "async_save", 1)),
            event_sink=tel.emit, tracer=tracer)
        spikes = SpikeDetector(SpikeConfig(
            zscore=getattr(args, "spike_z", 8.0),
            beta=getattr(args, "spike_beta", 0.98),
            warmup=getattr(args, "spike_warmup", 20)))
        if tel.resumed and tel.trailing_step_stats and start_step > 0:
            # crash/resume: re-seed the detector from the prior run's
            # flushed losses so it does not re-enter warmup and miss a
            # spike in the first post-resume steps (the exact window
            # where resume bugs bite). Gated on an ACTUAL checkpoint
            # resume (start_step > 0): a fresh run that merely reuses a
            # telemetry path must keep its warmup, or its legitimately
            # wild early losses fire against the old run's statistics
            fed = spikes.seed(
                [r.get("loss") for r in tel.trailing_step_stats],
                count_hint=max(r.get("step", 0)
                               for r in tel.trailing_step_stats))
            log.info(f"spike detector re-seeded from {fed} resumed "
                     f"step_stats (armed={spikes.count >= spikes.config.warmup})")
        # hang watchdog (--watchdog 0 disables): fires when no step
        # completes within watchdog_mult x rolling-median step time,
        # dumps all thread stacks + emits a `hang` event, then keeps
        # waiting (1) or aborts the process (2)
        wd_mode = getattr(args, "watchdog", 1)
        if wd_mode:
            wd = HangWatchdog(
                mult=getattr(args, "watchdog_mult", 10.0),
                min_deadline_s=getattr(args, "watchdog_min_s", 60.0),
                # the grace honors the flag exactly (its documented
                # meaning): compile no longer needs a padded grace —
                # the compile block suspends the clock
                grace_s=getattr(args, "watchdog_min_s", 60.0),
                stacks_file=(tel.path + ".stacks") if tel.path else "",
                abort=wd_mode == 2,
                # before an abort's os._exit(113): flush + newline-
                # terminate the stream so the shard a post-mortem reads
                # ends with the complete hang record, not a truncated
                # line (the flush serializes against any emit mid-write
                # on the step loop's thread)
                flush_fn=tel.flush_tail,
                # graftlint: disable=sync-hazard(the watchdog's device probe IS a deliberate sync, off the step loop's thread)
                probe_fn=lambda: jax.device_put(
                    jnp.zeros(())).block_until_ready(),
                on_hang=lambda p: (
                    # pre-exit flight recorder: grab the device trace
                    # of the wedged state BEFORE a --watchdog 2 abort
                    # can os._exit (bounded hold; never raises)
                    (auto_prof.capture_now("hang", p["step"])
                     if auto_prof is not None else None),
                    tel.emit("hang", last_seq=tel.last_seq, **p),
                    log.error(
                        f"HANG: no step for {p['stall_s']:.1f}s "
                        f"(deadline {p['deadline_s']:.1f}s) after step "
                        f"{p['step']}; stacks -> {p['stacks_file']}, "
                        f"device probe: {p['device_probe']}, "
                        f"action: {p['action']}")))
        # wd.paused() as a with-block at every known long pause
        # (compile, eval, checkpoint): the deadline clock stops — such
        # a pause may exceed any step-derived deadline — and the resume
        # cannot be forgotten. No-op context when the watchdog is off.
        pause = wd.paused if wd is not None else contextlib.nullcontext
        # straggler attribution: every straggler_cadence steps each host
        # gathers its median step time (collective, deterministic
        # cadence); the per-host map lands in step_stats.host_step_ms
        # and outliers raise `straggler` events (coordinator-side)
        strag_k = max(getattr(args, "straggler_cadence", 0), 0)
        strag_mult = getattr(args, "straggler_mult", 1.5)
        step_clock = StepClock()
        host_step_ms = {"latest": None}
        # flops_per_step covers the GLOBAL batch, so the MFU denominator is
        # the GLOBAL peak: per-chip peak × every device in the run (a
        # single-chip run is unchanged; an 8-chip run divided by one chip's
        # peak would report 8× the true utilization)
        peak_flops = device_peak_flops() * len(jax.devices())
        metrics_csv = MetricsLogger(args.metrics_csv) \
            if args.metrics_csv and coord else None
        eval_jsonl = JSONLWriter(args.eval_out) \
            if getattr(args, "eval_out", "") and coord else None
        if save_hook is not None and multiproc:
            orig_save = save_hook

            # gather-then-coordinator-write, unchanged under async save:
            # the gather is COLLECTIVE (every process participates, and
            # its cost is part of the blocking snapshot the loop pays);
            # only the coordinator snapshots/queues the write, so the
            # background writer thread exists on one process
            def save_hook(step, tr, opt, final=False, ckpt=None):
                tr_h, opt_h = gather_to_host(tr), gather_to_host(opt)
                if coord:
                    orig_save(step, tr_h, opt_h, final=final, ckpt=ckpt)
        # the eval path must feed global arrays under multi-host (raw host
        # numpy cannot address a global mesh); single-process keeps the
        # uncommitted-numpy fast path
        eval_mesh = mesh if (mesh is not None and multiproc) else None
        eval_sp = getattr(args, "sequence_parallel", False)

        eval_step = make_eval_step(nll_fn)
        if opt_state is None:
            opt_state = init_optimizer(trainable, tc, mask)

        if mesh is not None and replicate_trainable:
            # LoRA-style tiny trainables: replicate A/B + Adam state; FSDP'd
            # trainables (full FT) arrive pre-placed and are left alone.
            repl = replicated_sharding(mesh)
            trainable = jax.tree.map(
                lambda x: device_put_global(x, repl), trainable)
            opt_state = jax.tree.map(
                lambda x: device_put_global(x, repl), opt_state)

        # step_builder: alternate step factory with make_train_step's contract
        # (the optimizer-offload path, optim/opt_offload.py, plugs in here).
        # On a mesh, the compiled step's trainable/opt OUTPUTS are pinned to
        # their INPUT shardings (metrics replicate): the loop runs ONE
        # AOT-compiled executable with donated buffers, and a compiler-chosen
        # output sharding that drifts from the input sharding would make the
        # very next call reject its own donated outputs (seen on the
        # (1,N)-mesh full-FT path: replicated bias inputs came back
        # fsdp-sharded). Pinning makes the step a sharding fixed point by
        # construction. The offload step_builder manages its own placements.
        out_shardings = None
        if mesh is not None and step_builder is None:
            from jax.sharding import NamedSharding
            from mobilefinetuner_tpu.parallel.mesh import params_shardings
            tr_on_mesh = all(
                isinstance(getattr(x, "sharding", None), NamedSharding)
                and x.sharding.mesh == mesh
                for x in jax.tree.leaves(trainable))
            if tr_on_mesh:
                # trainable: keep exactly its input shardings. opt m/v:
                # the same FSDP RULE as the params (a fresh
                # init_optimizer's eager zeros sit uncommitted on one
                # device — their .sharding is not the intent; a resumed
                # tree arrives via place_opt_state, which IS this rule).
                repl = replicated_sharding(mesh)
                out_shardings = (
                    jax.tree.map(lambda x: x.sharding, trainable),
                    repl if replicate_trainable
                    else params_shardings(opt_state, mesh),
                    repl)  # prefix: every metrics leaf replicates
        # the degradation ladder (DESIGN.md §21) may REBUILD the step:
        # loss_fn is re-traced (the CLIs' loss closures read args.remat
        # and their offload cell at trace time) and tc_step carries the
        # accum_x2 rung's doubled micro-batch count. The STREAM keeps
        # the original tc.grad_accum_steps — the step batch is the
        # constant global batch either way, so batch shapes and
        # shardings never change across rungs and neither do the
        # donation/output-sharding pins above.
        tc_step = tc

        def build_step():
            if step_builder is not None:
                return step_builder(loss_fn, tc_step, mask=mask,
                                    donate=True)
            return make_train_step(loss_fn, tc_step, mask=mask,
                                   donate=True,
                                   out_shardings=out_shardings)

        step_fn = build_step()

        def place_state(tr_h, opt_h):
            """Host trees -> this run's mesh placement (the r13
            elastic-resume rule: replicate LoRA-style trainables, FSDP
            re-shard otherwise) — ONE helper shared by the rollback
            reload and the dispatch-OOM retry so the placement rule
            cannot fork."""
            if mesh is not None and replicate_trainable:
                repl = replicated_sharding(mesh)
                put = lambda x: device_put_global(jnp.asarray(x), repl)
                return jax.tree.map(put, tr_h), jax.tree.map(put, opt_h)
            if mesh is not None:
                from mobilefinetuner_tpu.parallel.mesh import shard_params
                return shard_params(tr_h, mesh), shard_params(opt_h, mesh)
            return (jax.tree.map(jnp.asarray, tr_h),
                    jax.tree.map(jnp.asarray, opt_h))

        ema = EMA(args.ema_beta)
        # async input pipeline: micro-batch assembly (tokenization, streaming
        # refetch, accum fill) runs in a background producer thread; dropout
        # keys + device placement are issued one batch AHEAD on the consumer
        # side, so batch k+1's host->HBM transfer overlaps step k's compute.
        # --prefetch 0 collapses to the synchronous path (same interface,
        # byte-identical batch sequence).
        prefetch_depth = max(getattr(args, "prefetch", 2), 0)
        sp = getattr(args, "sequence_parallel", False)
        place_batch = make_batch_placer(mesh, sp)

        # fault-injection harness (--inject, DESIGN.md §20): batches are
        # poisoned on the HOST side inside place_step — before dropout
        # keys and device placement — so the injected fault flows
        # through the real compiled forward/backward
        injector = FaultInjector(getattr(args, "inject", ""))

        def place_step(item):
            step, epoch, batch = item
            if injector.active:
                batch = injector.maybe_poison(step, batch)
            if dropout_rng is not None:
                nb = batch["input_ids"].shape[0]
                batch["dropout_rng"] = jax.random.split(
                    jax.random.fold_in(dropout_rng, step), nb)
            return step, epoch, place_batch(batch)

        def make_stream(from_step: int, data_skip: int) -> Prefetcher:
            """The numbered, placed step-batch stream from `from_step`.
            `data_skip` is the byte-pinned fast-forward in STEPS —
            normally == from_step (resume continues the exact data
            order); a rollback passes from_step + k*rollback_data_offset
            to diverge the replayed window's batch sequence. max(..., 0):
            a resume at/after total_steps runs zero steps (the loop
            below is empty) and must not build a stream at all."""
            def numbered():
                gen = micro_batches(train_ds, tc.grad_accum_steps,
                                    skip_steps=data_skip)
                for step in itertools.count(from_step):
                    epoch, batch = next(gen)
                    yield step, epoch, batch
            return Prefetcher(
                itertools.islice(numbered(),
                                 max(total_steps - from_step, 0)),
                depth=prefetch_depth, place_fn=place_step, lookahead=1,
                rss_limit_mb=getattr(args, "prefetch_rss_mb", 0),
                tracer=tracer)

        # ---- memory admission + degradation ladder (DESIGN.md §21) ------
        # The step is AOT-compiled HERE, from a zero probe batch with the
        # stream's exact shapes and placement — before the data stream
        # (and its producer thread) exists — so an inadmissible config
        # dies with a named error in seconds, and the `compile` event's
        # peak-HBM estimate is the SAME number the preflight judges.
        from mobilefinetuner_tpu.core import memory_guard as mg
        oom_mode = getattr(args, "on_oom_risk", "warn")
        adm_cap_mb = getattr(args, "hbm_cap_mb", 0)
        adm_headroom = getattr(args, "hbm_headroom", 0.1)
        compiled_step = None
        peak_hbm = {"mb": 0.0}     # from the compiled step's memory analysis
        rungs_applied: list = []
        oom_snap = None            # host insurance for the dispatch retry
        compile_err = {"e": None}  # original compile-time OOM (warn mode)

        def probe_batch():
            """The AOT compile's stand-in: zero arrays with exactly the
            step-batch rows the ORIGINAL accum assembles, run through
            the same place_step as real batches (injector grad_scale
            key, dropout keys, mesh placement) — the compiled
            executable serves the stream's batches unchanged."""
            b = train_ds.config.batch_size
            S = train_ds.config.seq_len
            rows = b * tc.grad_accum_steps
            zero = {"input_ids": np.zeros((rows, S), np.int32),
                    "attention_mask": np.zeros((rows, S), np.float32),
                    "labels": np.zeros((rows, S), np.int32)}
            # the probe must not CONSUME an injector fire (an --inject
            # grad_nan at the start step would otherwise spend one of
            # its n poisons on a batch that never trains): run the real
            # place_step for structural fidelity, then restore the latch
            fired = injector.fired
            placed = place_step((start_step, 0, zero))[2]
            injector.fired = fired
            return placed

        def over_check(phase: str) -> "mg.MemCheck":
            """A forced-over verdict for a REAL RESOURCE_EXHAUSTED (the
            estimate side is moot: the device already said no)."""
            cap, src = mg.device_capacity_mb(adm_cap_mb)
            return mg.MemCheck(est_mb=None, cap_mb=cap, verdict="over",
                               phase=phase, headroom=adm_headroom,
                               cap_source=src)

        def compile_and_check(at_step: int = start_step) -> "mg.MemCheck":
            """AOT-compile the current step and preflight it: one
            `compile` + one `mem_check` event per attempt (the ladder
            re-enters here after every rung). A RESOURCE_EXHAUSTED from
            the compiler itself IS an admission verdict, not a crash —
            it leaves compiled_step as None with the original error in
            compile_err (the warn-mode driver re-raises it: warn means
            'proceed anyway', and with no executable there is nothing
            to proceed WITH). The probe batch is built fresh per
            attempt and dropped with the frame: a full step batch of
            zeros must not sit in device memory for the whole run
            inside the very feature that budgets memory."""
            nonlocal compiled_step
            probe = probe_batch()
            meter.enter("compile")
            t_comp = time.perf_counter()
            try:
                with pause():
                    compiled_step = step_fn.lower(
                        trainable, frozen, opt_state, probe,
                        jnp.int32(start_step)).compile()
            except Exception as e:
                meter.enter("init")
                if not mg.is_resource_exhausted(e):
                    raise
                log.warning(f"RESOURCE_EXHAUSTED at compile: {e}")
                compiled_step = None
                compile_err["e"] = e
                c = over_check("compile")
                tel.emit("mem_check", **c.event())
                return c
            meter.enter("init")
            peak_hbm["mb"] = compiled_peak_mb(compiled_step)
            xla_flops = compiled_flops(compiled_step)
            # at_step: a mid-run ladder recompile (dispatch OOM) logs
            # at the step that forced it, aligned with its degrade/
            # mem_check neighbors — not back at start_step
            tel.emit("compile", step=at_step,
                     wall_s=round(time.perf_counter() - t_comp, 3),
                     flops=xla_flops or None,
                     peak_hbm_mb=peak_hbm["mb"] or None)
            c = mg.preflight(compiled_step, cap_mb=adm_cap_mb,
                             headroom=adm_headroom)
            tel.emit("mem_check", **c.event())
            if peak_hbm["mb"]:
                log.info(f"compiled step peak HBM: {peak_hbm['mb']:.0f} "
                         f"MB ({c.describe()})")
            return c

        def apply_rung(est_mb, at_step=None) -> bool:
            """Walk ONE rung of the bounded ladder (memory_guard.LADDER
            order: remat -> accum_x2 -> offload): mutate the config,
            emit a `degrade` event, and let the caller recompile.
            Returns False when no applicable rung remains."""
            nonlocal loss_fn, tc_step, frozen
            for rung in mg.LADDER:
                if rung in rungs_applied:
                    continue
                if rung == "remat":
                    if getattr(args, "remat", True):
                        continue  # already on: nothing left to give
                    # every CLI's loss closure reads args.remat at
                    # trace time — the flip lands at the recompile
                    args.remat = True
                    frm, to = "remat=off", "remat=on"
                elif rung == "accum_x2":
                    rows = (train_ds.config.batch_size
                            * tc.grad_accum_steps)
                    new_accum = tc_step.grad_accum_steps * 2
                    if new_accum > rows or rows % new_accum:
                        continue  # micro-batch cannot split further
                    import dataclasses as _dc
                    tc_step = _dc.replace(tc_step,
                                          grad_accum_steps=new_accum)
                    frm = f"accum={new_accum // 2}"
                    to = f"accum={new_accum}"
                else:  # offload
                    builder = (degrade_builders or {}).get("offload")
                    out = builder() if builder is not None else None
                    if out is None:
                        continue  # no offload path / already enabled
                    frozen, loss_fn = out
                    frm, to = "offload=off", "offload=on"
                rungs_applied.append(rung)
                tel.emit("degrade", step=at_step,
                         **{"rung": rung, "from": frm, "to": to,
                            "est_mb": (round(est_mb, 2) if est_mb
                                       else None)})
                log.warning(
                    f"DEGRADE rung {len(rungs_applied)} ({rung}: {frm} "
                    f"-> {to})"
                    + (f" — estimate {est_mb:.0f} MB over capacity"
                       if est_mb else "")
                    + "; recompiling")
                return True
            return False

        def recover_dispatch_oom(e: BaseException, step: int) -> None:
            """A RESOURCE_EXHAUSTED escaped the compiled step's
            dispatch: under --on_oom_risk=degrade walk the remaining
            ladder (recompile + re-preflight per rung), restore the
            donated trees from the host insurance snapshot, and let the
            loop retry the SAME batch — no process restart, no
            checkpoint touched, no rollback triggered. Re-raises when
            recovery is impossible (mode, no rungs left, or donated
            state unrecoverable on a real accelerator)."""
            nonlocal step_fn, trainable, opt_state, t_interval
            can_retry = (oom_snap is not None
                         or jax.default_backend() == "cpu")
            if oom_mode != "degrade" or not can_retry:
                raise e
            tel.emit("mem_check", **over_check("dispatch").event())
            log.warning(f"RESOURCE_EXHAUSTED at dispatch of step "
                        f"{step}: walking the degradation ladder")
            # settle the buffered steps first, then keep the recovery
            # wall OUT of the next flush's per-step average (the rule
            # the first-step compile block enforced and eval/save/
            # rollback all follow): an inflated sample here would feed
            # the watchdog deadline and the straggler window
            flush_metrics(emit_log=False)
            while True:
                if not apply_rung(peak_hbm["mb"] or None, at_step=step):
                    raise mg.MemoryAdmissionError(
                        f"RESOURCE_EXHAUSTED at dispatch of step {step} "
                        f"and the degradation ladder "
                        f"{tuple(rungs_applied)} is exhausted",
                        ladder=rungs_applied) from e
                step_fn = build_step()
                c = compile_and_check(at_step=step)
                if c.verdict != "over":
                    break
            if oom_snap is not None:
                trainable, opt_state = place_state(*oom_snap)
            t_interval = time.perf_counter()  # recompile ≠ step time

        if start_step < total_steps:
            injector.arm_ballast()
            check = compile_and_check()
            while check.verdict == "over" and oom_mode != "warn":
                if oom_mode == "fail":
                    raise mg.MemoryAdmissionError(
                        f"memory admission failed ({check.describe()}); "
                        f"rerun with a smaller config, --on_oom_risk "
                        f"degrade, or a larger device", check=check)
                if not apply_rung(check.est_mb):
                    raise mg.MemoryAdmissionError(
                        f"memory admission failed after exhausting the "
                        f"degradation ladder {tuple(rungs_applied)} "
                        f"({check.describe()})", check=check,
                        ladder=rungs_applied)
                step_fn = build_step()
                check = compile_and_check()
            if compiled_step is None:
                # warn mode with a compile-time RESOURCE_EXHAUSTED:
                # 'proceed anyway' has nothing to proceed with — the
                # honest outcome is the ORIGINAL compiler error, as
                # before round 16 (not a NoneType crash 30 lines later)
                raise compile_err["e"]
            if check.verdict == "over":
                log.warning(f"memory admission: {check.describe()} "
                            f"(--on_oom_risk warn: proceeding)")
            elif rungs_applied:
                log.warning(f"admitted after degradation ladder "
                            f"{tuple(rungs_applied)}: {check.describe()}")
            # dispatch-retry insurance: ONLY under armed pressure
            # injection keep a HOST copy of the donated trees until
            # the first step retires — a failed dispatch consumes
            # donated buffers on real accelerators, and the
            # retry-at-next-rung contract needs intact state to
            # re-place. In degrade mode this point is only reached
            # with verdict ok/unknown (an over verdict walked a rung
            # or raised), and neither justifies a whole-model
            # device_get per run: 'unknown' is EVERY run on platforms
            # without memory analysis. CPU ignores donation (retries
            # in place, no copy); multi-host skips it (device_get
            # cannot fetch cross-process shards — a pod-scale OOM is
            # the controller's problem, not an in-process retry).
            if (oom_mode == "degrade" and not multiproc
                    and jax.default_backend() != "cpu"
                    and injector.kind == "hbm_pressure"):
                # graftlint: disable=sync-hazard(OOM-retry insurance snapshot, armed only under a live admission-risk signal)
                oom_snap = jax.device_get((trainable, opt_state))

        stream = make_stream(start_step, start_step)
        # in-process rollback state (armed only when the CLI wired the
        # inverse load hook AND checkpoints exist to roll back to)
        rb = None
        if (load_hook is not None and ckpt_path
                and getattr(args, "rollback_budget", 0) > 0):
            rb = {"budget": int(args.rollback_budget), "count": 0,
                  "streak": 0, "due": None, "suppressed": False,
                  "skip_streak": max(
                      getattr(args, "rollback_skip_streak", 3), 1),
                  "offset": max(
                      getattr(args, "rollback_data_offset", 1), 0)}
        metrics = {}
        epoch = 0
        profile_dir = getattr(args, "profile_dir", "")
        prof_start = start_step + getattr(args, "profile_start", 10)
        prof_end = prof_start + getattr(args, "profile_steps", 5)
        prof_active = False

        def maybe_profile(step):
            nonlocal prof_active
            if not profile_dir:
                return
            try:
                if step == prof_start and not prof_active:
                    jax.profiler.start_trace(profile_dir)
                    prof_active = True
                elif step >= prof_end and prof_active:
                    if metrics:
                        # graftlint: disable=sync-hazard(profiler stop drains queued work so the trace window holds it)
                        jax.device_get(metrics["loss"])
                    jax.profiler.stop_trace()
                    prof_active = False
                    log.info(f"profiler trace -> {profile_dir}")
            except Exception as e:  # profiling must never kill training
                log.warning(f"profiler: {e}")
                prof_active = False

        # Per-step metrics stay on device; they are buffered and pulled to host
        # in ONE device_get per log boundary. An unconditional per-step
        # float(loss) would sync the dispatch queue every step and serialize
        # the pipeline (the reference has no such concern: it is synchronous
        # CPU code; on TPU async dispatch is the throughput lever).
        buffered = []  # [(step, epoch, tokens, device_metrics), ...]
        t_interval = time.perf_counter()
        slept_ms = 0.0  # governor sleep inside the interval, excluded from dt
        waited_ms = 0.0  # host-wait: step loop blocked on the input pipeline
        # flush cadence: the log interval; if step logging is off but a CSV was
        # requested, flush every 50 steps so rows survive a crash; 1000-step
        # hard cap bounds the device-metrics buffer in all cases.
        flush_every = (min(args.log_interval, 1000) if args.log_interval
                       else (50 if metrics_csv else 1000))

        def flush_metrics(emit_log=True):
            """One host sync for everything buffered since the last flush —
            the telemetry zero-sync invariant: the on-device health scalars
            (param_norm/update_ratio/nonfinite_count) ride the SAME
            device_get as loss/grad_norm/lr, so observability adds no syncs.
            Rows in a flush share the interval-averaged step_time_ms (per-step
            wall time under async dispatch measures only dispatch latency, so
            the average over a synced interval is the honest number) and
            host_wait_ms — the interval-averaged time the step loop spent
            BLOCKED pulling the next batch from the input pipeline (queue
            wait + lookahead placement; with the producer keeping up this is
            ~0, which is the observable proof the prefetch overlap works —
            the host/device breakdown, not an assumption). One step_stats
            telemetry event per flush; the host-side spike detector sees
            every per-step loss and emits `anomaly` events instead of
            silently training through divergence."""
            nonlocal t_interval, slept_ms, waited_ms
            if not buffered:
                return
            # graftlint: disable=sync-hazard(the zero-sync contract: ONE device_get per metrics flush, DESIGN.md section 13)
            fetched = jax.device_get([m for _, _, _, m in buffered])
            dt_ms = ((time.perf_counter() - t_interval) * 1000 - slept_ms) \
                / len(buffered)
            wait_ms = waited_ms / len(buffered)
            # the device_get above SYNCED the interval, so dt_ms is the
            # honest per-step time (a per-iteration clock under async
            # dispatch measures only enqueue latency): feed the fleet
            # timing consumers — the straggler window and the watchdog's
            # deadline median — from here, the same number step_stats
            # publishes
            prior_n, prior_med = step_clock.n, step_clock.median_ms()
            step_clock.record(dt_ms / 1000.0)
            if wd is not None:
                wd.pet(buffered[-1][0], dt_ms / 1000.0)
            # slow-step flight-recorder trigger (--auto_profile): this
            # flush interval ran a multiple of the rolling median —
            # arm a capture over the NEXT steps while whatever made it
            # slow is plausibly still happening. The median is the
            # PRIOR window's (the slow sample must not judge itself),
            # and the manual --profile_dir window keeps priority.
            if (auto_prof is not None and slow_mult > 0 and prior_n >= 3
                    and prior_med > 0 and dt_ms > slow_mult * prior_med
                    and not prof_active):
                if auto_prof.trigger("slow_step", buffered[-1][0] + 1):
                    log.warning(
                        f"auto_profile: step time {dt_ms:.1f} ms > "
                        f"{slow_mult:g}x rolling median "
                        f"{prior_med:.1f} ms — capturing "
                        f"{auto_prof.steps} step(s)")
            # live bytes when the backend reports them, else the
            # compiled-peak estimate, else NULL — a backend with no
            # memory accounting must not masquerade as 0 MB (round 16;
            # live_hbm_mb logs the backend once)
            hbm = live_hbm_mb()
            if hbm is None:
                hbm = peak_hbm["mb"] or None
            mfu = mfu_from(flops_per_step, dt_ms / 1000, peak_flops)
            for (s, ep, toks, _), m in zip(buffered, fetched):
                loss = float(m["loss"])
                avg = ema.update(loss)
                anom = spikes.update(loss)
                if anom is not None:
                    tel.emit("anomaly", step=s + 1, loss=loss, ema=avg,
                             **anom)
                    log.warning(
                        f"anomaly @ step {s + 1}: {anom['kind']} "
                        f"loss={loss:.4f}"
                        + (f" z={anom['zscore']}" if anom["zscore"] else ""))
                    if auto_prof is not None and not prof_active:
                        auto_prof.trigger(anom["kind"], s + 1)
                if rb is not None:
                    # rollback triggers, evaluated per flushed step:
                    # sustained divergence (the detector's escalated
                    # kind), a streak of skipped/nonfinite steps, or a
                    # nonfinite loss with the skip guard OFF (params
                    # already poisoned — waiting is pointless). A
                    # single skipped step or one-off loss_spike never
                    # triggers: that is the guard/winsorizer working.
                    # `suppressed` (set by a FAILED rollback) holds
                    # triggers until a clean step ends the episode —
                    # without it a checkpoint-less NaN run would emit
                    # one ok=false rollback + a full lineage CRC walk
                    # per step forever (stream-sizing rule).
                    bad = (int(m.get("skipped") or 0) > 0
                           or not math.isfinite(loss))
                    rb["streak"] = rb["streak"] + 1 if bad else 0
                    if not bad:
                        rb["suppressed"] = False
                    if rb["due"] is not None or rb["suppressed"]:
                        pass
                    elif anom is not None \
                            and anom["kind"] == "divergence":
                        rb["due"] = ("divergence", s + 1)
                    elif rb["streak"] >= rb["skip_streak"]:
                        rb["due"] = ("skip_streak", s + 1)
                    elif (not math.isfinite(loss)
                          and not tc.skip_nonfinite):
                        rb["due"] = ("nonfinite_loss", s + 1)
                if metrics_csv:
                    metrics_csv.log(epoch=ep, step=s + 1, loss=loss,
                                    avg_loss=avg, lr=float(m["lr"]),
                                    grad_norm=float(m["grad_norm"]),
                                    step_time_ms=dt_ms, host_wait_ms=wait_ms,
                                    tok_s=toks / (dt_ms / 1000), mfu=mfu,
                                    hbm_mb=hbm if hbm is not None else 0.0)
            s, ep, toks, _ = buffered[-1]
            m = fetched[-1]
            opt_f = lambda k: (float(m[k]) if k in m else None)
            tel.emit(
                "step_stats", step=s + 1, loss=float(m["loss"]),
                # graftlint: disable=sync-hazard(ema is the host-side spike detector's Python scalar, not a device array)
                ema=float(ema.value), lr=float(m["lr"]),
                grad_norm=float(m["grad_norm"]), step_time_ms=dt_ms,
                host_wait_ms=wait_ms, slept_ms=slept_ms,
                tok_s=toks / (dt_ms / 1000), mfu=mfu,
                param_norm=opt_f("param_norm"),
                update_ratio=opt_f("update_ratio"),
                nonfinite_count=(int(m["nonfinite_count"])
                                 if "nonfinite_count" in m else None),
                # COUNT over the flush interval (unlike the last-step
                # health scalars): the report's skipped-step total is a
                # sum of these, so no skip can fall between flushes
                skipped=(sum(int(fm["skipped"]) for fm in fetched)
                         if "skipped" in m else None),
                hbm_mb=hbm, queue_depth=stream.queue_depth(),
                host_step_ms=host_step_ms["latest"])
            if emit_log and args.log_interval:
                log.info(
                    f"step {s + 1}/{total_steps} loss={float(m['loss']):.4f} "
                    f"ema={ema.value:.4f} "
                    f"ppl={perplexity_from_loss(float(m['loss'])):.2f} "
                    f"grad_norm={float(m['grad_norm']):.3f} "
                    f"lr={float(m['lr']):.2e} "
                    f"{toks / (dt_ms / 1000):.0f} tok/s "
                    + (f"mfu={mfu:.3f} " if mfu is not None else "")
                    + f"host_wait={wait_ms:.1f}ms")
            buffered.clear()
            slept_ms = 0.0
            waited_ms = 0.0
            t_interval = time.perf_counter()

        def attempt_rollback(reason: str, at_step: int):
            """Close the sensors→recovery loop IN PROCESS (DESIGN.md
            §20): resolve the newest VERIFIED lineage checkpoint at or
            below the trigger step, reload trainable + Adam sidecar as
            host numpy, place both at THIS run's mesh (the r13
            elastic-resume placement — replicate for LoRA-style
            trainables, FSDP re-shard otherwise), rebuild the data
            stream past the poison region, and hand the loop its resume
            step. The compiled step is REUSED — shapes, shardings and
            donation are unchanged, so recovery costs a load + place,
            not a recompile. Returns the resume step, or None when no
            rollback happened (every verdict lands in the stream)."""
            nonlocal trainable, opt_state, stream, ema, spikes, \
                t_interval
            with pause():
                # the WHOLE recovery is a legitimate long pause — the
                # drain of an in-flight multi-GB write and the CRC walk
                # over the lineage candidates can each exceed any
                # step-derived watchdog deadline, same as the load
                try:  # lineage must be settled: finish in-flight writes
                    ckpt.drain()
                except Exception as e:
                    log.warning(f"rollback: checkpoint drain failed "
                                f"({e}); resolving against what is on "
                                f"disk")
                from mobilefinetuner_tpu.io.checkpoints import \
                    resolve_checkpoint
                # max_step = at_step - 1: a checkpoint written at the
                # very trigger boundary may already hold the poisoned
                # update (skip guard off) — never "recover" into it
                resolved, to_step, events = resolve_checkpoint(
                    None, verify=bool(getattr(args, "verify_ckpt", 1)),
                    lineage_base=ckpt_path, max_step=at_step - 1)
                for ev in events:
                    tel.emit("ckpt_verify", **ev)
                if resolved is None or to_step is None:
                    tel.emit("rollback", step=at_step, reason=reason,
                             ok=False, to_step=None, steps_lost=None,
                             ckpt=None, data_offset=None,
                             budget_left=rb["budget"])
                    log.warning(f"rollback wanted ({reason} @ step "
                                f"{at_step}) but no verified "
                                f"checkpoint exists — continuing "
                                f"without")
                    # suppress further triggers until a CLEAN step ends
                    # this episode: a checkpoint-less diverged run must
                    # not emit one ok=false rollback + a lineage CRC
                    # walk per step forever
                    rb["streak"] = 0
                    rb["suppressed"] = True
                    return None
                tr_h, opt_h = load_hook(resolved)
                trainable, opt_state = place_state(tr_h, opt_h)
            rb["count"] += 1
            rb["budget"] -= 1
            rb["streak"] = 0
            data_offset = rb["count"] * rb["offset"]
            stream.close()
            stream = make_stream(to_step, to_step + data_offset)
            # fresh host-side statistics: the old EMA/variance describe
            # the diverged trajectory, not the restored one (count_hint
            # keeps the detector armed — post-rollback losses are
            # healthy, not early-training wild)
            ema = EMA(args.ema_beta)
            spikes = SpikeDetector(SpikeConfig(
                zscore=getattr(args, "spike_z", 8.0),
                beta=getattr(args, "spike_beta", 0.98),
                warmup=getattr(args, "spike_warmup", 20)))
            spikes.seed([], count_hint=to_step)
            cur_step["step"] = to_step
            # recovery wall time is not step time: restart the flush
            # interval or the first post-rollback flush would fold the
            # whole drain+verify+load into its per-step average (and
            # feed that corrupted sample to the watchdog deadline and
            # the straggler window)
            t_interval = time.perf_counter()
            tel.emit("rollback", step=at_step, reason=reason, ok=True,
                     to_step=to_step, steps_lost=at_step - to_step,
                     ckpt=resolved, data_offset=data_offset,
                     budget_left=rb["budget"])
            log.warning(
                f"ROLLBACK ({reason}): step {at_step} -> {to_step} "
                f"from {resolved} ({at_step - to_step} step(s) lost, "
                f"data offset +{data_offset}, budget left "
                f"{rb['budget']})")
            return to_step

        if wd is not None:
            wd.start()
        try:
            step = start_step
            while step < total_steps:
                # the prefetched stream yields batches already placed (and
                # dropout-keyed); this next() is the step loop's only input
                # dependency, and the time it blocks is the host/device
                # breakdown's host_wait_ms
                meter.enter("input_wait")
                t_wait = time.perf_counter()
                step_i, epoch, batch = next(stream)
                waited_ms += (time.perf_counter() - t_wait) * 1000
                meter.enter("step")
                assert step_i == step  # strict order preservation
                maybe_profile(step)
                if injector.kind == "slow_step":
                    injector.maybe_slow(step)
                # the step was AOT-compiled (and admission-checked)
                # BEFORE the stream existed; a RESOURCE_EXHAUSTED that
                # still escapes the dispatch walks the remaining
                # degradation ladder and retries the SAME batch (the
                # batch is not donated — only trainable/opt are, and
                # recover_dispatch_oom restores those)
                while True:
                    try:
                        if injector.kind == "hbm_pressure":
                            injector.maybe_oom_dispatch(step)
                        trainable, opt_state, metrics = compiled_step(
                            trainable, frozen, opt_state, batch,
                            jnp.int32(step))
                        break
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except Exception as e:
                        if not mg.is_resource_exhausted(e):
                            raise
                        recover_dispatch_oom(e, step)
                oom_snap = None  # a retired step ends the retry window
                toks = batch["input_ids"].shape[0] * batch["input_ids"].shape[1]
                buffered.append((step, epoch, toks, metrics))
                if auto_prof is not None and auto_prof.active:
                    # countdown an armed capture; the stop syncs the
                    # device first so the async-dispatched step work is
                    # actually inside the captured window
                    auto_prof.tick(step, sync=lambda m=metrics:
                                   # graftlint: disable=sync-hazard(the flight recorder's stop syncs so the dispatched step lands inside the capture)
                                   jax.device_get(m["loss"]))
                log_boundary = bool(args.log_interval) \
                    and (step + 1) % args.log_interval == 0
                if log_boundary or (step + 1) % flush_every == 0:
                    # capped flushes (flush_every < log_interval) only write
                    # CSV rows; the log line fires exactly on the requested
                    # cadence
                    flush_metrics(emit_log=log_boundary)
                # idle-reset only (no duration sample — the honest
                # per-step time comes from the flush's synced interval
                # average, fed to the watchdog/straggler window inside
                # flush_metrics)
                if wd is not None:
                    wd.pet(step)
                if strag_k and (step + 1) % strag_k == 0 \
                        and step_clock.n:
                    # collective on a deterministic cadence: every
                    # process reaches this gather at the same step
                    fleet = allgather_scalars(step_clock.median_ms())
                    host_step_ms["latest"] = {
                        str(i): round(v, 3) for i, v in enumerate(fleet)}
                    med = statistics.median(fleet)
                    if coord and med > 0:
                        for h, v in enumerate(fleet):
                            if v > strag_mult * med:
                                tel.emit("straggler", step=step + 1,
                                         slow_host=h, host_ms=round(v, 3),
                                         fleet_ms=round(med, 3),
                                         ratio=round(v / med, 3))
                                log.warning(
                                    f"straggler: host {h} at {v:.1f} "
                                    f"ms/step vs fleet median "
                                    f"{med:.1f} ms ({v / med:.2f}x)")
                                if auto_prof is not None \
                                        and not prof_active:
                                    auto_prof.trigger("straggler",
                                                      step + 1)
                    step_clock.reset()

                if (args.eval_interval and valid_ds is not None
                        and (step + 1) % args.eval_interval == 0):
                    flush_metrics(emit_log=False)  # off-cadence boundary flush
                    meter.enter("eval")
                    with pause():  # an eval may exceed any step deadline
                        ev = evaluate(eval_step, trainable, frozen,
                                      valid_ds, args.eval_batches,
                                      mesh=eval_mesh,
                                      sequence_parallel=eval_sp,
                                      prefetch=prefetch_depth)
                    meter.enter("step")
                    log.info(f"eval @ step {step + 1}: loss={ev['loss']:.4f} "
                             f"ppl={ev['ppl']:.2f} ({ev['tokens']} tokens)")
                    if eval_jsonl:
                        eval_jsonl.write({"type": "eval", "step": step + 1,
                                          "loss": ev["loss"], "ppl": ev["ppl"],
                                          "tokens": ev["tokens"],
                                          "time": time.time() - t_start})
                    tel.emit("eval", step=step + 1, loss=ev["loss"],
                             ppl=ev["ppl"], tokens=ev["tokens"])
                    t_interval = time.perf_counter()  # eval time ≠ step time

                if args.save_every and save_hook and (step + 1) % \
                        args.save_every == 0 and (step + 1) < total_steps:
                    flush_metrics(emit_log=False)  # off-cadence boundary flush
                    # the meter's checkpoint bucket spans only this
                    # blocking call: under --async_save that is the
                    # batched snapshot (+ enqueue), and the background
                    # write's wall time stays charged to `step` — the
                    # overlap IS the feature. The checkpoint telemetry
                    # event (with the snapshot/write split) is emitted
                    # by the checkpointer when the write completes.
                    meter.enter("checkpoint")
                    with pause():  # a slow save is not a hang
                        save_hook(step + 1, trainable, opt_state,
                                  final=False, ckpt=ckpt)
                    meter.enter("step")
                    t_interval = time.perf_counter()  # save time ≠ step time
                    if injector.kind == "ckpt_corrupt" and ckpt_path:
                        # fault harness: bit-flip the newest lineage
                        # entry AFTER its write lands, so a later
                        # rollback/resume must fall back down the chain
                        try:
                            ckpt.drain()
                        except Exception:
                            pass
                        injector.maybe_corrupt_ckpt(ckpt_path)

                meter.enter("governor_sleep")
                slept_ms += governor.throttle(step)
                meter.enter("step")
                done_steps = step + 1 - start_step
                cur_step["step"] = step + 1

                if guard is not None and guard.triggered:
                    # preemption drain: the step in flight is done —
                    # flush the metrics buffer, take ONE final atomic
                    # checkpoint (final=True drains the async writer:
                    # the process must not exit before the recovery
                    # point is durable), end the stream with a
                    # schema-valid run_end{reason=preempted}, and exit
                    # with the RESUMABLE code. `--resume_from` the final
                    # artifact continues at step+1 with the data stream
                    # fast-forwarded (skip_steps) — the preemption cost
                    # is this one drain, not the steps since the last
                    # periodic save.
                    log.warning(
                        f"{guard.signal_name} received: draining at step "
                        f"{step + 1} (final save, then exit "
                        f"{EXIT_PREEMPTED})")
                    flush_metrics(emit_log=False)
                    tel.emit("preempt", step=step + 1,
                             signal=guard.signal_name or "SIGTERM")
                    if save_hook is not None:
                        meter.enter("checkpoint")
                        with pause():  # a slow drain save is not a hang
                            save_hook(step + 1, trainable, opt_state,
                                      final=True, ckpt=ckpt)
                    meter.enter("shutdown")
                    if metrics_csv:
                        metrics_csv.close()
                    end_run("preempted", done_steps, reason="preempted")
                    raise SystemExit(EXIT_PREEMPTED)

                if rb is not None and rb["due"] is not None:
                    # a flush inside THIS iteration raised a trigger:
                    # act at the step boundary (the metrics buffer is
                    # empty — triggers only arise from a flush)
                    reason, at_step = rb["due"]
                    rb["due"] = None
                    if rb["budget"] <= 0:
                        tel.emit("rollback", step=at_step, reason=reason,
                                 ok=False, to_step=None, steps_lost=None,
                                 ckpt=None, data_offset=None,
                                 budget_left=0)
                        log.warning(
                            f"rollback budget exhausted; training on "
                            f"through {reason} @ step {at_step}")
                        rb = None  # stop evaluating triggers
                    else:
                        resumed = attempt_rollback(reason, at_step)
                        if resumed is not None:
                            step = resumed
                            continue
                step += 1
        except BaseException as e:
            # the stream records HOW the run ended before the exception
            # propagates — a crashed run's tail is run_start..last flush +
            # run_end{exit: <type>}, which is what post-mortems need
            end_run(type(e).__name__, done_steps)
            raise
        finally:
            # stop the producer thread even when the consumer dies mid-epoch
            # (compiled-step failure, KeyboardInterrupt): no leaked threads,
            # and the original exception propagates untouched. The
            # watchdog is NOT stopped here — the post-loop tail (final
            # eval + final save) stays monitored; wd_ref's outer finally
            # owns the stop.
            stream.close()
            # profiler-leak fix: a run whose total_steps end (or whose
            # exception) lands inside the profiling window used to leave the
            # trace open — stop_trace() was only reachable from inside the
            # step loop. Closing here makes the trace land on EVERY exit
            # path (regression: tests/test_cli.py short-run profile test).
            if prof_active:
                maybe_profile(prof_end)

        # the post-loop tail (final flush/eval/save) carries the same
        # run_end-on-exception contract as the loop: a disk-full save or a
        # lost-worker collective here must still leave run_end{exit: <type>}
        meter.enter("shutdown")
        try:
            flush_metrics()
            if valid_ds is not None and args.eval_interval:
                meter.enter("eval")
                with pause():  # unbounded legitimate pause
                    ev = evaluate(eval_step, trainable, frozen, valid_ds,
                                  args.eval_batches, mesh=eval_mesh,
                                  sequence_parallel=eval_sp,
                                  prefetch=prefetch_depth)
                meter.enter("shutdown")
                log.info(f"final eval: loss={ev['loss']:.4f} "
                         f"ppl={ev['ppl']:.2f}")
                if eval_jsonl:
                    eval_jsonl.write({"type": "final_eval",
                                      "step": total_steps,
                                      "loss": ev["loss"], "ppl": ev["ppl"],
                                      "tokens": ev["tokens"]})
                tel.emit("eval", step=total_steps, loss=ev["loss"],
                         ppl=ev["ppl"], tokens=ev["tokens"])
            if save_hook:
                # final=True drains the writer inside the hook's submit:
                # the run must not end before its last checkpoint is on
                # disk, so this blocking span (snapshot + any queued
                # writes) honestly lands in the checkpoint bucket
                meter.enter("checkpoint")
                with pause():
                    save_hook(total_steps, trainable, opt_state,
                              final=True, ckpt=ckpt)
                meter.enter("shutdown")
        except BaseException as e:
            end_run(type(e).__name__, done_steps)
            raise
        live = live_hbm_mb()
        log.info(f"peak HBM: {peak_hbm['mb']:.0f} MB (compiled estimate)"
                 + (f", {live:.0f} MB live" if live else ""))
        if metrics_csv:
            metrics_csv.close()
        end_run("ok", total_steps - start_step)
        return trainable, opt_state, metrics
    except BaseException as e:
        end_run(type(e).__name__, done_steps)
        raise
    finally:
        # the watchdog outlives the step loop on purpose (the post-loop
        # tail stays monitored); this is the single stop for every exit
        # path — return, loop exception, tail exception, setup failure
        if wd is not None:
            wd.stop()
        # a capture left open by an exiting loop is stopped (the trace
        # of the steps that DID run is worth keeping), and the metrics
        # endpoint goes down with the run it described
        if auto_prof is not None:
            auto_prof.close()
        if metrics_srv is not None:
            metrics_srv.close()
        # belt-and-braces: end_run already drained the writer on every
        # path (close is idempotent) — this guards exits that never
        # reached an end_run, e.g. a failure inside end_run itself
        if ckpt is not None:
            ckpt.close(raise_errors=False)
        # restore the process's previous signal handlers: repeated
        # in-process runs (tests, notebooks) must not stack handlers
        if guard is not None:
            guard.uninstall()


def setup_frozen_params(args, params, mesh):
    """Place frozen base params: FSDP shardings + optional host offload.

    Returns (placed_params, fetch_fn, offload_arg):
      - fetch_fn pulls ALL offloaded leaves to device at once (the
        --shard_stream 0 path: fast, but the whole fetched tree is
        HBM-resident for the step);
      - offload_arg is the (plan, shardings) pair the model forwards accept
        to stream block weights per layer instead (default; the budget then
        bounds peak HBM, not just idle placement). None when offload is
        disabled or streaming is turned off.
    """
    shardings = params_shardings(params, mesh)
    ocfg = offload_config_from_args(args)
    plan = plan_placement(params, ocfg)
    placed = apply_placement(params, plan, shardings, ocfg)
    if ocfg.enable:
        stats = placement_stats(params, plan, ocfg)
        log.info(
            f"offload: {stats['n_offloaded']} params "
            f"({stats['offloaded_bytes'] / 2**20:.0f} MB) -> host RAM, "
            f"{stats['resident_bytes'] / 2**20:.0f} MB resident "
            f"(budget {args.shard_budget_mb} MB, "
            f"stream={'on' if getattr(args, 'shard_stream', 1) else 'off'})")

    def fetch_fn(p):
        return fetch(p, plan, shardings, compute_dtype=None)

    offload_arg = ((plan, shardings)
                   if ocfg.enable and getattr(args, "shard_stream", 1)
                   else None)
    return placed, fetch_fn, offload_arg
