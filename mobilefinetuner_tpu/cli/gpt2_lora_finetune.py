"""GPT-2 LoRA fine-tuning CLI.

TPU-native rebuild of the reference `gpt2_lora_finetune` binary
(reference: gpt2_lora_finetune/main.cpp — flag surface :80-171, training
loop :561-684): same flags and reporting, but the step is one compiled XLA
program (forward+backward+clip+LR+Adam with lax.scan grad-accum) running on
a ("data","fsdp") device mesh, with optional host-RAM offload of the frozen
base params replacing disk sharding.

Improvements over the reference, on purpose:
  - attention gradients flow on every path (the reference's default
    mem-efficient attention is forward-only, SURVEY.md §2.12.1);
  - --resume_from restores optimizer state + step counter from the .opt
    sidecar when present (the reference never wires Adam::save/load,
    SURVEY.md §5);
  - seeded LoRA init (the reference uses std::random_device,
    SURVEY.md §2.12.6).

Usage (tiny smoke):
  python -m mobilefinetuner_tpu.cli.gpt2_lora_finetune \
      --pretrained_dir /path/gpt2 --data_dir /path/wikitext-2 \
      --steps 10 --batch_size 4 --lora_out out/adapter.safetensors
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

import jax

from mobilefinetuner_tpu.cli import common
from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset
from mobilefinetuner_tpu.io import async_ckpt
from mobilefinetuner_tpu.io.checkpoints import load_gpt2
from mobilefinetuner_tpu.lora import peft_io
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                           num_trainable, trainable_mask)
from mobilefinetuner_tpu.models import gpt2
from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
from mobilefinetuner_tpu.optim import adam as adam_mod

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gpt2_lora_finetune",
        description="GPT-2 LoRA fine-tuning on WikiText-2 (TPU)")
    p.add_argument("--data_dir", required=True,
                   help="WikiText-2 directory (wiki.{train,valid}.tokens)")
    p.add_argument("--pretrained_dir", required=True,
                   help="HF GPT-2 checkpoint dir (config.json, "
                        "model.safetensors, vocab.json, merges.txt)")
    p.add_argument("--lora_out", default="gpt2_lora.safetensors")
    p.add_argument("--resume_from", default="",
                   help="adapter safetensors to resume from")
    p.add_argument("--eval_out", default="", help="eval JSONL output path")
    p.add_argument("--rank", type=int, default=8)
    p.add_argument("--alpha", type=float, default=16.0)
    p.add_argument("--lora_dropout", type=float, default=0.0)
    p.add_argument("--lora_targets", default="attn_qkv,attn_proj",
                   help="comma list of attn_qkv,attn_proj,mlp_fc_in,"
                        "mlp_fc_out,attn_q,attn_k,attn_v,lm_head "
                        "(PEFT-aligned default: fused c_attn + c_proj, "
                        "main.cpp:381-390; lm_head is a single unstacked "
                        "site on the tied head — native format only, "
                        "cannot be merged)")
    p.add_argument("--split_qkv", action="store_true",
                   help="replace the fused attn_qkv target with separate "
                        "q/k/v column-range adapters "
                        "(lora_injector.h:169-191)")
    p.add_argument("--peft_export_dir", default="",
                   help="also export an HF-PEFT adapter directory")
    common.add_align_flags(p)
    common.add_train_flags(p, lr=1e-4, seq_len=128, batch_size=1)
    common.add_pm_flags(p)
    common.add_shard_flags(p)
    common.add_mesh_flags(p)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.split_qkv and args.peft_export_dir:
        raise SystemExit("--split_qkv adapters have no PEFT "
                         "representation; drop --peft_export_dir "
                         "(the native adapter format supports them)")
    config, params = load_gpt2(args.pretrained_dir)
    config = dataclasses.replace(
        config, attention_impl=args.attention_impl)
    if args.no_model_dropout:
        config = dataclasses.replace(config, embd_pdrop=0.0,
                                     resid_pdrop=0.0, attn_pdrop=0.0)
    if args.seq_len > config.n_positions:
        log.warning(f"seq_len({args.seq_len}) > n_positions"
                    f"({config.n_positions}), clamped")
        args.seq_len = config.n_positions
    log.info(f"GPT-2: layers={config.n_layer} hidden={config.n_embd} "
             f"heads={config.n_head}")

    # LoRA: fresh init or resume (main.cpp:340-400). The resume source
    # is checksum-verified first and falls back down its lineage on
    # corruption (common.resolve_resume_from rewrites args.resume_from;
    # the ckpt_verify verdicts land in the telemetry stream).
    if args.resume_from:
        common.resolve_resume_from(args)
        lora, spec = peft_io.load_adapter(args.resume_from)
        log.info(f"resumed adapter: r={spec.rank} alpha={spec.alpha} "
                 f"targets={spec.targets}")
    else:
        targets = [t for t in args.lora_targets.split(",") if t]
        if args.split_qkv:
            targets = [t for t in targets if t != "attn_qkv"]
            targets = ["attn_q", "attn_k", "attn_v"] + targets
        spec = LoRASpec(rank=args.rank, alpha=args.alpha,
                        dropout=args.lora_dropout,
                        targets=targets, init="gpt2")
        lora = init_lora_gpt2(config, spec, jax.random.PRNGKey(args.seed))
    mask = trainable_mask(lora)
    log.info(f"trainable params: {num_trainable(lora):,}")

    tok = GPT2BPETokenizer.from_pretrained(args.pretrained_dir)
    wt2 = WT2Config(seq_len=args.seq_len, batch_size=args.batch_size,
                    data_fraction=args.data_fraction, seed=args.seed,
                    **common.data_retry_kwargs(args))
    train_ds = WikiText2Dataset(args.data_dir, "train", wt2, tok.encode,
                                tok.eos_id)
    valid_ds = None
    if args.eval_interval:
        wt2_eval = WT2Config(seq_len=args.seq_len,
                             batch_size=args.eval_batch_size, shuffle=False,
                             **common.data_retry_kwargs(args))
        valid_ds = WikiText2Dataset(args.data_dir, "valid", wt2_eval,
                                    tok.encode, tok.eos_id)

    steps_per_epoch = max(train_ds.num_batches() // args.grad_accum_steps, 1)
    total_steps = common.resolve_total_steps(args, steps_per_epoch)
    tc = common.train_config_from_args(args, total_steps)
    log.info(f"{train_ds.num_chunks} chunks, {steps_per_epoch} steps/epoch, "
             f"{total_steps} total steps")

    opt_state, start_step = common.maybe_resume_opt_state(
        args, lora, tc, mask)

    mesh, cp_mesh = common.build_mesh(args)
    if cp_mesh is not None and config.attn_pdrop > 0:
        log.warning(f"attn_pdrop={config.attn_pdrop} is unsupported by "
                    f"ring attention; attention-probs dropout is OFF in "
                    f"sequence-parallel mode (embd/resid dropout still "
                    f"applies; --no_model_dropout silences this)")
    params, fetch_fn, offload_arg = common.setup_frozen_params(
        args, params, mesh)
    compute_dtype = common.compute_dtype_from_args(args)
    model_pdrop = max(config.embd_pdrop, config.resid_pdrop,
                      config.attn_pdrop)
    base_rng = (jax.random.PRNGKey(args.seed + 1)
                if args.lora_dropout > 0 or model_pdrop > 0 else None)

    from mobilefinetuner_tpu.lora.lora import GPT2_TARGETS
    common.log_lora_impl_resolution(
        args, {t: GPT2_TARGETS[t](config) for t in spec.targets or []},
        spec.rank, compute_dtype)

    # loss/nll read args.remat and the offload cells AT TRACE TIME: the
    # memory-admission degradation ladder (common.run_training,
    # DESIGN.md §21) re-traces them after flipping remat or enabling
    # offload, so the rungs need no separate loss builders
    def loss_fn(lora_t, frozen, mb):
        # per-(step, micro-batch) dropout key, threaded via the batch
        rng = mb["dropout_rng"][0] if "dropout_rng" in mb else None
        p = frozen if offload_arg is not None else fetch_fn(frozen)
        logits = gpt2.forward(config, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              lora=lora_t, compute_dtype=compute_dtype,
                              remat=args.remat, offload=offload_arg,
                              lora_dropout=args.lora_dropout,
                              dropout_rng=rng, cp_mesh=cp_mesh,
                              lora_impl=args.lora_impl)
        return lm_cross_entropy_sum(logits, mb["labels"])

    def nll_fn(lora_t, frozen, mb):
        p = frozen if offload_arg is not None else fetch_fn(frozen)
        logits = gpt2.forward(config, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              lora=lora_t, compute_dtype=compute_dtype,
                              offload=offload_arg, cp_mesh=cp_mesh,
                              lora_impl=args.lora_impl)
        return lm_cross_entropy_sum(logits, mb["labels"])

    def offload_rung():
        """The ladder's last rung (policy shared with the Gemma LoRA
        CLI via common.offload_rung_state): re-place the frozen base
        with host offload at the streams-only budget. The loss/nll
        closures read the rebound cells at the ladder's recompile."""
        nonlocal params, fetch_fn, offload_arg
        out = common.offload_rung_state(args, params, mesh)
        if out is None:
            return None
        params, fetch_fn, offload_arg = out
        return params, loss_fn

    if args.align_dump_dir:
        from mobilefinetuner_tpu.align.dump import run_align_dump

        def trace_fn(lora_t, frozen, mb):
            p = fetch_fn(frozen)
            x, acts = gpt2.hidden_states(
                config, p, mb["input_ids"],
                attention_mask=mb["attention_mask"], lora=lora_t,
                compute_dtype=compute_dtype, collect_layers=True)
            logits = x @ p["wte"].astype(compute_dtype).T
            return logits, acts

        _, batch = next(common.micro_batches(train_ds, 1))
        run_align_dump(
            args.align_dump_dir, trace_fn=trace_fn, loss_fn=loss_fn,
            trainable=lora, frozen=params, batch=batch, tc=tc, mask=mask,
            spec=spec, family="gpt2", model_dir=args.pretrained_dir,
            steps=args.align_steps)
        return 0

    def save_hook(step, lora_t, opt_st, final, ckpt=None):
        path = args.lora_out
        if not final:  # _stepN suffix (main.cpp:180-187)
            root, ext = os.path.splitext(path)
            path = f"{root}_step{step}{ext}"
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        # blocking part: one batched D2H snapshot of adapter + opt state;
        # the write (key-map, encode, atomic safetensors publish) runs on
        # the checkpointer's background thread under --async_save
        (lora_h, opt_h), snap_ms = async_ckpt.timed_snapshot(
            (lora_t, opt_st))

        def write():
            peft_io.save_adapter(path, lora_h, spec)
            # loop_step: the resume point (Adam's own counter lags it
            # under --skip_nonfinite); lineage + GC ride the write hook
            adam_mod.save_state(path + ".opt", opt_h, tc.adam(),
                                extra_metadata={"loop_step": str(step)})
            common.record_ckpt_files(args, args.lora_out, step,
                                     [path, path + ".opt"])
            log.info(f"saved adapter -> {path}")
            if final and args.peft_export_dir:
                peft_io.export_peft(args.peft_export_dir, lora_h, spec,
                                    "gpt2",
                                    base_model_name=args.pretrained_dir)
                log.info(f"PEFT export -> {args.peft_export_dir}")
            return [path, path + ".opt"]

        async_ckpt.submit(ckpt, step, write, final=final,
                          snapshot_ms=snap_ms)

    # in-loop MFU: the SAME analytic estimator as bench.py's MFU column
    # (core/telemetry.transformer_flops), per GLOBAL optimizer step
    from mobilefinetuner_tpu.core.telemetry import transformer_flops
    flops = transformer_flops(
        sum(int(x.size) for x in jax.tree.leaves(lora)),
        gpt2.param_count(params), args.batch_size * tc.grad_accum_steps,
        args.seq_len, config.n_layer, config.n_head, config.head_dim,
        full_ft=False)

    common.run_training(
        args, trainable=lora, frozen=params, loss_fn=loss_fn, nll_fn=nll_fn,
        train_ds=train_ds, valid_ds=valid_ds, total_steps=total_steps,
        tc=tc, mask=mask, start_step=start_step, opt_state=opt_state,
        save_hook=save_hook, mesh=mesh, dropout_rng=base_rng,
        flops_per_step=flops,
        # the inverse of save_hook: arms in-process rollback
        # (--rollback_budget) against the lineage at --lora_out
        load_hook=common.make_rollback_loader(
            tc, mask, lambda p: peft_io.load_adapter(p)[0]),
        ckpt_path=args.lora_out,
        # memory-admission degradation ladder (DESIGN.md §21): remat
        # and accum_x2 need no hooks (run_training flips args.remat /
        # tc.grad_accum_steps and re-traces); offload re-places the
        # frozen base through this CLI's own setup path
        degrade_builders={"offload": offload_rung})
    return 0


if __name__ == "__main__":
    sys.exit(main())
