"""Gemma-3 full fine-tuning CLI (every parameter trainable).

Beyond-reference capability: the reference has full fine-tuning for GPT-2
only (gpt2_full_finetune/main.cpp) and LoRA-only for Gemma
(train_lora_gemma.cpp) — this CLI completes the model×mode matrix with
the same TPU-native skeleton as cli/gpt2_full_finetune.py: params are the
trainable tree, FSDP-sharded over the mesh with Adam m/v inheriting the
shardings (ZeRO optimizer-state partitioning), and the 262k-vocab
lm_head+CE runs through the chunked loss so [B, S, 262144] fp32 logits are
never materialized. The tied embedding is trainable, so its gradient sums
the embedding-gather and lm-head paths — which the chunked CE's
scan-accumulated dW provides (ops/loss.py).

Usage (tiny smoke):
  python -m mobilefinetuner_tpu.cli.gemma_full_finetune \
      --model_dir /path/gemma-3-270m --data_dir /path/wikitext-2 \
      --steps 10 --output_path out/gemma_full_ft.safetensors
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time

import jax

from mobilefinetuner_tpu.cli import common
from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset
from mobilefinetuner_tpu.io import async_ckpt
from mobilefinetuner_tpu.io.checkpoints import (gemma3_params_from_hf,
                                                load_gemma3, save_gemma3)
from mobilefinetuner_tpu.models import gemma3
from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
from mobilefinetuner_tpu.optim import adam as adam_mod
from mobilefinetuner_tpu.parallel.mesh import shard_params

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gemma_full_finetune",
        description="Gemma-3 full fine-tuning on WikiText-2 (TPU)")
    p.add_argument("--model_dir", required=True,
                   help="HF Gemma-3 checkpoint dir")
    p.add_argument("--data_dir", required=True)
    p.add_argument("--output_path", default="gemma_full_ft.safetensors")
    p.add_argument("--resume_from", default="",
                   help="full-model safetensors (or HF dir) to resume from")
    p.add_argument("--eval_out", default="")
    p.add_argument("--loss_chunks", type=int, default=8,
                   help="sequence chunks for the 262k-vocab chunked CE")
    p.add_argument("--opt_offload", action="store_true",
                   help="stream f32 master weights + Adam m/v from pinned "
                        "host RAM through a per-leaf scanned update; the "
                        "device holds only the compute-dtype copy. "
                        "Enables 1B-class full FT on one 16 GB chip "
                        "(optim/opt_offload.py); single-chip only")
    p.add_argument("--opt_offload_state_dtype", default="float32",
                   choices=["float32", "bfloat16", "float16"],
                   help="storage dtype for the streamed Adam m/v host "
                        "tier (16-bit halves their stream; v is "
                        "sqrt-encoded — OptOffloadSpec). The sidecar "
                        "must be resumed with the same dtype.")
    p.add_argument("--opt_offload_master_dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="storage dtype for the streamed master weights "
                        "(bfloat16 quantizes the update write-back with "
                        "stochastic rounding — OptOffloadSpec)")
    common.add_train_flags(p, lr=2e-5, seq_len=256, batch_size=1)
    common.add_pm_flags(p)
    common.add_mesh_flags(p)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    config, params = load_gemma3(args.model_dir)
    config = dataclasses.replace(
        config, attention_impl=args.attention_impl)
    log.info(f"Gemma-3 full FT: layers={config.num_hidden_layers} "
             f"hidden={config.hidden_size} vocab={config.vocab_size}")
    if args.no_model_dropout:
        # the shared flag surface carries this for GPT-2 configs; Gemma-3
        # checkpoints have no embd/resid/attn pdrop fields to zero
        log.warning("--no_model_dropout is a no-op for Gemma-3 "
                    "(the config has no dropout fields)")
    if args.resume_from:
        # verify-on-load with lineage fallback (DESIGN.md §20)
        common.resolve_resume_from(args)
        params = gemma3_params_from_hf(
            common.load_full_resume(args.resume_from), config)
        log.info(f"resumed full model from {args.resume_from}")
    if args.seq_len > config.max_position_embeddings:
        args.seq_len = config.max_position_embeddings

    tok = GemmaTokenizer.from_pretrained(args.model_dir)
    encode = lambda s: tok.encode(s, add_bos=False)
    wt2 = WT2Config(seq_len=args.seq_len, batch_size=args.batch_size,
                    data_fraction=args.data_fraction, seed=args.seed,
                    **common.data_retry_kwargs(args))
    train_ds = WikiText2Dataset(args.data_dir, "train", wt2, encode,
                                tok.eos_id, pad_id=tok.pad_id)
    valid_ds = None
    if args.eval_interval:
        wt2_eval = WT2Config(seq_len=args.seq_len,
                             batch_size=args.eval_batch_size, shuffle=False,
                             **common.data_retry_kwargs(args))
        valid_ds = WikiText2Dataset(args.data_dir, "valid", wt2_eval,
                                    encode, tok.eos_id, pad_id=tok.pad_id)

    steps_per_epoch = max(train_ds.num_batches() // args.grad_accum_steps, 1)
    total_steps = common.resolve_total_steps(args, steps_per_epoch)
    tc = common.train_config_from_args(args, total_steps)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    log.info(f"full FT: {n_params:,} trainable params, "
             f"{total_steps} steps")

    mesh, cp_mesh = common.build_mesh(args)
    compute_dtype = common.compute_dtype_from_args(args)
    step_builder = None
    plan = None
    if args.opt_offload:
        # master + Adam state stream from pinned host; device holds only
        # the compute copy (optim/opt_offload.py)
        from mobilefinetuner_tpu.optim import opt_offload as oo
        if mesh.size > 1:
            raise SystemExit("--opt_offload is single-chip (it streams "
                             "state through one chip's host link); drop "
                             "--mesh_data/--mesh_fsdp")
        if getattr(args, "skip_nonfinite", 0) \
                or getattr(args, "rollback_budget", 0) > 0:
            # refuse loudly rather than silently void the safety
            # promise: the offloaded step builder has no skip guard
            # (a NaN grad would poison the host-tier master/m/v) and
            # the generic rollback cannot reproduce its placements
            raise SystemExit(
                "--skip_nonfinite/--rollback_budget are not supported "
                "with --opt_offload (the offloaded update has no "
                "guarded-identity path; recovery there is "
                "process-level --resume_from) — drop the recovery "
                "flags or --opt_offload")
        oo_spec = oo.OptOffloadSpec(
            state_dtype=args.opt_offload_state_dtype,
            master_dtype=args.opt_offload_master_dtype)
        plan = oo.plan_opt_offload(params, oo_spec)
        trainable, opt_state = oo.init_opt_offload(
            params, plan, compute_dtype=compute_dtype, spec=oo_spec)
        start_step = 0
        if args.resume_from and os.path.exists(args.resume_from + ".opt"):
            opt_state = oo.resume_opt_sidecar(args.resume_from + ".opt",
                                              opt_state)
            start_step = int(opt_state["step"])
            log.info(f"restored offloaded opt state @ step {start_step}")
        n_streamed = sum(1 for c in jax.tree.leaves(plan) if c)
        import jax.numpy as jnp
        per_param = (jnp.dtype(oo_spec.master_dtype).itemsize
                     + 2 * jnp.dtype(oo_spec.state_dtype).itemsize)
        host_mb = sum(x.size * per_param / 2 ** 20
                      for x, c in zip(jax.tree.leaves(params),
                                      jax.tree.leaves(plan)) if c)
        log.info(f"opt offload: {n_streamed} leaves "
                 f"({host_mb:.0f} MB master+m+v, "
                 f"master={oo_spec.master_dtype} "
                 f"state={oo_spec.state_dtype}) -> pinned host")

        def step_builder(loss_fn, tc, mask=None, donate=True):
            return oo.make_offload_train_step(
                loss_fn, tc, plan, compute_dtype=compute_dtype,
                donate=donate, mask=mask, spec=oo_spec)
        params = trainable
    else:
        opt_state, start_step = common.maybe_resume_opt_state(
            args, params, tc, None)
        # Full FT: params themselves are the trainable tree — FSDP-shard
        # them (and thus Adam m/v) over the mesh; no host offload of
        # trainables. Checkpoint + sidecar hold FULL host tensors, so
        # this placement is where ANY mesh shape re-shards a resume
        # (elastic resume, DESIGN.md §18; shard_params is multi-host
        # safe, unlike a raw device_put).
        params = shard_params(params, mesh)
        if opt_state is not None:
            opt_state = common.place_opt_state(opt_state, mesh)

    # vocab-parallel CE on multi-device meshes (ops/loss.py): with the
    # tied embed TRAINABLE, this also keeps its gradient V-sharded
    # (reduce-scatter) instead of all-gathering table + grad per step.
    ce_mesh = mesh if (mesh.size > 1 and cp_mesh is None) else None

    def loss_fn(params_t, _unused, mb):
        hidden = gemma3.hidden_states(
            config, params_t, mb["input_ids"],
            attention_mask=mb["attention_mask"],
            compute_dtype=compute_dtype, remat=args.remat,
            cp_mesh=cp_mesh)
        return chunked_lm_cross_entropy_sum(
            hidden, params_t["embed"], mb["labels"],
            num_chunks=args.loss_chunks, mesh=ce_mesh)

    def nll_fn(params_t, _unused, mb):
        hidden = gemma3.hidden_states(
            config, params_t, mb["input_ids"],
            attention_mask=mb["attention_mask"],
            compute_dtype=compute_dtype, cp_mesh=cp_mesh)
        return chunked_lm_cross_entropy_sum(
            hidden, params_t["embed"], mb["labels"],
            num_chunks=args.loss_chunks, mesh=ce_mesh)

    def save_hook(step, params_t, opt_st, final, ckpt=None):
        path = args.output_path
        if not final:
            root, ext = os.path.splitext(path)
            path = f"{root}_step{step}{ext}"
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        if args.opt_offload:
            t0 = time.perf_counter()
            # the f32 MASTER is the real model (params_t is the bf16
            # compute copy); the sidecar carries step + m/v only. The
            # master/m/v tiers already live in host RAM — "snapshot"
            # here is the batched pull of the few device-resident
            # leaves plus reshaping, still the only blocking work
            from mobilefinetuner_tpu.optim import opt_offload as oo
            model_h = oo.master_to_params(opt_st, plan, params_t)
            side_h = async_ckpt.snapshot(
                {"step": opt_st["step"], "m": opt_st["m"],
                 "v": opt_st["v"]})
            snap_ms = (time.perf_counter() - t0) * 1000.0

            def write():
                save_gemma3(path, model_h)
                adam_mod.save_state(path + ".opt", side_h, tc.adam(),
                                    extra_metadata={
                                        "loop_step": str(step)})
                common.record_ckpt_files(args, args.output_path, step,
                                         [path, path + ".opt"])
                log.info(f"saved full model -> {path}")
                return [path, path + ".opt"]
        else:
            (params_h, opt_h), snap_ms = async_ckpt.timed_snapshot(
                (params_t, opt_st))

            def write():
                save_gemma3(path, params_h)
                adam_mod.save_state(path + ".opt", opt_h, tc.adam(),
                                    extra_metadata={
                                        "loop_step": str(step)})
                common.record_ckpt_files(args, args.output_path, step,
                                         [path, path + ".opt"])
                log.info(f"saved full model -> {path}")
                return [path, path + ".opt"]

        async_ckpt.submit(ckpt, step, write, final=final,
                          snapshot_ms=snap_ms)

    # in-loop MFU from the shared estimator (core/telemetry.py)
    from mobilefinetuner_tpu.core.telemetry import transformer_flops
    flops = transformer_flops(
        sum(int(x.size) for x in jax.tree.leaves(params)), 0,
        args.batch_size * tc.grad_accum_steps, args.seq_len,
        config.num_hidden_layers, config.num_attention_heads,
        config.head_dim, full_ft=True)

    common.run_training(
        args, trainable=params, frozen=None, loss_fn=loss_fn,
        nll_fn=nll_fn, train_ds=train_ds, valid_ds=valid_ds,
        total_steps=total_steps, tc=tc, mask=None, start_step=start_step,
        opt_state=opt_state, save_hook=save_hook, mesh=mesh,
        replicate_trainable=False, step_builder=step_builder,
        flops_per_step=flops,
        # rollback rides the plain-Adam path only: the opt-offload
        # builder owns its own host-tier placements, which the generic
        # rollback re-placement cannot reproduce — its recovery story
        # stays process-level restart (--resume_from)
        load_hook=(None if args.opt_offload
                   else common.make_rollback_loader(
                       tc, None,
                       lambda p: _load_full_gemma(p, config))),
        ckpt_path="" if args.opt_offload else args.output_path,
        # memory-admission ladder (DESIGN.md §21): remat + accum_x2
        # rungs only (loss_fn reads args.remat at trace time; the
        # accum rung re-invokes step_builder with the doubled count —
        # the opt-offload builder takes the same (loss_fn, tc) surface
        # as make_train_step). No frozen base, so no offload rung.
        degrade_builders=None)
    return 0


def _load_full_gemma(path, config):
    """Rollback inverse of the plain-Adam save_hook: HF-keyed Gemma-3
    file -> stacked host param tree."""
    from mobilefinetuner_tpu.io.safetensors_io import SafeTensorsReader
    return gemma3_params_from_hf(
        SafeTensorsReader(path).load_all(promote_to_f32=True), config)


if __name__ == "__main__":
    sys.exit(main())
