"""Gemma-3 LoRA fine-tuning CLI.

TPU-native rebuild of the reference `train_lora_gemma` binary
(reference: operators/finetune_ops/optim/train_lora_gemma.cpp — config/
weights/tokenizer load :352-496, target presets + --lora_targets override
:498-540, pretokenized-data mode :477-496, sharding registration :431-475,
training via GemmaLoRATrainer). The 262k-vocab lm_head+CE runs through the
chunked loss (ops/loss.py chunked_lm_cross_entropy) so [B,S,262144] fp32
logits are never materialized (SURVEY.md §7 hard part (d)).

Alignment-dump mode (--align_dump_dir) mirrors the reference's
single-batch npy dumps (:620-920) via align/dump.py; compare with the
torch/PEFT mirror tools/align_torch_mirror.py.

Usage (tiny smoke):
  python -m mobilefinetuner_tpu.cli.train_lora_gemma \
      --model_dir /path/gemma-3-270m --data_dir /path/wikitext-2 \
      --max_steps 10 --batch 2 --output_dir out/
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

import jax

from mobilefinetuner_tpu.cli import common
from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset
from mobilefinetuner_tpu.io import async_ckpt
from mobilefinetuner_tpu.io.checkpoints import load_gemma3
from mobilefinetuner_tpu.lora import peft_io
from mobilefinetuner_tpu.lora.lora import (GEMMA_PRESETS, LoRASpec,
                                           init_lora_gemma3, num_trainable,
                                           trainable_mask)
from mobilefinetuner_tpu.models import gemma3
from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
from mobilefinetuner_tpu.optim import adam as adam_mod

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="train_lora_gemma",
        description="Gemma-3 LoRA fine-tuning on WikiText-2 (TPU)")
    p.add_argument("--model_dir", required=True,
                   help="HF Gemma-3 checkpoint dir")
    p.add_argument("--data_dir", default="",
                   help="WikiText-2 directory (or use --pretokenized_path)")
    p.add_argument("--output_dir", default="gemma_lora_out")
    p.add_argument("--resume_from", default="")
    p.add_argument("--eval_out", default="")
    p.add_argument("--rank", type=int, default=8)
    p.add_argument("--alpha", type=float, default=32.0)
    p.add_argument("--lora_dropout", type=float, default=0.0)
    p.add_argument("--targets", default="full",
                   choices=list(GEMMA_PRESETS),
                   help="preset (gemma_lora_injector.h:9-34)")
    p.add_argument("--lora_targets", default="",
                   help="comma list overriding --targets "
                        "(q_proj,k_proj,v_proj,o_proj,gate_proj,up_proj,"
                        "down_proj,lm_head — lm_head is a single "
                        "unstacked site on the tied head; its delta "
                        "rides the chunked-CE epilogue, native adapter "
                        "format only)")
    p.add_argument("--pretokenized_path", default="",
                   help="pretokenized .bin (train split)")
    p.add_argument("--pretokenized_meta", default="",
                   help="(accepted for reference-CLI compat; the .bin's "
                        "sidecar meta.json is found automatically)")
    p.add_argument("--loss_chunks", type=int, default=8,
                   help="sequence chunks for the 262k-vocab chunked CE")
    p.add_argument("--peft_export_dir", default="")
    common.add_align_flags(p)
    p.add_argument("--max_steps", type=int, default=0,
                   help="alias of --steps (reference flag name)")
    common.add_train_flags(p, lr=1e-4, seq_len=256, batch_size=1)
    common.add_pm_flags(p)
    common.add_shard_flags(p)
    common.add_mesh_flags(p)
    # reference flag aliases
    p.add_argument("--batch", type=int, default=None,
                   help="alias of --batch_size")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.batch is not None:
        args.batch_size = args.batch
    if args.max_steps and not args.steps:
        args.steps = args.max_steps

    config, params = load_gemma3(args.model_dir)
    config = dataclasses.replace(
        config, attention_impl=args.attention_impl)
    log.info(f"Gemma-3: layers={config.num_hidden_layers} "
             f"hidden={config.hidden_size} vocab={config.vocab_size} "
             f"q/kv heads={config.num_attention_heads}/"
             f"{config.num_key_value_heads}")

    if args.resume_from:
        # verify-on-load with lineage fallback (DESIGN.md §20)
        common.resolve_resume_from(args)
        lora, spec = peft_io.load_adapter(args.resume_from)
        log.info(f"resumed adapter: r={spec.rank} targets={spec.targets}")
    else:
        targets = ([t for t in args.lora_targets.split(",") if t]
                   or GEMMA_PRESETS[args.targets])
        spec = LoRASpec(rank=args.rank, alpha=args.alpha,
                        dropout=args.lora_dropout, targets=targets,
                        init="peft")  # PEFT-default init (SURVEY §2.5)
        lora = init_lora_gemma3(config, spec, jax.random.PRNGKey(args.seed))
    mask = trainable_mask(lora)
    log.info(f"trainable params: {num_trainable(lora):,}")

    tok = GemmaTokenizer.from_pretrained(args.model_dir)
    encode = lambda s: tok.encode(s, add_bos=False)
    wt2 = WT2Config(seq_len=args.seq_len, batch_size=args.batch_size,
                    data_fraction=args.data_fraction, seed=args.seed,
                    **common.data_retry_kwargs(args))
    train_ds = WikiText2Dataset(
        args.data_dir, "train", wt2, encode, tok.eos_id,
        pad_id=tok.pad_id,
        pretokenized_bin=args.pretokenized_path or None)
    valid_ds = None
    if args.eval_interval and args.data_dir:
        wt2_eval = WT2Config(seq_len=args.seq_len,
                             batch_size=args.eval_batch_size, shuffle=False,
                             **common.data_retry_kwargs(args))
        valid_ds = WikiText2Dataset(args.data_dir, "valid", wt2_eval,
                                    encode, tok.eos_id, pad_id=tok.pad_id)

    steps_per_epoch = max(train_ds.num_batches() // args.grad_accum_steps, 1)
    total_steps = common.resolve_total_steps(args, steps_per_epoch)
    tc = common.train_config_from_args(args, total_steps)
    log.info(f"{train_ds.num_chunks} chunks, {total_steps} total steps")

    opt_state, start_step = common.maybe_resume_opt_state(
        args, lora, tc, mask)

    mesh, cp_mesh = common.build_mesh(args)
    params, fetch_fn, offload_arg = common.setup_frozen_params(
        args, params, mesh)
    compute_dtype = common.compute_dtype_from_args(args)
    base_rng = (jax.random.PRNGKey(args.seed + 1)
                if args.lora_dropout > 0 else None)

    def resolve(frozen):
        """Fetch offloaded top-level leaves (incl. the embed table, reused
        by the tied-lm-head chunked CE) once; block weights stream per
        layer via the returned stream fn. Reads the offload cells at
        TRACE time, so the degradation ladder's offload rung takes
        effect at its recompile (DESIGN.md §21)."""
        from mobilefinetuner_tpu.parallel.offload import resolve_offload
        if offload_arg is None:
            return fetch_fn(frozen), None
        return resolve_offload(frozen, offload_arg)

    def offload_rung():
        """Memory-admission ladder, last rung (policy shared with the
        GPT-2 LoRA CLI via common.offload_rung_state): re-place the
        frozen base with host offload at the streams-only budget — the
        262k embed stays resident, block stacks stream per layer
        inside the remat'd scan. None when offload is already on."""
        nonlocal params, fetch_fn, offload_arg
        out = common.offload_rung_state(args, params, mesh)
        if out is None:
            return None
        params, fetch_fn, offload_arg = out
        return params, loss_fn

    # vocab-parallel CE on multi-device meshes: the fsdp-sharded 262k
    # embed must not be all-gathered per step (ops/loss.py). In
    # sequence-parallel mode the fsdp axis carries the sequence, so the
    # CE runs the seq-sharded composition (chunk-wise hidden gather +
    # vocab-parallel softmax — ops/loss.py seq_shard).
    ce_mesh = mesh if mesh.size > 1 else None
    ce_sp = cp_mesh is not None

    from mobilefinetuner_tpu.lora.lora import GEMMA_TARGETS
    common.log_lora_impl_resolution(
        args, {t: GEMMA_TARGETS[t](config) for t in spec.targets or []},
        spec.rank, compute_dtype)

    def loss_fn(lora_t, frozen, mb):
        p, stream = resolve(frozen)
        # per-(step, micro-batch) dropout key, threaded via the batch
        rng = mb["dropout_rng"][0] if "dropout_rng" in mb else None
        hidden = gemma3.hidden_states(
            config, p, mb["input_ids"],
            attention_mask=mb["attention_mask"], lora=lora_t,
            compute_dtype=compute_dtype, remat=args.remat,
            lora_dropout=args.lora_dropout, dropout_rng=rng,
            block_stream=stream, cp_mesh=cp_mesh,
            lora_impl=args.lora_impl)
        # lm_head tied to embeddings; chunked CE avoids [B,S,262k]
        # logits — an opt-in "lm_head" adapter rides it as lora_head
        # (its delta stays chunk-local / in-kernel, DESIGN.md §17),
        # with --lora_dropout applied to its branch input like every
        # per-layer site
        return chunked_lm_cross_entropy_sum(
            hidden, p["embed"], mb["labels"], num_chunks=args.loss_chunks,
            mesh=ce_mesh, sequence_parallel=ce_sp,
            lora_head=lora_t["blocks"].get("lm_head"),
            lora_impl=args.lora_impl,
            lora_dropout=args.lora_dropout, dropout_rng=rng)

    def nll_fn(lora_t, frozen, mb):
        p, stream = resolve(frozen)
        hidden = gemma3.hidden_states(
            config, p, mb["input_ids"],
            attention_mask=mb["attention_mask"], lora=lora_t,
            compute_dtype=compute_dtype, block_stream=stream,
            cp_mesh=cp_mesh, lora_impl=args.lora_impl)
        return chunked_lm_cross_entropy_sum(
            hidden, p["embed"], mb["labels"], num_chunks=args.loss_chunks,
            mesh=ce_mesh, sequence_parallel=ce_sp,
            lora_head=lora_t["blocks"].get("lm_head"),
            lora_impl=args.lora_impl)

    if args.align_dump_dir:
        from mobilefinetuner_tpu.align.dump import run_align_dump

        def trace_fn(lora_t, frozen, mb):
            p = fetch_fn(frozen)
            x, acts = gemma3.hidden_states(
                config, p, mb["input_ids"],
                attention_mask=mb["attention_mask"], lora=lora_t,
                compute_dtype=compute_dtype, collect_layers=True)
            logits = x @ p["embed"].astype(compute_dtype).T
            return logits, acts

        _, batch = next(common.micro_batches(train_ds, 1))
        run_align_dump(
            args.align_dump_dir, trace_fn=trace_fn, loss_fn=loss_fn,
            trainable=lora, frozen=params, batch=batch, tc=tc, mask=mask,
            spec=spec, family="gemma", model_dir=args.model_dir,
            steps=args.align_steps)
        return 0

    def save_hook(step, lora_t, opt_st, final, ckpt=None):
        os.makedirs(args.output_dir, exist_ok=True)
        name = "gemma_lora.safetensors" if final \
            else f"gemma_lora_step{step}.safetensors"
        path = os.path.join(args.output_dir, name)
        # blocking snapshot on the loop thread; write off-loop (atomic)
        (lora_h, opt_h), snap_ms = async_ckpt.timed_snapshot(
            (lora_t, opt_st))

        def write():
            peft_io.save_adapter(path, lora_h, spec)
            adam_mod.save_state(path + ".opt", opt_h, tc.adam(),
                                extra_metadata={"loop_step": str(step)})
            common.record_ckpt_files(
                args, os.path.join(args.output_dir,
                                   "gemma_lora.safetensors"),
                step, [path, path + ".opt"])
            log.info(f"saved adapter -> {path}")
            if final and args.peft_export_dir:
                peft_io.export_peft(args.peft_export_dir, lora_h, spec,
                                    "gemma",
                                    base_model_name=args.model_dir)
            return [path, path + ".opt"]

        async_ckpt.submit(ckpt, step, write, final=final,
                          snapshot_ms=snap_ms)

    # in-loop MFU from the shared estimator (core/telemetry.py)
    from mobilefinetuner_tpu.core.telemetry import transformer_flops
    flops = transformer_flops(
        sum(int(x.size) for x in jax.tree.leaves(lora)),
        sum(int(x.size) for x in jax.tree.leaves(params)),
        args.batch_size * tc.grad_accum_steps, args.seq_len,
        config.num_hidden_layers, config.num_attention_heads,
        config.head_dim, full_ft=False)

    common.run_training(
        args, trainable=lora, frozen=params, loss_fn=loss_fn, nll_fn=nll_fn,
        train_ds=train_ds, valid_ds=valid_ds, total_steps=total_steps,
        tc=tc, mask=mask, start_step=start_step, opt_state=opt_state,
        save_hook=save_hook, mesh=mesh, dropout_rng=base_rng,
        flops_per_step=flops,
        load_hook=common.make_rollback_loader(
            tc, mask, lambda p: peft_io.load_adapter(p)[0]),
        ckpt_path=os.path.join(args.output_dir, "gemma_lora.safetensors"),
        # memory-admission degradation ladder (DESIGN.md §21)
        degrade_builders={"offload": offload_rung})
    return 0


if __name__ == "__main__":
    sys.exit(main())
