"""Multi-tenant LoRA training CLI: k adapter jobs, one base forward.

Drives mobilefinetuner_tpu/multitenant/ (DESIGN.md §23) from a
declarative jobs file (multitenant/jobspec.py): every per-job quantity —
LR schedule, step budget, adapter alpha, seeds, save path + checkpoint
policy — is DATA the engine multiplexes through one compiled train step,
so k personal adapters fine-tune against one frozen base at near-flat
step time in k (the mLoRA/LoRAFusion target; bench.py's multitenant
rows price it).

Usage:
  python -m mobilefinetuner_tpu.cli.train_multi_lora \
      --jobs jobs.json --pretrained_dir /path/gpt2 \
      --data_dir /path/wikitext-2 --slots 4 --out_dir out/

Jobs file (JSON or TOML):
  {"family": "gpt2",
   "defaults": {"rank": 8, "steps": 200},
   "jobs": [{"name": "alice", "lr": 1e-4, "seed": 1},
            {"name": "bob", "lr": 3e-4, "alpha": 32.0}]}
"""

from __future__ import annotations

import argparse
import sys

from mobilefinetuner_tpu.cli import common
from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.core.telemetry import Telemetry
from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset
from mobilefinetuner_tpu.multitenant import (EngineConfig,
                                             MultiTenantEngine,
                                             load_jobs_file)

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="train_multi_lora",
        description="k concurrent LoRA jobs through one shared base "
                    "forward (multitenant/, DESIGN.md §23)")
    p.add_argument("--jobs", required=True,
                   help="jobs file (.json or .toml) — family, defaults, "
                        "and the per-job specs (multitenant/jobspec.py)")
    p.add_argument("--pretrained_dir", required=True,
                   help="HF checkpoint dir of the SHARED frozen base")
    p.add_argument("--data_dir", required=True,
                   help="WikiText-2 directory (per-job streams differ "
                        "by each job's data_seed/data_fraction)")
    p.add_argument("--out_dir", default="multi_lora_out",
                   help="save root for jobs without an explicit "
                        "save_path")
    g = p.add_argument_group("engine (static — fixes the compiled step)")
    g.add_argument("--slots", type=int, default=4,
                   help="concurrent tenant slots; pending jobs refill "
                        "freed slots with zero retraces")
    g.add_argument("--batch_size", type=int, default=1,
                   help="micro-batch rows EACH tenant contributes per "
                        "accumulation slice")
    g.add_argument("--grad_accum_steps", "--grad_accum", type=int,
                   default=1)
    g.add_argument("--seq_len", type=int, default=128)
    g.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32")
    g.add_argument("--lr_schedule",
                   choices=["cosine", "linear", "constant"],
                   default="cosine",
                   help="schedule SHAPE (engine-wide; per-job peak LR/"
                        "warmup/budget are data)")
    g.add_argument("--min_lr_ratio", type=float, default=0.1)
    g.add_argument("--clip_grad_norm", type=float, default=1.0,
                   help="per-tenant clip: each slot clips by ITS OWN "
                        "global norm, exactly like a solo run")
    g.add_argument("--weight_decay", type=float, default=0.0)
    g.add_argument("--lora_impl", choices=["auto", "naive", "fused"],
                   default="auto")
    g.add_argument("--skip_nonfinite", type=int, default=0,
                   help="1 = per-slot guarded update: a tenant whose "
                        "grads go non-finite skips ITS update only — "
                        "the other k-1 tenants' updates apply")
    g.add_argument("--prefetch", type=int, default=2,
                   help="per-tenant bounded input queue depth (0 = "
                        "synchronous); a stalled tenant stream cannot "
                        "starve the others or grow unbounded memory")
    g.add_argument("--log_interval", type=int, default=10,
                   help="metrics flush cadence in engine steps")
    g.add_argument("--async_save", type=int, default=1,
                   help="1 = finished adapters save through the "
                        "background writer (io/async_ckpt.py); 0 = "
                        "synchronous oracle")
    g.add_argument("--telemetry_out", default="",
                   help="JSONL event stream: tenant lifecycle events + "
                        "per-tenant step_stats sections "
                        "(tools/telemetry_report.py renders a tenants "
                        "table)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    family, jobs = load_jobs_file(args.jobs)
    log.info(f"jobs file: {len(jobs)} {family} job(s) "
             f"({', '.join(j.name for j in jobs)})")

    from mobilefinetuner_tpu.cli.family import load_family
    bundle = load_family(args.pretrained_dir, family=family)
    config = bundle.config
    if args.seq_len > bundle.max_len:
        log.warning(f"seq_len({args.seq_len}) > model max "
                    f"({bundle.max_len}), clamped")
        args.seq_len = bundle.max_len
    tok = bundle.tok

    def make_stream(spec):
        """One tenant's step-batch stream: its OWN seeded epoch shuffle
        and data fraction over the shared corpus, assembled exactly
        like a solo run's (cli/common.micro_batches) — the per-tenant
        half of the k-vs-solo parity oracle."""
        wt2 = WT2Config(seq_len=args.seq_len,
                        batch_size=args.batch_size,
                        data_fraction=spec.data_fraction,
                        seed=spec.data_seed)
        ds = WikiText2Dataset(args.data_dir, "train", wt2, tok.encode,
                              tok.eos_id)

        def gen():
            for _epoch, batch in common.micro_batches(
                    ds, args.grad_accum_steps):
                yield batch
        return gen()

    cfg = EngineConfig(
        slots=args.slots, rows_per_tenant=args.batch_size,
        grad_accum_steps=args.grad_accum_steps, seq_len=args.seq_len,
        dtype=args.dtype, clip_grad_norm=args.clip_grad_norm,
        weight_decay=args.weight_decay, schedule=args.lr_schedule,
        min_lr_ratio=args.min_lr_ratio, lora_impl=args.lora_impl,
        skip_nonfinite=bool(args.skip_nonfinite),
        prefetch=args.prefetch, flush_every=args.log_interval,
        async_save=bool(args.async_save), out_dir=args.out_dir)

    tel = Telemetry(args.telemetry_out) if args.telemetry_out else None
    with MultiTenantEngine(family, config, bundle.params, jobs,
                           make_stream, cfg, telemetry=tel) as eng:
        eng.run()
        for name, t in eng.tenants.items():
            log.info(f"  {name}: {t.status} @ step {t.steps_done} "
                     f"({t.tokens} tokens"
                     + (f", loss {t.last_loss:.4f}" if t.last_loss
                        is not None else "")
                     + f") -> {t.save_path}")
        retraces = eng.total_traces()
    log.info(f"multi-tenant run complete ({retraces} total traces)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
