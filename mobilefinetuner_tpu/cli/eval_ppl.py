"""WikiText-2 perplexity evaluation CLI (GPT-2 AND Gemma-3).

TPU-native rebuild of the reference `eval_ppl` binary
(reference: gpt2_lora_finetune/eval_ppl.cpp): load the model (+ optional
LoRA adapter, merged into the base weights or applied dynamically,
eval_ppl.cpp:110-127), run the split with token-weighted mean NLL
(mean_nll = Σ(loss·tokens)/Σtokens; ppl = exp(mean_nll),
eval_ppl.cpp:157-200), JSONL progress + final record, unmerge after
(eval_ppl.cpp:222 — moot here: merge is functional, the base tree is never
mutated). Goes beyond the reference by also covering Gemma-3 adapters
(merge via merge_gemma3 or dynamic), with the 262k-vocab head evaluated
through the chunked CE so [B,S,262144] fp32 logits are never materialized
— the reference has no Gemma eval binary at all.

Usage:
  python -m mobilefinetuner_tpu.cli.eval_ppl \
      --pretrained_dir /path/gpt2-or-gemma --data_root /path/wikitext-2 \
      --split valid [--lora_path adapter.safetensors --lora_merge]
The model family is auto-detected from config.json (model_type /
text_config); force with --family.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time

import jax
import jax.numpy as jnp

from mobilefinetuner_tpu.cli.family import apply_adapter, load_family
from mobilefinetuner_tpu.core.logging import JSONLWriter, get_logger
from mobilefinetuner_tpu.data.prefetch import Prefetcher
from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset
from mobilefinetuner_tpu.ops.loss import (lm_cross_entropy_sum,
                                          perplexity_from_loss)

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="eval_ppl", description="WikiText-2 perplexity (TPU)")
    p.add_argument("--pretrained_dir", required=True)
    p.add_argument("--data_root", required=True)
    p.add_argument("--split", default="valid", choices=["valid", "test"])
    p.add_argument("--lora_path", default="")
    p.add_argument("--lora_merge", action="store_true",
                   help="fold the adapter into base weights instead of "
                        "applying it dynamically")
    p.add_argument("--batch_size", type=int, default=2)
    p.add_argument("--seq_len", type=int, default=1024)
    p.add_argument("--stride", type=int, default=0,
                   help="chunk stride; 0 = seq_len (no overlap, the "
                        "reference default stride=-1)")
    p.add_argument("--max_batches", type=int, default=0)
    p.add_argument("--log_every", type=int, default=20)
    p.add_argument("--out", default="", help="JSONL output path")
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32")
    p.add_argument("--family", choices=["auto", "gpt2", "gemma"],
                   default="auto")
    p.add_argument("--loss_chunks", type=int, default=8,
                   help="sequence chunks for Gemma's 262k-vocab chunked "
                        "CE")
    p.add_argument("--prefetch", type=int, default=2,
                   help="async input pipeline depth (background batch "
                        "producer + device-placement lookahead, "
                        "data/prefetch.py); 0 = synchronous")
    p.add_argument("--telemetry_out", default="",
                   help="JSONL run-telemetry stream (core/telemetry.py): "
                        "run_start manifest + eval progress + run_end")
    p.add_argument("--run_registry", default="",
                   help="append-only run registry stream (core/"
                        "run_registry.py): one crash-safe record per "
                        "eval run; default $MFT_RUN_REGISTRY, empty = "
                        "off")
    from mobilefinetuner_tpu.cli.common import add_mem_flags
    add_mem_flags(p)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    b = load_family(args.pretrained_dir, args.family)
    family = b.family
    lora = apply_adapter(b, args.lora_path, args.lora_merge)
    config, params, tok = b.config, b.params, b.tok

    if family == "gemma":
        from mobilefinetuner_tpu.models import gemma3
        from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
        encode = lambda s: tok.encode(s, add_bos=False)
        eos_id, pad_id = tok.eos_id, tok.pad_id

        @jax.jit
        def step(params, lora, batch):
            hidden = gemma3.hidden_states(
                config, params, batch["input_ids"],
                attention_mask=batch["attention_mask"], lora=lora,
                compute_dtype=compute_dtype)
            # an lm_head adapter entry rides the chunked CE as
            # lora_head (hidden_states only applies per-layer sites;
            # dropping it here would score a different model than the
            # one trained — DESIGN.md §17)
            head_entry = (None if lora is None
                          else lora["blocks"].get("lm_head"))
            return chunked_lm_cross_entropy_sum(
                hidden, params["embed"], batch["labels"],
                num_chunks=args.loss_chunks, lora_head=head_entry)
    else:
        from mobilefinetuner_tpu.models import gpt2
        encode, eos_id, pad_id = tok.encode, tok.eos_id, None

        @jax.jit
        def step(params, lora, batch):
            logits = gpt2.forward(config, params, batch["input_ids"],
                                  attention_mask=batch["attention_mask"],
                                  lora=lora, compute_dtype=compute_dtype)
            return lm_cross_entropy_sum(logits, batch["labels"])

    max_pos = b.max_len

    # Commit the weights to the device ONCE: checkpoint loading yields
    # host numpy arrays, and leaving them as jit arguments re-transfers
    # the full model every batch (20 s/batch for GPT-2s over a tunneled
    # TPU link vs milliseconds resident).
    params = jax.device_put(params)
    if lora is not None:
        lora = jax.device_put(lora)

    args.seq_len = min(args.seq_len, max_pos)
    wt2 = WT2Config(seq_len=args.seq_len, batch_size=args.batch_size,
                    stride=args.stride or None, shuffle=False,
                    drop_last=False)
    ds = WikiText2Dataset(args.data_root, args.split, wt2, encode,
                          eos_id, pad_id=pad_id)

    jsonl = JSONLWriter(args.out) if args.out else None
    from mobilefinetuner_tpu.core.telemetry import Telemetry, run_manifest
    # fleet-aware: each process writes its own host-stamped shard
    # (coordinator at the given path; merge with tools/fleet_report.py)
    tel = Telemetry.for_process(args.telemetry_out)
    tel.emit("run_start", **run_manifest(vars(args)))
    # run registry (core/run_registry.py): a crash between here and
    # finalize settles to "interrupted" on the next registry open
    from mobilefinetuner_tpu.core.run_registry import RunRegistry
    _reg = RunRegistry.from_args(args)
    run_rec = _reg.begin(
        "eval", "eval_ppl", config=vars(args),
        platform=jax.devices()[0].platform,
        artifacts=[p for p in (tel.path, args.out) if p],
        telemetry=tel) if _reg else None
    # memory-admission preflight (DESIGN.md §21): AOT-compile the
    # dominant full-shape batch and check it against device capacity
    # BEFORE the data loop — the same mem_check the train path emits,
    # minus the degradation ladder (--on_oom_risk fail raises the
    # named MemoryAdmissionError here; degrade/warn proceed with a
    # warning). The compiled executable then serves every full-shape
    # batch below, so the preflight compile IS the run's compile — the
    # short epoch tail (drop_last=False) falls back to the jit cache.
    from mobilefinetuner_tpu.cli.common import preflight_eval_compile
    full_shape = (args.batch_size, args.seq_len)
    spec = {"input_ids": jax.ShapeDtypeStruct(full_shape, jnp.int32),
            "attention_mask": jax.ShapeDtypeStruct(full_shape,
                                                   jnp.float32),
            "labels": jax.ShapeDtypeStruct(full_shape, jnp.int32)}
    compiled_step = preflight_eval_compile(
        lambda: step.lower(params, lora, spec).compile(), args, tel,
        what="eval_ppl compiled step")

    def run_step(batch):
        if batch["input_ids"].shape == full_shape:
            return compiled_step(params, lora, batch)
        return step(params, lora, batch)
    # device-side accumulation: per-batch float(s)/int(c) forced a full
    # device sync per eval step — the sums stay on device (tiny adds on
    # the async dispatch queue) and come to host only at progress-log
    # boundaries and once after the loop. Batches arrive via the async
    # producer + placement lookahead (tokenization and the host->device
    # transfer overlap the previous batch's compute; --prefetch 0 is the
    # synchronous reference path).
    total, count, n_done = None, None, 0
    t0 = time.time()
    source = ds.epoch(0)
    if args.max_batches:
        source = itertools.islice(source, args.max_batches)
    with Prefetcher(source, depth=args.prefetch,
                    place_fn=jax.device_put,
                    rss_limit_mb=args.prefetch_rss_mb) as batches:
        for n, batch in enumerate(batches):
            s, c = run_step(batch)
            total = s if total is None else total + s
            count = c if count is None else count + c
            n_done = n + 1
            if args.log_every and (n + 1) % args.log_every == 0:
                t, k = jax.device_get((total, count))
                mean = float(t) / max(int(k), 1)
                log.info(f"batch {n + 1}/{ds.num_batches()} "
                         f"nll={mean:.4f} "
                         f"ppl={perplexity_from_loss(mean):.2f}")
                if jsonl:
                    jsonl.write({"type": "progress", "batch": n + 1,
                                 "nll": mean,
                                 "ppl": perplexity_from_loss(mean)})
                tel.emit("eval", step=n + 1, loss=mean,
                         ppl=perplexity_from_loss(mean), tokens=int(k))
    if n_done:
        total, count = jax.device_get((total, count))
    total, count = (float(total), int(count)) if n_done else (0.0, 0)
    mean = total / max(count, 1)
    ppl = perplexity_from_loss(mean)
    record = {"type": "final", "family": family, "split": args.split,
              "nll": mean, "ppl": ppl, "tokens": count,
              "seq_len": args.seq_len, "lora": bool(args.lora_path),
              "merged": args.lora_merge,
              "seconds": round(time.time() - t0, 1)}
    log.info(f"{args.split} ppl={ppl:.3f} nll={mean:.4f} ({count} tokens)")
    if jsonl:
        jsonl.write(record)
    tel.emit("eval", step=n_done, loss=mean, ppl=ppl, tokens=count)
    # finalize before run_end so the mirrored `run` end event lands in
    # the stream while run_end stays the stream's LAST event
    if run_rec is not None:
        run_rec.finalize("ok")
    # goodput is None: the eval CLIs have no metered phase loop
    tel.emit("run_end", steps=n_done,
             wall_s=round(time.time() - t0, 3), exit="ok", goodput=None)
    tel.close()
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
