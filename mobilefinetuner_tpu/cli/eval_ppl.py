"""WikiText-2 perplexity evaluation CLI.

TPU-native rebuild of the reference `eval_ppl` binary
(reference: gpt2_lora_finetune/eval_ppl.cpp): load GPT-2 (+ optional LoRA
adapter, merged into the base weights or applied dynamically,
eval_ppl.cpp:110-127), run the split with token-weighted mean NLL
(mean_nll = Σ(loss·tokens)/Σtokens; ppl = exp(mean_nll),
eval_ppl.cpp:157-200), JSONL progress + final record, unmerge after
(eval_ppl.cpp:222 — moot here: merge is functional, the base tree is never
mutated).

Usage:
  python -m mobilefinetuner_tpu.cli.eval_ppl \
      --pretrained_dir /path/gpt2 --data_root /path/wikitext-2 \
      --split valid [--lora_path adapter.safetensors --lora_merge]
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp

from mobilefinetuner_tpu.core.logging import JSONLWriter, get_logger
from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset
from mobilefinetuner_tpu.io.checkpoints import load_gpt2
from mobilefinetuner_tpu.lora import peft_io
from mobilefinetuner_tpu.lora.lora import merge_gpt2
from mobilefinetuner_tpu.models import gpt2
from mobilefinetuner_tpu.ops.loss import (lm_cross_entropy_sum,
                                          perplexity_from_loss)

log = get_logger()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="eval_ppl", description="WikiText-2 perplexity (TPU)")
    p.add_argument("--pretrained_dir", required=True)
    p.add_argument("--data_root", required=True)
    p.add_argument("--split", default="valid", choices=["valid", "test"])
    p.add_argument("--lora_path", default="")
    p.add_argument("--lora_merge", action="store_true",
                   help="fold the adapter into base weights instead of "
                        "applying it dynamically")
    p.add_argument("--batch_size", type=int, default=2)
    p.add_argument("--seq_len", type=int, default=1024)
    p.add_argument("--stride", type=int, default=0,
                   help="chunk stride; 0 = seq_len (no overlap, the "
                        "reference default stride=-1)")
    p.add_argument("--max_batches", type=int, default=0)
    p.add_argument("--log_every", type=int, default=20)
    p.add_argument("--out", default="", help="JSONL output path")
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    config, params = load_gpt2(args.pretrained_dir)
    args.seq_len = min(args.seq_len, config.n_positions)

    lora = None
    if args.lora_path:
        lora, spec = peft_io.load_adapter(args.lora_path)
        log.info(f"adapter: r={spec.rank} alpha={spec.alpha} "
                 f"targets={spec.targets} "
                 f"({'merged' if args.lora_merge else 'dynamic'})")
        if args.lora_merge:
            params = merge_gpt2(params, lora)
            lora = None

    tok = GPT2BPETokenizer.from_pretrained(args.pretrained_dir)
    wt2 = WT2Config(seq_len=args.seq_len, batch_size=args.batch_size,
                    stride=args.stride or None, shuffle=False,
                    drop_last=False)
    ds = WikiText2Dataset(args.data_root, args.split, wt2, tok.encode,
                          tok.eos_id)
    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    @jax.jit
    def step(params, lora, batch):
        logits = gpt2.forward(config, params, batch["input_ids"],
                              attention_mask=batch["attention_mask"],
                              lora=lora, compute_dtype=compute_dtype)
        return lm_cross_entropy_sum(logits, batch["labels"])

    jsonl = JSONLWriter(args.out) if args.out else None
    total, count = 0.0, 0
    t0 = time.time()
    for n, batch in enumerate(ds.epoch(0)):
        s, c = step(params, lora, batch)
        total += float(s)
        count += int(c)
        if args.log_every and (n + 1) % args.log_every == 0:
            mean = total / max(count, 1)
            log.info(f"batch {n + 1}/{ds.num_batches()} "
                     f"nll={mean:.4f} ppl={perplexity_from_loss(mean):.2f}")
            if jsonl:
                jsonl.write({"type": "progress", "batch": n + 1,
                             "nll": mean,
                             "ppl": perplexity_from_loss(mean)})
        if args.max_batches and n + 1 >= args.max_batches:
            break
    mean = total / max(count, 1)
    ppl = perplexity_from_loss(mean)
    record = {"type": "final", "split": args.split, "nll": mean, "ppl": ppl,
              "tokens": count, "seq_len": args.seq_len,
              "lora": bool(args.lora_path), "merged": args.lora_merge,
              "seconds": round(time.time() - t0, 1)}
    log.info(f"{args.split} ppl={ppl:.3f} nll={mean:.4f} ({count} tokens)")
    if jsonl:
        jsonl.write(record)
    import json as _json
    print(_json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
