"""Block-paged KV cache: one preallocated pool, a host-side allocator.

The generate() path gives every request a contiguous [L, B, KV, T, D]
cache sized for its worst case — at serving batch sizes that fragments
HBM (a 2-token health-check ping reserves as much cache as a 2k-token
completion). Here the cache is ONE pool of fixed-size pages,

    pool_k / pool_v : [num_blocks, L, KV, block_T, D]

and request r's logical column t lives at physical page
`tbl[r, t // block_T]`, offset `t % block_T` — the vLLM PagedAttention
layout, TPU-shaped: block_T is sublane-aligned so a page is a clean
[bT, D] tile, and every page holds ALL layers' K/V for its span (one
allocator decision covers L scatters).

The allocator is deliberately host-side and trivial (a free list over
ints): allocation happens at most once per admitted request plus once
per block_T generated tokens, never inside the compiled step. Block 0
is reserved as the TRASH page: idle slots' writes and padded
block-table rows land there, so the device program needs no branches —
occupancy is expressed entirely through indices and masks.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

# physical page 0 is never allocated: idle slots write their garbage
# K/V there and padded block-table rows point at it (always masked)
TRASH_BLOCK = 0


class OutOfBlocks(RuntimeError):
    """The pool cannot hold another request (admission-time signal; the
    engine's reservation accounting makes mid-flight exhaustion a bug,
    not an operational state)."""


def blocks_for(tokens: int, block_T: int) -> int:
    """Pages needed to cache `tokens` columns."""
    return max(0, -(-int(tokens) // block_T))


def init_pools(num_blocks: int, L: int, KV: int, block_T: int, D: int,
               dtype=jnp.float32):
    """The two device pools, zero-filled (the trash page must hold
    finite values: idle slots attend their own zero column)."""
    shape = (num_blocks, L, KV, block_T, D)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def pool_partition_spec(kv_sharded: bool):
    """PartitionSpec for the pools under the serve ("dp", "tp") mesh
    (serve/sharding.py). The KV-head axis is the ONLY shardable one:
    page identity (NB) must stay whole so one host-side block table
    serves every shard, L is scanned over, and [bT, D] is the page tile
    the Pallas kernel DMAs. kv_sharded gives each tp shard a per-shard
    head slice [NB, L, KV/tp, bT, D] of every page; otherwise (GQA
    head counts indivisible by tp) the pools replicate and the query
    groups shard instead (ops/decode_attention.shard_heads)."""
    from jax.sharding import PartitionSpec as P
    return P(None, None, "tp", None, None) if kv_sharded else P()


def write_prompt_blocks(pool_k, pool_v, k, v, block_ids):
    """Scatter one prefilled request's K/V into its allocated pages.

    k/v: [L, KV, Ppad, D] from *_prefill (B squeezed), Ppad a block_T
    multiple; block_ids: [Ppad // block_T] physical pages, TRASH-padded
    past the prompt's real pages (their garbage columns are never
    attendable). Pure — the engine jits this with the pools donated.
    """
    NB, L, KV, bT, D = pool_k.shape
    M = k.shape[2] // bT
    # [L, KV, M, bT, D] -> [M, L, KV, bT, D]: one row per physical page
    pages = lambda t: t.reshape(L, KV, M, bT, D).transpose(2, 0, 1, 3, 4)
    pool_k = pool_k.at[block_ids].set(pages(k).astype(pool_k.dtype))
    pool_v = pool_v.at[block_ids].set(pages(v).astype(pool_v.dtype))
    return pool_k, pool_v


class BlockAllocator:
    """Free-list allocator over the pool's pages (block 0 reserved).

    alloc/append/free are the request lifecycle: `alloc(n)` takes the
    prompt's pages at admission, `append()` one more page when decode
    crosses a page boundary, `free(ids)` returns everything when the
    request finishes (or is cancelled). LIFO reuse keeps recently-hot
    pages recently-reused.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 data + reserved trash block "
                f"{TRASH_BLOCK}), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        """Pages currently handed out (trash page excluded) — the
        leak-accounting observable: after every request has reached a
        terminal state this must be 0, whatever path (finish, cancel,
        timeout, contained step error) released the pages."""
        return self.num_blocks - 1 - len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise OutOfBlocks(
                f"asked for {n} pages, {len(self._free)} free "
                f"(pool has {self.num_blocks - 1} allocatable)")
        out = [self._free.pop() for _ in range(n)]
        return out

    def append(self) -> int:
        return self.alloc(1)[0]

    def free(self, ids) -> None:
        for b in ids:
            b = int(b)
            if b == TRASH_BLOCK:
                raise ValueError("freeing the reserved trash block")
            if b in self._free or not 0 < b < self.num_blocks:
                raise ValueError(f"double/invalid free of block {b}")
            self._free.append(b)
