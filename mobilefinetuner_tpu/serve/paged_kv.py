"""Block-paged KV cache: one preallocated pool, a host-side allocator.

The generate() path gives every request a contiguous [L, B, KV, T, D]
cache sized for its worst case — at serving batch sizes that fragments
HBM (a 2-token health-check ping reserves as much cache as a 2k-token
completion). Here the cache is ONE pool of fixed-size pages,

    pool_k / pool_v : [num_blocks, L, KV, block_T, D]

and request r's logical column t lives at physical page
`tbl[r, t // block_T]`, offset `t % block_T` — the vLLM PagedAttention
layout, TPU-shaped: block_T is sublane-aligned so a page is a clean
[bT, D] tile, and every page holds ALL layers' K/V for its span (one
allocator decision covers L scatters).

The allocator is deliberately host-side and trivial (a free list over
ints): allocation happens at most once per admitted request plus once
per block_T generated tokens, never inside the compiled step. Block 0
is reserved as the TRASH page: idle slots' writes and padded
block-table rows land there, so the device program needs no branches —
occupancy is expressed entirely through indices and masks.

Round 21 (shared-prefix KV reuse, DESIGN.md §26) makes pages
REFCOUNTED: requests whose prompts share a hashed full-block prefix
map the same physical pages, so a page is released only on its LAST
reference. A page whose refcount hits zero while its contents are
still registered in the engine's PrefixCache is PARKED instead of
freed: parked pages count as free (they are reclaimable at any
moment, LRU-first) but keep their contents until the allocator
actually needs them — that is what turns a finished request's prompt
pages into the next request's prefix hit. The leak observable is
unchanged: `in_use` counts only referenced pages, so "every request
terminal => in_use == 0" holds whether or not a cache is parked on
top.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional

import jax.numpy as jnp

# physical page 0 is never allocated: idle slots write their garbage
# K/V there and padded block-table rows point at it (always masked)
TRASH_BLOCK = 0


class OutOfBlocks(RuntimeError):
    """The pool cannot hold another request (admission-time signal; the
    engine's reservation accounting makes mid-flight exhaustion a bug,
    not an operational state)."""


def blocks_for(tokens: int, block_T: int) -> int:
    """Pages needed to cache `tokens` columns."""
    return max(0, -(-int(tokens) // block_T))


def init_pools(num_blocks: int, L: int, KV: int, block_T: int, D: int,
               dtype=jnp.float32):
    """The two device pools, zero-filled (the trash page must hold
    finite values: idle slots attend their own zero column)."""
    shape = (num_blocks, L, KV, block_T, D)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def pool_partition_spec(kv_sharded: bool):
    """PartitionSpec for the pools under the serve ("dp", "tp") mesh
    (serve/sharding.py). The KV-head axis is the ONLY shardable one:
    page identity (NB) must stay whole so one host-side block table
    serves every shard, L is scanned over, and [bT, D] is the page tile
    the Pallas kernel DMAs. kv_sharded gives each tp shard a per-shard
    head slice [NB, L, KV/tp, bT, D] of every page; otherwise (GQA
    head counts indivisible by tp) the pools replicate and the query
    groups shard instead (ops/decode_attention.shard_heads)."""
    from jax.sharding import PartitionSpec as P
    return P(None, None, "tp", None, None) if kv_sharded else P()


def write_prompt_blocks(pool_k, pool_v, k, v, block_ids):
    """Scatter one prefilled request's K/V into its allocated pages.

    k/v: [L, KV, Ppad, D] from *_prefill (B squeezed), Ppad a block_T
    multiple; block_ids: [Ppad // block_T] physical pages, TRASH-padded
    past the prompt's real pages (their garbage columns are never
    attendable). Pure — the engine jits this with the pools donated.
    """
    NB, L, KV, bT, D = pool_k.shape
    M = k.shape[2] // bT
    # [L, KV, M, bT, D] -> [M, L, KV, bT, D]: one row per physical page
    pages = lambda t: t.reshape(L, KV, M, bT, D).transpose(2, 0, 1, 3, 4)
    pool_k = pool_k.at[block_ids].set(pages(k).astype(pool_k.dtype))
    pool_v = pool_v.at[block_ids].set(pages(v).astype(pool_v.dtype))
    return pool_k, pool_v


class BlockAllocator:
    """Refcounted free-list allocator over the pool's pages (block 0
    reserved).

    alloc/append/free are the request lifecycle: `alloc(n)` takes the
    prompt's pages at admission, `append()` one more page when decode
    crosses a page boundary, `free(ids)` drops one REFERENCE per page
    when the request finishes (or is cancelled). LIFO reuse keeps
    recently-hot pages recently-reused.

    Shared-prefix reuse (round 21) adds three verbs on top:

      retain(b)     +1 ref on an in-use page (a second request mapped
                    the same physical prefix page);
      adopt(b)      revive a PARKED page (ref 0, contents cached) back
                    to ref 1 — a prefix hit on a finished request's
                    pages;
      free(ids, park=fn)   at ref 0, `park(b)` decides the page's
                    fate: a cache key means "park it" (contents stay,
                    page counts as free, reclaimable LRU-first), None
                    means plain free. Reclaiming a parked page calls
                    `on_evict(b, key)` so the cache unregisters it.

    Pages are never referenced and parked at once: `in_use` counts
    exactly the referenced pages, so the terminal-accounting invariant
    (everything terminal => in_use == 0) is cache-agnostic.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 data + reserved trash block "
                f"{TRASH_BLOCK}), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        # parked pages: ref 0, contents registered in a PrefixCache —
        # insertion order is the LRU order (oldest first; a page parks
        # at the MRU end every time its last reference drops)
        self._parked: "collections.OrderedDict[int, object]" = \
            collections.OrderedDict()
        # called as on_evict(block, key) when alloc() reclaims a parked
        # page — the PrefixCache unregisters the mapping there
        self.on_evict: Optional[Callable[[int, object], None]] = None
        # lifetime count of pages handed out by alloc()/append() — the
        # bench's KV-cost denominator: prefix hits acquire() instead of
        # alloc(), so pages-per-request dropping below the cache-off
        # figure is the reuse actually paying
        self.pages_allocated = 0

    @property
    def free_blocks(self) -> int:
        """Allocatable pages: truly free + parked (parked pages are
        reclaimable at any moment, so admission math counts them)."""
        return len(self._free) + len(self._parked)

    @property
    def in_use(self) -> int:
        """Pages currently referenced (trash page excluded) — the
        leak-accounting observable: after every request has reached a
        terminal state this must be 0, whatever path (finish, cancel,
        timeout, contained step error) released the pages. Parked pages
        hold cache contents but NO references, so they count as free."""
        return len(self._ref)

    @property
    def parked_blocks(self) -> int:
        return len(self._parked)

    @property
    def refcounts(self) -> Dict[int, int]:
        """Snapshot {block: refcount} of every referenced page (the
        round-21 accounting observable: empty once everything is
        terminal — each shared page's count returned to zero)."""
        return dict(self._ref)

    def alloc(self, n: int) -> List[int]:
        if n > self.free_blocks:
            raise OutOfBlocks(
                f"asked for {n} pages, {self.free_blocks} free "
                f"(pool has {self.num_blocks - 1} allocatable)")
        out = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                # reclaim the least-recently-parked cached page; the
                # cache forgets it before the new owner ever writes
                b, key = self._parked.popitem(last=False)
                if self.on_evict is not None:
                    self.on_evict(b, key)
            self._ref[b] = 1
            out.append(b)
        self.pages_allocated += n
        return out

    def append(self) -> int:
        return self.alloc(1)[0]

    def retain(self, b: int) -> None:
        """One more reference on an in-use page (prefix sharing)."""
        b = int(b)
        if b not in self._ref:
            raise ValueError(f"retain of un-referenced block {b}")
        self._ref[b] += 1

    def acquire(self, b: int) -> None:
        """Take one reference on a CACHED page whichever state it is in:
        retain() if some resident already references it, adopt() if it
        sits parked — the engine's one prefix-hit acquisition verb.
        Acquired pages are eviction-proof, so acquire every cached page
        BEFORE alloc()ing fresh ones."""
        b = int(b)
        if b in self._ref:
            self._ref[b] += 1
        else:
            self.adopt(b)

    def adopt(self, b: int) -> None:
        """Revive a parked page to ref 1 (a prefix hit on cached
        contents). The page must currently be parked."""
        b = int(b)
        if b not in self._parked:
            raise ValueError(f"adopt of un-parked block {b}")
        del self._parked[b]
        self._ref[b] = 1

    def free(self, ids, park: Optional[Callable[[int], object]] = None
             ) -> None:
        """Drop one reference per page; at zero the page parks (when
        `park(b)` returns its cache key) or returns to the free list."""
        for b in ids:
            b = int(b)
            if b == TRASH_BLOCK:
                raise ValueError("freeing the reserved trash block")
            if b not in self._ref or not 0 < b < self.num_blocks:
                raise ValueError(f"double/invalid free of block {b}")
            self._ref[b] -= 1
            if self._ref[b]:
                continue
            del self._ref[b]
            key = park(b) if park is not None else None
            if key is not None:
                self._parked[b] = key      # MRU end of the LRU order
            else:
                self._free.append(b)

    def flush_parked(self) -> int:
        """Forget every parked page (containment rebuilt the pools, so
        cached contents no longer exist). Returns how many were
        dropped; the PrefixCache flushes its own mappings alongside."""
        n = len(self._parked)
        while self._parked:
            b, _ = self._parked.popitem()
            self._free.append(b)
        return n
