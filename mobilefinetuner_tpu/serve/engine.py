"""Continuous-batching serve loop: static slots, paged KV, adapter routing.

The scheduler's unit of work is one DECODE STEP over `num_slots` static
batch slots. Requests are admitted into free slots (one prefill each)
and evicted the step they finish — occupancy changes every step, but
every array the compiled step sees keeps its shape:

    tok [S]      the token each slot feeds this step
    pos [S]      its cache position (tokens already cached)
    tbl [S, M]   per-slot block tables into the shared page pool
    aid [S]      per-slot adapter index into the resident bank

Idle slots are not branches, they are DATA: pos=0, tbl=trash, and their
outputs are ignored on the host. That is the compile-stability
invariant the whole design serves — after warmup (one prefill trace +
one step trace) admissions, evictions, and adapter hot-swaps reuse the
same two executables (tests/test_serve.py asserts <= 2 traces after
warmup; `trace_counts` is the observable).

Greedy decode (temperature 0) is the bit-exact oracle: per-request
outputs are token-identical to batch-at-a-time generate() with the
same adapter (the paged-vs-contiguous parity suite) — deterministic
outputs are what make a serving rollout auditable. Round 21 adds
per-slot SAMPLING as data: temperature/top-k/top-p and a seeded
per-request PRNG key ride the slot arrays, the key is folded with the
emitted token's ABSOLUTE position (so cache on/off and chunked/
unchunked admission draw the identical stream for the same seed), and
rows with temperature <= 0 still take the greedy argmax inside the
same compiled step — one executable serves mixed greedy/sampled slots.

Round 21 (DESIGN.md §26) scales the plane to shared traffic:

  - shared-prefix KV reuse: full prompt blocks are chain-hashed
    (content + KV-producing weight identity) into a PrefixCache;
    requests with a common prefix map the SAME refcounted
    physical pages, copy-on-write at the divergence block, freed on
    last reference — pages whose contents are still cached PARK
    (reclaimable LRU-first) instead of freeing, so a finished
    request's prompt pages become the next request's prefix hit;
  - chunked prefill admission: prompts beyond max_prompt (up to
    max_prompt_chunked) — and cache-hit suffixes — prefill in static
    bucket-width chunks under a per-step() token budget of ONE widest
    bucket across the engine, so a long prompt costs the residents
    bounded TPOT jitter, never a head-of-line stall (while concurrent
    short suffixes share a step instead of serializing); chunk widths
    come from a static bucket set, one trace per width, never one per
    prompt length;
  - submit() rejects (reason=prompt_too_long) only beyond the TRUE
    cap max(max_prompt, max_prompt_chunked); everything else queues.

Scheduling policy is FCFS with conservative page reservation: a request
is admitted only when its worst case (prompt + max_new_tokens pages)
fits what the pool has left after every resident's own worst case.
Pages are still handed out LAZILY (alloc at admission for the prompt,
append on page-boundary crossings), so short/eos-early requests return
their tail reservation without ever touching it; the reservation only
guarantees `append` cannot fail mid-flight — there is no preemption
path to need.

Round 14 (DESIGN.md §19) hardens the loop for production traffic — the
serve-side mirror of the training path's sensors-to-recovery discipline:

  - bounded admission: `max_queue` caps the FCFS queue; over-limit
    submits terminate with `request{phase=reject, reason=queue_full}`,
    or `shed_policy="deadline"` drops the queued request closest to
    blowing its own deadline instead of the newest arrival;
  - per-request deadlines: `submit(..., deadline_ms=)` — queued
    requests past deadline are timed out WITHOUT ever prefilling,
    active ones are cancelled at the next step boundary with their
    partial output intact (phase=timeout, slot + pages released);
  - crash containment: a step-dispatch exception fails only the
    in-flight requests (phase=error, reason=<exception type>), resets
    slots and the page pool to a clean empty state, and — under the
    default `on_step_error="fail_active"` — keeps serving the queue;
  - graceful drain: `install_preemption()` arms a
    core/preempt.PreemptionGuard; the first SIGTERM stops admissions,
    rejects the queued remainder (reason=shutdown), finishes in-flight
    requests, and close() records `run_end{exit=preempted,
    reason=preempted}`; a second signal escalates (KeyboardInterrupt)
    so the caller cancels in-flight;
  - health: `health()` snapshots queue depth / occupancy / page
    headroom / rolling p95 step latency / terminal-state counters,
    emitted as cadenced `serve_stats` events under `stats_every`.

Every terminal transition goes through ONE bookkeeping path
(`_terminal`), so a request emits exactly one terminal `request` phase
and releases exactly the pages it allocated — the leak-accounting
invariant tests/test_serve_robustness.py asserts after every injected
fault. None of this touches the compiled programs: rejects, timeouts,
sheds, containment, and drain are host-side bookkeeping, so the ≤2
post-warmup trace invariant holds across every fault path.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.core.preempt import PreemptionGuard
from mobilefinetuner_tpu.core.telemetry import (HangWatchdog, Telemetry,
                                                run_manifest)
from mobilefinetuner_tpu.lora.lora import assign_adapters
from mobilefinetuner_tpu.models.generate import (gemma3_decode_step_paged,
                                                 gemma3_prefill,
                                                 gemma3_prefill_chunk,
                                                 gpt2_decode_step_paged,
                                                 gpt2_prefill,
                                                 gpt2_prefill_chunk,
                                                 sample_per_row)
from mobilefinetuner_tpu.serve.adapters import AdapterBank
from mobilefinetuner_tpu.serve.paged_kv import (TRASH_BLOCK, BlockAllocator,
                                                OutOfBlocks, blocks_for,
                                                init_pools,
                                                write_prompt_blocks)
from mobilefinetuner_tpu.serve.prefix_cache import PrefixCache, chain_keys

# lock-discipline declaration (core/static_checks.py, DESIGN.md §24):
# the engine is single-threaded BY DESIGN — every mutation happens on
# the serve loop's thread. health() is read from metrics_http handler
# threads, but it only snapshots scalar counters/gauges (torn reads are
# benign: no invariant spans two fields), and the HangWatchdog pet
# rides telemetry's own lock. Any future cross-thread MUTABLE state
# must be declared guarded here, with a real lock.
GRAFT_SHARED_STATE = {
    "ServeEngine": {
        "lock": "_health_lock",
        "guarded": ["_step_ms"],
        "channels": [],
        "note": "single-threaded step loop; health() runs on "
                "metrics_http handler threads (r17) — its deque "
                "iteration shares _health_lock with the loop's append; "
                "every other health() read is a scalar-only snapshot "
                "by contract",
    },
}


@dataclasses.dataclass
class ServeConfig:
    """Engine shape knobs — all STATIC: together they fix the compiled
    prefill/step programs and the pool's HBM footprint."""
    num_slots: int = 8        # concurrent requests per decode step
    block_T: int = 16         # tokens per KV page (sublane-aligned)
    num_blocks: int = 512     # pool pages incl. the reserved trash page
    max_prompt: int = 64      # prompts right-padded to this (block_T mult)
    max_new_tokens: int = 64  # per-request generation cap
    dtype: str = "float32"    # compute + cache dtype
    attn_impl: str = "auto"   # auto | xla | pallas (paged attention path)
    lora_impl: str = "auto"   # auto | naive | fused (models/lora_apply)
    # --- robustness knobs (round 14, DESIGN.md §19) — host-side policy
    # only: none of these reach a traced program, so changing them can
    # never cost a retrace
    max_queue: int = 0        # FCFS queue cap; 0 = unbounded
    shed_policy: str = "reject"   # reject the newest arrival, or
                                  # "deadline": shed the queued request
                                  # closest to blowing its deadline
    on_step_error: str = "fail_active"  # contain a step-dispatch
                                  # exception (fail in-flight, keep
                                  # serving) or "raise" after containing
    stats_every: int = 0      # serve_stats cadence (decode steps); 0=off
    # --- observability (round 17, DESIGN.md §22) ---------------------
    trace_spans: bool = False  # emit queue/prefill/decode `span` events
                              # per request (track "req:<id>") into the
                              # telemetry stream — tools/trace_export.py
                              # renders a serve session as one Perfetto
                              # timeline. Host-side only: span emission
                              # can never cost a retrace.
    # --- memory admission (round 16, core/memory_guard.py) ----------
    hbm_cap_mb: int = 0       # capacity override MB; 0 = auto (the
                              # backend's bytes_limit, else the
                              # device-kind HBM table) — tests drive
                              # the refusal deterministically with it
    hbm_headroom: float = 0.1  # admission margin (same meaning as the
                              # train path's --hbm_headroom)
    # --- mesh sharding (round 20, serve/sharding.py) ----------------
    # the serving step runs under a (dp, tp) device mesh when
    # mesh_dp * mesh_tp > 1: tp shards heads + MLP hidden (and the KV
    # pool's head axis when divisible), dp shards the slot axis;
    # (1, 1) — the default — is the unsharded single-chip engine,
    # bit-for-bit the pre-r20 program. Static: the mesh shape is part
    # of the compiled programs' identity.
    mesh_dp: int = 1
    mesh_tp: int = 1
    # --- traffic-scale serving (round 21, DESIGN.md §26) -------------
    prefix_cache: bool = False  # shared-prefix KV reuse: chain-hash
                              # full prompt blocks, refcount pages,
                              # copy-on-write at the divergence block
    max_prompt_chunked: int = 0  # the TRUE prompt cap under chunked
                              # admission (block_T multiple >
                              # max_prompt); 0 disables chunk-only
                              # admission — prompts beyond max_prompt
                              # then reject with reason=prompt_too_long
    chunk_buckets: tuple = ()  # static chunk widths (block_T
                              # multiples); () auto-derives doubling
                              # widths capped at max_prompt — the
                              # per-dispatch prefill budget the pool
                              # was sized for — so a long prompt walks
                              # SEVERAL chunks with decode steps
                              # between them (bounded in-flight TPOT),
                              # instead of one cap-wide stall. Each
                              # width is ONE compiled executable —
                              # widths bucket, prompt lengths never
                              # retrace.
    sampling: bool = False    # per-slot temperature/top-k/top-p +
                              # seeded PRNG keys ride the slot arrays
                              # as data; False keeps every program
                              # bit-identical to the greedy-only engine

    @property
    def true_cap(self) -> int:
        """The engine's REAL prompt ceiling: max_prompt one-shot, or
        max_prompt_chunked when chunked admission extends it."""
        return max(self.max_prompt, self.max_prompt_chunked)

    def validate(self) -> None:
        from mobilefinetuner_tpu.models.lora_apply import \
            validate_lora_impl
        validate_lora_impl(self.lora_impl)
        if self.max_prompt % self.block_T:
            raise ValueError(
                f"max_prompt ({self.max_prompt}) must be a multiple of "
                f"block_T ({self.block_T})")
        if self.num_slots < 1 or self.max_new_tokens < 1:
            raise ValueError("num_slots and max_new_tokens must be >= 1")
        if self.max_queue < 0 or self.stats_every < 0:
            raise ValueError("max_queue and stats_every must be >= 0")
        if self.shed_policy not in ("reject", "deadline"):
            raise ValueError(
                f"shed_policy must be 'reject' or 'deadline', got "
                f"{self.shed_policy!r}")
        if self.on_step_error not in ("fail_active", "raise"):
            raise ValueError(
                f"on_step_error must be 'fail_active' or 'raise', got "
                f"{self.on_step_error!r}")
        if self.mesh_dp < 1 or self.mesh_tp < 1:
            raise ValueError(
                f"mesh_dp and mesh_tp must be >= 1, got "
                f"({self.mesh_dp}, {self.mesh_tp})")
        if self.mesh_dp > 1 and self.num_slots % self.mesh_dp:
            raise ValueError(
                f"num_slots ({self.num_slots}) must be a multiple of "
                f"mesh_dp ({self.mesh_dp}): the slot axis is the dp "
                f"batch axis")
        if self.max_prompt_chunked:
            if self.max_prompt_chunked % self.block_T:
                raise ValueError(
                    f"max_prompt_chunked ({self.max_prompt_chunked}) "
                    f"must be a multiple of block_T ({self.block_T})")
            if self.max_prompt_chunked <= self.max_prompt:
                raise ValueError(
                    f"max_prompt_chunked ({self.max_prompt_chunked}) "
                    f"must exceed max_prompt ({self.max_prompt}) — "
                    f"prompts within max_prompt prefill one-shot")
        for w in self.chunk_buckets:
            if w < 1 or w % self.block_T:
                raise ValueError(
                    f"chunk_buckets entries must be positive block_T "
                    f"({self.block_T}) multiples, got {w}")
        # the pool must hold at least one worst-case request, or FCFS
        # admission can never fire and drain() spins forever
        worst = blocks_for(self.true_cap + self.max_new_tokens - 1,
                           self.block_T)
        if self.num_blocks - 1 < worst:
            raise ValueError(
                f"num_blocks={self.num_blocks} cannot hold one "
                f"worst-case request: true prompt cap ({self.true_cap})"
                f" + max_new_tokens - 1 columns need {worst} pages "
                f"plus the reserved trash page (have "
                f"{self.num_blocks - 1} allocatable)")


@dataclasses.dataclass
class Request:
    """One generation request and its telemetry timeline."""
    id: int
    prompt: List[int]
    max_new_tokens: int
    adapter: Optional[str] = None      # resident bank name; None = base
    # lifecycle: queued -> active -> one of the TERMINAL states
    # (finished | cancelled | rejected | timeout | error); queued
    # requests can reach rejected/timeout without ever becoming active
    state: str = "queued"
    reason: Optional[str] = None       # terminal detail (REQUEST_REASONS
                                       # policy string, or the exception
                                       # type name on state=error)
    rid: Optional[int] = None          # round-22 fleet-wide request id a
                                       # router stamped at ingress; rides
                                       # every request event + span so
                                       # trace_export --router joins the
                                       # two process timelines. None on
                                       # direct submits.
    tokens: List[int] = dataclasses.field(default_factory=list)
    enqueue_t: float = 0.0
    admit_t: float = 0.0
    first_token_t: float = 0.0
    finish_t: float = 0.0
    deadline_t: float = 0.0            # absolute perf_counter deadline
                                       # (enqueue_t + deadline_ms); 0=none
    # round-21 sampling state (rejected at submit() unless the engine
    # was built with cfg.sampling): temperature 0 = the greedy oracle
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    # engine-internal
    slot: int = -1
    aid: int = 0
    blocks: List[int] = dataclasses.field(default_factory=list)
    worst_blocks: int = 0
    # round-21 chunked-admission / prefix-hit state
    prefill_pos: int = 0               # prompt tokens already cached
    prefilling: bool = False           # suffix chunks still pending
    awaiting_first: bool = False       # full prefix hit re-feed: the
                                       # next decode step emits token 1
    cache_keys: List[bytes] = dataclasses.field(default_factory=list,
                                                repr=False)

    TERMINAL = ("finished", "cancelled", "rejected", "timeout", "error")

    @property
    def done(self) -> bool:
        return self.state in self.TERMINAL

    @property
    def ttft_ms(self) -> Optional[float]:
        if not self.first_token_t:
            return None
        return (self.first_token_t - self.enqueue_t) * 1000.0

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean per-token latency AFTER the first token (the streaming
        cadence a client sees)."""
        if not self.finish_t or len(self.tokens) < 2:
            return None
        return ((self.finish_t - self.first_token_t)
                / (len(self.tokens) - 1) * 1000.0)


class ServeEngine:
    """The serving loop. Drive it with submit() + step() (or drain());
    close() terminates the telemetry stream.

    family: "gpt2" | "gemma"; params: the frozen base tree;
    bank: optional AdapterBank for multi-tenant routing;
    telemetry: optional core.telemetry.Telemetry (emits run_start /
    per-request `request` events / run_end).
    """

    def __init__(self, family: str, config, params,
                 cfg: Optional[ServeConfig] = None,
                 bank: Optional[AdapterBank] = None,
                 telemetry: Optional[Telemetry] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 watchdog: Optional[HangWatchdog] = None):
        cfg = cfg or ServeConfig()
        cfg.validate()
        if family == "gpt2":
            L, KV, D = config.n_layer, config.n_head, config.head_dim
            if cfg.true_cap + cfg.max_new_tokens > config.n_positions:
                raise ValueError(
                    f"prompt cap + max_new_tokens = "
                    f"{cfg.true_cap + cfg.max_new_tokens} exceeds "
                    f"n_positions={config.n_positions}")
            self._prefill_fn, self._step_fn = gpt2_prefill, \
                gpt2_decode_step_paged
            self._chunk_fn = gpt2_prefill_chunk
        elif family == "gemma":
            L = config.num_hidden_layers
            KV, D = config.num_key_value_heads, config.head_dim
            self._prefill_fn, self._step_fn = gemma3_prefill, \
                gemma3_decode_step_paged
            self._chunk_fn = gemma3_prefill_chunk
        else:
            raise ValueError(f"unknown family {family!r}")
        self.family, self.config, self.cfg = family, config, cfg
        self.bank = bank
        self.eos_id, self.pad_id = eos_id, pad_id
        self.dtype = jnp.dtype(cfg.dtype)
        # (dp, tp) mesh placement (round 20, serve/sharding.py):
        # ServeSharding owns every NamedSharding decision — weights
        # column/row-parallel, KV pool per-shard head slices, bank
        # block-diagonal. None = the unsharded single-chip engine.
        self.sharding = None
        if cfg.mesh_dp * cfg.mesh_tp > 1:
            from mobilefinetuner_tpu.serve.sharding import ServeSharding
            self.sharding = ServeSharding.build(
                family, config, cfg.mesh_dp, cfg.mesh_tp)

        S = cfg.num_slots
        # block tables are sized for the TRUE cap (== max_prompt when
        # chunking is off, so the decode program's shape — and its
        # pinned compiled contract — is unchanged on legacy configs)
        self.M = blocks_for(cfg.true_cap + cfg.max_new_tokens - 1,
                            cfg.block_T)
        # ---- memory admission at BUILD (round 16, DESIGN.md §21):
        # params + adapter bank + both KV pools are the engine's static
        # HBM footprint — refuse an infeasible num_blocks/num_slots
        # BEFORE anything lands on device (the sizes come from the RAW
        # input trees: a params-dominated over-capacity config must be
        # refused by name, not crash in the placement below), naming
        # the max feasible values so the retry is a calculation.
        from mobilefinetuner_tpu.core import memory_guard as mg
        per_block_mb = (2 * L * KV * cfg.block_T * D
                        * self.dtype.itemsize) / 2 ** 20
        self.pool_mb = per_block_mb * cfg.num_blocks

        def tree_mb(t):
            return sum(
                int(np.prod(np.shape(x)))
                * np.dtype(getattr(x, "dtype", np.float32)).itemsize
                for x in jax.tree.leaves(t)) / 2 ** 20

        params_mb = tree_mb(params)
        bank_mb = tree_mb(bank.tree) if bank is not None else 0.0
        self.mem_check = mg.analytic_check(
            params_mb + bank_mb + self.pool_mb, cap_mb=cfg.hbm_cap_mb,
            headroom=cfg.hbm_headroom)
        if self.mem_check.verdict == "over":
            budget = (self.mem_check.cap_mb * (1 - cfg.hbm_headroom)
                      - params_mb - bank_mb)
            max_blocks = max(int(budget // per_block_mb), 0)
            max_slots = max((max_blocks - 1) // self.M, 0)
            raise mg.MemoryAdmissionError(
                f"serve config refused at build: "
                f"{self.mem_check.describe()} (params "
                f"{params_mb:.0f} MB + adapter bank {bank_mb:.0f} MB "
                f"+ KV pool {self.pool_mb:.0f} MB). Max feasible "
                f"num_blocks={max_blocks} "
                f"({per_block_mb:.2f} MB/page), which serves at most "
                f"num_slots={max_slots} worst-case requests of "
                f"{self.M} pages each", check=self.mem_check)
        sh = self.sharding
        if sh is not None:
            self.params = jax.device_put(params,
                                         sh.param_shardings(params))
            # every host-born array a compiled program sees must be
            # COMMITTED to the mesh, or jit refuses to mix placements
            # graftlint: disable=sync-hazard(host-born numpy coerced on its way INTO device_put; no device buffer is read)
            self._dev = lambda a: jax.device_put(np.asarray(a), sh.repl)
            if bank is not None:
                bank.place(sh.bank_shardings(bank.tree), sh.put_repl)
        else:
            self.params = jax.tree.map(jnp.asarray, params)
            self._dev = jnp.asarray
        self.alloc = BlockAllocator(cfg.num_blocks)
        # shared-prefix reuse (round 21): the cache owns the key<->page
        # maps, the allocator the refcounts/parking — None = every page
        # private, the pre-r21 allocator arithmetic exactly
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.alloc, cfg.block_T)
            if cfg.prefix_cache else None)
        self.cow_copies = 0
        # adapter hot-swap generations: part of the KV identity hashed
        # into prefix keys, so a reloaded tenant's stale cache entries
        # become unreachable (they drain via LRU parking, never served)
        self._adapter_gen: collections.Counter = collections.Counter()
        # static chunk widths (sorted): smallest bucket covering the
        # remaining suffix wins, else the largest rides repeated steps
        self.chunk_buckets: tuple = tuple(sorted(set(cfg.chunk_buckets)))
        if not self.chunk_buckets:
            # widths cap at max_prompt (block-rounded), NOT true_cap:
            # max_prompt is the one-dispatch prefill budget the
            # operator sized, so longer prompts ride it in slices —
            # per-step work stays bounded and decode interleaves
            cap = blocks_for(cfg.max_prompt, cfg.block_T) * cfg.block_T
            w, ws = cfg.block_T, []
            while w < cap:
                ws.append(w)
                w *= 2
            ws.append(cap)
            self.chunk_buckets = tuple(sorted(set(ws)))
        self._pool_dims = (L, KV, D)   # for the containment pool reset
        self.pool_k, self.pool_v = self._init_pools()
        self._tok = np.zeros(S, np.int32)
        self._pos = np.zeros(S, np.int32)
        self._tbl = np.full((S, self.M), TRASH_BLOCK, np.int32)
        self._aid = np.zeros(S, np.int32)
        # round-21 per-slot sampling state — DATA, not branches: rows
        # with temperature <= 0 take the greedy argmax inside the same
        # compiled step (idle slots and greedy requests alike)
        self._temp = np.zeros(S, np.float32)
        self._topk = np.zeros(S, np.int32)
        self._topp = np.ones(S, np.float32)
        self._key = np.zeros((S, 2), np.uint32)
        self._slots: List[Optional[Request]] = [None] * S
        self.queue: collections.deque = collections.deque()
        self.decode_steps = 0
        self._next_id = 0
        self._t0 = time.perf_counter()
        # --- robustness state (round 14) --------------------------------
        self.draining = False          # admissions stopped (drain/shutdown)
        self._closed = False
        self.guard: Optional[PreemptionGuard] = None
        self.watchdog = watchdog       # pet()-only: the harness owns its
                                       # lifecycle (start/stop)
        # fault-injection seam: called with decode_steps right before
        # every step dispatch, INSIDE the containment try — an exception
        # here exercises the same path a real dispatch failure takes
        # (tools/serve_bench.py --inject installs it)
        self.step_hook: Optional[Callable[[int], None]] = None
        self._step_ms: collections.deque = collections.deque(maxlen=256)
        self._health_lock = threading.Lock()
        self.counts: collections.Counter = collections.Counter()
        # True exactly while a pool-donating dispatch (_write) is in
        # flight: a failure in that window may have consumed the
        # donated buffers, so containment must treat the pools as lost
        self._pools_at_risk = False

        # --- the two compiled programs (+ the prompt-page writer) ----------
        # trace_counts is the compile-stability observable: the wrapped
        # python bodies run ONLY when jax (re)traces, so the counters
        # count executables, not calls.
        self.trace_counts: collections.Counter = collections.Counter()
        dt, impl = self.dtype, cfg.attn_impl
        l_impl = cfg.lora_impl
        prefill_raw, step_raw = self._prefill_fn, self._step_fn
        chunk_raw = self._chunk_fn
        conf = config
        sampling = cfg.sampling

        shd = self.sharding

        def _select(logits, pos_next, temp, topk, topp, key2):
            # key2 [R, 2] raw per-row keys, folded with the emitted
            # token's ABSOLUTE position pos_next [R] — one convention
            # across prefill/chunk/decode, so cache on/off and chunked/
            # unchunked admission draw the identical stream per seed
            folded = jax.vmap(jax.random.fold_in)(key2, pos_next)
            return sample_per_row(logits, temp, topk, topp, folded)

        def prefill_py(params, bank_tree, ids, mask, aid, *samp):
            self.trace_counts["prefill"] += 1
            lora = self._route(bank_tree, aid)
            logits, (pk, pv) = prefill_raw(conf, params, ids, mask,
                                           compute_dtype=dt, lora=lora,
                                           lora_impl=l_impl,
                                           shardings=shd)
            if sampling:
                n_real = mask.sum(-1).astype(jnp.int32)       # [1]
                tok0 = _select(logits, n_real, *samp)[0]
            else:
                tok0 = jnp.argmax(logits[0], -1).astype(jnp.int32)
            return tok0, pk[:, 0], pv[:, 0]

        def step_py(params, bank_tree, pool_k, pool_v, tok, pos, tbl,
                    aid, *samp):
            self.trace_counts["decode_step"] += 1
            lora = self._route(bank_tree, aid)
            logits, pk, pv = step_raw(conf, params, pool_k, pool_v, tok,
                                      pos, tbl, lora=lora,
                                      compute_dtype=dt, attn_impl=impl,
                                      lora_impl=l_impl, shardings=shd)
            if sampling:
                nxt = _select(logits, pos + 1, *samp)
            else:
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return nxt, pk, pv

        def write_py(pool_k, pool_v, k, v, block_ids):
            self.trace_counts["write_prefill"] += 1
            return write_prompt_blocks(pool_k, pool_v, k, v, block_ids)

        def chunk_py(params, bank_tree, pool_k, pool_v, ids, start,
                     n_tok, tbl, aid, *samp):
            self.trace_counts["prefill_chunk"] += 1
            W = ids.shape[1]
            # one request's rows all route the same adapter — broadcast
            # the [1] aid to the row count so the per-row lora gather is
            # shape-identical to the decode step's
            lora = self._route(bank_tree, jnp.broadcast_to(aid, (W,)))
            logits, pk, pv = chunk_raw(conf, params, pool_k, pool_v,
                                       ids, start, n_tok, tbl, lora=lora,
                                       compute_dtype=dt,
                                       lora_impl=l_impl, shardings=shd)
            if sampling:
                tok = _select(logits, (start + n_tok)[None], *samp)[0]
            else:
                tok = jnp.argmax(logits[0], -1).astype(jnp.int32)
            return tok, pk, pv

        def cow_py(pool_k, pool_v, src, dst):
            self.trace_counts["cow_copy"] += 1
            pk = pool_k.at[dst].set(pool_k[src])
            pv = pool_v.at[dst].set(pool_v[src])
            return pk, pv

        # donating the pools lets XLA scatter in place (the cache never
        # has two copies); CPU ignores donation, so skip the warning.
        # Under a mesh the outputs' shardings are PINNED to the inputs'
        # (pool in == pool out): donation must hand back buffers on the
        # same placement, and warmup must not depend on what GSPMD
        # would infer for an output nobody constrained.
        donate = jax.default_backend() != "cpu"
        pool_sh = None if shd is None else shd.pool_sharding()
        cache_sh = None if shd is None else shd.cache_sharding()
        self._prefill = jax.jit(
            prefill_py,
            out_shardings=None if shd is None
            else (shd.repl, cache_sh, cache_sh))
        self._step = jax.jit(
            step_py, donate_argnums=(2, 3) if donate else (),
            out_shardings=None if shd is None
            else (shd.repl, pool_sh, pool_sh))
        self._write = jax.jit(
            write_py, donate_argnums=(0, 1) if donate else (),
            out_shardings=None if shd is None else (pool_sh, pool_sh))
        self._chunk = jax.jit(
            chunk_py, donate_argnums=(2, 3) if donate else (),
            out_shardings=None if shd is None
            else (shd.repl, pool_sh, pool_sh))
        self._cow = jax.jit(
            cow_py, donate_argnums=(0, 1) if donate else (),
            out_shardings=None if shd is None else (pool_sh, pool_sh))

        # the lora_impl resolution is a pure function of the engine's
        # static shapes — resolve the decode-step site once and stamp it
        # into the manifest so a reader of the stream knows which path
        # served the run (train CLIs do the same per target)
        lora_impl_resolved = None
        if bank is not None:
            from mobilefinetuner_tpu.models.lora_apply import impl_summary
            # per-target map, not one arbitrary target: d_out differs
            # across targets, so boundary shapes can resolve differently
            # per site (same convention as the train CLIs' manifest)
            dims = {name: (int(e["A"].shape[-2]), int(e["B"].shape[-1]))
                    for name, e in bank.tree["blocks"].items()}
            rank = int(next(iter(
                bank.tree["blocks"].values()))["A"].shape[-1])
            lora_impl_resolved = impl_summary(
                dims, S, rank, cfg.lora_impl, self.dtype.itemsize)
        self.telemetry = telemetry or Telemetry("")
        # request-lifecycle span tracing (core/trace.py): queue/prefill/
        # decode spans per request on its own "req:<id>" track. Pure
        # host bookkeeping over stamps the engine already takes.
        from mobilefinetuner_tpu.core.trace import Tracer
        self.tracer = Tracer(self.telemetry.emit,
                             enabled=cfg.trace_spans)
        self.telemetry.emit("run_start", **run_manifest({
            "serve_family": family, "num_slots": S,
            "block_T": cfg.block_T, "num_blocks": cfg.num_blocks,
            "max_prompt": cfg.max_prompt,
            "max_new_tokens": cfg.max_new_tokens, "dtype": cfg.dtype,
            "lora_impl": cfg.lora_impl,
            "lora_impl_resolved": lora_impl_resolved,
            "adapter_slots": bank.capacity if bank else 0,
            "max_queue": cfg.max_queue, "shed_policy": cfg.shed_policy,
            "on_step_error": cfg.on_step_error,
            "stats_every": cfg.stats_every,
            "mesh_dp": cfg.mesh_dp, "mesh_tp": cfg.mesh_tp,
            "prefix_cache": cfg.prefix_cache,
            "max_prompt_chunked": cfg.max_prompt_chunked,
            "chunk_buckets": list(self.chunk_buckets),
            "sampling": cfg.sampling}))
        # the admission verdict that let this engine build (the refusal
        # path raised before the stream existed): est vs cap is the
        # "how many more blocks/slots could this chip hold" number the
        # ROADMAP's adapter-packing and KV-sizing questions start from
        self.telemetry.emit("mem_check", **self.mem_check.event())

    # ------------------------------------------------------------ helpers ---
    def _init_pools(self):
        """Fresh zeroed pools on their home placement (build + the
        containment reset share this so a rebuilt pool can never come
        back on the wrong devices)."""
        L, KV, D = self._pool_dims
        pk, pv = init_pools(self.cfg.num_blocks, L, KV,
                            self.cfg.block_T, D, self.dtype)
        if self.sharding is not None:
            psh = self.sharding.pool_sharding()
            pk, pv = jax.device_put(pk, psh), jax.device_put(pv, psh)
        return pk, pv

    @staticmethod
    def _route(bank_tree, aid):
        """Bank slots -> per-row lora tree (the ids-gather routing)."""
        if bank_tree is None:
            return None
        return assign_adapters(bank_tree, aid)

    @property
    def active(self) -> List[Request]:
        return [r for r in self._slots if r is not None]

    @property
    def idle(self) -> bool:
        return not self.queue and not self.active

    def total_traces(self) -> int:
        return sum(self.trace_counts.values()) + (
            self.bank.trace_count if self.bank else 0)

    def _committed_blocks(self) -> int:
        """Pages the residents may still demand (their reservations)."""
        return sum(r.worst_blocks - len(r.blocks) for r in self.active)

    def _emit_request(self, req: Request, phase: str) -> None:
        self.telemetry.emit(
            "request", id=req.id, phase=phase,
            prompt_tokens=len(req.prompt),
            adapter=req.aid if req.adapter is not None else None,
            queue_ms=((req.admit_t - req.enqueue_t) * 1000.0
                      if req.admit_t else None),
            new_tokens=len(req.tokens) or None,
            ttft_ms=req.ttft_ms, tpot_ms=req.tpot_ms, reason=req.reason,
            rid=req.rid)

    def _req_span(self, name: str, req: Request, t0: float,
                  dur_ms: float, **extra) -> None:
        """One span on the request's own `req:<id>` track. A router-
        stamped `rid` rides as an extra so trace_export --router can
        join the replica-side lifecycle to the router's route/queue
        spans without a lookup table."""
        if req.rid is not None:
            extra.setdefault("rid", req.rid)
        self.tracer.emit_span(name, f"req:{req.id}", t0, dur_ms,
                              id=req.id, **extra)

    def _terminal(self, req: Request, state: str, phase: str,
                  reason: Optional[str] = None) -> None:
        """THE terminal transition: every path out of the lifecycle
        funnels through here, so a request emits exactly one terminal
        `request` phase, is counted exactly once, and can never be
        double-terminated (the accounting invariant the robustness
        tests assert after every injected fault). The caller releases
        slot/pages FIRST (queued requests hold none)."""
        assert state in Request.TERMINAL, state
        assert not req.done, f"request {req.id} already {req.state}"
        req.state = state
        req.reason = reason
        req.finish_t = time.perf_counter()
        self.counts[state] += 1
        self._emit_request(req, phase=phase)
        if self.tracer.enabled:
            # the request's last span: decode for admitted requests
            # (admit -> terminal; partial output from a timeout/error
            # still shows its decode time), queue for ones that died
            # waiting (reject/shed/queued-timeout never prefilled)
            if req.admit_t:
                self._req_span(
                    "decode", req, req.admit_t,
                    (req.finish_t - req.admit_t) * 1000.0,
                    outcome=state)
            else:
                self._req_span(
                    "queue", req, req.enqueue_t,
                    (req.finish_t - req.enqueue_t) * 1000.0,
                    outcome=state)

    # ------------------------------------------------------------ tenancy ---
    def load_adapter(self, name: str, source, verify: bool = True) -> int:
        """Hot-swap `source` (native adapter safetensors path, or an
        already-loaded lora tree) into the resident bank under `name`.
        Replacing a resident that active/queued requests still route to
        is refused — finish or cancel them first. A file source is
        checksum-verified against its integrity manifest BEFORE the
        swap (AdapterBank.load_file): a corrupt tenant adapter raises
        CheckpointIntegrityError with the mismatch reason — recorded as
        a `ckpt_verify{ok=false}` telemetry event so the refusal is
        request-visible in the stream, never a silent load into a live
        slot."""
        if self.bank is None:
            raise RuntimeError("engine was built without an adapter bank")
        if name in self.bank.resident and self._adapter_in_use(name):
            raise RuntimeError(
                f"adapter {name!r} is routed by in-flight requests; "
                f"drain them before replacing it")
        if isinstance(source, dict):
            slot = self.bank.load(name, source)
            self._adapter_gen[name] += 1
            return slot
        from mobilefinetuner_tpu.io.safetensors_io import \
            CheckpointIntegrityError
        try:
            slot = self.bank.load_file(name, source, verify=verify)
        except CheckpointIntegrityError as e:
            self.telemetry.emit("ckpt_verify", path=str(source), ok=False,
                                reason=str(e), step=None, action="reject")
            raise
        if verify:
            self.telemetry.emit("ckpt_verify", path=str(source), ok=True,
                                reason=None, step=None, action="load")
        # the swap changes the KV-producing weights under this name:
        # bump its generation so prefix keys hashed against the old
        # weights become unreachable (stale pages drain via LRU parking)
        self._adapter_gen[name] += 1
        return slot

    def evict_adapter(self, name: str) -> int:
        if self.bank is None:
            raise RuntimeError("engine was built without an adapter bank")
        if self._adapter_in_use(name):
            raise RuntimeError(
                f"adapter {name!r} is routed by in-flight requests")
        slot = self.bank.evict(name)
        self._adapter_gen[name] += 1
        return slot

    def _kv_identity(self, req: Request) -> str:
        """The KV-producing weight identity hashed into prefix keys:
        the frozen base, or adapter name + hot-swap generation — a
        reloaded tenant can never hit another generation's pages."""
        if req.adapter is None:
            return "base"
        return f"{req.adapter}:{self._adapter_gen[req.adapter]}"

    def _adapter_in_use(self, name: str) -> bool:
        # QUEUED requests count as in-use too: submit() resolved their
        # bank slot at enqueue, so replacing/evicting the resident while
        # they wait would silently serve another tenant's weights at
        # admission (_admit additionally re-resolves the name —
        # belt-and-braces, both pinned by
        # test_serve.py::test_queued_request_pins_its_adapter)
        return any(r.adapter == name
                   for r in list(self.queue) + self.active)

    # ------------------------------------------------------------ intake ----
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 0,
               adapter: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: int = 0,
               rid: Optional[int] = None) -> Request:
        """Enqueue one request (admission happens inside step()).
        `deadline_ms` is the request's end-to-end budget from now: a
        queued request past it times out without prefilling, an active
        one is cancelled at the next step boundary with partial output.
        temperature/top_k/top_p/seed (cfg.sampling engines only) ride
        the request's slot as data; temperature 0 is the greedy oracle
        and a given seed is deterministic. Under a full bounded queue
        (`max_queue`) — or a prompt beyond the true cap
        (reason="prompt_too_long"); prompts in (max_prompt, true_cap]
        route to chunked admission instead, since round 21 — the
        returned request may already be terminal (state="rejected"):
        check `.state` rather than assuming it queued."""
        if self._closed:
            raise RuntimeError(
                "submit() on a closed ServeEngine: close() already "
                "ended the telemetry stream — build a new engine")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if (temperature or top_k or top_p != 1.0 or seed) \
                and not self.cfg.sampling:
            raise ValueError(
                "sampling parameters need a sampling-enabled engine "
                "(ServeConfig.sampling=True)")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        n_new = max_new_tokens or self.cfg.max_new_tokens
        if not 0 < n_new <= self.cfg.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {n_new} outside (0, "
                f"{self.cfg.max_new_tokens}]")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        aid = 0
        if adapter is not None:
            if self.bank is None:
                raise RuntimeError(
                    "request names an adapter but the engine has no bank")
            # resolve the slot NOW (raises KeyError if not resident) so
            # the enqueue/cancel events report the right tenant; the
            # slot cannot move while queued (in-use residents refuse
            # replacement and eviction)
            aid = self.bank.slot(adapter)
        req = Request(id=self._next_id, prompt=prompt,
                      max_new_tokens=n_new, adapter=adapter, aid=aid,
                      rid=int(rid) if rid is not None else None,
                      enqueue_t=time.perf_counter(),
                      temperature=float(temperature), top_k=int(top_k),  # graftlint: disable=sync-hazard(host submit args normalized; no device buffer is read)
                      top_p=float(top_p), seed=int(seed))  # graftlint: disable=sync-hazard(host submit args normalized; no device buffer is read)
        if deadline_ms is not None:
            req.deadline_t = req.enqueue_t + deadline_ms / 1000.0
        self._next_id += 1
        self._emit_request(req, phase="enqueue")
        if len(prompt) > self.cfg.true_cap:
            # beyond even chunked admission: a POLICY reject the caller
            # reads off .state, not a programming error — the pre-r21
            # ValueError is gone (prompts in (max_prompt, true_cap]
            # are valid chunked admissions now)
            self._terminal(req, "rejected", phase="reject",
                           reason="prompt_too_long")
            return req
        if self.draining:
            # drain in progress: admissions are closed for good
            self._terminal(req, "rejected", phase="reject",
                           reason="shutdown")
            return req
        if self.cfg.max_queue and len(self.queue) >= self.cfg.max_queue:
            victim = None
            if self.cfg.shed_policy == "deadline":
                # shed the queued request closest to blowing its own
                # deadline — it is the least likely to finish in time
                # anyway; deadline-less requests are never shed
                dl = [r for r in self.queue if r.deadline_t]
                if dl:
                    victim = min(dl, key=lambda r: r.deadline_t)
            if victim is None:
                self._terminal(req, "rejected", phase="reject",
                               reason="queue_full")
                return req
            self.queue.remove(victim)
            self._terminal(victim, "rejected", phase="reject",
                           reason="shed")
        self.queue.append(req)
        return req

    def cancel(self, req: Request) -> None:
        """Evict a queued or active request (frees its slot + pages)."""
        if req.state == "queued":
            self.queue.remove(req)
        elif req.state == "active":
            self._release(req)
        else:
            return
        self._terminal(req, "cancelled", phase="cancel")

    # ------------------------------------------------------------ the loop --
    def _samp_args(self, req: Request) -> tuple:
        """Per-request sampling params for the single-row programs
        (prefill/chunk) — empty on greedy-only engines, so those
        programs keep their pre-r21 signatures bit-for-bit."""
        if not self.cfg.sampling:
            return ()
        # graftlint: disable=sync-hazard(host scalars wrapped for the device; nothing is pulled back)
        return (self._dev(np.asarray([req.temperature], np.float32)),
                self._dev(np.asarray([req.top_k], np.int32)),  # graftlint: disable=sync-hazard(host scalars wrapped for the device; nothing is pulled back)
                self._dev(np.asarray([req.top_p], np.float32)),  # graftlint: disable=sync-hazard(host scalars wrapped for the device; nothing is pulled back)
                self._dev(np.asarray(  # graftlint: disable=sync-hazard(host scalars wrapped for the device; nothing is pulled back)
                    [[(req.seed >> 32) & 0xFFFFFFFF,
                      req.seed & 0xFFFFFFFF]], np.uint32)))

    def _admit(self, req: Request, slot: int) -> None:
        """Slot grant + path dispatch: one-shot prefill (the classic
        path — full miss within max_prompt), full-hit re-feed (every
        prompt block cached), or chunked suffix prefill (partial hit,
        or a long prompt)."""
        cfg = self.cfg
        P = len(req.prompt)
        req.worst_blocks = blocks_for(P + req.max_new_tokens - 1,
                                      cfg.block_T)
        req.slot, req.state = slot, "active"
        if self.bank is None:
            req.aid = 0
        elif req.adapter is not None:
            req.aid = self.bank.slot(req.adapter)
        else:
            req.aid = self.bank.base_slot  # zero slot: serve the base
        self._slots[slot] = req
        self._aid[slot] = req.aid
        if cfg.sampling:
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._topp[slot] = req.top_p
            self._key[slot] = ((req.seed >> 32) & 0xFFFFFFFF,
                               req.seed & 0xFFFFFFFF)
        cached: List[int] = []
        if self.prefix is not None:
            req.cache_keys = chain_keys(req.prompt, cfg.block_T,
                                        self._kv_identity(req))
            cached = self.prefix.lookup(req.cache_keys)
            self.prefix.note_lookup(len(cached) * cfg.block_T, P)
        C = len(cached) * cfg.block_T    # cached prefix, tokens
        if C == P:                       # full hit (P a block multiple)
            self._admit_full_hit(req, cached)
        elif C == 0 and P <= cfg.max_prompt:
            self._admit_prefill(req)
        else:                            # suffix hit, or a long prompt
            self._admit_chunked(req, cached, C)

    def _admit_prefill(self, req: Request) -> None:
        """The classic ONE-SHOT prefill (the pre-r21 path, unchanged):
        full cache miss, prompt within max_prompt."""
        cfg, slot, P = self.cfg, req.slot, len(req.prompt)
        req.blocks = self.alloc.alloc(blocks_for(P, cfg.block_T))
        ids = np.full((1, cfg.max_prompt), self.pad_id, np.int32)
        mask = np.zeros((1, cfg.max_prompt), np.int32)
        ids[0, :P], mask[0, :P] = req.prompt, 1
        bank_tree = self.bank.tree if self.bank else None
        t_prefill = time.perf_counter()
        tok0, k, v = self._prefill(
            self.params, bank_tree, self._dev(ids), self._dev(mask),
            # graftlint: disable=sync-hazard(host int wrapped for the device; nothing is pulled back)
            self._dev(np.asarray([req.aid], np.int32)),
            *self._samp_args(req))
        # scatter the prompt pages; table rows past the prompt stay trash
        block_ids = np.full(cfg.max_prompt // cfg.block_T, TRASH_BLOCK,
                            np.int32)
        block_ids[:len(req.blocks)] = req.blocks
        # the write DONATES the pools (non-CPU): if it raises, the old
        # buffers may already be consumed — flag the window so the
        # admission containment knows one-victim recovery is not enough
        self._pools_at_risk = True
        self.pool_k, self.pool_v = self._write(
            self.pool_k, self.pool_v, k, v, self._dev(block_ids))
        self._pools_at_risk = False
        tok0 = int(tok0)                 # host sync: the first token
        now = time.perf_counter()
        req.admit_t = req.first_token_t = now
        if self.tracer.enabled:
            # queue span closes where prefill begins; prefill span runs
            # through the first-token host sync (both on the request's
            # own track, stamps the engine already takes)
            self._req_span(
                "queue", req, req.enqueue_t,
                (t_prefill - req.enqueue_t) * 1000.0)
            self._req_span(
                "prefill", req, t_prefill, (now - t_prefill) * 1000.0)
        req.tokens.append(tok0)
        self._tok[slot], self._pos[slot] = tok0, P
        self._tbl[slot] = TRASH_BLOCK
        self._tbl[slot, :len(req.blocks)] = req.blocks
        if self.prefix is not None:
            # every FULL prompt block this prefill computed is now
            # shareable (first writer wins on races); decode never
            # rewrites prompt columns, so registered pages stay
            # immutable (cache_keys has P // block_T entries: zip
            # skips the partial tail block by construction)
            for key, b in zip(req.cache_keys, req.blocks):
                self.prefix.register(key, b)
        self._emit_request(req, phase="admit")
        self._emit_request(req, phase="first_token")
        if (self.eos_id is not None and tok0 == self.eos_id) \
                or req.max_new_tokens == 1:
            self._finish(req)

    def _admit_full_hit(self, req: Request, cached: List[int]) -> None:
        """Every prompt block is cached: skip prefill entirely and
        RE-FEED the last prompt token through the decode step — slot
        pos = P-1, so the next decode writes that one column and emits
        the request's first token at position P. The rewritten column
        lands in the last shared page, so that page is COPIED first
        (copy-on-write at the divergence block): shared page contents
        are immutable by construction, whatever this request does."""
        cfg, slot, P = self.cfg, req.slot, len(req.prompt)
        # acquisition order matters: acquire (pin) every cached page
        # BEFORE alloc() could LRU-evict a parked one out from under us
        for b in cached:
            self.alloc.acquire(b)
        dst = self.alloc.alloc(1)[0]
        src = cached[-1]
        # drop our reference on the source BEFORE the copy dispatches:
        # req.blocks then lists exactly the pages containment would
        # release if the (pool-donating) copy dies. Parking preserves
        # contents and nothing allocates before the copy reads it.
        self.alloc.free([src], park=self.prefix.park)
        req.blocks = cached[:-1] + [dst]
        self._pools_at_risk = True
        self.pool_k, self.pool_v = self._cow(
            self.pool_k, self.pool_v,
            # graftlint: disable=sync-hazard(host ints wrapped for the device; nothing is pulled back)
            self._dev(np.asarray(src, np.int32)),
            self._dev(np.asarray(dst, np.int32)))  # graftlint: disable=sync-hazard(host ints wrapped for the device; nothing is pulled back)
        self._pools_at_risk = False
        self.cow_copies += 1
        now = time.perf_counter()
        req.admit_t = now
        req.awaiting_first = True
        self._tok[slot] = req.prompt[-1]
        self._pos[slot] = P - 1
        self._tbl[slot] = TRASH_BLOCK
        self._tbl[slot, :len(req.blocks)] = req.blocks
        if self.tracer.enabled:
            # no prefill span: the whole prompt came from cached pages
            self._req_span(
                "queue", req, req.enqueue_t,
                (now - req.enqueue_t) * 1000.0)
        self._emit_request(req, phase="admit")

    def _admit_chunked(self, req: Request, cached: List[int],
                       C: int) -> None:
        """Chunked admission: the uncached SUFFIX (from the first
        uncached block — the whole prompt on a miss) prefills in static
        bucket-width chunks, at most one per step(), interleaved with
        decode. The slot holds idle data (pos=0, tbl=trash) until the
        final chunk lands the first token, so the compiled step treats
        a mid-prefill request exactly like an empty slot."""
        cfg, slot, P = self.cfg, req.slot, len(req.prompt)
        for b in cached:
            self.alloc.acquire(b)        # pin before alloc() can evict
        req.blocks = list(cached) + self.alloc.alloc(
            blocks_for(P, cfg.block_T) - len(cached))
        req.prefill_pos = C
        req.prefilling = True
        req.admit_t = time.perf_counter()
        self._tok[slot] = self._pos[slot] = 0
        self._tbl[slot] = TRASH_BLOCK
        if self.tracer.enabled:
            self._req_span(
                "queue", req, req.enqueue_t,
                (req.admit_t - req.enqueue_t) * 1000.0)
        self._emit_request(req, phase="admit")

    def _prefill_chunk(self, req: Request) -> None:
        """Dispatch ONE chunk of `req`'s pending prompt suffix: the
        smallest static bucket covering the remainder (else the
        largest, and the tail rides later steps). The final chunk's
        last-row logits are the request's first token."""
        cfg, slot, P = self.cfg, req.slot, len(req.prompt)
        start = req.prefill_pos
        remaining = P - start
        W = next((w for w in self.chunk_buckets if w >= remaining),
                 self.chunk_buckets[-1])
        n_tok = min(remaining, W)
        ids = np.full((1, W), self.pad_id, np.int32)
        ids[0, :n_tok] = req.prompt[start:start + n_tok]
        tbl = np.full((1, self.M), TRASH_BLOCK, np.int32)
        tbl[0, :len(req.blocks)] = req.blocks
        bank_tree = self.bank.tree if self.bank else None
        t_chunk = time.perf_counter()
        # the chunk donates the pools: a failure here is a full-
        # containment window, same as the prompt-page write
        self._pools_at_risk = True
        tok, self.pool_k, self.pool_v = self._chunk(
            self.params, bank_tree, self.pool_k, self.pool_v,
            self._dev(ids),
            # graftlint: disable=sync-hazard(host ints wrapped for the device; nothing is pulled back)
            self._dev(np.asarray(start, np.int32)),
            self._dev(np.asarray(n_tok, np.int32)), self._dev(tbl),  # graftlint: disable=sync-hazard(host ints wrapped for the device; nothing is pulled back)
            self._dev(np.asarray([req.aid], np.int32)),  # graftlint: disable=sync-hazard(host ints wrapped for the device; nothing is pulled back)
            *self._samp_args(req))
        self._pools_at_risk = False
        req.prefill_pos += n_tok
        if self.tracer.enabled:
            self._req_span(
                "prefill", req, t_chunk,
                (time.perf_counter() - t_chunk) * 1000.0)
        if req.prefill_pos < P:
            return
        # final chunk: its last real row IS the request's first token
        tok0 = int(tok)                  # host sync
        req.prefilling = False
        req.first_token_t = time.perf_counter()
        req.tokens.append(tok0)
        self._tok[slot], self._pos[slot] = tok0, P
        self._tbl[slot] = TRASH_BLOCK
        self._tbl[slot, :len(req.blocks)] = req.blocks
        if self.prefix is not None:
            for key, b in zip(req.cache_keys, req.blocks):
                self.prefix.register(key, b)
        self._emit_request(req, phase="first_token")
        if (self.eos_id is not None and tok0 == self.eos_id) \
                or req.max_new_tokens == 1:
            self._finish(req)

    def _release(self, req: Request) -> None:
        park = self.prefix.park if self.prefix is not None else None
        self.alloc.free(req.blocks, park=park)
        req.blocks = []
        req.prefilling = req.awaiting_first = False
        s = req.slot
        if s < 0:   # admission died before the slot was taken: nothing
            return  # slot-side to clean (containment path)
        self._slots[s] = None
        self._tok[s] = self._pos[s] = self._aid[s] = 0
        self._tbl[s] = TRASH_BLOCK
        if self.cfg.sampling:
            self._temp[s], self._topk[s], self._topp[s] = 0.0, 0, 1.0
            self._key[s] = 0

    def _finish(self, req: Request) -> None:
        self._release(req)
        self._terminal(req, "finished", phase="finish")

    def _expire(self, now: float) -> List[Request]:
        """Time out every request past its deadline: queued ones are
        dropped WITHOUT ever prefilling (no trace, no pages), active
        ones at this step boundary — partial output stays on
        `req.tokens`, slot and pages are released."""
        out: List[Request] = []
        for req in [r for r in self.queue
                    if r.deadline_t and now >= r.deadline_t]:
            self.queue.remove(req)
            self._terminal(req, "timeout", phase="timeout",
                           reason="deadline")
            out.append(req)
        for req in [r for r in self.active
                    if r.deadline_t and now >= r.deadline_t]:
            self._release(req)
            self._terminal(req, "timeout", phase="timeout",
                           reason="deadline")
            out.append(req)
        return out

    def _contain_step_error(self, e: BaseException) -> List[Request]:
        """A step-dispatch exception reached the scheduler: the step's
        in-flight work is unrecoverable (and the donated pools may have
        been consumed mid-dispatch), but the ENGINE is not — fail each
        active request individually (phase=error, reason=<exception
        type>), release its slot and exactly its pages, and rebuild the
        pool arrays so the next admission starts from a clean, empty
        cache. The queue is untouched: admission resumes on the next
        step() under `on_step_error="fail_active"`. The compiled
        executables survive (containment is host-side bookkeeping), so
        recovery costs zero retraces."""
        name = type(e).__name__
        failed: List[Request] = []
        for req in self.active:
            self._release(req)
            self._terminal(req, "error", phase="error", reason=name)
            failed.append(req)
        # every active released its own pages, so the allocator is whole
        # again by construction; the pools are rebuilt because a step
        # that died after dispatch may have invalidated the donated
        # buffers (and their contents described only the dead requests)
        self.pool_k, self.pool_v = self._init_pools()
        if self.prefix is not None:
            # the rebuilt pools hold NONE of the cached contents: drop
            # every mapping and parked page (the releases above just
            # parked the dead requests' shared pages — flush un-parks
            # them back to the plain free list)
            self.prefix.flush()
        self._pools_at_risk = False
        return failed

    def step(self) -> List[Request]:
        """One scheduler iteration: observe preemption, expire
        deadlines, admit what fits (unless draining), then one decode
        step for every active slot. Returns every request that reached
        a TERMINAL state on this iteration (finished, and since round
        14: timeout, error, and shutdown-rejected) — filter on
        `.state` when only completions matter."""
        cfg = self.cfg
        done: List[Request] = []
        # a preemption signal is observed at the step boundary (never
        # inside a dispatch): stop admissions, reject the queued
        # remainder, let the in-flight requests finish
        if self.guard is not None and self.guard.triggered \
                and not self.draining:
            self.telemetry.emit("preempt", step=self.decode_steps,
                                signal=self.guard.signal_name or "SIGTERM")
            done.extend(self.begin_shutdown())
        now = time.perf_counter()
        done.extend(self._expire(now))
        # FCFS admission under the worst-case page reservation
        while self.queue and not self.draining:
            free = [i for i, r in enumerate(self._slots) if r is None]
            if not free:
                break
            req = self.queue[0]
            worst = blocks_for(len(req.prompt) + req.max_new_tokens - 1,
                               cfg.block_T)
            if self.alloc.free_blocks - self._committed_blocks() < worst:
                break
            self.queue.popleft()
            try:
                self._admit(req, free[0])
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                # a failed PREFILL kills ONE request, not the engine —
                # and not the other residents' cache (the pools stay:
                # the in-flight requests are still live and their pages
                # untouched)
                self._release(req)
                self._terminal(req, "error", phase="error",
                               reason=type(e).__name__)
                done.append(req)
                if self._pools_at_risk:
                    # ...UNLESS the prompt-page WRITE died: it donates
                    # the pools, so every resident's cache is suspect —
                    # escalate to full containment (fail actives,
                    # rebuild pools)
                    done.extend(self._contain_step_error(e))
                if cfg.on_step_error == "raise":
                    raise
                continue
            if req.state == "finished":  # eos/cap hit on the first token
                done.append(req)

        # chunked prefill (round 21): dispatch chunks FCFS (oldest
        # request first) until this step's prefill-token BUDGET — one
        # widest bucket — is spent. The budget is what bounds the
        # residents' TPOT jitter; spending it on several small suffix
        # chunks (concurrent prefix hits) costs the residents the same
        # as one wide chunk, but keeps short suffixes from serializing
        # at one chunk per decode-step turn (a first-token tax measured
        # at ~1 decode step per queued hit on CPU gpt2s)
        budget = self.chunk_buckets[-1] if self.chunk_buckets else 0
        while budget > 0:
            chunking = [r for r in self.active if r.prefilling]
            if not chunking:
                break
            req = min(chunking, key=lambda r: r.id)
            remaining = len(req.prompt) - req.prefill_pos
            W = next((w for w in self.chunk_buckets if w >= remaining),
                     self.chunk_buckets[-1])
            if W > budget:
                break                    # next chunk outlives the budget
            try:
                self._prefill_chunk(req)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                # the chunk donates the pools: every resident's cache
                # is suspect — full containment, same as a dead step
                done.extend(self._contain_step_error(e))
                if cfg.on_step_error == "raise":
                    raise
                return done
            budget -= W
            if req.done:                 # eos/cap on the final chunk
                done.append(req)

        # mid-prefill requests hold idle slot data: the compiled step
        # runs over every slot regardless, but only completed-prefill
        # rows advance host-side
        live = [r for r in self.active if not r.prefilling]
        if not live:
            return done
        # a slot crossing a page boundary this step takes its next page
        # (guaranteed by the admission reservation)
        for req in live:
            j = int(self._pos[req.slot]) // cfg.block_T
            if j == len(req.blocks):
                try:
                    req.blocks.append(self.alloc.append())
                except OutOfBlocks as e:  # pragma: no cover — invariant
                    raise OutOfBlocks(
                        f"reservation accounting failed for request "
                        f"{req.id}: {e}") from e
                self._tbl[req.slot, j] = req.blocks[-1]

        bank_tree = self.bank.tree if self.bank else None
        t_step = time.perf_counter()
        try:
            if self.step_hook is not None:
                self.step_hook(self.decode_steps)
            step_args = [
                self.params, bank_tree, self.pool_k, self.pool_v,
                self._dev(self._tok), self._dev(self._pos),
                self._dev(self._tbl), self._dev(self._aid)]
            if cfg.sampling:
                # sampling state rides AFTER the legacy args so the
                # pool donation indices (2, 3) never move
                step_args += [self._dev(self._temp),
                              self._dev(self._topk),
                              self._dev(self._topp),
                              self._dev(self._key)]
            nxt, pool_k, pool_v = self._step(*step_args)
            # graftlint: disable=sync-hazard(the serve loop's ONE host sync per decode step: this step's tokens drive host-side scheduling)
            nxt = np.asarray(nxt)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            done.extend(self._contain_step_error(e))
            if cfg.on_step_error == "raise":
                raise
            return done
        self.pool_k, self.pool_v = pool_k, pool_v
        self.decode_steps += 1
        with self._health_lock:
            self._step_ms.append((time.perf_counter() - t_step) * 1000.0)
        if self.watchdog is not None:
            self.watchdog.pet(self.decode_steps,
                              time.perf_counter() - t_step)
        for req in live:
            s = req.slot
            self._pos[s] += 1
            self._tok[s] = int(nxt[s])
            req.tokens.append(int(nxt[s]))
            if req.awaiting_first:
                # full-hit re-feed: THIS decode emitted the request's
                # first token (the prompt never prefilled at all)
                req.awaiting_first = False
                req.first_token_t = time.perf_counter()
                self._emit_request(req, phase="first_token")
            if (self.eos_id is not None and req.tokens[-1] == self.eos_id) \
                    or len(req.tokens) >= req.max_new_tokens:
                self._finish(req)
                done.append(req)
        if cfg.stats_every and self.decode_steps % cfg.stats_every == 0:
            self.emit_stats()
        return done

    def drain(self) -> List[Request]:
        """step() until queue and slots are empty; returns every
        request that reached a terminal state along the way, submission
        order."""
        done: List[Request] = []
        while not self.idle:
            done.extend(self.step())
        return sorted(done, key=lambda r: r.id)

    # ------------------------------------------------------------ shutdown --
    def install_preemption(
            self, guard: Optional[PreemptionGuard] = None
    ) -> PreemptionGuard:
        """Arm SIGTERM/SIGINT drain (the serve-side mirror of
        run_training's --on_preempt): the first signal is observed at
        the next step boundary — admissions stop, the queued remainder
        is rejected with reason="shutdown", in-flight requests decode
        to completion, and close() records run_end{exit=preempted,
        reason=preempted}. A SECOND signal raises KeyboardInterrupt out
        of the drain (the guard's escalation): the caller cancels
        in-flight requests and closes — the operator always outranks a
        slow drain."""
        self.guard = guard or PreemptionGuard()
        if not self.guard.installed:
            self.guard.install()
        return self.guard

    def begin_shutdown(self, reason: str = "shutdown") -> List[Request]:
        """Stop admissions for good and reject every queued request
        (they would never be admitted); in-flight requests keep
        decoding — step()/drain() finish them. Returns the rejected
        requests. Idempotent once draining."""
        self.draining = True
        out: List[Request] = []
        while self.queue:
            req = self.queue.popleft()
            self._terminal(req, "rejected", phase="reject", reason=reason)
            out.append(req)
        return out

    # ------------------------------------------------------------ health ----
    def health(self) -> dict:
        """Host-side loop vitals — what an operator (or the cadenced
        serve_stats emission) reads to see pressure building BEFORE it
        becomes rejects: queue depth, slot occupancy, page-pool
        headroom, rolling p95 step latency, and the cumulative
        terminal-state counters."""
        with self._health_lock:
            ms = sorted(self._step_ms)
        p95 = (round(ms[min(int(0.95 * len(ms)), len(ms) - 1)], 3)
               if ms else None)
        from mobilefinetuner_tpu.core.xla_stats import live_hbm_mb
        hbm = live_hbm_mb()
        return {
            # round-22 router probe: metrics_http's /healthz returns
            # 503 on any non-"ok" status, so a draining replica stops
            # attracting traffic the moment admissions close — the
            # body still carries the full dict (incl. draining: true)
            # for the router's post-mortem line
            "status": "draining" if self.draining else "ok",
            "queue_depth": len(self.queue),
            "active": len(self.active),
            "occupancy": round(len(self.active) / self.cfg.num_slots, 4),
            "free_blocks": self.alloc.free_blocks,
            "blocks_in_use": self.alloc.in_use,
            "p95_step_ms": p95,
            "decode_steps": self.decode_steps,
            "draining": self.draining,
            # round-16 HBM vitals: live device bytes (null where the
            # backend reports none) + the static pool footprint the
            # admission charged — pressure is visible BEFORE it
            # becomes an allocator failure
            "hbm_mb": round(hbm, 2) if hbm is not None else None,
            "pool_mb": round(self.pool_mb, 2),
            "mesh": [self.cfg.mesh_dp, self.cfg.mesh_tp],
            # round-21 shared-prefix vitals: token-weighted hit rate
            # (null until the first lookup / with the cache off), COW
            # page copies, and how many pages sit parked (free but
            # holding cached contents)
            "prefix_hit_rate": (self.prefix.hit_rate
                                if self.prefix is not None else None),
            "cow_copies": self.cow_copies,
            "parked_blocks": self.alloc.parked_blocks,
            "counts": {s: int(self.counts.get(s, 0))
                       for s in Request.TERMINAL},
        }

    def emit_stats(self) -> None:
        """One `serve_stats` snapshot into the stream (step() calls
        this every `stats_every` decode steps)."""
        h = self.health()
        self.telemetry.emit(
            "serve_stats", step=self.decode_steps,
            queue_depth=h["queue_depth"], active=h["active"],
            occupancy=h["occupancy"], free_blocks=h["free_blocks"],
            blocks_in_use=h["blocks_in_use"],
            p95_step_ms=h["p95_step_ms"], hbm_mb=h["hbm_mb"],
            pool_mb=h["pool_mb"], mesh=h["mesh"],
            prefix_hit_rate=h["prefix_hit_rate"],
            cow_copies=h["cow_copies"], **h["counts"])

    # ------------------------------------------------------------ teardown --
    def close(self, exit: str = "ok", reason: Optional[str] = None) -> None:
        """End the stream (idempotent). A drain that a preemption
        signal started records the r13 exit contract — run_end
        {exit=preempted, reason=preempted} — so a fleet controller
        reads a served SIGTERM exactly like a trained one."""
        if self._closed:
            return
        self._closed = True
        if self.guard is not None:
            self.guard.uninstall()
        if exit == "ok" and self.guard is not None and self.guard.triggered:
            exit, reason = "preempted", "preempted"
        self.telemetry.emit(
            "run_end", steps=self.decode_steps,
            wall_s=time.perf_counter() - self._t0, exit=exit,
            goodput=None, reason=reason)
        self.telemetry.close()

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, exc_type, *_) -> None:
        # unwinding an exception is NOT a clean exit: exit="error" with
        # the exception type as reason (the old code recorded the type
        # name AS the exit, so no reader could filter on a stable value)
        if exc_type is None:
            self.close()
        else:
            self.close(exit="error", reason=exc_type.__name__)
