"""Shared-prefix KV cache: hashed full prompt blocks -> physical pages.

At tenant fan-out, prompts overwhelmingly share a head — system
prompts, few-shot templates, retrieval scaffolds. The block-paged pool
(serve/paged_kv.py) already gives KV pages identity, so reuse is pure
bookkeeping: hash every FULL prompt block into a position-chained key
and let a later request whose prompt starts with the same blocks map
the SAME physical pages instead of recomputing them. Prefill then
starts at the first uncached block (the engine chunk-prefills just the
suffix), which is the single biggest TTFT and HBM-per-request win on
the serve side (DESIGN.md §26).

Key structure — a chain, not independent block hashes:

    h_0 = H(identity)                 identity = KV-producing weights:
    h_i = H(h_{i-1} || tokens_i)      "base", or (adapter, generation)

so block i's key commits to the ENTIRE prefix through block i (two
prompts sharing block content at different offsets can never collide)
and to which weights produced the K/V. Adapter hot-swap bumps the
per-name generation, so stale entries become unreachable and drain via
the allocator's LRU parking — never served.

Lifecycle (the allocator owns the memory, this module owns the map):

  * register(key, block)  at admission, for every freshly-computed
    full prompt block — concurrent requests hit it immediately;
  * lookup(keys)          longest cached chain prefix -> its pages;
    the engine retains (in-use) or adopts (parked) each one;
  * park(block)           the allocator's `free(..., park=)` callback:
    a registered page whose last reference dropped keeps its contents
    and waits, LRU-parked, for the next hit;
  * _on_evict             the allocator reclaimed a parked page for
    fresh allocation: the mapping is forgotten BEFORE the new owner
    writes, so a stale key can never resolve to live foreign data.

Only FULL blocks are shared (a partial tail block's unwritten columns
would alias future decode writes); divergence inside a block simply
misses. The one page shared requests DO both write — a full-hit
re-feed's last block — is copy-on-write in the engine: shared page
contents are immutable by construction.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence


def chain_keys(prompt: Sequence[int], block_T: int,
               identity: str) -> List[bytes]:
    """Position-chained hash per FULL block of `prompt` (len(prompt) //
    block_T keys; the partial tail block, if any, is never keyed)."""
    h = hashlib.blake2b(identity.encode("utf-8"), digest_size=16).digest()
    out: List[bytes] = []
    for i in range(len(prompt) // block_T):
        blk = prompt[i * block_T:(i + 1) * block_T]
        raw = b"".join(int(t).to_bytes(4, "little", signed=True)
                       for t in blk)
        h = hashlib.blake2b(h + raw, digest_size=16).digest()
        out.append(h)
    return out


class PrefixCache:
    """The key<->block bijection over one engine's BlockAllocator."""

    def __init__(self, alloc, block_T: int):
        self.alloc = alloc
        self.block_T = int(block_T)
        self._key_to_block: Dict[bytes, int] = {}
        self._block_to_key: Dict[int, bytes] = {}
        # token-level counters feeding health()/serve_stats
        self.hit_tokens = 0
        self.lookup_tokens = 0
        alloc.on_evict = self._on_evict

    def __len__(self) -> int:
        return len(self._key_to_block)

    def lookup(self, keys: Sequence[bytes]) -> List[int]:
        """Physical pages of the LONGEST cached chain prefix of `keys`
        (chained keys make any gap a guaranteed miss for the rest)."""
        blocks: List[int] = []
        for k in keys:
            b = self._key_to_block.get(k)
            if b is None:
                break
            blocks.append(b)
        return blocks

    def register(self, key: bytes, block: int) -> bool:
        """Map a freshly-computed full block. First writer wins: a key
        already mapped (two same-prefix requests racing their prefills)
        keeps the existing page and the newcomer's copy stays private."""
        if key in self._key_to_block:
            return False
        self._key_to_block[key] = int(block)
        self._block_to_key[int(block)] = key
        return True

    def park(self, block: int) -> Optional[bytes]:
        """The allocator's free(..., park=) callback: a registered
        page's key (it parks, contents kept), None otherwise."""
        return self._block_to_key.get(int(block))

    def _on_evict(self, block: int, key: bytes) -> None:
        """The allocator reclaimed a parked page: forget it."""
        self._key_to_block.pop(key, None)
        self._block_to_key.pop(int(block), None)

    def flush(self) -> None:
        """Drop every mapping AND every parked page (containment
        rebuilt the pools — cached contents no longer exist)."""
        self._key_to_block.clear()
        self._block_to_key.clear()
        self.alloc.flush_parked()

    def note_lookup(self, hit_tokens: int, total_tokens: int) -> None:
        self.hit_tokens += int(hit_tokens)
        self.lookup_tokens += int(total_tokens)

    @property
    def hit_rate(self) -> Optional[float]:
        """Fraction of looked-up prompt tokens served from cached
        pages (None before any lookup)."""
        if not self.lookup_tokens:
            return None
        return round(self.hit_tokens / self.lookup_tokens, 4)

    def check_consistent(self) -> None:
        """The bijection + allocator agreement invariant (asserted by
        the robustness accounting helper after every fault e2e)."""
        assert len(self._key_to_block) == len(self._block_to_key), \
            "key<->block maps out of sync"
        for k, b in self._key_to_block.items():
            assert self._block_to_key.get(b) == k, \
                f"block {b} maps back to a different key"
        for b in getattr(self.alloc, "_parked", {}):
            assert b in self._block_to_key, \
                f"parked block {b} unknown to the cache"
