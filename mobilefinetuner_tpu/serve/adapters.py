"""Resident adapter bank with hot-swap: k LoRA tenants, one base model.

The decode step routes each slot to its adapter through the existing
ids-gather (lora.stack_adapters layout: every A/B/scale leaf stacked
along a leading [k] adapter axis, models/lora_apply.py `_multi_lora`).
The bank makes that stack a MUTABLE resident set: loading a tenant's
adapter from the safetensors store writes its factors into one bank
slot (`leaf.at[slot].set(new)` under a single jitted updater whose slot
index is traced), eviction zeroes the slot — shapes never change, so
the compiled serving step is reused across every swap. That is the
hot-swap contract: tenancy changes are DATA, not programs.

All residents must share rank and target set (the stack_adapters
constraint); a zeroed slot IS the base model (delta == 0), so empty
capacity serves base-only traffic for free.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from mobilefinetuner_tpu.lora.lora import stack_adapters


class AdapterBank:
    """k resident adapter slots, stacked leaves [k, ...].

    `template` fixes the structure every load must match (rank, targets,
    layer count); the bank starts all-zero (= base model in every slot).
    """

    def __init__(self, template, capacity: int):
        if capacity < 1:
            raise ValueError(f"bank capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        zero = jax.tree.map(jnp.zeros_like, template)
        # one hidden slot past capacity stays permanently zero: the BASE
        # route. Base-only requests carry aid=base_slot, so a banked
        # engine serves them without burning a tenant slot (and without
        # the id-0 trap of routing them to whichever tenant loaded
        # first).
        self.tree = stack_adapters([zero] * (capacity + 1))
        self._template_shapes = [
            (x.shape, x.dtype) for x in jax.tree.leaves(template)]
        self._template_structure = jax.tree.structure(template)
        self.names: List[Optional[str]] = [None] * capacity
        self.trace_count = 0

        def _swap(bank, new, i):
            self.trace_count += 1  # trace-time only: compile counter
            return jax.tree.map(
                lambda b, n: b.at[i].set(n.astype(b.dtype)), bank, new)

        self._swap_py = _swap
        self._swap = jax.jit(_swap)
        self._zero_one = jax.tree.map(jnp.zeros_like, template)
        self._put_incoming = None

    def place(self, shardings, put_incoming=None) -> None:
        """Pin the bank's leaves to `shardings` (a matching tree of
        NamedShardings — serve/sharding.ServeSharding.bank_shardings
        builds the block-diagonal layout: B sharded on d_out at
        column-parallel targets, A on d_in at row-parallel ones). The
        swap updater is re-jitted with out_shardings pinned so every
        `at[slot].set` lands back on the SAME placement — hot-swap stays
        one compiled program at any mesh shape. `put_incoming` (usually
        ServeSharding.put_repl) commits incoming host trees to the mesh
        so load/evict never mix committed and uncommitted arguments."""
        self.tree = jax.device_put(self.tree, shardings)
        self._swap = jax.jit(self._swap_py, out_shardings=shardings)
        if put_incoming is not None:
            self._put_incoming = put_incoming
            self._zero_one = put_incoming(self._zero_one)

    # ------------------------------------------------------------ lookup ----
    @property
    def base_slot(self) -> int:
        """The hidden all-zero slot (= base model) base-only rows route
        to; never loadable or evictable."""
        return self.capacity

    @property
    def resident(self) -> Dict[str, int]:
        return {n: i for i, n in enumerate(self.names) if n is not None}

    def slot(self, name: str) -> int:
        for i, n in enumerate(self.names):
            if n == name:
                return i
        raise KeyError(
            f"adapter {name!r} not resident (loaded: "
            f"{sorted(self.resident)}) — engine.load_adapter first")

    # ------------------------------------------------------------ mutate ----
    def _validate(self, tree) -> None:
        if jax.tree.structure(tree) != self._template_structure:
            raise ValueError(
                "adapter structure does not match the bank template "
                "(residents must share rank and target set)")
        shapes = [(x.shape, x.dtype) for x in jax.tree.leaves(tree)]
        for (ws, wd), (hs, _) in zip(self._template_shapes, shapes):
            if ws != hs:
                raise ValueError(
                    f"adapter leaf shape {hs} does not match bank "
                    f"template {ws} (rank mismatch?)")

    def load_file(self, name: str, path: str, verify: bool = True) -> int:
        """Load adapter `name` from a native adapter safetensors file,
        verifying its integrity manifest FIRST (verify=True, the
        default): a corrupt or unverifiable tenant upload raises
        CheckpointIntegrityError — a NAMED error whose message carries
        the per-tensor reason — BEFORE any bank slot is touched, so a
        bit-flipped adapter can never reach live routing (the engine
        surfaces the reason to the caller/request). verify=False is the
        explicit opt-out for trusted in-process artifacts."""
        from mobilefinetuner_tpu.io.safetensors_io import verify_file
        from mobilefinetuner_tpu.lora import peft_io
        if verify:
            verify_file(path)  # CheckpointIntegrityError on mismatch
        tree, _ = peft_io.load_adapter(path)
        return self.load(name, tree)

    def load(self, name: str, tree) -> int:
        """Load/replace adapter `name` into a bank slot; returns the
        slot. Same-name load overwrites in place (new adapter version);
        otherwise the first free slot is taken. Raises OverflowError
        when the bank is full — eviction policy belongs to the caller
        (the engine knows which residents are referenced)."""
        self._validate(tree)
        if self._put_incoming is not None:
            tree = self._put_incoming(tree)
        if name in self.resident:
            i = self.resident[name]
        elif None in self.names:
            i = self.names.index(None)
        else:
            raise OverflowError(
                f"bank full ({self.capacity} residents: "
                f"{sorted(self.resident)}) — evict one first")
        self.tree = self._swap(self.tree, tree, jnp.int32(i))
        self.names[i] = name
        return i

    def evict(self, name: str) -> int:
        """Zero `name`'s slot and free it. Zeroing (not just unmapping)
        means a stale routing id can only ever reach the base model,
        never another tenant's weights."""
        i = self.slot(name)
        self.tree = self._swap(self.tree, self._zero_one, jnp.int32(i))
        self.names[i] = None
        return i
