"""Serve-plane (dp, tp) mesh: tensor-parallel decode + block-diagonal banks.

The serving step's arrays are tiny on the activation side (one [S, E]
row per slot) and huge on the weight side (every layer's matrices, the
[NB, L, KV, bT, D] page pools, the stacked [k, ...] adapter bank). So
the sharded serve plane is weight-parallel, GSPMD-style: this module
PLACES the big buffers with NamedShardings and pins a handful of
`with_sharding_constraint`s at the head/hidden boundaries inside the
decode step — XLA inserts the (cheap, activation-sized) collectives,
and tools/check_compiled_contracts.py pins the census so a partitioning
regression moves a number instead of a pod bill.

Mesh layout (axes `("dp", "tp")` over the first dp*tp devices):

  dp   replicates weights and pools; the slot batch's activations are
       constrained to split over it ([S, ...] axis 0, S % dp == 0).
  tp   Megatron-style tensor parallelism:
         column-parallel (output-feature axis sharded): qkv/fc_in
           (GPT-2), q/gate/up (Gemma; k/v too when the KV heads
           divide tp) — each shard computes its own heads/hidden
           columns with NO communication;
         row-parallel (input-feature axis sharded): attn proj /
           fc_out (GPT-2), o_proj/down_proj (Gemma) — partial sums
           meet in one all-reduce per site.

Attention-head placement is decided ONCE per engine from the family's
head counts (ops/decode_attention.shard_heads is the single source of
truth, shared with the Pallas VMEM gates):

  KV % tp == 0   the page pools themselves shard on the KV-head axis
                 (serve/paged_kv.pool_partition_spec) — each tp shard
                 owns a per-shard head slice of the pool and reads
                 only its own pages;
  else, G % tp == 0   (GQA with few KV heads, e.g. Gemma-3 1B's
                 KV=1): pools replicate, the query-group axis G
                 shards — each shard attends all pages with its own
                 query groups;
  else           heads replicate entirely (the weights may still
                 shard; GSPMD re-gathers at the head reshape).

Block-diagonal adapter banks (PAPERS.md, arxiv 2510.23346): the bank's
stacked leaves are placed so each tp shard holds the block of every
adapter's factors that feeds its own weight shard —

  column-parallel target: B [k, L, r, d_out] shards on d_out. The
      bottleneck xa = x @ A is replicated (r is tiny), so the delta
      xa @ B is BORN on the shard that owns those output columns:
      zero adapter-specific collectives.
  row-parallel target: A [k, L, d_in, r] shards on d_in, matching the
      sharded input activation. The per-shard partial xa [S, r] joins
      the base matmul's existing all-reduce — the only adapter traffic
      is r columns riding a sum that was already being paid.

The factors stay mathematically DENSE (every request's outputs remain
token-identical to the single-chip engine — tests/test_serve_sharded.py
pins it); "block-diagonal" here is the PLACEMENT: the [k, ...] stack is
pre-cut along the TP axis so adapter hot-swap stays one traced
`at[slot].set` onto NamedSharding-stable buffers at any mesh shape
(AdapterBank.place re-jits the swap with out_shardings pinned — zero
retraces across tenancy changes).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from mobilefinetuner_tpu.ops.decode_attention import shard_heads
from mobilefinetuner_tpu.serve.paged_kv import pool_partition_spec


def make_serve_mesh(dp: int, tp: int, devices: Optional[Sequence] = None
                    ) -> Mesh:
    """The serve plane's ("dp", "tp") mesh over the first dp*tp devices.
    Distinct from parallel/mesh.make_mesh's ("data", "fsdp") train axes:
    serving shards WEIGHTS over tp and replicates them over dp, the
    opposite of the train plane's fsdp axis."""
    if dp < 1 or tp < 1:
        raise ValueError(f"mesh axes must be >= 1, got dp={dp}, tp={tp}")
    devices = list(devices if devices is not None else jax.devices())
    n = dp * tp
    if len(devices) < n:
        raise ValueError(
            f"serve mesh ({dp}, {tp}) needs {n} devices, have "
            f"{len(devices)} — on CPU tests, force_host_devices(8) "
            f"must run before jax initializes")
    return Mesh(np.array(devices[:n]).reshape(dp, tp), ("dp", "tp"))


# which LoRA targets are column- vs row-parallel (mirrors the param
# tables below; lora.GPT2_TARGETS / GEMMA_TARGETS name the sites)
_COL_TARGETS = frozenset({"attn_qkv", "attn_q", "attn_k", "attn_v",
                          "mlp_fc_in", "q_proj", "gate_proj", "up_proj"})
_KV_COL_TARGETS = frozenset({"k_proj", "v_proj"})   # only when pools shard
_ROW_TARGETS = frozenset({"attn_proj", "mlp_fc_out", "o_proj",
                          "down_proj"})

# param leaves sharded on the output-feature (last) axis / the
# input-feature (second-to-last) axis, by family. Biases ride their
# matmul's output axis. Everything unlisted (embeds, norms, row-parallel
# biases) replicates. GPT-2's fused qkv_w [E, 3E] shards the packed 3E
# axis: a tp boundary can cross the Q/K/V section edges — semantically
# fine under GSPMD (the jnp.split resharding is part of the pinned
# census), and head-aligned within each section because E % tp == 0.
_COL_LEAVES = {"gpt2": frozenset({"qkv_w", "qkv_b", "fc_w", "fc_b"}),
               "gemma": frozenset({"q_w", "gate_w", "up_w"})}
_KV_COL_LEAVES = {"gpt2": frozenset(), "gemma": frozenset({"k_w", "v_w"})}
_ROW_LEAVES = {"gpt2": frozenset({"proj_w"}),
               "gemma": frozenset({"o_w", "down_w"})}


@dataclasses.dataclass(frozen=True)
class ServeSharding:
    """One engine's placement decisions: the mesh plus the per-family
    head-axis choice, queried by the engine (device_put / out_shardings)
    and by the decode-step bodies (with_sharding_constraint helpers).
    Frozen: everything here is static w.r.t. the compiled programs."""

    mesh: Mesh
    dp: int
    tp: int
    family: str
    nq: int           # query heads
    kv: int           # KV heads
    kv_shards: int    # tp when the pool's KV axis shards, else 1
    g_shards: int     # tp when the GQA group axis shards instead, else 1

    @classmethod
    def build(cls, family: str, config, dp: int, tp: int,
              devices: Optional[Sequence] = None) -> "ServeSharding":
        if family == "gpt2":
            nq = kv = config.n_head
        elif family == "gemma":
            nq = config.num_attention_heads
            kv = config.num_key_value_heads
        else:
            raise ValueError(f"unknown family {family!r}")
        if nq % tp:
            raise ValueError(
                f"mesh_tp={tp} does not divide the {family} query-head "
                f"count ({nq}): column-parallel attention needs "
                f"head-aligned weight shards")
        kv_local, g_local = shard_heads(kv, nq // kv, tp)
        return cls(mesh=make_serve_mesh(dp, tp, devices), dp=dp, tp=tp,
                   family=family, nq=nq, kv=kv,
                   kv_shards=kv // kv_local,
                   g_shards=(nq // kv) // g_local)

    # ------------------------------------------------------- placement ----
    def named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    @property
    def repl(self) -> NamedSharding:
        """Fully-replicated placement — the host-side slot arrays
        (tok/pos/tbl/aid), prefill ids/mask, and incoming adapter trees
        must be COMMITTED here before dispatch: jit refuses to mix
        mesh-committed weights with uncommitted single-device arrays."""
        return self.named(P())

    def put_repl(self, tree):
        """device_put a host tree onto the mesh, replicated."""
        return jax.device_put(tree, self.repl)

    def pool_sharding(self) -> NamedSharding:
        """The [NB, L, KV, bT, D] page pools (layout: serve/paged_kv)."""
        return self.named(pool_partition_spec(self.kv_shards > 1))

    def cache_sharding(self) -> NamedSharding:
        """One prefilled request's [L, KV, Ppad, D] cache (the engine's
        _prefill output, B squeezed) — KV axis matches the pool."""
        kv = "tp" if self.kv_shards > 1 else None
        return self.named(P(None, kv, None, None))

    def param_shardings(self, params):
        """NamedSharding tree for the frozen base params (tables above;
        an axis shards only when tp divides it — indivisible leaves
        replicate, same fallback idiom as parallel/mesh.fsdp_spec_for)."""
        col = set(_COL_LEAVES[self.family])
        if self.kv_shards > 1:
            col |= _KV_COL_LEAVES[self.family]
        row = _ROW_LEAVES[self.family]

        def rule(path, leaf):
            name = getattr(path[-1], "key", None) if path else None
            shape, nd = np.shape(leaf), np.ndim(leaf)
            if self.tp > 1 and name in col and shape[-1] % self.tp == 0:
                return self.named(P(*([None] * (nd - 1)), "tp"))
            if self.tp > 1 and name in row and nd >= 2 \
                    and shape[-2] % self.tp == 0:
                return self.named(P(*([None] * (nd - 2)), "tp", None))
            return self.repl

        return jax.tree_util.tree_map_with_path(rule, params)

    def bank_shardings(self, tree):
        """The block-diagonal AdapterBank placement (module docstring):
        B shards d_out at column-parallel targets, A shards d_in at
        row-parallel targets, scale (and any indivisible or unstacked
        leaf, e.g. lm_head) replicates."""
        col = set(_COL_TARGETS)
        if self.kv_shards > 1:
            col |= _KV_COL_TARGETS

        def rule(path, leaf):
            keys = [getattr(p, "key", None) for p in path]
            leaf_name = keys[-1] if keys else None
            target = keys[-2] if len(keys) >= 2 else None
            shape, nd = np.shape(leaf), np.ndim(leaf)
            if self.tp > 1 and leaf_name == "B" and target in col \
                    and shape[-1] % self.tp == 0:
                return self.named(P(*([None] * (nd - 1)), "tp"))
            if self.tp > 1 and leaf_name == "A" and target in _ROW_TARGETS \
                    and nd >= 2 and shape[-2] % self.tp == 0:
                return self.named(P(*([None] * (nd - 2)), "tp", None))
            return self.repl

        return jax.tree_util.tree_map_with_path(rule, tree)

    # ------------------------------------------- in-step constraints ------
    # Each returns its input UNCHANGED when no axis applies: a forced
    # fully-replicated constraint would fight GSPMD's propagation, so
    # "nothing to pin" means "stay out of the partitioner's way".
    def _c(self, x, spec: P):
        return jax.lax.with_sharding_constraint(x, self.named(spec))

    def _dp(self) -> Optional[str]:
        return "dp" if self.dp > 1 else None

    def slots(self, x):
        """[S, ...] slot-batch activations split over dp (the engine
        validates S % dp == 0 at build)."""
        if self.dp > 1 and x.shape[0] % self.dp == 0:
            return self._c(x, P("dp", *([None] * (x.ndim - 1))))
        return x

    def kv_rows(self, x):
        """[S, KV, D] per-token K/V rows (and GPT-2's [S, H, D] q):
        head axis matches the pool's KV sharding."""
        dp = self._dp()
        kv = "tp" if self.kv_shards > 1 else None
        if dp is None and kv is None:
            return x
        return self._c(x, P(dp, kv, *([None] * (x.ndim - 2))))

    def heads4(self, x):
        """[S, KV, G, D] grouped queries / attention context: whichever
        head axis this engine shards."""
        dp = self._dp()
        kv = "tp" if self.kv_shards > 1 else None
        g = "tp" if self.g_shards > 1 else None
        if dp is None and kv is None and g is None:
            return x
        return self._c(x, P(dp, kv, g, None))

    def hidden(self, x):
        """[S, F] MLP hidden activations, column-sharded between the
        in- and out-projections (skipped when tp doesn't divide F)."""
        if self.tp > 1 and x.shape[-1] % self.tp == 0:
            return self._c(x, P(*([None] * (x.ndim - 1)), "tp"))
        return x

    def prefill_cache(self, x):
        """[L, B, KV, P, D] collected prefill caches — pinned so the
        engine's prompt-page scatter receives pool-aligned K/V."""
        if self.kv_shards > 1:
            return self._c(x, P(None, None, "tp", None, None))
        return x
