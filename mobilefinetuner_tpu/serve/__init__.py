"""Production decode service: continuous batching over static slots, a
block-paged KV cache shared by every resident request, and multi-adapter
hot-swap off one frozen base (DESIGN.md §16)."""

from mobilefinetuner_tpu.serve.adapters import AdapterBank
from mobilefinetuner_tpu.serve.engine import (Request, ServeConfig,
                                              ServeEngine)
from mobilefinetuner_tpu.serve.paged_kv import (TRASH_BLOCK, BlockAllocator,
                                                OutOfBlocks, blocks_for,
                                                init_pools,
                                                write_prompt_blocks)

__all__ = [
    "AdapterBank", "BlockAllocator", "OutOfBlocks", "Request",
    "ServeConfig", "ServeEngine", "TRASH_BLOCK", "blocks_for",
    "init_pools", "write_prompt_blocks",
]
