"""Production decode service: continuous batching over static slots, a
block-paged KV cache shared by every resident request, and multi-adapter
hot-swap off one frozen base (DESIGN.md §16; sharded across a (dp, tp)
mesh since round 20, DESIGN.md §25)."""

from mobilefinetuner_tpu.serve.adapters import AdapterBank
from mobilefinetuner_tpu.serve.engine import (Request, ServeConfig,
                                              ServeEngine)
from mobilefinetuner_tpu.serve.paged_kv import (TRASH_BLOCK, BlockAllocator,
                                                OutOfBlocks, blocks_for,
                                                init_pools,
                                                pool_partition_spec,
                                                write_prompt_blocks)
from mobilefinetuner_tpu.serve.prefix_cache import PrefixCache, chain_keys
from mobilefinetuner_tpu.serve.sharding import ServeSharding, make_serve_mesh

__all__ = [
    "AdapterBank", "BlockAllocator", "OutOfBlocks", "PrefixCache",
    "Request", "ServeConfig", "ServeEngine", "ServeSharding",
    "TRASH_BLOCK", "blocks_for", "chain_keys", "init_pools",
    "make_serve_mesh", "pool_partition_spec", "write_prompt_blocks",
]
