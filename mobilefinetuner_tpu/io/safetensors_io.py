"""SafeTensors reader/writer — self-contained implementation of the format
(8-byte little-endian header length + JSON header + raw blob).

Reference: operators/finetune_ops/graph/safetensors_loader.{h,cpp}
(`SafeTensorsReader`, safetensors_loader.h:45-92) and the hand-written writer
in gpt2_full_finetune/main.cpp:156-237 / graph/lora_saver.cpp. Like the
reference we parse the header ourselves and memory-map the blob; unlike the
reference (F32/F16 only, auto-promote to F32) we also handle BF16 — the
TPU-native parameter dtype.

Two interchangeable backends: the native C++ engine (native/
fast_safetensors.{cpp,py} — mmap + own JSON parser + streamed writer,
mirroring the reference's native loader role) is used automatically when it
builds; this module's pure-Python implementation is the behavioral
reference and the fallback. MFT_NO_NATIVE_ST=1 forces Python.
"""

from __future__ import annotations

import contextlib
import json
import os
import struct
import zlib
from typing import Dict, Optional, Tuple

import numpy as np


class CheckpointIntegrityError(ValueError):
    """A checkpoint failed its integrity contract: unreadable/truncated
    file, missing or stale checksum manifest, or a per-tensor checksum
    mismatch. A NAMED type so load paths can refuse corrupt artifacts
    distinctly from ordinary I/O errors — the serve AdapterBank and the
    train-path lineage fallback both key on it (DESIGN.md §20)."""


@contextlib.contextmanager
def atomic_publish(path: str):
    """Crash-safe file publication (DESIGN.md §15): yields a tmp path
    (`<path>.tmp.<pid>`) for the caller to write, then fsyncs it and
    atomically `os.replace`s it onto `path` (plus a best-effort fsync of
    the directory entry). A death at ANY instant before the rename —
    including SIGKILL from the energy governor's suspend path or a
    mid-write crash — leaves the previous `path` bytes untouched, so a
    resumable checkpoint can never be replaced by a truncated one
    (tests/test_async_ckpt.py kills a writer mid-write to pin this).
    On exception the tmp file is removed and the exception propagates;
    only a hard kill can leave a stale `.tmp.<pid>` file behind, which
    later successful saves ignore (the pid suffix keeps concurrent
    writers from colliding)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        yield tmp
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        try:  # durability of the rename itself (directory entry)
            dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                          os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # non-posix dir semantics: the data fsync already landed
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def _native_mod():
    try:
        from mobilefinetuner_tpu.native import fast_safetensors as m
        return m if m.load_library() is not None else None
    except Exception:
        return None

# safetensors dtype tag -> (numpy dtype used for raw decode, itemsize)
_DTYPES = {
    "F64": np.dtype("<f8"), "F32": np.dtype("<f4"), "F16": np.dtype("<f2"),
    "I64": np.dtype("<i8"), "I32": np.dtype("<i4"), "I16": np.dtype("<i2"),
    "I8": np.dtype("i1"), "U8": np.dtype("u1"), "BOOL": np.dtype("?"),
    # BF16 has no numpy dtype; decoded via uint16 bit tricks.
    "BF16": np.dtype("<u2"),
}
_TO_TAG = {
    np.dtype("float64"): "F64", np.dtype("float32"): "F32",
    np.dtype("float16"): "F16", np.dtype("int64"): "I64",
    np.dtype("int32"): "I32", np.dtype("int16"): "I16",
    np.dtype("int8"): "I8", np.dtype("uint8"): "U8", np.dtype("bool"): "BOOL",
}


def _bf16_to_f32(raw_u16: np.ndarray) -> np.ndarray:
    return (raw_u16.astype(np.uint32) << 16).view(np.float32)


def _f32_to_bf16_u16(x: np.ndarray) -> np.ndarray:
    u = np.ascontiguousarray(x, dtype=np.float32).view(np.uint32)
    # round-to-nearest-even on the truncated mantissa
    rounding = 0x7FFF + ((u >> 16) & 1)
    return ((u + rounding) >> 16).astype(np.uint16)


class SafeTensorsReader:
    """Parses header eagerly, memory-maps the blob, loads tensors lazily.

    Backed by the native C++ engine when available (identical entries/
    metadata/load results — tests/test_native_safetensors.py asserts
    byte-level parity), else by the pure-Python parse below.
    """

    def __init__(self, path: str):
        self.path = path
        self._native = None
        nat = _native_mod()
        if nat is not None:
            try:
                self._native = nat.NativeReader(path)
            except MemoryError:
                self._native = None
            # ValueError (malformed file) propagates: both backends reject
        if self._native is not None:
            self.metadata = self._native.metadata
            self.entries = self._native.entries
            self._blob = None
            return
        with open(path, "rb") as f:
            # malformed files raise ValueError from BOTH backends (the
            # native reader's st_error path raises ValueError above):
            # struct.error on a truncated length prefix is the one stdlib
            # type here that is NOT already a ValueError subclass
            # (json.JSONDecodeError and UnicodeDecodeError are).
            try:
                (header_len,) = struct.unpack("<Q", f.read(8))
                # a corrupt length prefix can decode to e.g. 2^60 — bound it
                # by the file size BEFORE read() attempts the allocation, so
                # MemoryError never escapes the ValueError contract
                if header_len > os.path.getsize(path) - 8:
                    raise ValueError(
                        f"header length {header_len} exceeds file size")
                header = json.loads(f.read(header_len).decode("utf-8"))
            except (struct.error, ValueError) as e:
                raise ValueError(
                    f"{path}: malformed safetensors header: {e}") from e
            if not isinstance(header, dict):
                raise ValueError(f"{path}: malformed safetensors header: "
                                 f"not a JSON object")
        self.metadata: Dict[str, str] = header.pop("__metadata__", {}) or {}
        self.entries: Dict[str, dict] = header
        self._blob = np.memmap(path, dtype=np.uint8, mode="r",
                               offset=8 + header_len)

    def keys(self):
        return list(self.entries.keys())

    def shape_dtype(self, name: str) -> Tuple[tuple, str]:
        e = self.entries[name]
        return tuple(e["shape"]), e["dtype"]

    def load(self, name: str, promote_to_f32: bool = False) -> np.ndarray:
        """Load one tensor as a numpy array (copy).

        BF16 always decodes to float32 (numpy can't hold bf16); other dtypes
        keep their storage dtype unless promote_to_f32.
        """
        e = self.entries[name]
        tag = e["dtype"]
        if tag not in _DTYPES:
            raise ValueError(f"unsupported safetensors dtype {tag}")
        if self._native is not None:
            raw = np.frombuffer(self._native.raw(name), dtype=_DTYPES[tag])
        else:
            begin, end = e["data_offsets"]
            raw = np.frombuffer(self._blob[begin:end], dtype=_DTYPES[tag])
        if tag == "BF16":
            arr = _bf16_to_f32(raw)
        else:
            arr = raw.copy()
        if promote_to_f32 and arr.dtype != np.float32:
            arr = arr.astype(np.float32)
        return arr.reshape(e["shape"])

    def load_all(self, promote_to_f32: bool = False) -> Dict[str, np.ndarray]:
        return {k: self.load(k, promote_to_f32) for k in self.entries}

    def raw_bytes(self, name: str) -> bytes:
        """One tensor's STORED payload bytes, undecoded — the unit the
        integrity manifest checksums (a BF16 tensor hashes its on-disk
        u16 bytes, not a decode). A truncated blob returns fewer bytes
        than the header promised; the verifier treats that as corruption
        rather than erroring here."""
        if self._native is not None:
            return bytes(self._native.raw(name))
        begin, end = self.entries[name]["data_offsets"]
        return bytes(self._blob[begin:min(end, len(self._blob))])


# --------------------------- integrity manifest ------------------------------

# The per-tensor checksum sidecar every writer publishes next to its
# safetensors file (`<path>.manifest.json`, via the same atomic_publish).
# Checksums cover the ENCODED payload bytes — exactly what lands on disk
# — so a bit flip anywhere in the blob, a truncation, or a stale/partial
# write is caught at load time instead of silently training/serving from
# a corrupt artifact. The manifest is written AFTER the main file's
# atomic rename: a crash in the window between the two leaves a stale
# manifest, which verification reports as corruption — the load paths
# then fall back down the checkpoint lineage (io/checkpoints.py), the
# conservative failure.
MANIFEST_VERSION = 1


def manifest_path(path: str) -> str:
    return path + ".manifest.json"


def _write_manifest(path: str, entries: Dict[str, dict]) -> str:
    mp = manifest_path(path)
    payload = {"version": MANIFEST_VERSION,
               "file": os.path.basename(path),
               "tensors": entries}
    with atomic_publish(mp) as tmp:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"), sort_keys=True)
    return mp


def verify_report(path: str) -> Tuple[str, Optional[str]]:
    """Integrity verdict for one safetensors file against its manifest:
    ('ok', None) — manifest present, every tensor's stored bytes match;
    ('unverified', reason) — the file parses but carries NO manifest
    (pre-manifest checkpoint): loadable only as a last resort;
    ('corrupt', reason) — missing/unparseable file, unreadable or stale
    manifest, size or checksum mismatch. Never raises."""
    if not os.path.exists(path):
        return "corrupt", "missing_file"
    try:
        reader = SafeTensorsReader(path)
    except (ValueError, OSError, MemoryError) as e:
        return "corrupt", f"malformed:{type(e).__name__}"
    mp = manifest_path(path)
    if not os.path.exists(mp):
        return "unverified", "manifest_missing"
    try:
        with open(mp, "r", encoding="utf-8") as f:
            manifest = json.load(f)
        tensors = manifest["tensors"]
        assert isinstance(tensors, dict)
    except (ValueError, KeyError, AssertionError, OSError):
        return "corrupt", "manifest_unreadable"
    if set(tensors) != set(reader.entries):
        return "corrupt", "manifest_stale"
    for name, spec in tensors.items():
        try:
            raw = reader.raw_bytes(name)
        except Exception as e:  # mmap fault on a truncated blob etc.
            return "corrupt", f"payload_unreadable:{name}:{type(e).__name__}"
        if len(raw) != spec.get("nbytes"):
            return "corrupt", f"size_mismatch:{name}"
        if (zlib.crc32(raw) & 0xFFFFFFFF) != spec.get("crc32"):
            return "corrupt", f"checksum_mismatch:{name}"
    return "ok", None


def verify_file(path: str) -> None:
    """Raise CheckpointIntegrityError unless `path` verifies 'ok'
    against its manifest (a missing manifest fails too — strict form,
    used where an unverified artifact must not be trusted, e.g. the
    serve AdapterBank's hot-swap path)."""
    status, reason = verify_report(path)
    if status != "ok":
        raise CheckpointIntegrityError(
            f"{path}: integrity verification failed ({reason})")


def _tensor_spec(name, arr, bf16_keys):
    """(tag, shape, nbytes, encode) for one tensor — the single source of
    the dtype-tag/encoding rules for both writers. `encode()` materializes
    the payload bytes; the streamed native writer calls it one tensor at a
    time, so declarations never require encoding up front."""
    arr = np.asarray(arr)
    # jax bf16 arrays arrive as ml_dtypes.bfloat16 numpy arrays — store
    # them as BF16, not silently upcast to F32.
    is_bf16_input = arr.dtype.name == "bfloat16"
    shape = arr.shape
    n = int(np.prod(shape, dtype=np.int64))
    if is_bf16_input or (bf16_keys and name in bf16_keys):
        encode = lambda: _f32_to_bf16_u16(arr.astype(np.float32)).tobytes()
        return "BF16", shape, n * 2, encode
    dtype = arr.dtype if arr.dtype in _TO_TAG else np.dtype(np.float32)
    encode = lambda: np.ascontiguousarray(arr.astype(dtype)
                                          if arr.dtype != dtype
                                          else arr).tobytes()
    return _TO_TAG[dtype], shape, n * dtype.itemsize, encode


def _encode_tensor(name, arr, bf16_keys) -> Tuple[str, tuple, bytes]:
    """(tag, shape, raw_bytes) — eager form, used by the Python writer."""
    tag, shape, _, encode = _tensor_spec(name, arr, bf16_keys)
    return tag, shape, encode()


def save_safetensors(path: str, tensors: Dict[str, np.ndarray],
                     metadata: Optional[Dict[str, str]] = None,
                     bf16_keys: Optional[set] = None,
                     manifest: bool = True):
    """Write a safetensors file. Keys in `bf16_keys` (or arrays already
    passed as jax bfloat16 via float32 conversion upstream) are stored BF16.
    Uses the native streamed writer when available; the Python writer below
    is the fallback and behavioral reference.

    EVERY write is atomically published (tmp + fsync + rename): since all
    checkpoint writers in the repo — adapters, full-model saves, the .opt
    optimizer sidecar — funnel through here, none of them can leave a
    truncated file where a resumable checkpoint used to be. With
    `manifest` (the default) a `<path>.manifest.json` checksum sidecar is
    published after the main rename, carrying crc32/nbytes per tensor
    over the stored payload bytes — the verify-on-load contract
    (`verify_report`/`verify_file`) every resume/rollback/adapter-swap
    path checks. The checksums are computed from the same encode pass
    the writer streams to disk, so the manifest costs no extra read.
    """
    sums: Dict[str, dict] = {}
    with atomic_publish(path) as tmp:
        _write_safetensors(tmp, tensors, metadata, bf16_keys,
                           checksums=sums if manifest else None)
    if manifest:
        _write_manifest(path, sums)


def _write_safetensors(path: str, tensors: Dict[str, np.ndarray],
                       metadata: Optional[Dict[str, str]] = None,
                       bf16_keys: Optional[set] = None,
                       checksums: Optional[Dict[str, dict]] = None):
    def _record(name, tag, shape, raw):
        if checksums is not None:
            checksums[name] = {"crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                               "nbytes": len(raw), "dtype": tag,
                               "shape": list(shape)}

    nat = _native_mod()
    if nat is not None:
        # real write failures (IOError) propagate — a disk that rejects
        # the native writer would reject the Python writer too. Payloads
        # go in as callables: the native writer declares the header from
        # (tag, shape, nbytes) and encodes ONE tensor at a time during the
        # data pass, so peak host memory is a single tensor's bytes. The
        # checksum wrapper rides that same single encode call, so the
        # manifest never forces a second encode pass.
        items = []
        for name, arr in tensors.items():
            tag, shape, nbytes, encode = _tensor_spec(name, arr, bf16_keys)

            def wrap(name=name, tag=tag, shape=shape, encode=encode):
                raw = encode()
                _record(name, tag, shape, raw)
                return raw

            items.append((name, tag, shape, nbytes, wrap))
        nat.native_write(path, items, metadata)
        return
    header: Dict[str, object] = {}
    if metadata:
        header["__metadata__"] = {str(k): str(v)
                                  for k, v in metadata.items()}
    blobs = []
    offset = 0
    for name, arr in tensors.items():
        tag, shape, raw = _encode_tensor(name, arr, bf16_keys)
        _record(name, tag, shape, raw)
        header[name] = {"dtype": tag, "shape": list(shape),
                        "data_offsets": [offset, offset + len(raw)]}
        blobs.append(raw)
        offset += len(raw)
    hjson = json.dumps(header, separators=(",", ":")).encode("utf-8")
    # Pad header to 8-byte alignment (spec-conformant, matches HF writer).
    pad = (-(len(hjson)) % 8)
    hjson += b" " * pad
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for b in blobs:
            f.write(b)
