"""HF checkpoint <-> framework-pytree conversion for GPT-2 and Gemma-3.

Reference: operators/finetune_ops/graph/safetensors_loader.cpp
(`GPT2KeyMapper` mapping HF `h.i.attn.c_attn.*` -> internal keys;
`GemmaKeyMapper` mapping `model.layers.i.*`). Our internal layout stacks
per-layer tensors into [L, ...] arrays (models/gpt2.py, models/gemma3.py),
so "mapping" here is gather+stack rather than per-key rename.

GPT-2 Conv1D subtlety (SURVEY.md §7.3): HF GPT-2 linear weights are stored
[in, out] (Conv1D) and our models compute y = x @ W, so NO transpose is
applied — the same reason the reference CLI disables its loader transpose
(gpt2_lora_finetune/main.cpp:292-296). Gemma weights are true nn.Linear
[out, in]; we transpose those to [in, out] at load.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.io.safetensors_io import (SafeTensorsReader,
                                                   save_safetensors)


def load_hf_state_dict(model_dir: str,
                       promote_to_f32: bool = True) -> Dict[str, np.ndarray]:
    """Load an HF checkpoint dir's full state dict — single-file or sharded
    (model.safetensors.index.json) layouts."""
    index = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index):
        import json
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        out = {}
        for shard in sorted(set(weight_map.values())):
            reader = SafeTensorsReader(os.path.join(model_dir, shard))
            out.update(reader.load_all(promote_to_f32))
        return out
    return SafeTensorsReader(
        _find_weights_file(model_dir)).load_all(promote_to_f32)


def _find_weights_file(model_dir: str) -> str:
    for name in ("model.safetensors", "pytorch_model.safetensors"):
        p = os.path.join(model_dir, name)
        if os.path.exists(p):
            return p
    cands = [f for f in os.listdir(model_dir) if f.endswith(".safetensors")]
    if len(cands) == 1:
        return os.path.join(model_dir, cands[0])
    if cands:
        raise FileNotFoundError(
            f"multiple safetensors shards in {model_dir} but no "
            "model.safetensors.index.json")
    raise FileNotFoundError(f"no safetensors weights in {model_dir}")


def _strip_prefix(tensors: Dict[str, np.ndarray],
                  prefixes=("transformer.", "model.")) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tensors.items():
        for p in prefixes:
            if k.startswith(p):
                k = k[len(p):]
                break
        out[k] = v
    return out


# ----------------------------- GPT-2 ---------------------------------------

def gpt2_params_from_hf(tensors: Dict[str, np.ndarray],
                        config: GPT2Config) -> dict:
    """HF GPT2LMHeadModel state-dict -> stacked pytree (float32 numpy)."""
    t = _strip_prefix(tensors)
    L = config.n_layer

    def stack(fmt):
        return np.stack([t[fmt.format(i)] for i in range(L)])

    return {
        "wte": t["wte.weight"],
        "wpe": t["wpe.weight"],
        "blocks": {
            "ln_1": {"g": stack("h.{}.ln_1.weight"),
                     "b": stack("h.{}.ln_1.bias")},
            "attn": {
                "qkv_w": stack("h.{}.attn.c_attn.weight"),
                "qkv_b": stack("h.{}.attn.c_attn.bias"),
                "proj_w": stack("h.{}.attn.c_proj.weight"),
                "proj_b": stack("h.{}.attn.c_proj.bias"),
            },
            "ln_2": {"g": stack("h.{}.ln_2.weight"),
                     "b": stack("h.{}.ln_2.bias")},
            "mlp": {
                "fc_w": stack("h.{}.mlp.c_fc.weight"),
                "fc_b": stack("h.{}.mlp.c_fc.bias"),
                "proj_w": stack("h.{}.mlp.c_proj.weight"),
                "proj_b": stack("h.{}.mlp.c_proj.bias"),
            },
        },
        "ln_f": {"g": t["ln_f.weight"], "b": t["ln_f.bias"]},
    }


def gpt2_params_to_hf(params, prefix: str = "") -> Dict[str, np.ndarray]:
    """Stacked pytree -> HF GPT2LMHeadModel key scheme (for full-FT save,
    reference: gpt2_full_finetune/main.cpp:156-237)."""
    p = {k: np.asarray(v) for k, v in (
        ("wte.weight", params["wte"]), ("wpe.weight", params["wpe"]),
        ("ln_f.weight", params["ln_f"]["g"]),
        ("ln_f.bias", params["ln_f"]["b"]))}
    b = params["blocks"]
    L = np.asarray(b["ln_1"]["g"]).shape[0]
    names = [
        ("h.{}.ln_1.weight", b["ln_1"]["g"]),
        ("h.{}.ln_1.bias", b["ln_1"]["b"]),
        ("h.{}.attn.c_attn.weight", b["attn"]["qkv_w"]),
        ("h.{}.attn.c_attn.bias", b["attn"]["qkv_b"]),
        ("h.{}.attn.c_proj.weight", b["attn"]["proj_w"]),
        ("h.{}.attn.c_proj.bias", b["attn"]["proj_b"]),
        ("h.{}.ln_2.weight", b["ln_2"]["g"]),
        ("h.{}.ln_2.bias", b["ln_2"]["b"]),
        ("h.{}.mlp.c_fc.weight", b["mlp"]["fc_w"]),
        ("h.{}.mlp.c_fc.bias", b["mlp"]["fc_b"]),
        ("h.{}.mlp.c_proj.weight", b["mlp"]["proj_w"]),
        ("h.{}.mlp.c_proj.bias", b["mlp"]["proj_b"]),
    ]
    for fmt, arr in names:
        arr = np.asarray(arr)
        for i in range(L):
            p[fmt.format(i)] = arr[i]
    if prefix:
        p = {prefix + k: v for k, v in p.items()}
    return p


def load_gpt2(model_dir: str, config: Optional[GPT2Config] = None):
    """(config, params) from an HF GPT-2 checkpoint directory."""
    if config is None:
        config = GPT2Config.from_pretrained(model_dir)
    tensors = load_hf_state_dict(model_dir)
    return config, gpt2_params_from_hf(tensors, config)


def save_gpt2(path: str, params, metadata: Optional[dict] = None):
    save_safetensors(path, gpt2_params_to_hf(jax_to_numpy(params)),
                     metadata=metadata or {"format": "pt"})


# ----------------------------- Gemma-3 -------------------------------------

def gemma3_params_from_hf(tensors: Dict[str, np.ndarray],
                          config: Gemma3TextConfig) -> dict:
    """HF Gemma3ForCausalLM (text) state-dict -> stacked pytree.

    HF keys: model.embed_tokens.weight, model.layers.{i}.self_attn.{q,k,v,o}_proj.weight,
    ...input_layernorm, post_attention_layernorm, pre_feedforward_layernorm,
    post_feedforward_layernorm, self_attn.{q,k}_norm, mlp.{gate,up,down}_proj,
    model.norm.weight. Linear weights are [out, in] -> transposed to [in, out].
    """
    t = {}
    for k, v in tensors.items():
        if k.startswith("model."):
            k = k[len("model."):]
        t[k] = v
    L = config.num_hidden_layers

    def stack_w(fmt):  # linear weight: transpose [out,in] -> [in,out]
        return np.stack([t[fmt.format(i)].T for i in range(L)])

    def stack(fmt):
        return np.stack([t[fmt.format(i)] for i in range(L)])

    a = "layers.{}.self_attn."
    m = "layers.{}.mlp."
    return {
        "embed": t["embed_tokens.weight"],
        "blocks": {
            "input_ln": stack("layers.{}.input_layernorm.weight"),
            "attn": {
                "q_w": stack_w(a + "q_proj.weight"),
                "k_w": stack_w(a + "k_proj.weight"),
                "v_w": stack_w(a + "v_proj.weight"),
                "o_w": stack_w(a + "o_proj.weight"),
                "q_norm": stack(a + "q_norm.weight"),
                "k_norm": stack(a + "k_norm.weight"),
            },
            "post_attn_ln": stack("layers.{}.post_attention_layernorm.weight"),
            "pre_ffn_ln": stack("layers.{}.pre_feedforward_layernorm.weight"),
            "mlp": {
                "gate_w": stack_w(m + "gate_proj.weight"),
                "up_w": stack_w(m + "up_proj.weight"),
                "down_w": stack_w(m + "down_proj.weight"),
            },
            "post_ffn_ln": stack("layers.{}.post_feedforward_layernorm.weight"),
        },
        "final_norm": t["norm.weight"],
    }


def gemma3_params_to_hf(params) -> Dict[str, np.ndarray]:
    """Stacked pytree -> HF Gemma3 text key scheme (inverse of
    gemma3_params_from_hf; linear weights back to [out, in]). Used by the
    full-size synthetic-checkpoint pipeline and Gemma full-state saves."""
    p = {"model.embed_tokens.weight": np.asarray(params["embed"])}
    b = params["blocks"]
    L = np.asarray(b["input_ln"]).shape[0]
    a, m = "model.layers.{}.self_attn.", "model.layers.{}.mlp."
    per_layer = [
        ("model.layers.{}.input_layernorm.weight", b["input_ln"], False),
        (a + "q_proj.weight", b["attn"]["q_w"], True),
        (a + "k_proj.weight", b["attn"]["k_w"], True),
        (a + "v_proj.weight", b["attn"]["v_w"], True),
        (a + "o_proj.weight", b["attn"]["o_w"], True),
        (a + "q_norm.weight", b["attn"]["q_norm"], False),
        (a + "k_norm.weight", b["attn"]["k_norm"], False),
        ("model.layers.{}.post_attention_layernorm.weight",
         b["post_attn_ln"], False),
        ("model.layers.{}.pre_feedforward_layernorm.weight",
         b["pre_ffn_ln"], False),
        (m + "gate_proj.weight", b["mlp"]["gate_w"], True),
        (m + "up_proj.weight", b["mlp"]["up_w"], True),
        (m + "down_proj.weight", b["mlp"]["down_w"], True),
        ("model.layers.{}.post_feedforward_layernorm.weight",
         b["post_ffn_ln"], False),
    ]
    for fmt, arr, is_linear in per_layer:
        arr = np.asarray(arr)
        for i in range(L):
            p[fmt.format(i)] = arr[i].T if is_linear else arr[i]
    p["model.norm.weight"] = np.asarray(params["final_norm"])
    return p


def load_gemma3(model_dir: str, config: Optional[Gemma3TextConfig] = None):
    if config is None:
        config = Gemma3TextConfig.from_pretrained(model_dir)
    tensors = load_hf_state_dict(model_dir)
    return config, gemma3_params_from_hf(tensors, config)


def save_gemma3(path: str, params, metadata: Optional[dict] = None):
    """Full-model Gemma-3 save in the HF key scheme (save_gpt2 analog —
    the Gemma full-FT CLI's checkpoint; loads back via load_gemma3 or HF
    transformers)."""
    save_safetensors(path, gemma3_params_to_hf(jax_to_numpy(params)),
                     metadata=metadata or {"format": "pt"})


def jax_to_numpy(tree):
    """Device pytree -> host numpy, BATCHED: all device->host transfers
    are issued async first, then awaited once (io/async_ckpt.snapshot) —
    a per-leaf np.asarray loop would serialize one blocking D2H per
    tensor, which was the dominant save stall on large trees."""
    from mobilefinetuner_tpu.io.async_ckpt import snapshot
    return snapshot(tree)
