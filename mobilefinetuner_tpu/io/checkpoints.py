"""HF checkpoint <-> framework-pytree conversion for GPT-2 and Gemma-3.

Reference: operators/finetune_ops/graph/safetensors_loader.cpp
(`GPT2KeyMapper` mapping HF `h.i.attn.c_attn.*` -> internal keys;
`GemmaKeyMapper` mapping `model.layers.i.*`). Our internal layout stacks
per-layer tensors into [L, ...] arrays (models/gpt2.py, models/gemma3.py),
so "mapping" here is gather+stack rather than per-key rename.

GPT-2 Conv1D subtlety (SURVEY.md §7.3): HF GPT-2 linear weights are stored
[in, out] (Conv1D) and our models compute y = x @ W, so NO transpose is
applied — the same reason the reference CLI disables its loader transpose
(gpt2_lora_finetune/main.cpp:292-296). Gemma weights are true nn.Linear
[out, in]; we transpose those to [in, out] at load.
"""

from __future__ import annotations

import contextlib
import glob as _glob
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.io.safetensors_io import (CheckpointIntegrityError,
                                                   SafeTensorsReader,
                                                   atomic_publish,
                                                   manifest_path,
                                                   save_safetensors,
                                                   verify_report)


def load_hf_state_dict(model_dir: str,
                       promote_to_f32: bool = True) -> Dict[str, np.ndarray]:
    """Load an HF checkpoint dir's full state dict — single-file or sharded
    (model.safetensors.index.json) layouts."""
    index = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index):
        import json
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        out = {}
        for shard in sorted(set(weight_map.values())):
            reader = SafeTensorsReader(os.path.join(model_dir, shard))
            out.update(reader.load_all(promote_to_f32))
        return out
    return SafeTensorsReader(
        _find_weights_file(model_dir)).load_all(promote_to_f32)


def _find_weights_file(model_dir: str) -> str:
    for name in ("model.safetensors", "pytorch_model.safetensors"):
        p = os.path.join(model_dir, name)
        if os.path.exists(p):
            return p
    cands = [f for f in os.listdir(model_dir) if f.endswith(".safetensors")]
    if len(cands) == 1:
        return os.path.join(model_dir, cands[0])
    if cands:
        raise FileNotFoundError(
            f"multiple safetensors shards in {model_dir} but no "
            "model.safetensors.index.json")
    raise FileNotFoundError(f"no safetensors weights in {model_dir}")


def _strip_prefix(tensors: Dict[str, np.ndarray],
                  prefixes=("transformer.", "model.")) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tensors.items():
        for p in prefixes:
            if k.startswith(p):
                k = k[len(p):]
                break
        out[k] = v
    return out


# ----------------------------- GPT-2 ---------------------------------------

def gpt2_params_from_hf(tensors: Dict[str, np.ndarray],
                        config: GPT2Config) -> dict:
    """HF GPT2LMHeadModel state-dict -> stacked pytree (float32 numpy)."""
    t = _strip_prefix(tensors)
    L = config.n_layer

    def stack(fmt):
        return np.stack([t[fmt.format(i)] for i in range(L)])

    return {
        "wte": t["wte.weight"],
        "wpe": t["wpe.weight"],
        "blocks": {
            "ln_1": {"g": stack("h.{}.ln_1.weight"),
                     "b": stack("h.{}.ln_1.bias")},
            "attn": {
                "qkv_w": stack("h.{}.attn.c_attn.weight"),
                "qkv_b": stack("h.{}.attn.c_attn.bias"),
                "proj_w": stack("h.{}.attn.c_proj.weight"),
                "proj_b": stack("h.{}.attn.c_proj.bias"),
            },
            "ln_2": {"g": stack("h.{}.ln_2.weight"),
                     "b": stack("h.{}.ln_2.bias")},
            "mlp": {
                "fc_w": stack("h.{}.mlp.c_fc.weight"),
                "fc_b": stack("h.{}.mlp.c_fc.bias"),
                "proj_w": stack("h.{}.mlp.c_proj.weight"),
                "proj_b": stack("h.{}.mlp.c_proj.bias"),
            },
        },
        "ln_f": {"g": t["ln_f.weight"], "b": t["ln_f.bias"]},
    }


def gpt2_params_to_hf(params, prefix: str = "") -> Dict[str, np.ndarray]:
    """Stacked pytree -> HF GPT2LMHeadModel key scheme (for full-FT save,
    reference: gpt2_full_finetune/main.cpp:156-237)."""
    p = {k: np.asarray(v) for k, v in (
        ("wte.weight", params["wte"]), ("wpe.weight", params["wpe"]),
        ("ln_f.weight", params["ln_f"]["g"]),
        ("ln_f.bias", params["ln_f"]["b"]))}
    b = params["blocks"]
    L = np.asarray(b["ln_1"]["g"]).shape[0]
    names = [
        ("h.{}.ln_1.weight", b["ln_1"]["g"]),
        ("h.{}.ln_1.bias", b["ln_1"]["b"]),
        ("h.{}.attn.c_attn.weight", b["attn"]["qkv_w"]),
        ("h.{}.attn.c_attn.bias", b["attn"]["qkv_b"]),
        ("h.{}.attn.c_proj.weight", b["attn"]["proj_w"]),
        ("h.{}.attn.c_proj.bias", b["attn"]["proj_b"]),
        ("h.{}.ln_2.weight", b["ln_2"]["g"]),
        ("h.{}.ln_2.bias", b["ln_2"]["b"]),
        ("h.{}.mlp.c_fc.weight", b["mlp"]["fc_w"]),
        ("h.{}.mlp.c_fc.bias", b["mlp"]["fc_b"]),
        ("h.{}.mlp.c_proj.weight", b["mlp"]["proj_w"]),
        ("h.{}.mlp.c_proj.bias", b["mlp"]["proj_b"]),
    ]
    for fmt, arr in names:
        arr = np.asarray(arr)
        for i in range(L):
            p[fmt.format(i)] = arr[i]
    if prefix:
        p = {prefix + k: v for k, v in p.items()}
    return p


def load_gpt2(model_dir: str, config: Optional[GPT2Config] = None):
    """(config, params) from an HF GPT-2 checkpoint directory."""
    if config is None:
        config = GPT2Config.from_pretrained(model_dir)
    tensors = load_hf_state_dict(model_dir)
    return config, gpt2_params_from_hf(tensors, config)


def save_gpt2(path: str, params, metadata: Optional[dict] = None):
    save_safetensors(path, gpt2_params_to_hf(jax_to_numpy(params)),
                     metadata=metadata or {"format": "pt"})


# ----------------------------- Gemma-3 -------------------------------------

def gemma3_params_from_hf(tensors: Dict[str, np.ndarray],
                          config: Gemma3TextConfig) -> dict:
    """HF Gemma3ForCausalLM (text) state-dict -> stacked pytree.

    HF keys: model.embed_tokens.weight, model.layers.{i}.self_attn.{q,k,v,o}_proj.weight,
    ...input_layernorm, post_attention_layernorm, pre_feedforward_layernorm,
    post_feedforward_layernorm, self_attn.{q,k}_norm, mlp.{gate,up,down}_proj,
    model.norm.weight. Linear weights are [out, in] -> transposed to [in, out].
    """
    t = {}
    for k, v in tensors.items():
        if k.startswith("model."):
            k = k[len("model."):]
        t[k] = v
    L = config.num_hidden_layers

    def stack_w(fmt):  # linear weight: transpose [out,in] -> [in,out]
        return np.stack([t[fmt.format(i)].T for i in range(L)])

    def stack(fmt):
        return np.stack([t[fmt.format(i)] for i in range(L)])

    a = "layers.{}.self_attn."
    m = "layers.{}.mlp."
    return {
        "embed": t["embed_tokens.weight"],
        "blocks": {
            "input_ln": stack("layers.{}.input_layernorm.weight"),
            "attn": {
                "q_w": stack_w(a + "q_proj.weight"),
                "k_w": stack_w(a + "k_proj.weight"),
                "v_w": stack_w(a + "v_proj.weight"),
                "o_w": stack_w(a + "o_proj.weight"),
                "q_norm": stack(a + "q_norm.weight"),
                "k_norm": stack(a + "k_norm.weight"),
            },
            "post_attn_ln": stack("layers.{}.post_attention_layernorm.weight"),
            "pre_ffn_ln": stack("layers.{}.pre_feedforward_layernorm.weight"),
            "mlp": {
                "gate_w": stack_w(m + "gate_proj.weight"),
                "up_w": stack_w(m + "up_proj.weight"),
                "down_w": stack_w(m + "down_proj.weight"),
            },
            "post_ffn_ln": stack("layers.{}.post_feedforward_layernorm.weight"),
        },
        "final_norm": t["norm.weight"],
    }


def gemma3_params_to_hf(params) -> Dict[str, np.ndarray]:
    """Stacked pytree -> HF Gemma3 text key scheme (inverse of
    gemma3_params_from_hf; linear weights back to [out, in]). Used by the
    full-size synthetic-checkpoint pipeline and Gemma full-state saves."""
    p = {"model.embed_tokens.weight": np.asarray(params["embed"])}
    b = params["blocks"]
    L = np.asarray(b["input_ln"]).shape[0]
    a, m = "model.layers.{}.self_attn.", "model.layers.{}.mlp."
    per_layer = [
        ("model.layers.{}.input_layernorm.weight", b["input_ln"], False),
        (a + "q_proj.weight", b["attn"]["q_w"], True),
        (a + "k_proj.weight", b["attn"]["k_w"], True),
        (a + "v_proj.weight", b["attn"]["v_w"], True),
        (a + "o_proj.weight", b["attn"]["o_w"], True),
        (a + "q_norm.weight", b["attn"]["q_norm"], False),
        (a + "k_norm.weight", b["attn"]["k_norm"], False),
        ("model.layers.{}.post_attention_layernorm.weight",
         b["post_attn_ln"], False),
        ("model.layers.{}.pre_feedforward_layernorm.weight",
         b["pre_ffn_ln"], False),
        (m + "gate_proj.weight", b["mlp"]["gate_w"], True),
        (m + "up_proj.weight", b["mlp"]["up_w"], True),
        (m + "down_proj.weight", b["mlp"]["down_w"], True),
        ("model.layers.{}.post_feedforward_layernorm.weight",
         b["post_ffn_ln"], False),
    ]
    for fmt, arr, is_linear in per_layer:
        arr = np.asarray(arr)
        for i in range(L):
            p[fmt.format(i)] = arr[i].T if is_linear else arr[i]
    p["model.norm.weight"] = np.asarray(params["final_norm"])
    return p


def load_gemma3(model_dir: str, config: Optional[Gemma3TextConfig] = None):
    if config is None:
        config = Gemma3TextConfig.from_pretrained(model_dir)
    tensors = load_hf_state_dict(model_dir)
    return config, gemma3_params_from_hf(tensors, config)


def save_gemma3(path: str, params, metadata: Optional[dict] = None):
    """Full-model Gemma-3 save in the HF key scheme (save_gpt2 analog —
    the Gemma full-FT CLI's checkpoint; loads back via load_gemma3 or HF
    transformers)."""
    save_safetensors(path, gemma3_params_to_hf(jax_to_numpy(params)),
                     metadata=metadata or {"format": "pt"})


# --------------------------- checkpoint lineage ------------------------------
#
# Step-tagged last-known-good checkpoints with GC and verify-on-load
# fallback (DESIGN.md §20). Every train CLI's write hook records each
# completed save into `<final_path>.lineage.json` (atomic publish),
# newest-first: [{"step": S, "files": [basenames...]}, ...] where
# files[0] is the loadable checkpoint and the rest are sidecars (.opt).
# `--keep_ckpts K` prunes the list to the K newest step-tagged entries
# BEFORE unlinking the pruned files — a SIGKILL between the two leaves
# orphan files (harmless), never a lineage that names deleted
# checkpoints as retained. Load paths (`--resume_from`, in-process
# rollback, serve hot-swap) walk the lineage through
# `resolve_checkpoint`, verifying each candidate's manifest and falling
# back down the chain on mismatch instead of crashing on — or silently
# loading — the newest file.

def lineage_path(final_path: str) -> str:
    return final_path + ".lineage.json"


def _load_lineage(final_path: str) -> List[dict]:
    try:
        with open(lineage_path(final_path), "r", encoding="utf-8") as f:
            entries = json.load(f)["entries"]
        return [e for e in entries
                if isinstance(e.get("step"), int) and e.get("files")]
    except (OSError, ValueError, KeyError, TypeError):
        return []


def lineage_entries(final_path: str) -> List[dict]:
    """Newest-first [{step, files: [abs paths]}] from the lineage json
    next to `final_path`; [] when absent/unreadable. Paths are made
    absolute against the checkpoint directory (the lineage stores
    basenames so a checkpoint directory can be moved wholesale)."""
    d = os.path.dirname(os.path.abspath(final_path))
    out = []
    for e in sorted(_load_lineage(final_path),
                    key=lambda e: e["step"], reverse=True):
        out.append({"step": e["step"],
                    "files": [os.path.join(d, os.path.basename(f))
                              for f in e["files"]]})
    return out


def record_checkpoint(final_path: str, step: int, files: List[str],
                      keep: int = 0) -> List[str]:
    """Record one completed save into the lineage and GC old entries.
    `files`: the paths this save wrote (files[0] = the loadable
    checkpoint). `keep` > 0 retains only the `keep` newest STEP-TAGGED
    entries (an entry whose checkpoint is `final_path` itself — the
    run's final artifact — is never pruned); 0 keeps everything.
    Returns the pruned files it unlinked. Kill-safe ordering: the
    pruned lineage publishes atomically FIRST, then files are unlinked
    — dying between the two leaves orphans, not a lineage pointing at
    deleted checkpoints (tests/test_recovery.py pins this)."""
    d = os.path.dirname(os.path.abspath(final_path))
    final_base = os.path.basename(final_path)
    bases = [os.path.basename(f) for f in files]
    entries = [e for e in _load_lineage(final_path)
               if e["step"] != step and e["files"][0] != bases[0]]
    entries.append({"step": int(step), "files": bases})
    entries.sort(key=lambda e: e["step"], reverse=True)
    pruned: List[dict] = []
    if keep and keep > 0:
        kept, tagged = [], 0
        for e in entries:
            if e["files"][0] == final_base:
                kept.append(e)  # the final artifact is never GC'd
            elif tagged < keep:
                kept.append(e)
                tagged += 1
            else:
                pruned.append(e)
        entries = kept
    with atomic_publish(lineage_path(final_path)) as tmp:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": entries}, f,
                      separators=(",", ":"))
    removed = []
    keep_set = {b for e in entries for b in e["files"]}
    for e in pruned:
        for b in e["files"]:
            if b in keep_set:
                continue  # shared file (should not happen; be safe)
            p = os.path.join(d, b)
            for victim in (p, manifest_path(p)):
                with contextlib.suppress(OSError):
                    os.unlink(victim)
                removed.append(victim)
    return removed


def lineage_base_for(path: str) -> Optional[str]:
    """The FINAL-artifact path whose lineage json lists `path` as a
    checkpoint — found by scanning `*.lineage.json` next to it. A
    step-tagged file (`a_step6.safetensors`) carries no lineage of its
    own; its chain lives at `a.safetensors.lineage.json`."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    if os.path.exists(lineage_path(path)):
        return path
    for lp in _glob.glob(os.path.join(_glob.escape(d), "*.lineage.json")):
        final = lp[: -len(".lineage.json")]
        for e in _load_lineage(final):
            if e["files"] and os.path.basename(e["files"][0]) == base:
                return final
    return None


def lineage_step_for(path: str) -> Optional[int]:
    """The LOOP step a checkpoint file was saved at, looked up from the
    lineage that lists it (lineage_base_for). Needed because a
    `--skip_nonfinite` run's Adam step counter lags the loop step by
    the skipped updates — the .opt sidecar's `step` tensor is the wrong
    resume point then (the sidecar's `loop_step` metadata is the
    primary source; this is the fallback for sidecars written before
    it existed)."""
    base = lineage_base_for(path)
    if base is None:
        return None
    name = os.path.basename(path)
    for e in lineage_entries(base):
        if os.path.basename(e["files"][0]) == name:
            return e["step"]
    return None


def _verify_entry(files: List[str]) -> Tuple[str, Optional[str]]:
    """Aggregate verify_report over an entry's file set: 'corrupt'
    dominates, then 'unverified', else 'ok'. A missing SIDECAR is
    corruption of the entry (the checkpoint alone cannot resume the
    optimizer); reasons are prefixed with the offending basename."""
    worst, why = "ok", None
    for f in files:
        status, reason = verify_report(f)
        tagged = f"{os.path.basename(f)}:{reason}" if reason else None
        if status == "corrupt":
            return "corrupt", tagged
        if status == "unverified" and worst == "ok":
            worst, why = "unverified", tagged
    return worst, why


def resolve_checkpoint(path: Optional[str], verify: bool = True,
                       lineage_base: Optional[str] = None,
                       max_step: Optional[int] = None):
    """Resolve the checkpoint a load should actually use, walking the
    integrity lineage: returns (resolved_path, step_or_None, events)
    where events is a list of `ckpt_verify` telemetry payloads
    ({path, ok, reason, step, action}) in visit order.

    Candidates: the explicit `path` first (when given), then the
    lineage entries next to `lineage_base` (default: `path`) newest-
    first, skipping entries newer than `max_step` (the rollback caller
    must not "resume" into the future). The first candidate whose
    manifest fully verifies wins; if NONE verifies, the newest
    'unverified' candidate (parseable file, no manifest — a
    pre-manifest checkpoint) is accepted with ok=false so legacy
    resumes keep working; if nothing is loadable at all, an explicit
    `path` raises CheckpointIntegrityError and a lineage-only walk
    (rollback) returns (None, None, events). verify=False short-
    circuits to the explicit path unchanged (--verify_ckpt 0)."""
    if not verify:
        # trust-the-newest mode (--verify_ckpt 0): no checksum walk,
        # but a lineage-only call (rollback's path=None) must still
        # resolve the newest EXISTING entry — "don't verify" must not
        # mean "can't roll back"
        if path:
            return path, lineage_step_for(path), []
        for e in (lineage_entries(lineage_base) if lineage_base else []):
            if max_step is not None and e["step"] > max_step:
                continue
            if os.path.exists(e["files"][0]):
                return e["files"][0], e["step"], []
        return None, None, []
    base = lineage_base or (lineage_base_for(path) if path else None)
    candidates: List[Tuple[str, Optional[int], List[str]]] = []
    seen = set()
    if path:
        files = [path] + ([path + ".opt"]
                          if os.path.exists(path + ".opt") else [])
        candidates.append((path, lineage_step_for(path), files))
        seen.add(os.path.abspath(path))
    if base:
        for e in lineage_entries(base):
            main = e["files"][0]
            if os.path.abspath(main) in seen:
                continue
            if max_step is not None and e["step"] > max_step:
                continue
            seen.add(os.path.abspath(main))
            candidates.append((main, e["step"], e["files"]))
    events: List[dict] = []
    fallback: Optional[Tuple[str, Optional[int]]] = None
    for main, step, files in candidates:
        status, reason = _verify_entry(files)
        ok = status == "ok"
        events.append({"path": main, "ok": ok, "reason": reason,
                       "step": step,
                       "action": "load" if ok else "reject"})
        if ok:
            return main, step, events
        if status == "unverified" and fallback is None:
            fallback = (main, step)
    if fallback is not None:
        main, step = fallback
        events.append({"path": main, "ok": False,
                       "reason": "loaded_unverified", "step": step,
                       "action": "load"})
        return main, step, events
    if path:
        raise CheckpointIntegrityError(
            f"{path}: no loadable checkpoint in its lineage "
            f"({len(candidates)} candidate(s) rejected: "
            f"{[e['reason'] for e in events]})")
    return None, None, events


def jax_to_numpy(tree):
    """Device pytree -> host numpy, BATCHED: all device->host transfers
    are issued async first, then awaited once (io/async_ckpt.snapshot) —
    a per-leaf np.asarray loop would serialize one blocking D2H per
    tensor, which was the dominant save stall on large trees."""
    from mobilefinetuner_tpu.io.async_ckpt import snapshot
    return snapshot(tree)
