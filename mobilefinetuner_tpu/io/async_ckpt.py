"""Async overlapped checkpointing: snapshot-then-write (DESIGN.md §15).

The reference is built around interrupted training — the energy governor
suspends runs on battery/thermal signals, so frequent checkpoints are a
first-class workload — yet a naive save stalls the step loop for the
device→host pull PLUS the disk write. This module splits the two:

  - **snapshot** (`snapshot`/`timed_snapshot`): the step loop's ONLY
    blocking work. Phase 1 issues `copy_to_host_async` on every
    addressable shard of every device leaf in ONE batched pass (the
    transfers overlap each other and any in-flight device compute);
    phase 2 is one bounded wait that materializes the host numpy tree.
    The wait is NOT optional — it is the donation-hazard guard: the
    caller's next dispatched train step donates the trainable/optimizer
    buffers (`make_train_step(donate=True)`), and an un-awaited D2H copy
    would race the donated buffers' reuse and snapshot garbage. After
    `snapshot` returns, the host tree is immutable numpy and the step
    loop may dispatch freely (regression-pinned by
    tests/test_async_ckpt.py's donation test).

  - **write** (`AsyncCheckpointer`): HF key-mapping, bf16 encode, and
    the safetensors write run on a single background thread, off the
    step loop. Crash safety belongs to the writers themselves
    (`safetensors_io.atomic_publish`: tmp + fsync + atomic rename — a
    kill mid-write can never corrupt the checkpoint `--resume_from`
    loads). Backpressure is a bounded depth-1 queue: a save request
    landing while one is in flight COALESCES to the newest snapshot
    (the superseded snapshot is dropped with a `ckpt_dropped` telemetry
    event — checkpoints are recovery points, only the newest matters);
    `final=True` saves drain the queue and block until everything is on
    disk. Background write failures are stored and re-raised at the
    next save()/drain()/close(raise_errors=True), so a disk-full writer
    surfaces instead of silently losing checkpoints.

Telemetry: the `checkpoint` event is emitted HERE (not by the step
loop), carrying the split the goodput accounting needs — `wall_s` and
`snapshot_ms` are the blocking cost charged to the loop (what the
goodput `checkpoint` bucket counts), `write_ms`/`bytes`/`mb_s` the
background cost that now overlaps `step` time. The sync oracle path
(`--async_save 0`, enabled=False) runs the same write_fn inline and
emits the same event shape with `async: false` and `wall_s` covering
snapshot + write — the two paths produce byte-identical files
(tests/test_async_ckpt.py pins the parity for both model families).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Iterable, Optional

import numpy as np

# lock-discipline declaration (core/static_checks.py, DESIGN.md §24):
# the cross-thread contract graftlint enforces mechanically — every
# guarded field may be touched only under the declared lock.
GRAFT_SHARED_STATE = {
    "AsyncCheckpointer": {
        "lock": "_lock",
        "guarded": ["_pending", "_inflight", "_error", "_stop"],
        "locked_helpers": [],
        "channels": ["_work"],  # Condition BUILT ON _lock
        "note": "dropped is written under _lock on the step-loop side; "
                "written is writer-thread-only; _thread is started "
                "under _lock and joined only by the step-loop thread",
    },
}


# ----------------------------- snapshot -------------------------------------

def snapshot(tree):
    """Batched device→host pull of a pytree: issue `copy_to_host_async`
    on EVERY device leaf first (one batched issue — the transfers
    overlap instead of serializing), then one bounded wait materializing
    numpy. Host/numpy leaves pass through untouched, so the function is
    idempotent and safe on already-gathered (multi-host) trees.

    The returned tree is plain numpy: safe to hand to a background
    writer while the step loop keeps training — including steps that
    DONATE the source buffers (the wait in phase 2 completes before any
    such dispatch can happen; see the module docstring's donation
    hazard)."""
    import jax
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                leaf.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # committed-to-host or deleted arrays: asarray below
    return jax.tree.map(np.asarray, tree)


def timed_snapshot(tree):
    """(host_tree, blocking_ms) — the number the step loop charges to
    the checkpoint goodput bucket and `checkpoint.snapshot_ms`."""
    t0 = time.perf_counter()
    host = snapshot(tree)
    return host, (time.perf_counter() - t0) * 1000.0


def tree_bytes(host_tree) -> int:
    """Total nbytes of a host snapshot (telemetry/bench accounting)."""
    import jax
    return int(sum(np.asarray(x).nbytes for x in jax.tree.leaves(host_tree)))


# ----------------------------- background writer -----------------------------

class CheckpointDrainTimeout(TimeoutError):
    """A bounded drain/close expired with a checkpoint write still in
    flight — the writer thread is wedged (dead filesystem, hung NFS).
    Carries the stuck step in the message so the operator knows WHICH
    recovery point never landed; atomic publication guarantees the
    unfinished write left no corrupt file behind. A NAMED type so
    cleanup paths can distinguish 'writer wedged, abandon it' from a
    real write error (which close() re-raises as RuntimeError)."""

    def __init__(self, step: int, timeout: float):
        self.step = step
        self.timeout = timeout
        super().__init__(
            f"checkpoint write for step {step} still in flight after "
            f"{timeout:.1f}s drain timeout (writer thread wedged; the "
            f"unfinished write cannot corrupt any published checkpoint)")


class _SaveItem:
    __slots__ = ("step", "write_fn", "final", "snapshot_ms", "done")

    def __init__(self, step, write_fn, final, snapshot_ms):
        self.step = step
        self.write_fn = write_fn
        self.final = final
        self.snapshot_ms = snapshot_ms
        self.done = threading.Event()


class AsyncCheckpointer:
    """Snapshot-then-write checkpoint pipeline (one per training run).

    `save(step, write_fn, final=..., snapshot_ms=...)` hands a
    zero-argument `write_fn` — closing over an already-snapshotted HOST
    tree — to a single background writer thread. `write_fn` must return
    the paths it wrote (for the bytes/MB-s accounting) and must go
    through atomically-publishing writers (every safetensors writer in
    this repo does — `safetensors_io.atomic_publish`).

    enabled=False is the synchronous oracle (`--async_save 0`): save()
    runs write_fn inline and returns after the write — same event
    shape, same bytes on disk, no thread.

    event_sink has `Telemetry.emit`'s signature (event, **fields) and
    may be None; emission is serialized by Telemetry's own lock, so the
    writer thread and the step loop share one stream safely.
    """

    def __init__(self, enabled: bool = True,
                 event_sink: Optional[Callable] = None, tracer=None):
        self.enabled = bool(enabled)
        self._sink = event_sink
        # span tracing (core/trace.py, --trace_spans): each disk write
        # lands as a `span` on the "ckpt" track — emitted from the
        # writer THREAD, so the exported timeline shows the background
        # write overlapping `step` time (the overlap is this module's
        # whole point; the trace draws it)
        self._tracer = tracer
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._pending: Optional[_SaveItem] = None
        self._inflight: Optional[_SaveItem] = None
        self._error: Optional[BaseException] = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.dropped = 0   # coalesced-away snapshots (observable in tests)
        self.written = 0   # completed writes

    # -- step-loop side -------------------------------------------------------

    def save(self, step: int, write_fn: Callable[[], Iterable[str]], *,
             final: bool = False, snapshot_ms: float = 0.0) -> None:
        """Queue (async) or perform (sync) one checkpoint write. Blocking
        time for the caller: ~0 async (enqueue + possible coalesce), the
        full write when sync or final=True (final drains — the run must
        not end before its last checkpoint is durable). Raises a stored
        background-write error instead of enqueueing more work onto a
        broken writer."""
        self._raise_pending_error()
        if not self.enabled:
            self._write(_SaveItem(step, write_fn, final, snapshot_ms))
            self._raise_pending_error()
            return
        item = _SaveItem(step, write_fn, final, snapshot_ms)
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="ckpt-writer")
                self._thread.start()
            if self._pending is not None:
                # depth-1 backpressure: coalesce to the newest snapshot.
                # A checkpoint is a recovery point — when the writer
                # falls behind, writing every intermediate one buys
                # nothing but queue growth (unbounded host copies of the
                # whole tree); the superseded snapshot is dropped and
                # recorded.
                old = self._pending
                self._pending = item
                self.dropped += 1
                old.done.set()  # nobody will write it; unblock waiters
                self._emit(event="ckpt_dropped", step=old.step,
                           superseded_by=item.step)
            else:
                self._pending = item
            self._work.notify()
        if final:
            self.drain()

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until the queue is empty and the in-flight write (if
        any) completed; re-raise any background-write error. timeout
        (per outstanding item) bounds the wait for cleanup paths — a
        final=True save drains WITHOUT one (the run must not end before
        its last checkpoint is durable). A bounded drain that expires
        raises CheckpointDrainTimeout NAMING the in-flight step (it
        used to return silently, which let a hung write stall shutdown
        indefinitely downstream — the caller had no way to know the
        drain gave up)."""
        while True:
            with self._lock:
                item = self._inflight or self._pending
            if item is None:
                break
            if not item.done.wait(timeout):
                raise CheckpointDrainTimeout(item.step, timeout or 0.0)
        self._raise_pending_error()

    def close(self, raise_errors: bool = True,
              drain_timeout: float = 600.0) -> None:
        """Drain outstanding writes (a snapshot already taken is a
        checkpoint worth finishing, even when the training loop died)
        and stop the writer thread. raise_errors=False swallows write
        errors — for exception-path cleanup where re-raising would mask
        the original failure. The drain is BOUNDED (generously — any
        real write finishes in minutes; a dead filesystem never does)
        so a wedged writer cannot hang cleanup forever: on
        CheckpointDrainTimeout the daemon thread is ABANDONED — no
        30-second join against a thread known to be stuck (atomic
        publication means the unfinished write leaves no corrupt file
        behind) — and with raise_errors the named timeout propagates so
        shutdown reports WHICH step's recovery point was lost. The
        writer thread is stopped/joined on every other path, including
        when the drain re-raises a stored write error (no thread
        leak)."""
        wedged = False
        try:
            self.drain(timeout=drain_timeout)
        except CheckpointDrainTimeout:
            wedged = True
            if raise_errors:
                raise
        except BaseException:
            if raise_errors:
                raise
        finally:
            with self._lock:
                self._stop = True
                self._work.notify()
            if self._thread is not None:
                self._thread.join(timeout=0.2 if wedged else 30.0)
                self._thread = None

    # -- writer side ----------------------------------------------------------

    def _raise_pending_error(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError("background checkpoint write failed") from err

    def _emit(self, event: str, **fields):
        if self._sink is not None:
            self._sink(event, **fields)

    def _write(self, item: _SaveItem) -> None:
        t0 = time.perf_counter()
        try:
            paths = list(item.write_fn() or ())
        except BaseException as e:  # surfaced at the next save()/drain()
            with self._lock:
                self._error = e
            return
        finally:
            item.done.set()
        write_ms = (time.perf_counter() - t0) * 1000.0
        if self._tracer is not None:
            self._tracer.emit_span(f"ckpt_write(step {item.step})",
                                   "ckpt", t0, write_ms, step=item.step)
        nbytes = 0
        for p in paths:
            try:
                nbytes += os.path.getsize(p)
            except OSError:
                pass
        self.written += 1
        # wall_s = the BLOCKING cost this save charged to the step loop
        # (snapshot only under async; snapshot + write sync) — the same
        # number the goodput `checkpoint` bucket and partial_goodput
        # count, so the stream's checkpoint accounting matches the meter
        blocking_ms = item.snapshot_ms + (0.0 if self.enabled else write_ms)
        self._emit(event="checkpoint", step=item.step, final=item.final,
                   wall_s=round(blocking_ms / 1000.0, 4),
                   snapshot_ms=round(item.snapshot_ms, 3),
                   write_ms=round(write_ms, 3),
                   bytes=nbytes,
                   mb_s=(round(nbytes / 2**20 / (write_ms / 1000.0), 2)
                         if write_ms > 0 and nbytes else None),
                   **{"async": self.enabled})

    def _run(self) -> None:
        while True:
            with self._lock:
                while self._pending is None and not self._stop:
                    self._work.wait()
                if self._pending is None and self._stop:
                    return
                self._inflight, self._pending = self._pending, None
                item = self._inflight
            try:
                self._write(item)
            finally:
                with self._lock:
                    self._inflight = None


def submit(ckpt: Optional[AsyncCheckpointer], step: int,
           write_fn: Callable[[], Iterable[str]], *, final: bool = False,
           snapshot_ms: float = 0.0) -> None:
    """Save-hook helper: route through the run's checkpointer when the
    loop passed one, else write inline (direct/legacy callers — align
    dumps, tests driving a save hook by hand)."""
    if ckpt is None:
        write_fn()
        return
    ckpt.save(step, write_fn, final=final, snapshot_ms=snapshot_ms)
