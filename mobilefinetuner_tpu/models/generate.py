"""Autoregressive generation with a KV cache, for GPT-2 and Gemma-3.

The reference framework is training/eval-only: its only KV-cache and
sampling code sits in the excluded legacy tree (reference:
legacy/transformer/kv_cache.cpp + autoregressive_ops, catalogued "orphan"
in SURVEY.md §2.10). This module supplies that missing capability
TPU-natively:

  * prefill = ONE full-sequence forward (the models' scan path, MXU-sized
    matmuls) that also returns every layer's K/V (`collect_kv=True`);
  * decode = a `lax.scan` over token steps; each step runs all layers via
    an inner scan over the stacked [L, ...] weights, updating the cache
    with `dynamic_update_slice` — static shapes throughout, one compiled
    program for the whole generation;
  * prompts are LEFT-padded to a common length so every cache write lands
    at the same column; positions/RoPE phases are mask-derived per sample,
    matching the models' HF-aligned padded-batch semantics.

LoRA: merge adapters into the base weights first (lora.merge_gpt2 /
merge_gemma3) — generation reads plain params.

Sampling: greedy, temperature, top-k, nucleus (top-p), composable; eos
stops a row (further slots fill with pad_id) and `lax.while_loop`-free
full-length scan keeps shapes static.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.models import gemma3, gpt2
from mobilefinetuner_tpu.models.lora_apply import maybe_lora
from mobilefinetuner_tpu.ops.rope import apply_rope, rope_cos_sin

NEG_INF = -1e30


def _head_lora(logits, h, lora_b, impl):
    """Apply an optional "lm_head" adapter entry at a logits projection
    site (decode/prefill shapes are one token per row — the cost model
    keeps these on the rank-r XLA order)."""
    if lora_b is None or "lm_head" not in lora_b:
        return logits
    return maybe_lora(logits, h, lora_b["lm_head"], None, impl=impl)


@dataclasses.dataclass(frozen=True)
class SampleConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0     # <= 0 or greedy=True -> argmax
    top_k: int = 0               # 0 = off
    top_p: float = 1.0           # 1.0 = off
    greedy: bool = False
    eos_id: Optional[int] = None
    pad_id: int = 0


def _filter_logits(logits, cfg: SampleConfig):
    """Apply top-k then top-p filtering (HF order) to [B, V] logits."""
    V = logits.shape[-1]
    if cfg.top_k and cfg.top_k < V:
        kth = jnp.sort(logits, axis=-1)[:, V - cfg.top_k][:, None]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if cfg.top_p < 1.0:
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose cumulative mass (exclusive) is < top_p; the
        # first token is always kept
        keep_sorted = (cum - probs) < cfg.top_p
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(logits.shape[0])[:, None], sort_idx].set(keep_sorted)
        logits = jnp.where(keep, logits, NEG_INF)
    return logits


def _sample(logits, key, cfg: SampleConfig):
    """[B, V] logits -> [B] token ids."""
    if cfg.greedy or cfg.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _filter_logits(logits / cfg.temperature, cfg)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_per_row(logits, temperature, top_k, top_p, keys):
    """Per-row sampling for the serve engine's slot batch: [S, V]
    logits with PER-ROW temperature [S] f32 / top_k [S] i32 / top_p
    [S] f32 and per-row PRNG keys [S, 2] uint32 (raw legacy layout,
    already fold_in'd with the token's absolute position by the
    caller). Generalizes _filter_logits' scalar top-k/top-p to vector
    parameters so one compiled step serves mixed greedy/sampled slots:
    temperature <= 0 rows take the bit-exact greedy argmax (idle slots
    and greedy requests), top_k <= 0 / top_p >= 1 disable each filter
    per row."""
    S, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # per-row top-k: kth-value threshold (the row-gathered analog of
    # _filter_logits' scalar sort-index)
    k_eff = jnp.where((top_k > 0) & (top_k < V), top_k, V)
    kth = jnp.sort(scaled, axis=-1)[jnp.arange(S), V - k_eff][:, None]
    scaled = jnp.where(scaled < kth, NEG_INF, scaled)
    # per-row top-p: exclusive-cumulative-mass keep mask scattered back
    # through the descending sort (HF order, as _filter_logits)
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < jnp.minimum(top_p, 1.0)[:, None]
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(S)[:, None], sort_idx].set(keep_sorted)
    scaled = jnp.where(keep, scaled, NEG_INF)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row))(keys, scaled)
    return jnp.where(temperature <= 0.0, greedy,
                     sampled.astype(jnp.int32))


def _advance(tok_raw, done, cfg: SampleConfig):
    """eos bookkeeping: emit pad for finished rows, mark rows that just
    emitted eos as finished AFTER emitting it."""
    tok = jnp.where(done, jnp.int32(cfg.pad_id), tok_raw)
    if cfg.eos_id is not None:
        done = done | (tok_raw == cfg.eos_id)
    return tok, done


def _col_positions(attention_mask, P, T):
    """Per-sample position ids of every cache column [B, T]: prompt columns
    use mask-derived positions (HF convention), generated column P+j has
    position n_real + j."""
    n_real = attention_mask.sum(-1).astype(jnp.int32)            # [B]
    prompt_pos = jnp.clip(
        jnp.cumsum(attention_mask.astype(jnp.int32), axis=-1) - 1, 0)
    gen_pos = n_real[:, None] + jnp.arange(T - P, dtype=jnp.int32)[None, :]
    return jnp.concatenate([prompt_pos, gen_pos], axis=-1)


def _col_valid(attention_mask, P, T, t):
    """[B, T] bool: which cache columns are attendable at decode step t
    (prompt columns per the mask; generated columns 0..t)."""
    cols = jnp.arange(T)
    gen_ok = cols[None, :] <= P + t
    prompt = jnp.pad(attention_mask.astype(bool),
                     ((0, 0), (0, T - P)), constant_values=True)
    return prompt & gen_ok


# ----------------------------------------------------------- GPT-2 ----------

def gpt2_generate(config: GPT2Config, params, input_ids, attention_mask,
                  cfg: SampleConfig, rng: Optional[jax.Array] = None,
                  compute_dtype=jnp.float32, lora=None,
                  lora_impl: str = "auto"):
    """Generate [B, max_new_tokens] ids from LEFT-padded prompts [B, P].

    One jittable program: full-forward prefill (collect_kv) + scanned
    single-token decode over a [L, B, H, P+N, D] cache.

    lora: optional adapter pytree (lora/lora.py) applied DYNAMICALLY —
    prefill through the training forward's LoRA path, decode via
    per-layer maybe_lora at every adapter site. Serving many adapters
    without materializing merged weight copies; merge_gpt2 + lora=None
    remains the (slightly faster) single-adapter path.
    """
    B, P = input_ids.shape
    N = cfg.max_new_tokens
    T = P + N
    if T > config.n_positions:
        # learned absolute positions: an out-of-range wpe gather would
        # silently clamp to the last row and quietly degrade sampling.
        # Validated BEFORE the N<=0 early-out so an over-long prompt
        # errors regardless of how many tokens were requested.
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({N}) = {T} exceeds "
            f"n_positions={config.n_positions}")
    if N <= 0:
        # honor max_new_tokens=0 instead of silently emitting the prefill
        # sample (the decode scan below always appends the carried token)
        return jnp.zeros((B, 0), jnp.int32)
    E, H, D = config.n_embd, config.n_head, config.head_dim
    L = config.n_layer
    rng = jax.random.PRNGKey(0) if rng is None else rng
    params = jax.tree.map(jnp.asarray, params)

    x, (pk, pv) = gpt2.hidden_states(
        config, params, input_ids, attention_mask, lora=lora,
        compute_dtype=compute_dtype, collect_kv=True,
        lora_impl=lora_impl)
    lora_b = None if lora is None else lora.get("blocks")
    logits0 = x[:, -1] @ params["wte"].astype(compute_dtype).T  # [B, V]
    logits0 = _head_lora(logits0, x[:, -1], lora_b, lora_impl)

    pad_kv = lambda t: jnp.pad(t, ((0, 0), (0, 0), (0, 0), (0, N), (0, 0)))
    kc, vc = pad_kv(pk), pad_kv(pv)                  # [L, B, H, T, D]

    n_real = attention_mask.sum(-1).astype(jnp.int32)
    wb = params["blocks"]
    eps = config.layer_norm_epsilon
    cast = lambda t: (t.astype(compute_dtype)
                      if jnp.issubdtype(t.dtype, jnp.floating) else t)
    wb = jax.tree.map(cast, wb)

    def decode_step(carry, step_rng_t):
        tok, done, kc, vc = carry
        t, key = step_rng_t
        pos = n_real + t                                        # [B]
        x = params["wte"][tok].astype(compute_dtype) \
            + params["wpe"][pos].astype(compute_dtype)          # [B, E]
        valid = _col_valid(attention_mask, P, T, t)             # [B, T]

        def apply_lora(y, x_in, name, i):
            entry = None if lora_b is None else lora_b.get(name)
            return maybe_lora(y, x_in, entry, i, impl=lora_impl)

        def layer(inner, inp):
            # The [L, B, H, T, D] caches ride the inner CARRY and are
            # updated with one [1,B,H,1,D] dynamic-update-slice per layer.
            # The previous structure scanned them as xs and restacked them
            # as ys, which materialized a full-cache copy per decode step
            # (~460 us/step for GPT-2s B=8, measured — the single largest
            # decode cost). As carry leaves, the updates alias in place.
            x, kc, vc = inner
            bp, i = inp
            h = gpt2.layer_norm(x, bp["ln_1"]["g"], bp["ln_1"]["b"], eps)
            qkv = h @ bp["attn"]["qkv_w"] + bp["attn"]["qkv_b"]
            qkv = apply_lora(qkv, h, "attn_qkv", i)
            if lora_b is not None:
                # split-QKV adapters hit their column range of the fused
                # c_attn output (models/gpt2.py _block, same site salts)
                from mobilefinetuner_tpu.lora.lora import \
                    GPT2_SPLIT_QKV_SLOTS
                for name, slot in GPT2_SPLIT_QKV_SLOTS.items():
                    if name in lora_b:
                        sl = (Ellipsis, slice(slot * E, (slot + 1) * E))
                        qkv = qkv.at[sl].set(
                            apply_lora(qkv[sl], h, name, i))
            q, k, v = jnp.split(qkv, 3, axis=-1)
            hd = lambda z: z.reshape(B, H, D)
            q, k, v = hd(q), hd(k), hd(v)
            kc = jax.lax.dynamic_update_slice(
                kc, k[None, :, :, None, :].astype(kc.dtype),
                (i, 0, 0, P + t, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v[None, :, :, None, :].astype(vc.dtype),
                (i, 0, 0, P + t, 0))
            kc_l = jax.lax.dynamic_index_in_dim(kc, i, 0, keepdims=False)
            vc_l = jax.lax.dynamic_index_in_dim(vc, i, 0, keepdims=False)
            # keep the cache operands in their storage dtype and accumulate
            # in f32 (preferred_element_type): an explicit .astype(f32) on
            # the [B,H,T,D] cache slices materializes ~9 MB of converts
            # per layer per token — measured decode cost, not a numerics
            # win (softmax statistics stay f32 either way). (Tried and
            # rejected: broadcasting q to 8 query rows to force the MXU —
            # the extra consumer broke the cache DUS aliasing and brought
            # full-cache copies back, 1.35 -> 1.62 ms/token.)
            s = jnp.einsum("bhd,bhtd->bht", q, kc_l,
                           preferred_element_type=jnp.float32) / (D ** 0.5)
            s = jnp.where(valid[:, None, :], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bht,bhtd->bhd", p.astype(vc_l.dtype), vc_l,
                             preferred_element_type=jnp.float32)
            ctx = ctx.reshape(B, E).astype(compute_dtype)
            proj = ctx @ bp["attn"]["proj_w"] + bp["attn"]["proj_b"]
            proj = apply_lora(proj, ctx, "attn_proj", i)
            x = x + proj
            h2 = gpt2.layer_norm(x, bp["ln_2"]["g"], bp["ln_2"]["b"], eps)
            fc = h2 @ bp["mlp"]["fc_w"] + bp["mlp"]["fc_b"]
            fc = gpt2.gelu_new(apply_lora(fc, h2, "mlp_fc_in", i))
            out = fc @ bp["mlp"]["proj_w"] + bp["mlp"]["proj_b"]
            out = apply_lora(out, fc, "mlp_fc_out", i)
            return (x + out, kc, vc), None

        (x, kc, vc), _ = jax.lax.scan(
            layer, (x, kc, vc), (wb, jnp.arange(L, dtype=jnp.int32)))
        x = gpt2.layer_norm(x, params["ln_f"]["g"].astype(compute_dtype),
                            params["ln_f"]["b"].astype(compute_dtype), eps)
        logits = x @ params["wte"].astype(compute_dtype).T
        logits = _head_lora(logits, x, lora_b, lora_impl)
        nxt_raw = _sample(logits.astype(jnp.float32), key, cfg)
        nxt, done = _advance(nxt_raw, done, cfg)
        return (nxt, done, kc, vc), tok

    all_keys = jax.random.split(rng, N + 1)
    tok0_raw = _sample(logits0.astype(jnp.float32), all_keys[N], cfg)
    tok0, done0 = _advance(tok0_raw, jnp.zeros((B,), bool), cfg)
    # N-1 decode steps: step t consumes token t and samples token t+1, so
    # the final token comes out of the carry — no trailing all-layers
    # forward whose sample would be discarded
    steps = jnp.arange(N - 1, dtype=jnp.int32)
    keys = all_keys[:N - 1]
    (tok_last, _, _, _), toks = jax.lax.scan(
        decode_step, (tok0, done0, kc, vc), (steps, keys))
    toks = jnp.concatenate([toks, tok_last[None]], axis=0)
    return toks.T                                              # [B, N]


# ---------------------------------------------------------- Gemma-3 ---------

def _gemma_chunked_prefill(c, params, wb, input_ids, attention_mask,
                           lora_b, T, compute_dtype, W, apply_rope_fn,
                           lora_impl: str = "auto"):
    """Windowed prefill for LONG prompts: process the prompt in W-token
    windows, each window's attention reading the K/V cache of everything
    before it plus itself — peak score memory is O(W·P) instead of the
    whole-forward's O(P^2) blocks, and windows compile per static prefix
    length (the window loop is a Python loop over static offsets).
    Returns (last_hidden [B, E], kc, vc [L, B, Hkv, T, D]).

    The math per window is the training block's (sandwich norms, GQA,
    q/k RMSNorm, dual-theta RoPE, sliding window over POSITION ids)
    vectorized the decode way: scores against the cache with explicit
    validity masks, so left padding and window boundaries cannot shift
    phases. Gemma-only: GPT-2's 1024 learned positions make long prompts
    impossible before memory does.

    This is deliberately the THIRD spelling of the Gemma block (after
    gemma3._block and decode_step's layer) rather than a shared
    windowed-layer function: the decode copy's buffer structure is
    perf-fragile (an extra consumer of the cache broke its in-place DUS
    aliasing once already — DESIGN.md §10), and each copy is pinned by
    an exact-parity CI oracle (training ≡ HF, decode ≡ no-cache rollout,
    chunked ≡ whole-prompt), so a site change that misses one copy fails
    tests instead of shipping."""
    B, P = input_ids.shape
    nq, nkv, D = c.num_attention_heads, c.num_key_value_heads, c.head_dim
    G = nq // nkv
    eps = c.rms_norm_eps
    scale = c.query_pre_attn_scalar ** -0.5
    L = c.num_hidden_layers
    is_global = jnp.asarray([c.is_global_layer(i) for i in range(L)])
    normalizer = jnp.asarray(c.hidden_size ** 0.5, compute_dtype)
    col_pos = _col_positions(attention_mask, P, T)              # [B, T]
    prompt_ok = attention_mask.astype(bool)                     # [B, P]

    kc = jnp.zeros((L, B, nkv, T, D), compute_dtype)
    vc = jnp.zeros((L, B, nkv, T, D), compute_dtype)

    def apply_lora(y, x_in, name, i):
        entry = None if lora_b is None else lora_b.get(name)
        return maybe_lora(y, x_in, entry, i, impl=lora_impl)

    x_last = None
    for w0 in range(0, P, W):
        ids_w = input_ids[:, w0:w0 + W]
        pos_w = col_pos[:, w0:w0 + W]                           # [B, W]
        x = params["embed"][ids_w].astype(compute_dtype) * normalizer
        cos_g, sin_g = rope_cos_sin(pos_w, D, c.rope_theta)
        cos_l, sin_l = rope_cos_sin(pos_w, D, c.rope_local_base_freq)
        hi = w0 + W                          # static prefix length
        # [B, W, hi]: prompt-mask valid AND causal vs the global column
        cols = jnp.arange(hi)
        causal = cols[None, None, :] <= (w0 + jnp.arange(W))[None, :, None]
        valid = prompt_ok[:, None, :hi] & causal
        win = (pos_w[:, :, None] - col_pos[:, None, :hi]) < c.sliding_window

        def layer(inner, inp):
            x, kc, vc = inner
            bp, glob, i = inp
            a = bp["attn"]
            h = gemma3.rms_norm(x, bp["input_ln"], eps)
            q = apply_lora(h @ a["q_w"], h, "q_proj", i) \
                .reshape(B, W, nq, D)
            k = apply_lora(h @ a["k_w"], h, "k_proj", i) \
                .reshape(B, W, nkv, D)
            v = apply_lora(h @ a["v_w"], h, "v_proj", i) \
                .reshape(B, W, nkv, D)
            q = gemma3.rms_norm(q, a["q_norm"], eps)
            k = gemma3.rms_norm(k, a["k_norm"], eps)
            cos = jnp.where(glob, cos_g, cos_l)
            sin = jnp.where(glob, sin_g, sin_l)
            # apply_rope expects [B, H, S, D]; v joins the cache layout
            q = apply_rope_fn(q.transpose(0, 2, 1, 3), cos, sin)
            k = apply_rope_fn(k.transpose(0, 2, 1, 3), cos, sin)
            v = v.transpose(0, 2, 1, 3)              # [B, nkv, W, D]
            kc = jax.lax.dynamic_update_slice(
                kc, k[None].astype(kc.dtype), (i, 0, 0, w0, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v[None].astype(vc.dtype), (i, 0, 0, w0, 0))
            kc_l = jax.lax.dynamic_index_in_dim(kc, i, 0, keepdims=False)
            vc_l = jax.lax.dynamic_index_in_dim(vc, i, 0, keepdims=False)
            k_pre = kc_l[:, :, :hi]          # static slice: grown prefix
            v_pre = vc_l[:, :, :hi]
            qg = q.reshape(B, nkv, G, W, D)
            s = jnp.einsum("bkgwd,bktd->bkgwt", qg, k_pre,
                           preferred_element_type=jnp.float32) * scale
            ok = jnp.where(glob, valid, valid & win)            # [B,W,hi]
            s = jnp.where(ok[:, None, None], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bkgwt,bktd->bkgwd", p.astype(v_pre.dtype),
                             v_pre, preferred_element_type=jnp.float32)
            ctx = ctx.reshape(B, nq, W, D).transpose(0, 2, 1, 3) \
                .reshape(B, W, nq * D).astype(compute_dtype)
            attn_out = apply_lora(ctx @ a["o_w"], ctx, "o_proj", i)
            attn_out = gemma3.rms_norm(attn_out, bp["post_attn_ln"], eps)
            x = x + attn_out
            h2 = gemma3.rms_norm(x, bp["pre_ffn_ln"], eps)
            act = gemma3.gelu_tanh(
                apply_lora(h2 @ bp["mlp"]["gate_w"], h2, "gate_proj", i)) \
                * apply_lora(h2 @ bp["mlp"]["up_w"], h2, "up_proj", i)
            down = apply_lora(act @ bp["mlp"]["down_w"], act,
                              "down_proj", i)
            down = gemma3.rms_norm(down, bp["post_ffn_ln"], eps)
            return (x + down, kc, vc), None

        (x, kc, vc), _ = jax.lax.scan(
            layer, (x, kc, vc),
            (wb, is_global, jnp.arange(L, dtype=jnp.int32)))
        x_last = x
    x_last = gemma3.rms_norm(
        x_last, params["final_norm"].astype(compute_dtype), eps)
    return x_last[:, -1], kc, vc


def gemma3_generate(config: Gemma3TextConfig, params, input_ids,
                    attention_mask, cfg: SampleConfig,
                    rng: Optional[jax.Array] = None,
                    compute_dtype=jnp.float32, lora=None,
                    prefill_chunk: Optional[int] = None,
                    lora_impl: str = "auto"):
    """Gemma-3 generation: GQA cache [L, B, Hkv, T, D], per-layer
    global/local RoPE + sliding-window validity over POSITION ids.
    lora: optional adapter pytree applied dynamically (see
    gpt2_generate). prefill_chunk: process prompts longer than this in
    W-sized windows against the growing cache (_gemma_chunked_prefill)
    instead of one whole-prompt forward — bounds prefill score memory
    for long prompts."""
    c = config
    B, P = input_ids.shape
    N = cfg.max_new_tokens
    if N <= 0:
        # honor max_new_tokens=0 (see gpt2_generate)
        return jnp.zeros((B, 0), jnp.int32)
    nq, nkv, D = c.num_attention_heads, c.num_key_value_heads, c.head_dim
    G = nq // nkv
    rng = jax.random.PRNGKey(0) if rng is None else rng
    params = jax.tree.map(jnp.asarray, params)
    lora_b = None if lora is None else lora.get("blocks")

    if prefill_chunk is not None and prefill_chunk < 1:
        raise ValueError(
            f"prefill_chunk must be >= 1, got {prefill_chunk}")
    chunked = prefill_chunk is not None and P > prefill_chunk
    if chunked:
        # pad the prompt on the LEFT to a window multiple (extra pads are
        # masked out; positions are mask-derived, so phases don't move)
        W = int(prefill_chunk)
        pad_n = (-P) % W
        if pad_n:
            input_ids = jnp.pad(input_ids, ((0, 0), (pad_n, 0)),
                                constant_values=cfg.pad_id)
            attention_mask = jnp.pad(attention_mask, ((0, 0), (pad_n, 0)))
            P += pad_n
    T = P + N

    cast = lambda t: (t.astype(compute_dtype)
                      if jnp.issubdtype(t.dtype, jnp.floating) else t)
    wb_pre = jax.tree.map(cast, params["blocks"])

    if chunked:
        x_last, kc, vc = _gemma_chunked_prefill(
            c, params, wb_pre, input_ids, attention_mask, lora_b, T,
            compute_dtype, W, apply_rope, lora_impl=lora_impl)
        logits0 = x_last @ params["embed"].astype(compute_dtype).T
        logits0 = _head_lora(logits0, x_last, lora_b, lora_impl)
    else:
        x, (pk, pv) = gemma3.hidden_states(
            c, params, input_ids, attention_mask, lora=lora,
            compute_dtype=compute_dtype, collect_kv=True,
            lora_impl=lora_impl)
        logits0 = x[:, -1] @ params["embed"].astype(compute_dtype).T
        logits0 = _head_lora(logits0, x[:, -1], lora_b, lora_impl)
        pad_kv = lambda t: jnp.pad(
            t, ((0, 0), (0, 0), (0, 0), (0, N), (0, 0)))
        kc, vc = pad_kv(pk), pad_kv(pv)

    n_real = attention_mask.sum(-1).astype(jnp.int32)
    col_pos = _col_positions(attention_mask, P, T)              # [B, T]
    is_global = jnp.asarray([c.is_global_layer(i)
                             for i in range(c.num_hidden_layers)])
    eps = c.rms_norm_eps
    scale = c.query_pre_attn_scalar ** -0.5
    wb = wb_pre
    normalizer = jnp.asarray(c.hidden_size ** 0.5, compute_dtype)

    def decode_step(carry, step_rng_t):
        tok, done, kc, vc = carry
        t, key = step_rng_t
        pos = n_real + t                                        # [B]
        x = params["embed"][tok].astype(compute_dtype) * normalizer
        cos_g, sin_g = rope_cos_sin(pos[:, None], D, c.rope_theta)
        cos_l, sin_l = rope_cos_sin(pos[:, None], D, c.rope_local_base_freq)
        valid = _col_valid(attention_mask, P, T, t)             # [B, T]
        # sliding-window validity uses POSITION ids (mask-derived), same
        # phases as the padded-batch training forward
        win_ok = (pos[:, None] - col_pos) < c.sliding_window    # [B, T]

        def apply_lora(y, x_in, name, i):
            entry = None if lora_b is None else lora_b.get(name)
            return maybe_lora(y, x_in, entry, i, impl=lora_impl)

        def layer(inner, inp):
            # caches ride the inner CARRY (one [1,B,Hkv,1,D] DUS per
            # layer); scanning them as xs/ys restacked the full cache
            # every decode step — see the GPT-2 decode note above
            x, kc, vc = inner
            bp, glob, i = inp
            a = bp["attn"]
            h = gemma3.rms_norm(x, bp["input_ln"], eps)
            q = apply_lora(h @ a["q_w"], h, "q_proj", i).reshape(B, nq, D)
            k = apply_lora(h @ a["k_w"], h, "k_proj", i).reshape(B, nkv, D)
            v = apply_lora(h @ a["v_w"], h, "v_proj", i).reshape(B, nkv, D)
            q = gemma3.rms_norm(q, a["q_norm"], eps)
            k = gemma3.rms_norm(k, a["k_norm"], eps)
            cos = jnp.where(glob, cos_g, cos_l)
            sin = jnp.where(glob, sin_g, sin_l)
            # apply_rope expects [..., S, D]; insert S=1
            q = apply_rope(q[:, :, None, :], cos, sin)[:, :, 0]
            k = apply_rope(k[:, :, None, :], cos, sin)[:, :, 0]
            kc = jax.lax.dynamic_update_slice(
                kc, k[None, :, :, None, :].astype(kc.dtype),
                (i, 0, 0, P + t, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v[None, :, :, None, :].astype(vc.dtype),
                (i, 0, 0, P + t, 0))
            kc_l = jax.lax.dynamic_index_in_dim(kc, i, 0, keepdims=False)
            vc_l = jax.lax.dynamic_index_in_dim(vc, i, 0, keepdims=False)
            qg = q.reshape(B, nkv, G, D)
            # storage-dtype operands + f32 accumulation (see GPT-2 note)
            s = jnp.einsum("bkgd,bktd->bkgt", qg, kc_l,
                           preferred_element_type=jnp.float32) * scale
            ok = jnp.where(glob, valid, valid & win_ok)         # [B, T]
            s = jnp.where(ok[:, None, None, :], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bkgt,bktd->bkgd", p.astype(vc_l.dtype), vc_l,
                             preferred_element_type=jnp.float32)
            ctx = ctx.reshape(B, nq * D).astype(compute_dtype)
            attn_out = apply_lora(ctx @ a["o_w"], ctx, "o_proj", i)
            attn_out = gemma3.rms_norm(attn_out, bp["post_attn_ln"], eps)
            x = x + attn_out
            h2 = gemma3.rms_norm(x, bp["pre_ffn_ln"], eps)
            act = gemma3.gelu_tanh(
                apply_lora(h2 @ bp["mlp"]["gate_w"], h2, "gate_proj", i)) \
                * apply_lora(h2 @ bp["mlp"]["up_w"], h2, "up_proj", i)
            down = apply_lora(act @ bp["mlp"]["down_w"], act,
                              "down_proj", i)
            down = gemma3.rms_norm(down, bp["post_ffn_ln"], eps)
            return (x + down, kc, vc), None

        (x, kc, vc), _ = jax.lax.scan(
            layer, (x, kc, vc),
            (wb, is_global,
             jnp.arange(c.num_hidden_layers, dtype=jnp.int32)))
        x = gemma3.rms_norm(x, params["final_norm"].astype(compute_dtype),
                            eps)
        logits = x @ params["embed"].astype(compute_dtype).T
        logits = _head_lora(logits, x, lora_b, lora_impl)
        nxt_raw = _sample(logits.astype(jnp.float32), key, cfg)
        nxt, done = _advance(nxt_raw, done, cfg)
        return (nxt, done, kc, vc), tok

    all_keys = jax.random.split(rng, N + 1)
    tok0_raw = _sample(logits0.astype(jnp.float32), all_keys[N], cfg)
    tok0, done0 = _advance(tok0_raw, jnp.zeros((B,), bool), cfg)
    # N-1 decode steps: step t consumes token t and samples token t+1, so
    # the final token comes out of the carry — no trailing all-layers
    # forward whose sample would be discarded
    steps = jnp.arange(N - 1, dtype=jnp.int32)
    keys = all_keys[:N - 1]
    (tok_last, _, _, _), toks = jax.lax.scan(
        decode_step, (tok0, done0, kc, vc), (steps, keys))
    toks = jnp.concatenate([toks, tok_last[None]], axis=0)
    return toks.T


# ------------------------------------------------- serving entry points -----
#
# The serving subsystem (serve/engine.py, DESIGN.md §16) decomposes the
# one-shot generate() programs above into two trace-stable pieces it can
# drive per request / per step:
#
#   *_prefill            one full-sequence forward for ONE admitted
#                        request (right-padded to the engine's static
#                        prompt length), returning the next-token logits
#                        at the last REAL position plus every layer's
#                        K/V for the host to scatter into pool blocks;
#   *_decode_step_paged  one token step for ALL slots against the shared
#                        block pool [NB, L, KV, bT, D]: write the fed
#                        token's K/V at (tbl[s, pos//bT], pos%bT), read
#                        each slot's pages through its block table
#                        (ops/decode_attention.paged_attention — the
#                        Pallas paged kernel is the TPU fast path), and
#                        return the next-token logits.
#
# Serve sequences start at position 0 with no padding inside (the engine
# right-pads only the prompt TAIL), so validity is simply col <= pos —
# none of the left-padded mask algebra above applies. The layer math is
# kept line-for-line with decode_step; the buffer structure differs
# (pool scatter/gather instead of contiguous DUS), and each copy is
# pinned by the tests/test_serve.py paged-vs-contiguous greedy oracle.


def gpt2_prefill(config: GPT2Config, params, input_ids, attention_mask,
                 compute_dtype=jnp.float32, lora=None,
                 lora_impl: str = "auto", shardings=None):
    """Prefill for serving: [B, P] right-padded prompts -> (next-token
    logits [B, V] f32 at each row's last real position, (k, v) per-layer
    caches [L, B, H, P, D]). shardings: a serve/sharding.ServeSharding
    under the (dp, tp) serve mesh — the prefill matmuls TP-partition by
    propagation from the column/row-sharded weight placement; the only
    explicit pin is the collected caches' KV-head axis, so the engine's
    prompt-page scatter receives pool-aligned K/V."""
    params = jax.tree.map(jnp.asarray, params)
    x, (pk, pv) = gpt2.hidden_states(
        config, params, input_ids, attention_mask, lora=lora,
        compute_dtype=compute_dtype, collect_kv=True,
        lora_impl=lora_impl)
    if shardings is not None:
        pk = shardings.prefill_cache(pk)
        pv = shardings.prefill_cache(pv)
    n_real = attention_mask.sum(-1).astype(jnp.int32)
    last = x[jnp.arange(x.shape[0]), n_real - 1]          # [B, E]
    logits = last @ params["wte"].astype(compute_dtype).T
    lora_b = None if lora is None else lora.get("blocks")
    logits = _head_lora(logits, last, lora_b, lora_impl)
    return logits.astype(jnp.float32), (pk, pv)


def gemma3_prefill(config: Gemma3TextConfig, params, input_ids,
                   attention_mask, compute_dtype=jnp.float32, lora=None,
                   lora_impl: str = "auto", shardings=None):
    """Gemma-3 serving prefill (see gpt2_prefill)."""
    params = jax.tree.map(jnp.asarray, params)
    x, (pk, pv) = gemma3.hidden_states(
        config, params, input_ids, attention_mask, lora=lora,
        compute_dtype=compute_dtype, collect_kv=True,
        lora_impl=lora_impl)
    if shardings is not None:
        pk = shardings.prefill_cache(pk)
        pv = shardings.prefill_cache(pv)
    n_real = attention_mask.sum(-1).astype(jnp.int32)
    last = x[jnp.arange(x.shape[0]), n_real - 1]
    logits = last @ params["embed"].astype(compute_dtype).T
    lora_b = None if lora is None else lora.get("blocks")
    logits = _head_lora(logits, last, lora_b, lora_impl)
    return logits.astype(jnp.float32), (pk, pv)


def gpt2_prefill_chunk(config: GPT2Config, params, pool_k, pool_v, ids,
                       start, n_tok, tbl, lora=None,
                       compute_dtype=jnp.float32,
                       lora_impl: str = "auto", shardings=None):
    """One fixed-width prefill CHUNK against the block pool (round 21):
    W prompt tokens starting at absolute position `start`, attending
    the pages earlier chunks (or the prefix cache) already wrote.

    ids [1, W] the chunk's tokens (pad-padded past n_tok); start 0-d
    i32 (block_T-aligned chunk origin); n_tok 0-d i32 real tokens in
    the chunk (1..W); tbl [1, M] the request's block table (garbage
    regions -> trash block 0). Returns (logits [1, V] f32 at the
    chunk's last real row, pool_k, pool_v) with the chunk's K/V
    scattered in at (tbl[0, (start+w)//bT], (start+w)%bT) — padded
    rows land in the trash page.

    W is one of the engine's STATIC chunk buckets, and start/n_tok ride
    as 0-d device scalars, so the whole bucket set costs one trace per
    width — never one per prompt length. Row w's causal span is the
    union of the already-written prefix (pool columns < start) and the
    chunk's own rows j <= w, so attention splits into a read-only page
    gather plus a dense within-chunk part under ONE joint softmax —
    token-identical to one-shot prefill. The pools are NOT layer-scan
    carries: the scan threads only the hidden state, stacks each
    layer's chunk K/V as scan outputs, and a single post-scan scatter
    lands all L layers' rows at once. That keeps the chunk program's
    cost proportional to the chunk width, not the pool size (pool-
    sized carries made every dispatch pay pool-copy traffic on
    backends without donation). XLA partitions both attention parts
    under `shardings` like any dense op (a chunk-shaped Pallas kernel
    is future work, gated behind the same benched decision)."""
    from mobilefinetuner_tpu.ops.decode_attention import NEG_INF
    from mobilefinetuner_tpu.serve.paged_kv import TRASH_BLOCK
    W = ids.shape[1]
    M = tbl.shape[1]
    NB, L, H, bT, D = pool_k.shape
    E = config.n_embd
    eps = config.layer_norm_epsilon
    params = jax.tree.map(jnp.asarray, params)
    lora_b = None if lora is None else lora.get("blocks")
    cast = lambda t: (t.astype(compute_dtype)
                      if jnp.issubdtype(t.dtype, jnp.floating) else t)
    wb = jax.tree.map(cast, params["blocks"])
    shd = shardings

    rows = jnp.arange(W, dtype=jnp.int32)
    pos = start + rows                                        # [W]
    real = rows < n_tok
    # padded rows clip their position lookup (their K/V goes to trash
    # and their logits row is never read)
    x = params["wte"][ids[0]].astype(compute_dtype) \
        + params["wpe"][jnp.minimum(
            pos, config.n_positions - 1)].astype(compute_dtype)
    if shd is not None:
        x = shd.slots(x)
    cols = jnp.arange(M * bT, dtype=jnp.int32)
    # every chunk row shares the prefix span (pool columns < start);
    # columns >= start are this chunk's own rows, attended densely
    pre_ok = cols < start                                     # [M*bT]
    causal = rows[:, None] >= rows[None, :]                   # [W, W]
    blk = jnp.where(real, tbl[0, pos // bT],
                    jnp.int32(TRASH_BLOCK))                   # [W]
    off = pos % bT
    scale = D ** -0.5

    def apply_lora(y, x_in, name, i):
        entry = None if lora_b is None else lora_b.get(name)
        return maybe_lora(y, x_in, entry, i, impl=lora_impl)

    def layer(x, inp):
        bp, i = inp
        h = gpt2.layer_norm(x, bp["ln_1"]["g"], bp["ln_1"]["b"], eps)
        qkv = h @ bp["attn"]["qkv_w"] + bp["attn"]["qkv_b"]
        qkv = apply_lora(qkv, h, "attn_qkv", i)
        if lora_b is not None:
            from mobilefinetuner_tpu.lora.lora import GPT2_SPLIT_QKV_SLOTS
            for name, slot in GPT2_SPLIT_QKV_SLOTS.items():
                if name in lora_b:
                    sl = (Ellipsis, slice(slot * E, (slot + 1) * E))
                    qkv = qkv.at[sl].set(apply_lora(qkv[sl], h, name, i))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        hd = lambda z: z.reshape(W, H, D)
        q, k, v = hd(q), hd(k), hd(v)
        if shd is not None:
            q, k, v = shd.kv_rows(q), shd.kv_rows(k), shd.kv_rows(v)
        # pool-dtype roundtrip: within-chunk attention must read the
        # same values the pages will hold, or chunked-vs-one-shot
        # token parity drifts at low pool precision
        kq = k.astype(pool_k.dtype)
        vq = v.astype(pool_v.dtype)
        # joint softmax over [prefix pages | chunk rows]: the pools
        # are closed over READ-ONLY here (gather, never scatter), so
        # they are not scan carries — dtype discipline mirrors
        # ops.decode_attention.paged_attention
        kc = pool_k[tbl[0], i]                    # [M, H, bT, D]
        vc = pool_v[tbl[0], i]
        s1 = jnp.einsum("whd,mhtd->whmt", q, kc,
                        preferred_element_type=jnp.float32) * scale
        s1 = jnp.where(pre_ok[None, None, :],
                       s1.reshape(W, H, M * bT), NEG_INF)
        s2 = jnp.einsum("whd,jhd->whj", q, kq,
                        preferred_element_type=jnp.float32) * scale
        s2 = jnp.where(causal[:, None, :], s2, NEG_INF)
        p = jax.nn.softmax(jnp.concatenate([s1, s2], -1), axis=-1)
        ctx = jnp.einsum("whmt,mhtd->whd",
                         p[..., :M * bT].reshape(W, H, M, bT)
                         .astype(vc.dtype), vc,
                         preferred_element_type=jnp.float32) \
            + jnp.einsum("whj,jhd->whd",
                         p[..., M * bT:].astype(vq.dtype), vq,
                         preferred_element_type=jnp.float32)
        if shd is not None:
            ctx = shd.heads4(ctx[:, :, None, :]).reshape(W, H, D)
        ctx = ctx.reshape(W, E).astype(compute_dtype)
        proj = ctx @ bp["attn"]["proj_w"] + bp["attn"]["proj_b"]
        proj = apply_lora(proj, ctx, "attn_proj", i)
        x = x + proj
        h2 = gpt2.layer_norm(x, bp["ln_2"]["g"], bp["ln_2"]["b"], eps)
        fc = h2 @ bp["mlp"]["fc_w"] + bp["mlp"]["fc_b"]
        if shd is not None:
            fc = shd.hidden(fc)
        fc = gpt2.gelu_new(apply_lora(fc, h2, "mlp_fc_in", i))
        out = fc @ bp["mlp"]["proj_w"] + bp["mlp"]["proj_b"]
        out = apply_lora(out, fc, "mlp_fc_out", i)
        return x + out, (kq, vq)

    x, (ks, vs) = jax.lax.scan(
        layer, x, (wb, jnp.arange(L, dtype=jnp.int32)))
    # one scatter for all L layers' chunk rows (padded rows -> trash):
    # [L, W, H, D] -> assignment shape [W, L, H, D]
    pool_k = pool_k.at[blk, :, :, off, :].set(ks.transpose(1, 0, 2, 3))
    pool_v = pool_v.at[blk, :, :, off, :].set(vs.transpose(1, 0, 2, 3))
    x = gpt2.layer_norm(x, params["ln_f"]["g"].astype(compute_dtype),
                        params["ln_f"]["b"].astype(compute_dtype), eps)
    last = jax.lax.dynamic_index_in_dim(x, n_tok - 1, 0,
                                        keepdims=True)        # [1, E]
    logits = last @ params["wte"].astype(compute_dtype).T
    logits = _head_lora(logits, last, lora_b, lora_impl)
    return logits.astype(jnp.float32), pool_k, pool_v


def gemma3_prefill_chunk(config: Gemma3TextConfig, params, pool_k,
                         pool_v, ids, start, n_tok, tbl, lora=None,
                         compute_dtype=jnp.float32,
                         lora_impl: str = "auto", shardings=None):
    """Gemma-3 prefill chunk (see gpt2_prefill_chunk): GQA pool, per-
    layer global/local RoPE on the chunk's absolute positions, and the
    sliding-window validity composed per layer — the same per-layer
    `where(glob, causal, causal & window)` the paged decode step
    applies, here split across the read-only prefix gather and the
    dense within-chunk part of the joint softmax. As in the GPT-2
    chunk, the pools ride closed-over (reads only) and one post-scan
    scatter lands every layer's rows."""
    from mobilefinetuner_tpu.ops.decode_attention import NEG_INF
    from mobilefinetuner_tpu.serve.paged_kv import TRASH_BLOCK
    c = config
    W = ids.shape[1]
    M = tbl.shape[1]
    NB, L, KV, bT, D = pool_k.shape
    nq = c.num_attention_heads
    G = nq // KV
    eps = c.rms_norm_eps
    scale = c.query_pre_attn_scalar ** -0.5
    params = jax.tree.map(jnp.asarray, params)
    lora_b = None if lora is None else lora.get("blocks")
    cast = lambda t: (t.astype(compute_dtype)
                      if jnp.issubdtype(t.dtype, jnp.floating) else t)
    wb = jax.tree.map(cast, params["blocks"])
    is_global = jnp.asarray([c.is_global_layer(i) for i in range(L)])
    normalizer = jnp.asarray(c.hidden_size ** 0.5, compute_dtype)
    shd = shardings

    rows = jnp.arange(W, dtype=jnp.int32)
    pos = start + rows                                        # [W]
    real = rows < n_tok
    x = params["embed"][ids[0]].astype(compute_dtype) * normalizer
    if shd is not None:
        x = shd.slots(x)
    cos_g, sin_g = rope_cos_sin(pos[:, None], D, c.rope_theta)
    cos_l, sin_l = rope_cos_sin(pos[:, None], D, c.rope_local_base_freq)
    cols = jnp.arange(M * bT, dtype=jnp.int32)
    pre_valid = jnp.broadcast_to(cols[None, :] < start,
                                 (W, M * bT))                 # prefix
    win_ok = (pos[:, None] - cols[None, :]) < c.sliding_window
    causal = rows[:, None] >= rows[None, :]                   # [W, W]
    win_in = (rows[:, None] - rows[None, :]) < c.sliding_window
    blk = jnp.where(real, tbl[0, pos // bT],
                    jnp.int32(TRASH_BLOCK))
    off = pos % bT

    def apply_lora(y, x_in, name, i):
        entry = None if lora_b is None else lora_b.get(name)
        return maybe_lora(y, x_in, entry, i, impl=lora_impl)

    def layer(x, inp):
        bp, glob, i = inp
        a = bp["attn"]
        h = gemma3.rms_norm(x, bp["input_ln"], eps)
        q = apply_lora(h @ a["q_w"], h, "q_proj", i).reshape(W, nq, D)
        k = apply_lora(h @ a["k_w"], h, "k_proj", i).reshape(W, KV, D)
        v = apply_lora(h @ a["v_w"], h, "v_proj", i).reshape(W, KV, D)
        q = gemma3.rms_norm(q, a["q_norm"], eps)
        k = gemma3.rms_norm(k, a["k_norm"], eps)
        cos = jnp.where(glob, cos_g, cos_l)
        sin = jnp.where(glob, sin_g, sin_l)
        q = apply_rope(q[:, :, None, :], cos, sin)[:, :, 0]
        k = apply_rope(k[:, :, None, :], cos, sin)[:, :, 0]
        if shd is not None:
            k, v = shd.kv_rows(k), shd.kv_rows(v)
        kq = k.astype(pool_k.dtype)               # pool-dtype roundtrip
        vq = v.astype(pool_v.dtype)
        ok1 = jnp.where(glob, pre_valid, pre_valid & win_ok)
        ok2 = jnp.where(glob, causal, causal & win_in)        # [W, W]
        q4 = q.reshape(W, KV, G, D)
        if shd is not None:
            q4 = shd.heads4(q4)
        kc = pool_k[tbl[0], i]                    # [M, KV, bT, D]
        vc = pool_v[tbl[0], i]
        s1 = jnp.einsum("wkgd,mktd->wkgmt", q4, kc,
                        preferred_element_type=jnp.float32) * scale
        s1 = jnp.where(ok1[:, None, None, :],
                       s1.reshape(W, KV, G, M * bT), NEG_INF)
        s2 = jnp.einsum("wkgd,jkd->wkgj", q4, kq,
                        preferred_element_type=jnp.float32) * scale
        s2 = jnp.where(ok2[:, None, None, :], s2, NEG_INF)
        p = jax.nn.softmax(jnp.concatenate([s1, s2], -1), axis=-1)
        ctx = jnp.einsum("wkgmt,mktd->wkgd",
                         p[..., :M * bT].reshape(W, KV, G, M, bT)
                         .astype(vc.dtype), vc,
                         preferred_element_type=jnp.float32) \
            + jnp.einsum("wkgj,jkd->wkgd",
                         p[..., M * bT:].astype(vq.dtype), vq,
                         preferred_element_type=jnp.float32)
        if shd is not None:
            ctx = shd.heads4(ctx)
        ctx = ctx.reshape(W, nq * D).astype(compute_dtype)
        attn_out = apply_lora(ctx @ a["o_w"], ctx, "o_proj", i)
        attn_out = gemma3.rms_norm(attn_out, bp["post_attn_ln"], eps)
        x = x + attn_out
        h2 = gemma3.rms_norm(x, bp["pre_ffn_ln"], eps)
        act = gemma3.gelu_tanh(
            apply_lora(h2 @ bp["mlp"]["gate_w"], h2, "gate_proj", i)) \
            * apply_lora(h2 @ bp["mlp"]["up_w"], h2, "up_proj", i)
        if shd is not None:
            act = shd.hidden(act)
        down = apply_lora(act @ bp["mlp"]["down_w"], act, "down_proj", i)
        down = gemma3.rms_norm(down, bp["post_ffn_ln"], eps)
        return x + down, (kq, vq)

    x, (ks, vs) = jax.lax.scan(
        layer, x, (wb, is_global, jnp.arange(L, dtype=jnp.int32)))
    # one scatter for all L layers' chunk rows (padded rows -> trash)
    pool_k = pool_k.at[blk, :, :, off, :].set(ks.transpose(1, 0, 2, 3))
    pool_v = pool_v.at[blk, :, :, off, :].set(vs.transpose(1, 0, 2, 3))
    x = gemma3.rms_norm(x, params["final_norm"].astype(compute_dtype),
                        eps)
    last = jax.lax.dynamic_index_in_dim(x, n_tok - 1, 0,
                                        keepdims=True)        # [1, E]
    logits = last @ params["embed"].astype(compute_dtype).T
    logits = _head_lora(logits, last, lora_b, lora_impl)
    return logits.astype(jnp.float32), pool_k, pool_v


def gpt2_decode_step_paged(config: GPT2Config, params, pool_k, pool_v,
                           tok, pos, tbl, lora=None,
                           compute_dtype=jnp.float32,
                           attn_impl: str = "auto",
                           lora_impl: str = "auto", shardings=None):
    """One continuous-batching decode step over a block-paged KV pool.

    pool_k/pool_v [NB, L, H, bT, D]; tok [S] the token each slot feeds;
    pos [S] its cache position (= tokens already cached); tbl [S, M]
    per-slot block tables (idle slots -> trash block 0). Returns
    (logits [S, V] f32, pool_k, pool_v) with the fed tokens' K/V
    scattered in at (tbl[s, pos//bT], pos%bT).

    attn_impl: "xla" = gather-based paged_attention (every backend),
    "pallas" = the scalar-prefetch paged kernel, "auto" = pallas on TPU
    when eligible. Both are parity-pinned to each other and to the
    contiguous generate() oracle.

    shardings: a serve/sharding.ServeSharding — the layer math is
    unchanged; the head/hidden axes get with_sharding_constraint pins
    (GSPMD inserts the collectives; check_compiled_contracts pins the
    census), the Pallas gate charges per-shard head counts, and the
    kernel path routes through sharded_paged_attend's shard_map."""
    from mobilefinetuner_tpu.ops.decode_attention import (
        paged_attention, paged_decode_attention, paged_eligible,
        sharded_paged_attend)
    S, M = tbl.shape
    NB, L, H, bT, D = pool_k.shape
    E = config.n_embd
    eps = config.layer_norm_epsilon
    params = jax.tree.map(jnp.asarray, params)
    lora_b = None if lora is None else lora.get("blocks")
    cast = lambda t: (t.astype(compute_dtype)
                      if jnp.issubdtype(t.dtype, jnp.floating) else t)
    wb = jax.tree.map(cast, params["blocks"])
    shd = shardings
    use_pallas = attn_impl == "pallas" or (
        attn_impl == "auto" and jax.default_backend() == "tpu"
        and paged_eligible(H, 1, bT, D, pool_k.dtype.itemsize,
                           tp=1 if shd is None else shd.tp))
    if shd is not None:
        attend = sharded_paged_attend(shd) if use_pallas \
            else paged_attention
    else:
        attend = paged_decode_attention if use_pallas else paged_attention

    x = params["wte"][tok].astype(compute_dtype) \
        + params["wpe"][pos].astype(compute_dtype)            # [S, E]
    if shd is not None:
        x = shd.slots(x)
    cols = jnp.arange(M * bT, dtype=jnp.int32)
    ok = cols[None, :] <= pos[:, None]                        # [S, M*bT]
    blk = tbl[jnp.arange(S), pos // bT]                       # [S]
    off = pos % bT

    def apply_lora(y, x_in, name, i):
        entry = None if lora_b is None else lora_b.get(name)
        return maybe_lora(y, x_in, entry, i, impl=lora_impl)

    def layer(inner, inp):
        x, pk, pv = inner
        bp, i = inp
        h = gpt2.layer_norm(x, bp["ln_1"]["g"], bp["ln_1"]["b"], eps)
        qkv = h @ bp["attn"]["qkv_w"] + bp["attn"]["qkv_b"]
        qkv = apply_lora(qkv, h, "attn_qkv", i)
        if lora_b is not None:
            from mobilefinetuner_tpu.lora.lora import GPT2_SPLIT_QKV_SLOTS
            for name, slot in GPT2_SPLIT_QKV_SLOTS.items():
                if name in lora_b:
                    sl = (Ellipsis, slice(slot * E, (slot + 1) * E))
                    qkv = qkv.at[sl].set(apply_lora(qkv[sl], h, name, i))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        hd = lambda z: z.reshape(S, H, D)
        q, k, v = hd(q), hd(k), hd(v)
        if shd is not None:
            q, k, v = shd.kv_rows(q), shd.kv_rows(k), shd.kv_rows(v)
        # scatter the fed token's K/V into its slot's current page; idle
        # slots land in the reserved trash block (never attended)
        pk = pk.at[blk, i, :, off, :].set(k.astype(pk.dtype))
        pv = pv.at[blk, i, :, off, :].set(v.astype(pv.dtype))
        ctx = attend(q[:, :, None, :], pk, pv, tbl, i, ok, D ** -0.5)
        if shd is not None:
            ctx = shd.heads4(ctx)
        ctx = ctx.reshape(S, E).astype(compute_dtype)
        proj = ctx @ bp["attn"]["proj_w"] + bp["attn"]["proj_b"]
        proj = apply_lora(proj, ctx, "attn_proj", i)
        x = x + proj
        h2 = gpt2.layer_norm(x, bp["ln_2"]["g"], bp["ln_2"]["b"], eps)
        fc = h2 @ bp["mlp"]["fc_w"] + bp["mlp"]["fc_b"]
        if shd is not None:
            fc = shd.hidden(fc)
        fc = gpt2.gelu_new(apply_lora(fc, h2, "mlp_fc_in", i))
        out = fc @ bp["mlp"]["proj_w"] + bp["mlp"]["proj_b"]
        out = apply_lora(out, fc, "mlp_fc_out", i)
        return (x + out, pk, pv), None

    (x, pool_k, pool_v), _ = jax.lax.scan(
        layer, (x, pool_k, pool_v), (wb, jnp.arange(L, dtype=jnp.int32)))
    x = gpt2.layer_norm(x, params["ln_f"]["g"].astype(compute_dtype),
                        params["ln_f"]["b"].astype(compute_dtype), eps)
    logits = x @ params["wte"].astype(compute_dtype).T
    logits = _head_lora(logits, x, lora_b, lora_impl)
    return logits.astype(jnp.float32), pool_k, pool_v


def gemma3_decode_step_paged(config: Gemma3TextConfig, params, pool_k,
                             pool_v, tok, pos, tbl, lora=None,
                             compute_dtype=jnp.float32,
                             attn_impl: str = "auto",
                             lora_impl: str = "auto", shardings=None):
    """Gemma-3 paged decode step (see gpt2_decode_step_paged): GQA pool
    [NB, L, Hkv, bT, D], per-layer global/local RoPE, sliding-window
    validity over absolute positions (serve sequences are unpadded, so
    the column index IS the position).

    Under `shardings` the GQA head placement follows shard_heads: the
    pool's KV axis shards when Hkv % tp == 0, otherwise the query-group
    axis does (pools replicated) — either way the gate charges per-shard
    head counts and constraints pin the 4D [S, KV, G, D] layout."""
    from mobilefinetuner_tpu.ops.decode_attention import (
        paged_attention, paged_decode_attention, paged_eligible,
        sharded_paged_attend)
    c = config
    S, M = tbl.shape
    NB, L, KV, bT, D = pool_k.shape
    nq = c.num_attention_heads
    G = nq // KV
    eps = c.rms_norm_eps
    scale = c.query_pre_attn_scalar ** -0.5
    params = jax.tree.map(jnp.asarray, params)
    lora_b = None if lora is None else lora.get("blocks")
    cast = lambda t: (t.astype(compute_dtype)
                      if jnp.issubdtype(t.dtype, jnp.floating) else t)
    wb = jax.tree.map(cast, params["blocks"])
    is_global = jnp.asarray([c.is_global_layer(i) for i in range(L)])
    normalizer = jnp.asarray(c.hidden_size ** 0.5, compute_dtype)
    shd = shardings
    use_pallas = attn_impl == "pallas" or (
        attn_impl == "auto" and jax.default_backend() == "tpu"
        and paged_eligible(KV, G, bT, D, pool_k.dtype.itemsize,
                           tp=1 if shd is None else shd.tp))
    if shd is not None:
        attend = sharded_paged_attend(shd) if use_pallas \
            else paged_attention
    else:
        attend = paged_decode_attention if use_pallas else paged_attention

    x = params["embed"][tok].astype(compute_dtype) * normalizer
    if shd is not None:
        x = shd.slots(x)
    cos_g, sin_g = rope_cos_sin(pos[:, None], D, c.rope_theta)
    cos_l, sin_l = rope_cos_sin(pos[:, None], D, c.rope_local_base_freq)
    cols = jnp.arange(M * bT, dtype=jnp.int32)
    valid = cols[None, :] <= pos[:, None]                     # [S, M*bT]
    win_ok = (pos[:, None] - cols[None, :]) < c.sliding_window
    blk = tbl[jnp.arange(S), pos // bT]
    off = pos % bT

    def apply_lora(y, x_in, name, i):
        entry = None if lora_b is None else lora_b.get(name)
        return maybe_lora(y, x_in, entry, i, impl=lora_impl)

    def layer(inner, inp):
        x, pk, pv = inner
        bp, glob, i = inp
        a = bp["attn"]
        h = gemma3.rms_norm(x, bp["input_ln"], eps)
        q = apply_lora(h @ a["q_w"], h, "q_proj", i).reshape(S, nq, D)
        k = apply_lora(h @ a["k_w"], h, "k_proj", i).reshape(S, KV, D)
        v = apply_lora(h @ a["v_w"], h, "v_proj", i).reshape(S, KV, D)
        q = gemma3.rms_norm(q, a["q_norm"], eps)
        k = gemma3.rms_norm(k, a["k_norm"], eps)
        cos = jnp.where(glob, cos_g, cos_l)
        sin = jnp.where(glob, sin_g, sin_l)
        q = apply_rope(q[:, :, None, :], cos, sin)[:, :, 0]
        k = apply_rope(k[:, :, None, :], cos, sin)[:, :, 0]
        if shd is not None:
            k, v = shd.kv_rows(k), shd.kv_rows(v)
        pk = pk.at[blk, i, :, off, :].set(k.astype(pk.dtype))
        pv = pv.at[blk, i, :, off, :].set(v.astype(pv.dtype))
        ok = jnp.where(glob, valid, valid & win_ok)           # [S, M*bT]
        q4 = q.reshape(S, KV, G, D)
        if shd is not None:
            q4 = shd.heads4(q4)
        ctx = attend(q4, pk, pv, tbl, i, ok, scale)
        if shd is not None:
            ctx = shd.heads4(ctx)
        ctx = ctx.reshape(S, nq * D).astype(compute_dtype)
        attn_out = apply_lora(ctx @ a["o_w"], ctx, "o_proj", i)
        attn_out = gemma3.rms_norm(attn_out, bp["post_attn_ln"], eps)
        x = x + attn_out
        h2 = gemma3.rms_norm(x, bp["pre_ffn_ln"], eps)
        act = gemma3.gelu_tanh(
            apply_lora(h2 @ bp["mlp"]["gate_w"], h2, "gate_proj", i)) \
            * apply_lora(h2 @ bp["mlp"]["up_w"], h2, "up_proj", i)
        if shd is not None:
            act = shd.hidden(act)
        down = apply_lora(act @ bp["mlp"]["down_w"], act, "down_proj", i)
        down = gemma3.rms_norm(down, bp["post_ffn_ln"], eps)
        return (x + down, pk, pv), None

    (x, pool_k, pool_v), _ = jax.lax.scan(
        layer, (x, pool_k, pool_v),
        (wb, is_global, jnp.arange(L, dtype=jnp.int32)))
    x = gemma3.rms_norm(x, params["final_norm"].astype(compute_dtype),
                        eps)
    logits = x @ params["embed"].astype(compute_dtype).T
    logits = _head_lora(logits, x, lora_b, lora_impl)
    return logits.astype(jnp.float32), pool_k, pool_v


def left_pad(seqs, pad_id: int):
    """[[ids...], ...] -> (input_ids [B, P], attention_mask [B, P]) with
    LEFT padding (generation convention; cache writes share one column)."""
    import numpy as np
    P = max(len(s) for s in seqs)
    B = len(seqs)
    ids = np.full((B, P), pad_id, np.int32)
    mask = np.zeros((B, P), np.int32)
    for i, s in enumerate(seqs):
        if len(s):
            ids[i, P - len(s):] = s
            mask[i, P - len(s):] = 1
    return ids, mask
