"""Gemma-3 text decoder, pure-functional JAX.

Re-design of the reference's GemmaModel graph
(reference: operators/finetune_ops/graph/gemma_model.{h,cpp}), HF-Gemma3
aligned (SURVEY.md §2.5):
  - embeddings scaled by sqrt(hidden_size) (gemma_model.cpp:222-248);
  - GQA (num_attention_heads q-heads over num_key_value_heads kv-heads) —
    expressed as a broadcast einsum, not materialized repeat_kv_heads;
  - per-head q/k RMSNorm before RoPE;
  - dual RoPE theta: rope_theta (global layers) vs rope_local_base_freq
    (sliding-window layers) selected per layer_types[i]
    (gemma_model.cpp:579-625);
  - sliding-window mask (default 512) on local layers (gemma_model.h:26);
  - sandwich norms: input_ln -> attn -> post_attn_ln -> residual;
    pre_ffn_ln -> MLP(gelu_tanh(gate)*up -> down) -> post_ffn_ln ->
    residual (gemma_model.cpp:579-680);
  - RMSNorm with Gemma (1 + weight) semantics, fp32 accumulation
    (core/ops.cpp:1489);
  - query scaling by query_pre_attn_scalar^-0.5 (gemma_model.h:33);
  - lm_head tied to the embedding table (HF Gemma-3 text checkpoints).

Layers are stacked [L, ...] and run under lax.scan; per-layer global/local
behavior is selected with jnp.where over precomputed global+local RoPE
tables and masks (static shapes, no data-dependent control flow).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from mobilefinetuner_tpu.core.config import Gemma3TextConfig
from mobilefinetuner_tpu.ops.attention import attention, causal_mask
from mobilefinetuner_tpu.ops.rope import apply_rope, rope_cos_sin


def rms_norm(x, w, eps, dtype=None):
    """Gemma RMSNorm: x/rms(x) * (1 + w), fp32 math
    (reference: core/ops.cpp:1489, scale at ops.cpp:1515)."""
    dtype = dtype or x.dtype
    xf = x.astype(jnp.float32)
    var = (xf * xf).mean(-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps) * (1.0 + w.astype(jnp.float32))
    return out.astype(dtype)


def gelu_tanh(x):
    return jax.nn.gelu(x, approximate=True)


def init_params(config: Gemma3TextConfig, key: jax.Array,
                dtype=jnp.float32) -> Dict[str, Any]:
    c = config
    L, H, D = c.num_hidden_layers, c.hidden_size, c.head_dim
    nq, nkv, I = c.num_attention_heads, c.num_key_value_heads, \
        c.intermediate_size
    ks = jax.random.split(key, 8)
    std = 0.02

    def n(k, shape):
        return (jax.random.normal(k, shape) * std).astype(dtype)

    z = lambda *s: jnp.zeros(s, dtype)
    return {
        "embed": n(ks[0], (c.vocab_size, H)),
        "blocks": {
            "input_ln": z(L, H),
            "attn": {
                "q_w": n(ks[1], (L, H, nq * D)),
                "k_w": n(ks[2], (L, H, nkv * D)),
                "v_w": n(ks[3], (L, H, nkv * D)),
                "o_w": n(ks[4], (L, nq * D, H)),
                "q_norm": z(L, D),
                "k_norm": z(L, D),
            },
            "post_attn_ln": z(L, H),
            "pre_ffn_ln": z(L, H),
            "mlp": {
                "gate_w": n(ks[5], (L, H, I)),
                "up_w": n(ks[6], (L, H, I)),
                "down_w": n(ks[7], (L, I, H)),
            },
            "post_ffn_ln": z(L, H),
        },
        "final_norm": z(H),
    }


from mobilefinetuner_tpu.models.lora_apply import maybe_lora


def _block(c: Gemma3TextConfig, bp, x, padding_mask, masks, ropes,
           is_global, lora_b, i, lora_dropout=0.0, dropout_rng=None,
           cp_mesh=None, cp_axis="fsdp", collect_kv: bool = False,
           lora_impl: str = "auto"):
    """One Gemma-3 block; bp leaves are THIS layer's weights (sliced out of
    the [L, ...] stacks by the scan body); i (traced scalar) indexes the
    still-stacked LoRA leaves, RoPE tables, and masks. collect_kv: also
    return this layer's post-norm post-RoPE (k, v) head tensors
    [B, Hkv, S, D] (KV-cache prefill, models/generate.py)."""
    eps = c.rms_norm_eps
    B, S, H = x.shape
    nq, nkv, D = (c.num_attention_heads, c.num_key_value_heads, c.head_dim)
    rng = None if dropout_rng is None else jax.random.fold_in(dropout_rng, i)

    def lora(y, x_in, name, site):
        entry = None if lora_b is None else lora_b.get(name)
        return maybe_lora(y, x_in, entry, i, lora_dropout,
                          None if rng is None
                          else jax.random.fold_in(rng, site),
                          impl=lora_impl)

    a = bp["attn"]

    # --- attention, sandwich-normed (named scopes label the phase in
    # profiler traces and compiled-HLO op metadata, DESIGN.md §13)
    with jax.named_scope("attention"):
        h = rms_norm(x, bp["input_ln"], eps)
        q = lora(h @ a["q_w"], h, "q_proj", 0)
        k = lora(h @ a["k_w"], h, "k_proj", 1)
        v = lora(h @ a["v_w"], h, "v_proj", 2)
        q = q.reshape(B, S, nq, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, nkv, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, nkv, D).transpose(0, 2, 1, 3)
        q = rms_norm(q, a["q_norm"], eps)
        k = rms_norm(k, a["k_norm"], eps)
        cos = jnp.where(is_global[i], ropes["cos_g"], ropes["cos_l"])
        sin = jnp.where(is_global[i], ropes["sin_g"], ropes["sin_l"])
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kv_out = (k, v) if collect_kv else None
        scale = c.query_pre_attn_scalar ** -0.5
        impl = c.attention_impl
        if impl == "auto":
            # resolved here (not inside attention()) because the flash
            # path needs the flag-based branch below instead of masks
            from mobilefinetuner_tpu.ops.attention import resolve_impl
            impl = resolve_impl(S, D)
        if cp_mesh is not None:
            # sequence-parallel: ring attention over the mesh axis; the
            # global/local choice is a traced bool under the layer scan,
            # so branch with lax.cond like the flash path
            from mobilefinetuner_tpu.parallel.ring_attention import \
                ring_attention
            ctx = jax.lax.cond(
                is_global[i],
                lambda ops: ring_attention(*ops, cp_mesh, axis=cp_axis,
                                           scale=scale, is_causal=True,
                                           padding_mask=padding_mask),
                lambda ops: ring_attention(*ops, cp_mesh, axis=cp_axis,
                                           scale=scale, is_causal=True,
                                           sliding_window=c.sliding_window,
                                           padding_mask=padding_mask),
                (q, k, v))
        elif impl == "flash":
            # The Pallas kernel takes causal/sliding-window as STATIC
            # config, not a mask matrix; under the layer scan the
            # global/local choice is a traced bool, so branch with
            # lax.cond (each branch compiles its own kernel variant).
            ctx = jax.lax.cond(
                is_global[i],
                lambda ops: attention(*ops, impl="flash", scale=scale,
                                      is_causal=True,
                                      padding_mask=padding_mask),
                lambda ops: attention(*ops, impl="flash", scale=scale,
                                      is_causal=True,
                                      sliding_window=c.sliding_window,
                                      padding_mask=padding_mask),
                (q, k, v))
        else:
            mask = jnp.where(is_global[i], masks["global"], masks["local"])
            ctx = attention(q, k, v, impl=impl, scale=scale,
                            is_causal=False, attn_mask=mask,
                            padding_mask=padding_mask)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, nq * D)
        attn_out = lora(ctx @ a["o_w"], ctx, "o_proj", 3)
        attn_out = rms_norm(attn_out, bp["post_attn_ln"], eps)
        x = x + attn_out

    # --- MLP, sandwich-normed
    with jax.named_scope("mlp"):
        h = rms_norm(x, bp["pre_ffn_ln"], eps)
        gate = lora(h @ bp["mlp"]["gate_w"], h, "gate_proj", 4)
        up = lora(h @ bp["mlp"]["up_w"], h, "up_proj", 5)
        act = gelu_tanh(gate) * up
        down = lora(act @ bp["mlp"]["down_w"], act, "down_proj", 6)
        down = rms_norm(down, bp["post_ffn_ln"], eps)
    if collect_kv:
        return x + down, kv_out
    return x + down


def hidden_states(config: Gemma3TextConfig, params, input_ids,
                  attention_mask=None, lora=None,
                  compute_dtype=jnp.float32, remat: bool = False,
                  lora_dropout: float = 0.0, dropout_rng=None,
                  offload=None, block_stream=None,
                  collect_layers: bool = False, collect_kv: bool = False,
                  cp_mesh=None, cp_axis: str = "fsdp",
                  scan_unroll: int = 1, lora_impl: str = "auto"):
    """offload: optional (plan, shardings) pair matching `params`; offloaded
    block weights stream host->HBM per layer inside the scan (forces remat
    of the block body) — see parallel/offload.py. block_stream: pre-resolved
    stream fn for callers that already ran resolve_offload (so the fetched
    embedding table is reused by the tied lm_head, not fetched twice).
    collect_layers: also return {"embed", "layers"} activations for the
    alignment harness (reference: train_lora_gemma.cpp:620-920 npy dumps,
    gemma_model.h:100-143 per-layer dump requests)."""
    from mobilefinetuner_tpu.parallel.offload import resolve_offload
    c = config
    B, S = input_ids.shape
    params = jax.tree.map(jnp.asarray, params)
    if offload is not None:
        params, block_stream = resolve_offload(params, offload)
    stream = block_stream
    with jax.named_scope("embed"):
        if (cp_mesh is not None and cp_axis in cp_mesh.axis_names
                and c.vocab_size % cp_mesh.shape[cp_axis] == 0
                and S % cp_mesh.shape[cp_axis] == 0):
            # sequence-parallel + V-sharded tied table: the structural
            # vocab-parallel lookup — GSPMD left alone all-gathers the
            # full table here at large mesh sizes (ops/loss.vp_embed_lookup)
            from mobilefinetuner_tpu.ops.loss import vp_embed_lookup
            x = vp_embed_lookup(params["embed"], input_ids, cp_mesh,
                                vocab_axis=cp_axis).astype(compute_dtype)
        else:
            x = params["embed"][input_ids].astype(compute_dtype)
        # sqrt(hidden) embedding scaling, in the embed dtype as HF does
        normalizer = jnp.asarray(c.hidden_size ** 0.5, compute_dtype)
        x = x * normalizer

    if attention_mask is not None:
        # mask-derived positions (HF convention) so left-padded batches get
        # the same RoPE phases as HF Gemma-3
        positions = jnp.clip(
            jnp.cumsum(attention_mask.astype(jnp.int32), axis=-1) - 1, 0)
    else:
        positions = jnp.arange(S)
    cos_g, sin_g = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    cos_l, sin_l = rope_cos_sin(positions, c.head_dim,
                                c.rope_local_base_freq)
    ropes = {"cos_g": cos_g, "sin_g": sin_g, "cos_l": cos_l, "sin_l": sin_l}
    masks = {"global": causal_mask(S, S),
             "local": causal_mask(S, S, sliding_window=c.sliding_window)}
    is_global = jnp.asarray([c.is_global_layer(i)
                             for i in range(c.num_hidden_layers)])

    from mobilefinetuner_tpu.parallel.offload import layer_slicer
    slice_layer = layer_slicer(params["blocks"], stream, compute_dtype)
    lora_b = None if lora is None else lora.get("blocks")

    embed_out = x

    def body(x, i):
        r = _block(c, slice_layer(i), x, attention_mask, masks, ropes,
                   is_global, lora_b, i, lora_dropout, dropout_rng,
                   cp_mesh, cp_axis, collect_kv=collect_kv,
                   lora_impl=lora_impl)
        x2, kv = r if collect_kv else (r, None)
        return x2, (kv if collect_kv else (x2 if collect_layers else None))
    if remat or stream is not None:
        body = jax.checkpoint(body)
    # scan_unroll > 1 issues several layers' host->HBM fetches per loop
    # iteration on the streaming path — the host link is LATENCY-bound
    # (~2 GiB/s single stream vs ~8 concurrent), so overlapping fetches
    # raises effective bandwidth (bench offload-frontier rows)
    x, extras = jax.lax.scan(body, x, jnp.arange(c.num_hidden_layers),
                             unroll=scan_unroll)
    x = rms_norm(x, params["final_norm"].astype(compute_dtype),
                 c.rms_norm_eps)
    if collect_kv:
        return x, extras  # ([L,B,Hkv,S,D] k, [L,B,Hkv,S,D] v)
    if collect_layers:
        return x, {"embed": embed_out, "layers": extras}
    return x


def forward(config: Gemma3TextConfig, params, input_ids,
            attention_mask=None, lora=None, compute_dtype=jnp.float32,
            remat: bool = False, lora_dropout: float = 0.0,
            dropout_rng=None, offload=None, cp_mesh=None,
            cp_axis: str = "fsdp", lora_impl: str = "auto") -> jnp.ndarray:
    """Logits [B, S, V]; lm_head tied to the embedding table. An
    "lm_head" adapter entry adds its delta at the logits projection
    (training paths should prefer the chunked CE's lora_head= instead —
    this materializes [B, S, V] by construction)."""
    from mobilefinetuner_tpu.parallel.offload import resolve_offload
    params, stream = resolve_offload(params, offload)
    x = hidden_states(config, params, input_ids, attention_mask, lora,
                      compute_dtype, remat, lora_dropout, dropout_rng,
                      block_stream=stream, cp_mesh=cp_mesh,
                      cp_axis=cp_axis, lora_impl=lora_impl)
    logits = x @ params["embed"].astype(compute_dtype).T
    lora_b = None if lora is None else lora.get("blocks")
    if lora_b is not None and "lm_head" in lora_b:
        rng = (None if dropout_rng is None
               else jax.random.fold_in(dropout_rng, 2000))
        logits = maybe_lora(logits, x, lora_b["lm_head"], None,
                            lora_dropout, rng, impl=lora_impl)
    return logits
