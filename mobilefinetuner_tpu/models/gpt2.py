"""GPT-2 family decoder, pure-functional JAX.

Re-design of the reference's GPT2Model graph
(reference: operators/finetune_ops/graph/gpt2_model.{h,cpp}): pre-LN blocks
with fused-QKV attention, gelu_new MLP, final LN, tied lm_head = x @ wte^T
(gpt2_model.cpp:421-440). Differences by design:
  - parameters are a pytree of stacked per-layer arrays ([L, ...]) and the
    block stack runs under `lax.scan` — one compiled block body instead of L
    unrolled copies (compile time, remat-friendly), idiomatic for XLA;
  - weights keep HF Conv1D [in, out] layout so `y = x @ W + b` loads GPT-2
    checkpoints without transposition (the reference needs a no-transpose
    flag for exactly this reason, gpt2_lora_finetune/main.cpp:292-296);
  - attention is fully differentiable on every path (the reference's default
    memory-efficient attention is forward-only, SURVEY.md §2.12.1 — a bug we
    deliberately do not replicate);
  - autodiff, fusion, and memory management come from JAX/XLA instead of the
    reference's L0-L3 hand-written engine.

LoRA enters functionally: `forward(..., lora=...)` takes an optional pytree
(see lora/lora.py) whose entries add scale·(x@A@B) to the matching linears.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.ops.attention import attention


def layer_norm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps) * g + b
    return out.astype(x.dtype)


def gelu_new(x):
    """tanh-approx gelu, matches HF gelu_new (reference core/ops.cpp:1055)."""
    return jax.nn.gelu(x, approximate=True)


from mobilefinetuner_tpu.ops.dropout import inverted_dropout as _dropout


from mobilefinetuner_tpu.models.lora_apply import maybe_lora


def init_params(config: GPT2Config, key: jax.Array,
                dtype=jnp.float32) -> Dict[str, Any]:
    """Random init (N(0, 0.02), zeros for biases/proj per GPT-2 paper)."""
    E, L, V, P = (config.n_embd, config.n_layer, config.vocab_size,
                  config.n_positions)
    ks = jax.random.split(key, 8)
    std = 0.02

    def n(k, shape):
        return (jax.random.normal(k, shape) * std).astype(dtype)

    z = lambda *shape: jnp.zeros(shape, dtype)
    o = lambda *shape: jnp.ones(shape, dtype)
    return {
        "wte": n(ks[0], (V, E)),
        "wpe": n(ks[1], (P, E)),
        "blocks": {
            "ln_1": {"g": o(L, E), "b": z(L, E)},
            "attn": {
                "qkv_w": n(ks[2], (L, E, 3 * E)), "qkv_b": z(L, 3 * E),
                "proj_w": n(ks[3], (L, E, E)), "proj_b": z(L, E),
            },
            "ln_2": {"g": o(L, E), "b": z(L, E)},
            "mlp": {
                "fc_w": n(ks[4], (L, E, 4 * E)), "fc_b": z(L, 4 * E),
                "proj_w": n(ks[5], (L, 4 * E, E)), "proj_b": z(L, E),
            },
        },
        "ln_f": {"g": o(E), "b": z(E)},
    }


def _block(config: GPT2Config, bp, x, padding_mask, lora_b, layer_idx,
           lora_dropout=0.0, dropout_rng=None, cp_mesh=None,
           cp_axis="fsdp", collect_kv: bool = False,
           lora_impl: str = "auto"):
    """One pre-LN transformer block. bp leaves are THIS layer's weights
    (already sliced out of the [L, ...] stacks by the scan body); layer_idx
    (traced scalar) indexes the still-stacked LoRA leaves and salts
    dropout keys. cp_mesh: sequence-parallel mode — attention runs as
    ring attention over the mesh axis (parallel/ring_attention.py).
    collect_kv: also return this layer's (k, v) head tensors [B, H, S, D]
    (KV-cache prefill, models/generate.py)."""
    eps = config.layer_norm_epsilon
    H, D = config.n_head, config.head_dim
    B, S, E = x.shape
    rng = (None if dropout_rng is None
           else jax.random.fold_in(dropout_rng, layer_idx))

    def lora(y, x_in, name, site):
        entry = None if lora_b is None else lora_b.get(name)
        return maybe_lora(y, x_in, entry, layer_idx, lora_dropout,
                          None if rng is None
                          else jax.random.fold_in(rng, site),
                          impl=lora_impl)

    # named scopes label the phase in profiler traces AND compiled-HLO
    # op metadata (asserted by tests/test_telemetry.py; DESIGN.md §13)
    with jax.named_scope("attention"):
        h = layer_norm(x, bp["ln_1"]["g"], bp["ln_1"]["b"], eps)
        qkv = h @ bp["attn"]["qkv_w"] + bp["attn"]["qkv_b"]
        qkv = lora(qkv, h, "attn_qkv", 0)
        # split-QKV adapters hit only their column range of the fused
        # c_attn output (reference: lora_injector.h:169-191
        # col_offset/col_size)
        if lora_b is not None:
            from mobilefinetuner_tpu.lora.lora import GPT2_SPLIT_QKV_SLOTS
            for name, slot in GPT2_SPLIT_QKV_SLOTS.items():
                if name in lora_b:
                    sl = (Ellipsis, slice(slot * E, (slot + 1) * E))
                    qkv = qkv.at[sl].set(lora(qkv[sl], h, name, 4 + slot))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        kv_out = (to_heads(k), to_heads(v)) if collect_kv else None
        attn_rng = (None if rng is None or config.attn_pdrop <= 0.0
                    else jax.random.fold_in(rng, 9))
        if cp_mesh is not None:
            from mobilefinetuner_tpu.parallel.ring_attention import \
                ring_attention
            ctx = ring_attention(to_heads(q), to_heads(k), to_heads(v),
                                 cp_mesh, axis=cp_axis, is_causal=True,
                                 padding_mask=padding_mask)
        else:
            ctx = attention(to_heads(q), to_heads(k), to_heads(v),
                            impl=config.attention_impl, is_causal=True,
                            padding_mask=padding_mask,
                            attn_dropout=config.attn_pdrop,
                            attn_dropout_rng=attn_rng)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, E)
        proj = ctx @ bp["attn"]["proj_w"] + bp["attn"]["proj_b"]
        proj = lora(proj, ctx, "attn_proj", 1)
        proj = _dropout(proj, config.resid_pdrop,
                        None if rng is None else jax.random.fold_in(rng, 7))
        x = x + proj

    with jax.named_scope("mlp"):
        h = layer_norm(x, bp["ln_2"]["g"], bp["ln_2"]["b"], eps)
        fc = h @ bp["mlp"]["fc_w"] + bp["mlp"]["fc_b"]
        fc = lora(fc, h, "mlp_fc_in", 2)
        act = gelu_new(fc)
        out = act @ bp["mlp"]["proj_w"] + bp["mlp"]["proj_b"]
        out = lora(out, act, "mlp_fc_out", 3)
        out = _dropout(out, config.resid_pdrop,
                       None if rng is None else jax.random.fold_in(rng, 8))
    if collect_kv:
        return x + out, kv_out
    return x + out


def hidden_states(config: GPT2Config, params, input_ids,
                  attention_mask=None, lora=None,
                  compute_dtype=jnp.float32, remat: bool = False,
                  lora_dropout: float = 0.0, dropout_rng=None,
                  offload=None, block_stream=None,
                  collect_layers: bool = False, collect_kv: bool = False,
                  cp_mesh=None, cp_axis: str = "fsdp",
                  lora_impl: str = "auto"):
    """Final-LN hidden states [B, S, E] (pre lm_head).

    offload: optional (plan, shardings) pytree pair matching `params`
    (parallel/offload.py). Offloaded block weights are streamed host->HBM
    one layer at a time inside the scan; streaming forces remat of the
    block body so the backward re-fetches layers instead of keeping every
    layer's weights alive as residuals (which would defeat the budget).
    block_stream: pre-resolved stream fn from resolve_offload, for callers
    that already fetched the top-level leaves themselves (e.g. forward,
    which reuses the fetched wte for the tied lm_head).
    collect_layers: also return {"embed": [B,S,E], "layers": [L,B,S,E]}
    (post-embedding and post-block activations) for the alignment harness
    (reference: train_lora_gemma.cpp:620-920 npy dumps, GPT2_ALIGN_DUMP_DIR
    in gpt2_model.cpp:327-399).
    """
    from mobilefinetuner_tpu.parallel.offload import resolve_offload
    B, S = input_ids.shape
    params = jax.tree.map(jnp.asarray, params)
    if offload is not None:
        params, block_stream = resolve_offload(params, offload)
    stream = block_stream
    with jax.named_scope("embed"):
        if attention_mask is not None:
            # HF convention: position ids count only unmasked tokens, so
            # left-padded batches line up with HF GPT-2 exactly.
            positions = jnp.clip(
                jnp.cumsum(attention_mask.astype(jnp.int32), axis=-1) - 1,
                0)
            pos_emb = params["wpe"][positions]
        else:
            pos_emb = params["wpe"][:S][None, :, :]
        x = params["wte"][input_ids] + pos_emb
        x = x.astype(compute_dtype)
        x = _dropout(x, config.embd_pdrop,
                     None if dropout_rng is None
                     else jax.random.fold_in(dropout_rng, 1000))
    padding_mask = attention_mask
    from mobilefinetuner_tpu.parallel.offload import layer_slicer
    slice_layer = layer_slicer(params["blocks"], stream, compute_dtype)
    lora_b = None if lora is None else lora.get("blocks")

    embed_out = x

    def body(x, i):
        r = _block(config, slice_layer(i), x, padding_mask, lora_b, i,
                   lora_dropout, dropout_rng, cp_mesh, cp_axis,
                   collect_kv=collect_kv, lora_impl=lora_impl)
        x2, kv = r if collect_kv else (r, None)
        return x2, (kv if collect_kv else (x2 if collect_layers else None))
    if remat or stream is not None:
        body = jax.checkpoint(body)
    x, extras = jax.lax.scan(body, x, jnp.arange(config.n_layer))
    x = layer_norm(x, params["ln_f"]["g"].astype(compute_dtype),
                   params["ln_f"]["b"].astype(compute_dtype),
                   config.layer_norm_epsilon)
    if collect_kv:
        return x, extras  # ([L,B,H,S,D] k, [L,B,H,S,D] v)
    if collect_layers:
        return x, {"embed": embed_out, "layers": extras}
    return x


def forward(config: GPT2Config, params, input_ids, attention_mask=None,
            lora=None, compute_dtype=jnp.float32, remat: bool = False,
            lora_dropout: float = 0.0, dropout_rng=None,
            offload=None, cp_mesh=None, cp_axis: str = "fsdp",
            lora_impl: str = "auto") -> jnp.ndarray:
    """Logits [B, S, V]. Tied lm_head: x @ wte^T (gpt2_model.cpp:421-440).

    The reference caches wte^T when embeddings are frozen (SURVEY.md
    §2.12.5); under XLA the transpose is a free layout change, so no cache.
    An "lm_head" adapter entry (lora/lora.py UNSTACKED_TARGETS) adds its
    delta at the logits projection.
    """
    from mobilefinetuner_tpu.parallel.offload import resolve_offload
    params, stream = resolve_offload(params, offload)
    x = hidden_states(config, params, input_ids, attention_mask, lora,
                      compute_dtype, remat, lora_dropout, dropout_rng,
                      block_stream=stream, cp_mesh=cp_mesh,
                      cp_axis=cp_axis, lora_impl=lora_impl)
    logits = x @ params["wte"].astype(compute_dtype).T
    lora_b = None if lora is None else lora.get("blocks")
    if lora_b is not None and "lm_head" in lora_b:
        rng = (None if dropout_rng is None
               else jax.random.fold_in(dropout_rng, 2000))
        logits = maybe_lora(logits, x, lora_b["lm_head"], None,
                            lora_dropout, rng, impl=lora_impl)
    return logits


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))
