"""Shared functional LoRA application used by all model families.

y = base(x) + scale · ((dropout(x) @ A) @ B), PEFT semantics: dropout is
applied to the LoRA branch's input only, never the base path
(reference: nn/lora_linear.cpp:47-106 forward; dropout field in
LoraSpec, lora_injector.h:29-71). "scale" is stop-gradiented — it is a
hyperparameter leaf living in the pytree, not a trainable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def maybe_lora(y, x, lora_entry, layer_idx=None, dropout: float = 0.0,
               rng: Optional[jax.Array] = None):
    """Add the LoRA delta to y if an entry exists.

    lora_entry: {"A": [in,r] or [L,in,r], "B": [r,out] or [L,r,out],
    "scale": scalar}; stacked leaves are indexed by layer_idx (a traced
    scalar under lax.scan). dropout>0 with rng!=None enables train-mode
    inverted dropout on the branch input.
    """
    if lora_entry is None:
        return y
    A, B = lora_entry["A"], lora_entry["B"]
    if layer_idx is not None and A.ndim == 3:
        A, B = A[layer_idx], B[layer_idx]
    from mobilefinetuner_tpu.ops.dropout import inverted_dropout
    xb = inverted_dropout(x, dropout, rng)
    delta = (xb @ A.astype(x.dtype)) @ B.astype(x.dtype)
    scale = jax.lax.stop_gradient(
        jnp.asarray(lora_entry["scale"]).astype(y.dtype))
    return y + scale * delta
