"""Shared functional LoRA application used by all model families.

y = base(x) + scale · ((dropout(x) @ A) @ B), PEFT semantics: dropout is
applied to the LoRA branch's input only, never the base path
(reference: nn/lora_linear.cpp:47-106 forward; dropout field in
LoraSpec, lora_injector.h:29-71). "scale" is stop-gradiented — it is a
hyperparameter leaf living in the pytree, not a trainable.

Multi-adapter batched serving: an entry carrying an "ids" leaf ([B]
int32, one adapter index per batch row) has its A/B/scale leaves stacked
along a LEADING adapter axis (lora.stack_adapters + assign_adapters);
each row's delta uses its own adapter's factors via a per-row gather —
N adapters serve one batch without materializing merged weight copies,
and the models stay unchanged (the entry itself carries the routing).

Implementation selector (DESIGN.md §17) — `impl`, mirroring the flash
backward's `bwd_impl=auto|merged|split` discipline:

  naive  the parity ORACLE: fixed (x@A)@B contraction, per-row adapter
         gather on the ids-routed path, pure XLA. Since round 12 the
         oracle itself accumulates the rank-r bottleneck in f32
         (`preferred_element_type`) with the A/B/scale casts hoisted —
         the old per-call bf16-accumulate chain lost ~2 decimal digits
         at S=2048 (pinned by tests/test_lora.py).
  fused  shape-aware compute graph: contraction order picked per call
         site by the FLOPs+bytes cost model below, the k-adapter
         ids-routed path switched between the per-row GATHER order and
         the DENSE all-k + one-hot-route order by the same model, and
         the delta folded into a Pallas epilogue pass
         (ops/lora_fused.lora_epilogue) at eligible sites so the
         [N, d_out] delta never round-trips HBM. Ineligible sites fall
         back to the cost-model XLA order — same numerics contract.
  auto   resolve per call site: `fused` where the epilogue kernel is
         eligible AND the delta is large enough to be memory-bound
         (resolve_lora_impl), else `naive`. Off-TPU auto is always
         naive. The resolution is a pure function of static shapes, so
         it happens once per traced call site; the LoRA CLIs log the
         per-target resolution string into the telemetry run_start
         manifest (impl_summary).

Contraction-order cost model (Run LoRA Run, PAPERS.md): with rank
r ≪ d, (x@A)@B costs 2·N·r·(d_in+d_out) FLOPs while x@(A@B) pays the
merged [d_in, d_out] product — 2·r·d_in·d_out + 2·N·d_in·d_out. Merged
could only win when r·(d_in+d_out) > d_in·d_out, i.e. r above the
harmonic mean of the dims — never at LoRA ranks; pick_order ASSERTS
that instead of silently materializing a [d, d] product.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

LORA_IMPLS = ("auto", "naive", "fused")

# v5e machine balance: ~197e12 bf16 FLOP/s over ~819 GB/s HBM — the
# FLOPs-per-byte equivalence the cost model uses to weigh the two
# resources on one axis (the exact chip hardly matters: every TPU
# generation sits within 2x of this ratio, and the decisions below are
# order-of-magnitude ones).
FLOPS_PER_BYTE = 240.0

# auto engages the fused epilogue only when the delta it eliminates is
# at least this many bytes — below it the tensor lives in registers/
# cache through XLA fusion anyway and the kernel's per-tile loop
# overhead is all cost (the fused-CE kernel history, DESIGN.md §5a).
FUSED_MIN_DELTA_BYTES = 1 << 20


def validate_lora_impl(impl: str) -> str:
    if impl not in LORA_IMPLS:
        raise ValueError(
            f"lora_impl must be one of {'/'.join(LORA_IMPLS)}, "
            f"got {impl!r}")
    return impl


def order_costs(n_tok: int, d_in: int, d_out: int,
                r: int, itemsize: int = 2) -> Dict[str, float]:
    """Byte-equivalent cost of each single-adapter contraction order
    (FLOPs/FLOPS_PER_BYTE + HBM bytes moved beyond the unavoidable
    x/y traffic). Exposed for tests and DESIGN.md §17."""
    # (x@A)@B: two rank-r matmuls; extra traffic = A, B, and the [N, r]
    # bottleneck written+read between them (zero when fused).
    xa_b = (2.0 * n_tok * r * (d_in + d_out) / FLOPS_PER_BYTE
            + (r * (d_in + d_out) + 2 * n_tok * r) * itemsize)
    # x@(A@B): materialize the merged [d_in, d_out] product, then a full
    # dense matmul — only conceivably profitable when r exceeds the
    # harmonic mean of the dims.
    x_ab = ((2.0 * r * d_in * d_out + 2.0 * n_tok * d_in * d_out)
            / FLOPS_PER_BYTE
            + (r * (d_in + d_out) + d_in * d_out) * itemsize)
    return {"xA_B": xa_b, "x_AB": x_ab}


def pick_order(n_tok: int, d_in: int, d_out: int, r: int,
               itemsize: int = 2) -> str:
    """Single-adapter contraction order for this call site: always
    (x@A)@B. Merged x@(A@B) could only pay when the rank-r factor pair
    does MORE work than the dense product it expands to — i.e. when
    r·(d_in+d_out) > d_in·d_out, rank above the harmonic mean of the
    dims. That never holds at LoRA ranks, so instead of implementing a
    merged path no shape reaches, this ASSERTS the criterion (a
    [d_in, d_out] temp at every adapter site would be a silent OOM
    machine; a rank that big should be merged offline via
    lora.merge_gpt2/merge_gemma3)."""
    if r * (d_in + d_out) > d_in * d_out:
        raise AssertionError(
            f"r={r} exceeds the harmonic-mean bound for d_in={d_in}, "
            f"d_out={d_out} (r*(d_in+d_out)={r * (d_in + d_out)} > "
            f"{d_in * d_out}): the factored form does more work than "
            f"the dense product — merge the adapter instead "
            f"(lora.merge_gpt2/merge_gemma3)")
    return "xA_B"


def multi_order_costs(n_rows: int, n_tok: int, d_in: int, d_out: int,
                      r: int, k: int,
                      itemsize: int = 2) -> Dict[str, float]:
    """Byte-equivalent cost of the two ids-routed k-adapter orders.

    gather  per-row A/B gather ([n_rows, d_in, r] + [n_rows, r, d_out]
            copies through HBM), then two batched rank-r matmuls.
    dense   compute ALL k adapters' deltas (k× the rank-r FLOPs and a
            [k, n_tok, d_out] f32 intermediate) and one-hot-route rows —
            no per-row factor copies; wins only when n_tok is tiny
            (decode: one token per slot) and k modest.
    """
    per_pair = r * (d_in + d_out)
    gather = (2.0 * n_tok * per_pair / FLOPS_PER_BYTE
              + n_rows * per_pair * itemsize)
    dense = (2.0 * k * n_tok * per_pair / FLOPS_PER_BYTE
             + k * per_pair * itemsize          # read the bank once
             + k * n_tok * d_out * 4)           # routed f32 intermediate
    return {"gather": gather, "dense": dense}


def resolve_multi_order(n_rows: int, n_tok: int, d_in: int, d_out: int,
                        r: int, k: int, itemsize: int = 2) -> str:
    costs = multi_order_costs(n_rows, n_tok, d_in, d_out, r, k, itemsize)
    return "dense" if costs["dense"] < costs["gather"] else "gather"


def resolve_lora_impl(n_tok: int, d_in: int, d_out: int, r: int,
                      itemsize: int = 2,
                      backend: Optional[str] = None) -> str:
    """The `auto` rule for ONE call site (static shapes -> resolved once
    per trace): `fused` when the Pallas epilogue is shape-eligible on a
    TPU backend and the eliminated [n_tok, d_out] delta round-trip is
    big enough to be memory-bound, else `naive`. Kept as one function so
    the models, the serve engine, and the manifest summary all resolve
    through the same gate (the acceptance bar: auto never selects an
    ineligible fused site)."""
    from mobilefinetuner_tpu.ops.lora_fused import lora_epilogue_eligible
    if backend is None:
        backend = jax.default_backend()
    if backend != "tpu":
        return "naive"
    if not lora_epilogue_eligible(n_tok, d_out, r, itemsize):
        return "naive"
    if n_tok * d_out * itemsize < FUSED_MIN_DELTA_BYTES:
        return "naive"
    return "fused"


def impl_summary(target_dims: Dict[str, Tuple[int, int]], n_tok: int,
                 r: int, impl: str, itemsize: int = 2,
                 backend: Optional[str] = None) -> str:
    """'target=impl,...' — the per-call-site resolution of `auto` for
    the run's dominant shapes, logged into the telemetry run_start
    manifest by the LoRA CLIs (forced impls summarize as themselves)."""
    validate_lora_impl(impl)
    parts = []
    for name in sorted(target_dims):
        d_in, d_out = target_dims[name]
        site = impl
        if impl == "auto":
            site = resolve_lora_impl(n_tok, d_in, d_out, r, itemsize,
                                     backend=backend)
        parts.append(f"{name}={site}")
    return ",".join(parts)


def _finish(y, scale, delta):
    """y + scale·delta with the accumulation kept f32 until the single
    cast back to y's dtype (scale arrives f32, delta f32-accumulated)."""
    return y + (scale * delta).astype(y.dtype)


def _multi_lora(y, x, entry, layer_idx, dropout, rng, impl):
    """Per-row adapter routing: A [N,(L,)in,r], B [N,(L,)r,out],
    scale [N], ids [B] -> row b's delta uses adapter ids[b]. Order
    (gather vs dense) picked by the cost model under fused/auto; naive
    pins the per-row gather as the oracle."""
    from mobilefinetuner_tpu.ops.dropout import inverted_dropout
    ids = entry["ids"]
    A, B = entry["A"], entry["B"]
    if layer_idx is not None and A.ndim == 4:
        A, B = A[:, layer_idx], B[:, layer_idx]
    A = A.astype(x.dtype)                            # [k, in, r] (hoisted)
    B = B.astype(x.dtype)                            # [k, r, out]
    k, d_in, r = A.shape
    d_out = B.shape[-1]
    n_rows = ids.shape[0]
    n_tok = y.size // d_out
    scale = jax.lax.stop_gradient(
        jnp.asarray(entry["scale"]).astype(jnp.float32))[ids]   # [B]
    scale = scale.reshape((-1,) + (1,) * (y.ndim - 1))
    xb = inverted_dropout(x, dropout, rng)
    # auto follows the module contract (off-TPU auto is always naive —
    # the cost-model constants are TPU machine balance); an explicit
    # `fused` exercises the cost-model order on any backend (the parity
    # tests pin the dense order against the gather oracle on CPU)
    order = "gather"
    if impl == "fused" or (impl == "auto"
                           and jax.default_backend() == "tpu"):
        order = resolve_multi_order(n_rows, n_tok, d_in, d_out, r, k,
                                    x.dtype.itemsize)
    if order == "dense":
        # all-k compute + one-hot routing: reads the bank once instead
        # of gathering [n_rows, in, r]+[n_rows, r, out] factor copies —
        # the decode-shape win (n_tok == n_rows == slots)
        route = jax.nn.one_hot(ids, k, dtype=jnp.float32)        # [B, k]
        t1 = jnp.einsum("b...i,kir->kb...r", xb, A,
                        preferred_element_type=jnp.float32)
        t2 = jnp.einsum("kb...r,kro->kb...o", t1.astype(x.dtype), B,
                        preferred_element_type=jnp.float32)
        delta = jnp.einsum("kb...o,bk->b...o", t2, route,
                           preferred_element_type=jnp.float32)
    else:
        A_rows = A[ids]                              # [B, in, r]
        B_rows = B[ids]                              # [B, r, out]
        t1 = jnp.einsum("b...i,bir->b...r", xb, A_rows,
                        preferred_element_type=jnp.float32)
        delta = jnp.einsum("b...r,bro->b...o", t1.astype(x.dtype),
                           B_rows, preferred_element_type=jnp.float32)
    return _finish(y, scale, delta)


def maybe_lora(y, x, lora_entry, layer_idx=None, dropout: float = 0.0,
               rng: Optional[jax.Array] = None, impl: str = "auto"):
    """Add the LoRA delta to y if an entry exists.

    lora_entry: {"A": [in,r] or [L,in,r], "B": [r,out] or [L,r,out],
    "scale": scalar}; stacked leaves are indexed by layer_idx (a traced
    scalar under lax.scan). dropout>0 with rng!=None enables train-mode
    inverted dropout on the branch input. An entry with an "ids" leaf is
    a MULTI-adapter stack routed per batch row (see module docstring).
    impl: auto|naive|fused (module docstring); both matmuls accumulate
    f32 via preferred_element_type on EVERY impl, with the A/B/scale
    casts hoisted to one site.
    """
    if lora_entry is None:
        return y
    validate_lora_impl(impl)
    if "ids" in lora_entry:
        return _multi_lora(y, x, lora_entry, layer_idx, dropout, rng,
                           impl)
    A, B = lora_entry["A"], lora_entry["B"]
    if layer_idx is not None and A.ndim == 3:
        A, B = A[layer_idx], B[layer_idx]
    A = A.astype(x.dtype)                            # [in, r]  (hoisted)
    B = B.astype(x.dtype)                            # [r, out]
    d_in, r = A.shape
    d_out = B.shape[-1]
    n_tok = y.size // d_out
    scale = jax.lax.stop_gradient(
        jnp.asarray(lora_entry["scale"]).astype(jnp.float32))
    if impl == "auto":
        impl = resolve_lora_impl(n_tok, d_in, d_out, r, x.dtype.itemsize)
    pick_order(n_tok, d_in, d_out, r, x.dtype.itemsize)  # asserts xA_B
    from mobilefinetuner_tpu.ops.dropout import inverted_dropout
    xb = inverted_dropout(x, dropout, rng)
    xa = jnp.einsum("...i,ir->...r", xb, A,
                    preferred_element_type=jnp.float32).astype(x.dtype)
    if impl == "fused":
        from mobilefinetuner_tpu.ops.lora_fused import (
            lora_epilogue_add, lora_epilogue_eligible)
        if lora_epilogue_eligible(n_tok, d_out, r, x.dtype.itemsize):
            return lora_epilogue_add(y, xa, B, scale)
    delta = jnp.einsum("...r,ro->...o", xa, B,
                       preferred_element_type=jnp.float32)
    return _finish(y, scale, delta)
