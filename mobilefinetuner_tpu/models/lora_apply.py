"""Shared functional LoRA application used by all model families.

y = base(x) + scale · ((dropout(x) @ A) @ B), PEFT semantics: dropout is
applied to the LoRA branch's input only, never the base path
(reference: nn/lora_linear.cpp:47-106 forward; dropout field in
LoraSpec, lora_injector.h:29-71). "scale" is stop-gradiented — it is a
hyperparameter leaf living in the pytree, not a trainable.

Multi-adapter batched serving: an entry carrying an "ids" leaf ([B]
int32, one adapter index per batch row) has its A/B/scale leaves stacked
along a LEADING adapter axis (lora.stack_adapters + assign_adapters);
each row's delta uses its own adapter's factors via a per-row gather —
N adapters serve one batch without materializing merged weight copies,
and the models stay unchanged (the entry itself carries the routing).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _multi_lora(y, x, entry, layer_idx, dropout, rng):
    """Per-row adapter routing: A [N,(L,)in,r], B [N,(L,)r,out],
    scale [N], ids [B] -> row b's delta uses adapter ids[b]."""
    from mobilefinetuner_tpu.ops.dropout import inverted_dropout
    ids = entry["ids"]
    A, B = entry["A"], entry["B"]
    if layer_idx is not None and A.ndim == 4:
        A, B = A[:, layer_idx], B[:, layer_idx]
    A_rows = A[ids].astype(x.dtype)                  # [B, in, r]
    B_rows = B[ids].astype(x.dtype)                  # [B, r, out]
    xb = inverted_dropout(x, dropout, rng)
    delta = jnp.einsum("b...i,bir->b...r", xb, A_rows)
    delta = jnp.einsum("b...r,bro->b...o", delta, B_rows)
    scale = jax.lax.stop_gradient(
        jnp.asarray(entry["scale"]).astype(y.dtype))[ids]   # [B]
    return y + scale.reshape((-1,) + (1,) * (y.ndim - 1)) * delta


def maybe_lora(y, x, lora_entry, layer_idx=None, dropout: float = 0.0,
               rng: Optional[jax.Array] = None):
    """Add the LoRA delta to y if an entry exists.

    lora_entry: {"A": [in,r] or [L,in,r], "B": [r,out] or [L,r,out],
    "scale": scalar}; stacked leaves are indexed by layer_idx (a traced
    scalar under lax.scan). dropout>0 with rng!=None enables train-mode
    inverted dropout on the branch input. An entry with an "ids" leaf is
    a MULTI-adapter stack routed per batch row (see module docstring).
    """
    if lora_entry is None:
        return y
    if "ids" in lora_entry:
        return _multi_lora(y, x, lora_entry, layer_idx, dropout, rng)
    A, B = lora_entry["A"], lora_entry["B"]
    if layer_idx is not None and A.ndim == 3:
        A, B = A[layer_idx], B[layer_idx]
    from mobilefinetuner_tpu.ops.dropout import inverted_dropout
    xb = inverted_dropout(x, dropout, rng)
    delta = (xb @ A.astype(x.dtype)) @ B.astype(x.dtype)
    scale = jax.lax.stop_gradient(
        jnp.asarray(lora_entry["scale"]).astype(y.dtype))
    return y + scale * delta
