"""Hendrycks MMLU taxonomy: 57 subjects -> topics -> 4 macro categories,
and the category-level accuracy rollup.

The taxonomy is public dataset metadata from the MMLU paper's evaluation
code (reference vendors it at data/mmlu/hendrycks_test/categories.py:
`subcategories` maps each subject to a topic, `categories` groups topics
into STEM / humanities / social sciences / other); the reference's own
category report comes from evaluate.py's rollup. Subjects outside the
official 57 (custom CSVs) report under "uncategorized" rather than being
dropped or misfiled.
"""

from __future__ import annotations

from typing import Dict, List

# subject -> topic (the paper's "subcategory")
SUBJECT_TOPICS: Dict[str, str] = {
    "abstract_algebra": "math",
    "anatomy": "health",
    "astronomy": "physics",
    "business_ethics": "business",
    "clinical_knowledge": "health",
    "college_biology": "biology",
    "college_chemistry": "chemistry",
    "college_computer_science": "computer science",
    "college_mathematics": "math",
    "college_medicine": "health",
    "college_physics": "physics",
    "computer_security": "computer science",
    "conceptual_physics": "physics",
    "econometrics": "economics",
    "electrical_engineering": "engineering",
    "elementary_mathematics": "math",
    "formal_logic": "philosophy",
    "global_facts": "other",
    "high_school_biology": "biology",
    "high_school_chemistry": "chemistry",
    "high_school_computer_science": "computer science",
    "high_school_european_history": "history",
    "high_school_geography": "geography",
    "high_school_government_and_politics": "politics",
    "high_school_macroeconomics": "economics",
    "high_school_mathematics": "math",
    "high_school_microeconomics": "economics",
    "high_school_physics": "physics",
    "high_school_psychology": "psychology",
    "high_school_statistics": "math",
    "high_school_us_history": "history",
    "high_school_world_history": "history",
    "human_aging": "health",
    "human_sexuality": "culture",
    "international_law": "law",
    "jurisprudence": "law",
    "logical_fallacies": "philosophy",
    "machine_learning": "computer science",
    "management": "business",
    "marketing": "business",
    "medical_genetics": "health",
    "miscellaneous": "other",
    "moral_disputes": "philosophy",
    "moral_scenarios": "philosophy",
    "nutrition": "health",
    "philosophy": "philosophy",
    "prehistory": "history",
    "professional_accounting": "other",
    "professional_law": "law",
    "professional_medicine": "health",
    "professional_psychology": "psychology",
    "public_relations": "politics",
    "security_studies": "politics",
    "sociology": "culture",
    "us_foreign_policy": "politics",
    "virology": "health",
    "world_religions": "philosophy",
}

# macro category -> topics
MACRO_CATEGORIES: Dict[str, List[str]] = {
    "STEM": ["physics", "chemistry", "biology", "computer science",
             "math", "engineering"],
    "humanities": ["history", "philosophy", "law"],
    "social sciences": ["politics", "culture", "economics", "geography",
                        "psychology"],
    "other (business, health, misc.)": ["other", "business", "health"],
}

UNCATEGORIZED = "uncategorized"

_TOPIC_TO_MACRO = {topic: macro
                   for macro, topics in MACRO_CATEGORIES.items()
                   for topic in topics}


def subject_macro_category(subject: str) -> str:
    """Macro category for a subject; UNCATEGORIZED for non-official ones."""
    topic = SUBJECT_TOPICS.get(subject)
    return _TOPIC_TO_MACRO.get(topic, UNCATEGORIZED) if topic \
        else UNCATEGORIZED


def category_rollup(result) -> Dict[str, dict]:
    """Per-macro-category accuracies from an MMLUResult: macro (mean of the
    member subjects' accuracies — the paper's headline aggregation) and
    micro (pooled over items), plus counts. Categories with no evaluated
    subjects are omitted."""
    groups: Dict[str, list] = {}
    for r in result.per_subject:
        groups.setdefault(subject_macro_category(r.subject), []).append(r)
    out = {}
    for cat in list(MACRO_CATEGORIES) + [UNCATEGORIZED]:
        rs = groups.get(cat)
        if not rs:
            continue
        total = sum(r.total for r in rs)
        out[cat] = {
            "macro_accuracy": round(
                sum(r.accuracy for r in rs) / len(rs), 4),
            "micro_accuracy": round(
                sum(r.correct for r in rs) / total, 4) if total else 0.0,
            "subjects": len(rs),
            "correct": sum(r.correct for r in rs),
            "total": total,
        }
    return out
