"""MMLU 4-choice evaluation: CSV loading, k-shot prompt building,
letter-token argmax prediction, per-subject / macro / micro reporting.

Behavioral spec mirrors the reference MMLURunner
(reference: gpt2_lora_finetune/mmlu/mmlu_runner.{h,cpp}):
  - every *.csv under <mmlu_root>/<split>/ is loaded; quoted CSV fields with
    escaped double-quotes are handled (parse_csv_line);
  - both headered CSVs (subject/question/a/b/c/d/answer columns) and the
    headerless Hendrycks layout (question,A,B,C,D,answer with the subject
    taken from the filename) are accepted;
  - prompt = "Question: ...\nA. ...\nB. ...\nC. ...\nD. ...\nAnswer: "
    with k-shot examples prefixed, answered, and separated by blank lines
    (build_prompt, trailing space included);
  - few-shot examples are the first k items of the same subject, excluding
    the current item (no leakage; evaluate());
  - prediction = argmax over the log-softmax of the LAST-token logits
    restricted to the token ids of "A"/"B"/"C"/"D" (predict_letter);
  - macro accuracy = mean of per-subject accuracies, micro = pooled
    (mmlu_runner.h:12-54).

Model access is through a `logits_fn(ids: np.ndarray[1,S]) -> np.ndarray[V]`
callable (last-token logits), so the same runner drives GPT-2, Gemma, or any
future model; the CLI builds a jitted, bucketed-length version.
"""

from __future__ import annotations

import dataclasses
import io
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class MCQItem:
    subject: str
    question: str
    A: str
    B: str
    C: str
    D: str
    answer: str  # "A".."D"


@dataclasses.dataclass
class SubjectReport:
    subject: str
    correct: int
    total: int

    @property
    def accuracy(self) -> float:
        return self.correct / self.total if self.total else 0.0


@dataclasses.dataclass
class MMLUResult:
    per_subject: List[SubjectReport]
    macro: float
    micro: float
    total: int


def parse_csv_line(line: str) -> List[str]:
    """Minimal RFC-4180 field split: quotes + escaped double-quotes
    (mmlu_runner.cpp parse_csv_line semantics)."""
    fields, cur, in_quotes = [], [], False
    i = 0
    while i < len(line):
        c = line[i]
        if in_quotes:
            if c == '"':
                if i + 1 < len(line) and line[i + 1] == '"':
                    cur.append('"')
                    i += 1
                else:
                    in_quotes = False
            else:
                cur.append(c)
        else:
            if c == ",":
                fields.append("".join(cur))
                cur = []
            elif c == '"':
                in_quotes = True
            else:
                cur.append(c)
        i += 1
    fields.append("".join(cur))
    return fields


def _subject_from_filename(path: str) -> str:
    """abstract_algebra_test.csv -> abstract_algebra."""
    name = os.path.splitext(os.path.basename(path))[0]
    for suffix in ("_test", "_val", "_dev", "_train"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def read_mmlu_csv(path: str) -> List[MCQItem]:
    """Load one CSV; headered or headerless-Hendrycks layouts."""
    with open(path, encoding="utf-8") as f:
        return parse_mmlu_text(f.read(), _subject_from_filename(path),
                               origin=path)


def parse_mmlu_text(text: str, default_subject: str,
                    origin: str = "<text>") -> List[MCQItem]:
    """Parse MMLU CSV text (headered or headerless-Hendrycks) — the single
    parser behind read_mmlu_csv and tools/mmlu_prep.py's zip ingestion, so
    header detection cannot diverge between sources."""
    lines = [ln.rstrip("\n") for ln in text.splitlines() if ln.strip()]
    if not lines:
        return []
    first = parse_csv_line(lines[0])
    lowered = [c.strip().lower() for c in first]
    required = ("question", "a", "b", "c", "d", "answer")
    # header detection needs BOTH marker columns (a lone 'answer' cell in a
    # headerless data row must not trigger it); a detected header must then
    # carry every required column or the file is malformed.
    looks_headered = "question" in lowered and "answer" in lowered
    headered = looks_headered and all(n in lowered for n in required)
    if looks_headered and not headered:
        missing = [n for n in required if n not in lowered]
        raise ValueError(
            f"{origin}: headered MMLU CSV is missing column(s) "
            f"{missing}; need all of {list(required)}")
    items: List[MCQItem] = []
    if headered:
        idx = {name: lowered.index(name) for name in required}
        subj_idx = lowered.index("subject") if "subject" in lowered else None
        rows = lines[1:]
        for line in rows:
            f2 = parse_csv_line(line)
            if len(f2) <= max(idx.values()):
                continue
            # an empty subject CELL falls back to the file-level default
            # (filename-derived), not straight to "unknown"
            subject = ((f2[subj_idx].strip() or default_subject)
                       if subj_idx is not None else default_subject) \
                or "unknown"
            ans = f2[idx["answer"]].strip()
            items.append(MCQItem(
                subject=subject, question=f2[idx["question"]].strip(),
                A=f2[idx["a"]].strip(), B=f2[idx["b"]].strip(),
                C=f2[idx["c"]].strip(), D=f2[idx["d"]].strip(),
                answer=(ans[:1].upper() or "A")))
    else:
        subject = default_subject
        for line in lines:
            f2 = parse_csv_line(line)
            if len(f2) < 6:
                continue
            items.append(MCQItem(
                subject=subject, question=f2[0].strip(), A=f2[1].strip(),
                B=f2[2].strip(), C=f2[3].strip(), D=f2[4].strip(),
                answer=(f2[5].strip()[:1].upper() or "A")))
    return items


def load_split(mmlu_root: str, split: str) -> Dict[str, List[MCQItem]]:
    """All *.csv under <root>/<split>/ grouped by subject."""
    split_dir = os.path.join(mmlu_root, split)
    by_subject: Dict[str, List[MCQItem]] = {}
    for name in sorted(os.listdir(split_dir)):
        if not name.endswith(".csv"):
            continue
        for item in read_mmlu_csv(os.path.join(split_dir, name)):
            by_subject.setdefault(item.subject, []).append(item)
    return by_subject


def build_prompt(item: MCQItem,
                 shots: Optional[Sequence[MCQItem]] = None) -> str:
    def one(q: MCQItem) -> str:
        return (f"Question: {q.question}\n"
                f"A. {q.A}\nB. {q.B}\nC. {q.C}\nD. {q.D}\nAnswer: ")

    prompt = ""
    for s in shots or ():
        prompt += one(s) + s.answer + "\n\n"
    return prompt + one(item)


LETTERS = ("A", "B", "C", "D")


def restricted_argmax(logits_row: np.ndarray,
                      letter_ids: Sequence[int]) -> str:
    """argmax over the A-D letter token ids (out-of-range ids score -inf);
    raw logits are rank-equivalent to the reference's log-softmax."""
    scores = [logits_row[i] if 0 <= i < logits_row.shape[-1] else -1e30
              for i in letter_ids]
    return LETTERS[int(np.argmax(scores))]


def finalize_reports(correct: Dict[str, int],
                     totals: Dict[str, int]) -> "MMLUResult":
    reports = [SubjectReport(s, correct[s], totals[s])
               for s in sorted(totals)]
    macro = (sum(r.accuracy for r in reports) / len(reports)
             if reports else 0.0)
    total = sum(totals.values())
    micro = sum(correct.values()) / total if total else 0.0
    return MMLUResult(reports, macro, micro, total)


def predict_letter(prompt: str, logits_fn: Callable[[np.ndarray], np.ndarray],
                   encode_fn: Callable[[str], List[int]],
                   letter_ids: Sequence[int]) -> str:
    """argmax over the last-token log-probs restricted to the A-D token ids.

    log_softmax is rank-preserving over the restricted set, so raw logits
    argmax is equivalent (the reference computes the full log_softmax first,
    predict_letter; we skip the normalization)."""
    ids = encode_fn(prompt) or [0]
    logits = logits_fn(np.asarray(ids, np.int32)[None, :])
    return restricted_argmax(logits, letter_ids)


def letter_token_ids(encode_fn: Callable[[str], List[int]]) -> List[int]:
    """First token id of each letter (predict_letter id lookup)."""
    out = []
    for fallback, letter in enumerate(LETTERS):
        ids = encode_fn(letter)
        out.append(ids[0] if ids else fallback)
    return out


def bucket_for(n_ids: int, min_bucket: int = 32,
               max_len: int = 1024) -> int:
    """The power-of-two length bucket a prompt of n_ids tokens lands in
    (clamped to [min_bucket, max_len]) — ONE rule shared by the runner
    and the round-16 admission preflight, so the bucket the CLI
    preflights is exactly a bucket the runner will feed."""
    return min(max(1 << (n_ids - 1).bit_length(), min_bucket), max_len)


def materialize_work(by_subject: Dict[str, List[MCQItem]],
                     encode_fn: Callable[[str], List[int]],
                     fewshot_k: int = 0,
                     max_items_per_subject: int = 0,
                     max_len: int = 1024):
    """(work, totals): the exact evaluate() work list — (subject,
    item_no, n_subject, item, token_ids) per item, same shot exclusion
    — encoded ONCE. Split out of evaluate_batched (round 16) so the
    CLI can size its admission preflight from the REAL max bucket and
    then hand the list back without re-encoding every prompt."""
    work = []
    totals: Dict[str, int] = {}
    for subject in sorted(by_subject):
        items = by_subject[subject]
        if max_items_per_subject:
            items = items[:max_items_per_subject]
        shots = items[:fewshot_k] if fewshot_k > 0 else []
        totals[subject] = len(items)
        for n, item in enumerate(items):
            shots_ex = [s for s in shots if s is not item]
            ids = encode_fn(build_prompt(item, shots_ex or None)) or [0]
            work.append((subject, n, len(items), item, ids[-max_len:]))
    return work, totals


def evaluate_batched(by_subject: Dict[str, List[MCQItem]],
                     batched_logits_fn: Callable[[np.ndarray, np.ndarray],
                                                 np.ndarray],
                     encode_fn: Callable[[str], List[int]],
                     fewshot_k: int = 0,
                     progress_fn: Optional[Callable[[str, int, int],
                                                    None]] = None,
                     max_items_per_subject: int = 0,
                     letter_encode_fn: Optional[Callable[[str],
                                                         List[int]]] = None,
                     batch_size: int = 16,
                     max_len: int = 1024,
                     min_bucket: int = 32,
                     work=None) -> MMLUResult:
    """TPU-first runner: identical predictions/reporting to evaluate(),
    but prompts are grouped into power-of-two length buckets and fed
    batch_size at a time — one compiled program per (bucket, batch) shape
    instead of a [1, S] forward per item (the reference runs per-item,
    mmlu_runner.cpp; on the MXU that leaves 15/16ths of the batch
    dimension idle).

    batched_logits_fn(ids [B, S], last_idx [B]) -> [B, V] last-REAL-token
    logits (right-padded rows; last_idx selects the real last token).
    Partial batches are padded by repeating the first row; padded rows'
    predictions are discarded.

    progress_fn fires in BUCKET order (items of different subjects
    interleave), unlike evaluate()'s strict per-subject order — only the
    final reports are order-identical.
    """
    letter_ids = letter_token_ids(letter_encode_fn or encode_fn)
    if work is None:
        work, totals = materialize_work(
            by_subject, encode_fn, fewshot_k=fewshot_k,
            max_items_per_subject=max_items_per_subject,
            max_len=max_len)
    else:
        totals = {}
        for subject, _n, n_sub, _item, _ids in work:
            totals[subject] = n_sub

    by_bucket: Dict[int, list] = {}
    for w in work:
        by_bucket.setdefault(
            bucket_for(len(w[4]), min_bucket, max_len), []).append(w)

    correct: Dict[str, int] = {s: 0 for s in totals}
    for bucket in sorted(by_bucket):
        ws = by_bucket[bucket]
        for i in range(0, len(ws), batch_size):
            chunk = ws[i:i + batch_size]
            B = len(chunk)
            ids = np.zeros((batch_size, bucket), np.int32)
            last = np.zeros((batch_size,), np.int32)
            for r, (_, _, _, _, tok_ids) in enumerate(chunk):
                ids[r, :len(tok_ids)] = tok_ids
                last[r] = len(tok_ids) - 1
            if B < batch_size:       # pad rows: repeat row 0, discard
                ids[B:] = ids[0]
                last[B:] = last[0]
            logits = np.asarray(batched_logits_fn(ids, last))  # [B, V]
            for r, (subject, n, n_sub, item, _) in enumerate(chunk):
                pred = restricted_argmax(logits[r], letter_ids)
                correct[subject] += int(pred == item.answer)
                if progress_fn:
                    progress_fn(subject, n + 1, n_sub)

    return finalize_reports(correct, totals)


def evaluate(by_subject: Dict[str, List[MCQItem]],
             logits_fn: Callable[[np.ndarray], np.ndarray],
             encode_fn: Callable[[str], List[int]],
             fewshot_k: int = 0,
             progress_fn: Optional[Callable[[str, int, int], None]] = None,
             max_items_per_subject: int = 0,
             letter_encode_fn: Optional[Callable[[str], List[int]]] = None
             ) -> MMLUResult:
    # letter_encode_fn: encoder WITHOUT sequence-start decoration for the
    # A-D id lookup (a Gemma-style auto-BOS encoder would make every
    # letter's first token the BOS id); prompts keep using encode_fn.
    letter_ids = letter_token_ids(letter_encode_fn or encode_fn)
    correct_by: Dict[str, int] = {}
    totals: Dict[str, int] = {}
    for subject in sorted(by_subject):
        items = by_subject[subject]
        if max_items_per_subject:
            items = items[:max_items_per_subject]
        shots = items[:fewshot_k] if fewshot_k > 0 else []
        correct = 0
        for n, item in enumerate(items):
            shots_ex = [s for s in shots if s is not item]
            pred = predict_letter(build_prompt(item, shots_ex or None),
                                  logits_fn, encode_fn, letter_ids)
            correct += int(pred == item.answer)
            if progress_fn:
                progress_fn(subject, n + 1, len(items))
        correct_by[subject] = correct
        totals[subject] = len(items)
    return finalize_reports(correct_by, totals)
