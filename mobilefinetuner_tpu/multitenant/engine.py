"""Multi-tenant LoRA training engine: k adapter jobs, ONE base forward
(DESIGN.md §23; mLoRA / LoRAFusion, PAPERS.md).

A million-user product fine-tunes thousands of personal adapters against
the SAME frozen base; running them one CLI process at a time leaves the
memory-bound LoRA step mostly idle and pays inter-job compile/init
bubbles. This engine fuses k jobs into one compiled train step:

  - the adapter bank is a stacked [k, ...] trainable tree
    (lora.stack_adapters layout); each micro-batch row carries its
    adapter id and the ids-routed `_multi_lora` forward
    (models/lora_apply.py) makes per-adapter grads fall out of the
    gather's backward — one base forward serves every tenant's rows;
  - Adam m/v/step are stacked [k, ...] with PER-SLOT step counters, LR,
    and step budgets (optim/adam.multi_adam_update,
    train/trainer.make_multi_train_step) — every per-tenant quantity is
    data, and each tenant's update is numerically the solo step's
    (k-vs-solo parity <= 1e-5, tests/test_multitenant.py);
  - tenant slots are STATIC (the r11 ServeEngine discipline): jobs
    join/leave mid-run as data — admission writes the fresh adapter into
    slot j under ONE jitted `at[j].set` with a traced index and zeroes
    the slot's optimizer state; a finished job's slot refills from the
    pending queue with ZERO retraces (`trace_counts` is the observable);
  - per-tenant data streams multiplex round-robin through per-tenant
    bounded `Prefetcher`s (TenantMux): a stalled tenant cannot starve
    the other k-1 producers or grow unbounded host memory, and the
    step loop's wait is ATTRIBUTED per tenant (wait_ms);
  - each finished adapter saves independently through io/async_ckpt.py
    (bank snapshot -> `lora.unstack_adapter` slot slice -> the SAME
    peft_io writer the solo CLIs use, manifest + lineage + optional
    PEFT export) — a bank-trained adapter is byte-identical on disk to
    a solo-trained one, so serve/AdapterBank.load_file hot-loads it
    manifest-verified with no special casing.

Telemetry rides the existing stream: `tenant` lifecycle events
(admit/save/finish/cancel), per-tenant sections in step_stats
(`tenants` field), and checkpoint events from the shared async writer.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time
from typing import Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.core.telemetry import Telemetry, run_manifest
from mobilefinetuner_tpu.data.prefetch import Prefetcher
from mobilefinetuner_tpu.io import async_ckpt
from mobilefinetuner_tpu.lora import peft_io
from mobilefinetuner_tpu.lora.lora import (assign_adapters,
                                           init_lora_gemma3,
                                           init_lora_gpt2, stack_adapters,
                                           trainable_mask, unstack_adapter)
from mobilefinetuner_tpu.multitenant.jobspec import JobSpec, validate_jobs
from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_rows
from mobilefinetuner_tpu.optim.adam import init_multi_state
from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                               make_multi_train_step)

log = get_logger()

# lock-discipline declaration (core/static_checks.py, DESIGN.md §24):
# threading lives INSIDE the per-tenant Prefetchers (each has its own
# producer thread + bounded queue, declared in data/prefetch.py); the
# mux and the engine itself run entirely on the training loop's thread.
GRAFT_SHARED_STATE = {
    "TenantMux": {
        "lock": None,
        "guarded": [],
        "channels": [],
        "note": "_pf/wait_ms are consumer-thread-only; cross-thread "
                "handoff is each Prefetcher's bounded queue",
    },
    "MultiTenantEngine": {
        "lock": None,
        "guarded": [],
        "channels": [],
        "note": "single-threaded step loop over the TenantMux",
    },
}


@dataclasses.dataclass
class EngineConfig:
    """Engine shape knobs — all STATIC: together they fix the ONE
    compiled train step every tenant shares. Per-job quantities (LR,
    budget, alpha, seeds, save policy) live in JobSpec as data."""
    slots: int = 2            # concurrent adapter jobs per step
    rows_per_tenant: int = 1  # micro-batch rows each tenant contributes
    grad_accum_steps: int = 1
    seq_len: int = 128
    dtype: str = "float32"    # compute dtype
    clip_grad_norm: float = 1.0
    weight_decay: float = 0.0
    schedule: str = "cosine"  # schedule SHAPE is engine-wide (a per-job
                              # branch would retrace); peak LR / warmup /
                              # budget are per-job data
    min_lr_ratio: float = 0.1
    lora_impl: str = "auto"
    skip_nonfinite: bool = False
    prefetch: int = 2         # per-tenant bounded queue depth (0 = sync)
    flush_every: int = 10     # buffered-metrics flush cadence (steps)
    async_save: bool = True
    out_dir: str = ""         # default save root for spec-less save_path
    dropout_seed: int = 1234  # engine-level dropout key (shared dropout
                              # rate comes from the jobs' common value)

    def validate(self) -> None:
        if self.slots < 1 or self.rows_per_tenant < 1 \
                or self.grad_accum_steps < 1:
            raise ValueError(
                "slots, rows_per_tenant, and grad_accum_steps must be "
                ">= 1")
        if self.prefetch < 0 or self.flush_every < 1:
            raise ValueError("prefetch must be >= 0, flush_every >= 1")

    def compute_dtype(self):
        return jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32


class TenantMux:
    """Per-tenant bounded input queues, pulled round-robin (slot order)
    into one combined step batch. Each tenant gets its OWN Prefetcher
    (producer thread + bounded queue of `depth` step batches), so a
    stalled tenant stream (a) never blocks the other k-1 producers and
    (b) never grows unbounded host memory — the step loop still has to
    wait for the straggler's rows (every slot feeds the same compiled
    step), but the wait is ATTRIBUTED: `wait_ms[name]` accumulates
    exactly the time `pull(name)` blocked, which is what the per-tenant
    host_wait attribution in step_stats renders (the fairness
    observable tests/test_multitenant.py pins with an injected slow
    stream)."""

    def __init__(self, depth: int = 2):
        self.depth = max(int(depth), 0)
        self._pf: Dict[str, Prefetcher] = {}
        self.wait_ms: Dict[str, float] = {}

    def add(self, name: str, source: Iterable) -> None:
        if name in self._pf:
            raise ValueError(f"tenant {name!r} already has a stream")
        # lookahead=0: the mux holds HOST batches only (device placement
        # happens when the combined step batch is fed), so the bound on
        # buffered batches per tenant is exactly `depth`
        self._pf[name] = Prefetcher(source, depth=self.depth,
                                    lookahead=0)
        self.wait_ms[name] = 0.0

    def remove(self, name: str) -> None:
        pf = self._pf.pop(name, None)
        if pf is not None:
            pf.close()
        # a departed tenant's residual wait is dropped WITH its stream:
        # the accumulators always describe the current resident set
        self.wait_ms.pop(name, None)

    def pull(self, name: str):
        """Next step batch for `name`; blocks on a stalled producer and
        charges the wait to that tenant alone."""
        t0 = time.perf_counter()
        try:
            batch = next(self._pf[name])
        except StopIteration:
            raise RuntimeError(
                f"tenant {name!r}'s data stream ended before its step "
                f"budget (streams must cycle epochs like "
                f"cli/common.micro_batches)") from None
        self.wait_ms[name] += (time.perf_counter() - t0) * 1000.0
        return batch

    def queue_depth(self, name: Optional[str] = None) -> int:
        if name is not None:
            pf = self._pf.get(name)
            return pf.queue_depth() if pf is not None else 0
        return sum(pf.queue_depth() for pf in self._pf.values())

    def take_waits(self) -> Dict[str, float]:
        """Drain the per-tenant wait accumulators (one flush interval)."""
        out, self.wait_ms = self.wait_ms, {n: 0.0 for n in self.wait_ms}
        return out

    def close(self) -> None:
        for pf in self._pf.values():
            pf.close()
        self._pf.clear()


class _Tenant:
    """One admitted (or pending) job's runtime state."""

    __slots__ = ("spec", "slot", "steps_done", "tokens", "last_loss",
                 "status", "save_path")

    def __init__(self, spec: JobSpec, out_dir: str):
        self.spec = spec
        self.slot = -1
        self.steps_done = 0
        self.tokens = 0           # cumulative valid tokens trained
        self.last_loss: Optional[float] = None
        self.status = "pending"   # pending|active|finished|cancelled
        self.save_path = spec.resolved_save_path(out_dir)


class MultiTenantEngine:
    """Drive with run() (admit -> step until every job finishes) or the
    finer-grained admit_pending()/step() for tests; close() drains the
    async writer and terminates the telemetry stream.

    family: "gpt2" | "gemma"; config: the model config; params: the
    frozen base tree (shared by every tenant, never copied);
    make_stream(job) -> iterator of per-tenant step batches
    ({input_ids/attention_mask/labels} of [rows_per_tenant *
    grad_accum_steps, seq_len]) cycling epochs forever.
    """

    def __init__(self, family: str, config, params, jobs: List[JobSpec],
                 make_stream: Callable[[JobSpec], Iterable],
                 cfg: Optional[EngineConfig] = None,
                 telemetry: Optional[Telemetry] = None):
        cfg = cfg or EngineConfig()
        cfg.validate()
        if family == "gpt2":
            self._init_lora = init_lora_gpt2
            self._forward = _gpt2_forward
            default_init = "gpt2"
        elif family == "gemma":
            self._init_lora = init_lora_gemma3
            self._forward = _gemma_forward
            default_init = "peft"
        else:
            raise ValueError(f"unknown model family {family!r}")
        validate_jobs(jobs)
        self.family = family
        self.config = config
        self.cfg = cfg
        self.params = params
        self.k = cfg.slots
        self._default_init = default_init
        self._dropout = jobs[0].dropout      # shared (validate_jobs)
        self._make_stream = make_stream
        self.tel = telemetry or Telemetry("", enabled=False)
        self.trace_counts: collections.Counter = collections.Counter()

        # the stacked bank: slot shapes come from the SHARED spec (rank/
        # targets validated equal); empty slots are all-zero (delta == 0)
        template = self._init_lora(
            config, jobs[0].lora_spec(default_init), jax.random.PRNGKey(0))
        zero = jax.tree.map(jnp.zeros_like, template)
        self.bank = stack_adapters([zero] * self.k)
        self.mask = trainable_mask(self.bank)
        self._tc = TrainConfig(
            total_steps=1, lr=0.0, warmup_ratio=0.0,
            schedule=cfg.schedule, min_lr_ratio=cfg.min_lr_ratio,
            clip_grad_norm=cfg.clip_grad_norm,
            grad_accum_steps=cfg.grad_accum_steps,
            weight_decay=cfg.weight_decay,
            skip_nonfinite=cfg.skip_nonfinite)
        self.opt = init_multi_state(self.bank, self._tc.adam(), self.k,
                                    self.mask)

        # per-slot schedule/apply arrays: HOST data handed to the step
        # each call — tenant join/leave/budget changes mutate these,
        # never a compiled program
        self._lr = np.zeros(self.k, np.float32)
        self._total = np.ones(self.k, np.float32)
        self._warmup = np.zeros(self.k, np.float32)
        self._step_k = np.zeros(self.k, np.int32)
        self._active = np.zeros(self.k, bool)

        compute_dtype = cfg.compute_dtype()

        def loss_rows(tr, frozen, mb):
            # trace-time only: the compile-stability counter (the jit
            # runs this Python exactly when it traces)
            self.trace_counts["train_step"] += 1
            routed = assign_adapters(tr, mb["adapter_ids"])
            rng = mb["dropout_rng"][0] if "dropout_rng" in mb else None
            logits = self._forward(
                config, frozen, mb, routed, compute_dtype,
                self._dropout, rng, cfg.lora_impl)
            return lm_cross_entropy_rows(logits, mb["labels"])

        self._step_fn = make_multi_train_step(loss_rows, self._tc,
                                              self.k, self.mask)

        def _admit_py(bank, opt, new, j):
            self.trace_counts["admit"] += 1
            bank2 = jax.tree.map(
                lambda b, n: b.at[j].set(jnp.asarray(n).astype(b.dtype)),
                bank, new)
            zero_slot = lambda x: (
                x if x.ndim == 0 or x.shape[0] != self.k
                else x.at[j].set(jnp.zeros_like(x[0])))
            opt2 = dict(opt)
            opt2["step"] = opt["step"].at[j].set(0)
            opt2["m"] = jax.tree.map(zero_slot, opt["m"])
            opt2["v"] = jax.tree.map(zero_slot, opt["v"])
            if "v_hat" in opt:
                opt2["v_hat"] = jax.tree.map(zero_slot, opt["v_hat"])
            return bank2, opt2

        self._admit_jit = jax.jit(_admit_py, donate_argnums=(0, 1))
        self._zero_adapter = jax.tree.map(np.asarray, zero)

        # tenants + slots
        self.tenants: Dict[str, _Tenant] = {
            j.name: _Tenant(j, cfg.out_dir) for j in jobs}
        self.pending: collections.deque = collections.deque(
            self.tenants[j.name] for j in jobs)
        self.slot_tenant: List[Optional[_Tenant]] = [None] * self.k
        self.mux = TenantMux(depth=cfg.prefetch)
        self._zero_batch = None
        self.global_step = 0
        self._buffered: List[tuple] = []   # (gstep, names, metrics)
        self._t_interval = time.perf_counter()
        self._ema: Optional[float] = None
        self._dropout_key = (jax.random.PRNGKey(cfg.dropout_seed)
                             if self._dropout > 0 else None)
        self.ckpt = async_ckpt.AsyncCheckpointer(
            enabled=cfg.async_save, event_sink=self.tel.emit)
        self._closed = False
        self._t_start = time.time()
        self.tel.emit("run_start", **run_manifest(
            {"engine": "multitenant", "family": family,
             "slots": self.k, "jobs": [j.name for j in jobs],
             "rows_per_tenant": cfg.rows_per_tenant,
             "grad_accum_steps": cfg.grad_accum_steps,
             "seq_len": cfg.seq_len, "dtype": cfg.dtype,
             "schedule": cfg.schedule, "lora_impl": cfg.lora_impl},
            None))

    # ------------------------------------------------------------ info ----
    def total_traces(self) -> int:
        return sum(self.trace_counts.values())

    @property
    def active(self) -> List[_Tenant]:
        return [t for t in self.slot_tenant if t is not None]

    def _has_work(self) -> bool:
        return bool(self.pending or self.active)

    # ------------------------------------------------------- admission ----
    def admit_pending(self) -> int:
        """Fill free slots from the pending queue; returns jobs admitted."""
        n = 0
        for j in range(self.k):
            if self.slot_tenant[j] is None and self.pending:
                self._admit(self.pending.popleft(), j)
                n += 1
        return n

    def _admit(self, tenant: _Tenant, j: int) -> None:
        spec = tenant.spec
        fresh = self._init_lora(self.config,
                                spec.lora_spec(self._default_init),
                                jax.random.PRNGKey(spec.seed))
        self.bank, self.opt = self._admit_jit(self.bank, self.opt, fresh,
                                              jnp.int32(j))
        self._lr[j] = spec.lr
        self._total[j] = spec.steps
        self._warmup[j] = spec.warmup_ratio
        self._step_k[j] = 0
        self._active[j] = True
        tenant.slot = j
        tenant.status = "active"
        self.slot_tenant[j] = tenant
        self.mux.add(spec.name, self._make_stream(spec))
        self.tel.emit("tenant", name=spec.name, slot=j, phase="admit",
                      step=0, job_steps=spec.steps, tokens=0, loss=None,
                      path=None, tenant=spec.name)
        log.info(f"tenant {spec.name!r} -> slot {j} "
                 f"(lr={spec.lr:g}, {spec.steps} steps)")

    def _release_slot(self, tenant: _Tenant) -> None:
        """Zero the slot (hygiene: a stale id can only reach a zero
        delta), free it, and refill from the pending queue — all data,
        zero retraces (the same jitted admit writer serves the zeroing
        and the refill)."""
        j = tenant.slot
        self.bank, self.opt = self._admit_jit(self.bank, self.opt,
                                              self._zero_adapter,
                                              jnp.int32(j))
        self._active[j] = False
        self._lr[j] = 0.0
        self.slot_tenant[j] = None
        tenant.slot = -1
        self.mux.remove(tenant.spec.name)
        if self.pending:
            self._admit(self.pending.popleft(), j)

    def cancel(self, name: str) -> None:
        """Cancel a pending or active job (no save); its slot refills."""
        t = self.tenants[name]
        slot = t.slot
        if t.status == "pending":
            self.pending.remove(t)
        elif t.status == "active":
            self._flush_metrics()
            self._release_slot(t)
        else:
            return
        t.status = "cancelled"
        self.tel.emit("tenant", name=name, slot=slot, phase="cancel",
                      step=t.steps_done, job_steps=t.spec.steps,
                      tokens=t.tokens, loss=t.last_loss, path=None,
                      tenant=name)

    # ------------------------------------------------------------ step ----
    def _batch_template(self):
        if self._zero_batch is None:
            rows = self.cfg.rows_per_tenant * self.cfg.grad_accum_steps
            S = self.cfg.seq_len
            self._zero_batch = {
                "input_ids": np.zeros((rows, S), np.int32),
                "attention_mask": np.zeros((rows, S), np.float32),
                "labels": np.zeros((rows, S), np.int32)}
        return self._zero_batch

    def _assemble(self) -> dict:
        """Pull one step batch per active slot (idle slots contribute
        zero rows the masked update ignores) and interleave them so
        `reshape_for_accum` slices accum micro-batches each carrying
        every tenant's rows: row (a, slot, r) -> a*k*b + slot*b + r."""
        A = self.cfg.grad_accum_steps
        b = self.cfg.rows_per_tenant
        S = self.cfg.seq_len
        k = self.k
        per_slot = []
        for j in range(k):
            t = self.slot_tenant[j]
            if t is None:
                per_slot.append(self._batch_template())
            else:
                tb = self.mux.pull(t.spec.name)
                if isinstance(tb, tuple):   # (epoch, batch) generators
                    tb = tb[-1]
                per_slot.append(tb)
        batch = {}
        for key, dt in (("input_ids", np.int32),
                        ("attention_mask", np.float32),
                        ("labels", np.int32)):
            buf = np.empty((A * k * b, S), dt)
            for a in range(A):
                for j, tb in enumerate(per_slot):
                    buf[a * k * b + j * b:a * k * b + (j + 1) * b] = \
                        tb[key][a * b:(a + 1) * b]
            batch[key] = buf
        batch["adapter_ids"] = np.tile(
            np.repeat(np.arange(k, dtype=np.int32), b), A)
        if self._dropout_key is not None:
            batch["dropout_rng"] = jax.random.split(
                jax.random.fold_in(self._dropout_key, self.global_step),
                A * k * b)
        return batch

    def step(self) -> None:
        """One fused optimizer step over every resident tenant, then the
        bookkeeping: per-slot step counters, flush cadence, completions
        (save + refill) at the step boundary."""
        if not self.active:
            self.admit_pending()
            if not self.active:
                return
        batch = self._assemble()
        sched = {"step": jnp.asarray(self._step_k),
                 "total": jnp.asarray(self._total),
                 "lr": jnp.asarray(self._lr),
                 "warmup_ratio": jnp.asarray(self._warmup),
                 "active": jnp.asarray(self._active)}
        self.bank, self.opt, metrics = self._step_fn(
            self.bank, self.params, self.opt, batch, sched)
        names = tuple(t.spec.name if t is not None else None
                      for t in self.slot_tenant)
        self._buffered.append((self.global_step, names, metrics))
        self.global_step += 1
        done: List[_Tenant] = []
        for j, t in enumerate(self.slot_tenant):
            if t is None:
                continue
            self._step_k[j] += 1
            t.steps_done += 1
            spec = t.spec
            if t.steps_done >= spec.steps:
                done.append(t)
            elif spec.save_every and t.steps_done % spec.save_every == 0:
                self._save_tenant(t, final=False)
        if self.global_step % self.cfg.flush_every == 0:
            self._flush_metrics()
        for t in done:
            self._finish(t)

    def run(self) -> None:
        """Admit, step until every job is finished, final flush."""
        self.admit_pending()
        while self._has_work():
            self.step()
        self._flush_metrics()

    # ----------------------------------------------------------- saves ----
    def _save_tenant(self, tenant: _Tenant, final: bool) -> None:
        """One tenant's independent save through the shared async
        writer: blocking part = ONE batched bank snapshot; the slot
        slice, safetensors write (atomic + manifest), lineage record,
        and optional PEFT export run on the writer thread."""
        # off-cadence boundary flush (the run_training save discipline):
        # the tenant event below reports tokens/loss, which only advance
        # at a flush — without this, a save landing before the cadence
        # flush would stamp stale (or zero) progress on a current
        # checkpoint
        self._flush_metrics()
        spec = tenant.spec
        j = tenant.slot
        step = tenant.steps_done
        path = tenant.save_path
        if not final:
            root, ext = os.path.splitext(path)
            path = f"{root}_step{step}{ext}"
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        bank_h, snap_ms = async_ckpt.timed_snapshot(self.bank)
        lspec = spec.lora_spec(self._default_init)
        name = spec.name
        final_path = tenant.save_path

        def write():
            tree = unstack_adapter(bank_h, j)
            peft_io.save_adapter(path, tree, lspec,
                                 extra_metadata={"tenant": name,
                                                 "loop_step": str(step)})
            try:
                from mobilefinetuner_tpu.io.checkpoints import \
                    record_checkpoint
                record_checkpoint(final_path, step, [path],
                                  keep=max(spec.keep_ckpts, 0))
            except Exception as e:
                log.warning(f"tenant {name!r} lineage update failed: {e}")
            if final and spec.peft_export_dir:
                peft_io.export_peft(spec.peft_export_dir, tree, lspec,
                                    self.family)
            return [path]

        async_ckpt.submit(self.ckpt, step, write, final=final,
                          snapshot_ms=snap_ms)
        self.tel.emit("tenant", name=name, slot=j, phase="save",
                      step=step, job_steps=spec.steps,
                      tokens=tenant.tokens, loss=tenant.last_loss,
                      path=path, tenant=name)

    def _finish(self, tenant: _Tenant) -> None:
        self._save_tenant(tenant, final=True)  # flushes first
        tenant.status = "finished"
        self.tel.emit("tenant", name=tenant.spec.name, slot=tenant.slot,
                      phase="finish", step=tenant.steps_done,
                      job_steps=tenant.spec.steps, tokens=tenant.tokens,
                      loss=tenant.last_loss, path=tenant.save_path,
                      tenant=tenant.spec.name)
        log.info(f"tenant {tenant.spec.name!r} finished at step "
                 f"{tenant.steps_done} -> {tenant.save_path}")
        self._release_slot(tenant)

    # --------------------------------------------------------- metrics ----
    def _flush_metrics(self) -> None:
        """One device_get for everything buffered since the last flush
        (the zero-sync invariant): per-slot [k] metric vectors are
        attributed to the tenant resident in that slot AT THAT STEP
        (refills mid-interval keep their history straight), aggregates
        land as a schema-valid step_stats with the per-tenant `tenants`
        section, and the mux's per-tenant wait attribution rides along."""
        if not self._buffered:
            return
        # graftlint: disable=sync-hazard(the zero-sync contract: ONE device_get per metrics flush, DESIGN.md section 23)
        fetched = jax.device_get([m for _, _, m in self._buffered])
        dt_ms = ((time.perf_counter() - self._t_interval) * 1000.0
                 / len(self._buffered))
        waits = self.mux.take_waits()
        tenants_out: Dict[str, dict] = {}
        total_tokens = 0.0
        for (gstep, names, _), m in zip(self._buffered, fetched):
            for j, name in enumerate(names):
                if name is None:
                    continue
                t = self.tenants[name]
                toks = float(m["tokens"][j])
                t.tokens += int(toks)
                total_tokens += toks
                if m["active"][j]:
                    t.last_loss = float(m["loss"][j])
        last = fetched[-1]
        names = self._buffered[-1][1]
        act = [j for j in range(self.k) if names[j] is not None]
        for j in act:
            t = self.tenants[names[j]]
            tenants_out[names[j]] = {
                "slot": j, "step": t.steps_done,
                "loss": t.last_loss, "tokens": t.tokens,
                "wait_ms": round(waits.get(names[j], 0.0), 2)}
        def mean(key):
            vals = [float(last[key][j]) for j in act]
            return sum(vals) / len(vals) if vals else 0.0
        w = np.asarray(last["tokens"], np.float64)
        l = np.asarray(last["loss"], np.float64)
        wsum = float(sum(w[j] for j in act)) or 1.0
        loss = float(sum(l[j] * w[j] for j in act)) / wsum
        self._ema = loss if self._ema is None else \
            0.9 * self._ema + 0.1 * loss
        n_steps = len(self._buffered)
        step_time_s = max(dt_ms / 1000.0, 1e-9)
        self.tel.emit(
            "step_stats", step=self.global_step, loss=loss,
            ema=self._ema, lr=mean("lr"), grad_norm=mean("grad_norm"),
            step_time_ms=dt_ms,
            host_wait_ms=sum(waits.values()) / n_steps,
            slept_ms=None, tok_s=total_tokens / n_steps / step_time_s,
            mfu=None, param_norm=mean("param_norm"),
            update_ratio=mean("update_ratio"),
            nonfinite_count=int(sum(int(last["nonfinite_count"][j])
                                    for j in act)),
            skipped=int(sum(int(fm["skipped"][j]) for fm in fetched
                            for j in act)),
            hbm_mb=None, queue_depth=self.mux.queue_depth(),
            host_step_ms=None, tenants=tenants_out)
        self._buffered.clear()
        self._t_interval = time.perf_counter()

    # -------------------------------------------------------- lifecycle ----
    def close(self, exit_name: str = "ok") -> None:
        """Drain the async writer, stop the tenant producers, terminate
        the stream with run_end (exactly once — idempotent)."""
        if self._closed:
            return
        self._closed = True
        try:
            self._flush_metrics()
            self.ckpt.close(raise_errors=exit_name == "ok")
        finally:
            self.mux.close()
            self.tel.emit("run_end", steps=self.global_step,
                          wall_s=round(time.time() - self._t_start, 3),
                          exit=exit_name, goodput=None)
            self.tel.close()

    def __enter__(self) -> "MultiTenantEngine":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        # the stream records HOW the run ended (the run_training
        # contract): an exception's run_end names its type, and writer
        # errors must not mask it
        self.close("ok" if exc_type is None else exc_type.__name__)


# --------------------------- family forwards --------------------------------

def _gpt2_forward(config, frozen, mb, routed, compute_dtype, dropout,
                  rng, lora_impl):
    from mobilefinetuner_tpu.models import gpt2
    return gpt2.forward(config, frozen, mb["input_ids"],
                        attention_mask=mb["attention_mask"], lora=routed,
                        compute_dtype=compute_dtype, lora_dropout=dropout,
                        dropout_rng=rng, lora_impl=lora_impl)


def _gemma_forward(config, frozen, mb, routed, compute_dtype, dropout,
                   rng, lora_impl):
    from mobilefinetuner_tpu.models import gemma3
    return gemma3.forward(config, frozen, mb["input_ids"],
                          attention_mask=mb["attention_mask"],
                          lora=routed, compute_dtype=compute_dtype,
                          lora_dropout=dropout, dropout_rng=rng,
                          lora_impl=lora_impl)
