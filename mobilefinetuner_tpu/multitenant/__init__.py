from mobilefinetuner_tpu.multitenant.engine import (EngineConfig,
                                                    MultiTenantEngine,
                                                    TenantMux)
from mobilefinetuner_tpu.multitenant.jobspec import (JobSpec,
                                                     load_jobs_file,
                                                     parse_jobs)

__all__ = ["EngineConfig", "MultiTenantEngine", "TenantMux", "JobSpec",
           "load_jobs_file", "parse_jobs"]
