"""Declarative LoRA training-job specs: the multi-tenant admission
interface (DESIGN.md §23, ROADMAP item 5's unlock).

A jobs file describes k independent adapter fine-tuning jobs against ONE
shared frozen base — each job is pure DATA (rank/targets/alpha/dropout,
LR schedule, data stream config, save path + checkpoint policy, step
budget), which is exactly what lets a scheduler multiplex them: the
multi-tenant engine admits JobSpecs into static slots, and everything
that differs between jobs rides the compiled step as arrays, never as a
retrace.

File format (JSON, or TOML via the stdlib tomllib):

    {
      "family": "gpt2",                  # gpt2 | gemma (one base model)
      "defaults": {"rank": 8, "steps": 200, ...},   # optional
      "jobs": [
        {"name": "alice", "lr": 1e-4, "seed": 1,
         "save_path": "out/alice.safetensors"},
        {"name": "bob",   "lr": 3e-4, "alpha": 32.0, "steps": 120}
      ]
    }

Shared-vs-per-job split (the stack_adapters constraint + compile
stability): `rank`, `targets`, and `dropout` must agree across every
job in a file — the adapter bank stacks [k, r, d] factors, so a rank or
target-set mismatch has no slot to live in (validate_jobs raises naming
the offender). `alpha` (scale stacks to [k]), `lr`, `warmup_ratio`,
`steps`, seeds, and the save/checkpoint policy are all per-job data.
The schedule SHAPE (cosine/linear/constant) is engine-wide — a per-job
branch would be a retrace.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

from mobilefinetuner_tpu.lora.lora import LoRASpec


@dataclasses.dataclass
class JobSpec:
    """One adapter job, as data. Everything a slot needs to train,
    checkpoint, and export one tenant's adapter."""
    name: str
    # adapter shape (rank/targets/dropout must match the file's other
    # jobs — the stacked-bank constraint; alpha is per-job data)
    rank: int = 8
    alpha: float = 16.0
    dropout: float = 0.0
    targets: Optional[List[str]] = None      # None = family default
    init: str = ""                           # "" = family default
    # optimization (per-job data riding the compiled step)
    lr: float = 1e-4
    warmup_ratio: float = 0.0
    steps: int = 100                         # step budget; job finishes here
    # data stream config
    seed: int = 0                            # adapter-init seed
    data_seed: int = 0                       # per-epoch shuffle seed
    data_fraction: float = 1.0
    # artifacts + checkpoint policy
    save_path: str = ""                      # "" = <out_dir>/<name>.safetensors
    save_every: int = 0                      # periodic step-tagged saves
    keep_ckpts: int = 0                      # lineage GC (0 = keep all)
    peft_export_dir: str = ""                # also export HF-PEFT layout

    def lora_spec(self, default_init: str) -> LoRASpec:
        return LoRASpec(rank=self.rank, alpha=self.alpha,
                        dropout=self.dropout, targets=self.targets,
                        init=self.init or default_init)

    def resolved_save_path(self, out_dir: str) -> str:
        if self.save_path:
            return self.save_path
        return os.path.join(out_dir or ".", f"{self.name}.safetensors")


_JOB_FIELDS = {f.name for f in dataclasses.fields(JobSpec)}


def _job_from_dict(raw: dict, defaults: dict, index: int) -> JobSpec:
    merged = {**defaults, **raw}
    unknown = sorted(set(merged) - _JOB_FIELDS)
    if unknown:
        raise ValueError(
            f"job #{index} ({merged.get('name', '?')!r}) has unknown "
            f"field(s) {unknown}; valid: {sorted(_JOB_FIELDS)}")
    if not merged.get("name"):
        raise ValueError(f"job #{index} is missing a name")
    spec = JobSpec(**merged)
    if spec.rank < 1 or spec.steps < 1:
        raise ValueError(
            f"job {spec.name!r}: rank and steps must be >= 1 "
            f"(got rank={spec.rank}, steps={spec.steps})")
    if spec.dropout < 0 or spec.dropout >= 1:
        raise ValueError(
            f"job {spec.name!r}: dropout must be in [0, 1), "
            f"got {spec.dropout}")
    return spec


def validate_jobs(jobs: List[JobSpec]) -> None:
    """The stacked-bank constraints: unique names; rank/targets/dropout
    shared across every job (a [k, r, d] bank has exactly one r and one
    target set; dropout is a trace-time constant of the shared step)."""
    if not jobs:
        raise ValueError("jobs file declares no jobs")
    seen: Dict[str, int] = {}
    for i, j in enumerate(jobs):
        if j.name in seen:
            raise ValueError(
                f"duplicate job name {j.name!r} (jobs #{seen[j.name]} "
                f"and #{i})")
        seen[j.name] = i
    ref = jobs[0]
    for j in jobs[1:]:
        for field, shared in (("rank", ref.rank),
                              ("targets", ref.targets),
                              ("dropout", ref.dropout)):
            got = getattr(j, field)
            if got != shared:
                raise ValueError(
                    f"job {j.name!r} has {field}={got!r} but job "
                    f"{ref.name!r} has {shared!r}: the stacked adapter "
                    f"bank shares one rank/target-set/dropout across "
                    f"all tenants (alpha/lr/steps are per-job) — split "
                    f"mismatched jobs into separate runs")


def parse_jobs(doc: dict) -> Tuple[str, List[JobSpec]]:
    """(family, validated jobs) from a parsed jobs document."""
    family = doc.get("family", "gpt2")
    if family not in ("gpt2", "gemma"):
        raise ValueError(f"family must be gpt2|gemma, got {family!r}")
    raw_jobs = doc.get("jobs")
    if not isinstance(raw_jobs, list) or not raw_jobs:
        raise ValueError("jobs file needs a non-empty 'jobs' list")
    defaults = doc.get("defaults", {})
    if not isinstance(defaults, dict):
        raise ValueError("'defaults' must be a table/object")
    jobs = [_job_from_dict(r, defaults, i) for i, r in enumerate(raw_jobs)]
    validate_jobs(jobs)
    return family, jobs


def load_jobs_file(path: str) -> Tuple[str, List[JobSpec]]:
    """Parse a .json or .toml jobs file -> (family, jobs)."""
    if path.endswith(".toml"):
        try:
            import tomllib
        except ModuleNotFoundError:     # py<3.11: the tomllib backport
            import tomli as tomllib
        with open(path, "rb") as f:
            doc = tomllib.load(f)
    else:
        with open(path) as f:
            doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: jobs file must be a JSON object / "
                         f"TOML document")
    return parse_jobs(doc)
