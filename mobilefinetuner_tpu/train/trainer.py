"""Jitted train/eval step builders: value_and_grad + lax.scan gradient
accumulation + clip + LR schedule + Adam, one XLA program per optimizer step.

Re-design of the reference trainer loops (reference: optim/trainer.{h,cpp}
`LoRATrainer`, optim/gemma_trainer.{h,cpp} `GemmaLoRATrainer`, and the inline
loop in gpt2_lora_finetune/main.cpp:561-684): where the reference runs
per-micro-batch Python-level forward/backward with loss scaled by 1/accum
(main.cpp:569-583), we scan over the micro-batch axis INSIDE the compiled
step — micro-batches stream through one compiled block, gradients accumulate
in registers/HBM, and the optimizer update happens in the same program
(no host round-trips inside an optimizer step).

Generic over "what is trainable": LoRA training passes the LoRA tree as
`trainable` and the frozen base params as `frozen`; full fine-tuning passes
the model params as `trainable`. The loss_fn contract is
loss_fn(trainable, frozen, micro_batch) -> scalar loss.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from mobilefinetuner_tpu.optim.adam import (AdamConfig, adam_update,
                                            clip_by_global_norm, init_state)
from mobilefinetuner_tpu.optim.schedule import lr_schedule


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    total_steps: int = 1000
    lr: float = 1e-4
    warmup_ratio: float = 0.03
    schedule: str = "cosine"  # cosine | linear | constant
    min_lr_ratio: float = 0.1
    clip_grad_norm: float = 1.0
    grad_accum_steps: int = 1
    weight_decay: float = 0.0
    coupled_weight_decay: bool = False
    amsgrad: bool = False
    # guarded update (--skip_nonfinite, DESIGN.md §20): when the step's
    # gradients carry any non-finite element (or the global grad norm is
    # non-finite), the Adam update degenerates to identity — params and
    # optimizer state pass through via a jnp.where tree-select inside the
    # SAME compiled program (donation and AOT shardings untouched, the
    # LR schedule still advances with the loop step), and a `skipped`
    # flag rides the buffered metrics with zero added host syncs.
    skip_nonfinite: bool = False

    def adam(self) -> AdamConfig:
        return AdamConfig(lr=self.lr, weight_decay=self.weight_decay,
                          coupled_weight_decay=self.coupled_weight_decay,
                          amsgrad=self.amsgrad)


def reshape_for_accum(batch: dict, accum: int) -> dict:
    """[accum*micro_b, ...] arrays -> [accum, micro_b, ...] for lax.scan.

    The step's accum and the data stream's accum are allowed to differ:
    the memory-admission degradation ladder (cli/common.run_training,
    DESIGN.md §21) rebuilds the step with DOUBLED grad_accum_steps at
    constant global batch — the same [rows, ...] step batch simply
    scans as twice as many half-size micro-batches, so batch shapes,
    shardings, and donation are untouched and only float reassociation
    moves (loss parity <=1e-5). The divisibility assert below is the
    ladder's gate: a rung that cannot split further is skipped."""
    def r(x):
        total = x.shape[0]
        assert total % accum == 0, (total, accum)
        return x.reshape(accum, total // accum, *x.shape[1:])
    return jax.tree.map(r, batch)


def make_train_step(loss_fn: Callable[[Any, Any, dict], tuple],
                    train_cfg: TrainConfig,
                    mask: Optional[Any] = None,
                    donate: bool = True,
                    in_shardings=None, out_shardings=None):
    """Build the jitted optimizer step.

    loss_fn(trainable, frozen, micro_batch) -> (sum_loss, weight): the SUM
    of per-token losses and the token count (or any weight). Accumulation
    sums both across micro-batches and divides once at the end, so the
    update equals the gradient of total_loss/total_weight over the whole
    batch — exact even when micro-batches have unequal valid-token counts
    (masked labels), unlike mean-of-means accumulation. (The reference
    scales each micro loss by 1/accum, main.cpp:569-583, which has the
    mean-of-means bias; we keep the exact semantics.)

    Returns step_fn(trainable, frozen, opt_state, batch, step) ->
    (trainable, opt_state, metrics) where batch leaves are
    [accum*micro_b, ...] and step is the 0-based optimizer step index
    (drives the LR schedule as a traced value — no recompiles).
    metrics = {loss, grad_norm, lr} (scalars, pre-clip global norm as in
    main.cpp:490-516) plus the on-device train-health scalars
    {param_norm, update_ratio, nonfinite_count, skipped} (`skipped` is
    1 exactly when the skip_nonfinite guard turned this update into
    identity, else 0): ||w|| over the
    trainable leaves (pre-update — measured inside the optimizer kernel
    so the donated tree's lifetime is untouched), the step's relative
    update size ||Δw||/||w||, and the global count of non-finite
    gradient elements. All of them are device scalars that
    ride the step loop's buffered-metrics path (cli/common.run_training
    pulls the whole buffer in ONE device_get per flush), so health
    monitoring adds zero per-step host syncs — the telemetry
    zero-sync invariant (DESIGN.md §13).
    """
    accum = train_cfg.grad_accum_steps
    adam_cfg = train_cfg.adam()

    def step_fn(trainable, frozen, opt_state, batch, step):
        batch = dict(batch)
        # fault-injection seam (--inject grad_nan, cli/common.py): when
        # armed, every batch carries a [B] "grad_scale" row (1.0 on
        # clean steps, NaN in the poison window) that multiplies the
        # accumulated gradients INSIDE the compiled step — the honest
        # way to produce non-finite grads end to end. [B]-shaped so it
        # shards like every other batch leaf; absent on normal runs
        # (the key changes the compiled program, never per-step work).
        gscale = batch.pop("grad_scale", None)
        micro = reshape_for_accum(batch, accum)

        def sum_fn(tr, mb):
            s, w = loss_fn(tr, frozen, mb)
            return s, w

        vg = jax.value_and_grad(sum_fn, has_aux=True)

        def body(carry, mb):
            g_acc, loss_acc, w_acc = carry
            (s, w), g = vg(trainable, mb)
            g_acc = jax.tree.map(jnp.add, g_acc, g)
            return (g_acc, loss_acc + s,
                    w_acc + w.astype(jnp.float32)), None

        g0 = jax.tree.map(lambda t: jnp.zeros(t.shape, jnp.float32),
                          trainable)
        (g_sum, loss_sum, w_sum), _ = jax.lax.scan(
            body, (g0, jnp.float32(0.0), jnp.float32(0.0)), micro)
        inv = 1.0 / jnp.maximum(w_sum, 1.0)
        grads = jax.tree.map(lambda g: g * inv, g_sum)
        if gscale is not None:
            s = gscale.reshape(-1)[0].astype(jnp.float32)
            grads = jax.tree.map(lambda g: g * s, grads)
        loss = loss_sum * inv
        # health: count non-finite grad elements BEFORE clipping (clip
        # propagates a NaN norm into every element, which would turn one
        # bad value into "all of them")
        nonfinite = sum(jnp.sum(~jnp.isfinite(g))
                        for g in jax.tree.leaves(grads))
        if train_cfg.clip_grad_norm and train_cfg.clip_grad_norm > 0:
            grads, norm = clip_by_global_norm(grads,
                                              train_cfg.clip_grad_norm)
        else:
            from mobilefinetuner_tpu.optim.adam import global_norm
            norm = global_norm(grads)
        lr = lr_schedule(step, train_cfg.total_steps, train_cfg.lr,
                         train_cfg.warmup_ratio, train_cfg.schedule,
                         train_cfg.min_lr_ratio)
        with jax.named_scope("optimizer"):
            # ||Δw|| and pre-update ||w|| come from INSIDE the update
            # (adam_update with_norms), where the delta already exists —
            # a post-hoc new-minus-old subtraction would keep the donated
            # pre-update tree alive past the in-place write and cost a
            # params-sized peak-HBM bump on full fine-tunes.
            trainable2, opt_state2, (upd_norm, w_norm) = adam_update(
                grads, opt_state, trainable, adam_cfg, lr, mask,
                with_norms=True)
        if train_cfg.skip_nonfinite:
            # guarded update: a scalar `bad` predicate selects, per leaf,
            # the PRE-update tree (params, Adam m/v AND Adam's own step
            # counter — a skipped step must not advance bias correction).
            # On clean steps jnp.where(False, old, new) IS `new`
            # bitwise, so the guard is numerically free — a guarded
            # clean run's loss trajectory is byte-identical to an
            # unguarded one (tests/test_recovery.py pins it). The
            # select happens inside the same compiled program: output
            # structure/shardings are unchanged, donation stays legal.
            bad = (nonfinite > 0) | ~jnp.isfinite(norm)
            keep = lambda old, new: jnp.where(bad, old, new)
            trainable2 = jax.tree.map(keep, trainable, trainable2)
            opt_state2 = jax.tree.map(keep, opt_state, opt_state2)
            skipped = bad.astype(jnp.int32)
        else:
            skipped = jnp.zeros((), jnp.int32)
        metrics = {"loss": loss, "grad_norm": norm, "lr": lr,
                   "param_norm": w_norm,
                   "update_ratio": upd_norm / jnp.maximum(w_norm, 1e-20),
                   "nonfinite_count": nonfinite.astype(jnp.int32),
                   "skipped": skipped}
        return trainable2, opt_state2, metrics

    donate_argnums = (0, 2) if donate else ()
    return jax.jit(step_fn, donate_argnums=donate_argnums,
                   in_shardings=in_shardings, out_shardings=out_shardings)


def make_multi_train_step(loss_rows_fn: Callable[[Any, Any, dict], tuple],
                          train_cfg: TrainConfig, k: int,
                          mask: Optional[Any] = None,
                          donate: bool = True):
    """The multi-tenant optimizer step: k independent LoRA jobs through
    ONE compiled program (DESIGN.md §23, mobilefinetuner_tpu/multitenant/).

    loss_rows_fn(stacked_trainable, frozen, micro_batch) -> (row_nll_sums
    [R], row_token_counts [R]): per-ROW loss over a micro-batch whose
    every row carries its adapter id in micro_batch["adapter_ids"] [R]
    (the ids-routed `_multi_lora` forward — models/lora_apply.py — makes
    per-adapter grads fall out of the per-row gather's backward: slot j's
    gradient is the scatter-add of exactly its own rows' contributions).

    Per-tenant exactness (the k-vs-solo parity oracle): the scan
    accumulates UNNORMALIZED per-slot loss/token sums plus the grads of
    the total row-sum, then normalizes slot j's gradient by slot j's OWN
    token count, clips by slot j's own pre-clip norm, schedules slot j's
    own LR from its own step counter, and applies a per-slot Adam update
    with per-slot bias correction (optim/adam.multi_adam_update) — every
    per-slot quantity is the solo step's formula with the batch axis
    re-labelled, so adapter j's trajectory matches a solo run on the
    same data/seed to float-reassociation noise (<= 1e-5, pinned by
    tests/test_multitenant.py).

    step_fn(trainable, frozen, opt_state, batch, sched) ->
    (trainable, opt_state, metrics): `sched` carries the per-slot [k]
    DATA arrays {step, total, lr, warmup_ratio, active} — tenant
    join/leave/refill, per-job budgets, and per-job LR schedules never
    retrace. Inactive slots (active=False) contribute dummy rows whose
    grads are computed and discarded: params, Adam m/v, AND the slot's
    Adam step counter pass through untouched, so a refilled slot starts
    from a genuinely fresh optimizer state. metrics are per-slot [k]
    vectors (loss, grad_norm, lr, tokens, nonfinite_count, skipped,
    param_norm, update_ratio) riding the caller's buffered-metrics path
    (one device_get per flush, the zero-sync telemetry invariant).
    """
    from mobilefinetuner_tpu.optim.adam import (clip_by_slot_norm,
                                                multi_adam_update,
                                                slot_norms)
    from mobilefinetuner_tpu.optim.schedule import multi_lr_schedule
    accum = train_cfg.grad_accum_steps
    adam_cfg = train_cfg.adam()

    def step_fn(trainable, frozen, opt_state, batch, sched):
        micro = reshape_for_accum(dict(batch), accum)

        def sum_fn(tr, mb):
            s_rows, w_rows = loss_rows_fn(tr, frozen, mb)
            return s_rows.sum(), (s_rows, w_rows)

        vg = jax.value_and_grad(sum_fn, has_aux=True)

        def seg(rows, ids):
            return jnp.zeros((k,), jnp.float32).at[ids].add(
                rows.astype(jnp.float32))

        def body(carry, mb):
            g_acc, loss_k, w_k = carry
            (_, (s_rows, w_rows)), g = vg(trainable, mb)
            ids = mb["adapter_ids"]
            g_acc = jax.tree.map(jnp.add, g_acc, g)
            return (g_acc, loss_k + seg(s_rows, ids),
                    w_k + seg(w_rows, ids)), None

        g0 = jax.tree.map(lambda t: jnp.zeros(t.shape, jnp.float32),
                          trainable)
        z = jnp.zeros((k,), jnp.float32)
        (g_sum, loss_sum_k, w_k), _ = jax.lax.scan(body, (g0, z, z), micro)
        inv = 1.0 / jnp.maximum(w_k, 1.0)                       # [k]
        bsel = lambda v, x: v.reshape((k,) + (1,) * (x.ndim - 1))
        grads = jax.tree.map(lambda g: g * bsel(inv, g), g_sum)
        loss_k = loss_sum_k * inv
        # per-slot non-finite census BEFORE clipping (a NaN norm would
        # smear one bad slot's poison over its whole tree — and per-slot
        # isolation is the point: tenant j's NaN must not gate tenant i)
        nonfinite_k = None
        for g in jax.tree.leaves(grads):
            s = jnp.sum(~jnp.isfinite(g), axis=tuple(range(1, g.ndim)))
            nonfinite_k = s if nonfinite_k is None else nonfinite_k + s
        if train_cfg.clip_grad_norm and train_cfg.clip_grad_norm > 0:
            grads, norm_k = clip_by_slot_norm(grads,
                                              train_cfg.clip_grad_norm)
        else:
            norm_k = slot_norms(grads)
        lr_k = multi_lr_schedule(sched["step"], sched["total"],
                                 sched["lr"], sched["warmup_ratio"],
                                 train_cfg.schedule,
                                 train_cfg.min_lr_ratio)
        active = jnp.asarray(sched["active"]).astype(bool)        # [k]
        apply_k = active
        if train_cfg.skip_nonfinite:
            bad = (nonfinite_k > 0) | ~jnp.isfinite(norm_k)
            apply_k = active & ~bad
            skipped = (active & bad).astype(jnp.int32)
        else:
            skipped = jnp.zeros((k,), jnp.int32)
        with jax.named_scope("optimizer"):
            trainable2, opt_state2, (upd_k, wn_k) = multi_adam_update(
                grads, opt_state, trainable, adam_cfg, lr_k, apply_k,
                mask, with_norms=True)
        metrics = {"loss": loss_k, "grad_norm": norm_k, "lr": lr_k,
                   "tokens": w_k,
                   "param_norm": wn_k,
                   "update_ratio": upd_k / jnp.maximum(wn_k, 1e-20),
                   "nonfinite_count": nonfinite_k.astype(jnp.int32),
                   "skipped": skipped,
                   "active": active.astype(jnp.int32)}
        return trainable2, opt_state2, metrics

    donate_argnums = (0, 2) if donate else ()
    return jax.jit(step_fn, donate_argnums=donate_argnums)


def make_eval_step(nll_fn: Callable[[Any, Any, dict], tuple]):
    """Jitted eval step: nll_fn(trainable, frozen, batch) ->
    (sum_nll, token_count). Token-weighted accumulation is the caller's job
    (eval_ppl.cpp:157-200 semantics)."""
    @jax.jit
    def eval_step(trainable, frozen, batch):
        return nll_fn(trainable, frozen, batch)
    return eval_step


def init_optimizer(trainable, train_cfg: TrainConfig,
                   mask: Optional[Any] = None) -> dict:
    return init_state(trainable, train_cfg.adam(), mask)


# The trainer's timing hook for the fleet-observability layer
# (DESIGN.md §14): the step loop records each completed optimizer step's
# wall seconds (deliberate idleness — governor sleep, input wait —
# excluded by the caller) and the straggler-attribution cadence gathers
# `median_ms()` across hosts via `parallel.distributed.allgather_scalars`.
# ONE implementation serves both it and the hang watchdog's deadline
# window, so it lives in core/telemetry (no jax dependency) and is
# re-exported here as the training-facing surface.
from mobilefinetuner_tpu.core.telemetry import StepClock  # noqa: E402,F401
