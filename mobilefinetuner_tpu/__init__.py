"""mobilefinetuner_tpu — a TPU-native LLM fine-tuning framework.

A from-scratch JAX/XLA rebuild with the capabilities of the MobileFineTuner
reference (C++ CPU mobile fine-tuning framework): end-to-end LoRA and full
fine-tuning of GPT-2 (small/medium/large/xl) and Gemma-3 (270M/1B) on
WikiText-2, HF-compatible SafeTensors weight/adapter I/O, PEFT-format adapter
save/resume, perplexity + MMLU evaluation, gradient accumulation, FSDP-style
parameter/grad/optimizer-state sharding over a TPU mesh (the TPU-native
equivalent of the reference's single-device disk-offload ParameterSharder),
host-RAM offload, and a deterministic step governor (the reference's
energy-aware throttler re-imagined as a duty-cycle knob).

Layer map (TPU-native re-design of the reference's L0-L10; see SURVEY.md):
  - L0-L3 (memory pools, autograd engine, hand-written kernels) collapse into
    JAX/XLA: `jnp` ops + autodiff + the XLA allocator; `ops/` holds only what
    XLA does not give us for free (fused LM loss with internal label shift,
    flash attention via Pallas, RoPE).
  - L4-L5 models are pure-functional pytree modules (`models/`).
  - L6 data/tokenizers are host-side (`data/`), with native C++ fast paths.
  - L7 optimizers/trainers: `optim/`, `train/`.
  - L8 CLIs: `cli/`.
  - L9 system optimizations: `parallel/` (FSDP, offload), `train/governor.py`.
"""

__version__ = "0.1.0"
