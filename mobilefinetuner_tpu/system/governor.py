"""Step-pacing governor: deterministic throttle / duty-cycle control for the
training loop.

TPU-native analog of the reference's energy-aware PowerMonitor
(reference: operators/opt_ops/energy/power_monitor.{h,cpp}): every
`check_interval_steps` steps, telemetry (battery %, temperature °C) maps to a
target step frequency, and the trainer sleeps `suggest_sleep_ms(step)` between
optimizer steps:

  f_batt = freq_batt_low  if battery < battery_threshold else freq_batt_high
  f_temp = freq_temp_low  if temp    > temp_threshold    else freq_temp_high
  f      = min(f_batt, f_temp);  sleep_ms = 1000 / f, clamped to 5000
  (power_monitor.cpp:72-96)

A deterministic override schedule string "0-99:300,100-199:150,200-:50"
(step-range -> sleep_ms) takes precedence over telemetry
(power_monitor.cpp:28-70). Telemetry can be injected manually for platforms
without sensors (power_monitor.h:47-48) — on a TPU host there is no battery,
so manual injection / schedule mode is the normal use; the governor is a
duty-cycle knob for shared-host politeness and for reproducing the
reference's energy benchmarks (scripts/benchmark/test_energy_function.sh).
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Callable, List, Optional

MAX_SLEEP_MS = 5000.0  # clamp, power_monitor.cpp:92


@dataclasses.dataclass
class StepSleep:
    """One parsed schedule range: steps in [start, end] sleep `sleep_ms`.
    end=None means open-ended ("200-:50")."""
    start: int
    end: Optional[int]
    sleep_ms: float

    def covers(self, step: int) -> bool:
        return step >= self.start and (self.end is None or step <= self.end)


def parse_schedule(spec: str) -> List[StepSleep]:
    """Parse "0-99:300,100-199:150,200-:50" (power_monitor.cpp:28-70).

    Each entry is "<start>-<end>:<ms>" or "<start>-:<ms>" (open-ended).
    A bare "<step>:<ms>" pins a single step. Whitespace tolerated.
    """
    out: List[StepSleep] = []
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.fullmatch(r"(\d+)\s*-\s*(\d*)\s*:\s*(\d+(?:\.\d+)?)", part)
        if m:
            start, end_s, ms = m.group(1), m.group(2), m.group(3)
            out.append(StepSleep(int(start),
                                 int(end_s) if end_s else None, float(ms)))
            continue
        m = re.fullmatch(r"(\d+)\s*:\s*(\d+(?:\.\d+)?)", part)
        if m:
            s = int(m.group(1))
            out.append(StepSleep(s, s, float(m.group(2))))
            continue
        raise ValueError(f"bad schedule entry: {part!r}")
    return out


@dataclasses.dataclass
class GovernorConfig:
    """Mirror of the reference PowerConfig (power_monitor.h:20-35)."""
    enable: bool = False
    check_interval_steps: int = 10    # <= 0 disables the telemetry policy
    battery_threshold: float = 20.0   # percent
    temp_threshold: float = 40.0      # celsius
    freq_batt_high: float = 10.0      # steps/sec when battery healthy
    freq_batt_low: float = 1.0        # steps/sec when battery low
    freq_temp_high: float = 10.0
    freq_temp_low: float = 0.5
    schedule: str = ""                # deterministic override
    manual_battery: Optional[float] = None
    manual_temp: Optional[float] = None


class StepGovernor:
    """suggest_sleep_ms(step) -> ms to sleep after this optimizer step.

    Telemetry readers default to the manual injections in the config; a real
    platform can pass `battery_fn` / `temp_fn` callables.

    `event_sink`: optional callable(dict); a throttle() that actually
    sleeps reports {step, sleep_ms, battery, temp, source} through it —
    the run-telemetry `throttle` event (core/telemetry.py), so duty-cycle
    decisions that silently stretch step time become visible in the
    event stream instead of looking like a slow device. Events fire on
    DECISION CHANGES (a different sleep_ms or source than the last
    emitted), not per sleeping step: a steady `--pm_schedule "0-:100"`
    run emits ONE event, not one per step — the event stream stays
    small (telemetry's own sizing rule), while the per-interval sleep
    TOTAL rides in step_stats.slept_ms.
    """

    def __init__(self, config: GovernorConfig,
                 battery_fn: Optional[Callable[[], float]] = None,
                 temp_fn: Optional[Callable[[], float]] = None,
                 event_sink: Optional[Callable[[dict], object]] = None):
        self.config = config
        self._schedule = parse_schedule(config.schedule)
        self._battery_fn = battery_fn
        self._temp_fn = temp_fn
        self._event_sink = event_sink
        self._cached_sleep_ms = 0.0
        self._last_check_step: Optional[int] = None
        # last SAMPLED sensor values (set by _telemetry_sleep_ms) — the
        # throttle event reports these instead of re-reading possibly
        # expensive sensor callables outside the check cadence
        self._last_battery: Optional[float] = None
        self._last_temp: Optional[float] = None
        self._last_emitted = None  # (sleep_ms, source) of the last event
        # run-total deliberate idleness, independently clocked from the
        # goodput meter's governor_sleep bucket; run_end carries it as
        # governor_slept_ms (cli/common.end_run) so a post-mortem can
        # cross-check the two (the per-flush slept_ms in step_stats is
        # interval-scoped and resets)
        self.total_slept_ms = 0.0

    # -- telemetry ----------------------------------------------------------
    def set_manual_telemetry(self, battery: Optional[float] = None,
                             temp: Optional[float] = None):
        """Manual injection (power_monitor.h:47-48)."""
        if battery is not None:
            self.config.manual_battery = battery
        if temp is not None:
            self.config.manual_temp = temp
        self._last_check_step = None  # force re-evaluation next step

    def _read_battery(self) -> Optional[float]:
        if self.config.manual_battery is not None:
            return self.config.manual_battery
        return self._battery_fn() if self._battery_fn else None

    def _read_temp(self) -> Optional[float]:
        if self.config.manual_temp is not None:
            return self.config.manual_temp
        return self._temp_fn() if self._temp_fn else None

    # -- policy -------------------------------------------------------------
    def _sensor_snapshot(self):
        """(battery, temp) for event payloads WITHOUT touching the sensor
        callables: manual injections are free to read; fn-backed sensors
        report their last sample from the check cadence (None before the
        first check) — the event must not defeat check_interval_steps'
        rate limiting."""
        batt = (self.config.manual_battery
                if self.config.manual_battery is not None
                else self._last_battery)
        temp = (self.config.manual_temp
                if self.config.manual_temp is not None
                else self._last_temp)
        return batt, temp

    def _telemetry_sleep_ms(self) -> float:
        c = self.config
        battery, temp = self._read_battery(), self._read_temp()
        self._last_battery, self._last_temp = battery, temp
        f_batt = (c.freq_batt_low if (battery is not None
                                      and battery < c.battery_threshold)
                  else c.freq_batt_high)
        f_temp = (c.freq_temp_low if (temp is not None
                                      and temp > c.temp_threshold)
                  else c.freq_temp_high)
        f = min(f_batt, f_temp)
        if f <= 0:
            return MAX_SLEEP_MS
        return min(1000.0 / f, MAX_SLEEP_MS)

    def suggest_sleep_ms(self, step: int) -> float:
        if not self.config.enable:
            return 0.0
        for rng in self._schedule:  # schedule overrides telemetry
            if rng.covers(step):
                return min(rng.sleep_ms, MAX_SLEEP_MS)
        # Uncovered steps fall through to the telemetry policy (the
        # reference PowerMonitor does the same, power_monitor.cpp
        # suggest_sleep_ms), so --pm_schedule composes with --pm_interval.
        # check_interval_steps <= 0 disables telemetry entirely, so a
        # schedule-only config runs uncovered steps at full speed.
        if self.config.check_interval_steps <= 0:
            return 0.0
        k = max(self.config.check_interval_steps, 1)
        if (self._last_check_step is None
                or step - self._last_check_step >= k):
            self._cached_sleep_ms = self._telemetry_sleep_ms()
            self._last_check_step = step
        return self._cached_sleep_ms

    def throttle(self, step: int):
        """Sleep per policy (trainer call site; gemma_trainer.cpp loop,
        gpt2_lora_finetune/main.cpp:679-683). A non-zero sleep whose
        DECISION differs from the last emitted one first reports it AND
        its inputs through event_sink, so the telemetry stream records
        why steps are being stretched without growing per-step."""
        ms = self.suggest_sleep_ms(step)
        if ms > 0:
            if self._event_sink is not None:
                src = ("schedule"
                       if any(r.covers(step) for r in self._schedule)
                       else "telemetry")
                if (ms, src) != self._last_emitted:
                    self._last_emitted = (ms, src)
                    batt, temp = self._sensor_snapshot()
                    self._event_sink({
                        "step": step, "sleep_ms": ms, "battery": batt,
                        "temp": temp, "source": src})
            self.total_slept_ms += ms
            time.sleep(ms / 1000.0)
        return ms
