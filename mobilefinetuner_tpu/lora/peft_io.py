"""Adapter save/load: native format (fast resume) + PEFT-compatible export.

Reference: graph/lora_saver.{h,cpp} — PEFT-compatible safetensors of adapter
weights with rank/alpha/dropout metadata in the safetensors header, plus
`load_safetensors -> attach_from_state` for resume. We mirror both:

  - native format: keys `blocks.{target}.{A|B}` holding the stacked
    [L, ...] arrays, spec in the header metadata — exact, single-blob resume;
  - PEFT export/import: per-layer `base_model.model.<hf_module_path>.
    lora_A.weight` ([r, in], torch layout) + `adapter_config.json`, loadable
    by HF PEFT on the matching base model.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.io.safetensors_io import (SafeTensorsReader,
                                                   save_safetensors)
from mobilefinetuner_tpu.lora.lora import LoRASpec

# target name -> HF module path fragment (PEFT keys prepend
# "base_model.model." and append ".lora_A.weight"/".lora_B.weight")
GPT2_PEFT_MODULES = {
    "attn_qkv": "transformer.h.{}.attn.c_attn",
    "attn_proj": "transformer.h.{}.attn.c_proj",
    "mlp_fc_in": "transformer.h.{}.mlp.c_fc",
    "mlp_fc_out": "transformer.h.{}.mlp.c_proj",
}
GEMMA_PEFT_MODULES = {
    t: "model.layers.{}.self_attn." + t for t in
    ("q_proj", "k_proj", "v_proj", "o_proj")
}
GEMMA_PEFT_MODULES.update({
    t: "model.layers.{}.mlp." + t for t in
    ("gate_proj", "up_proj", "down_proj")
})
# For adapter_config.json target_modules. PEFT suffix-matches these against
# full module paths, so they must be path-qualified: a bare "c_proj" would
# match BOTH attn.c_proj and mlp.c_proj and make PEFT instantiate phantom
# adapters the safetensors has no weights for.
PEFT_TARGET_MODULES = {
    "attn_qkv": "attn.c_attn", "attn_proj": "attn.c_proj",
    "mlp_fc_in": "mlp.c_fc", "mlp_fc_out": "mlp.c_proj",
}


# ----------------------------- native format --------------------------------

def save_adapter(path: str, lora_tree, spec: LoRASpec,
                 extra_metadata: Optional[Dict[str, str]] = None):
    """Native adapter safetensors: stacked arrays + spec metadata.
    Atomically published via save_safetensors (tmp + fsync + rename) —
    a crash mid-save leaves the previous adapter intact."""
    tensors = {}
    for name, entry in lora_tree["blocks"].items():
        tensors[f"blocks.{name}.A"] = np.asarray(entry["A"],
                                                 dtype=np.float32)
        tensors[f"blocks.{name}.B"] = np.asarray(entry["B"],
                                                 dtype=np.float32)
    md = spec.to_metadata()
    md["format"] = "mobilefinetuner_tpu.lora.v1"
    if extra_metadata:
        md.update(extra_metadata)
    save_safetensors(path, tensors, metadata=md)


def load_adapter(path: str) -> Tuple[dict, LoRASpec]:
    """Load a native adapter -> (lora_tree, spec). Resume analog of the
    reference's attach_from_state (lora_saver.h:16-46)."""
    reader = SafeTensorsReader(path)
    spec = LoRASpec.from_metadata(reader.metadata)
    blocks: dict = {}
    for key in reader.keys():
        assert key.startswith("blocks."), key
        _, name, leaf = key.split(".")
        blocks.setdefault(name, {})[leaf] = jnp.asarray(reader.load(key))
    for name in blocks:
        blocks[name]["scale"] = jnp.asarray(spec.scale, jnp.float32)
    spec.targets = sorted(blocks)
    return {"blocks": blocks}, spec


# ----------------------------- PEFT export ----------------------------------

def export_peft(out_dir: str, lora_tree, spec: LoRASpec, family: str,
                base_model_name: str = ""):
    """Write adapter_model.safetensors + adapter_config.json loadable by HF
    PEFT. A/B are stored in torch nn.Linear layout: lora_A.weight [r, in],
    lora_B.weight [out, r] (our stacked layout is A [L, in, r], B [L, r, out]
    → transpose per layer)."""
    modules = (GPT2_PEFT_MODULES if family == "gpt2"
               else GEMMA_PEFT_MODULES)
    unsupported = sorted(set(lora_tree["blocks"]) - set(modules))
    if unsupported:
        raise ValueError(
            f"targets {unsupported} have no PEFT representation (HF PEFT "
            f"cannot express column-sliced adapters on the fused c_attn; "
            f"reference split-QKV uses its own key scheme too, "
            f"lora_saver.cpp make_peft_key) — use the native adapter "
            f"format for split-QKV runs")
    os.makedirs(out_dir, exist_ok=True)
    tensors = {}
    for name, entry in lora_tree["blocks"].items():
        A = np.asarray(entry["A"], dtype=np.float32)
        B = np.asarray(entry["B"], dtype=np.float32)
        L = A.shape[0]
        for i in range(L):
            mod = "base_model.model." + modules[name].format(i)
            tensors[mod + ".lora_A.weight"] = A[i].T.copy()
            tensors[mod + ".lora_B.weight"] = B[i].T.copy()
    save_safetensors(os.path.join(out_dir, "adapter_model.safetensors"),
                     tensors, metadata={"format": "pt"})
    if family == "gpt2":
        target_modules = sorted({PEFT_TARGET_MODULES[t]
                                 for t in lora_tree["blocks"]})
        fan_in_fan_out = True  # GPT-2 Conv1D
    else:
        target_modules = sorted(lora_tree["blocks"])
        fan_in_fan_out = False
    cfg = {
        "peft_type": "LORA", "task_type": "CAUSAL_LM",
        "base_model_name_or_path": base_model_name,
        "r": spec.rank, "lora_alpha": spec.alpha,
        "lora_dropout": spec.dropout, "bias": "none",
        "fan_in_fan_out": fan_in_fan_out,
        "target_modules": target_modules,
        "inference_mode": False,
    }
    from mobilefinetuner_tpu.io.safetensors_io import atomic_publish
    cfg_path = os.path.join(out_dir, "adapter_config.json")
    with atomic_publish(cfg_path) as tmp:  # crash-safe like the tensors
        with open(tmp, "w") as f:
            json.dump(cfg, f, indent=2)


def import_peft(adapter_dir: str, family: str) -> Tuple[dict, LoRASpec]:
    """Load an HF-PEFT adapter dir into our stacked lora_tree."""
    with open(os.path.join(adapter_dir, "adapter_config.json")) as f:
        cfg = json.load(f)
    spec = LoRASpec(rank=cfg["r"], alpha=cfg["lora_alpha"],
                    dropout=cfg.get("lora_dropout", 0.0), init="peft")
    path = os.path.join(adapter_dir, "adapter_model.safetensors")
    raw = SafeTensorsReader(path).load_all(promote_to_f32=True)
    modules = (GPT2_PEFT_MODULES if family == "gpt2"
               else GEMMA_PEFT_MODULES)
    blocks: dict = {}
    for name, fmt in modules.items():
        per_layer_A, per_layer_B = [], []
        i = 0
        while True:
            mod = "base_model.model." + fmt.format(i)
            ka, kb = mod + ".lora_A.weight", mod + ".lora_B.weight"
            if ka not in raw:
                break
            per_layer_A.append(raw[ka].T)
            per_layer_B.append(raw[kb].T)
            i += 1
        if per_layer_A:
            blocks[name] = {
                "A": jnp.asarray(np.stack(per_layer_A)),
                "B": jnp.asarray(np.stack(per_layer_B)),
                "scale": jnp.asarray(spec.scale, jnp.float32),
            }
    spec.targets = sorted(blocks)
    return {"blocks": blocks}, spec
