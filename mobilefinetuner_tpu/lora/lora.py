"""LoRA: spec, initialization, merge/unmerge, trainable-subset partitioning.

TPU-native re-design of the reference's LoRA machinery
(reference: graph/lora_injector.{h,cpp} for GPT-2,
graph/gemma_lora_injector.{h,cpp} for Gemma, nn/lora_linear.{h,cpp}).
The reference wraps each linear in a LoRALinear module holding pointers to
the frozen base weight; here LoRA is a *separate pytree* of stacked per-layer
A/B factors that the model forward adds functionally
(y = x@W + scale·(x@A@B)), so:
  - base params stay frozen by construction (grads are taken w.r.t. the LoRA
    tree only via jax.grad argnums),
  - FSDP can shard base params independently of the tiny trainable tree
    (SURVEY.md §7 hard part (c)),
  - merge/unmerge is a pure pytree->pytree function.

Entry layout per target: {"A": [L, in, r], "B": [L, r, out], "scale": ()}
with scale = alpha/rank (lora_injector.h:29-71). "scale" leaves are
non-trainable: forward stop-gradients them and trainable_mask() excludes
them from optimizer updates.

Init parity (SURVEY.md §2.5):
  - gpt2 style: A ~ N(0, 1/sqrt(r)), B = 0 (lora_injector.cpp:18-42) — but
    seeded jax.random instead of the reference's std::random_device
    (SURVEY.md §2.12.6: the reference is non-reproducible; we are).
  - peft style (Gemma, gemma_lora_injector.cpp:31): kaiming_uniform(a=√5)
    on A = U(-1/sqrt(in), 1/sqrt(in)) scaled by gain for fan_in; B = 0.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# GPT-2 target name -> (in_dim_fn, out_dim_fn) over config
GPT2_TARGETS = {
    "attn_qkv": lambda c: (c.n_embd, 3 * c.n_embd),
    # split-QKV: separate adapters on the q/k/v column ranges of the fused
    # c_attn projection (reference: lora_injector.h:169-191 Hook
    # col_offset/col_size) — finer-grained than the fused default
    "attn_q": lambda c: (c.n_embd, c.n_embd),
    "attn_k": lambda c: (c.n_embd, c.n_embd),
    "attn_v": lambda c: (c.n_embd, c.n_embd),
    "attn_proj": lambda c: (c.n_embd, c.n_embd),
    "mlp_fc_in": lambda c: (c.n_embd, 4 * c.n_embd),
    "mlp_fc_out": lambda c: (4 * c.n_embd, c.n_embd),
    # head adapter on the tied lm_head (logits = x @ wte^T): a SINGLE
    # unstacked site — A [E, r], B [r, V] — applied at the logits
    # projection. Opt-in (never part of the defaults/presets): its delta
    # rides the chunked-CE/fused-CE epilogue so [B, S, V] never
    # materializes in training (DESIGN.md §17); merge is refused (the
    # table is tied — folding ΔW in would change the input lookup too).
    "lm_head": lambda c: (c.n_embd, c.vocab_size),
}
# column slot of each split target within the fused [E, 3E] c_attn weight
GPT2_SPLIT_QKV_SLOTS = {"attn_q": 0, "attn_k": 1, "attn_v": 2}
# Default PEFT-aligned GPT-2 topology: fused c_attn + c_proj
# (reference: gpt2_lora_finetune/main.cpp:381-390).
GPT2_DEFAULT_TARGETS = ["attn_qkv", "attn_proj"]

GEMMA_TARGETS = {
    "q_proj": lambda c: (c.hidden_size, c.num_attention_heads * c.head_dim),
    "k_proj": lambda c: (c.hidden_size, c.num_key_value_heads * c.head_dim),
    "v_proj": lambda c: (c.hidden_size, c.num_key_value_heads * c.head_dim),
    "o_proj": lambda c: (c.num_attention_heads * c.head_dim, c.hidden_size),
    "gate_proj": lambda c: (c.hidden_size, c.intermediate_size),
    "up_proj": lambda c: (c.hidden_size, c.intermediate_size),
    "down_proj": lambda c: (c.intermediate_size, c.hidden_size),
    "lm_head": lambda c: (c.hidden_size, c.vocab_size),  # tied embed head
}
# targets with ONE site instead of a per-layer stack: A [in, r],
# B [r, out] (no leading L axis; maybe_lora's ndim checks skip the
# layer_idx slice for them)
UNSTACKED_TARGETS = frozenset({"lm_head"})
# Target presets (reference: gemma_lora_injector.h:9-34). lm_head is
# opt-in only — "full" keeps the reference's per-layer target set.
GEMMA_PRESETS = {
    "full": [t for t in GEMMA_TARGETS if t not in UNSTACKED_TARGETS],
    "attn": ["q_proj", "k_proj", "v_proj", "o_proj"],
    "light": ["q_proj", "v_proj"],
}


@dataclasses.dataclass
class LoRASpec:
    rank: int = 8
    alpha: float = 16.0
    dropout: float = 0.0
    targets: Optional[List[str]] = None
    init: str = "gpt2"  # "gpt2" | "peft"

    @property
    def scale(self) -> float:
        return self.alpha / self.rank

    def to_metadata(self) -> Dict[str, str]:
        return {"lora_rank": str(self.rank), "lora_alpha": str(self.alpha),
                "lora_dropout": str(self.dropout),
                "lora_targets": ",".join(self.targets or []),
                "lora_init": self.init}

    @classmethod
    def from_metadata(cls, md: Dict[str, str]) -> "LoRASpec":
        return cls(rank=int(md["lora_rank"]),
                   alpha=float(md["lora_alpha"]),
                   dropout=float(md.get("lora_dropout", 0.0)),
                   targets=[t for t in md.get("lora_targets", "").split(",")
                            if t],
                   init=md.get("lora_init", "gpt2"))


def _init_A(key, shape, style: str, dtype):
    """shape = [L, in, r]."""
    _, fan_in, r = shape
    if style == "peft":
        # torch kaiming_uniform_(a=sqrt(5)) on a [r, in] matrix:
        # bound = sqrt(3) * (1/sqrt(5+1) gain...) — torch computes
        # gain = sqrt(2/(1+a^2)) = sqrt(1/3), std = gain/sqrt(fan_in),
        # bound = sqrt(3)*std = 1/sqrt(fan_in).
        bound = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -bound, bound)
    # reference GPT-2 init: N(0, 1/sqrt(r)) (lora_injector.cpp:18-42)
    return (jax.random.normal(key, shape) / math.sqrt(r)).astype(dtype)


def init_lora(target_dims: Dict[str, Tuple[int, int]], n_layers: int,
              spec: LoRASpec, key: jax.Array, dtype=jnp.float32) -> dict:
    """Build the stacked LoRA pytree for the given targets."""
    tree = {}
    keys = jax.random.split(key, max(len(target_dims), 1))
    for k, name in zip(keys, sorted(target_dims)):
        fan_in, fan_out = target_dims[name]
        if name in UNSTACKED_TARGETS:  # single site, no layer stack
            tree[name] = {
                "A": _init_A(k, (1, fan_in, spec.rank), spec.init,
                             dtype)[0],
                "B": jnp.zeros((spec.rank, fan_out), dtype),
                "scale": jnp.asarray(spec.scale, dtype),
            }
            continue
        tree[name] = {
            "A": _init_A(k, (n_layers, fan_in, spec.rank), spec.init, dtype),
            "B": jnp.zeros((n_layers, spec.rank, fan_out), dtype),
            "scale": jnp.asarray(spec.scale, dtype),
        }
    return {"blocks": tree}


def init_lora_gpt2(config, spec: LoRASpec, key: jax.Array,
                   dtype=jnp.float32) -> dict:
    targets = spec.targets or GPT2_DEFAULT_TARGETS
    dims = {t: GPT2_TARGETS[t](config) for t in targets}
    return init_lora(dims, config.n_layer, spec, key, dtype)


def init_lora_gemma3(config, spec: LoRASpec, key: jax.Array,
                     dtype=jnp.float32) -> dict:
    targets = spec.targets or GEMMA_PRESETS["full"]
    if isinstance(targets, str):
        targets = GEMMA_PRESETS[targets]
    dims = {t: GEMMA_TARGETS[t](config) for t in targets}
    return init_lora(dims, config.num_hidden_layers, spec, key, dtype)


def stack_adapters(loras) -> dict:
    """Stack N same-shaped adapter trees along a new leading ADAPTER axis
    (multi-adapter batched serving, models/lora_apply.py). All adapters
    must share rank and target set; scale stacks to [N] so per-adapter
    alpha/r survives."""
    if not loras:
        raise ValueError("stack_adapters needs at least one adapter")
    ref = jax.tree.structure(loras[0])
    ref_flat = jax.tree_util.tree_flatten_with_path(loras[0])[0]
    for i, t in enumerate(loras[1:], 1):
        if jax.tree.structure(t) != ref:
            names = sorted(t.get("blocks", {})) if isinstance(t, dict) \
                else []
            ref_names = sorted(loras[0].get("blocks", {}))
            raise ValueError(
                f"adapter {i} has different targets/structure than "
                f"adapter 0: targets {names} vs {ref_names} "
                f"(multi-adapter serving needs identical rank + target "
                f"sets)")
        flat = jax.tree_util.tree_flatten_with_path(t)[0]
        for (path, x0), (_, xi) in zip(ref_flat, flat):
            if x0.shape != xi.shape:
                # keystr spelling varies across jax versions; build the
                # path by hand for a stable message
                leaf = "".join(str(p) for p in path)
                raise ValueError(
                    f"adapter {i} leaf {leaf} has shape "
                    f"{tuple(xi.shape)} but adapter 0 has "
                    f"{tuple(x0.shape)} (rank/dim mismatch — stacked "
                    f"serving needs identical shapes)")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *loras)


def unstack_adapter(stacked: dict, index: int) -> dict:
    """Inverse of one stack_adapters slot: slice adapter `index` out of a
    stacked [k, ...] bank back to the solo tree layout (A [L, in, r],
    B [L, r, out], scalar scale). The multi-tenant engine's save path
    uses this so a bank-trained adapter round-trips through peft_io
    BYTE-IDENTICAL to a solo-trained one (tests/test_multitenant.py pins
    the file bytes) — the serve/eval/PEFT consumers never learn the
    adapter was trained in a bank. Routing `ids` leaves (assign_adapters)
    are dropped: they are batch data, not adapter state."""
    first = next(iter(stacked["blocks"].values()))
    # .shape on the leaf directly: this runs on the async writer thread
    # over HOST snapshots, and a jnp.asarray just to read a dimension
    # would copy the whole stacked bank to the device
    n = int(first["A"].shape[0])
    if not (0 <= index < n):
        raise ValueError(
            f"adapter index {index} out of range for a stacked bank of "
            f"{n} adapter(s) (valid: 0..{n - 1})")
    out = dict(stacked)
    out["blocks"] = {
        name: {leaf: v[index] for leaf, v in entry.items()
               if leaf != "ids"}
        for name, entry in stacked["blocks"].items()}
    return out


def assign_adapters(stacked: dict, adapter_ids) -> dict:
    """Route batch rows to adapters: insert the per-row index array into
    every site entry of a stack_adapters tree. SERVING/EVAL only: the
    returned tree drops into the models' `lora=` argument for forwards
    and generation, but it is not a trainable tree (the int32 "ids" leaf
    cannot be differentiated, and routing indices are not parameters —
    trainable_mask excludes them).

    Concrete ids are validated against the stacked bank size here — a
    jnp gather CLAMPS out-of-range indices, so an id typo would silently
    serve every overflowing row from the LAST adapter in the bank (the
    worst possible failure for multi-tenant routing: tenant A quietly
    gets tenant Z's weights). Traced ids (the serve engine routes inside
    its compiled step) skip the check; the engine's bank resolution is
    the validator there."""
    ids = jnp.asarray(adapter_ids, jnp.int32)
    first = next(iter(stacked["blocks"].values()))
    n = int(first["A"].shape[0])
    if not isinstance(ids, jax.core.Tracer):
        concrete = np.asarray(ids)
        bad = concrete[(concrete < 0) | (concrete >= n)]
        if bad.size:
            raise ValueError(
                f"adapter id(s) {sorted(set(int(b) for b in bad))} out "
                f"of range for a stacked bank of {n} adapter(s) "
                f"(valid: 0..{n - 1})")
    out = dict(stacked)
    out["blocks"] = {name: dict(entry, ids=ids)
                     for name, entry in stacked["blocks"].items()}
    return out


def trainable_mask(lora_tree) -> dict:
    """Pytree of bools: True for trainable leaves (A/B), False for scale
    and for multi-adapter routing ids. Feed to the optimizer so those are
    never updated/decayed."""
    # tree_util spelling: jax.tree.map_with_path only exists on newer jax
    return jax.tree_util.tree_map_with_path(
        lambda path, _: not (path and getattr(path[-1], "key", None)
                             in ("scale", "ids")),
        lora_tree)


def num_trainable(lora_tree) -> int:
    mask = trainable_mask(lora_tree)
    return sum(int(x.size) for x, m in
               zip(jax.tree.leaves(lora_tree), jax.tree.leaves(mask)) if m)


def _delta_w(entry) -> jnp.ndarray:
    """[L, in, out] = scale * A @ B per layer."""
    return entry["scale"] * jnp.einsum("lir,lro->lio", entry["A"],
                                       entry["B"])


# name of the base-weight leaf each target modifies, per model family;
# an optional third element is the column slot within the fused weight
# (split-QKV, lora_injector.h:169-191)
_GPT2_BASE = {"attn_qkv": ("attn", "qkv_w"), "attn_proj": ("attn", "proj_w"),
              "mlp_fc_in": ("mlp", "fc_w"), "mlp_fc_out": ("mlp", "proj_w"),
              **{name: ("attn", "qkv_w", slot)
                 for name, slot in GPT2_SPLIT_QKV_SLOTS.items()}}
_GEMMA_BASE = {"q_proj": ("attn", "q_w"), "k_proj": ("attn", "k_w"),
               "v_proj": ("attn", "v_w"), "o_proj": ("attn", "o_w"),
               "gate_proj": ("mlp", "gate_w"), "up_proj": ("mlp", "up_w"),
               "down_proj": ("mlp", "down_w")}


def _merge(params, lora_tree, base_map, sign: float):
    """params + sign * ΔW on every LoRA'd base weight (functional).
    Split targets add their ΔW into the matching column range of the
    fused weight."""
    params = jax.tree.map(jnp.asarray, params)
    blocks = dict(params["blocks"])
    groups = {g: dict(blocks[g]) for g in {v[0] for v in base_map.values()}}
    for name, entry in lora_tree["blocks"].items():
        if name not in base_map:
            raise ValueError(
                f"target {name!r} cannot be merged into the base "
                f"weights (the lm_head is TIED to the embedding table — "
                f"folding its ΔW in would change the input lookup too); "
                f"serve it dynamically via the lora= argument")
        spec = base_map[name]
        group, leaf = spec[0], spec[1]
        w = groups[group][leaf]
        delta = sign * _delta_w(entry).astype(w.dtype)
        if len(spec) == 3:
            out = delta.shape[-1]
            col0 = spec[2] * out
            w = w.at[:, :, col0:col0 + out].add(delta)
        else:
            w = w + delta
        groups[group][leaf] = w
    blocks.update(groups)
    out = dict(params)
    out["blocks"] = blocks
    return out


def merge_gpt2(params, lora_tree):
    """Fold ΔW into base weights (reference: lora_linear.cpp:109-176
    merge; used by eval with --merge)."""
    return _merge(params, lora_tree, _GPT2_BASE, +1.0)


def unmerge_gpt2(params, lora_tree):
    return _merge(params, lora_tree, _GPT2_BASE, -1.0)


def merge_gemma3(params, lora_tree):
    return _merge(params, lora_tree, _GEMMA_BASE, +1.0)


def unmerge_gemma3(params, lora_tree):
    return _merge(params, lora_tree, _GEMMA_BASE, -1.0)
