"""WikiText-2 data pipeline: concat-lines + EOS + fixed-length chunking,
with in-RAM, streaming-window, and pretokenized-binary modes.

Behavioral spec mirrors the reference's WikiText2Dataset
(reference: data/wikitext2_dataset.{h,cpp}):
  - lines are tokenized and concatenated with an EOS inserted after each
    line (HF-aligned; wikitext2_dataset.cpp chunking);
  - fixed seq_len chunks at `stride` intervals (stride == seq_len ->
    no overlap; smaller stride -> overlapping chunks whose overlapping
    prefix is label-masked to -100, wikitext2_dataset.h:27-39);
  - three modes (wikitext2_dataset.h:36-39, :92-111): (a) in-RAM,
    (b) streaming — prescan the file for per-line token offsets, keep only
    a bounded token window in RAM, re-tokenize on demand,
    (c) pretokenized .bin + meta.json (np.memmap; producer:
    `pretokenize()` below, analog of scripts/pretokenize_wikitext2_gemma.py);
  - per-epoch seeded shuffle of chunk order (wikitext2_dataset.cpp:266-268,
    seeded mt19937 — here np.random.Generator, equally reproducible);
  - batches {input_ids i32 [B,S], attention_mask f32 [B,S], labels i32
    [B,S] with pad = -100} (wikitext2_dataset.h:44-48);
  - data_fraction / drop_last (wikitext2_dataset.h:27-39).

Tokenizer-agnostic: pass any `encode_fn(str)->List[int]` + eos/pad ids
(the reference ctor's encode_fn hook, wikitext2_dataset.h:53-54).
"""

from __future__ import annotations

import bisect
import dataclasses
import json
import os
import random
import time
from typing import Callable, Iterator, List, Optional

import numpy as np

IGNORE_INDEX = -100

_SPLIT_FILENAMES = {
    "train": ("wiki.train.tokens", "wiki.train.raw", "train.txt"),
    "valid": ("wiki.valid.tokens", "wiki.valid.raw", "valid.txt",
              "validation.txt"),
    "test": ("wiki.test.tokens", "wiki.test.raw", "test.txt"),
}


def resolve_split_file(path: str, split: str) -> str:
    """`path` may be a file (used directly) or a wikitext dir."""
    if os.path.isfile(path):
        return path
    for name in _SPLIT_FILENAMES[split]:
        p = os.path.join(path, name)
        if os.path.exists(p):
            return p
    raise FileNotFoundError(f"no {split} split under {path}")


@dataclasses.dataclass
class WT2Config:
    seq_len: int = 128
    batch_size: int = 4
    stride: Optional[int] = None  # None -> seq_len (no overlap)
    data_fraction: float = 1.0
    drop_last: bool = True
    shuffle: bool = True
    seed: int = 42
    streaming: bool = False
    window_tokens: int = 100_000  # streaming-mode resident window
    # transient-I/O resilience for the streaming refetch (--data_retries/
    # --data_backoff_s): a fleet's shared filesystem hiccup (NFS/GCS
    # stall, ESTALE) must cost a bounded backoff, not the run. 0 = fail
    # fast (pre-round-13 behavior).
    retries: int = 0
    retry_backoff_s: float = 0.5


class WikiText2Dataset:
    def __init__(self, path: str, split: str, config: WT2Config,
                 encode_fn: Callable[[str], List[int]], eos_id: int,
                 pad_id: Optional[int] = None,
                 pretokenized_bin: Optional[str] = None):
        self.config = config
        self.eos_id = eos_id
        self.pad_id = eos_id if pad_id is None else pad_id
        self.encode_fn = encode_fn
        self._tokens: Optional[np.ndarray] = None
        self._epoch = 0
        # retry telemetry hook: run_training points this at a closure
        # emitting `anomaly`{kind=data_retry} events so a surviving I/O
        # hiccup leaves a record instead of being invisible. Called from
        # whatever thread runs the fetch (the prefetch producer);
        # Telemetry.emit is lock-serialized, so that is safe.
        self.event_sink: Optional[Callable[..., None]] = None

        if pretokenized_bin is not None:
            meta_path = pretokenized_bin + ".meta.json"
            if not os.path.exists(meta_path):
                meta_path = os.path.join(
                    os.path.dirname(pretokenized_bin), "meta.json")
            with open(meta_path) as f:
                meta = json.load(f)
            dtype = np.dtype(meta.get("dtype", "int32"))
            self._tokens = np.memmap(pretokenized_bin, dtype=dtype,
                                     mode="r")
            total = int(meta.get("count", len(self._tokens)))
            self._total_tokens = min(total, len(self._tokens))
        else:
            file = resolve_split_file(path, split)
            self._file = file
            if config.streaming:
                self._prescan(file)
            else:
                ids: List[int] = []
                with open(file, encoding="utf-8") as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if not line.strip():
                            continue
                        ids.extend(encode_fn(line))
                        ids.append(eos_id)
                self._tokens = np.asarray(ids, dtype=np.int32)
                self._total_tokens = len(ids)

        if config.data_fraction < 1.0:
            self._total_tokens = max(
                int(self._total_tokens * config.data_fraction),
                config.seq_len + 1)

        stride = config.stride or config.seq_len
        assert 0 < stride <= config.seq_len
        self._stride = stride
        n_full = max((self._total_tokens - config.seq_len) // stride + 1, 0)
        has_tail = (n_full == 0 or
                    (self._total_tokens - config.seq_len) % stride != 0)
        if config.drop_last or self._total_tokens < config.seq_len:
            self.num_chunks = n_full
        else:
            self.num_chunks = n_full + (1 if has_tail else 0)
        if self.num_chunks == 0 and self._total_tokens > 1:
            self.num_chunks = 1  # single short chunk, padded

    # -- streaming machinery -------------------------------------------------

    def _prescan(self, file: str):
        """Token-offset prescan: cumulative token count per line, without
        keeping tokens (wikitext2_dataset.cpp:230-249 semantics)."""
        offsets = [0]
        lines_pos: List[int] = []
        with self._open_text(file) as f:
            pos = f.tell()
            for line in iter(f.readline, ""):
                stripped = line.rstrip("\n")
                if stripped.strip():
                    lines_pos.append(pos)
                    n = len(self.encode_fn(stripped)) + 1  # +1 for EOS
                    offsets.append(offsets[-1] + n)
                pos = f.tell()
        self._line_offsets = offsets  # len = n_lines + 1
        self._line_pos = lines_pos
        self._total_tokens = offsets[-1]
        self._win_start = 0
        self._win_tokens = np.empty(0, dtype=np.int32)

    def _open_text(self, path: str):
        """Source-file open, factored so tests can inject transient I/O
        faults (and so an alternative storage layer can interpose)."""
        return open(path, encoding="utf-8")

    def _io_retry(self, fn, what: str):
        """Run `fn` under the bounded-retry policy (`config.retries`,
        exponential backoff with jitter): a transient I/O error on the
        streaming refetch path — a shared-filesystem stall under a
        whole fleet rereading the same corpus — costs a backoff and an
        `anomaly`{kind=data_retry} event instead of killing the run.
        The jitter desynchronizes a fleet whose hosts all hit the same
        hiccup at once. After the budget, the ORIGINAL error raises."""
        cfg = self.config
        first_err: Optional[OSError] = None
        for attempt in range(max(cfg.retries, 0) + 1):
            try:
                return fn()
            except OSError as e:
                # keep the FIRST error: it names the root cause (an
                # ESTALE), while later attempts often fail with
                # follow-on noise (the mount is simply gone)
                first_err = first_err or e
                if attempt >= max(cfg.retries, 0):
                    raise first_err
                delay = cfg.retry_backoff_s * (2 ** attempt)
                delay *= 1.0 + 0.25 * random.random()
                if self.event_sink is not None:
                    try:
                        self.event_sink(
                            kind="data_retry", attempt=attempt + 1,
                            error=f"{type(e).__name__}: {e}", what=what,
                            backoff_s=round(delay, 3))
                    except Exception:
                        pass  # telemetry must never break the pipeline
                time.sleep(delay)

    def _window_fetch(self, start: int, end: int) -> np.ndarray:
        """Return tokens[start:end] by re-tokenizing the covering lines,
        keeping a bounded resident window. The refetch I/O retries
        transient errors under `_io_retry` (each attempt restarts the
        window read from scratch — partial token lists never leak into
        the resident window)."""
        ws, we = self._win_start, self._win_start + len(self._win_tokens)
        if start >= ws and end <= we:
            return self._win_tokens[start - ws:end - ws]
        # recompute a window beginning at the line containing `start`
        li = bisect.bisect_right(self._line_offsets, start) - 1
        win_start_tok = self._line_offsets[li]
        want = max(end - win_start_tok, self.config.window_tokens)

        def read_window() -> List[int]:
            toks: List[int] = []
            with self._open_text(self._file) as f:
                j = li
                while j < len(self._line_pos) and len(toks) < want:
                    f.seek(self._line_pos[j])
                    line = f.readline().rstrip("\n")
                    toks.extend(self.encode_fn(line))
                    toks.append(self.eos_id)
                    j += 1
            return toks

        toks = self._io_retry(read_window, what="window_fetch")
        self._win_start = win_start_tok
        self._win_tokens = np.asarray(toks, dtype=np.int32)
        ws = self._win_start
        return self._win_tokens[start - ws:end - ws]

    # -- chunk/batch API -----------------------------------------------------

    def _chunk_tokens(self, idx: int) -> np.ndarray:
        start = idx * self._stride
        end = min(start + self.config.seq_len, self._total_tokens)
        if self._tokens is not None:
            return np.asarray(self._tokens[start:end], dtype=np.int32)
        return self._window_fetch(start, end)

    def chunk(self, idx: int):
        """(input_ids, attention_mask, labels) for one chunk, padded to
        seq_len."""
        S = self.config.seq_len
        toks = self._chunk_tokens(idx)
        n = len(toks)
        input_ids = np.full(S, self.pad_id, dtype=np.int32)
        input_ids[:n] = toks
        mask = np.zeros(S, dtype=np.float32)
        mask[:n] = 1.0
        labels = np.full(S, IGNORE_INDEX, dtype=np.int32)
        labels[:n] = toks
        if idx > 0 and self._stride < S:
            # overlapping prefix is context only — matches sliding-window
            # PPL convention
            labels[:S - self._stride] = IGNORE_INDEX
        return input_ids, mask, labels

    def num_batches(self) -> int:
        b = self.config.batch_size
        if self.config.drop_last:
            return self.num_chunks // b
        return (self.num_chunks + b - 1) // b

    def chunk_order(self, epoch: int) -> np.ndarray:
        """The epoch's chunk visitation order: seeded per-epoch shuffle
        (wikitext2_dataset.cpp:266-268 analog). Exposed so batch builders
        that assemble multi-micro-batch step buffers directly
        (cli/common.micro_batches, the prefetch producer) share the EXACT
        order `epoch()` uses — the determinism contract of the async
        input pipeline hangs off this single function."""
        order = np.arange(self.num_chunks)
        if self.config.shuffle:
            rng = np.random.default_rng(self.config.seed + epoch)
            if self.config.streaming and self._tokens is None:
                # window-local shuffle: permute blocks of window-resident
                # chunks, and chunks within each block, so nearly every
                # access hits the resident window instead of re-tokenizing
                # ~window_tokens per chunk
                per_block = max(self.config.window_tokens
                                // max(self._stride, 1), 1)
                blocks = [order[i:i + per_block]
                          for i in range(0, len(order), per_block)]
                for b in blocks:
                    rng.shuffle(b)
                bidx = np.arange(len(blocks))
                rng.shuffle(bidx)
                order = np.concatenate([blocks[i] for i in bidx]) \
                    if blocks else order
            else:
                rng.shuffle(order)
        return order

    def fill_rows(self, idxs, input_ids: np.ndarray, mask: np.ndarray,
                  labels: np.ndarray, row0: int = 0) -> None:
        """Write chunks `idxs` into rows [row0, row0+len(idxs)) of
        preallocated [N, S] batch arrays — the allocation-free core of
        batch assembly (`epoch()` and `micro_batches` both build on it,
        so a step buffer is filled ONCE instead of stack-then-concat)."""
        for j, ci in enumerate(idxs):
            i_row, m_row, l_row = self.chunk(int(ci))
            input_ids[row0 + j] = i_row
            mask[row0 + j] = m_row
            labels[row0 + j] = l_row

    def epoch(self, epoch: Optional[int] = None,
              start_batch: int = 0) -> Iterator[dict]:
        """Yield batches for one epoch; chunk order reshuffled per epoch
        from (seed, epoch) (`chunk_order`). start_batch skips ahead
        without building the skipped batches (checkpoint-resume
        fast-forward)."""
        if epoch is None:
            epoch = self._epoch
            self._epoch += 1
        order = self.chunk_order(epoch)
        b = self.config.batch_size
        S = self.config.seq_len
        nb = self.num_batches()
        for bi in range(start_batch, nb):
            idxs = order[bi * b:(bi + 1) * b]
            n = len(idxs)
            batch = {"input_ids": np.empty((n, S), np.int32),
                     "attention_mask": np.empty((n, S), np.float32),
                     "labels": np.empty((n, S), np.int32)}
            self.fill_rows(idxs, batch["input_ids"],
                           batch["attention_mask"], batch["labels"])
            yield batch

    def total_valid_tokens(self) -> int:
        return self._total_tokens


def pretokenize(input_file: str, encode_fn: Callable[[str], List[int]],
                eos_id: int, out_bin: str):
    """Offline pretokenization -> .bin + .bin.meta.json
    (scripts/pretokenize_wikitext2_gemma.py analog)."""
    count = 0
    with open(out_bin, "wb") as out:
        with open(input_file, encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line.strip():
                    continue
                ids = encode_fn(line) + [eos_id]
                np.asarray(ids, dtype=np.int32).tofile(out)
                count += len(ids)
    with open(out_bin + ".meta.json", "w") as f:
        json.dump({"dtype": "int32", "count": count, "eos_id": eos_id}, f)
    return count
