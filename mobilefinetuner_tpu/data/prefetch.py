"""Async double-buffered input pipeline: bounded-queue background batch
producer + device-placement lookahead.

The optimizer step on TPU dispatches asynchronously, so the only thing
that can stall the device between steps is the HOST: tokenization (the
streaming-window refetch in data/wikitext2.py re-encodes lines on a
window miss), step-batch assembly across grad-accum micro-batches, and
the blocking shard/`device_put` before the compiled step can be fed.
This module takes all of that off the step loop's critical path:

  stage 1 — producer thread: runs the existing host-side batch generator
      (`cli/common.micro_batches`, `WikiText2Dataset.epoch`) into a
      bounded FIFO queue (`depth` items). ONE thread consumes the
      generator, so the queue order IS the generator order — the
      determinism contract below costs nothing.
  stage 2 — device lookahead: `place_fn` (shard_batch /
      `device_put_global`) is issued for batch k+1 while the caller still
      computes step k, so the host->HBM transfer overlaps device compute
      (classic double buffering; `lookahead` placed batches in flight).

Determinism contract: the prefetched stream yields the BYTE-IDENTICAL
batch sequence of the synchronous path — same generator, consumed in
order, placed in order. Resume (`skip_steps` fast-forward), per-epoch
shuffle, and multi-host per-process sharding therefore behave exactly as
without prefetch (every process still runs the same seeded pipeline and
feeds only its addressable shards; nothing about placement changes, only
WHEN it happens). `depth=0` is the kill-switch: no thread, no lookahead,
the caller pulls the generator synchronously through the same interface.

Shutdown: `close()` (also wired through `__exit__`/`__del__`) stops the
producer promptly even when it is blocked on a full queue — the producer
only ever waits on the queue with a timeout and re-checks a stop event —
and a generator that RAISES in the producer thread re-raises the same
exception at the consumer's next `__next__`. A consumer that dies
mid-epoch just calls `close()`; no thread outlives it.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

_DONE = object()

# lock-discipline declaration (core/static_checks.py, DESIGN.md §24):
# this module deliberately has NO lock — producer<->consumer state rides
# self-synchronizing primitives, and everything else is single-thread.
GRAFT_SHARED_STATE = {
    "Prefetcher": {
        "lock": None,
        "guarded": [],
        "channels": ["_q", "_stop"],  # bounded Queue + stop Event
        "note": "_buf/_exhausted/_error/_closed are consumer-thread-"
                "only; _rss_limit/_rss_logged producer-only after "
                "__init__ (construction happens-before thread start); "
                "rss_sheds is a monotonic int gauge (benign race by "
                "design, documented observable)",
    },
}


class _Failure:
    """Producer-side exception, carried through the queue to the consumer."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class Prefetcher:
    """Iterator over `source` with a background producer and placement
    lookahead.

    Args:
      source: any iterable of batches (host-side work happens in its
        `__next__` — that is what moves off the critical path).
      depth: bounded queue size (max host batches buffered ahead of the
        consumer). 0 disables BOTH the thread and the lookahead — the
        synchronous reference path, same interface.
      place_fn: optional per-item placement (shard_batch/device_put);
        applied in order, `lookahead` items ahead of the consumer.
      lookahead: placed items in flight beyond the one being returned
        (1 = classic double buffering).

    Consumers that want the host/device breakdown time their own
    `next()` calls (cli/common.run_training's host_wait_ms does): that
    covers queue wait AND lookahead placement with one mechanism, and
    reads the same for the depth=0 synchronous path.
    """

    def __init__(self, source: Iterable, depth: int = 2,
                 place_fn: Optional[Callable[[Any], Any]] = None,
                 lookahead: int = 1, rss_limit_mb: float = 0,
                 rss_fn: Optional[Callable[[], Optional[float]]] = None,
                 tracer=None):
        if depth < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        # span tracing (core/trace.py, --trace_spans): each batch the
        # producer thread assembles lands as a `span` on the "prefetch"
        # track, so the exported timeline shows host batch assembly
        # overlapping device steps — the overlap IS this module's
        # claim, and the trace makes it visible instead of inferred
        # from host_wait_ms
        self._tracer = tracer
        self._place = place_fn if place_fn is not None else (lambda x: x)
        self._lookahead = max(lookahead, 0) if depth > 0 else 0
        self._buf: collections.deque = collections.deque()
        self._exhausted = False
        self._closed = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._q = None
        # host-RSS shed guard (core/memory_guard.host_rss_mb): while the
        # process RSS sits above rss_limit_mb the producer stops
        # assembling lookahead batches until the consumer drains the
        # queue — the pipeline degrades toward depth-1 instead of the
        # OS OOM-killer picking a victim. 0 = off. rss_fn is injectable
        # for tests; a backend whose RSS cannot be read disables the
        # guard (never block on a sensor that cannot answer).
        self._rss_limit = max(float(rss_limit_mb), 0.0)
        if rss_fn is None:
            from mobilefinetuner_tpu.core.memory_guard import host_rss_mb
            rss_fn = host_rss_mb
        self._rss_fn = rss_fn
        self._rss_logged = False
        self.rss_sheds = 0  # lookahead batches deferred under pressure
        if depth > 0:
            self._q = queue.Queue(maxsize=depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._produce, args=(source,),
                name="batch-producer", daemon=True)
            self._thread.start()
        else:
            self._it = iter(source)

    # -- producer thread -----------------------------------------------------

    def _put(self, item) -> bool:
        """Queue-put that stays responsive to close(); False = stopping."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _shed_on_rss(self) -> None:
        """Hold the producer BEFORE it assembles the next batch while
        host RSS exceeds the limit and the consumer still has queued
        batches to drain: under memory pressure the lookahead is the
        one host allocation this pipeline controls, so it is the first
        thing to give back. Resumes as soon as RSS drops below the
        limit or the queue empties (a starved consumer always wins —
        shedding must degrade throughput, never deadlock it)."""
        if not self._rss_limit:
            return
        rss = self._rss_fn()
        if rss is None:
            self._rss_limit = 0  # unreadable sensor: guard off, once
            return
        if rss <= self._rss_limit:
            return
        self.rss_sheds += 1
        if not self._rss_logged:
            self._rss_logged = True
            from mobilefinetuner_tpu.core.logging import get_logger
            get_logger().warning(
                f"host RSS {rss:.0f} MB over the {self._rss_limit:.0f} "
                f"MB prefetch guard: shedding lookahead depth until "
                f"pressure clears")
        while not self._stop.is_set() and self._q.qsize() > 0:
            rss = self._rss_fn()
            if rss is None or rss <= self._rss_limit:
                break
            self._stop.wait(0.02)

    def _produce(self, source):
        try:
            import time as _time
            it = iter(source)
            n = 0
            while True:
                self._shed_on_rss()
                if self._stop.is_set():
                    return
                t0 = _time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    self._put(_DONE)
                    return
                if self._tracer is not None:
                    self._tracer.emit_span(
                        f"produce[{n}]", "prefetch", t0,
                        (_time.perf_counter() - t0) * 1000.0)
                n += 1
                if not self._put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — carried to the consumer
            self._put(_Failure(e))

    # -- consumer side -------------------------------------------------------

    def _get(self):
        """Next raw item, or the _DONE / _Failure terminal marker."""
        if self._thread is None:
            try:
                return next(self._it)
            except StopIteration:
                return _DONE
            except BaseException as e:  # sync path: same deferral contract
                return _Failure(e)
        return self._q.get()

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        # keep `lookahead + 1` placed items in flight: before item k is
        # returned, items k+1..k+lookahead are already placed (their
        # host->device transfers overlap the caller's step k)
        while not self._exhausted and len(self._buf) < self._lookahead + 1:
            item = self._get()
            if item is _DONE:
                self._exhausted = True
            elif isinstance(item, _Failure):
                # surface the generator's exception only once everything
                # produced BEFORE it has been consumed — the exact point
                # the synchronous path would raise at
                self._exhausted = True
                self._error = item.exc
            else:
                self._buf.append(self._place(item))
        if self._buf:
            return self._buf.popleft()
        err, self._error = self._error, None
        self.close()
        if err is not None:
            raise err
        raise StopIteration

    def queue_depth(self) -> int:
        """Instantaneous gauge: host batches buffered ahead of the
        consumer (producer queue + placed lookahead buffer). A healthy
        pipeline sits near its depth; a gauge stuck at 0 means the
        producer is the bottleneck — the telemetry step_stats field that
        tells a host-bound run from a device-bound one without a
        profiler. 0 on the synchronous (depth=0) path."""
        q = self._q.qsize() if self._q is not None else 0
        return q + len(self._buf)

    # -- lifecycle -----------------------------------------------------------

    def close(self, join_timeout: float = 5.0):
        """Stop the producer and release the queue. Idempotent; safe from
        any consumer error path (use as a context manager or try/finally).
        """
        if self._closed:
            return
        self._closed = True
        self._buf.clear()
        if self._thread is not None:
            self._stop.set()
            # unblock a producer sitting in a full-queue put (it re-checks
            # the stop event on its put timeout anyway; draining just
            # shortens the join)
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=join_timeout)
            self._thread = None

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort: never leak the producer thread
        try:
            self.close(join_timeout=0.1)
        except Exception:
            pass
