"""Gemma tokenizer: SentencePiece-style BPE parsed from HF `tokenizer.json`.

Behavioral spec mirrors the reference's GemmaTokenizer
(reference: core/tokenizer_gemma.{h,cpp} — vocab + merges parsed from
tokenizer.json (tokenizer_gemma.h:71-74), `▁` space marker
(tokenizer_gemma.h:70), special tokens <pad>/<eos>/<bos>/<unk>
(tokenizer_gemma.h:23-31), add_bos default true). Implemented from the
HF tokenizer.json schema, not ported.

Supported tokenizer.json mechanisms (the set Gemma uses):
  - normalizer: Replace / Prepend / Sequence
  - model: BPE with byte_fallback (unknown chars -> <0xXX> byte tokens)
  - no pre_tokenizer (BPE runs over the whole normalized string) or
    Metaspace
  - added_tokens: matched verbatim before BPE (special tokens survive)
  - decoder: ▁ -> space, byte-token fusion

BPE uses a heap over adjacent-pair ranks (O(n log n)) instead of the naive
quadratic rescan — the reference notes its Gemma tokenizer is slow enough to
need offline pretokenization (SURVEY.md §2.4); ours keeps the same
pretokenized-.bin escape hatch but is fast enough for online use. A native
C++ engine (native/fast_gemma_bpe) runs the merge+lookup stage when it
builds; this module's heap is the behavioral reference and fallback
(MFT_NO_NATIVE_GEMMA_BPE=1 forces it — the oracle parity tests do).
"""

from __future__ import annotations

import heapq
import json
import os
import re as stdre
from typing import Dict, List, Optional, Tuple


class _Normalizer:
    def __init__(self, spec: Optional[dict]):
        self.steps: List[Tuple[str, str, str]] = []
        if spec:
            self._parse(spec)

    def _parse(self, spec: dict):
        t = spec.get("type")
        if t == "Sequence":
            for sub in spec.get("normalizers", []):
                self._parse(sub)
        elif t == "Replace":
            pat = spec["pattern"]
            if "String" in pat:
                self.steps.append(("replace_str", pat["String"],
                                   spec["content"]))
            else:
                self.steps.append(("replace_re", pat["Regex"],
                                   spec["content"]))
        elif t == "Prepend":
            self.steps.append(("prepend", spec["prepend"], ""))
        elif t in ("NFC", "NFD", "NFKC", "NFKD"):
            self.steps.append(("unicode", t, ""))
        else:
            raise ValueError(f"unsupported normalizer {t}")

    def __call__(self, text: str) -> str:
        import unicodedata
        for kind, a, b in self.steps:
            if kind == "replace_str":
                text = text.replace(a, b)
            elif kind == "replace_re":
                text = stdre.sub(a, b, text)
            elif kind == "prepend":
                text = a + text if text else text
            elif kind == "unicode":
                text = unicodedata.normalize(a, text)
        return text


def _bpe_heap(symbols: List[str], ranks: Dict[Tuple[str, str], int]
              ) -> List[str]:
    """Merge adjacent symbol pairs in rank order via a heap over a
    doubly-linked list."""
    n = len(symbols)
    if n < 2:
        return symbols
    sym = list(symbols)
    nxt = list(range(1, n)) + [-1]
    prv = [-1] + list(range(n - 1))
    alive = [True] * n
    heap: List[Tuple[int, int, str, str]] = []
    for i in range(n - 1):
        r = ranks.get((sym[i], sym[i + 1]))
        if r is not None:
            heapq.heappush(heap, (r, i, sym[i], sym[i + 1]))
    while heap:
        r, i, a, b = heapq.heappop(heap)
        if not alive[i] or sym[i] != a:
            continue
        j = nxt[i]
        if j == -1 or not alive[j] or sym[j] != b:
            continue
        # merge j into i
        sym[i] = a + b
        alive[j] = False
        nxt[i] = nxt[j]
        if nxt[j] != -1:
            prv[nxt[j]] = i
        p = prv[i]
        if p != -1 and alive[p]:
            r2 = ranks.get((sym[p], sym[i]))
            if r2 is not None:
                heapq.heappush(heap, (r2, p, sym[p], sym[i]))
        q = nxt[i]
        if q != -1 and alive[q]:
            r2 = ranks.get((sym[i], sym[q]))
            if r2 is not None:
                heapq.heappush(heap, (r2, i, sym[i], sym[q]))
    out = []
    i = 0
    while i != -1:
        if alive[i]:
            out.append(sym[i])
        i = nxt[i]
    return out


class GemmaTokenizer:
    def __init__(self, path_or_spec):
        if isinstance(path_or_spec, str):
            with open(path_or_spec, encoding="utf-8") as f:
                spec = json.load(f)
        else:
            spec = path_or_spec
        model = spec["model"]
        assert model.get("type", "BPE") == "BPE", model.get("type")
        self.vocab: Dict[str, int] = model["vocab"]
        self.id_to_token = {v: k for k, v in self.vocab.items()}
        merges = model.get("merges", [])
        norm_merges: List[Tuple[str, str]] = []
        for m in merges:
            if isinstance(m, str):
                a, b = m.split(" ")
            else:
                a, b = m
            norm_merges.append((a, b))
        self.ranks = {pair: i for i, pair in enumerate(norm_merges)}
        self.byte_fallback = model.get("byte_fallback", False)
        self.unk_token = model.get("unk_token")
        self.normalizer = _Normalizer(spec.get("normalizer"))
        # Space handling may live in a Metaspace pre_tokenizer instead of a
        # Replace normalizer (common in SentencePiece-converted tokenizers).
        # Anything else unsupported -> raise, never silently produce garbage.
        self.metaspace: Optional[Tuple[str, str]] = None  # (repl, scheme)
        self._parse_pre_tokenizer(spec.get("pre_tokenizer"))
        self.added_tokens = {t["content"]: t["id"]
                             for t in spec.get("added_tokens", [])}
        self._added_re = None
        if self.added_tokens:
            pat = "|".join(stdre.escape(t) for t in
                           sorted(self.added_tokens, key=len, reverse=True))
            self._added_re = stdre.compile(f"({pat})")

        def _tid(name, default=None):
            return self.added_tokens.get(name, self.vocab.get(name, default))

        self.pad_id = _tid("<pad>", 0)
        self.eos_id = _tid("<eos>", 1)
        self.bos_id = _tid("<bos>", 2)
        self.unk_id = _tid("<unk>", 3)
        self.add_bos = True  # Gemma default (tokenizer_gemma.h add_bos)
        self._native = None
        try:
            from mobilefinetuner_tpu.native.fast_gemma_bpe import \
                NativeGemmaBPE
            unk = (self.vocab[self.unk_token]
                   if self.unk_token is not None else None)
            self._native = NativeGemmaBPE(
                norm_merges, self.vocab, unk, self.byte_fallback)
        except Exception:
            self._native = None  # pure-Python heap path below

    def _parse_pre_tokenizer(self, spec: Optional[dict]):
        if spec is None:
            return
        t = spec.get("type")
        if t == "Sequence":
            for sub in spec.get("pretokenizers", []):
                self._parse_pre_tokenizer(sub)
        elif t == "Metaspace":
            self.metaspace = (spec.get("replacement", "▁"),
                              spec.get("prepend_scheme",
                                       "always" if spec.get("add_prefix_space",
                                                            True)
                                       else "never"))
        else:
            raise ValueError(f"unsupported pre_tokenizer {t}")

    @classmethod
    def from_pretrained(cls, model_dir: str) -> "GemmaTokenizer":
        return cls(os.path.join(model_dir, "tokenizer.json"))

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def _encode_chunk(self, text: str, first: bool = True) -> List[int]:
        if not text:
            return []
        text = self.normalizer(text)
        if self.metaspace is not None:
            rep, scheme = self.metaspace
            text = text.replace(" ", rep)
            if (scheme == "always" or (scheme == "first" and first)) \
                    and not text.startswith(rep):
                text = rep + text
        if self._native is not None:
            return self._native.encode_chunk(text)
        pieces = _bpe_heap(list(text), self.ranks)
        ids: List[int] = []
        for piece in pieces:
            tid = self.vocab.get(piece)
            if tid is not None:
                ids.append(tid)
            elif self.byte_fallback:
                for byte in piece.encode("utf-8"):
                    ids.append(self.vocab[f"<0x{byte:02X}>"])
            elif self.unk_token is not None:
                ids.append(self.vocab[self.unk_token])
        return ids

    def encode(self, text: str, add_bos: Optional[bool] = None) -> List[int]:
        add_bos = self.add_bos if add_bos is None else add_bos
        ids: List[int] = [self.bos_id] if add_bos else []
        if self._added_re:
            parts = self._added_re.split(text)
        else:
            parts = [text]
        # HF Metaspace prepend_scheme="first": the space marker is prepended
        # only to a part at offset 0 of the original string — a part that
        # follows a special token is NOT "first" (verified vs HF tokenizers:
        # "<bos>user" -> [bos, "user"], not [bos, "▁user"]).
        first = True
        for part in parts:
            if not part:
                continue
            if part in self.added_tokens:
                ids.append(self.added_tokens[part])
            else:
                ids.extend(self._encode_chunk(part, first=first))
            first = False
        return ids

    def decode(self, ids: List[int], skip_special: bool = True) -> str:
        special = {self.pad_id, self.eos_id, self.bos_id}
        out: List[str] = []
        byte_buf = bytearray()
        byte_re = stdre.compile(r"^<0x([0-9A-Fa-f]{2})>$")

        def flush():
            if byte_buf:
                out.append(byte_buf.decode("utf-8", errors="replace"))
                byte_buf.clear()

        for i in ids:
            i = int(i)
            if skip_special and i in special:
                continue
            tok = self.id_to_token.get(i, "")
            m = byte_re.match(tok)
            if m:
                byte_buf.append(int(m.group(1), 16))
                continue
            flush()
            out.append(tok)
        flush()
        return "".join(out).replace("▁", " ")
