"""GPT-2 byte-level BPE tokenizer, HF-aligned.

Behavioral spec mirrors the reference's GPT2BPETokenizer
(reference: core/tokenizer_bpe.{h,cpp} — exact bytes_to_unicode table
(tokenizer_bpe.cpp:110-167), the GPT-2 pre-tokenization regex
(tokenizer_bpe.cpp:257-275), vocab.json/merges.txt loading, and
eos=bos=pad=unk=50256 (tokenizer_bpe.h:29-33)), itself aligned with the
public GPT-2 tokenizer algorithm. Implemented from the public algorithm, not
ported. Uses the `regex` module for \\p{L}/\\p{N} unicode categories.

The Python implementation is the behavioral reference; a native C++ merge
engine (native/fast_bpe.cpp, built on first use and bound via ctypes) is
the fast path for the BPE hot loop, with automatic fallback when the
compiler or library is unavailable. Parity between the two is asserted in
tests/test_native_bpe.py (and the Python side against HF's tokenizers).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Dict, List, Optional, Tuple

import regex as re

# GPT-2 pre-tokenization pattern (public, from the GPT-2 release).
_PAT = re.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"""
    r""" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+""")


@functools.lru_cache(maxsize=1)
def bytes_to_unicode() -> Dict[int, str]:
    """The GPT-2 reversible byte<->unicode-char table: printable bytes map
    to themselves, the rest to U+0100+n."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def _get_pairs(word: Tuple[str, ...]) -> set:
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class GPT2BPETokenizer:
    """Byte-level BPE with merge ranks; encode/decode exactly match HF's
    GPT2TokenizerFast on the same vocab/merges files."""

    def __init__(self, vocab: Dict[str, int], merges: List[Tuple[str, str]],
                 eos_token: str = "<|endoftext|>",
                 use_native: bool = True):
        self.encoder = dict(vocab)
        self.decoder = {v: k for k, v in vocab.items()}
        self.bpe_ranks = {pair: i for i, pair in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.eos_token = eos_token
        self.eos_id = self.encoder.get(eos_token, len(vocab) - 1)
        # GPT-2 convention: all special roles share <|endoftext|>
        # (tokenizer_bpe.h:29-33)
        self.bos_id = self.pad_id = self.unk_id = self.eos_id
        self._cache: Dict[str, List[str]] = {}
        self._id_cache: Dict[str, List[int]] = {}
        self._native = None
        if use_native:
            try:
                from mobilefinetuner_tpu.native.fast_bpe import NativeBPE
                self._native = NativeBPE(merges, vocab)
            except Exception:
                self._native = None  # pure-Python fallback

    # -- construction --------------------------------------------------------

    @classmethod
    def from_pretrained(cls, model_dir: str,
                        use_native: bool = True) -> "GPT2BPETokenizer":
        with open(os.path.join(model_dir, "vocab.json"),
                  encoding="utf-8") as f:
            vocab = json.load(f)
        merges: List[Tuple[str, str]] = []
        with open(os.path.join(model_dir, "merges.txt"),
                  encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#version"):
                    continue
                a, b = line.split(" ")
                merges.append((a, b))
        eos = "<|endoftext|>"
        stm = os.path.join(model_dir, "special_tokens_map.json")
        if os.path.exists(stm):
            with open(stm, encoding="utf-8") as f:
                sm = json.load(f)
            e = sm.get("eos_token", eos)
            eos = e["content"] if isinstance(e, dict) else e
        return cls(vocab, merges, eos, use_native=use_native)

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    # -- BPE core ------------------------------------------------------------

    def _bpe(self, token: str) -> List[str]:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        word = tuple(token)
        if len(word) == 1:
            self._cache[token] = [token]
            return [token]
        pairs = _get_pairs(word)
        while True:
            best = min(pairs,
                       key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if best not in self.bpe_ranks:
                break
            a, b = best
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(a, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                if j < len(word) - 1 and word[j + 1] == b:
                    new_word.append(a + b)
                    i = j + 2
                else:
                    new_word.append(word[j])
                    i = j + 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = list(word)
        self._cache[token] = out
        return out

    # -- public API ----------------------------------------------------------

    def _word_ids(self, mapped: str) -> List[int]:
        """ids for one byte->unicode-mapped word: native merge engine when
        built (cached here), Python reference otherwise (cached inside
        _bpe — one cache per mode, never both)."""
        if self._native is None:
            return [self.encoder.get(sub, self.unk_id)
                    for sub in self._bpe(mapped)]
        cached = self._id_cache.get(mapped)
        if cached is None:
            cached = self._native.encode_word(mapped, self.unk_id)
            self._id_cache[mapped] = cached
        return cached

    def encode(self, text: str) -> List[int]:
        # Special tokens are matched verbatim before BPE (HF AddedToken
        # semantics): "<|endoftext|>" in the text becomes the single eos id,
        # not the BPE pieces of its characters.
        ids: List[int] = []
        for part in text.split(self.eos_token):
            for piece in _PAT.findall(part):
                mapped = "".join(self.byte_encoder[b]
                                 for b in piece.encode("utf-8"))
                ids.extend(self._word_ids(mapped))
            ids.append(self.eos_id)
        ids.pop()  # one eos per separator, not per part
        return ids

    def decode(self, ids: List[int]) -> str:
        text = "".join(self.decoder.get(int(i), "") for i in ids)
        raw = bytearray(self.byte_decoder[c] for c in text)
        return raw.decode("utf-8", errors="replace")
