"""Ring attention: exact attention over sequence-sharded activations.

The long-context scale-out lever the reference cannot have (it is
single-device; its only long-context tools are O(S)-memory streaming
softmax and Gemma's sliding window — SURVEY.md §2.11/§5). Here the
sequence axis is sharded across mesh devices and K/V chunks rotate around
the ring with `lax.ppermute` while each device keeps its Q shard and
accumulates ONLINE-softmax partial results — attention memory per device
stays O(S_local · S_local) for scores and O(S_local · D) for K/V, so
context length scales linearly with the number of devices, and each
rotation's communication can overlap the previous chunk's compute (XLA's
latency-hiding scheduler; collectives ride ICI).

Semantics match ops.attention.dot_product_attention exactly (causal,
sliding window implies causal, GQA via Hkv < Hq, key-padding mask) — the
parity and gradient tests run both on a virtual 8-device CPU mesh
(tests/test_ring_attention.py). Differentiable end to end: the ring is a
`lax.scan` over static mesh-size steps inside `shard_map`, so reverse-mode
AD runs the rotation backwards with the transposed permutation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _chunk_attend(q, k, v, pad, row0, col0, scale, causal, window):
    """Partial attention of a local Q shard against one K/V chunk at
    global column offset col0; returns (m, l, acc) online-softmax stats.
    q: [B, Hq, Sq, D]; k, v: [B, Hkv, Sk, D]; pad: [B, Sk]."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    G = Hq // Hkv
    qg = q.reshape(B, Hkv, G, Sq, D).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale

    rows = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0) + row0
    cols = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1) + col0
    mask = jnp.ones((Sq, Sk), jnp.bool_)
    if causal:
        mask = mask & (cols <= rows)
    if window is not None:
        mask = mask & (cols > rows - window)
    mask = mask[None, None, None] & (pad > 0)[:, None, None, None, :]
    s = jnp.where(mask, s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)                 # [B,Hkv,G,Sq,1]
    p = jnp.where(mask, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhgqk,bhkd->bhgqd", p, v.astype(jnp.float32))
    return m, l, acc


def _merge_lse(lse1, o1, lse2, o2):
    """Online-softmax merge in (lse, out) form: logaddexp the lse's, and
    weight each partial output by exp(lse_c − lse_new). NEG_INF is a
    finite sentinel, so fully-masked partials merge to weight 0 without
    producing NaN (−inf − −inf)."""
    lse = jnp.logaddexp(lse1, lse2)
    return lse, (o1 * jnp.exp(lse1 - lse) + o2 * jnp.exp(lse2 - lse))


def _ring_hops(n: int, window, Sq: int) -> int:
    """How many rotations the ring actually needs. Causal-only: n−1 (every
    earlier chunk is visible). A sliding window w only reaches rows up to
    w−1 columns back. Hop t's NEAREST cell (local row 0 vs the chunk's
    last column) is (t−1)·Sq + 1 rows back, so hop t has visible cells
    iff (t−1)·Sq + 1 ≤ w−1, i.e. t ≤ (w−2)//Sq + 1 — chunks past that
    never travel, saving both compute AND ppermute traffic. w=1 (self
    only) needs 0 hops: floor division of the negative numerator handles
    it, and the max() guards the clamp."""
    if window is None:
        return n - 1
    return min(n - 1, max(0, (int(window) - 2) // Sq + 1))


def _ring_shard_flash(q, k, v, pad, *, axis, n, scale, window):
    """Flash-kernel ring body: per-device memory is O(Sq·D) — scores only
    ever exist blockwise in VMEM (ops/flash_attention.py), never as a
    [.., Sq, Sk] tensor in HBM. The hop loop is unrolled so each hop's
    mask is STATIC: hop t's chunk sits exactly t·Sq rows behind the local
    queries, so the diagonal hop is plain causal(+window) and hop t ≥ 1 is
    the non-causal band sliding_window = window − t·Sq (None = fully
    visible). Wrap-around chunks (from devices AHEAD of this one) are
    future tokens: computed in lockstep (SPMD — skipping wouldn't free the
    step) and merged with weight 0 via an lse of NEG_INF. Gradients flow
    through both out and lse of every partial (flash_attention_partial's
    joint custom_vjp), so reverse-mode AD of the merge tree is exact —
    and each partial's backward dispatches through the same
    resolve_bwd_impl selector as the plain kernel, so a kernel-eligible
    ring shard runs the merged one-pass dK/dV+dQ kernel per hop (half
    the backward launches per rotation; the dlse cotangent folds into Δ
    before the kernel, identically for either backward impl)."""
    from mobilefinetuner_tpu.ops.flash_attention import \
        flash_attention_partial

    # n arrives STATIC from the caller (mesh.shape[axis]): the hop loop
    # is unrolled over it, so it cannot be a traced axis_size
    idx = jax.lax.axis_index(axis)
    B, Hq, Sq, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    out, lse = flash_attention_partial(q, k, v, pad, scale=scale,
                                       is_causal=True,
                                       sliding_window=window)
    out = out.astype(jnp.float32)
    kc, vc, pc = k, v, pad
    for t in range(1, _ring_hops(n, window, Sq) + 1):
        kc = jax.lax.ppermute(kc, axis, perm)
        vc = jax.lax.ppermute(vc, axis, perm)
        pc = jax.lax.ppermute(pc, axis, perm)
        weff = None if window is None else int(window) - t * Sq
        o_c, lse_c = flash_attention_partial(q, kc, vc, pc, scale=scale,
                                             is_causal=False,
                                             sliding_window=weff)
        # hop t carries the chunk of device idx−t; idx < t means it wrapped
        # around from a device ahead — causally invisible
        lse_c = jnp.where(idx >= t, lse_c, NEG_INF)
        lse, out = _merge_lse(lse, out, lse_c,
                              o_c.astype(jnp.float32))
    return out.astype(q.dtype)


def _ring_shard(q, k, v, pad, *, axis, n, scale, causal, window):
    """Runs on each device inside shard_map: local Q stays, K/V/pad
    rotate; online-softmax merge across the n (static, from mesh.shape)
    ring steps."""
    idx = jax.lax.axis_index(axis)
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    row0 = idx * Sq
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(stats, chunk):
        m, l, acc = stats
        m_c, l_c, a_c = chunk
        m_new = jnp.maximum(m, m_c)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.exp(m_c - m_new)
        return m_new, l * a1 + l_c * a2, acc * a1 + a_c * a2

    def step(carry, _):
        # rotate FIRST: the local chunk was attended before the scan, so
        # only n-1 rotations happen — no trailing ppermute whose result
        # would be thrown away
        k_cur, v_cur, pad_cur, src, m, l, acc = carry
        k_cur = jax.lax.ppermute(k_cur, axis, perm)
        v_cur = jax.lax.ppermute(v_cur, axis, perm)
        pad_cur = jax.lax.ppermute(pad_cur, axis, perm)
        src = (src - 1) % n
        chunk = _chunk_attend(q, k_cur, v_cur, pad_cur, row0, src * Sq,
                              scale, causal, window)
        m, l, acc = merge((m, l, acc), chunk)
        return (k_cur, v_cur, pad_cur, src, m, l, acc), None

    m0, l0, acc0 = _chunk_attend(q, k, v, pad, row0, idx * Sq, scale,
                                 causal, window)
    (_, _, _, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, pad, idx, m0, l0, acc0), None, length=n - 1)
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(B, Hq, Sq, D).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *,
                   axis: str = "fsdp",
                   batch_axis: Optional[str] = "data",
                   scale: Optional[float] = None,
                   is_causal: bool = True,
                   sliding_window: Optional[int] = None,
                   padding_mask: Optional[jnp.ndarray] = None):
    """Exact attention with the sequence axis sharded over `mesh[axis]`.

    q: [B, Hq, S, D]; k, v: [B, Hkv, S, D]; padding_mask: [B, S] (1 =
    real token). S must divide by the axis size. The batch axis shards
    over `batch_axis` when the mesh has it (each data group rings over
    its OWN batch shard — without this, every group would all-gather and
    redundantly attend over the global batch). Returns [B, Hq, S, D]
    sharded the same way. Call under jit (or eagerly); shard_map handles
    the placement.
    """
    B, Hq, S, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    is_causal = bool(is_causal) or sliding_window is not None
    if padding_mask is None:
        padding_mask = jnp.ones((B, S), jnp.float32)
    pad = padding_mask.astype(jnp.float32)

    ba = batch_axis if (batch_axis in mesh.axis_names) else None
    spec_s = P(ba, None, axis, None)     # batch + sequence sharded
    spec_p = P(ba, axis)
    window = None if sliding_window is None else int(sliding_window)
    # Flash-kernel ring body when the LOCAL shard shape is kernel-eligible
    # (per-device scores stay blockwise in VMEM, O(Sq·D) HBM); the dense
    # body is the fallback oracle for tiny/odd shapes and non-causal use.
    from mobilefinetuner_tpu.ops.flash_attention import \
        flash_partial_eligible
    Sq = S // mesh.shape[axis]
    if is_causal and flash_partial_eligible(Sq, D):
        fn = partial(_ring_shard_flash, axis=axis, n=mesh.shape[axis],
                     scale=float(scale), window=window)
    else:
        fn = partial(_ring_shard, axis=axis, n=mesh.shape[axis],
                     scale=float(scale), causal=is_causal, window=window)
    from mobilefinetuner_tpu.core.compat import shard_map
    return shard_map(
        fn, mesh=mesh,
        in_specs=(spec_s, spec_s, spec_s, spec_p),
        out_specs=spec_s,
        check_vma=False,
    )(q, k, v, pad)
