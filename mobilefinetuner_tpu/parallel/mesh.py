"""Device mesh + sharding-spec utilities.

The TPU-native replacement for the reference's single-device ZeRO-style
ParameterSharder (reference: operators/opt_ops/sharding/parameter_sharder.h):
instead of tiering parameters between RAM and disk under a byte budget, we
shard parameters/gradients/optimizer state FSDP-style across chips over ICI
(axis "fsdp") and batch-shard over axis "data". XLA inserts the
all-gather/reduce-scatter collectives; we only annotate shardings.

Mesh axes:
  data — pure data parallelism (batch axis of activations)
  fsdp — ZeRO-3-style parameter/grad/optimizer-state sharding; activations'
         batch axis is also sharded over it (fsdp acts as a second DP axis),
         so the effective data-parallel world is data*fsdp.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(data: int = 1, fsdp: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 2D ("data", "fsdp") mesh over the available devices.

    fsdp=None → use all remaining devices on the fsdp axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if fsdp is None:
        if n % data != 0:
            raise ValueError(f"{n} devices not divisible by data={data}")
        fsdp = n // data
    if data * fsdp != n:
        raise ValueError(f"data*fsdp={data * fsdp} != n_devices={n}")
    arr = np.asarray(devices).reshape(data, fsdp)
    return Mesh(arr, axis_names=("data", "fsdp"))


def single_device_mesh() -> Mesh:
    return make_mesh(data=1, fsdp=1, devices=jax.devices()[:1])


def fsdp_spec_for(shape: Tuple[int, ...], mesh: Mesh,
                  min_size: int = 2 ** 16) -> P:
    """FSDP sharding rule for one parameter: shard the largest axis that
    divides evenly by the fsdp mesh size; replicate small params.

    This is the weight-sharding analog of the reference sharder's per-param
    registration (parameter_sharder.cpp:215-232) — but across chips, not to
    disk. Small params (norms, biases) stay replicated: gathering them is
    cheaper than the latency of tiny collectives.
    """
    n_fsdp = mesh.shape.get("fsdp", 1)
    if n_fsdp <= 1 or int(np.prod(shape)) < min_size:
        return P()
    # Largest divisible axis, ties broken toward the first axis.
    best = None
    for i, d in enumerate(shape):
        if d % n_fsdp == 0 and (best is None or d > shape[best]):
            best = i
    if best is None:
        return P()
    spec = [None] * len(shape)
    spec[best] = "fsdp"
    return P(*spec)


def params_shardings(params, mesh: Mesh, min_size: int = 2 ** 16):
    """Pytree of NamedShardings implementing FSDP over `mesh`."""
    return jax.tree.map(
        lambda x: NamedSharding(mesh, fsdp_spec_for(x.shape, mesh, min_size)),
        params)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch axis sharded over both mesh axes (data-parallel over the full
    device set; fsdp doubles as a DP axis for activations)."""
    return NamedSharding(mesh, P(("data", "fsdp")))


def sp_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sequence-parallel placement: batch over "data", SEQUENCE over
    "fsdp" (ring attention consumes the S shards —
    parallel/ring_attention.py)."""
    return NamedSharding(mesh, P("data", "fsdp"))


def shard_params(params, mesh: Mesh, min_size: int = 2 ** 16):
    """Place a parameter pytree onto the mesh with FSDP shardings
    (multi-host safe: every process holds the same host copy and feeds its
    addressable shards)."""
    from mobilefinetuner_tpu.parallel.distributed import device_put_global
    shardings = params_shardings(params, mesh, min_size)
    return jax.tree.map(device_put_global, params, shardings)


def make_batch_placer(mesh: Optional[Mesh],
                      sequence_parallel: bool = False):
    """Build place(batch) -> placed once, NamedShardings precomputed —
    the per-step closure the async input pipeline (data/prefetch.py)
    issues for batch k+1 while step k computes. Placement is identical
    to `shard_batch`; only WHEN it runs differs. mesh=None returns
    identity (the single-process uncommitted-host-numpy fast path, where
    the jit transfers on dispatch)."""
    from mobilefinetuner_tpu.parallel.distributed import put_batch_global
    if mesh is None:
        return lambda batch: batch
    if not sequence_parallel:
        s = batch_sharding(mesh)
        return lambda batch: put_batch_global(batch, lambda k: s)
    sp = sp_batch_sharding(mesh)
    b_only = NamedSharding(mesh, P("data"))
    # per-sample leaves WITHOUT a sequence axis shard only the batch
    # dim: dropout keys and the fault harness's [B] grad_scale row
    # (a rank-2 S-sharding spec on a rank-1 leaf would reject)
    return lambda batch: put_batch_global(
        batch, lambda k: b_only if k in ("dropout_rng", "grad_scale")
        else sp)


def shard_batch(batch, mesh: Mesh, sequence_parallel: bool = False):
    """Place a batch pytree (leading batch axis) onto the mesh. In
    sequence-parallel mode, [B, S] token arrays shard S over "fsdp";
    per-sample leaves without a sequence axis (dropout_rng keys) shard
    only the batch dim. Multi-host: every process holds the same global
    batch and feeds only its addressable shards
    (parallel/distributed.device_put_global)."""
    return make_batch_placer(mesh, sequence_parallel)(batch)
