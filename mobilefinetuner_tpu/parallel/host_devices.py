"""Force a virtual N-device CPU platform (test/dryrun harness).

The analog of the reference's mocked-telemetry testing culture (SURVEY.md
§4.6): FSDP/mesh code paths must run without a TPU pod. Shared by
tests/conftest.py and __graft_entry__.dryrun_multichip so the two subtle
workarounds below live in exactly one place:

  - XLA reads --xla_force_host_platform_device_count from XLA_FLAGS at
    backend init; an existing entry with a DIFFERENT value must be rewritten,
    not just detected by substring.
  - TPU images may PRELOAD jax at interpreter start (sitecustomize) with
    JAX_PLATFORMS preset to the TPU plugin — the config default is captured
    then, so setting the env var afterwards does nothing and
    jax.config.update is required. But selecting cpu via config.update
    leaves the backend without host/device memory-space accounting
    (host-placed arguments get billed as device memory in compiled
    memory_analysis()). In fact the CPU backend never separates the two
    (host RAM IS its device memory), so the offload peak-memory proof
    (tools/check_stream_memory.py) runs on the machine's default
    accelerator platform in a subprocess and skips on cpu.
"""

from __future__ import annotations

import os
import re


def force_host_devices(n: int) -> None:
    """Arrange for jax to expose >= n virtual CPU devices.

    Must run before the first jax backend initialization to take full
    effect; afterwards it is best-effort (config update may raise if the
    backend is live — swallowed, callers assert on jax.devices()).
    """
    flag = "--xla_force_host_platform_device_count"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(flag + r"=(\d+)", flags)
    if m:
        if int(m.group(1)) < n:
            flags = re.sub(flag + r"=\d+", f"{flag}={n}", flags)
    else:
        flags = (flags + f" {flag}={n}").strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    if jax.config.jax_platforms != "cpu":
        # jax was imported before the env override took effect (interpreter
        # preload); force via config — see module docstring for the cost.
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
