"""Budget-driven parameter placement: HBM vs pinned host RAM.

TPU-native analog of the reference's ZeRO-inspired single-device
ParameterSharder (reference: operators/opt_ops/sharding/parameter_sharder.{h,cpp}):
the reference tiers parameters between RAM and local disk under a byte budget
(`max_resident_bytes`), optionally FP16-quantizing on write
(parameter_sharder.cpp:215-232), and models call `require(name)` to fault a
parameter back in (parameter_sharder.cpp:242-271, LRU eviction 181-199).

On TPU the memory hierarchy is HBM <-> pinned host RAM, and the "fault in"
is a compiled H2D transfer XLA can overlap with compute. The mapping:

  reference                         this module
  ---------------------------------------------------------------
  register_parameter(name, ...)     plan_placement(params, config)
  max_resident_bytes budget         OffloadConfig.max_resident_bytes
  quantize_fp16_on_disk             OffloadConfig.offload_dtype="bfloat16"
                                    (bf16 is the TPU-idiomatic 16-bit type)
  require(name) disk->RAM load      fetch(...) whole-tree, or fetch_layer(...)
                                    per layer inside the model's lax.scan:
                                    jax.device_put back to "device" memory
  per-layer require() in the model  fetch_layer(blocks, plan, i, ...) —
  (gpt2_model.cpp:536-549)          slices layer i out of the [L, ...]-stacked
                                    host arrays; XLA emits an async host->HBM
                                    dynamic-slice DMA it overlaps with the
                                    previous layer's compute
  LRU eviction                      static spill plan, streamable stacks
                                    first then largest-first (the whole
                                    step's working set is known at trace
                                    time — no runtime eviction needed)
  offload_all()                     apply_placement(...)
  owner_ptr nulling                 functional pytrees: the host copy IS the
                                    storage; nothing to null

Peak-HBM semantics: `fetch` pulls the whole tree, so fetched weights are
device-resident for the entire step — the budget then governs only idle
placement. `fetch_layer` is the reference's actual working-set bound
(parameter_sharder.cpp:242-271): only ~one-two layers of offloaded weights
are HBM-resident at a time, provided the layer scan body is rematerialized
(jax.checkpoint) so the backward re-fetches instead of keeping every
layer's weights alive as saved residuals. The model forwards handle both
(models/gpt2.py, models/gemma3.py `offload=` argument).

Overlap engineering note (measured, v5e round 3): XLA's while-loop double
buffering already pipelines each iteration's host->HBM dynamic-slice DMA
behind the adjacent iteration's compute. An explicit double-buffer —
carrying prefetched layer-(i+d) weights through the scan carry under a
custom_vjp so the backward could re-fetch in reverse with the same
pipeline — measured STRICTLY WORSE (gpt2s budget-0: 120k vs 140k tok/s;
gemma-1B stream B=32: 12.9k vs 15.9k), because an HLO while-loop carry is
a concrete value: every prefetch issued in iteration i must COMPLETE in
iteration i to form the carry, so the manual pipeline only reorders waits
while defeating the compiler's own transfer pipelining (and lax.scan
unroll=2 was neutral-to-worse as well). The levers that do cut streaming
overhead are placement (spill streamable >=3-D stacks before whole-fetch
leaves — plan_placement below) and batch amortization (bench.py offload
B=32 rows: overhead vs same-batch resident within noise).

Budget semantics are strict (test_sharder_strict.cpp analog): the PLANNED
resident set never exceeds `max_resident_bytes`. The reference must auto-raise
its budget to fit the largest single parameter (train_lora_gemma.cpp:434-441)
because `require()` materializes a param in the resident RAM pool; here a
fetched param is transient working set inside one XLA program, not a resident
pool entry, so no raise is needed — even a budget of 0 is valid (stream
everything).

Composes with FSDP: placement operates on whatever shardings you pass —
`NamedSharding.with_memory_kind("pinned_host")` keeps the partition spec, so
a parameter can be simultaneously FSDP-sharded across chips AND offloaded to
each chip's host RAM.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

HOST = "pinned_host"
DEVICE = "device"


def host_kind() -> str:
    """The host-tier memory kind on the CURRENT backend: "pinned_host"
    on TPU; the CPU backend's sole memory otherwise (its NAME varies
    across jax versions — "device" vs "unpinned_host" — so it is read
    off the device rather than assumed). Resolved lazily: resolving at
    import would initialize the backend before force_host_devices can
    set the virtual device count."""
    d = jax.devices()[0]
    return HOST if d.platform != "cpu" else d.default_memory().kind


def device_kind() -> str:
    """Device-tier memory kind on the current backend (see host_kind)."""
    d = jax.devices()[0]
    return DEVICE if d.platform != "cpu" else d.default_memory().kind


@dataclasses.dataclass
class OffloadConfig:
    """Analog of ShardConfig (parameter_sharder.h:37-41)."""
    enable: bool = False
    max_resident_bytes: int = 0          # HBM budget for the planned tree
    offload_dtype: str = "bfloat16"      # "bfloat16" | "float32"
    min_offload_size: int = 2 ** 12      # tiny params never offloaded

    @property
    def np_offload_dtype(self):
        return jnp.bfloat16 if self.offload_dtype == "bfloat16" \
            else jnp.float32


def _leaf_bytes(x, dtype=None) -> int:
    d = np.dtype(dtype) if dtype is not None else \
        np.dtype(getattr(x, "dtype", np.float32))
    return int(np.prod(np.shape(x))) * d.itemsize


def is_streamable(x) -> bool:
    """Leaf-level half of the streaming predicate: >=3-D [L, in, out]
    stacks. 2-D stacks (biases/norms) and plain 2-D tables (embeddings)
    are fetched whole — both because their per-layer slices hit the TPU
    host-DMA small-transfer limitation (see resolve_offload) and because a
    whole-tensor fetch is a serial transfer the placement plan should
    treat as expensive. The FULL predicate is positional too: only leaves
    under the model tree's `blocks` entry stream (resolve_offload fetches
    every top-level leaf whole), so plan_placement / streams_only_budget
    combine this with a blocks_key path check via _streamable_mask."""
    return np.ndim(x) >= 3


def _streamable_mask(params, blocks_key):
    """(flat streamable flags, flat leaves, treedef) for a model tree:
    a leaf is streamable iff it sits under `blocks_key` AND is_streamable.
    Trees without a `blocks_key` entry (generic pytrees) get all-False —
    plan_placement then degrades to pure largest-first."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(params)
    flags = [len(p) > 0 and getattr(p[0], "key", None) == blocks_key
             and is_streamable(x) for p, x in paths]
    return flags, [x for _, x in paths], treedef


def streams_only_budget(params, min_offload_size: int = None,
                        blocks_key: str = "blocks") -> int:
    """The intermediate-budget point on the overhead/residency curve: the
    smallest budget whose plan spills ONLY streamable leaves (those
    >= min_offload_size — smaller ones can never spill), keeping every
    whole-fetch leaf (embedding table, norms, biases) HBM-resident so no
    serial whole-tensor transfer lands on the step's critical path."""
    if min_offload_size is None:
        min_offload_size = OffloadConfig.min_offload_size
    flags, leaves, _ = _streamable_mask(params, blocks_key)
    total = spill = 0
    for x, streamable in zip(leaves, flags):
        b = _leaf_bytes(x)
        total += b
        if streamable and b >= min_offload_size:
            spill += b
    return total - spill


def plan_placement(params, config: OffloadConfig,
                   blocks_key: str = "blocks") -> Any:
    """Pytree of bool: True = offload this leaf to host RAM.

    Greedy spill, streamable-first then largest-first: keep everything
    resident if it fits; otherwise offload until the resident set is under
    budget, preferring streamable leaves (>=3-D [L, in, out] stacks under
    `blocks_key` — _streamable_mask). Those are the leaves resolve_offload
    streams one layer at a time inside the scan, where XLA's while-loop
    double buffering hides the H2D DMA behind the adjacent layers' compute
    — so their spill is cheap. Whole-fetch leaves (embedding tables,
    stacked biases/norms, anything outside `blocks_key`) cost a serial
    transfer on the step's critical path (measured on v5e: the host link
    is latency-bound, ~2 GiB/s for a single stream vs ~8 GiB/s for the
    concurrent per-layer leaf fetches), so they spill only when the
    streamable leaves alone cannot meet the budget. Within each class,
    largest-first meets the budget with the fewest transfers — where the
    reference's LRU had to guess, the static plan knows the whole step's
    access pattern.
    """
    streamable, leaves, treedef = _streamable_mask(params, blocks_key)
    if not config.enable:
        return jax.tree.unflatten(treedef, [False] * len(leaves))
    sizes = [_leaf_bytes(x) for x in leaves]
    total = sum(sizes)
    budget = config.max_resident_bytes
    offload = [False] * len(leaves)
    resident = total
    order = sorted(range(len(leaves)),
                   key=lambda i: (not streamable[i], -sizes[i]))
    for i in order:
        if resident <= budget:
            break
        if sizes[i] < config.min_offload_size:
            continue
        offload[i] = True
        resident -= sizes[i]
    if resident > budget:
        import warnings
        warnings.warn(
            f"offload plan over budget: {resident} resident bytes > "
            f"{budget} budget — leaves below min_offload_size="
            f"{config.min_offload_size} alone exceed the budget",
            stacklevel=2)
    return jax.tree.unflatten(treedef, offload)


def placement_stats(params, plan, config: OffloadConfig) -> Dict[str, int]:
    """Resident/offloaded byte counts (reference's sharder stats report)."""
    resident = offloaded = 0
    for x, off in zip(jax.tree.leaves(params), jax.tree.leaves(plan)):
        if off:
            offloaded += _leaf_bytes(x, config.np_offload_dtype)
        else:
            resident += _leaf_bytes(x)
    return {"resident_bytes": resident, "offloaded_bytes": offloaded,
            "n_offloaded": sum(map(bool, jax.tree.leaves(plan)))}


def apply_placement(params, plan, shardings, config: OffloadConfig):
    """Place the tree: offloaded leaves -> host memory in offload_dtype,
    resident leaves -> their given sharding unchanged.

    `shardings` is a pytree of jax.sharding.Sharding (e.g. from
    parallel.mesh.params_shardings) or a single sharding applied to all.
    """
    if not isinstance(shardings, (dict, list, tuple)):
        shardings = jax.tree.map(lambda _: shardings, params)
    od = config.np_offload_dtype

    from mobilefinetuner_tpu.parallel.distributed import device_put_global

    def place(x, off, sh):
        x = jnp.asarray(x)
        if off:
            return device_put_global(x.astype(od),
                                     sh.with_memory_kind(host_kind()))
        return device_put_global(x, sh)

    return jax.tree.map(place, params, plan, shardings)


def fetch(params, plan, shardings, compute_dtype=None):
    """The `require()` analog, usable INSIDE jit: move offloaded leaves back
    to device memory (and optionally cast). Under jit this lowers to H2D
    copies that XLA schedules/overlaps; outside jit it is an eager transfer.
    """
    if not isinstance(shardings, (dict, list, tuple)):
        shardings = jax.tree.map(lambda _: shardings, params)

    def pull(x, off, sh):
        if off:
            x = jax.device_put(x, sh.with_memory_kind(device_kind()))
        if compute_dtype is not None and jnp.issubdtype(x.dtype,
                                                        jnp.floating):
            x = x.astype(compute_dtype)
        return x

    return jax.tree.map(pull, params, plan, shardings)


def _slice_sharding(sh):
    """Device-memory sharding for a leaf sliced out of a [L, ...] stack:
    drop the leading (layer) axis of the partition spec. If the stack was
    FSDP-sharded on the layer axis itself, the slice falls back to
    replicated (a single layer cannot be partitioned along L)."""
    from jax.sharding import NamedSharding, PartitionSpec
    if isinstance(sh, NamedSharding):
        rest = tuple(sh.spec)[1:]
        return NamedSharding(sh.mesh, PartitionSpec(*rest),
                             memory_kind=device_kind())
    return sh.with_memory_kind(device_kind())


def fetch_layer(blocks, plan, i, shardings, compute_dtype=None):
    """Per-layer `require()` (parameter_sharder.cpp:242-271 analog), usable
    inside the model's layer scan: slice layer `i` out of each [L, ...]-
    stacked leaf, pulling offloaded leaves host->HBM one layer at a time.

    `i` is a traced scalar (the scan induction variable). For an offloaded
    leaf the slice's operand lives in host memory, so XLA lowers it to an
    async dynamic-slice DMA out of host RAM — only the single layer ever
    occupies HBM, and the latency-hiding scheduler overlaps the transfer of
    layer i with the compute of layer i-1. Resident leaves are sliced in
    HBM as usual.
    """
    if not isinstance(shardings, (dict, list, tuple)):
        shardings = jax.tree.map(lambda _: shardings, blocks)

    def pull(t, off, sh):
        x = jax.lax.dynamic_index_in_dim(t, i, 0, keepdims=False)
        if off:
            x = jax.device_put(x, _slice_sharding(sh))
        if compute_dtype is not None and jnp.issubdtype(x.dtype,
                                                        jnp.floating):
            x = x.astype(compute_dtype)
        return x

    return jax.tree.map(pull, blocks, plan, shardings)


def any_offloaded(plan) -> bool:
    return any(map(bool, jax.tree.leaves(plan)))


def resolve_offload(params, offload, blocks_key: str = "blocks"):
    """Split an offload spec for a stacked-layer model tree: non-block
    leaves are fetched whole up front; block leaves stream per layer inside
    the model's scan (see module docstring for the peak-HBM semantics).

    params: model tree whose `blocks_key` entry holds [L, ...]-stacked
    leaves. offload: None or (plan, shardings) pytrees matching params.
    Returns (params_with_top_leaves_fetched, stream_fn_or_None) where
    stream_fn(blocks, i, compute_dtype) is fetch_layer bound to the block
    plan. Call it ONCE per jitted function and reuse the returned tree —
    e.g. the tied lm_head should read the already-fetched embedding table,
    not re-fetch it.
    """
    if offload is None:
        return params, None
    plan, shardings = offload
    top = {k: v for k, v in params.items() if k != blocks_key}
    top = fetch(top, {k: plan[k] for k in top},
                {k: shardings[k] for k in top})
    blocks, bplan, bshard = (params[blocks_key], plan[blocks_key],
                             shardings[blocks_key])
    # Only streamable leaves (>=3-D [L, in, out] stacks — is_streamable)
    # stream per layer. 2-D stacks (biases/norms, [L, n]) are fetched whole
    # up front: their per-layer slices would be 1-row transfers, which the
    # TPU host-DMA path rejects at larger n (observed on v5e: [2304] and
    # [1, 2304] host->device dynamic slices fail with INTERNAL while
    # [768, 2304] works), and all of a model's 2-D stacks together are <1%
    # of its bytes — streaming them would save nothing.
    whole = jax.tree.map(lambda t, o: bool(o) and not is_streamable(t),
                         blocks, bplan)
    stream_plan = jax.tree.map(lambda t, o: bool(o) and is_streamable(t),
                               blocks, bplan)
    blocks = fetch(blocks, whole, bshard)
    params = dict(top, **{blocks_key: blocks})
    if not any_offloaded(stream_plan):
        return params, None

    def stream(blocks, i, compute_dtype):
        return fetch_layer(blocks, stream_plan, i, bshard, compute_dtype)
    return params, stream


def layer_slicer(blocks, stream, compute_dtype):
    """The scan-body slice function shared by the model forwards:
    slice_layer(i) -> this layer's weight subtree in compute_dtype.

    Resident path (stream=None): cast the whole stacked tree once, slice in
    HBM per layer. Streaming path: slice+fetch+cast per layer (a whole-tree
    cast would materialize the host-resident stacks in HBM). Callers MUST
    remat the scan body when stream is not None, or the backward keeps all
    fetched layers alive as residuals.
    """
    if stream is None:
        cast = lambda t: (t.astype(compute_dtype)
                          if jnp.issubdtype(t.dtype, jnp.floating) else t)
        bp = jax.tree.map(cast, blocks)
        return lambda i: jax.tree.map(
            lambda t: jax.lax.dynamic_index_in_dim(t, i, 0, keepdims=False),
            bp)
    return lambda i: stream(blocks, i, compute_dtype)
